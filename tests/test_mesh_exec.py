"""Differential parity for the MESH execution tier (ops.mesh): the
4-region scan→join→agg fan-out whose region partials land on their home
shards (region-id-hash placement over the device mesh) and whose grouped
partial-aggregate states combine via psum/pmin/pmax over ICI must be
row-for-row identical to the single-device combine AND the row protocol
— over a 1-shard and a multi-shard mesh, through mid-scan split/merge
re-placement, with float-SUM exact sequential rounding kept on host, and
under mesh-collective faults degrading to the single-device combine with
unchanged answers. The sharded join probe and the [R, G] state combine
are parity-checked against their single-device twins directly.

The test process spans 8 virtual CPU devices (conftest sets
xla_force_host_platform_device_count), so the multi-shard regimes cross
REAL shard boundaries with real collectives.
"""

from __future__ import annotations

import itertools

import numpy as np
import pytest

from tidb_tpu import errors, failpoint, metrics, tablecodec as tc
from tidb_tpu.executor import fused_agg
from tidb_tpu.ops import mesh as mesh_mod
from tidb_tpu.session import Session, new_store

# commit the process to the TPU tier so DistCoprClient.mesh (sys.modules
# gate) answers the executor's mesh probes, as a real TPU deployment would
import tidb_tpu.ops.client  # noqa: F401

_id = itertools.count(1)

N_ROWS = 240

JOIN_AGG_Q = ("select count(*), sum(t.v), min(t.v), max(d.d_f), "
              "avg(t.v), sum(t.f) from t join d on t.k = d.d_k")
GROUPED_Q = ("select t.k, count(*), sum(t.v), min(t.f), max(t.v) "
             "from t join d on t.k = d.d_k group by t.k order by t.k")
# float sums above a JOIN: the fused aggregate answers from planes (a
# bare-scan group-by pushes the aggregate down the row protocol — the
# standing fallback, where re-segmentation legitimately re-orders float
# partial merges), and the host accumulator keeps row order exactly
FLOAT_SUM_Q = ("select t.k, count(*), sum(t.f), avg(t.f) "
               "from t join d on t.k = d.d_k group by t.k order by t.k")
QUERIES = [
    JOIN_AGG_Q,
    GROUPED_Q,
    FLOAT_SUM_Q,
    "select count(*), sum(v), min(v), max(v) from t",
    "select count(*), sum(v) from t join d on t.k = d.d_k "
    "where t.v > 500",
]


def _mesh(n_shards: int):
    from tidb_tpu.parallel import CoprMesh
    return CoprMesh(n_devices=n_shards)


@pytest.fixture(autouse=True)
def _mesh_tier_reset():
    """Every test starts from the lazy default mesh with the tier on,
    and cannot leak an explicit mesh, a disabled tier, or a failpoint."""
    mesh_mod.set_mesh(None)
    mesh_mod.set_enabled(True)
    yield
    failpoint.disable_all()
    mesh_mod.set_mesh(None)
    mesh_mod.set_enabled(True)


def _build(n_regions: int = 4) -> Session:
    store = new_store(f"cluster://3/meshexec{next(_id)}")
    s = Session(store)
    s.execute("create database me")
    s.execute("use me")
    s.execute("create table t (id bigint primary key, k bigint, "
              "v bigint, f double)")
    # f = i/10: not binary-representable, so float-SUM answers are
    # sensitive to accumulation ORDER — the sequential-rounding probe
    rows = ", ".join(
        f"({i}, {i % 7}, {i * 10}, {i / 10!r})" if i % 11 else
        f"({i}, null, {i * 10}, null)"
        for i in range(1, N_ROWS + 1))
    s.execute(f"insert into t values {rows}")
    s.execute("create table d (d_k bigint primary key, d_f double)")
    s.execute("insert into d values " +
              ", ".join(f"({i}, {i}.5)" for i in range(7)))
    if n_regions > 1:
        tid = s.info_schema().table_by_name("me", "t").info.id
        step = N_ROWS // n_regions
        s.store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _row_protocol(s: Session, queries=QUERIES) -> list:
    client = s.store.get_client()
    client.columnar_scan = False
    try:
        return [s.execute(q)[0].values() for q in queries]
    finally:
        client.columnar_scan = True


# ---------------------------------------------------------------------------
# region → shard placement
# ---------------------------------------------------------------------------

class TestPlacement:
    def test_deterministic_and_spread(self):
        """The shard is a pure hash of the region id: identical across
        placement instances (a restarted process re-derives the same
        map), and spread over every shard for realistic region counts."""
        a = mesh_mod.RegionPlacement(8)
        b = mesh_mod.RegionPlacement(8)
        ids = list(range(1, 257))
        assert a.shard_of(ids) == b.shard_of(ids)
        assert set(a.shard_of(ids)) == set(range(8)), \
            "256 regions left some shard empty"

    def test_stable_under_neighbor_churn(self):
        """A surviving region NEVER moves when other regions split or
        merge away — its shard depends on nothing but its own id."""
        pl = mesh_mod.RegionPlacement(8)
        home = pl.place(42)
        for rid in range(1000, 1100):     # neighbors come and go
            pl.place(rid)
        assert pl.place(42) == home

    def test_epoch_bump_replaces_deterministically(self):
        """An epoch bump (split/merge bumps the region version)
        re-places the region — counted — onto the same hash-derived
        shard, so mid-scan topology changes never strand partials."""
        pl = mesh_mod.RegionPlacement(8)
        home = pl.place(7, epoch=(1, 1))
        assert pl.replacements == 0
        again = pl.place(7, epoch=(2, 1))
        assert again == home
        assert pl.replacements == 1
        assert pl.place(7, epoch=(2, 1)) == home
        assert pl.replacements == 1       # same epoch: no re-place


# ---------------------------------------------------------------------------
# the core differential suite: 1-shard and multi-shard mesh vs the
# single-device combine vs the row protocol
# ---------------------------------------------------------------------------

class TestMeshParity:
    @pytest.mark.parametrize("n_shards", [1, 8])
    def test_fanout_parity(self, n_shards):
        """4-region scan→join→agg over an n-shard mesh: every query
        matches the single-device combine and the row protocol
        row-for-row, and the combine really rode the mesh tier."""
        s = _build(4)
        mesh_mod.set_mesh(_mesh(n_shards))
        want_row = _row_protocol(s)

        mc0 = fused_agg.stats["mesh_combines"]
        got_mesh = [s.execute(q)[0].values() for q in QUERIES]
        assert fused_agg.stats["mesh_combines"] > mc0, \
            "no fusion combined over the mesh tier"
        assert fused_agg.stats["last_mesh_shards"] == n_shards

        # mesh off: the single-device combine (degradation rung 2)
        s.execute("set global tidb_tpu_mesh = 0")
        try:
            mc1 = fused_agg.stats["mesh_combines"]
            got_single = [s.execute(q)[0].values() for q in QUERIES]
            assert fused_agg.stats["mesh_combines"] == mc1, \
                "mesh combines counted while the tier was off"
        finally:
            s.execute("set global tidb_tpu_mesh = 1")

        for q, m, sd, r in zip(QUERIES, got_mesh, got_single, want_row):
            assert m == sd, \
                f"{n_shards}-shard mesh diverged from single-device " \
                f"combine on {q!r}"
            assert m == r, \
                f"{n_shards}-shard mesh diverged from row protocol " \
                f"on {q!r}"

    def test_float_sum_sequential_rounding_on_host(self):
        """Float SUM/AVG never enter the mesh combine: they keep the
        sequential host accumulator, so the answer is BIT-identical to
        the row protocol's left-to-right accumulation — while the count
        states of the same fusion still combine over the mesh."""
        s = _build(4)
        mesh_mod.set_mesh(_mesh(8))
        mc0 = fused_agg.stats["mesh_combines"]
        got = s.execute(FLOAT_SUM_Q)[0].values()
        assert fused_agg.stats["mesh_combines"] > mc0
        want = _row_protocol(s, [FLOAT_SUM_Q])[0]
        assert got == want, \
            "mesh-tier float SUM diverged from sequential rounding"
        # the probe is real: for at least one group, accumulating i/10 in
        # a different order genuinely rounds differently — so the parity
        # above could only hold because the accumulation ORDER matched
        def acc(xs):
            t = 0.0
            for x in xs:
                t += x
            return t

        groups: dict[int, list[float]] = {}
        for i in range(1, N_ROWS + 1):
            if i % 11:
                groups.setdefault(i % 7, []).append(i / 10)
        assert any(acc(v) != acc(v[::-1]) for v in groups.values()), \
            "float data is order-insensitive — the probe proves nothing"

    def test_exact_i64_min_survives_max(self):
        """max() over a group holding exactly -2^63 answers -2^63 on the
        mesh rung (regression: the max monoid identity was I64_MIN + 1,
        off by one for this value on every combine path)."""
        store = new_store(f"cluster://3/meshexec{next(_id)}")
        s = Session(store)
        s.execute("create database mn")
        s.execute("use mn")
        s.execute("create table t (id bigint primary key, k bigint, "
                  "v bigint)")
        lo = -(1 << 63)
        # group 1 holds ONLY the int64 minimum: its max IS the identity
        s.execute("insert into t values " + ", ".join(
            f"({i}, {i % 2}, {lo if i % 2 else i})"
            for i in range(1, 41)))
        s.execute("create table d (d_k bigint primary key)")
        s.execute("insert into d values (0), (1)")
        tid = s.info_schema().table_by_name("mn", "t").info.id
        store.cluster.split_keys(
            [tc.encode_row_key(tid, 10 * i + 1) for i in range(1, 4)])
        mesh_mod.set_mesh(_mesh(8))
        q = ("select t.k, count(*), max(t.v), min(t.v) from t "
             "join d on t.k = d.d_k group by t.k order by t.k")
        mc0 = fused_agg.stats["mesh_combines"]
        got = s.execute(q)[0].values()
        assert fused_agg.stats["mesh_combines"] > mc0
        assert got == _row_protocol(s, [q])[0]
        assert [r for r in got if r[0] == 1][0][2] == lo, \
            "max over an all--2^63 group rounded to the monoid identity"


class TestTopologyChangesMidScan:
    """Region split / merge DURING the mesh fan-out: the worklist
    re-emits partials for the new region shape, the placement re-places
    bumped epochs onto their deterministic shards, and answers never
    change."""

    def _with_mid_scan(self, mutate):
        s = _build(4)
        store = s.store
        mesh_mod.set_mesh(_mesh(8))
        want = _row_protocol(s)
        orig = store.rpc.cop_request
        state = {"n": 0, "done": False}

        def hook(ctx, sel, ranges, read_ts):
            state["n"] += 1
            if state["n"] == 2 and not state["done"]:
                state["done"] = True
                mutate(store)
            return orig(ctx, sel, ranges, read_ts)

        store.rpc.cop_request = hook
        mc0 = fused_agg.stats["mesh_combines"]
        try:
            got = [s.execute(q)[0].values() for q in QUERIES]
        finally:
            store.rpc.cop_request = orig
        assert state["done"], "topology mutation never fired"
        assert fused_agg.stats["mesh_combines"] > mc0
        for q, g, w in zip(QUERIES, got, want):
            assert g == w, f"mid-scan topology change diverged on {q!r}"
        # post-mutation steady state: re-placed regions, same answers
        after = [s.execute(q)[0].values() for q in QUERIES]
        for q, a, w in zip(QUERIES, after, want):
            assert a == w, f"post-mutation steady state diverged on {q!r}"
        pl = mesh_mod.placement_for(mesh_mod.get_mesh())
        assert pl.placements > 0, "no region was ever placed on a shard"

    def test_split_mid_scan(self):
        def split(store):
            s = Session(store)
            tid = s.info_schema().table_by_name("me", "t").info.id
            store.cluster.split_keys([tc.encode_row_key(tid, 31),
                                      tc.encode_row_key(tid, 171)])

        self._with_mid_scan(split)

    def test_merge_mid_scan(self):
        def merge(store):
            regions = store.cluster.regions
            for i in range(len(regions) - 1):
                if regions[i].start:
                    store.cluster.merge(regions[i].region_id,
                                        regions[i + 1].region_id)
                    return

        self._with_mid_scan(merge)


# ---------------------------------------------------------------------------
# mesh-tier fault degradation (device/mesh_collective failpoint)
# ---------------------------------------------------------------------------

class TestMeshDegradation:
    def test_collective_fault_degrades_to_single_device(self):
        """An ICI collective fault degrades mesh → single-device combine
        (counted on copr.degraded_mesh) with unchanged answers — never a
        statement error; the tier resumes once the fault clears."""
        s = _build(4)
        mesh_mod.set_mesh(_mesh(8))
        want = [s.execute(q)[0].values() for q in QUERIES]
        deg = metrics.counter("copr.degraded_mesh")

        failpoint.enable("device/mesh_collective")
        try:
            d0, mc0 = deg.value, fused_agg.stats["mesh_combines"]
            pc0 = fused_agg.stats["partial_combines"]
            got = [s.execute(q)[0].values() for q in QUERIES]
            assert deg.value > d0, \
                "mesh fault never accounted a copr.degraded_mesh"
            assert fused_agg.stats["mesh_combines"] == mc0, \
                "a faulted combine still counted as a mesh combine"
            assert fused_agg.stats["partial_combines"] > pc0, \
                "degradation skipped the single-device combine rung"
        finally:
            failpoint.disable_all()
        for q, g, w in zip(QUERIES, got, want):
            assert g == w, f"mesh degradation changed answers on {q!r}"
        # fault cleared: combines ride the mesh again
        mc1 = fused_agg.stats["mesh_combines"]
        assert s.execute(JOIN_AGG_Q)[0].values() == want[0]
        assert fused_agg.stats["mesh_combines"] > mc1

    def test_kill_switch_is_global_only(self):
        s = _build(1)
        with pytest.raises(errors.TiDBError, match="GLOBAL"):
            s.execute("set tidb_tpu_mesh = 0")
        s.execute("set global tidb_tpu_mesh = 0")
        try:
            assert mesh_mod.get_mesh() is None
        finally:
            s.execute("set global tidb_tpu_mesh = 1")
        assert mesh_mod.get_mesh() is not None


# ---------------------------------------------------------------------------
# the sharded kernels against their single-device twins, directly
# ---------------------------------------------------------------------------

class TestShardedKernelParity:
    def test_join_probe_sharded_matches_single_device(self):
        """The mesh-sharded probe (build replicated, probe rows sharded,
        one merged packed readback) emits the SAME (l_idx, r_idx) pairs
        in the same order as the single-device probe — including rows
        with multiple matches and the capacity-escalation retry."""
        from tidb_tpu.ops import kernels
        rng = np.random.RandomState(11)
        lkey = rng.randint(0, 40, size=1000).astype(np.int64)
        lvalid = rng.rand(1000) > 0.1
        rkey = rng.randint(0, 40, size=300).astype(np.int64)
        rvalid = rng.rand(300) > 0.1
        li0, ri0 = kernels.join_match_pairs(lkey, lvalid, rkey, rvalid)
        li1, ri1 = kernels.join_match_pairs(lkey, lvalid, rkey, rvalid,
                                            mesh=_mesh(8))
        assert np.array_equal(li0, li1)
        assert np.array_equal(ri0, ri1)

    def test_join_probe_rides_mesh_end_to_end(self):
        """With the dispatch floor at 0, a cluster-store join routes to
        the SHARDED probe (spy on ops.mesh.join_probe_sharded) and the
        answers match the row protocol."""
        s = _build(4)
        mesh_mod.set_mesh(_mesh(8))
        want = _row_protocol(s)
        seen = {"n": 0}
        orig = mesh_mod.join_probe_sharded

        def spy(*a, **kw):
            seen["n"] += 1
            return orig(*a, **kw)

        s.execute("set global tidb_tpu_dispatch_floor = 0")
        mesh_mod.join_probe_sharded = spy
        try:
            got = [s.execute(q)[0].values() for q in QUERIES]
        finally:
            mesh_mod.join_probe_sharded = orig
            s.execute("set global tidb_tpu_dispatch_floor = 16384")
        assert seen["n"] > 0, "no join ever took the sharded probe"
        for q, g, w in zip(QUERIES, got, want):
            assert g == w, f"sharded probe diverged on {q!r}"

    def test_state_combine_matches_single_device(self):
        """combine_states_sharded ([R, G] states placed onto shards,
        reduced locally, merged over ICI) is bit-identical to the
        single-device combine_region_partials — the MULTICHIP dryrun
        contract, held on tier-1 too."""
        from tidb_tpu.ops import kernels
        rng = np.random.RandomState(5)
        R, G = 9, 13
        states = [
            rng.randint(0, 1 << 30, size=(R, G)).astype(np.int64),
            rng.randint(-(1 << 50), 1 << 50, size=(R, G)).astype(np.int64),
            rng.rand(R, G) * 1e6 - 5e5,
            rng.randint(-(1 << 31), 1 << 31, size=(R, G)).astype(np.int64),
        ]
        ops = ["sum", "min", "min", "max"]
        want = kernels.combine_region_partials(states, ops)
        for n_shards in (1, 8):
            got = mesh_mod.combine_states_sharded(states, ops,
                                                  _mesh(n_shards))
            for i, (g, w) in enumerate(zip(got, want)):
                assert np.array_equal(np.asarray(g), np.asarray(w)), \
                    f"{n_shards}-shard state combine diverged on " \
                    f"state {i}"
