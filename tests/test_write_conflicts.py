"""INSERT … ON DUPLICATE KEY UPDATE / INSERT IGNORE / REPLACE and
handle-moving updates.

Reference: executor/executor_write.go:554-608 (onDuplicateUpdate,
batchGetInsertKeys eager conflict detection), parser/parser.y:2043.
"""

from __future__ import annotations

import pytest

from tidb_tpu import errors
from tests.testkit import TestKit


@pytest.fixture
def tk():
    t = TestKit()
    t.exec("create database test")
    t.exec("use test")
    t.exec("create table t (a int primary key, b int, unique key ub (b))")
    t.exec("insert into t values (1, 10), (2, 20)")
    return t


class TestOnDuplicateKeyUpdate:
    def test_pk_conflict_updates(self, tk):
        tk.exec("insert into t values (1, 11) "
                "on duplicate key update b = b + 100")
        tk.query("select * from t order by a").check([[1, 110], [2, 20]])

    def test_unique_index_conflict_targets_existing_row(self, tk):
        # conflicts on ub (b=20) → row a=2 is the one updated
        tk.exec("insert into t values (9, 20) "
                "on duplicate key update b = b + 5")
        tk.query("select * from t order by a").check([[1, 10], [2, 25]])

    def test_values_function(self, tk):
        tk.exec("insert into t values (1, 77) "
                "on duplicate key update b = values(b) + 1")
        tk.query("select b from t where a = 1").check([[78]])

    def test_no_conflict_inserts_normally(self, tk):
        tk.exec("insert into t values (3, 30) "
                "on duplicate key update b = 999")
        tk.query("select * from t order by a").check(
            [[1, 10], [2, 20], [3, 30]])

    def test_affected_rows_two_for_update(self, tk):
        tk.exec("insert into t values (1, 12) "
                "on duplicate key update b = 12")
        assert tk.session.vars.affected_rows == 2

    def test_updating_pk_moves_row(self, tk):
        tk.exec("insert into t values (1, 0) "
                "on duplicate key update a = a + 100")
        tk.query("select * from t order by a").check([[2, 20], [101, 10]])
        # index still points at the moved row
        tk.query("select a from t where b = 10").check([[101]])


class TestInsertIgnore:
    def test_ignores_pk_and_unique_conflicts(self, tk):
        tk.exec("insert ignore into t values (1, 99), (8, 20), (3, 30)")
        tk.query("select * from t order by a").check(
            [[1, 10], [2, 20], [3, 30]])

    def test_affected_counts_only_inserted(self, tk):
        tk.exec("insert ignore into t values (1, 99), (4, 40)")
        assert tk.session.vars.affected_rows == 1


class TestDupEntryErrors:
    def test_pk_duplicate_is_1062_with_clean_message(self, tk):
        with pytest.raises(errors.DupEntryError) as ei:
            tk.exec("insert into t values (1, 5)")
        assert getattr(ei.value, "code", None) == 1062
        assert "Duplicate entry '1' for key 'PRIMARY'" in str(ei.value)

    def test_update_pk_collision_is_1062(self, tk):
        with pytest.raises(errors.DupEntryError):
            tk.exec("update t set a = 2 where a = 1")


class TestReplaceUniqueIndex:
    def test_replace_via_unique_key(self, tk):
        tk.exec("replace into t values (7, 20)")   # displaces row a=2
        tk.query("select * from t order by a").check([[1, 10], [7, 20]])

    def test_update_pk_move_keeps_indexes(self, tk):
        tk.exec("update t set a = 50 where a = 2")
        tk.query("select a from t where b = 20").check([[50]])
        tk.exec("insert into t values (2, 99)")   # old handle is free again
        tk.query("select count(1) from t").check([[3]])


class TestMultiUniqueConflicts:
    @pytest.fixture
    def tk2(self):
        t = TestKit()
        t.exec("create database test")
        t.exec("use test")
        t.exec("create table m (id int primary key auto_increment, "
               "a int, b int, unique key ua (a), unique key ub (b))")
        t.exec("insert into m (a, b) values (1, 1), (2, 2)")
        return t

    def test_replace_deletes_every_conflicting_row(self, tk2):
        # collides with row 1 on ua AND row 2 on ub: both must go
        tk2.exec("replace into m (a, b) values (1, 2)")
        tk2.query("select a, b from m").check([[1, 2]])

    def test_ignore_leaves_no_dangling_index_entries(self, tk2):
        # collides on ub only — the ua entry for a=3 must NOT be committed
        tk2.exec("insert ignore into m (a, b) values (3, 2)")
        tk2.query("select count(1) from m").check([[2]])
        # an index scan on a=3 must find nothing (no phantom handle)
        tk2.query("select a, b from m where a = 3").check([])
        # and inserting a=3 with a fresh b must now succeed
        tk2.exec("insert into m (a, b) values (3, 30)")
        tk2.query("select a, b from m where a = 3").check([[3, 30]])


class TestBulkAddRecords:
    """Table.add_records: the bulk KV build must be byte-identical to the
    per-row path, and the fast preconditions must gate correctly."""

    def _mk(self, name, ddl):
        from tidb_tpu.session import Session, new_store
        store = new_store(f"memory://{name}")
        s = Session(store)
        s.execute("create database b")
        s.execute("use b")
        s.execute(ddl)
        return store, s, s.info_schema().table_by_name("b", "t")

    def _rows(self, n):
        from tidb_tpu.types import Datum
        return [[Datum.i64(i), Datum.i64(i * 7), Datum.string(f"s{i}")]
                for i in range(1, n + 1)]

    def _dump(self, store, tid):
        from tidb_tpu import tablecodec as tc
        snap = store.get_snapshot()
        a, b = tc.encode_record_range(tid)
        return list(snap.iterate(a, b))

    def test_bulk_matches_per_row_bytes(self):
        ddl = "create table t (id bigint primary key, a int, s varchar(10))"
        s1, sess1, t1 = self._mk("bulk_a", ddl)
        s2, sess2, t2 = self._mk("bulk_b", ddl)
        rows = self._rows(500)
        txn = s1.begin()
        t1.add_records(txn, rows, skip_unique_check=True)
        txn.commit()
        txn = s2.begin()
        for r in rows:
            t2.add_record(txn, r, skip_unique_check=True)
        txn.commit()
        d1 = self._dump(s1, t1.id)
        d2 = self._dump(s2, t2.id)
        assert d1 == d2 and len(d1) == 500
        # auto-id rebased identically (next alloc past the max handle)
        assert t1._alloc.alloc() == t2._alloc.alloc()

    def test_bulk_falls_back_with_secondary_index(self):
        ddl = ("create table t (id bigint primary key, a int, "
               "s varchar(10), key ia (a))")
        store, sess, tbl = self._mk("bulk_idx", ddl)
        txn = store.begin()
        tbl.add_records(txn, self._rows(50), skip_unique_check=True)
        txn.commit()
        # the per-row fallback maintained the index
        sess.execute("admin check table t")
        r = sess.execute("select id from t use index (ia) where a = 70")
        assert r[0].values() == [[10]]

    def test_bulk_respects_unique_check_request(self):
        import pytest
        from tidb_tpu import errors
        ddl = "create table t (id bigint primary key, a int, s varchar(10))"
        store, sess, tbl = self._mk("bulk_uniq", ddl)
        txn = store.begin()
        tbl.add_records(txn, self._rows(10))   # checks requested
        txn.commit()
        txn = store.begin()
        with pytest.raises(errors.TiDBError):
            tbl.add_records(txn, self._rows(1))   # duplicate handle 1
            txn.commit()
        txn.rollback()

    def test_bulk_visible_to_tpu_batch_and_sql(self):
        ddl = "create table t (id bigint primary key, a int, s varchar(10))"
        store, sess, tbl = self._mk("bulk_sql", ddl)
        txn = store.begin()
        tbl.add_records(txn, self._rows(300), skip_unique_check=True)
        txn.commit()
        [[cnt, sa, mn]] = sess.execute(
            "select count(*), sum(a), min(s) from t")[0].values()
        mn = mn.decode() if isinstance(mn, bytes) else mn
        assert (cnt, int(sa), mn) == (300, 7 * (300 * 301) // 2, "s1")


def test_skip_constraint_check_insert_bulk_path():
    """tidb_skip_constraint_check (reference kv.SkipCheckForWrite) routes
    plain multi-VALUES INSERTs through the bulk KV build; checks stay
    enforced when the sysvar is off, and reactive forms (IGNORE/ON
    DUPLICATE/REPLACE) never take the unchecked path."""
    import pytest
    from tidb_tpu import errors
    from tidb_tpu.session import Session, new_store
    s = Session(new_store("memory://skip_chk"))
    s.execute("create database w")
    s.execute("use w")
    s.execute("create table t (id bigint primary key, a int)")
    s.execute("set tidb_skip_constraint_check = 1")
    s.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    assert s.execute("select count(*) from t")[0].values() == [[3]]
    s.execute("admin check table t")
    # reactive forms still observe conflicts even with the var set
    s.execute("insert into t values (1, 99), (4, 40) "
              "on duplicate key update a = 99")
    assert s.execute("select a from t where id = 1")[0].values() == [[99]]
    s.execute("set tidb_skip_constraint_check = 0")
    with pytest.raises(errors.TiDBError):
        s.execute("insert into t values (2, 1), (999, 1)")


def test_skip_constraint_check_applies_to_single_row():
    """Review finding: the skip must not depend on statement row count —
    a single-row INSERT under the var behaves like any batch row
    (reference kv.SkipCheckForWrite applies to every write)."""
    from tidb_tpu.session import Session, new_store
    s = Session(new_store("memory://skip_chk1"))
    s.execute("create database w")
    s.execute("use w")
    s.execute("create table t (id bigint primary key, a int)")
    s.execute("insert into t values (5, 50)")
    s.execute("set tidb_skip_constraint_check = 1")
    s.execute("insert into t values (5, 77)")   # silently overwrites
    assert s.execute("select a from t where id = 5")[0].values() == [[77]]
