"""Binlog hooks (reference sessionctx/binloginfo + 2pc.go:462-505) and
the Prometheus push client (tidb-server/main.go:175-199)."""

import threading

import pytest

from tidb_tpu import binloginfo, errors
from tidb_tpu.session import Session, new_store


@pytest.fixture
def pump():
    p = binloginfo.MemoryPump()
    binloginfo.set_pump(p)
    yield p
    binloginfo.set_pump(None)


class TestBinlog:
    def test_commit_writes_prewrite_then_commit(self, pump):
        store = new_store("cluster://4/binlog_c")
        s = Session(store)
        s.execute("create database b")
        s.execute("use b")
        s.execute("create table t (a bigint primary key, v int)")
        pump.entries.clear()   # DDL/bootstrap noise out of the way
        s.execute("insert into t values (1, 10), (2, 20)")
        # background txns (owner leases, stats) binlog too — find the
        # insert's prewrite: the one carrying exactly our 2 row keys
        pre = next(e for e in pump.entries
                   if e["tp"] == "prewrite" and len(e["mutations"]) == 2)
        com = next(e for e in pump.entries
                   if e["tp"] == "commit"
                   and e["start_ts"] == pre["start_ts"])
        assert com["commit_ts"] > pre["start_ts"]
        # the prewrite carries the primary key + the full mutation set
        assert pre["prewrite_key"] == pre["mutations"][0][0]
        assert all(isinstance(k, bytes) and isinstance(v, bytes)
                   for k, v in pre["mutations"])
        # every commit in the stream pairs with a prior prewrite of the
        # same start_ts (writeFinishBinlog invariant)
        starts = {e["start_ts"] for e in pump.entries
                  if e["tp"] == "prewrite"}
        assert all(e["start_ts"] in starts for e in pump.entries
                   if e["tp"] == "commit")

    def test_conflict_rollback_writes_rollback(self, pump):
        store = new_store("cluster://4/binlog_r")
        s1 = Session(store)
        s1.execute("create database b")
        s1.execute("use b")
        s1.execute("create table t (a bigint primary key, v int)")
        s1.execute("insert into t values (1, 0)")
        s2 = Session(store)
        s2.execute("use b")
        s1.execute("begin")
        s2.execute("begin")
        s1.execute("update t set v = 1 where a = 1")
        s2.execute("update t set v = 2 where a = 1")
        pump.entries.clear()
        s1.execute("commit")
        try:
            s2.execute("commit")   # conflict → optimistic retry may
            #                        succeed (replay) or raise
        except errors.TiDBError:
            pass
        # every commit record pairs with a prewrite of the same start_ts;
        # a failed prewrite leaves a rollback record instead
        starts = {e["start_ts"] for e in pump.entries
                  if e["tp"] == "prewrite"}
        assert all(e["start_ts"] in starts for e in pump.entries
                   if e["tp"] in ("commit", "rollback"))
        assert any(e["tp"] == "commit" for e in pump.entries)

    def test_pump_errors_never_fail_the_txn(self):
        class ExplodingPump:
            def write_binlog(self, payload):
                raise RuntimeError("pump down")

        binloginfo.set_pump(ExplodingPump())
        try:
            store = new_store("cluster://2/binlog_x")
            s = Session(store)
            s.execute("create database b")
            s.execute("use b")
            s.execute("create table t (a bigint primary key)")
            s.execute("insert into t values (1)")
            assert s.execute("select count(*) from t")[0].values() == [[1]]
        finally:
            binloginfo.set_pump(None)

    def test_localstore_commits_skip_binlog(self, pump):
        """Binlog attaches at the cluster 2PC boundary only — the
        reference writes binlog in the tikv committer, not in
        localstore."""
        s = Session(new_store("memory://binlog_l"))
        s.execute("create database b")
        s.execute("use b")
        s.execute("create table t (a bigint primary key)")
        pump.entries.clear()
        s.execute("insert into t values (1)")
        assert pump.entries == []

    def test_file_pump_round_trips(self, tmp_path, pump):
        import json
        path = str(tmp_path / "binlog.jsonl")
        fp = binloginfo.FilePump(path)
        binloginfo.set_pump(fp)
        store = new_store("cluster://2/binlog_f")
        s = Session(store)
        s.execute("create database b")
        s.execute("use b")
        s.execute("create table t (a bigint primary key)")
        s.execute("insert into t values (7)")
        fp.close()
        lines = [json.loads(line) for line in open(path)]
        assert any(e["tp"] == "commit" for e in lines)
        pre = next(e for e in lines if e["tp"] == "prewrite")
        assert all(isinstance(k, str) for k, _v in pre["mutations"])
        bytes.fromhex(pre["prewrite_key"])   # hex round-trips


class TestMetricsPush:
    def test_push_once_sends_exposition(self):
        from tidb_tpu import metrics
        from tidb_tpu.metrics import push as mpush
        metrics.counter("push.test_counter").inc(3)
        sent = {}

        def transport(url, body):
            sent["url"], sent["body"] = url, body

        ok = mpush.push_once("gw:9091", job="tidb-tpu",
                             instance="test-host", transport=transport)
        assert ok
        assert sent["url"] == \
            "http://gw:9091/metrics/job/tidb-tpu/instance/test-host"
        assert b"push.test_counter" in sent["body"] or \
            b"push_test_counter" in sent["body"]

    def test_push_errors_are_swallowed(self):
        from tidb_tpu.metrics import push as mpush

        def transport(url, body):
            raise IOError("gateway down")

        assert mpush.push_once("gw:9091", transport=transport) is False

    def test_push_loop_over_real_http(self):
        """End-to-end against an in-process Pushgateway-shaped server."""
        import http.server
        import time
        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append((self.path, self.rfile.read(n)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            from tidb_tpu.metrics import push as mpush
            addr = f"127.0.0.1:{srv.server_port}"
            t = mpush.start_push_client(addr, 0.05, job="jx")
            assert t is not None
            deadline = time.time() + 5
            while not received and time.time() < deadline:
                time.sleep(0.02)
            t.stop_event.set()
            t.join(timeout=2)
            assert received, "no push arrived"
            path, body = received[0]
            assert path.startswith("/metrics/job/jx/instance/")
            assert body  # exposition text
        finally:
            srv.shutdown()

    def test_disabled_configs(self):
        from tidb_tpu.metrics import push as mpush
        assert mpush.start_push_client("", 15) is None
        assert mpush.start_push_client("gw:9091", 0) is None


def test_primary_committed_never_binlogs_rollback():
    """Review finding: a failure committing the primary batch's REMAINDER
    must not emit a rollback binlog — once the primary lands the txn IS
    committed (2pc.go 'succeed with error') and a drainer replaying a
    rollback record would silently diverge."""
    from tidb_tpu.cluster.twopc import TwoPhaseCommitter
    from tidb_tpu.session import Session

    pump = binloginfo.MemoryPump()
    binloginfo.set_pump(pump)
    try:
        store = new_store("cluster://1/binlog_partial")
        Session(store)  # bootstrap
        start_ts = store.oracle.current_version()
        muts = {b"zk%02d" % i: b"v%d" % i for i in range(6)}
        c = TwoPhaseCommitter(store, start_ts, muts)
        orig = TwoPhaseCommitter._commit_batch
        state = {"n": 0}

        def flaky(self, keys, commit_ts, bo):
            state["n"] += 1
            if state["n"] == 2:   # the primary batch's remainder
                raise errors.TiDBError("injected region error")
            return orig(self, keys, commit_ts, bo)

        TwoPhaseCommitter._commit_batch = flaky
        pump.entries.clear()
        try:
            c.execute()           # must SUCCEED: primary landed
        finally:
            TwoPhaseCommitter._commit_batch = orig
        assert c.committed
        tps = [e["tp"] for e in pump.entries]
        assert "rollback" not in tps, tps
        assert tps == ["prewrite", "commit"], tps
        # the stragglers' locks resolve on the next read
        snap = store.get_snapshot()
        got = dict(snap.iterate(b"zk", b"zl"))
        assert got == muts
    finally:
        binloginfo.set_pump(None)
