"""Datum comparison/conversion tests (mirrors util/types tests)."""

from decimal import Decimal

import pytest

from tidb_tpu import errors, mysqldef as my
from tidb_tpu.types import (
    Datum, Kind, NULL, compare_datum, convert_datum, datum_from_py,
    FieldType, parse_time, parse_duration,
)
from tidb_tpu.types.field_type import new_field_type, agg_field_type


def cmp(a, b):
    return compare_datum(datum_from_py(a), datum_from_py(b))


def test_cross_type_numeric_compare():
    assert cmp(1, 1.0) == 0
    assert cmp(1, Decimal("1.00")) == 0
    assert cmp(2, 1.5) == 1
    assert cmp(-1, 0.5) == -1
    assert cmp(Decimal("1.1"), 1.1) == 0
    assert cmp((1 << 63) - 1, float((1 << 63) - 1)) in (-1, 0)  # float rounding tolerated
    assert cmp("12", 12) == 0
    assert cmp("12.5", 12.5) == 0
    assert cmp("abc", 0) == 0  # non-numeric string coerces to 0


def test_string_compare_binary():
    assert cmp("a", "b") == -1
    assert cmp(b"ab", "ab") == 0
    assert cmp("abc", "ab") == 1


def test_null_ordering():
    assert compare_datum(NULL, NULL) == 0
    assert compare_datum(NULL, Datum.i64(-(1 << 63))) == -1
    assert compare_datum(Datum.string(""), NULL) == 1


def test_time_duration_compare():
    t1 = datum_from_py(parse_time("1998-09-02"))
    t2 = datum_from_py(parse_time("1998-09-03"))
    assert compare_datum(t1, t2) == -1
    d1 = datum_from_py(parse_duration("01:00:00"))
    d2 = datum_from_py(parse_duration("-01:00:00"))
    assert compare_datum(d1, d2) == 1


def test_convert_int_bounds():
    ft = new_field_type(my.TypeTiny)
    assert convert_datum(Datum.i64(127), ft).get_int() == 127
    with pytest.raises(errors.OverflowError_):
        convert_datum(Datum.i64(128), ft)
    ft.flag |= my.UnsignedFlag
    assert convert_datum(Datum.i64(255), ft).get_int() == 255
    with pytest.raises(errors.OverflowError_):
        convert_datum(Datum.i64(-1), ft)


def test_convert_rounding():
    ft = new_field_type(my.TypeLong)
    assert convert_datum(Datum.f64(1.5), ft).get_int() == 2
    assert convert_datum(Datum.f64(-1.5), ft).get_int() == -2
    assert convert_datum(Datum.f64(2.4), ft).get_int() == 2
    assert convert_datum(Datum.string("3.6"), ft).get_int() == 4


def test_convert_decimal_quantize():
    ft = new_field_type(my.TypeNewDecimal)
    ft.flen, ft.decimal = 10, 2
    d = convert_datum(Datum.string("1.005"), ft)
    assert d.val == Decimal("1.01")
    d = convert_datum(Datum.f64(2.5), ft)
    assert d.val == Decimal("2.50")


def test_convert_string_flen():
    ft = new_field_type(my.TypeVarchar)
    ft.flen = 3
    assert convert_datum(Datum.string("abc"), ft).get_string() == "abc"
    with pytest.raises(errors.OverflowError_):
        convert_datum(Datum.string("abcd"), ft)


def test_convert_time():
    ft = new_field_type(my.TypeDate)
    d = convert_datum(Datum.string("1998-09-02 11:22:33"), ft)
    assert str(d.val) == "1998-09-02"
    ft2 = new_field_type(my.TypeDatetime)
    d2 = convert_datum(Datum.string("19980902112233"), ft2)
    assert str(d2.val) == "1998-09-02 11:22:33"


def test_time_packed_roundtrip():
    t = parse_time("2026-07-29 11:30:45.123456")
    from tidb_tpu.types.time_types import Time
    assert Time.from_packed_int(t.to_packed_int()).dt == t.dt


def test_agg_field_types():
    dec = new_field_type(my.TypeNewDecimal)
    dec.decimal = 2
    assert agg_field_type("count", dec).tp == my.TypeLonglong
    assert agg_field_type("sum", dec).tp == my.TypeNewDecimal
    assert agg_field_type("sum", new_field_type(my.TypeDouble)).tp == my.TypeDouble
    assert agg_field_type("avg", dec).decimal == 6
    assert agg_field_type("max", dec).tp == my.TypeNewDecimal


def test_duration_two_part_is_hours_minutes():
    # regression: 'HH:MM' must parse as hours:minutes (MySQL), not MM:SS
    d = parse_duration("11:30", fsp=0)
    assert str(d) == "11:30:00"
    assert parse_duration("-2:05").to_number() == -20500


def test_wide_decimal_quantize_no_crash():
    from tidb_tpu.types.convert import quantize_decimal
    from decimal import Decimal
    v = Decimal("12345678901234567890123456789.1")
    assert quantize_decimal(v, 2) == Decimal("12345678901234567890123456789.10")


class TestEnumSetBitHex:
    """ENUM/SET/BIT/HEX value semantics (round-3 verdict missing #6;
    util/types/enum.go, set.go, bit.go, hex.go)."""

    def test_parse_enum(self):
        from tidb_tpu.types.enumset import parse_enum_name, parse_enum_value
        e = parse_enum_name(["red", "green"], "GREEN")
        assert (e.name, e.value) == ("green", 2)
        assert parse_enum_value(["red", "green"], 1).name == "red"
        assert parse_enum_name(["red", "green"], "2").name == "green"
        with pytest.raises(errors.TiDBError):
            parse_enum_name(["red"], "blue")
        with pytest.raises(errors.TiDBError):
            parse_enum_value(["red"], 0)
        with pytest.raises(errors.TiDBError):
            parse_enum_value(["red"], 2)

    def test_parse_set(self):
        from tidb_tpu.types.enumset import parse_set_name, parse_set_value
        s = parse_set_name(["a", "b", "c"], "c,a")
        assert (s.name, s.value) == ("a,c", 0b101)
        assert parse_set_name(["a", "b"], "").value == 0
        assert parse_set_value(["a", "b", "c"], 6).name == "b,c"
        # numbers in string form, and de-dup of repeated members
        assert parse_set_name(["a", "b"], "3").name == "a,b"
        assert parse_set_name(["a", "b"], "a,a,b").value == 0b11
        with pytest.raises(errors.TiDBError):
            parse_set_name(["a"], "z")
        with pytest.raises(errors.TiDBError):
            parse_set_value(["a"], 2)

    def test_parse_bit_hex(self):
        from tidb_tpu.types.enumset import Bit, parse_bit, parse_hex
        b = parse_bit("b'1010'", Bit.UNSPECIFIED_WIDTH)
        assert (b.value, b.width) == (10, 8)
        assert parse_bit("0b11", 2).value == 3
        assert str(parse_bit("b'101'", 4)) == "0b0101"
        assert parse_bit("b'1'", -1).to_bytes() == b"\x01"
        with pytest.raises(errors.TiDBError):
            parse_bit("b'102'", -1)
        with pytest.raises(errors.TiDBError):
            parse_bit("b'111'", 2)
        h = parse_hex("0x4142")
        assert h.value == 0x4142 and h.to_bytes() == b"AB"
        assert str(parse_hex("x'0a'")) == "0x0A"
        assert parse_hex("0x0").to_bytes() == b"\x00"
        with pytest.raises(errors.TiDBError):
            parse_hex("x'1'")   # odd digit count

    def test_datum_views_and_compare(self):
        from tidb_tpu.types.datum import Kind, compare_datum
        from tidb_tpu.types.enumset import Bit, Enum, Hex, SetVal
        e = Datum(Kind.ENUM, Enum("green", 2))
        assert e.get_string() == "green" and e.as_number() == 2
        # vs string → by NAME; vs number → by index
        assert compare_datum(e, Datum.string("green")) == 0
        assert compare_datum(e, Datum.string("red")) < 0
        assert compare_datum(e, Datum.i64(2)) == 0
        assert compare_datum(e, Datum.i64(3)) < 0
        s = Datum(Kind.SET, SetVal("a,c", 0b101))
        assert compare_datum(s, Datum.string("a,c")) == 0
        assert compare_datum(s, Datum.i64(5)) == 0
        h = Datum(Kind.HEX, Hex(0x41))
        assert compare_datum(h, Datum.string("A")) == 0
        assert compare_datum(h, Datum.i64(65)) == 0
        b = Datum(Kind.BIT, Bit(65, 8))
        assert compare_datum(b, Datum.string("A")) == 0
        assert compare_datum(b, Datum.i64(65)) == 0

    def test_convert_roundtrip_through_codec(self):
        """Flatten/unflatten contract: enum/set/bit survive the codec as
        uints and come back as rich objects via the column FieldType."""
        from tidb_tpu import codec
        from tidb_tpu.types.convert import convert_datum, unflatten_datum
        from tidb_tpu.types.datum import Kind
        from tidb_tpu.types.field_type import FieldType
        import tidb_tpu.mysqldef as my

        eft = FieldType(my.TypeEnum, elems=["red", "green"])
        sft = FieldType(my.TypeSet, elems=["a", "b"])
        bft = FieldType(my.TypeBit, flen=8)
        for ft, raw, flat, shown in [
                (eft, Datum.string("green"), 2, "green"),
                (sft, Datum.string("b,a"), 3, "a,b"),
                (bft, Datum.i64(9), 9, "\t")]:
            stored = convert_datum(raw, ft)
            enc = codec.encode_value([stored])
            dec, _ = codec.decode_one(enc, 0)
            assert dec.kind in (Kind.INT64, Kind.UINT64) and dec.val == flat
            back = unflatten_datum(dec, ft)
            assert back.kind == stored.kind
            assert back.get_string() == shown

    def test_review_fixes_roundtrip(self):
        """Round-4 review findings: hex leading zeros / empty literal,
        binary (non-UTF8) compare, oversized bit literals."""
        from tidb_tpu.types.datum import Kind, compare_datum
        from tidb_tpu.types.enumset import Hex, parse_hex
        assert parse_hex("x'0041'").to_bytes() == b"\x00A"
        assert parse_hex("x''").to_bytes() == b""
        assert parse_hex("0x1").to_bytes() == b"\x01"
        h = Datum(Kind.HEX, Hex(0xFF, 1))
        assert compare_datum(h, Datum.bytes_(b"\xff")) == 0
