"""Datum comparison/conversion tests (mirrors util/types tests)."""

from decimal import Decimal

import pytest

from tidb_tpu import errors, mysqldef as my
from tidb_tpu.types import (
    Datum, Kind, NULL, compare_datum, convert_datum, datum_from_py,
    FieldType, parse_time, parse_duration,
)
from tidb_tpu.types.field_type import new_field_type, agg_field_type


def cmp(a, b):
    return compare_datum(datum_from_py(a), datum_from_py(b))


def test_cross_type_numeric_compare():
    assert cmp(1, 1.0) == 0
    assert cmp(1, Decimal("1.00")) == 0
    assert cmp(2, 1.5) == 1
    assert cmp(-1, 0.5) == -1
    assert cmp(Decimal("1.1"), 1.1) == 0
    assert cmp((1 << 63) - 1, float((1 << 63) - 1)) in (-1, 0)  # float rounding tolerated
    assert cmp("12", 12) == 0
    assert cmp("12.5", 12.5) == 0
    assert cmp("abc", 0) == 0  # non-numeric string coerces to 0


def test_string_compare_binary():
    assert cmp("a", "b") == -1
    assert cmp(b"ab", "ab") == 0
    assert cmp("abc", "ab") == 1


def test_null_ordering():
    assert compare_datum(NULL, NULL) == 0
    assert compare_datum(NULL, Datum.i64(-(1 << 63))) == -1
    assert compare_datum(Datum.string(""), NULL) == 1


def test_time_duration_compare():
    t1 = datum_from_py(parse_time("1998-09-02"))
    t2 = datum_from_py(parse_time("1998-09-03"))
    assert compare_datum(t1, t2) == -1
    d1 = datum_from_py(parse_duration("01:00:00"))
    d2 = datum_from_py(parse_duration("-01:00:00"))
    assert compare_datum(d1, d2) == 1


def test_convert_int_bounds():
    ft = new_field_type(my.TypeTiny)
    assert convert_datum(Datum.i64(127), ft).get_int() == 127
    with pytest.raises(errors.OverflowError_):
        convert_datum(Datum.i64(128), ft)
    ft.flag |= my.UnsignedFlag
    assert convert_datum(Datum.i64(255), ft).get_int() == 255
    with pytest.raises(errors.OverflowError_):
        convert_datum(Datum.i64(-1), ft)


def test_convert_rounding():
    ft = new_field_type(my.TypeLong)
    assert convert_datum(Datum.f64(1.5), ft).get_int() == 2
    assert convert_datum(Datum.f64(-1.5), ft).get_int() == -2
    assert convert_datum(Datum.f64(2.4), ft).get_int() == 2
    assert convert_datum(Datum.string("3.6"), ft).get_int() == 4


def test_convert_decimal_quantize():
    ft = new_field_type(my.TypeNewDecimal)
    ft.flen, ft.decimal = 10, 2
    d = convert_datum(Datum.string("1.005"), ft)
    assert d.val == Decimal("1.01")
    d = convert_datum(Datum.f64(2.5), ft)
    assert d.val == Decimal("2.50")


def test_convert_string_flen():
    ft = new_field_type(my.TypeVarchar)
    ft.flen = 3
    assert convert_datum(Datum.string("abc"), ft).get_string() == "abc"
    with pytest.raises(errors.OverflowError_):
        convert_datum(Datum.string("abcd"), ft)


def test_convert_time():
    ft = new_field_type(my.TypeDate)
    d = convert_datum(Datum.string("1998-09-02 11:22:33"), ft)
    assert str(d.val) == "1998-09-02"
    ft2 = new_field_type(my.TypeDatetime)
    d2 = convert_datum(Datum.string("19980902112233"), ft2)
    assert str(d2.val) == "1998-09-02 11:22:33"


def test_time_packed_roundtrip():
    t = parse_time("2026-07-29 11:30:45.123456")
    from tidb_tpu.types.time_types import Time
    assert Time.from_packed_int(t.to_packed_int()).dt == t.dt


def test_agg_field_types():
    dec = new_field_type(my.TypeNewDecimal)
    dec.decimal = 2
    assert agg_field_type("count", dec).tp == my.TypeLonglong
    assert agg_field_type("sum", dec).tp == my.TypeNewDecimal
    assert agg_field_type("sum", new_field_type(my.TypeDouble)).tp == my.TypeDouble
    assert agg_field_type("avg", dec).decimal == 6
    assert agg_field_type("max", dec).tp == my.TypeNewDecimal


def test_duration_two_part_is_hours_minutes():
    # regression: 'HH:MM' must parse as hours:minutes (MySQL), not MM:SS
    d = parse_duration("11:30", fsp=0)
    assert str(d) == "11:30:00"
    assert parse_duration("-2:05").to_number() == -20500


def test_wide_decimal_quantize_no_crash():
    from tidb_tpu.types.convert import quantize_decimal
    from decimal import Decimal
    v = Decimal("12345678901234567890123456789.1")
    assert quantize_decimal(v, 2) == Decimal("12345678901234567890123456789.10")
