"""Differential suite for EXPRESSION aggregate arguments (PR 18): the
arg-plane compiler (ops.exprc.compile_arg_plane) lowers arithmetic over
columns into jitted plane programs evaluated INSIDE the batched states
dispatch (kernels.region_agg_states_batched) — no extra device round
trip. The contract across 1/2/4/8 regions: zero columnar fallbacks and
row-for-row identity with BOTH oracles — the per-region host exprc rung
(failpoint copr/arg_plane) and the row protocol (kill switch) — through
NULL propagation (`a * (1 - b)` with NULL b), decimal rescale exactness
at mixed scales, the int-overflow pre-guard's row-protocol bail,
float-SUM/AVG sequential-rounding bit parity, every failpoint rung of
the states ladder, and mid-scan split/merge."""

from __future__ import annotations

import itertools

import pytest

from tidb_tpu import failpoint, metrics, tablecodec as tc
from tidb_tpu.copr import columnar_region
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 240

# every query's aggregate argument is an EXPRESSION — these must all
# ride the fused arg-plane states path with ZERO fallbacks
QUERIES = [
    # NULL propagation: b NULL every 7th row → a*(1-b) contributes
    # nothing for that row (valid-plane fold), exactly like the row path
    "select g, sum(a * (1 - b)), count(*) from t group by g order by g",
    # decimal rescale at MIXED scales: p scale 2, q scale 4 → product
    # scale 6, sums exact at full precision
    "select g, sum(p * (1 - q)), min(p - q), max(p + q) from t "
    "group by g order by g",
    # pure-int IntDiv / Mod (Go truncation semantics on device)
    "select g, sum(a div (b + 1)), sum(a % (b + 1)) from t "
    "where b is not null group by g order by g",
    # float expression args: SUM/AVG must keep the row path's
    # sequential rounding bit for bit (device plane, host accumulation)
    "select g, sum(f * 2), avg(f + 0.5), sum(f / 2) from t "
    "group by g order by g",
    # unary minus + int avg with NULL propagation
    "select g, sum(-a), avg(a * 3 - b) from t group by g order by g",
    # scalar (no group by): G == 1 per region
    "select sum(p * q), count(*) from t",
]


def _build(n_regions: int) -> Session:
    store = new_store(f"cluster://3/argplanes{next(_id)}")
    s = Session(store)
    s.execute("create database ap")
    s.execute("use ap")
    s.execute(
        "create table t (id bigint primary key, a bigint, b bigint, "
        "p decimal(10,2), q decimal(8,4), f double, g varchar(4), "
        "big bigint)")
    vals = []
    for i in range(1, N_ROWS + 1):
        b = "null" if i % 7 == 0 else str(i % 5)
        vals.append(
            f"({i}, {i % 23}, {b}, {i % 40 + (i % 4) * 0.25}, "
            f"{(i % 13) / 16}, {(i % 9) * 0.01!r}, "
            f"'{('A', 'B', 'C')[i % 3]}', {(1 << 40) + i})")
    s.execute("insert into t values " + ",".join(vals))
    if n_regions > 1:
        tid = s.info_schema().table_by_name("ap", "t").info.id
        step = N_ROWS // n_regions
        store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _c(name: str) -> int:
    return metrics.counter(name).value


def _all(s: Session, queries=QUERIES) -> list:
    return [s.execute(q)[0].values() for q in queries]


def _row_protocol(s: Session, queries=QUERIES) -> list:
    s.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        return [s.execute(q)[0].values() for q in queries]
    finally:
        s.execute("set global tidb_tpu_columnar_scan = 1")


def _norm(rows):
    out = []
    for row in rows:
        nr = []
        for v in row:
            if v is None:
                nr.append(None)
            else:
                try:
                    nr.append(round(float(v), 9))
                except (TypeError, ValueError):
                    nr.append(v.decode() if isinstance(v, bytes) else v)
        out.append(nr)
    return out


@pytest.mark.parametrize("n_regions", [1, 2, 4, 8])
def test_arg_planes_zero_fallbacks_and_row_parity(n_regions, monkeypatch):
    """The headline invariant: every expression-argument aggregate runs
    columnar (zero fallbacks), its programs counted on the arg-plane
    metrics, with answers identical to the row protocol."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(n_regions)
    fb0 = _c("distsql.columnar_fallbacks")
    sp0 = _c("copr.arg_plane.specs")
    ap0 = _c("distsql.columnar_arg_planes")
    got = _all(s)
    assert _c("distsql.columnar_fallbacks") == fb0, \
        "an expression-argument aggregate fell off the columnar tier"
    assert _c("copr.arg_plane.specs") - sp0 >= len(QUERIES), \
        "no aggregate spec lowered through the arg-plane compiler"
    assert _c("distsql.columnar_arg_planes") - ap0 >= len(QUERIES), \
        "no statement counted arg-plane states partials"
    want = _row_protocol(s)
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"arg-plane states diverged from the row protocol on {q!r}"


def test_decimal_rescale_exactness_mixed_scales(monkeypatch):
    """Decimal products at mixed scales (2 x 4 → 6) sum EXACTLY — the
    fixed-point rescale on device matches the row path's arbitrary-
    precision Decimal arithmetic value for value, not approximately."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    q = ("select g, sum(p * (1 - q)), sum(p * q) from t "
         "group by g order by g")
    got = s.execute(q)[0].values()
    want = _row_protocol(s, [q])[0]
    for g, w in zip(got, want):
        for a, b in zip(g, w):
            # exact Decimal equality at full precision, AND identical
            # rendering — the states route must reproduce the row
            # protocol's codec-canonical display scale, not just the
            # numeric value
            assert a == b, f"decimal rescale diverged: {a} != {b}"
            assert str(a) == str(b), \
                f"decimal display scale diverged: {a!r} != {b!r}"


def test_float_sum_avg_bit_parity(monkeypatch):
    """Float SUM/AVG over expression args stay bit-identical to the row
    protocol: the plane computes on device but reads back row-space so
    the host accumulates in row order (np.add.at), reproducing the row
    path's sequential rounding exactly."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    q = ("select g, sum(f * 2), avg(f + 0.5), sum(f / 2) from t "
         "group by g order by g")
    got = s.execute(q)[0].values()
    want = _row_protocol(s, [q])[0]
    assert got == want     # bitwise-identical floats


def test_int_overflow_preguard_bails_to_row_protocol(monkeypatch):
    """big*big exceeds the int64 plane bound: the compile-time bound
    walk rejects the program (mask-independent, so the states probe
    agrees) and the statement degrades to the row protocol — which
    raises MySQL's BIGINT-out-of-range error, never a silently wrapped
    plane sum. The columnar route must surface the SAME error."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    q = "select g, sum(big * big) from t group by g order by g"
    with pytest.raises(Exception) as col_err:
        s.execute(q)
    s.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        with pytest.raises(Exception) as row_err:
            s.execute(q)
    finally:
        s.execute("set global tidb_tpu_columnar_scan = 1")
    assert "out of range" in str(col_err.value)
    assert type(col_err.value) is type(row_err.value)


def test_unpushable_div_degrades_with_parity(monkeypatch):
    """Div outside float context (row side divides in exact Decimal) is
    rejected by the arg-plane compiler — the statement's regions answer
    through the row protocol with identical results (the certified
    bottom rung, counted as fallbacks, never wrong answers)."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    q = "select g, sum(p / 2) from t group by g order by g"
    got = s.execute(q)[0].values()
    want = _row_protocol(s, [q])[0]
    assert _norm(got) == _norm(want)


def test_arg_plane_failpoint_lowers_to_host_exprc(monkeypatch):
    """copr/arg_plane forces every program off the fused states kernel
    onto the per-region host exprc rung (copr.degraded_arg_plane):
    answers bit-identical, still zero row-protocol fallbacks."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _all(s)
    deg = metrics.counter("copr.degraded_arg_plane")
    fb0, d0 = _c("distsql.columnar_fallbacks"), deg.value
    failpoint.enable("copr/arg_plane", action="return", value=True)
    try:
        got = _all(s)
    finally:
        failpoint.disable("copr/arg_plane")
    assert deg.value > d0, \
        "copr/arg_plane never lowered a program to the host exprc rung"
    assert _c("distsql.columnar_fallbacks") == fb0, \
        "the host exprc rung fell through to the row protocol"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"host exprc rung diverged from the fused kernel on {q!r}"


def test_device_fault_ladder_bottoms_out_with_arg_planes(monkeypatch):
    """device/agg_states takes out the device states rungs under
    arg-plane reductions: programs lower host-side
    (copr.degraded_arg_plane via the fault path) and the statement still
    answers through the states channel — answers unchanged."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _all(s)
    deg = metrics.counter("copr.degraded_states_to_host")
    d0 = deg.value
    failpoint.enable("device/agg_states")
    try:
        got = _all(s)
    finally:
        failpoint.disable("device/agg_states")
    assert deg.value > d0, \
        "device/agg_states never pushed the states ladder to the host"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"host-ladder answers diverged on {q!r}"


def test_region_fault_bails_to_row_protocol_with_parity(monkeypatch):
    """copr/agg_states (region-time typed fault) drops every region to
    the row protocol — the bottom of the ladder — with identical
    answers for expression-argument aggregates."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _all(s)
    fb0 = _c("distsql.columnar_fallbacks")
    failpoint.enable("copr/agg_states")
    try:
        got = _all(s)
    finally:
        failpoint.disable("copr/agg_states")
    assert _c("distsql.columnar_fallbacks") > fb0, \
        "copr/agg_states never degraded a region to the row protocol"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"row-protocol bottom rung diverged on {q!r}"


def test_mid_scan_split_and_merge_rebatch(monkeypatch):
    """A split/merge injected DURING the fan-out: the stale-epoch retry
    re-collects payloads and the finisher still evaluates every
    arg-plane program over the NEW region set — answers unchanged."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    store = s.store
    want = _all(s)
    tid = s.info_schema().table_by_name("ap", "t").info.id

    def mutate_split(st):
        st.cluster.split_keys([tc.encode_row_key(tid, 33),
                               tc.encode_row_key(tid, 177)])

    def mutate_merge(st):
        regions = st.cluster.regions
        for i in range(len(regions) - 1):
            if regions[i].start:
                st.cluster.merge(regions[i].region_id,
                                 regions[i + 1].region_id)
                return

    for mutate in (mutate_split, mutate_merge):
        orig = store.rpc.cop_request
        state = {"n": 0, "done": False}

        def hook(ctx, sel, ranges, read_ts, orig=orig, state=state,
                 mutate=mutate):
            state["n"] += 1
            if state["n"] == 2 and not state["done"]:
                state["done"] = True
                mutate(store)
            return orig(ctx, sel, ranges, read_ts)

        store.rpc.cop_request = hook
        try:
            got = _all(s)
        finally:
            store.rpc.cop_request = orig
        assert state["done"]
        for q, g, w in zip(QUERIES, got, want):
            assert _norm(g) == _norm(w), \
                f"mid-scan topology change diverged on {q!r}"


def test_serial_route_matches_batched(monkeypatch):
    """BATCH_STATES_ENABLED=False pins every region to the serial
    per-region states kernel — arg-plane programs evaluate through
    kernels.region_agg_states (not the batched variant) with identical
    answers."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _all(s)
    monkeypatch.setattr(columnar_region, "BATCH_STATES_ENABLED", False)
    got = _all(s)
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"serial states route diverged on {q!r}"


def test_q1_shape_two_dispatch_budget(monkeypatch):
    """The real-q1 shape (filtered, expression args, grouped) costs at
    most 2 device dispatches for the whole fan-out: one batched filter,
    one batched states with the arg programs fused in."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    q = ("select g, sum(p * (1 - q)), count(*) from t "
         "where a <= 18 group by g order by g")
    s.execute(q)     # warm (pack + jit)
    disp = (metrics.counter("copr.states_batch.dispatches"),
            metrics.counter("copr.mesh.near_data_dispatches"),
            metrics.counter("copr.states_batch.serial_dispatches"),
            metrics.counter("copr.filter.batched_dispatches"))
    d0 = sum(c.value for c in disp)
    got = s.execute(q)[0].values()
    assert sum(c.value for c in disp) - d0 <= 2, \
        "real-q1 shape exceeded the 2-device-dispatch budget"
    want = _row_protocol(s, [q])[0]
    assert _norm(got) == _norm(want)
