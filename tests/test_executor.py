"""Executor integration tests: SQL text → plan → executor tree → rows,
without the session layer (that arrives next; these pin the engine).

Mirrors executor/executor_test.go shapes at smaller scale.
"""

import pytest

from tidb_tpu import mysqldef as my
from tidb_tpu.ddl.ddl import ColumnSpec, IndexSpec
from tidb_tpu.domain import Domain, clear_domains
from tidb_tpu.executor import ExecContext, ExecutorBuilder
from tidb_tpu.localstore import LocalStore
from tidb_tpu.parser.parser import Parser
from tidb_tpu.plan import optimize
from tidb_tpu.types.field_type import FieldType


def _ft(tp, flag=0, flen=-1, dec=-1):
    return FieldType(tp, flag, flen, dec)


@pytest.fixture
def ctx():
    clear_domains()
    store = LocalStore()
    dom = Domain(store)
    dom.ddl.create_schema("test")
    dom.ddl.create_table("test", "t", [
        ColumnSpec("id", _ft(my.TypeLonglong)),
        ColumnSpec("a", _ft(my.TypeLong)),
        ColumnSpec("b", _ft(my.TypeVarchar, flen=64)),
        ColumnSpec("c", _ft(my.TypeDouble)),
    ], [IndexSpec("primary", ["id"], primary=True),
        IndexSpec("idx_b", ["b"])])
    dom.ddl.create_table("test", "s", [
        ColumnSpec("id", _ft(my.TypeLonglong)),
        ColumnSpec("t_id", _ft(my.TypeLonglong)),
        ColumnSpec("v", _ft(my.TypeVarchar, flen=64)),
    ], [IndexSpec("primary", ["id"], primary=True)])
    return ExecContext(store, dom, "test")


def run(ctx, sql, commit=True):
    stmt = Parser().parse_one(sql)
    plan = optimize(stmt, ctx, ctx.client, ctx.dirty_tables)
    exec_ = ExecutorBuilder(ctx).build(plan)
    rows = []
    while True:
        r = exec_.next()
        if r is None:
            break
        rows.append([d.val for d in r])
    exec_.close()
    if commit:
        ctx.commit()
    return rows


def seed(ctx):
    run(ctx, "insert into t values (1, 10, 'x', 1.5), (2, 20, 'y', 2.5), "
             "(3, 30, 'x', 3.5), (4, 40, 'z', 4.5), (5, 50, 'y', null)")


class TestReadPath:
    def test_insert_and_scan(self, ctx):
        seed(ctx)
        rows = run(ctx, "select * from t")
        assert len(rows) == 5
        assert rows[0] == [1, 10, "x", 1.5]

    def test_where_pushed(self, ctx):
        seed(ctx)
        assert run(ctx, "select id from t where a > 25") == [[3], [4], [5]]

    def test_pk_range(self, ctx):
        seed(ctx)
        assert run(ctx, "select id from t where id between 2 and 4") == \
            [[2], [3], [4]]

    def test_projection_exprs(self, ctx):
        seed(ctx)
        rows = run(ctx, "select a * 2 + 1, upper(b) from t where id = 1")
        assert rows == [[21, "X"]]

    def test_agg_pushdown_end_to_end(self, ctx):
        seed(ctx)
        rows = run(ctx, "select count(*), sum(a), min(c), max(c) from t")
        [[cnt, s, mn, mx]] = rows
        assert cnt == 5 and int(s) == 150 and mn == 1.5 and mx == 4.5

    def test_group_by(self, ctx):
        seed(ctx)
        rows = run(ctx, "select b, count(*), sum(a) from t "
                        "group by b order by b")
        assert rows == [["x", 2, 40], ["y", 2, 70], ["z", 1, 40]]

    def test_group_by_multi_region(self, ctx):
        seed(ctx)
        from tidb_tpu import tablecodec as tc
        tbl = ctx.info_schema().table_by_name("test", "t")
        ctx.store.regions.split_keys([tc.encode_row_key(tbl.info.id, 3)])
        rows = run(ctx, "select b, count(*) from t group by b order by b")
        assert rows == [["x", 2], ["y", 2], ["z", 1]]

    def test_having(self, ctx):
        seed(ctx)
        rows = run(ctx, "select b, count(*) as cnt from t group by b "
                        "having cnt > 1 order by b")
        assert rows == [["x", 2], ["y", 2]]

    def test_order_limit(self, ctx):
        seed(ctx)
        assert run(ctx, "select id from t order by a desc limit 2") == \
            [[5], [4]]
        assert run(ctx, "select id from t order by c limit 1") == [[5]]

    def test_distinct(self, ctx):
        seed(ctx)
        rows = run(ctx, "select distinct b from t order by b")
        assert rows == [["x"], ["y"], ["z"]]

    def test_index_single_read(self, ctx):
        seed(ctx)
        rows = run(ctx, "select id from t where b = 'y'")
        assert sorted(rows) == [[2], [5]]

    def test_index_double_read(self, ctx):
        seed(ctx)
        rows = run(ctx, "select a, c from t where b = 'x'")
        assert sorted(rows) == [[10, 1.5], [30, 3.5]]

    def test_select_no_from(self, ctx):
        assert run(ctx, "select 1 + 1, 'hi'") == [[2, "hi"]]

    def test_count_empty_table(self, ctx):
        assert run(ctx, "select count(*) from t") == [[0]]

    def test_avg_null_handling(self, ctx):
        seed(ctx)
        [[avg_c]] = run(ctx, "select avg(c) from t")
        assert float(avg_c) == pytest.approx(3.0)  # null row excluded


class TestJoins:
    def seed_join(self, ctx):
        seed(ctx)
        run(ctx, "insert into s values (1, 1, 'one'), (2, 1, 'uno'), "
                 "(3, 3, 'three'), (4, 99, 'orphan')")

    def test_inner_join(self, ctx):
        self.seed_join(ctx)
        rows = run(ctx, "select t.id, s.v from t join s on t.id = s.t_id "
                        "order by t.id, s.v")
        assert rows == [[1, "one"], [1, "uno"], [3, "three"]]

    def test_left_join(self, ctx):
        self.seed_join(ctx)
        rows = run(ctx, "select t.id, s.v from t left join s on t.id = s.t_id "
                        "where t.id <= 2 order by t.id, s.v")
        assert rows == [[1, "one"], [1, "uno"], [2, None]]

    def test_cross_join(self, ctx):
        self.seed_join(ctx)
        [[n]] = run(ctx, "select count(*) from t, s")
        assert n == 20


class TestWritePath:
    def test_update(self, ctx):
        seed(ctx)
        run(ctx, "update t set a = a + 100 where b = 'x'")
        assert run(ctx, "select id, a from t where a > 100 order by id") == \
            [[1, 110], [3, 130]]

    def test_delete(self, ctx):
        seed(ctx)
        run(ctx, "delete from t where b = 'y'")
        assert run(ctx, "select id from t") == [[1], [3], [4]]

    def test_update_with_limit(self, ctx):
        seed(ctx)
        run(ctx, "update t set a = 0 order by id desc limit 2")
        assert run(ctx, "select id from t where a = 0 order by id") == \
            [[4], [5]]

    def test_insert_defaults(self, ctx):
        run(ctx, "insert into t (id, a) values (9, 7)")
        rows = run(ctx, "select id, a, b from t")
        assert rows == [[9, 7, None]]

    def test_insert_select(self, ctx):
        """INSERT ... SELECT: the select subplan must be physicalized
        (regression: executor got the logical projection)."""
        seed(ctx)
        run(ctx, "insert into t (id, a, b) "
                 "select id + 100, a * 2, b from t where a <= 20")
        assert run(ctx, "select id, a from t where id > 100 order by id") \
            == [[101, 20], [102, 40]]

    def test_insert_missing_not_null_errors(self, ctx):
        from tidb_tpu import errors
        with pytest.raises(errors.ExecError):
            run(ctx, "insert into t (a) values (7)")
        ctx.rollback()

    def test_duplicate_pk_error(self, ctx):
        seed(ctx)
        from tidb_tpu import errors
        with pytest.raises(errors.DupEntryError):
            run(ctx, "insert into t values (1, 0, 'dup', 0)")
        ctx.rollback()

    def test_read_own_writes_union_scan(self, ctx):
        seed(ctx)
        # same-txn read after write: UnionScan merges the txn buffer
        run(ctx, "insert into t values (6, 60, 'w', 6.5)", commit=False)
        rows = run(ctx, "select id from t where a >= 50", commit=False)
        assert rows == [[5], [6]]
        run(ctx, "update t set a = 99 where id = 1", commit=False)
        rows = run(ctx, "select id from t where a = 99", commit=False)
        assert rows == [[1]]
        run(ctx, "delete from t where id = 2", commit=False)
        rows = run(ctx, "select id from t", commit=False)
        assert rows == [[1], [3], [4], [5], [6]]
        ctx.commit()
        assert run(ctx, "select count(*) from t") == [[5]]


from tests.testkit import TestKit


class TestVectorJoin:
    """The numpy sort-merge fast path in HashJoinExec must be invisible:
    same rows, same order as the dict build/probe path."""

    def _tk(self):
        tk = TestKit()
        tk.exec("create database vj; use vj")
        return tk

    def test_left_drain_bailout_preserves_rows(self):
        """Review regression: an unsigned LEFT key bails out of the vector
        path AFTER draining both children — the slow path must replay
        them, not silently join an exhausted left side."""
        tk = self._tk()
        tk.exec("create table t1 (a bigint unsigned)")
        tk.exec("create table t2 (b bigint)")
        tk.exec("insert into t1 values (1), (2)")
        tk.query("select * from t1 left join t2 on t1.a = t2.b").check(
            [[1, None], [2, None]])
        tk.exec("insert into t2 values (2), (3)")
        # u64 vs i64 keys encode differently in the dict path's codec, so
        # they never match — the point here is the rows ARE replayed (the
        # left-join output above proves non-empty replay)
        tk.query("select * from t1 join t2 on t1.a = t2.b").check([])
        tk.query("select * from t1 left join t2 on t1.a = t2.b").check(
            [[1, None], [2, None]])

    def test_mixed_kind_left_key_bails_and_replays(self):
        """A derived left side mixing int and float key kinds bails out
        of the vector path after BOTH children were drained; the slow
        path must still produce the float-key match."""
        tk = self._tk()
        tk.exec("create table t2 (b double)")
        tk.exec("insert into t2 values (2.0)")
        tk.query(
            "select k, b from "
            "(select 1 as k union all select 2.0e0 as k) x "
            "join t2 on x.k = t2.b").check([[2.0, 2.0]])

    def test_vector_and_dict_paths_agree(self):
        from tidb_tpu.executor import executors
        tk = self._tk()
        tk.exec("create table l (id bigint primary key, k int, v double)")
        tk.exec("create table r (id bigint primary key, k int, w int)")
        tk.exec("insert into l values (1, 1, 1.5), (2, 2, null), "
                "(3, null, 3.5), (4, 2, 4.5), (5, 9, 5.5)")
        tk.exec("insert into r values (10, 2, 20), (11, 2, 21), "
                "(12, 1, 22), (13, null, 23)")
        queries = [
            "select l.id, r.id from l join r on l.k = r.k",
            "select l.id, r.id from l left join r on l.k = r.k",
            "select l.id, r.w from l join r on l.k = r.k and l.v > 2",
            "select l.id, r.id from l left join r on l.k = r.k "
            "where l.id > 1",
        ]
        results = {}
        for forced in (False, True):
            orig = executors.HashJoinExec._try_vector_join
            if forced:
                executors.HashJoinExec._try_vector_join = \
                    lambda self: False
            try:
                results[forced] = [tk.query(q).rows for q in queries]
            finally:
                executors.HashJoinExec._try_vector_join = orig
        assert results[False] == results[True]
        # sanity: the inner join actually matched rows
        assert len(results[False][0]) == 5
