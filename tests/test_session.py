"""Session-level integration tests over the TestKit harness.

Mirrors session_test.go / executor/executor_test.go SQL shapes: full stack
from SQL text through parse/plan/execute/commit against memory storage.
"""

import pytest

from tidb_tpu import errors
from testkit import TestKit


@pytest.fixture
def tk():
    t = TestKit()
    t.exec("create database test")
    t.exec("use test")
    return t


class TestBasics:
    def test_bootstrap_created_system_tables(self, tk):
        r = tk.query("show databases")
        assert ["mysql"] in r.rows and ["test"] in r.rows
        r = tk.query("select User from mysql.user")
        r.check([["root"]])

    def test_ddl_and_crud(self, tk):
        tk.exec("create table t (id bigint primary key, v varchar(32), "
                "n int default 7)")
        tk.exec("insert into t values (1, 'a', 10), (2, 'b', 20)")
        tk.exec("insert into t (id, v) values (3, 'c')")
        tk.query("select * from t order by id").check(
            [[1, "a", 10], [2, "b", 20], [3, "c", 7]])
        tk.exec("update t set v = concat(v, '!') where id < 3")
        tk.query("select v from t order by id").check([["a!"], ["b!"], ["c"]])
        tk.exec("delete from t where id = 2")
        tk.query("select count(*) from t").check([[2]])

    def test_show_and_explain(self, tk):
        tk.exec("create table t (id bigint primary key, v varchar(32))")
        tk.query("show tables").check([["t"]])
        r = tk.query("show create table t")
        assert "CREATE TABLE `t`" in r.rows[0][1]
        r = tk.query("show columns from t")
        assert r.rows[0][0] == "id"
        r = tk.query("explain select * from t where id > 3")
        assert any("tscan" in row[0] for row in r.rows)

    def test_sysvars(self, tk):
        tk.exec("set @@tidb_distsql_scan_concurrency = 4")
        assert tk.session.distsql_concurrency() == 4
        tk.exec("set @x = 41")
        tk.query("select @x + 1").check([[42]])
        r = tk.query("show variables like 'autocommit'")
        r.check([["autocommit", "1"]])

    def test_alter_table(self, tk):
        tk.exec("create table t (id bigint primary key)")
        tk.exec("insert into t values (1)")
        tk.exec("alter table t add column v varchar(16) default 'd'")
        tk.query("select v from t").check([["d"]])
        tk.exec("alter table t drop column v")
        tk.query("select * from t").check([[1]])

    def test_create_index_with_backfill(self, tk):
        tk.exec("create table t (id bigint primary key, v varchar(16))")
        tk.exec("insert into t values (1,'b'), (2,'a'), (3,'b')")
        tk.exec("create index idx_v on t (v)")
        tk.query("select id from t where v = 'b' order by id").check([[1], [3]])
        tk.exec("admin check table t")

    def test_admin_show_ddl(self, tk):
        r = tk.query("admin show ddl")
        assert len(r.rows) == 1


class TestTransactions:
    def test_explicit_txn_commit(self, tk):
        tk.exec("create table t (id bigint primary key)")
        tk.exec("begin")
        tk.exec("insert into t values (1)")
        tk.query("select count(*) from t").check([[1]])  # RYOW
        tk.exec("commit")
        tk.query("select count(*) from t").check([[1]])

    def test_explicit_txn_rollback(self, tk):
        tk.exec("create table t (id bigint primary key)")
        tk.exec("begin")
        tk.exec("insert into t values (1)")
        tk.exec("rollback")
        tk.query("select count(*) from t").check([[0]])

    def test_two_sessions_isolation(self, tk):
        tk.exec("create table t (id bigint primary key, v int)")
        tk.exec("insert into t values (1, 10)")
        tk2 = tk.new_session()
        tk2.exec("use test")
        tk2.exec("begin")
        tk2.query("select v from t where id = 1").check([[10]])
        tk.exec("update t set v = 20 where id = 1")
        # snapshot isolation: tk2's txn still sees the old value
        tk2.query("select v from t where id = 1").check([[10]])
        tk2.exec("commit")
        tk2.query("select v from t where id = 1").check([[20]])

    def test_optimistic_retry_on_conflict(self, tk):
        tk.exec("create table t (id bigint primary key, v int)")
        tk.exec("insert into t values (1, 0)")
        tk2 = tk.new_session()
        tk2.exec("use test")
        tk2.exec("begin")
        tk2.exec("update t set v = v + 1 where id = 1")
        # conflicting write committed by session 1 after tk2's start
        tk.exec("update t set v = v + 10 where id = 1")
        tk2.exec("commit")  # conflict → retry replays the update
        tk.query("select v from t where id = 1").check([[11]])

    def test_write_conflict_autocommit_retries(self, tk):
        tk.exec("create table t (id bigint primary key, v int)")
        tk.exec("insert into t values (1, 0)")
        # autocommit single statements retry internally; both land
        for _ in range(5):
            tk.exec("update t set v = v + 1 where id = 1")
        tk.query("select v from t").check([[5]])


class TestMultiStatement:
    def test_multi_statement_execute(self, tk):
        tk.exec("create table t (id bigint primary key); "
                "insert into t values (1); insert into t values (2)")
        tk.query("select count(*) from t").check([[2]])


class TestReviewRegressions:
    def test_autocommit_reads_release_snapshot(self, tk):
        """A read-only autocommit statement must not pin its snapshot:
        later reads see other sessions' commits."""
        tk.exec("create table t (id bigint primary key, v int)")
        tk.exec("insert into t values (1, 10)")
        tk.query("select v from t").check([[10]])
        tk2 = tk.new_session()
        tk2.exec("use test")
        tk2.exec("update t set v = 20 where id = 1")
        tk.query("select v from t").check([[20]])

    def test_set_global_persists_to_table(self, tk):
        tk.exec("set @@global.version_comment = \"it's mine\"")
        tk.query("select variable_value from mysql.global_variables "
                 "where variable_name = 'version_comment'").check(
            [["it's mine"]])
        assert tk.session.global_vars.get("version_comment") == "it's mine"

    def test_global_concurrency_respected(self, tk):
        tk.exec("set @@global.tidb_distsql_scan_concurrency = 4")
        tk2 = tk.new_session()
        assert tk2.session.distsql_concurrency() == 4


def test_set_transaction_isolation_end_to_end():
    """Round-4 verdict missing #2: drivers issue SET TRANSACTION ISOLATION
    LEVEL at connection setup. REPEATABLE READ is the engine's truth and
    sets cleanly; other levels store the requested value but leave a
    warning (snapshot isolation is what actually runs)."""
    tk = TestKit()
    tk.exec("set session transaction isolation level repeatable read")
    assert tk.query("show warnings").rows == []
    tk.query("select @@tx_isolation").check([["REPEATABLE-READ"]])
    tk.exec("set transaction isolation level read committed")
    warn = tk.query("show warnings").rows
    assert len(warn) == 1 and warn[0][0] == "Warning"
    assert "READ-COMMITTED" in warn[0][2]
    tk.query("select @@tx_isolation").check([["READ-COMMITTED"]])
    # diagnostics area resets on the next non-diagnostic statement
    assert tk.query("show warnings").rows == []
    with pytest.raises(errors.TiDBError):
        tk.exec("set tx_isolation = 'chaos'")


def test_microsecond_builtin():
    tk = TestKit()
    tk.query(
        "select microsecond('2024-01-01 10:00:00.123456')"
    ).check([[123456]])
    tk.query("select microsecond(null)").check([[None]])


def test_isolation_alias_and_global_warning():
    """tx_isolation and transaction_isolation are one variable with two
    names (Connector/J 8 reads the latter), and a GLOBAL-scope isolation
    warning must survive the internal persist statements (review
    findings: alias missing; nested execute wiped the warning)."""
    tk = TestKit()
    tk.exec("set transaction isolation level serializable")
    tk.query("select @@tx_isolation, @@transaction_isolation").check(
        [["SERIALIZABLE", "SERIALIZABLE"]])
    tk.exec("set transaction_isolation = 'READ-COMMITTED'")
    tk.query("select @@tx_isolation").check([["READ-COMMITTED"]])
    tk.exec("set global transaction isolation level read uncommitted")
    warn = tk.query("show warnings").rows
    assert len(warn) == 1 and "READ-UNCOMMITTED" in warn[0][2]
