"""Out-of-core execution tier (PR 20): the partitioned external sort,
the spilling group-by states, and window functions over the membudget
ledger — every operator differentially tested against TWO oracles: the
budget-0 kill switch (host/unpartitioned route) and the row protocol
(python comparator + streaming aggregation contexts). Chaos schedules
inject device/oom mid-pass and assert the pass-level checkpointing
replayed completed partitions instead of re-running them.

PR 20 adds NO new sysvar: the whole tier is governed by the existing
GLOBAL-only `tidb_tpu_hbm_budget_bytes` (its GLOBAL-only scoping and
spec validation are pinned in test_membudget) — the new-knob sysvar
clause of this suite is therefore vacuously covered, and the kill-switch
tests below pin that budget 0 disables every new partitioned route.
"""

from __future__ import annotations

import itertools

import numpy as np
import pytest

from tidb_tpu import failpoint, metrics
from tidb_tpu.ops import TpuClient, extsort, kernels, membudget
from tidb_tpu.session import new_store
from tidb_tpu.types import Datum
from tests.testkit import TestKit


@pytest.fixture(autouse=True)
def _reset():
    yield
    membudget.set_budget(membudget.DEFAULT_BUDGET_SPEC)
    membudget.set_stats_provider(None)
    failpoint.disable_all()


def _cnt(name: str) -> int:
    return metrics.counter(name).value


def _base() -> int:
    """Live ledger charge (pinned planes from earlier tests + any
    reservations): budgets in this suite ride on top of it so the
    intended HEADROOM is what the operators actually see."""
    return sum(membudget.usage())


def _pieces_budget(est: int, pieces: int) -> int:
    """Budget whose pass target is est // pieces. extsort._pass_target
    takes max(headroom, budget // 8), so under a full-suite run — where
    earlier tests leave megabytes of pinned plane-cache charge riding in
    the budget — the budget//8 floor would swallow a fixed headroom and
    collapse the partitioning to one pass. Callers size n (via _scaled_n)
    so est/pieces clears _base()/7, keeping budget//8 at or below the
    intended target."""
    t = est // pieces
    b = _base() + t
    assert b // 8 <= t, \
        "working set too small for the ambient pinned charge — scale n"
    return b


def _scaled_n(row_bytes: int, pieces: int, min_n: int) -> int:
    """Row count whose per-pass estimate (n*row_bytes/pieces) stays
    above _base()/7 plus a device-floor margin, so _pieces_budget's
    invariant holds regardless of how much pinned state the rest of the
    suite accumulated before this test ran."""
    return max(min_n, (_base() * pieces) // (7 * row_bytes) + pieces * 4096)


# ---------------------------------------------------------------------------
# partitioned external sort (ops.extsort.sort_order)
# ---------------------------------------------------------------------------

def _mk_sort_planes(n=20_000, seed=3, tied_primary=False):
    """Two key levels in np.lexsort convention (least-significant
    first): [sec_vals, sec_nulls, pri_vals, pri_nulls]."""
    rng = np.random.default_rng(seed)
    pri = np.zeros(n, np.int64) if tied_primary \
        else rng.integers(-1 << 40, 1 << 40, n)
    sec = rng.integers(0, 1 << 20, n)
    pnull = (rng.random(n) < 0.03).astype(np.int8)
    snull = (rng.random(n) < 0.03).astype(np.int8)
    return [sec.astype(np.int64), snull, pri.astype(np.int64), pnull]


class TestExternalSort:
    def test_single_device_pass_parity(self):
        planes = _mk_sort_planes(n=6_000)
        membudget.set_budget(_base() + (1 << 22))
        s0 = _cnt("copr.spill.sorts")
        order = extsort.sort_order(planes, 6_000)
        assert _cnt("copr.spill.sorts") == s0, \
            "an in-headroom sort took the partitioned route"
        assert np.array_equal(order, np.lexsort(planes))

    def test_partitioned_parity_and_counters(self):
        # est = n * (2*18 + 24) = 60 B/row; a ~half-est pass target
        # forces the range-partitioned route with >= 4096-row
        # (device-floor) pieces
        n = _scaled_n(60, 2, min_n=20_000)
        planes = _mk_sort_planes(n=n)
        membudget.set_budget(
            _pieces_budget(extsort.sort_bytes_estimate(planes, n), 2))
        s0, p0 = _cnt("copr.spill.sorts"), _cnt("copr.spill.sort_passes")
        st: dict = {}
        order = extsort.sort_order(planes, n, stats=st)
        assert st["spilled"] and st["sort_passes"] >= 2
        assert st["sort_partitions"] >= 2
        assert not st["sort_host_rung"]
        assert _cnt("copr.spill.sorts") == s0 + 1
        assert _cnt("copr.spill.sort_passes") - p0 == st["sort_passes"]
        assert np.array_equal(order, np.lexsort(planes))

    def test_kill_switch_and_device_floor(self):
        planes = _mk_sort_planes(n=20_000)
        membudget.set_budget(0)        # the kill switch: host comparator
        s0 = _cnt("copr.spill.sorts")
        assert np.array_equal(extsort.sort_order(planes, 20_000),
                              np.lexsort(planes))
        small = [p[:512] for p in planes]
        membudget.set_budget(_base() + (1 << 22))
        assert np.array_equal(extsort.sort_order(small, 512),
                              np.lexsort(small))
        assert _cnt("copr.spill.sorts") == s0, \
            "kill switch / sub-floor sorts touched the spill counters"

    def test_chaos_oom_checkpointed_resume(self):
        """device/oom fires every 3rd dispatch: completed partitions
        must checkpoint (their sorted slices replayed, not re-sorted)
        while the pass target escalates — answers unchanged."""
        n = _scaled_n(60, 4, min_n=20_000)
        planes = _mk_sort_planes(n=n, seed=11)
        oracle = np.lexsort(planes)
        membudget.set_budget(
            _pieces_budget(extsort.sort_bytes_estimate(planes, n), 4))
        c0 = _cnt("copr.spill.checkpoint_hits")
        e0 = _cnt("copr.spill.escalations")
        failpoint.enable("device/oom", when=("every", 3))
        try:
            order = extsort.sort_order(planes, n)
        finally:
            failpoint.disable("device/oom")
        assert _cnt("copr.spill.escalations") > e0, \
            "no pass ever escalated under the chaos schedule"
        assert _cnt("copr.spill.checkpoint_hits") > c0, \
            "an escalation replayed completed partitions from scratch"
        assert np.array_equal(order, oracle)

    def test_salted_two_level_split_on_tied_primary(self):
        """A primary key the range split cannot shrink (every row tied)
        descends to the secondary key — the salted two-level split —
        instead of dispatching an over-target pass."""
        n = _scaled_n(60, 2, min_n=20_000)
        planes = _mk_sort_planes(n=n, seed=5, tied_primary=True)
        membudget.set_budget(
            _pieces_budget(extsort.sort_bytes_estimate(planes, n), 2))
        h0 = _cnt("copr.spill.salted_splits")
        order = extsort.sort_order(planes, n)
        assert _cnt("copr.spill.salted_splits") > h0, \
            "the fully tied primary key never took the salted split"
        assert np.array_equal(order, np.lexsort(planes))


# ---------------------------------------------------------------------------
# spilling group-by states (ops.extsort.region_states_spill)
# ---------------------------------------------------------------------------

def _mk_segs(nregions=2, n=9_000, G=3_000, seed=7):
    rng = np.random.default_rng(seed)
    segs = []
    for _ in range(nregions):
        gid = rng.integers(0, G, n).astype(np.int64)
        vals = rng.integers(-1000, 1000, n).astype(np.int64)
        ok = rng.random(n) > 0.05
        ok2 = rng.random(n) > 0.5
        segs.append((gid, [("sum", vals, ok), ("min", vals, ok),
                           ("max", vals, ok), ("sum", None, ok2)], G))
    return segs


def _states_equal(a, b):
    for ra, rb in zip(a, b):
        for sa, sb in zip(ra, rb):
            if not np.array_equal(np.asarray(sa), np.asarray(sb)):
                return False
    return True


class TestSpillStates:
    def test_parity_vs_batched_oracle_and_counters(self):
        # 4 specs over 2 regions = 2*(4*17+8) = 152 B per row-index
        segs = _mk_segs(n=_scaled_n(152, 4, min_n=9_000))
        oracle = kernels.region_agg_states_batched(segs)
        membudget.set_budget(
            _pieces_budget(extsort.states_bytes_estimate(segs), 4))
        assert extsort.states_over_headroom(segs)
        g0, p0 = _cnt("copr.spill.groupbys"), \
            _cnt("copr.spill.groupby_passes")
        outs = extsort.region_states_spill(segs)
        assert _cnt("copr.spill.groupbys") == g0 + 1
        assert _cnt("copr.spill.groupby_passes") >= p0 + 2
        assert _states_equal(outs, oracle)

    def test_chaos_oom_checkpointed_resume(self):
        segs = _mk_segs(n=_scaled_n(152, 4, min_n=9_000), seed=13)
        oracle = kernels.region_agg_states_batched(segs)
        membudget.set_budget(
            _pieces_budget(extsort.states_bytes_estimate(segs), 4))
        c0 = _cnt("copr.spill.checkpoint_hits")
        e0 = _cnt("copr.spill.escalations")
        failpoint.enable("device/oom", when=("every", 3))
        try:
            outs = extsort.region_states_spill(segs)
        finally:
            failpoint.disable("device/oom")
        assert _cnt("copr.spill.escalations") > e0
        assert _cnt("copr.spill.checkpoint_hits") > c0, \
            "escalation re-ran completed states partitions"
        assert _states_equal(outs, oracle)

    def test_salted_hot_group_split(self):
        """One group owning every row: radix escalation can never
        separate a single group id, so its ROWS split by the salted
        positional hash and the partial states merge by monoid."""
        rng = np.random.default_rng(19)
        n = _scaled_n(42, 2, min_n=9_000)    # 2 specs, 1 region
        vals = rng.integers(-500, 500, n).astype(np.int64)
        ok = rng.random(n) > 0.1
        segs = [(np.zeros(n, np.int64),
                 [("sum", vals, ok), ("max", vals, ok)], 1)]
        oracle = kernels.region_agg_states_batched(segs)
        membudget.set_budget(
            _pieces_budget(extsort.states_bytes_estimate(segs), 2))
        h0 = _cnt("copr.spill.salted_splits")
        outs = extsort.region_states_spill(segs)
        assert _cnt("copr.spill.salted_splits") > h0, \
            "the hot group never took the salted row split"
        assert _states_equal(outs, oracle)

    def test_arg_planes_block_should_spill_not_over_headroom(self):
        class _FakeArgPlane:
            is_arg_plane = True

        n = 9_000
        gid = np.arange(n, dtype=np.int64) % 3000
        segs = [(gid, [("sum", _FakeArgPlane(), np.ones(n, bool))], 3000)]
        membudget.set_budget(_base() + 10_000)
        assert extsort.states_over_headroom(segs), \
            "the raw trigger must ignore arg planes (lengths only)"
        assert not extsort.states_should_spill(segs), \
            "the no-lowering gate must refuse row-aligned arg planes"


# ---------------------------------------------------------------------------
# SQL level: spilling group-by + external sort over a join
# ---------------------------------------------------------------------------

# stores are cached process-wide by URL: each builder call takes a
# fresh one so a rebuilt store never sees a prior test's schema
_store_seq = itertools.count(1)


def _bulk_insert(tk, db, name, rows):
    tbl = tk.session.info_schema().table_by_name(db, name)
    for start in range(0, len(rows), 4000):
        txn = tk.store.begin()
        tbl.add_records(txn, rows[start:start + 4000],
                        skip_unique_check=True)
        txn.commit()


GBY_Q = "select g, sum(v), count(*) from t group by g order by g"


def _gby_store() -> TestKit:
    tk = TestKit(store=new_store(f"cluster://3/tspill{next(_store_seq)}"))
    tk.exec("create database sg")
    tk.exec("use sg")
    tk.exec("create table t (id bigint primary key, g bigint, v bigint)")
    n = 6_000
    _bulk_insert(tk, "sg", "t",
                 [[Datum.i64(i), Datum.i64((i * 7919) % 3000),
                   Datum.i64((i * 31) % 1009)]
                  for i in range(1, n + 1)])
    from tidb_tpu import tablecodec as tc
    tid = tk.session.info_schema().table_by_name("sg", "t").info.id
    tk.store.cluster.split_keys([tc.encode_row_key(tid, n // 2 + 1)])
    # keep the DEFAULT region fan-out client: the spilling states path
    # lives in the region engine's batched dispatch, not the direct
    # TpuClient's fused grouped kernel
    tk.exec("set global tidb_tpu_dispatch_floor = 0")
    return tk


class TestSQLGroupBySpill:
    def test_high_ndv_groupby_parity_vs_kill_switch(self):
        tk = _gby_store()
        membudget.set_budget(0)
        oracle = tk.query(GBY_Q).rows
        membudget.set_budget(_base() + 120_000)
        g0, p0 = _cnt("copr.spill.groupbys"), \
            _cnt("copr.spill.groupby_passes")
        got = tk.query(GBY_Q).rows
        assert _cnt("copr.spill.groupbys") > g0, \
            "the high-NDV states table never spilled at SQL level"
        assert _cnt("copr.spill.groupby_passes") >= p0 + 2
        assert got == oracle
        # kill switch pins the unpartitioned batched dispatch
        membudget.set_budget(0)
        g1 = _cnt("copr.spill.groupbys")
        assert tk.query(GBY_Q).rows == oracle
        assert _cnt("copr.spill.groupbys") == g1

    def test_chaos_oom_mid_pass_checkpointed(self):
        tk = _gby_store()
        membudget.set_budget(0)
        oracle = tk.query(GBY_Q).rows
        membudget.set_budget(_base() + 120_000)
        c0 = _cnt("copr.spill.checkpoint_hits")
        failpoint.enable("device/oom", when=("every", 2))
        try:
            got = tk.query(GBY_Q).rows
        finally:
            failpoint.disable("device/oom")
        assert _cnt("copr.spill.checkpoint_hits") > c0, \
            "mid-pass OOM re-ran completed partitions"
        assert got == oracle


SORT_Q = ("select l.id, l.v, r.w from l join r on l.k = r.k "
          "order by l.v desc, l.id")


def _sort_store(n: int) -> TestKit:
    tk = TestKit(store=new_store(f"cluster://3/tspill{next(_store_seq)}"))
    tk.exec("create database ss")
    tk.exec("use ss")
    tk.exec("create table l (id bigint primary key, k bigint, v bigint)")
    tk.exec("create table r (k bigint primary key, w bigint)")
    _bulk_insert(tk, "ss", "l",
                 [[Datum.i64(i), Datum.i64(i % 3000),
                   Datum.i64((i * 2654435761) % 65521)]
                  for i in range(1, n + 1)])
    _bulk_insert(tk, "ss", "r",
                 [[Datum.i64(k), Datum.i64(k * 3)] for k in range(3000)])
    tk.store.set_client(TpuClient(tk.store, dispatch_floor_rows=0))
    return tk


class TestSQLOrderBySpill:
    def test_order_by_rides_partitioned_plane_sort(self):
        # size BEFORE the store exists (its plane pins grow the base);
        # pieces=4 in the sizing but 2 in the budget leaves 2x slack for
        # that growth, and _pieces_budget re-checks the invariant after
        n = _scaled_n(60, 4, min_n=12_000)
        tk = _sort_store(n)
        membudget.set_budget(0)
        oracle = tk.query(SORT_Q).rows    # row comparator (kill switch)
        # est = 60 B/row * n join rows; a half-est pass target gives 2
        # range partitions of ~n/2 rows (>= the device floor)
        membudget.set_budget(_pieces_budget(60 * n, 2))
        pl0, s0, p0 = _cnt("copr.spill.plane_sorts"), \
            _cnt("copr.spill.sorts"), _cnt("copr.spill.sort_passes")
        got = tk.query(SORT_Q).rows
        assert _cnt("copr.spill.plane_sorts") > pl0, \
            "join ORDER BY never rode the columnar plane sort"
        assert _cnt("copr.spill.sorts") > s0, \
            "the over-headroom ORDER BY never partitioned"
        assert _cnt("copr.spill.sort_passes") >= p0 + 2
        assert got == oracle


# ---------------------------------------------------------------------------
# window functions
# ---------------------------------------------------------------------------

WIN_QS = [
    "select id, row_number() over (partition by g order by o, id) from w",
    "select id, rank() over (partition by g order by o) from w",
    "select id, dense_rank() over (partition by g order by o) from w",
    "select id, sum(v) over (partition by g order by o, id) from w",
    "select id, count(v) over (partition by g order by o) from w",
    "select id, min(v) over (partition by g order by o) from w",
    "select id, max(v) over (partition by g order by o) from w",
    "select id, sum(v) over () from w",
    "select id, count(*) over (partition by g) from w",
]


def _win_store() -> TestKit:
    tk = TestKit(store=new_store(f"cluster://3/tspill{next(_store_seq)}"))
    tk.exec("create database sw")
    tk.exec("use sw")
    tk.exec("create table w (id bigint primary key, g bigint, o bigint, "
            "v bigint)")
    n = 4_500     # >= extsort.SORT_DEVICE_FLOOR: the device scan engages
    _bulk_insert(tk, "sw", "w",
                 [[Datum.i64(i), Datum.i64(i % 37),
                   Datum.i64((i * 7) % 13),
                   Datum.null() if i % 11 == 0 else Datum.i64((i * 13) % 97)]
                  for i in range(1, n + 1)])
    return tk


class TestWindowFunctions:
    def test_device_scan_parity_vs_kill_switch_and_row_protocol(
            self, monkeypatch):
        tk = _win_store()
        membudget.set_budget(_base() + (1 << 22))
        w0, p0 = _cnt("copr.spill.windows"), \
            _cnt("copr.spill.window_passes")
        got = [tk.query(q).rows for q in WIN_QS]
        assert _cnt("copr.spill.windows") - w0 == len(WIN_QS), \
            "not every window call rode the device segment scan"
        assert _cnt("copr.spill.window_passes") >= p0 + len(WIN_QS)
        # oracle 1: budget 0 — the host numpy rung, same formulas
        membudget.set_budget(0)
        assert [tk.query(q).rows for q in WIN_QS] == got, \
            "window parity vs the kill-switch host rung"
        # oracle 2: the row protocol — python comparator + streaming
        # aggregation contexts (the rung ci collations land on)
        from tidb_tpu.executor import window as win
        monkeypatch.setattr(win.WindowExec, "_try_planes",
                            lambda self, desc, rows: None)
        membudget.set_budget(_base() + (1 << 22))
        assert [tk.query(q).rows for q in WIN_QS] == got, \
            "window parity vs the row protocol"

    def test_over_headroom_scan_chunks_into_passes(self):
        tk = _win_store()
        membudget.set_budget(0)
        oracle = [tk.query(q).rows for q in WIN_QS[:4]]
        # rank scans cost 24 B/row (108 KB at 4500 rows): a ~70 KB
        # headroom splits the scan at whole-partition boundaries — and
        # sends the key-plane sort through the partitioned route too
        membudget.set_budget(_base() + 70_000)
        p0 = _cnt("copr.spill.window_passes")
        got = [tk.query(q).rows for q in WIN_QS[:4]]
        assert _cnt("copr.spill.window_passes") >= p0 + 2 * len(got), \
            "no over-headroom window scan split into passes"
        assert got == oracle

    def test_scan_fault_lands_on_host_rung(self):
        tk = _win_store()
        membudget.set_budget(0)
        oracle = tk.query(WIN_QS[1]).rows
        membudget.set_budget(_base() + (1 << 22))
        d0 = _cnt("copr.degraded_spill_window")
        failpoint.enable("device/window_scan")
        try:
            got = tk.query(WIN_QS[1]).rows
        finally:
            failpoint.disable("device/window_scan")
        assert _cnt("copr.degraded_spill_window") > d0, \
            "the window_scan fault was not accounted as a degradation"
        assert got == oracle


# ---------------------------------------------------------------------------
# backend allocator reconciliation (the membudget stats hook)
# ---------------------------------------------------------------------------

class TestAllocatorHook:
    def test_estimate_error_ratio_gauge_with_injected_stats(self):
        reads = iter([10_000, 18_000])
        membudget.set_stats_provider(
            lambda: {"bytes_in_use": next(reads)})
        membudget.set_budget(1 << 20)
        with membudget.reserve(16_000, "test"):
            pass
        g = metrics.gauge("device.hbm.estimate_error_ratio").value
        assert abs(g - 0.5) < 1e-9, \
            f"measured 8 KB over a 16 KB estimate must gauge 0.5, got {g}"

    def test_shrinking_allocator_clamps_to_zero(self):
        reads = iter([40_000, 30_000])
        membudget.set_stats_provider(
            lambda: {"bytes_in_use": next(reads)})
        membudget.set_budget(1 << 20)
        with membudget.reserve(16_000, "test"):
            pass
        assert metrics.gauge(
            "device.hbm.estimate_error_ratio").value == 0.0

    def test_unmeasurable_rig_pays_nothing(self):
        membudget.set_stats_provider(lambda: None)
        membudget.set_budget(1 << 20)
        g0 = metrics.gauge("device.hbm.estimate_error_ratio").value
        with membudget.reserve(16_000, "test"):
            pass
        assert metrics.gauge(
            "device.hbm.estimate_error_ratio").value == g0
