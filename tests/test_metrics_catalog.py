"""Metrics-catalog conformance (tier-1, test_exception_hygiene spirit):
walk the source tree for every metrics.counter/gauge/histogram call site
in tidb_tpu/ and assert each emitted name is registered in the catalog
with the right type and documented in README's observability tables — a
new metric cannot land silently undocumented, and a documented metric
cannot silently change type.
"""

from __future__ import annotations

import os
import re

from tidb_tpu.metrics import catalog

ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tidb_tpu")

# metrics.counter("literal.name") — including the string-concat form
# metrics.counter("prefix." + expr)
_LITERAL = re.compile(
    r"""metrics\s*\.\s*(counter|gauge|histogram)\s*\(\s*"([^"]+)"\s*([),+])""",
    re.S)
# metrics.counter(f"prefix.{var}") — dynamic families: the literal
# prefix before the first placeholder must be a catalog PREFIX (or be
# covered by exact entries that share it)
_FSTRING = re.compile(
    r"""metrics\s*\.\s*(counter|gauge|histogram)\s*\(\s*f"([^"{]+)\{""",
    re.S)


def _walk_sources():
    for dirpath, _dirs, files in os.walk(ROOT):
        for fn in files:
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                with open(path, encoding="utf-8") as f:
                    yield os.path.relpath(path, ROOT), f.read()


def _collect():
    exact: dict[str, tuple[str, str]] = {}    # name → (type, where)
    prefixes: dict[str, tuple[str, str]] = {}
    for rel, src in _walk_sources():
        for m in _LITERAL.finditer(src):
            kind, name, tail = m.group(1), m.group(2), m.group(3)
            if tail == "+":
                # "literal." + expr concatenation: a dynamic family
                prefixes[name] = (kind, rel)
            else:
                exact[name] = (kind, rel)
        for m in _FSTRING.finditer(src):
            prefixes[m.group(2)] = (m.group(1), rel)
    return exact, prefixes


def test_every_emitted_metric_is_in_the_catalog_with_its_type():
    exact, prefixes = _collect()
    assert len(exact) >= 40, "source walk found suspiciously few metrics"
    problems = []
    for name, (kind, where) in sorted(exact.items()):
        hit = catalog.lookup(name)
        if hit is None:
            problems.append(f"{name} ({where}): not in catalog")
        elif hit[0] != kind:
            problems.append(
                f"{name} ({where}): emitted as {kind}, catalog says "
                f"{hit[0]}")
        elif not hit[1].strip():
            problems.append(f"{name} ({where}): empty help text")
    assert not problems, "metric drift:\n" + "\n".join(problems)


def test_every_dynamic_family_prefix_is_covered():
    _exact, prefixes = _collect()
    assert prefixes, "no dynamic metric families found (regex rot?)"
    problems = []
    for prefix, (kind, where) in sorted(prefixes.items()):
        # covered when the prefix itself is a catalog family entry, or
        # every plausible expansion resolves through exact entries that
        # share the prefix (the plane-cache COUNTER_NAMES pattern)
        if prefix in catalog.CATALOG:
            if catalog.CATALOG[prefix][0] != kind:
                problems.append(
                    f"{prefix}* ({where}): emitted as {kind}, catalog "
                    f"says {catalog.CATALOG[prefix][0]}")
            continue
        # other call sites may register other-typed metrics under the
        # same dotted prefix (plane-cache gauges beside its counters),
        # so require at least one same-typed exact entry as evidence
        # the family is documented
        covered = [n for n in catalog.CATALOG if n.startswith(prefix)
                   and n != prefix and catalog.CATALOG[n][0] == kind]
        if not covered:
            problems.append(
                f"{prefix}* ({where}): no catalog family entry and no "
                f"exact {kind} entries under the prefix")
    assert not problems, "dynamic-family drift:\n" + "\n".join(problems)


def test_catalog_prefix_resolution():
    assert catalog.lookup("copr.degraded_mesh") == \
        catalog.CATALOG["copr.degraded_"]
    assert catalog.lookup("kv.backoff.rpc") == \
        catalog.CATALOG["kv.backoff."]
    # histogram series sampled as _count/_sum resolve to their family
    assert catalog.lookup("ops.kernel_seconds_count")[0] == "histogram"
    assert catalog.lookup("no.such.metric") is None


def test_readme_documents_every_catalog_entry():
    """README's observability tables are the operator-facing copy of the
    catalog: every entry (exact name or dynamic-family prefix) must
    appear there — and in backticks, so it renders as a metric name."""
    readme = os.path.join(os.path.dirname(ROOT), "README.md")
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    missing = [name for name in sorted(catalog.CATALOG)
               if f"`{name}" not in text]
    assert not missing, \
        "catalog entries missing from README's observability tables:\n" \
        + "\n".join(missing)
