"""FOREIGN KEY end-to-end: grammar, table metadata, online DDL add/drop,
SHOW CREATE TABLE and INFORMATION_SCHEMA exposure, durability.

Semantics match the reference's 2016 contract — the key is RECORDED, not
enforced (reference ddl/foreign_key.go:46 "We just support record the
foreign key"; grammar parser.y:1171-1190 ReferDef)."""

import pytest

from tidb_tpu import errors
from tests.testkit import TestKit


@pytest.fixture
def tk():
    t = TestKit()
    t.exec("create database fkdb; use fkdb")
    t.exec("create table p (x int primary key, y int)")
    return t


def _show(tk, table):
    return tk.query(f"show create table {table}").rows[0][1]


class TestCreateTable:
    def test_verdict_probe_statement(self, tk):
        """The exact probe from the round-4 verdict (missing #1)."""
        tk.exec("create table fk (a int, foreign key (a) references p(a))")
        assert "FOREIGN KEY (`a`) REFERENCES `p` (`a`)" in _show(tk, "fk")

    def test_named_fk_with_actions_round_trips(self, tk):
        tk.exec("create table c (a int, b int, constraint myfk "
                "foreign key (a, b) references p (x, y) "
                "on delete cascade on update set null)")
        out = _show(tk, "c")
        assert "CONSTRAINT `myfk` FOREIGN KEY (`a`, `b`) " \
               "REFERENCES `p` (`x`, `y`) " \
               "ON DELETE CASCADE ON UPDATE SET NULL" in out

    def test_auto_named_fk(self, tk):
        tk.exec("create table c (a int, foreign key (a) references p(x))")
        assert "CONSTRAINT `fk_a` FOREIGN KEY" in _show(tk, "c")

    def test_no_enforcement(self, tk):
        """2016 semantics: metadata only — writes violating the reference
        are accepted, like the reference engine."""
        tk.exec("create table c (a int, foreign key (a) references p(x))")
        tk.exec("insert into c values (999)")   # no parent row: fine
        tk.query("select a from c").check([[999]])

    def test_validation_errors(self, tk):
        with pytest.raises(errors.TiDBError):
            tk.exec("create table bad (a int, "
                    "foreign key (a) references p(x, y))")   # len mismatch
        with pytest.raises(errors.TiDBError):
            tk.exec("create table bad (a int, "
                    "foreign key (zz) references p(x))")     # unknown col
        with pytest.raises(errors.TiDBError):
            tk.exec("create table bad (a int, b int, "
                    "constraint d foreign key (a) references p(x), "
                    "constraint d foreign key (b) references p(x))")


class TestAlterTable:
    def test_add_drop_cycle(self, tk):
        """ALTER ADD/DROP through the online-DDL job queue (reference
        ddl/foreign_key.go onCreateForeignKey/onDropForeignKey)."""
        tk.exec("create table c (a int)")
        tk.exec("alter table c add constraint f1 foreign key (a) "
                "references p(x) on delete no action")
        assert "CONSTRAINT `f1`" in _show(tk, "c")
        assert "ON DELETE NO ACTION" in _show(tk, "c")
        tk.exec("alter table c drop foreign key f1")
        assert "FOREIGN KEY" not in _show(tk, "c")
        # the schema version moved: other sessions converge via reload
        tk2 = tk.new_session()
        tk2.exec("use fkdb")
        assert "FOREIGN KEY" not in _show(tk2, "c")

    def test_add_duplicate_name_rejected(self, tk):
        tk.exec("create table c (a int, constraint f1 foreign key (a) "
                "references p(x))")
        with pytest.raises(errors.TiDBError):
            tk.exec("alter table c add constraint f1 foreign key (a) "
                    "references p(y)")

    def test_drop_missing_rejected(self, tk):
        tk.exec("create table c (a int)")
        with pytest.raises(errors.TiDBError):
            tk.exec("alter table c drop foreign key ghost")


class TestExposure:
    def test_key_column_usage(self, tk):
        tk.exec("create table c (a int, constraint cfk foreign key (a) "
                "references p(x))")
        rows = tk.query(
            "select column_name, referenced_table_name, "
            "referenced_column_name from "
            "information_schema.key_column_usage "
            "where constraint_name = 'cfk'").rows
        assert rows == [[b"a", b"p", b"x"]] or rows == [["a", "p", "x"]]

    def test_referential_constraints(self, tk):
        tk.exec("create table c (a int, constraint cfk foreign key (a) "
                "references p(x) on delete cascade)")
        rows = tk.query(
            "select delete_rule, update_rule, referenced_table_name "
            "from information_schema.referential_constraints "
            "where constraint_name = 'cfk'").rows
        [[dr, ur, rt]] = rows
        as_str = lambda v: v.decode() if isinstance(v, bytes) else v
        assert (as_str(dr), as_str(ur), as_str(rt)) == \
            ("CASCADE", "RESTRICT", "p")


def test_fk_survives_restart(tmp_path):
    from tidb_tpu.domain import clear_domains
    from tidb_tpu.kv.kv import close_store
    from tidb_tpu.session import Session, new_store
    url = f"local://{tmp_path}/fkdur"
    s = Session(new_store(url))
    s.execute("create database d")
    s.execute("use d")
    s.execute("create table c (a int, constraint k1 foreign key (a) "
              "references p(x) on update restrict)")
    close_store(url)
    clear_domains()
    s2 = Session(new_store(url))
    s2.execute("use d")
    out = s2.execute("show create table c")[0].values()[0][1]
    assert "CONSTRAINT `k1` FOREIGN KEY (`a`) REFERENCES `p` (`x`) " \
           "ON UPDATE RESTRICT" in out
