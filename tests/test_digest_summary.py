"""Workload observability: statement/plan digests, the perfschema
digest summary (windowed current+history, capped with eviction
accounting), TOP-SQL, region heat, SHOW PROCESSLIST digest reporting,
and the reconciliation contract — a concurrent multi-session workload's
per-digest exec counts and resource tallies must sum EXACTLY to the
flat global counters, with no cross-session bleed.

Also the digest-pipeline overhead guard: computing digests + updating
the summary must cost < 2 ms per statement vs the summary disabled.
"""

from __future__ import annotations

import itertools
import threading
import time

import pytest

from tidb_tpu import digest, metrics, perfschema, tablecodec as tc
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 240

JOIN_AGG_Q = ("select count(*), sum(t.v), min(t.v), max(d.d_f) "
              "from t join d on t.k = d.d_k")


def _build(n_regions: int = 4):
    store = new_store(f"cluster://3/digest{next(_id)}")
    s = Session(store)
    s.execute("create database dg")
    s.execute("use dg")
    s.execute("create table t (id bigint primary key, k bigint, "
              "v bigint, f double)")
    rows = ", ".join(f"({i}, {i % 7}, {i * 10}, {i}.25)"
                     for i in range(1, N_ROWS + 1))
    s.execute(f"insert into t values {rows}")
    s.execute("create table d (d_k bigint primary key, d_f double)")
    s.execute("insert into d values " +
              ", ".join(f"({i}, {i}.5)" for i in range(7)))
    if n_regions > 1:
        tid = s.info_schema().table_by_name("dg", "t").info.id
        step = N_ROWS // n_regions
        s.store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _summary(store) -> "perfschema.DigestSummary":
    return perfschema.perf_for(store).digest_summary


def _reset_summary(store) -> None:
    """Fresh summary window with NO statements recorded for the reset
    itself (the SQL kill switch would work too, but the SET statements
    would race the measured phase's first snapshot)."""
    ds = _summary(store)
    ds.set_enabled(False)
    ds.set_enabled(True)


def _entries(store) -> dict:
    return _summary(store).windows()[-1][2]


# ---------------------------------------------------------------------------
# normalization: the digest identity itself
# ---------------------------------------------------------------------------

class TestNormalization:
    def test_literal_variants_share_one_digest(self):
        variants = [
            "select v from t where id = 5",
            "select v from t where id = 999",
            "SELECT V FROM T WHERE ID = 123",
            "select  v\nfrom t   where id=7",
            "select v from t where id = 5 -- trailing comment",
            "select v from t where id = ?",   # prepared text, same shape
        ]
        digs = {digest.sql_digest(v)[0] for v in variants}
        assert len(digs) == 1, digs

    def test_in_lists_collapse_across_arity(self):
        digs = {digest.sql_digest(q)[0] for q in (
            "select v from t where id in (1)",       # arity 1 too
            "select v from t where id in (-1)",      # signed singleton
            "select v from t where id in (?)",       # prepared singleton
            "select v from t where id in (1, 2)",
            "select v from t where id in (1, 2, 3, 4, 5)",
            "select v from t where id in (9, -8, 7.5, 'x')",
        )}
        assert len(digs) == 1, digs
        # a bare parenthesized literal NOT after IN keeps its shape
        assert "(...)" not in digest.normalize("select (1)")

    def test_unary_sign_folds_into_the_literal(self):
        # text `-1` and a prepared param bound to -1 share a digest
        assert digest.sql_digest("select v from t where a = -1")[0] \
            == digest.sql_digest("select v from t where a = ?")[0]
        assert digest.sql_digest("select v from t where a = -1.5 "
                                 "and b < +3")[0] \
            == digest.sql_digest("select v from t where a = ? "
                                 "and b < ?")[0]
        # BINARY minus (operand on its left) keeps its shape
        assert digest.normalize("select a - 1 from t") \
            == "select a - ? from t"
        assert digest.normalize("select (a) - 1 from t") \
            == "select (a) - ? from t"
        assert digest.normalize("select 1 - 2") == "select ? - ?"

    def test_distinct_shapes_get_distinct_digests(self):
        shapes = [
            "select v from t where id = 5",
            "select v from t where k = 5",
            "select v, k from t where id = 5",
            "select v from t where id > 5",
            "select sum(v) from t where id = 5",
            "select v from d where id = 5",
            "insert into t values (1, 2, 3, 4.0)",
        ]
        digs = [digest.sql_digest(s)[0] for s in shapes]
        assert len(set(digs)) == len(shapes)

    def test_mixed_tuple_keeps_shape(self):
        # "(?, col)" is not a pure literal list: it must NOT collapse
        a = digest.normalize("select * from t where (1, k) = (2, 3)")
        assert "(? , k)" in a.replace(", ", " , ") or "(?, k)" in a, a

    def test_unlexable_text_still_digests(self):
        d, norm = digest.sql_digest("select ' unterminated")
        assert d and norm   # stable fallback fold, never an exception

    def test_plan_digest_tracks_shape_not_constants(self):
        s = _build(1)
        from tidb_tpu.plan.builder import PlanBuilder
        from tidb_tpu.plan.optimizer import optimize_plan

        def plan_of(sql: str):
            stmt = s.parser.parse_one(sql)
            return optimize_plan(PlanBuilder(s).build(stmt), s, s.client,
                                 s.dirty_tables)

        p1, _ = digest.plan_digest(plan_of("select v from t where id > 5"))
        p2, _ = digest.plan_digest(plan_of("select v from t where id > 99"))
        p3, _ = digest.plan_digest(plan_of("select d_f from d"))
        assert p1 == p2          # constants do not change the plan shape
        assert p1 != p3          # different table/tree does


# ---------------------------------------------------------------------------
# the acceptance criterion: concurrent reconciliation
# ---------------------------------------------------------------------------

class TestConcurrentReconciliation:
    def test_multi_session_counts_reconcile_with_global_counters(self):
        """Three sessions, 4-region store, mixed point/range/join/agg
        workload: per-digest exec counts must equal each thread's known
        statement count summed (no bleed), and the per-digest resource
        tallies must sum EXACTLY to the flat global counter deltas."""
        s_main = _build(4)
        store = s_main.store
        sessions = [s_main, Session(store), Session(store)]
        for s in sessions[1:]:
            s.execute("use dg")
        # warm every path OUTSIDE the measured window (jit compile,
        # plane cache, plan caches)
        for s in sessions:
            s.execute(JOIN_AGG_Q)
            s.execute("select v from t where id = 3")
        _reset_summary(store)

        point = "select v from t where id = %d"
        rng = "select sum(v) from t where id between %d and %d"
        agg = "select k, count(*), max(v) from t group by k"
        # per-session schedule: (sql template kind, count)
        plans = [
            [("point", 9), ("join", 3), ("agg", 2)],
            [("point", 5), ("range", 6), ("join", 2)],
            [("range", 4), ("agg", 3), ("join", 1)],
        ]
        g0 = {name: metrics.counter(name).value
              for name in ("distsql.columnar_hits",
                           "distsql.columnar_partials",
                           "ops.kernel_dispatches", "ops.readbacks",
                           "ops.readback_bytes")}
        barrier = threading.Barrier(len(sessions))
        errs: list = []

        def run(sess, plan, seed):
            try:
                barrier.wait(timeout=30)
                for kind, n in plan:
                    for i in range(n):
                        if kind == "point":
                            sess.execute(point % (seed * 31 + i))
                        elif kind == "range":
                            sess.execute(rng % (seed, seed + 40 + i))
                        elif kind == "join":
                            sess.execute(JOIN_AGG_Q)
                        else:
                            sess.execute(agg)
            except Exception as e:  # surfaced after join
                errs.append(e)

        threads = [threading.Thread(target=run, args=(s, p, i + 1))
                   for i, (s, p) in enumerate(zip(sessions, plans))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs

        entries = _entries(store)
        by_norm = {e.norm_sql: e for e in entries.values()}
        want = {
            digest.normalize(point % 0): 9 + 5,
            digest.normalize(rng % (0, 0)): 6 + 4,
            digest.normalize(JOIN_AGG_Q): 3 + 2 + 1,
            digest.normalize(agg): 2 + 3,
        }
        assert len(entries) == len(want), sorted(by_norm)
        for norm, count in want.items():
            assert by_norm[norm].exec_count == count, \
                f"{norm}: {by_norm[norm].exec_count} != {count}"
            assert by_norm[norm].errors == 0

        # resource reconciliation: per-digest sums == global deltas
        def digest_sum(key: str) -> int:
            return sum(e.res.get(key, 0) for e in entries.values())

        for key, name in (("columnar_hits", "distsql.columnar_hits"),
                          ("columnar_partials",
                           "distsql.columnar_partials"),
                          ("kernel_dispatches", "ops.kernel_dispatches"),
                          ("readbacks", "ops.readbacks"),
                          ("readback_bytes", "ops.readback_bytes")):
            got = digest_sum(key)
            delta = metrics.counter(name).value - g0[name]
            assert got == delta, \
                f"{key}: digest sum {got} != global delta {delta}"
        # the join workload actually exercised the columnar channel
        assert digest_sum("columnar_partials") >= 4 * 6

    def test_errored_statements_are_workload_too(self):
        s = _build(1)
        _reset_summary(s.store)
        for _ in range(3):
            with pytest.raises(Exception):
                s.execute("select no_such_column from t where id = 1")
        [e] = _entries(s.store).values()
        assert e.exec_count == 3
        assert e.errors == 3

    def test_binary_protocol_shares_the_text_digest(self):
        s = _build(1)
        _reset_summary(s.store)
        from tidb_tpu.types import Datum
        sid, n_params = s.prepare_binary(
            "select v from t where id = ?")
        assert n_params == 1
        s.execute_binary(sid, [Datum.i64(7)])
        s.execute_binary(sid, [Datum.i64(8)])
        s.execute("select v from t where id = 99")
        [e] = _entries(s.store).values()
        assert e.exec_count == 3, \
            "binary and text executions of one shape did not share a digest"


# ---------------------------------------------------------------------------
# summary windows, caps, eviction accounting
# ---------------------------------------------------------------------------

class TestSummaryWindows:
    def test_p95_and_latency_bounds(self):
        s = _build(1)
        _reset_summary(s.store)
        for i in range(20):
            s.execute(f"select v from t where id = {i + 1}")
        [e] = _entries(s.store).values()
        assert e.exec_count == 20
        assert 0 < e.min_latency_ms <= e.max_latency_ms
        assert e.min_latency_ms <= e.p95_latency_ms()
        assert abs(e.sum_latency_ms / 20
                   - e.sum_latency_ms / e.exec_count) < 1e-9
        assert e.first_seen <= e.last_seen

    def test_cap_evicts_lru_with_exact_accounting(self):
        s = _build(1)
        s.execute("set global tidb_tpu_stmt_summary_max_digests = 2")
        try:
            _reset_summary(s.store)
            shapes = ["select v from t where id = 1",
                      "select k from t where id = 1",
                      "select f from t where id = 1",
                      "select v, k from t where id = 1"]
            for i, q in enumerate(shapes):
                for _ in range(i + 1):    # 1, 2, 3, 4 executions
                    s.execute(q)
            ds = _summary(s.store)
            with ds.lock:
                n_entries = len(ds.entries)
                kept_exec = sum(e.exec_count for e in ds.entries.values())
                ev_digests, ev_exec = (ds.evicted_digests,
                                       ds.evicted_exec_count)
            assert n_entries == 2
            assert ev_digests == 2
            # recorded = Σ kept + evicted: nothing lost to the cap
            assert kept_exec + ev_exec == 1 + 2 + 3 + 4
            rows = s.execute(
                "select EVICTED_DIGESTS, EVICTED_EXEC_COUNT from "
                "performance_schema.events_statements_summary_evicted"
            )[0].values()
            assert [int(rows[-1][0]), int(rows[-1][1])] == [2, ev_exec]
        finally:
            s.execute("set global tidb_tpu_stmt_summary_max_digests = 512")

    def test_window_rotation_into_bounded_history(self):
        s = _build(1)
        ds = _summary(s.store)
        s.execute("set global tidb_tpu_stmt_summary_history_size = 2")
        try:
            _reset_summary(s.store)
            for w in range(4):
                s.execute(f"select v from t where id = {w + 1}")
                with ds.lock:       # age the window past the interval
                    ds.window_begin -= ds.refresh_interval_s + 1
            # lazy rotation applies on read: 4 aged windows rolled, ring
            # keeps the newest 2, the current window is empty
            wins = ds.windows()
            assert len(wins) == 3            # 2 history + current
            assert all(w[1] is not None for w in wins[:-1])
            assert wins[-1][1] is None and not wins[-1][2]
            rows = s.execute(
                "select DIGEST, EXEC_COUNT from performance_schema."
                "events_statements_summary_by_digest_history")[0].values()
            assert len(rows) == 2
        finally:
            s.execute("set global tidb_tpu_stmt_summary_history_size = 24")

    def test_kill_switch_clears_and_skips_pipeline(self):
        s = _build(1)
        s.execute("select v from t where id = 1")
        assert _entries(s.store)
        s.execute("set global tidb_tpu_stmt_summary = 0")
        try:
            assert not _entries(s.store)
            s.execute("select v from t where id = 2")
            assert not _entries(s.store), \
                "disabled summary still recorded statements"
        finally:
            s.execute("set global tidb_tpu_stmt_summary = 1")
        s.execute("select v from t where id = 3")
        assert len(_entries(s.store)) == 1

    def test_history_ring_cap_sysvar(self):
        s = _build(1)
        ps = perfschema.perf_for(s.store)
        s.execute("set global tidb_tpu_perfschema_history_cap = 5")
        try:
            for i in range(12):
                s.execute(f"select v from t where id = {i + 1}")
            rows = ps.rows(perfschema.T_STMT_HISTORY)
            assert len(rows) == 5
        finally:
            s.execute("set global tidb_tpu_perfschema_history_cap = 1024")

    def test_sysvars_are_global_only_and_validated(self):
        s = _build(1)
        from tidb_tpu import errors
        with pytest.raises(errors.ExecError):
            s.execute("set tidb_tpu_stmt_summary = 0")   # session scope
        with pytest.raises(errors.ExecError):
            s.execute("set global tidb_tpu_stmt_summary_max_digests = 'x'")
        with pytest.raises(errors.ExecError):
            s.execute("set global tidb_tpu_stmt_summary_max_digests = 0")


# ---------------------------------------------------------------------------
# TOP-SQL + hot regions + processlist
# ---------------------------------------------------------------------------

class TestTopSqlAndHeat:
    def test_top_sql_ranks_by_device_time(self):
        s = _build(4)
        _reset_summary(s.store)
        for _ in range(3):
            s.execute(JOIN_AGG_Q)          # device combine → dispatch_us
        for i in range(10):
            s.execute(f"select v from t where id = {i + 1}")   # no device
        rows = s.execute(
            "select RANK, DIGEST, EXEC_COUNT, DEVICE_TIME_MS from "
            "information_schema.TIDB_TPU_TOP_SQL")[0].values()
        assert rows, "TOP_SQL empty after a device workload"
        top = rows[0]
        join_dig = digest.sql_digest(JOIN_AGG_Q)[0]
        assert top[1].decode() == join_dig
        assert int(top[0]) == 1 and int(top[2]) == 3
        assert float(top[3]) > 0, "device time not attributed per digest"
        # ranking is by device time descending
        times = [float(r[3]) for r in rows]
        assert times == sorted(times, reverse=True)

    def test_hot_regions_rank_follows_access_skew(self):
        s = _build(4)
        tid = s.info_schema().table_by_name("dg", "t").info.id
        heat = s.store.rpc.region_heat
        heat.clear()
        # skew: hammer handles that live in the LAST region (181..240)
        for _ in range(6):
            for hid in (190, 200, 210, 220, 230, 240):
                s.execute(f"select v from t where id = {hid}")
        hot_region = s.store.cluster.region_by_key(
            tc.encode_row_key(tid, 200))
        rows = s.execute(
            "select RANK, REGION_ID, READ_ROWS, TOTAL_READ_ROWS, HEAT "
            "from information_schema.TIDB_TPU_HOT_REGIONS")[0].values()
        assert rows, "no heat recorded"
        assert int(rows[0][1]) == hot_region.region_id, \
            f"skewed region did not rank first: {rows}"
        assert int(rows[0][3]) >= 36
        heats = [float(r[4]) for r in rows]
        assert heats == sorted(heats, reverse=True)

    def test_heat_decays_but_totals_are_monotonic(self):
        from tidb_tpu.cluster.heat import RegionHeat
        h = RegionHeat(half_life_s=0.05)
        h.record_read(1, 1000, 8000)
        first = h.snapshot()[0]
        assert first["read_rows"] == pytest.approx(1000, rel=0.2)
        time.sleep(0.2)
        decayed = h.snapshot()[0]
        assert decayed["read_rows"] < first["read_rows"] / 4
        assert decayed["total_read_rows"] == 1000   # flat total: exact

    def test_write_heat_lands_at_prewrite(self):
        s = _build(4)
        heat = s.store.rpc.region_heat
        heat.clear()
        s.execute("insert into t values (1000, 1, 1, 1.0)")
        snap = heat.snapshot()
        assert sum(int(h["total_write_rows"]) for h in snap) >= 1
        rows = s.execute(
            "select WRITE_ROWS from information_schema.TIDB_TPU_HOT_REGIONS"
            " where WRITE_ROWS > 0")[0].values()
        assert rows

    def test_show_processlist_reports_time_state_digest(self):
        s = _build(1)
        other = Session(s.store)
        other.execute("use dg")
        other.execute("select v from t where id = 42")
        rows = s.execute("show full processlist")[0].values()

        def dec(v):
            return v.decode() if isinstance(v, bytes) else v

        by_id = {int(r[0]): r for r in rows}
        own = by_id[s.vars.connection_id]
        assert dec(own[4]) == "Query" and dec(own[6]) == "executing"
        assert dec(own[7]) == "show full processlist"
        assert dec(own[8]) == digest.sql_digest("show full processlist")[0]
        peer = by_id[other.vars.connection_id]
        assert dec(peer[4]) == "Sleep" and dec(peer[6]) == ""
        assert int(peer[5]) >= 0
        assert dec(peer[8]) == \
            digest.sql_digest("select v from t where id = 42")[0]


# ---------------------------------------------------------------------------
# overhead guard: the digest pipeline must stay under 2 ms/statement
# ---------------------------------------------------------------------------

class TestOverheadGuard:
    def test_digest_pipeline_under_2ms_per_statement(self):
        s = _build(1)
        sql = "select count(*) from t"
        n = 60

        def timed() -> float:
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(n):
                    s.execute(sql)
                best = min(best, time.perf_counter() - t0)
            return best

        s.execute(sql)                     # warm
        with_pipeline = timed()
        _summary(s.store).set_enabled(False)
        try:
            s.execute(sql)
            baseline = timed()
        finally:
            _summary(s.store).set_enabled(True)
        per_stmt = (with_pipeline - baseline) / n
        assert per_stmt < 0.002, \
            f"digest pipeline costs {per_stmt * 1e6:.0f}us per " \
            f"statement, over the 2ms bound"
