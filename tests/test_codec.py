"""Codec invariants: roundtrip and memcmp-order preservation.

Mirrors the reference's util/codec/codec_test.go table-driven style.
"""

import random
from decimal import Decimal

import pytest

from tidb_tpu.codec import (
    encode_key, encode_value, decode_all,
    encode_bytes, decode_bytes,
    encode_varint, decode_varint, encode_uvarint, decode_uvarint,
)
from tidb_tpu.types import Datum, Kind, NULL, compare_datum, datum_from_py
from tidb_tpu.types.time_types import Duration, Time, parse_time, parse_duration


INTS = [0, 1, -1, 2, -2, 127, -128, 255, 1 << 31, -(1 << 31), (1 << 63) - 1, -(1 << 63),
        12345678901234, -98765432109876]
FLOATS = [0.0, -0.0, 1.5, -1.5, 3.1415926, -2.718, 1e300, -1e300, 1e-300, -1e-300,
          float("inf"), float("-inf")]
BYTES = [b"", b"a", b"ab", b"abcdefg", b"abcdefgh", b"abcdefghi",
         b"abcdefgh\x00", b"\x00", b"\xff" * 17, bytes(range(256))]
DECIMALS = ["0", "1", "-1", "1.5", "-1.5", "0.001", "-0.001", "123456789.987654321",
            "-123456789.987654321", "1E10", "-1E10", "0.5", "0.55", "-0.5", "-0.55",
            "99999999999999999999.9999", "1.50", "150", "15000000"]


def _roundtrip(datums, comparable):
    enc = encode_key(datums) if comparable else encode_value(datums)
    back = decode_all(enc)
    assert len(back) == len(datums)
    for a, b in zip(datums, back):
        if a.kind == Kind.NULL:
            assert b.kind == Kind.NULL
        elif a.kind == Kind.STRING:
            assert b.get_bytes() == a.get_bytes()
        else:
            assert compare_datum(a, b) == 0, (a, b)


@pytest.mark.parametrize("comparable", [True, False])
def test_roundtrip_all_kinds(comparable):
    datums = (
        [Datum.i64(v) for v in INTS]
        + [Datum.u64(v) for v in [0, 1, (1 << 64) - 1, 1 << 63]]
        + [Datum.f64(v) for v in FLOATS]
        + [Datum.bytes_(v) for v in BYTES]
        + [Datum.dec(Decimal(s)) for s in DECIMALS]
        + [NULL,
           Datum(Kind.DURATION, parse_duration("11:30:45.999999")),
           Datum(Kind.TIME, parse_time("2026-07-29 11:30:45.123456")),
           Datum(Kind.TIME, parse_time("1998-09-02"))]
    )
    _roundtrip(datums, comparable)


def _assert_order_preserved(datums):
    """encode_key order must equal compare_datum order."""
    encoded = [(encode_key([d]), d) for d in datums]
    for i, (ea, da) in enumerate(encoded):
        for eb, db in encoded:
            want = compare_datum(da, db)
            got = -1 if ea < eb else (0 if ea == eb else 1)
            assert got == want, (da, db, ea.hex(), eb.hex())


def test_int_order():
    _assert_order_preserved([Datum.i64(v) for v in INTS])


def test_mixed_int_uint_order():
    # uint and int share memcmp space only within their own flags; check each
    _assert_order_preserved([Datum.u64(v) for v in [0, 1, 255, 1 << 40, (1 << 64) - 1]])


def test_float_order():
    vals = [v for v in FLOATS]
    _assert_order_preserved([Datum.f64(v) for v in vals])


def test_bytes_order():
    _assert_order_preserved([Datum.bytes_(v) for v in BYTES])


def test_decimal_order():
    _assert_order_preserved([Datum.dec(Decimal(s)) for s in DECIMALS])


def test_time_order():
    ts = ["1000-01-01", "1998-09-02", "1998-09-02 00:00:01", "2026-07-29 23:59:59.999999",
          "9999-12-31 23:59:59"]
    _assert_order_preserved([Datum(Kind.TIME, parse_time(t)) for t in ts])


def test_duration_order():
    ds = ["-838:59:59", "-00:00:01", "00:00:00", "00:00:01", "838:59:59"]
    _assert_order_preserved([Datum(Kind.DURATION, parse_duration(d)) for d in ds])


def test_null_sorts_first():
    enc_null = encode_key([NULL])
    for d in [Datum.i64(-(1 << 63)), Datum.bytes_(b""), Datum.f64(float("-inf")),
              Datum.dec(Decimal("-1E100"))]:
        assert enc_null < encode_key([d])


def test_compound_key_order():
    rows = [
        [Datum.i64(1), Datum.bytes_(b"a")],
        [Datum.i64(1), Datum.bytes_(b"ab")],
        [Datum.i64(2), Datum.bytes_(b"")],
        [Datum.i64(2), NULL],
    ]
    keys = [encode_key(r) for r in rows]
    assert keys[0] < keys[1] < keys[2]
    assert keys[3] < keys[2]  # NULL sorts before ""


def test_bytes_group_boundary_fuzz():
    rng = random.Random(42)
    pool = []
    for _ in range(200):
        n = rng.choice([0, 1, 7, 8, 9, 15, 16, 17, rng.randrange(0, 40)])
        pool.append(bytes(rng.randrange(256) for _ in range(n)))
    encs = sorted((encode_key([Datum.bytes_(p)]), p) for p in pool)
    raws = [p for _, p in encs]
    assert raws == sorted(pool)
    for p in pool:
        buf = bytearray()
        encode_bytes(buf, p)
        back, used = decode_bytes(memoryview(bytes(buf)), 0)
        assert back == p and used == len(buf)


def test_varint_roundtrip():
    for v in INTS:
        buf = bytearray()
        encode_varint(buf, v)
        got, pos = decode_varint(memoryview(bytes(buf)), 0)
        assert got == v and pos == len(buf)
    for v in [0, 1, 300, (1 << 64) - 1]:
        buf = bytearray()
        encode_uvarint(buf, v)
        got, pos = decode_uvarint(memoryview(bytes(buf)), 0)
        assert got == v and pos == len(buf)


def test_decimal_canonical_trailing_zeros():
    a = encode_key([Datum.dec(Decimal("1.5"))])
    b = encode_key([Datum.dec(Decimal("1.50"))])
    assert a == b


def test_decimal_beyond_context_precision():
    # regression: Decimal.normalize()/scaleb() round to the 28-digit context
    # precision; the codec must stay exact for arbitrarily long mantissas
    vals = [Decimal("9" * 60), Decimal("-" + "9" * 60), Decimal("1E-1000"),
            Decimal("1." + "123456789" * 5)]
    for v in vals:
        enc = encode_key([Datum.dec(v)])
        assert decode_all(enc)[0].val == v


def test_decode_malformed_raises_valueerror():
    for raw in [b"\x03\x00\x00", b"\x06\x02", b"\x09\x02\x09", b"\xf0",
                b"\x01abc", b"\x02\x08abc", b"\x08\x01"]:
        with pytest.raises(ValueError):
            decode_all(raw)
