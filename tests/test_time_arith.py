"""INTERVAL expressions, DATE_ADD/DATE_SUB/EXTRACT, typed date literals,
and plan-time constant folding.

Reference: parser.y (DateLiteral, TimeUnit, DateArith productions),
evaluator/builtin_time.go (DATE_ADD/DATE_SUB/EXTRACT),
expression FoldConstant.
"""

from __future__ import annotations

import pytest

from tidb_tpu import errors
from tests.testkit import TestKit


@pytest.fixture
def tk():
    t = TestKit()
    t.exec("create database test")
    t.exec("use test")
    t.exec("create table t (a int primary key, d date, dt datetime)")
    t.exec("insert into t values "
           "(1, '1998-09-01', '2024-01-31 10:30:00'), "
           "(2, '1998-09-02', '2024-02-29 23:59:59'), "
           "(3, '1998-09-03', null)")
    return t


def _s(rows):
    return [[str(v) if v is not None and not isinstance(v, int) else v
             for v in r] for r in rows]


class TestIntervalArith:
    def test_tpch_q1_predicate_shape(self, tk):
        tk.query("select a from t where d <= date '1998-12-01' - "
                 "interval 90 day order by a").check([[1], [2]])

    def test_interval_plus_prefix_form(self, tk):
        tk.query("select a from t where d = interval 1 day + "
                 "date '1998-09-01'").check([[2]])

    def test_string_interval_count(self, tk):
        tk.query("select a from t where d <= date '1998-12-01' - "
                 "interval '90' day order by a").check([[1], [2]])

    def test_month_clamps_to_month_end(self, tk):
        r = tk.query("select date_add('2024-01-31', interval 1 month)").rows
        assert str(r[0][0]).startswith("2024-02-29")

    def test_year_and_week_units(self, tk):
        r = tk.query("select date_add('2020-02-29', interval 1 year), "
                     "date_sub('2024-01-08', interval 1 week)").rows
        assert str(r[0][0]).startswith("2020-02-28") or \
            str(r[0][0]).startswith("2021-02-28")
        assert str(r[0][1]).startswith("2024-01-01")

    def test_hour_unit_on_column(self, tk):
        r = tk.query("select date_add(dt, interval 2 hour) from t "
                     "where a = 1").rows
        assert str(r[0][0]).startswith("2024-01-31 12:30:00")

    def test_null_propagates(self, tk):
        tk.query("select date_add(dt, interval 1 day) from t "
                 "where a = 3").check([[None]])

    def test_adddate_plain_days(self, tk):
        r = tk.query("select adddate(d, 5) from t where a = 1").rows
        assert str(r[0][0]).startswith("1998-09-06")

    def test_interval_alone_is_an_error(self, tk):
        with pytest.raises(errors.TiDBError):
            tk.exec("select interval 1 day from t")


class TestExtract:
    def test_extract_units(self, tk):
        tk.query("select extract(year from dt), extract(month from dt), "
                 "extract(day from dt), extract(hour from dt) "
                 "from t where a = 1").check([[2024, 1, 31, 10]])

    def test_quarter_week_datediff(self, tk):
        tk.query("select quarter(d), datediff(d, '1998-08-31') from t "
                 "where a = 1").check([[3, 1]])


class TestConstantFolding:
    def test_folded_predicate_reaches_pushdown(self, tk):
        # the folded constant comparison must be fully pushable: EXPLAIN
        # shows the pushed where rather than a SQL-side Selection
        plan = tk.query("explain select count(1) from t where "
                        "d <= date '1998-12-01' - interval 90 day").rows
        txt = "\n".join(str(r[0]) for r in plan)
        assert "selection" not in txt.lower() or "where" in txt.lower()

    def test_fold_is_not_applied_to_now(self, tk):
        # smoke: NOW() still works (not folded away / not cached wrong)
        r = tk.query("select now()").rows
        assert r[0][0] is not None


class TestIndexHints:
    """USE/FORCE/IGNORE INDEX obeyed over the cost model
    (parser.y:505-507 IndexHint → access-path selection)."""

    @pytest.fixture
    def ht(self):
        t = TestKit()
        t.exec("create database test")
        t.exec("use test")
        t.exec("create table h (a int primary key, b int, c int, "
               "key ib (b), key ic (c))")
        t.exec("insert into h values " +
               ", ".join(f"({i}, {i % 5}, {i % 7})" for i in range(1, 120)))
        t.exec("analyze table h")
        return t

    def _plan(self, t, sql):
        return "\n".join(str(r[0]) for r in t.query("explain " + sql).rows)

    def test_use_index_overrides_cost(self, ht):
        # stats would pick ib for b=3; the hint forces ic
        p = self._plan(ht, "select * from h use index (ic) where b = 3")
        assert "index:ic" in p
        # and results stay correct (condition kept SQL-side)
        ht.query("select count(1) from h use index (ic) where b = 3") \
            .check([[24]])

    def test_ignore_index_excludes(self, ht):
        p = self._plan(ht, "select * from h ignore index (ib) where b = 3")
        assert "index:ib" not in p

    def test_force_index_without_conditions(self, ht):
        p = self._plan(ht, "select b from h force index (ib)")
        assert "index:ib" in p
        ht.query("select count(1) from h force index (ib)").check([[119]])

    def test_unknown_index_errors_1176(self, ht):
        with pytest.raises(errors.TiDBError) as ei:
            ht.exec("select * from h use index (nope)")
        assert getattr(ei.value, "code", None) == 1176

    def test_use_index_primary_alone_pins_table_scan(self, ht):
        p = self._plan(ht, "select * from h use index (primary) "
                           "where b = 3")
        assert "index:" not in p

    def test_use_index_primary_plus_secondary_keeps_cost_choice(self, ht):
        # USE INDEX (PRIMARY, ic) admits BOTH the handle scan and ic as
        # candidates — with no selective condition on c, the non-covering
        # ic double-read costs more than the table scan, which must win
        # (it is explicitly allowed by the hint)
        p = self._plan(ht, "select * from h use index (primary, ic) "
                           "where b = 3")
        assert "index:" not in p
        # but a selective range on c flips the choice to ic by cost
        p = self._plan(ht, "select * from h use index (primary, ic) "
                           "where c = 3 and b = 3")
        assert "index:ic" in p
        # i ≡ 3 (mod 35) over 1..119 → {3, 38, 73, 108}
        ht.query("select count(1) from h use index (primary, ic) "
                 "where c = 3 and b = 3").check([[4]])
