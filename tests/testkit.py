"""TestKit: the SQL test harness.

Reference: util/testkit/testkit.go:29 — MustExec / MustQuery with
Result.Check assertions against an in-memory store.
"""

from __future__ import annotations

import itertools

import uuid

from tidb_tpu.domain import clear_domains
from tidb_tpu.session import Session, new_store

_store_id = itertools.count(1)
# stores are cached process-wide by URL (tidb.go NewStore); this module can
# be imported both as `testkit` and `tests.testkit` (two counter copies),
# so URLs carry a per-module-instance token to stay collision-free
_token = uuid.uuid4().hex[:6]


class Result:
    def __init__(self, result_sets):
        self.result_sets = result_sets

    @property
    def rows(self):
        if not self.result_sets:
            return []
        return self.result_sets[-1].values()

    def check(self, expected: list[list]) -> None:
        got = self.rows
        norm_got = [[_norm(v) for v in row] for row in got]
        norm_exp = [[_norm(v) for v in row] for row in expected]
        assert norm_got == norm_exp, f"\n got: {norm_got}\nwant: {norm_exp}"

    def sort(self) -> "Result":
        for rs in self.result_sets:
            rs.rows.sort(key=lambda r: [repr(d.val) for d in r])
        return self


def _norm(v):
    from decimal import Decimal
    if isinstance(v, Decimal):
        return float(v) if v != v.to_integral_value() else int(v)
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v  # floats stay floats: expectations distinguish 1 from 1.0


class TestKit:
    __test__ = False  # not a pytest class

    def __init__(self, store=None):
        clear_domains()
        self.store = store or new_store(
            f"memory://tk{_token}_{next(_store_id)}")
        self.session = Session(self.store)

    def exec(self, sql: str):
        return Result(self.session.execute(sql))

    must_exec = exec

    def query(self, sql: str) -> Result:
        return Result(self.session.execute(sql))

    def new_session(self) -> "TestKit":
        tk = TestKit.__new__(TestKit)
        tk.store = self.store
        tk.session = Session(self.store)
        return tk
