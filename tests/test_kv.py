"""KV layer tests: membuffer/unionstore semantics, MVCC visibility,
optimistic commit conflicts, region split. Mirrors kv/ and store/localstore
test suites in the reference."""

import threading

import pytest

from tidb_tpu import errors
from tidb_tpu.kv import MemBuffer, UnionStore, run_in_new_txn
from tidb_tpu.kv.union_store import OPT_PRESUME_KEY_NOT_EXISTS
from tidb_tpu.localstore import LocalStore


def test_membuffer_basic():
    mb = MemBuffer()
    mb.set(b"a", b"1")
    mb.set(b"c", b"3")
    mb.set(b"b", b"2")
    assert mb.get(b"a") == b"1"
    with pytest.raises(errors.KeyNotExistsError):
        mb.get(b"x")
    assert [k for k, _ in mb.iterate()] == [b"a", b"b", b"c"]
    assert [k for k, _ in mb.iterate(b"b")] == [b"b", b"c"]
    assert [k for k, _ in mb.iterate(b"a\x00", b"c")] == [b"b"]
    mb.delete(b"b")
    with pytest.raises(errors.KeyNotExistsError):
        mb.get(b"b")
    assert [k for k, _ in mb.iterate()] == [b"a", b"c"]
    assert [k for k, _ in mb.iterate_reverse()] == [b"c", b"a"]


def test_txn_read_own_writes():
    store = LocalStore()
    txn = store.begin()
    txn.set(b"k1", b"v1")
    assert txn.get(b"k1") == b"v1"
    txn.delete(b"k1")
    with pytest.raises(errors.KeyNotExistsError):
        txn.get(b"k1")
    txn.set(b"k1", b"v2")
    txn.commit()
    assert store.get_snapshot().get(b"k1") == b"v2"


def test_snapshot_isolation():
    store = LocalStore()
    t1 = store.begin()
    t1.set(b"k", b"v1")
    t1.commit()

    snap_before = store.get_snapshot()
    t2 = store.begin()
    t3 = store.begin()
    t2.set(b"k", b"v2")
    t2.commit()
    # t3 started before t2 committed: must still see v1
    assert t3.get(b"k") == b"v1"
    assert snap_before.get(b"k") == b"v1"
    assert store.get_snapshot().get(b"k") == b"v2"


def test_write_conflict_is_retryable():
    store = LocalStore()
    t1 = store.begin()
    t2 = store.begin()
    t1.set(b"k", b"t1")
    t2.set(b"k", b"t2")
    t1.commit()
    with pytest.raises(errors.WriteConflictError):
        t2.commit()


def test_rollback_discards():
    store = LocalStore()
    t = store.begin()
    t.set(b"k", b"v")
    t.rollback()
    with pytest.raises(errors.KeyNotExistsError):
        store.get_snapshot().get(b"k")
    with pytest.raises(errors.KVError):
        t.set(b"k", b"again")


def test_union_iteration_overlay():
    store = LocalStore()
    t = store.begin()
    for k in (b"a", b"b", b"c"):
        t.set(k, b"snap")
    t.commit()
    t2 = store.begin()
    t2.set(b"b", b"dirty")      # overwrite
    t2.delete(b"c")             # tombstone
    t2.set(b"d", b"new")        # insert
    got = list(t2.iterate(b"a", b"z"))
    assert got == [(b"a", b"snap"), (b"b", b"dirty"), (b"d", b"new")]
    rev = [k for k, _ in t2.iterate_reverse(b"a", b"z")]
    assert rev == [b"d", b"b", b"a"]


def test_presume_key_not_exists():
    store = LocalStore()
    t = store.begin()
    t.set(b"dup", b"v")
    t.commit()

    t2 = store.begin()
    t2.set_option(OPT_PRESUME_KEY_NOT_EXISTS)
    with pytest.raises(errors.KeyNotExistsError):
        t2.get(b"dup")  # presumed absent, recorded as lazy condition
    t2.set(b"dup", b"v2")
    with pytest.raises(errors.KeyExistsError):
        t2.commit()


def test_mvcc_compact():
    store = LocalStore()
    for i in range(5):
        t = store.begin()
        t.set(b"k", f"v{i}".encode())
        t.commit()
    t = store.begin()
    t.delete(b"gone")  # no-op delete of absent key writes tombstone
    t.set(b"gone", b"x")
    t.commit()
    t = store.begin()
    t.delete(b"gone")
    t.commit()
    snap_ver = store.current_version()
    removed = store.compact(safe_point_ts=snap_ver)
    assert removed >= 4
    assert store.get_snapshot().get(b"k") == b"v4"
    with pytest.raises(errors.KeyNotExistsError):
        store.get_snapshot().get(b"gone")


def test_run_in_new_txn_retries():
    store = LocalStore()
    t = store.begin()
    t.set(b"ctr", b"0")
    t.commit()
    attempts = []

    def bump(txn):
        attempts.append(1)
        v = int(txn.get(b"ctr"))
        if len(attempts) == 1:
            # sneak in a conflicting commit mid-txn
            other = store.begin()
            other.set(b"ctr", str(v + 100).encode())
            other.commit()
        txn.set(b"ctr", str(v + 1).encode())

    run_in_new_txn(store, True, bump)
    assert store.get_snapshot().get(b"ctr") == b"101"
    assert len(attempts) == 2


def test_concurrent_increments():
    store = LocalStore()
    t = store.begin()
    t.set(b"n", b"0")
    t.commit()

    def worker():
        def bump(txn):
            txn.set(b"n", str(int(txn.get(b"n")) + 1).encode())
        run_in_new_txn(store, True, bump)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert store.get_snapshot().get(b"n") == b"8"


def test_region_split_and_range():
    store = LocalStore()
    rm = store.regions
    assert len(rm.all_regions()) == 1
    rm.split_keys([b"g", b"p"])
    regions = rm.all_regions()
    assert [(r.start, r.end) for r in regions] == [(b"", b"g"), (b"g", b"p"), (b"p", None)]
    tasks = rm.regions_for_range(b"c", b"x")
    assert len(tasks) == 3
    assert tasks[0][1:] == (b"c", b"g")
    assert tasks[1][1:] == (b"g", b"p")
    assert tasks[2][1:] == (b"p", b"x")
    tasks = rm.regions_for_range(b"h", b"i")
    assert len(tasks) == 1 and tasks[0][0].start == b"g"
