"""Key/value layout tests (mirrors tablecodec tests)."""

from decimal import Decimal

from tidb_tpu import tablecodec as tc
from tidb_tpu.types import Datum, NULL, compare_datum


def test_row_key_roundtrip():
    for tid, h in [(1, 1), (5, -7), (1 << 40, (1 << 63) - 1), (3, -(1 << 63))]:
        key = tc.encode_row_key(tid, h)
        assert tc.decode_row_key(key) == (tid, h)
        assert tc.decode_table_id(key) == tid


def test_row_key_order_matches_handle_order():
    tid = 42
    handles = [-(1 << 63), -100, -1, 0, 1, 99, (1 << 63) - 1]
    keys = [tc.encode_row_key(tid, h) for h in handles]
    assert keys == sorted(keys)


def test_record_prefix_contains_all_handles():
    tid = 7
    start, end = tc.encode_record_range(tid)
    for h in [-(1 << 63), 0, (1 << 63) - 1]:
        k = tc.encode_row_key(tid, h)
        assert start <= k < end
    other = tc.encode_row_key(8, 0)
    assert not (start <= other < end)


def test_tables_dont_interleave():
    # all keys of table 7 sort strictly before all keys of table 8
    last_t7 = tc.encode_row_key(7, (1 << 63) - 1)
    first_t8 = tc.encode_index_key(8, 1, [NULL], None)
    assert last_t7 < tc.table_prefix(8) <= first_t8


def test_row_value_roundtrip():
    cols = [1, 3, 7]
    vals = [Datum.i64(5), Datum.string("hello"), Datum.dec(Decimal("1.25"))]
    enc = tc.encode_row(cols, vals)
    back = tc.decode_row(enc)
    assert set(back) == {1, 3, 7}
    for cid, d in zip(cols, vals):
        assert compare_datum(back[cid], d) == 0


def test_empty_row_value():
    enc = tc.encode_row([], [])
    assert len(enc) == 1
    assert tc.decode_row(enc) == {}


def test_index_key_roundtrip():
    vals = [Datum.i64(9), Datum.string("xy")]
    key = tc.encode_index_key(11, 2, vals, handle=77)
    got, suffix = tc.cut_index_key(key, 2)
    assert compare_datum(got[0], vals[0]) == 0
    assert got[1].get_bytes() == b"xy"
    assert tc.decode_handle_from_index_suffix(suffix) == 77


def test_index_key_order():
    rows = [[Datum.i64(1), Datum.string("a")],
            [Datum.i64(1), Datum.string("b")],
            [Datum.i64(2), Datum.string("a")]]
    keys = [tc.encode_index_key(1, 1, r, handle=i) for i, r in enumerate(rows)]
    assert keys == sorted(keys)


def test_handle_range_keys():
    tid = 3
    start, end = tc.handle_range_keys(tid, 10, 20)
    assert start <= tc.encode_row_key(tid, 10) < end
    assert start <= tc.encode_row_key(tid, 20) < end
    assert not (start <= tc.encode_row_key(tid, 21) < end)
    assert not (start <= tc.encode_row_key(tid, 9) < end)
    # unbounded high end
    start, end = tc.handle_range_keys(tid, 0, (1 << 63) - 1)
    assert start <= tc.encode_row_key(tid, (1 << 63) - 1) < end
