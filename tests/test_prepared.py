"""PREPARE / EXECUTE / DEALLOCATE — text protocol prepared statements.

Reference: executor/prepared.go (PrepareExec/ExecuteExec/DeallocateExec),
session.go:478-563, parser.y PreparedStmt productions.
"""

import pytest

from testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.exec("create database test")
    tk.exec("use test")
    tk.exec("create table t (id int primary key, a int, b varchar(32))")
    tk.exec("insert into t values (1, 10, 'x'), (2, 20, 'y'), (3, 30, 'z')")
    return tk


class TestPrepare:
    def test_basic_select(self, tk):
        tk.exec("prepare s1 from 'select a from t where id = ?'")
        tk.exec("set @v = 2")
        tk.exec("execute s1 using @v").check([[20]])
        tk.exec("set @v = 3")
        tk.exec("execute s1 using @v").check([[30]])

    def test_prepare_from_user_var(self, tk):
        tk.exec("set @sql = 'select b from t where id = ?'")
        tk.exec("prepare s2 from @sql")
        tk.exec("set @p = 1")
        tk.exec("execute s2 using @p").check([["x"]])

    def test_no_params(self, tk):
        tk.exec("prepare s from 'select count(*) from t'")
        tk.exec("execute s").check([[3]])

    def test_multiple_params(self, tk):
        tk.exec("prepare s from 'select id from t where a > ? and b != ? "
                "order by id'")
        tk.exec("set @lo = 10, @skip = 'z'")
        tk.exec("execute s using @lo, @skip").check([[2]])

    def test_wrong_arg_count(self, tk):
        tk.exec("prepare s from 'select * from t where id = ?'")
        with pytest.raises(Exception, match="Incorrect arguments"):
            tk.exec("execute s")

    def test_unknown_handler(self, tk):
        with pytest.raises(Exception, match="Unknown prepared statement"):
            tk.exec("execute nope")

    def test_deallocate(self, tk):
        tk.exec("prepare s from 'select 1'")
        tk.exec("deallocate prepare s")
        with pytest.raises(Exception, match="Unknown prepared statement"):
            tk.exec("execute s")
        with pytest.raises(Exception, match="Unknown prepared statement"):
            tk.exec("deallocate prepare s")

    def test_prepare_write_stmt(self, tk):
        tk.exec("prepare ins from 'insert into t values (?, ?, ?)'")
        tk.exec("set @i = 4, @a = 40, @b = 'w'")
        tk.exec("execute ins using @i, @a, @b")
        tk.exec("select a from t where id = 4").check([[40]])
        tk.exec("prepare upd from 'update t set a = ? where id = ?'")
        tk.exec("set @na = 99, @i = 1")
        tk.exec("execute upd using @na, @i")
        tk.exec("select a from t where id = 1").check([[99]])

    def test_prepared_show_and_explain(self, tk):
        tk.exec("prepare s from 'show tables'")
        tk.exec("execute s").check([["t"]])
        tk.exec("prepare e from 'explain select count(*) from t'")
        assert len(tk.exec("execute e").rows) >= 1

    def test_re_prepare_replaces(self, tk):
        tk.exec("prepare s from 'select 1'")
        tk.exec("prepare s from 'select 2'")
        tk.exec("execute s").check([[2]])

    def test_nested_prepare_rejected(self, tk):
        with pytest.raises(Exception, match="not supported"):
            tk.exec("prepare s from 'prepare x from ''select 1'''")


class TestPlanCache:
    def test_plan_reused_across_executes(self, tk):
        s = tk.session
        tk.exec("prepare s from 'select a from t where id = ?'")
        tk.exec("set @v = 1")
        tk.exec("execute s using @v")
        assert not s.vars.last_plan_from_cache
        first = s.prepared["s"].plan
        assert first is not None
        tk.exec("set @v = 2")
        tk.exec("execute s using @v").check([[20]])
        assert s.vars.last_plan_from_cache
        assert s.prepared["s"].plan is first

    def test_cache_invalidated_by_ddl(self, tk):
        s = tk.session
        tk.exec("prepare s from 'select count(*) from t where id = ?'")
        tk.exec("set @v = 1")
        tk.exec("execute s using @v").check([[1]])
        first = s.prepared["s"].plan
        tk.exec("alter table t add column c int")
        tk.exec("execute s using @v").check([[1]])
        assert not s.vars.last_plan_from_cache
        assert s.prepared["s"].plan is not first

    def test_cache_bypassed_for_dirty_txn(self, tk):
        s = tk.session
        tk.exec("prepare s from 'select count(*) from t'")
        tk.exec("execute s").check([[3]])
        tk.exec("begin")
        tk.exec("insert into t values (7, 70, 'q')")
        # dirty writes must be visible (UnionScan) — the cached plan has no
        # UnionScan, so the cache is bypassed
        tk.exec("execute s").check([[4]])
        assert not s.vars.last_plan_from_cache
        tk.exec("rollback")
        tk.exec("execute s").check([[3]])

    def test_subquery_in_prepared(self, tk):
        tk.exec("create table s2 (id int primary key, x int)")
        tk.exec("insert into s2 values (1, 10), (2, 25)")
        tk.exec("prepare q from 'select id from t where a in "
                "(select x from s2) and a > ? order by id'")
        tk.exec("set @m = 5")
        tk.exec("execute q using @m").check([[1]])
