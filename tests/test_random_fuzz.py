"""Seeded random differential fuzz: generated SELECTs run on the CPU
engine, the single-chip TPU engine, and the 8-virtual-device mesh, and
must agree (float-tolerant, order-insensitive unless ORDER BY).

This is the adversarial version of test_tpu_fuzz's fixed query list:
random predicate shapes (comparisons, BETWEEN, IN, LIKE, REGEXP,
IS NULL, AND/OR nesting, row expressions), random aggregate sets with
and without GROUP BY, over a schema that crosses every value-semantics
feature added in round 4 (ci collation, enum, decimal fixed-point,
NULL-dense columns). Templates are drawn from closed pools so kernel
signatures repeat and the jit cache amortizes.

The generator is deterministic (seeded); a failure prints the SQL, so
any divergence is a one-line repro.
"""

import random

import pytest

from tidb_tpu.ops import TpuClient
from tidb_tpu.session import Session, new_store
from tests.testkit import _store_id

N_ROWS = 3000
N_QUERIES = 80


def _build(store):
    from decimal import Decimal as _D

    from tidb_tpu.types import Datum, datum_from_py
    from tidb_tpu.types.datum import NULL
    from tidb_tpu.types.time_types import Time, parse_time

    s = Session(store)
    s.execute("create database rf")
    s.execute("use rf")
    s.execute(
        "create table t (id bigint primary key, i1 int, i2 bigint, "
        "f1 double, d1 date, s1 varchar(16) collate utf8_general_ci, "
        "s2 varchar(16), e1 enum('lo','mid','hi'), m1 decimal(12,2))")
    tbl = s.info_schema().table_by_name("rf", "t")
    date_tp = tbl.info.columns[4].field_type.tp   # d1

    rng = random.Random(20260730)
    words = ["Ant", "ant", "BEE", "bee", "Cat", "cat", "dog", "DOG"]
    base = parse_time("2024-01-01")
    import datetime as dt
    txn = store.begin()
    for i in range(1, N_ROWS + 1):
        row = [
            Datum.i64(i),
            Datum.i64(rng.randint(0, 9)),
            Datum.i64(rng.randint(-10**9, 10**9))
            if rng.random() > 0.2 else NULL,
            Datum.f64(round(rng.uniform(-1e4, 1e4), 3))
            if rng.random() > 0.25 else NULL,
            datum_from_py(Time(
                base.dt + dt.timedelta(days=rng.randint(0, 400)), date_tp))
            if rng.random() > 0.15 else NULL,
            Datum.string(rng.choice(words)) if rng.random() > 0.1 else NULL,
            Datum.string(rng.choice(words)) if rng.random() > 0.1 else NULL,
            Datum.string(rng.choice(["lo", "mid", "hi"]))
            if rng.random() > 0.2 else NULL,
            Datum.dec(_D(rng.randint(-10**6, 10**6)) / 100)
            if rng.random() > 0.2 else NULL,
        ]
        tbl.add_record(txn, row, skip_unique_check=True)
        if i % 1000 == 0:
            txn.commit()
            txn = store.begin()
    txn.commit()
    return s


@pytest.fixture(scope="module")
def engines():
    from tidb_tpu.parallel import CoprMesh

    sid = next(_store_id)
    cpu = _build(new_store(f"memory://rfz_cpu{sid}"))
    tstore = new_store(f"memory://rfz_tpu{sid}")
    tstore.set_client(TpuClient(tstore, dispatch_floor_rows=0))
    tpu = _build(tstore)
    mstore = new_store(f"memory://rfz_mesh{sid}")
    mstore.set_client(TpuClient(mstore, mesh=CoprMesh(), dispatch_floor_rows=0))
    mesh = _build(mstore)
    return cpu, tpu, mesh


# closed template pools: signatures repeat → jit cache amortizes
_PREDS = [
    "i1 between {a} and {b}",
    "i2 > {big}",
    "i2 is null",
    "f1 < {f}",
    "f1 is not null",
    "d1 >= '2024-{mm:02d}-01'",
    "s1 = '{w}'",
    "s2 = '{w}'",
    "s1 like '{pfx}%'",
    "s2 regexp '^{pfx}'",
    "e1 = '{e}'",
    "e1 > 1",
    "m1 between -{md} and {md}",
    "i1 in ({i1a}, {i1b}, {i1c})",
    "(i1, e1) in (({i1a}, '{e}'), ({i1b}, 'lo'))",
]

_AGGS = [
    "count(*)", "count(i2)", "sum(i1)", "sum(m1)", "avg(f1)",
    "min(f1)", "max(f1)", "min(s2)", "max(d1)", "count(distinct i1)",
    "count(distinct s1)", "sum(distinct i1)",
]

_GROUPS = ["i1", "e1", "s1", "s2", "i1, e1"]


def _gen(rng) -> str:
    def pred():
        t = rng.choice(_PREDS)
        return t.format(
            a=rng.randint(0, 4), b=rng.randint(5, 9),
            big=rng.randint(-10**8, 10**8), f=round(rng.uniform(-5e3, 5e3), 1),
            mm=rng.randint(1, 12), w=rng.choice(["ant", "BEE", "cat"]),
            pfx=rng.choice(["a", "B", "c", "d"]), e=rng.choice(["lo", "hi"]),
            md=rng.randint(100, 9000),
            i1a=rng.randint(0, 9), i1b=rng.randint(0, 9),
            i1c=rng.randint(0, 9))

    where = ""
    r = rng.random()
    if r > 0.7:
        where = f" where {pred()} and {pred()}"
    elif r > 0.4:
        where = f" where {pred()} or {pred()}"
    elif r > 0.15:
        where = f" where {pred()}"

    if rng.random() < 0.55:
        aggs = ", ".join(rng.sample(_AGGS, rng.randint(1, 3)))
        if rng.random() < 0.5:
            g = rng.choice(_GROUPS)
            return (f"select {g}, {aggs} from t{where} group by {g} "
                    f"order by {g}")
        return f"select {aggs} from t{where}"
    cols = "id, i1, s1, m1"
    if rng.random() < 0.5:
        lim = rng.choice([1, 7, 23, 50])
        key = rng.choice(["id", "f1 desc, id", "i2, id", "s2, id"])
        return f"select {cols} from t{where} order by {key} limit {lim}"
    return f"select {cols} from t{where} order by id"


def _norm(rows, ordered: bool):
    from decimal import Decimal
    out = []
    for row in rows:
        nr = []
        for v in row:
            if isinstance(v, Decimal):
                v = float(v)
            if isinstance(v, bytes):
                v = v.decode()
            if isinstance(v, float):
                nr.append(round(v, 6))
            else:
                nr.append(str(v) if v is not None else None)
        out.append(tuple(nr))
    return out if ordered else sorted(out, key=repr)


def _close_rows(a, b):
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float):
                if abs(x - y) > 1e-6 * max(abs(x), abs(y), 1.0):
                    return False
            elif x != y:
                return False
    return True


def test_random_differential(engines):
    cpu, tpu, mesh = engines
    rng = random.Random(42)
    mismatches = []
    for qi in range(N_QUERIES):
        sql = _gen(rng)
        ordered = "order by" in sql
        try:
            want = _norm(cpu.execute(sql)[0].values(), ordered)
        except Exception as e:  # generator bug, not an engine bug
            raise AssertionError(f"CPU engine rejected: {sql!r}: {e}")
        for name, eng in (("tpu", tpu), ("mesh", mesh)):
            got = _norm(eng.execute(sql)[0].values(), ordered)
            if not _close_rows(want, got):
                mismatches.append((name, sql, want[:5], got[:5]))
    assert not mismatches, mismatches[:3]


def test_engines_actually_engaged(engines):
    _, tpu, mesh = engines
    assert tpu.store.get_client().stats["tpu_requests"] > 10
    assert mesh.store.get_client().stats["tpu_requests"] > 10
