"""Differential suite for the NEAR-DATA states channel (PR 16): a
grouped/scalar aggregate over the cluster store's fan-out ships every
region's partial states PENDING, and the statement-level finisher
(copr.columnar_region.finish_states_batch) computes ALL of them in ONE
batched segmented dispatch — routed shard-owned over the device mesh
(ops.mesh.region_states_sharded) when one is up, the single-device
ragged kernel (kernels.region_agg_states_batched) otherwise. The
contract across 1/2/4/8 regions: exactly one states dispatch per
statement, row-for-row identical to the serial per-region path
(BATCH_STATES_ENABLED=False) AND the row protocol — including mid-scan
split/merge re-batching, every failpoint rung of the degradation ladder
(mesh → single-device batched → serial → host), float-SUM sequential
rounding bit for bit, and the plane-cache keep set that stops a live
old snapshot from re-packing (copr.plane_cache.kept_active).
"""

from __future__ import annotations

import gc
import itertools

import pytest

from tidb_tpu import failpoint, metrics, tablecodec as tc
from tidb_tpu.copr import columnar_region
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 260

QUERIES = [
    # TPC-H-q1 shape: decimal sums, double avg, string group keys
    "select l_flag, l_status, sum(l_qty), sum(l_price), avg(l_qty), "
    "avg(l_price), avg(l_disc), count(*) from lineitem "
    "where l_ship <= '1998-09-02' "
    "group by l_flag, l_status order by l_flag, l_status",
    # scalar aggregates (no group by): G == 1 per region
    "select count(*), sum(l_qty), min(l_price), max(l_price), "
    "avg(l_disc), sum(l_disc) from lineitem",
    # NULL group keys form one group; float sums keep sequential rounding
    "select l_k, count(*), sum(l_disc), min(l_disc), max(l_qty) "
    "from lineitem group by l_k order by l_k",
    # filtered grouped aggregate
    "select l_status, count(*), sum(l_price) from lineitem "
    "where l_qty > 10 group by l_status order by l_status",
]


def _row_spec(i: int):
    from decimal import Decimal
    flag = ("A", "N", "R")[i % 3]
    status = ("F", "O")[i % 2]
    qty = Decimal(i % 50) + Decimal(i % 4) / 4
    price = Decimal(900 + i * 7) + Decimal(i % 10) / 10
    disc = (i % 11) * 0.01
    k = None if i % 11 == 0 else i % 7
    ship = f"1998-{(i % 12) + 1:02d}-{(i % 27) + 1:02d}"
    return flag, status, qty, price, disc, k, ship


def _build(n_regions: int) -> Session:
    store = new_store(f"cluster://3/statesbatch{next(_id)}")
    s = Session(store)
    s.execute("create database nd")
    s.execute("use nd")
    s.execute(
        "create table lineitem (l_id bigint primary key, "
        "l_flag varchar(4), l_status varchar(4), l_qty decimal(12,2), "
        "l_price decimal(12,2), l_disc double, l_k bigint, l_ship date)")
    vals = []
    for i in range(1, N_ROWS + 1):
        flag, status, qty, price, disc, k, ship = _row_spec(i)
        vals.append(f"({i}, '{flag}', '{status}', {qty}, {price}, "
                    f"{disc!r}, {'null' if k is None else k}, '{ship}')")
    s.execute(f"insert into lineitem values {', '.join(vals)}")
    if n_regions > 1:
        tid = s.info_schema().table_by_name("nd", "lineitem").info.id
        step = N_ROWS // n_regions
        store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _c(name: str) -> int:
    return metrics.counter(name).value


def _disp() -> int:
    """Total batched states dispatches, whichever route answered."""
    return (_c("copr.states_batch.dispatches")
            + _c("copr.mesh.near_data_dispatches"))


def _all(s: Session) -> list:
    return [s.execute(q)[0].values() for q in QUERIES]


def _row_protocol(s: Session, queries=QUERIES) -> list:
    s.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        return [s.execute(q)[0].values() for q in queries]
    finally:
        s.execute("set global tidb_tpu_columnar_scan = 1")


def _norm(rows):
    out = []
    for row in rows:
        nr = []
        for v in row:
            if v is None:
                nr.append(None)
            else:
                try:
                    nr.append(round(float(v), 9))
                except (TypeError, ValueError):
                    nr.append(v.decode() if isinstance(v, bytes) else v)
        out.append(nr)
    return out


@pytest.mark.parametrize("n_regions", [1, 2, 4, 8])
def test_one_batched_dispatch_per_statement(n_regions, monkeypatch):
    """The headline invariant: EVERY region's states compute in ONE
    segmented dispatch per statement (never one per region), on either
    route, with answers identical to the serial per-region path and the
    row protocol."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(n_regions)
    d0 = _disp()
    ser0 = _c("copr.states_batch.serial_dispatches")
    reg0 = (_c("copr.states_batch.regions")
            + _c("copr.mesh.near_data_regions"))
    got = _all(s)
    assert _disp() - d0 == len(QUERIES), \
        (f"{_disp() - d0} batched dispatches for {len(QUERIES)} "
         f"statements over {n_regions} regions — not one per statement")
    assert _c("copr.states_batch.serial_dispatches") == ser0, \
        "a region fell off the batch onto the serial per-region path"
    regs = (_c("copr.states_batch.regions")
            + _c("copr.mesh.near_data_regions")) - reg0
    assert regs >= n_regions * len(QUERIES) - len(QUERIES), \
        f"only {regs} region segments rode the batched dispatches"

    # oracle 1: the serial per-region path (pre-PR-16 behavior)
    monkeypatch.setattr(columnar_region, "BATCH_STATES_ENABLED", False)
    serial = _all(s)
    assert _c("copr.states_batch.serial_dispatches") > ser0, \
        "BATCH_STATES_ENABLED=False never took the serial device path"
    monkeypatch.setattr(columnar_region, "BATCH_STATES_ENABLED", True)
    for q, g, w in zip(QUERIES, got, serial):
        assert _norm(g) == _norm(w), \
            f"batched states diverged from the serial path on {q!r}"
    # oracle 2: the row protocol
    want = _row_protocol(s)
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"batched states diverged from the row protocol on {q!r}"


def test_float_sum_sequential_rounding_bitexact(monkeypatch):
    """Float SUM/AVG through the BATCHED device dispatch stay EXACT
    (==, not approximate) vs the row protocol: partials accumulate in
    row order inside each region segment and merge in task order,
    reproducing the row path's rounding sequence bit for bit."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    q = ("select l_k, sum(l_disc), avg(l_disc) from lineitem "
         "group by l_k order by l_k")
    d0 = _disp()
    got = s.execute(q)[0].values()
    assert _disp() > d0, "float-sum query missed the batched dispatch"
    want = _row_protocol(s, [q])[0]
    assert got == want     # bitwise-identical floats


def test_mid_scan_split_and_merge_rebatch(monkeypatch):
    """A split/merge injected DURING the fan-out: the stale-epoch retry
    re-collects payloads and the finisher still computes the statement
    in one batched dispatch over the NEW region set — answers
    unchanged."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    store = s.store
    want = _all(s)
    tid = s.info_schema().table_by_name("nd", "lineitem").info.id

    def mutate_split(st):
        st.cluster.split_keys([tc.encode_row_key(tid, 33),
                               tc.encode_row_key(tid, 177)])

    def mutate_merge(st):
        regions = st.cluster.regions
        for i in range(len(regions) - 1):
            if regions[i].start:
                st.cluster.merge(regions[i].region_id,
                                 regions[i + 1].region_id)
                return

    for mutate in (mutate_split, mutate_merge):
        orig = store.rpc.cop_request
        state = {"n": 0, "done": False}

        def hook(ctx, sel, ranges, read_ts, orig=orig, state=state,
                 mutate=mutate):
            state["n"] += 1
            if state["n"] == 2 and not state["done"]:
                state["done"] = True
                mutate(store)
            return orig(ctx, sel, ranges, read_ts)

        store.rpc.cop_request = hook
        d0 = _disp()
        try:
            got = _all(s)
        finally:
            store.rpc.cop_request = orig
        assert state["done"]
        assert _disp() - d0 == len(QUERIES), \
            "mid-scan topology change broke one-dispatch-per-statement"
        for q, g, w in zip(QUERIES, got, want):
            assert _norm(g) == _norm(w), \
                f"mid-scan topology change diverged on {q!r}"


def test_mesh_fault_degrades_to_single_device_batch(monkeypatch):
    """device/mesh_collective under the shard-owned route → the
    single-device batched kernel answers (copr.degraded_near_data), the
    dispatch stays ONE per statement, answers unchanged."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _all(s)
    deg = metrics.counter("copr.degraded_near_data")
    d0, sd0, md0 = deg.value, _c("copr.states_batch.dispatches"), \
        _c("copr.mesh.near_data_dispatches")
    failpoint.enable("device/mesh_collective")
    try:
        got = _all(s)
    finally:
        failpoint.disable("device/mesh_collective")
    from tidb_tpu.ops import mesh as mesh_mod
    if mesh_mod.get_mesh() is not None:
        assert deg.value > d0, \
            "mesh collective fault never degraded the near-data route"
        assert _c("copr.mesh.near_data_dispatches") == md0
    assert _c("copr.states_batch.dispatches") - sd0 >= len(QUERIES), \
        "degraded statements missed the single-device batched kernel"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"single-device degraded batch diverged on {q!r}"


def test_device_fault_ladder_bottoms_out_at_host(monkeypatch):
    """device/agg_states + device/mesh_collective take out EVERY device
    rung: mesh → (degraded_near_data) batched single-device →
    (degraded_states_batch) serial per-region → (degraded_states_to_host)
    host numpy — answers unchanged at the bottom."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _row_protocol(s)
    deg_b = metrics.counter("copr.degraded_states_batch")
    deg_h = metrics.counter("copr.degraded_states_to_host")
    b0, h0 = deg_b.value, deg_h.value
    st0 = _c("distsql.columnar_states")
    failpoint.enable("device/mesh_collective")
    failpoint.enable("device/agg_states")
    try:
        got = _all(s)
    finally:
        failpoint.disable("device/agg_states")
        failpoint.disable("device/mesh_collective")
    assert deg_b.value > b0, \
        "batched-kernel fault never degraded to the serial path"
    assert deg_h.value > h0, \
        "serial-kernel fault never degraded to host numpy"
    assert _c("distsql.columnar_states") - st0 >= 4 * len(QUERIES), \
        "host-degraded regions stopped shipping states payloads"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"host-degraded states diverged on {q!r}"


def test_copr_agg_states_fault_degrades_to_rows():
    """copr/agg_states → regions drop to partial ROWS (the bottom rung
    below the states channel entirely) — counted as per-partial
    fallbacks, answers unchanged."""
    s = _build(4)
    want = _row_protocol(s)
    f0 = _c("distsql.columnar_fallbacks")
    failpoint.enable("copr/agg_states")
    try:
        got = _all(s)
    finally:
        failpoint.disable("copr/agg_states")
    assert _c("distsql.columnar_fallbacks") > f0
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"row-degraded aggregate diverged on {q!r}"


def _pc(name: str) -> int:
    return metrics.counter(f"copr.plane_cache.{name}").value


def test_plane_cache_keeps_live_old_snapshot_generation():
    """The oldest-active-ts keep set (HTAP residual): a NEWER reader's
    version sweep KEEPS the generation a live old snapshot still reads
    verbatim (copr.plane_cache.kept_active) — the old snapshot's re-read
    HITS instead of re-packing — and once that reader is gone the next
    sweep reclaims it as usual."""
    s1 = _build(4)
    store = s1.store
    s1.execute("set global tidb_tpu_delta_pack = 0")
    try:
        s2 = Session(store)
        s2.execute("use nd")
        q = "select count(*), sum(l_qty) from lineitem"
        s1.execute("begin")
        old = s1.execute(q)[0].values()    # packs planes at the OLD version
        s2.execute("insert into lineitem values "
                   "(900, 'A', 'F', 5, 1000, 0.05, 1, '1998-01-01')")
        ka0, iv0 = _pc("kept_active"), _pc("invalidations_version")
        new = s2.execute(q)[0].values()
        assert new != old, "newer session missed the committed write"
        assert _pc("kept_active") > ka0, \
            "the live old snapshot's generation was swept"
        assert _pc("invalidations_version") == iv0, \
            "the keep set still let the version sweep reclaim entries"
        h0, m0 = _pc("hits"), _pc("misses")
        assert s1.execute(q)[0].values() == old, \
            "old snapshot diverged after the newer reader's sweep"
        assert _pc("hits") - h0 >= 4, \
            "old snapshot re-read did not hit its kept generation"
        assert _pc("misses") == m0, \
            "old snapshot re-packed despite the keep set"
        s1.execute("commit")
        gc.collect()           # drop any lingering snapshot registrants
        s2.execute("insert into lineitem values "
                   "(901, 'N', 'O', 6, 1001, 0.06, 2, '1998-01-02')")
        iv1 = _pc("invalidations_version")
        s2.execute(q)
        assert _pc("invalidations_version") > iv1, \
            "with no live old reader the stale generations must be swept"
    finally:
        s1.execute("set global tidb_tpu_delta_pack = 1")
