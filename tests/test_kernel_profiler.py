"""Kernel-level continuous profiler: per-(kind, signature) roofline
attribution off the metered dispatch lock, the TIDB_TPU_KERNEL_PROFILE
table, cross-thread Perfetto trace-event export, HBM high-water
telemetry, and the statement-level `profile:` clause.

The accounting contract under test: the dispatch-serial lock's __exit__
computes ONE integer microsecond figure and feeds it to BOTH
`device.busy_us` and `profiler.publish`, so Σ per-signature device_us
must equal the busy_us delta exactly — including under concurrent
sessions (no cross-attribution, no second accounting path). The kill
switch retains nothing; the always-on cost stays under the same <2 ms
per-statement guard as the digest pipeline (PR 10).
"""

from __future__ import annotations

import itertools
import json
import threading
import time

import pytest

from tidb_tpu import errors, inspection, metrics, profiler, tracing
from tidb_tpu import flight
from tidb_tpu import tablecodec as tc
from tidb_tpu.metrics import timeseries
from tidb_tpu.session import Session, new_store
from tidb_tpu.types import Datum

_id = itertools.count(1)

N_ROWS = 40_000
N_REGIONS = 8
AGG_Q = "select b, sum(a), count(c) from t group by b"


def _build(n_rows: int = N_ROWS, n_regions: int = N_REGIONS) -> Session:
    """Cluster store split into n_regions, each region's row count above
    the device-states floor (4096) so the fan-out dispatches per-region
    device kernels on the drain-pool workers AND a mesh/combine on the
    statement thread — the cross-thread shape the trace-event export
    must render."""
    store = new_store(f"cluster://4/kprof{next(_id)}")
    s = Session(store)
    s.execute("create database kp")
    s.execute("use kp")
    s.execute("create table t (id bigint primary key, a bigint, "
              "b bigint, c bigint)")
    tbl = s.info_schema().table_by_name("kp", "t")
    rows = [[Datum.i64(i), Datum.i64(i % 97), Datum.i64(i % 13),
             Datum.i64(i)] for i in range(1, n_rows + 1)]
    for start in range(0, n_rows, 10_000):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + 10_000],
                        skip_unique_check=True)
        txn.commit()
    step = max(n_rows // n_regions, 1)
    store.cluster.split_keys(
        [tc.encode_row_key(tbl.info.id, step * i + 1)
         for i in range(1, n_regions)])
    return s


@pytest.fixture(scope="module")
def sess() -> Session:
    profiler.set_enabled(True)
    s = _build()
    s.execute(AGG_Q)   # warm: jit compile + plane pack
    return s


def _sv(v):
    return v.decode() if isinstance(v, bytes) else v


def _rows(s, sql):
    return s.execute(sql)[0].values()


# ---------------------------------------------------------------------------
# 1. registry attribution + windowed reconciliation
# ---------------------------------------------------------------------------

class TestAttribution:
    def test_registry_attributes_device_dispatches(self, sess):
        snap0 = profiler.registry_snapshot()
        sess.execute(AGG_Q)
        snap1 = profiler.registry_snapshot()
        grown = {label: e for label, e in snap1.items()
                 if e["dispatches"] > snap0.get(label,
                                                {"dispatches": 0})
                 ["dispatches"]}
        assert grown, f"no signature grew: {sorted(snap1)}"
        for label, e in grown.items():
            kind, _, sig = label.partition("|")
            assert kind and sig, label
            assert e["device_us"] > 0, (label, e)
        # the statement moved real bytes through the tunnel somewhere
        assert any(e["readback_bytes"] > 0 for e in snap1.values())
        assert any(e["rows"] > 0 for e in snap1.values())

    def test_device_us_reconciles_across_concurrent_sessions(self, sess):
        """Acceptance: Σ per-signature device_us == device.busy_us delta
        with 3 sessions dispatching concurrently — both sides are fed
        the same integer inside the lock's __exit__, so equality is
        exact, not approximate."""
        store = sess.store
        sessions = [Session(store) for _ in range(3)]
        for ss in sessions:
            ss.execute("use kp")
        busy0 = metrics.counter("device.busy_us").value
        snap0 = profiler.registry_snapshot()
        barrier = threading.Barrier(3)
        errs: list = []

        def run(ss):
            try:
                barrier.wait()
                for _ in range(2):
                    ss.execute(AGG_Q)
            except Exception as e:   # surfaced by the assert below
                errs.append(e)

        ts = [threading.Thread(target=run, args=(ss,))
              for ss in sessions]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errs, errs
        busy_delta = metrics.counter("device.busy_us").value - busy0
        snap1 = profiler.registry_snapshot()
        sig_delta = sum(
            e["device_us"] - snap0.get(label, {"device_us": 0})
            ["device_us"] for label, e in snap1.items())
        assert busy_delta > 0
        assert sig_delta == busy_delta, (sig_delta, busy_delta)

    def test_no_cross_attribution_between_sessions(self, sess):
        """A session running only below-floor statements must not pick
        up another session's concurrent device dispatches in its own
        per-statement profile tally."""
        store = sess.store
        heavy, light = Session(store), Session(store)
        heavy.execute("use kp")
        light.execute("use kp")
        barrier = threading.Barrier(2)
        out: dict = {}

        def run_heavy():
            barrier.wait()
            for _ in range(3):
                heavy.execute(AGG_Q)

        def run_light():
            barrier.wait()
            for _ in range(20):
                kp0 = tracing.kernel_profile_snapshot()
                light.execute("select 1")
                d = tracing.kernel_profile_delta(kp0)
                out.setdefault("deltas", []).append(d)

        ts = [threading.Thread(target=run_heavy),
              threading.Thread(target=run_light)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        leaked = [d for d in out["deltas"] if d]
        assert not leaked, leaked

    def test_windowed_profile_reconciles_with_busy_us(self, sess):
        """The TIDB_TPU_KERNEL_PROFILE window derivation: over ONE
        recorder window, Σ profiler.sig.device_us deltas equals the
        device.busy_us delta (both are counters sampled at the same
        instants)."""
        timeseries.recorder.sample()
        sess.execute(AGG_Q)
        time.sleep(0.002)
        d, _begin, _end = timeseries.recorder.sample_window(
            int(inspection.threshold("window_samples")))
        sig_sum = sum(delta for name, delta in d.items()
                      if name.startswith(profiler.METRIC_PREFIX
                                         + "device_us."))
        assert sig_sum == pytest.approx(d.get("device.busy_us", 0.0))
        assert sig_sum > 0


# ---------------------------------------------------------------------------
# 2. queryable surfaces: profile table, profile clause, retrace rule
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_kernel_profile_table(self, sess):
        sess.execute(AGG_Q)
        time.sleep(0.002)
        rows = _rows(sess,
                     "select KIND, SIGNATURE, DISPATCHES, RETRACES, "
                     "DEVICE_US, TRACE_US, EXECUTE_US, READBACK_BYTES, "
                     "H2D_BYTES, PROCESSED_ROWS, BYTES_PER_DEVICE_SEC, "
                     "ROWS_PER_SEC, BOUND from "
                     "information_schema.TIDB_TPU_KERNEL_PROFILE")
        assert rows, "profile table empty after a device statement"
        for r in rows:
            kind, sig = _sv(r[0]), _sv(r[1])
            assert kind and sig
            assert r[2] >= 1 and r[4] > 0          # dispatches, device_us
            assert r[6] == r[4] - r[5]             # execute = device-trace
            assert _sv(r[12]) in ("readback-bound", "compute-bound",
                                  "idle")
        # ordered hottest-first by device time
        dev = [r[4] for r in rows]
        assert dev == sorted(dev, reverse=True)

    def test_profile_clause_in_execution_detail_and_digest(self, sess):
        sess.execute(AGG_Q)
        details = [_sv(r[1]) or "" for r in _rows(
            sess, "select SQL_TEXT, EXECUTION_DETAIL from "
                  "performance_schema.events_statements_history")]
        assert any("profile:" in d for d in details), details[-5:]
        prof = [_sv(r[1]) for r in _rows(
            sess, "select DIGEST_TEXT, PROFILE from "
                  "performance_schema.events_statements_summary_by_digest")
            if r[1] is not None]
        assert prof and all("|" in p and p.endswith("us") for p in prof)

    def test_profile_clause_in_slow_log(self, sess, caplog):
        import logging
        sess.execute("set global tidb_slow_log_threshold = 1")
        try:
            with caplog.at_level(logging.WARNING, "tidb_tpu.slowlog"):
                sess.execute(AGG_Q)
        finally:
            sess.execute("set global tidb_slow_log_threshold = 300")
        slow = [r.getMessage() for r in caplog.records
                if "SLOW_QUERY" in r.getMessage()]
        assert any("profile:" in m for m in slow), slow

    def test_retrace_storm_rule_fires(self, sess):
        burst = int(inspection.threshold("retrace_burst"))
        label = "fake|99pl/32768"
        timeseries.recorder.sample()
        metrics.counter(
            f"{profiler.METRIC_PREFIX}jit_misses.{label}").inc(burst + 1)
        metrics.counter(
            f"{profiler.METRIC_PREFIX}device_us.{label}").inc(50_000)
        metrics.counter(
            f"{profiler.METRIC_PREFIX}trace_us.{label}").inc(45_000)
        time.sleep(0.002)
        rows = _rows(sess,
                     "select RULE, ITEM, ITEM_VALUE, DETAILS from "
                     "information_schema.TIDB_TPU_INSPECTION_RESULT")
        hits = [r for r in rows if _sv(r[0]) == "retrace-storm"
                and _sv(r[1]) == label]
        assert hits, [(_sv(r[0]), _sv(r[1])) for r in rows]
        assert "retraced" in _sv(hits[0][3])


# ---------------------------------------------------------------------------
# 3. trace-event export (Perfetto) — cross-thread timeline
# ---------------------------------------------------------------------------

class TestTraceEventExport:
    def _export(self, sess) -> dict:
        sess.execute("set global tidb_slow_log_threshold = 1")
        try:
            sess.execute(AGG_Q)
        finally:
            sess.execute("set global tidb_slow_log_threshold = 300")
        entries = flight.recorder_for(sess.store).entries()
        agg = [e for e in entries if "group by" in e["sql"]]
        assert agg, [e["sql"][:40] for e in entries]
        return json.loads(flight.trace_event_json(agg[-1]))

    def test_export_valid_with_four_lanes_and_kernel_args(self, sess):
        """Acceptance: the fan-out statement's export parses as valid
        JSON with >= 4 distinct thread lanes (statement thread, drain
        pool workers, the synthetic device-serial lane) and >= 1 kernel
        slice carrying bytes/rows args. Lane count rides on which pool
        workers win the eight region tasks — one worker can drain them
        all on a quiet scheduler — so the statement retries until the
        timeline shows the multi-worker shape."""
        for _ in range(10):
            doc = self._export(sess)
            slices = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
            assert slices
            lanes = {e["tid"] for e in slices}
            if len(lanes) >= 4:
                break
        for e in slices:
            assert e["dur"] >= 0 and isinstance(e["tid"], int)
        assert len(lanes) >= 4, sorted(lanes)
        with_io = [e for e in slices
                   if set(e.get("args", {})) & {"readback_bytes",
                                                "rows", "n_rows"}]
        assert with_io, [e["name"] for e in slices][:20]
        # thread_name metadata labels every lane (Perfetto track names)
        named = {e["tid"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "thread_name"}
        assert lanes <= named
        # the dispatch-serial lock lane carries at least one hold
        assert any(e["tid"] == 0 and e.get("cat") == "device"
                   for e in slices)

    def test_slow_traces_trace_event_json_column(self, sess):
        self._export(sess)
        rows = _rows(sess,
                     "select SQL_TEXT, TRACE_EVENT_JSON from "
                     "information_schema.TIDB_TPU_SLOW_TRACES")
        assert rows
        doc = json.loads(_sv(rows[-1][1]))
        assert doc["traceEvents"]

    def test_admin_tpu_profile_export(self, sess):
        self._export(sess)
        rows = _rows(sess, "admin tpu profile export")
        assert len(rows) == 1
        digest, sql_text, tej = (_sv(c) for c in rows[0])
        assert digest and sql_text
        doc = json.loads(tej)
        assert {e.get("ph") for e in doc["traceEvents"]} >= {"X", "M"}


# ---------------------------------------------------------------------------
# 4. sysvars: GLOBAL-only, persisted, kill switch retains nothing
# ---------------------------------------------------------------------------

class TestSysvars:
    def test_global_only_and_persisted(self, sess):
        with pytest.raises(errors.TiDBError):
            sess.execute("set tidb_tpu_kernel_profile = 0")
        with pytest.raises(errors.TiDBError):
            sess.execute("set tidb_tpu_profile_max_signatures = 8")
        sess.execute("set global tidb_tpu_profile_max_signatures = 300")
        try:
            row = _rows(sess,
                        "select variable_value from "
                        "mysql.global_variables where variable_name = "
                        "'tidb_tpu_profile_max_signatures'")
            assert _sv(row[0][0]) == "300"
        finally:
            sess.execute(
                "set global tidb_tpu_profile_max_signatures = 256")

    def test_kill_switch_retains_nothing(self, sess):
        sess.execute("set global tidb_tpu_kernel_profile = 0")
        try:
            assert not profiler.is_enabled()
            assert profiler.registry_snapshot() == {}
            # a device statement while off must not repopulate anything
            busy0 = metrics.counter("device.busy_us").value
            sess.execute(AGG_Q)
            assert metrics.counter("device.busy_us").value > busy0, \
                "workload did not dispatch — kill-switch test is vacuous"
            assert profiler.registry_snapshot() == {}
            assert len(profiler._holds) == 0
            assert profiler._thread_names == {}
        finally:
            sess.execute("set global tidb_tpu_kernel_profile = 1")
        assert profiler.is_enabled()

    def test_max_signatures_folds_overflow(self):
        profiler.set_enabled(True)
        profiler.set_max_signatures(2)
        try:
            base = dict.fromkeys(("rows", "rb", "h2d"), 0)
            for i in range(5):
                profiler.publish(("tkind", f"sig{i}", 0, 0, 0, False), 7)
            snap = profiler.registry_snapshot()
            mine = {l: e for l, e in snap.items()
                    if l.startswith("tkind|")}
            assert "tkind|~overflow" in mine, sorted(snap)
            # the fold keeps the device_us sum closed
            assert sum(e["device_us"] for e in mine.values()) == 35, mine
            del base
        finally:
            profiler.set_max_signatures(256)


# ---------------------------------------------------------------------------
# 5. overhead guard + HBM high-water telemetry
# ---------------------------------------------------------------------------

class TestOverheadAndHbm:
    def test_profiler_overhead_under_2ms_per_stmt(self):
        """PR 10 guard pattern: best-of-3 timed loops, profiler on vs
        off, on a trivial statement — the per-statement cost of the
        kprof snapshot/delta + publish path must stay under 2 ms."""
        store = new_store(f"memory://kprofov{next(_id)}")
        s = Session(store)
        s.execute("set global tidb_slow_log_threshold = 0")
        s.execute("create database o")
        s.execute("use o")
        n = 40

        def timed_loop() -> float:
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(n):
                    s.execute("select 1")
                best = min(best, time.perf_counter() - t0)
            return best

        s.execute("select 1")
        t_on = timed_loop()
        s.execute("set global tidb_tpu_kernel_profile = 0")
        try:
            t_off = timed_loop()
        finally:
            s.execute("set global tidb_tpu_kernel_profile = 1")
        per_stmt_ms = max(0.0, (t_on - t_off) / n) * 1e3
        assert per_stmt_ms < 2.0, f"{per_stmt_ms:.3f} ms/stmt"

    def test_hbm_highwater_marks(self):
        from tidb_tpu.ops import membudget
        membudget.reset_highwater()
        with membudget.reserve(1000, kind="probe"):
            with membudget.reserve(2500, kind="probe"):
                pass
        with membudget.reserve(700, kind="build"):
            pass
        hw = membudget.highwater()
        assert hw["probe"] == 3500 and hw["build"] >= 700
        assert hw["total"] >= 3500
        # gauges mirror the ledger for the metrics/inspection surfaces
        assert metrics.gauge("device.hbm.hw.probe").value == 3500
        assert metrics.gauge("device.hbm.hw.total").value == hw["total"]
        membudget.reset_highwater()
        assert membudget.highwater()["total"] == 0
        assert metrics.gauge("device.hbm.hw.probe").value == 0

    def test_highwater_sampled_into_metrics_history(self, sess):
        from tidb_tpu.ops import membudget
        with membudget.reserve(4096, kind="dispatch"):
            timeseries.recorder.sample()
        time.sleep(0.002)
        rows = _rows(sess,
                     "select NAME, LABELS, METRIC_VALUE from "
                     "information_schema.TIDB_TPU_METRICS_HISTORY "
                     "where NAME = 'device.hbm.hw'")
        kinds = {_sv(r[1]) for r in rows}
        assert 'kind="total"' in kinds, sorted(kinds)
        assert any(_sv(r[1]) == 'kind="total"' and r[2] >= 4096
                   for r in rows)
