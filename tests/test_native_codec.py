"""Native (C) codec vs pure-Python codec: byte-identical output on
randomized datums, plus fallback behavior for unsupported kinds."""

import random

import pytest

from tidb_tpu import native
from tidb_tpu.codec import codec
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import Kind, NULL
from tidb_tpu.types.time_types import Duration, Time, parse_time


pytestmark = pytest.mark.skipif(native.codecx is None,
                                reason="native codec not built")


def _py_encode(datums, comparable):
    buf = bytearray()
    for d in datums:
        codec.encode_datum(buf, d, comparable)
    return bytes(buf)


def _random_datum(rng):
    roll = rng.random()
    if roll < 0.1:
        return NULL
    if roll < 0.3:
        return Datum.i64(rng.randint(-(1 << 63), (1 << 63) - 1))
    if roll < 0.4:
        return Datum.u64(rng.randint(0, (1 << 64) - 1))
    if roll < 0.55:
        return Datum.f64(rng.uniform(-1e12, 1e12))
    if roll < 0.7:
        n = rng.randint(0, 40)
        return Datum.string("".join(chr(rng.randint(32, 0x24F))
                                    for _ in range(n)))
    if roll < 0.8:
        n = rng.randint(0, 40)
        return Datum.bytes_(bytes(rng.randint(0, 255) for _ in range(n)))
    if roll < 0.9:
        return Datum(Kind.DURATION,
                     Duration(rng.randint(-(10 ** 15), 10 ** 15)))
    import datetime as dt
    t = parse_time("2000-01-01")
    return Datum(Kind.TIME, Time(
        t.dt + dt.timedelta(days=rng.randint(0, 10000),
                            seconds=rng.randint(0, 86399),
                            microseconds=rng.randint(0, 999999)), t.tp))


@pytest.mark.parametrize("comparable", [True, False])
def test_differential_random(comparable):
    rng = random.Random(99)
    for _ in range(300):
        datums = [_random_datum(rng) for _ in range(rng.randint(1, 6))]
        expect = _py_encode(datums, comparable)
        got = native.codecx.encode_datums(datums, comparable)
        assert got == expect, datums


def test_encode_row_matches():
    rng = random.Random(7)
    from tidb_tpu import tablecodec as tc
    for _ in range(100):
        n = rng.randint(0, 5)
        cids = [rng.randint(1, 200) for _ in range(n)]
        datums = [_random_datum(rng) for _ in range(n)]
        got = tc.encode_row(cids, datums)
        buf = bytearray()
        if not cids:
            expect = bytes([codec.NIL_FLAG])
        else:
            for cid, d in zip(cids, datums):
                codec.encode_datum(buf, Datum.i64(cid), comparable=False)
                codec.encode_datum(buf, d, comparable=False)
            expect = bytes(buf)
        assert got == expect


def test_decodes_back():
    rng = random.Random(5)
    from tidb_tpu import tablecodec as tc
    for _ in range(50):
        n = rng.randint(1, 6)
        cids = list(range(1, n + 1))
        datums = [_random_datum(rng) for _ in range(n)]
        row = tc.decode_row(tc.encode_row(cids, datums))
        for cid, d in zip(cids, datums):
            if d.is_null():
                assert cid not in row or row[cid].is_null()
            else:
                assert cid in row


def test_unsupported_falls_back():
    from decimal import Decimal
    # DECIMAL is not natively encodable; encode_value must fall back to
    # the Python path and still succeed
    d = Datum.dec(Decimal("123.456"))
    out = codec.encode_value([d, Datum.i64(5)])
    buf = bytearray()
    codec.encode_datum(buf, d, False)
    codec.encode_datum(buf, Datum.i64(5), False)
    assert out == bytes(buf)
    with pytest.raises(native.codecx.Unsupported):
        native.codecx.encode_datums([d], False)


def test_iterator_argument_survives_fallback():
    """encode_key/encode_value must not consume a generator argument in
    the native attempt and then fall back over an exhausted iterator."""
    from decimal import Decimal
    datums = [Datum.dec(Decimal("1.5")), Datum.i64(1)]
    expect = _py_encode(datums, True)
    got = codec.encode_key(d for d in datums)
    assert got == expect and len(got) > 0


class TestNativeDecodeRow:
    """decode_row_datums (C) must be indistinguishable from the Python
    decoder — same kinds (real Kind enum members), same values — and
    fall back for flags it doesn't handle."""

    def test_all_kind_parity(self):
        from tidb_tpu import tablecodec as tc
        from tidb_tpu.codec import codec as cdc
        from tidb_tpu.native import codecx
        from tidb_tpu.types import Datum
        from tidb_tpu.types.datum import Kind
        from tidb_tpu.types.time_types import Duration, parse_time
        if codecx is None:
            import pytest
            pytest.skip("native build unavailable")
        cases = [
            ([], []),
            ([1, 2, 3], [Datum.i64(-5), Datum.u64(2**63 + 1),
                         Datum.f64(-1.25)]),
            ([4, 5], [Datum.bytes_(b"he\x00llo"), Datum.null()]),
            ([6], [Datum(Kind.DURATION, Duration(-3_600_000_000_000))]),
            ([7], [Datum(Kind.TIME, parse_time("2024-02-29 13:14:15"))]),
            ([8], [Datum.string("café")]),
        ]
        for cids, ds in cases:
            enc = tc.encode_row(cids, ds)
            nat = codecx.decode_row_datums(enc)
            ref = {}
            mv = memoryview(enc)
            pos = 0
            if enc != bytes([cdc.NIL_FLAG]):
                while pos < len(mv):
                    cd, pos = cdc.decode_one(mv, pos)
                    vd, pos = cdc.decode_one(mv, pos)
                    ref[cd.get_int()] = vd
            assert set(nat) == set(ref)
            for k, b in ref.items():
                a = nat[k]
                assert isinstance(a.kind, type(b.kind))
                assert a.kind == b.kind
                if a.kind == Kind.DURATION:
                    assert a.val.nanos == b.val.nanos
                elif a.kind == Kind.TIME:
                    assert (a.val.dt, a.val.tp) == (b.val.dt, b.val.tp)
                else:
                    assert a.val == b.val

    def test_decimal_falls_back_to_python(self):
        from decimal import Decimal
        from tidb_tpu import tablecodec as tc
        from tidb_tpu.types import Datum
        from tidb_tpu.types.datum import Kind
        enc = tc.encode_row([9, 10], [Datum.dec(Decimal("1.5")),
                                      Datum.i64(7)])
        row = tc.decode_row(enc)
        assert row[9].kind == Kind.DECIMAL and row[9].val == Decimal("1.5")
        assert row[10].val == 7

    def test_raw_response_scan_matches_sql(self):
        """A scan through the raw SelectResponse path returns the same
        rows the chunk path produced (probed via full SQL round trip
        over every column kind)."""
        from tests.testkit import TestKit
        tk = TestKit()
        tk.exec("create database nd; use nd")
        tk.exec("create table t (id bigint primary key, a int, b double, "
                "c varchar(10), d date, e time, f decimal(8,3))")
        tk.exec("insert into t values "
                "(1, -5, 1.5, 'x', '2024-01-02', '10:20:30', '1.250'), "
                "(2, null, null, null, null, null, null)")
        rows = tk.query("select * from t order by id").rows
        norm = [[str(v) if v is not None and not isinstance(
                     v, (int, float, str, bytes)) else v
                 for v in r] for r in rows]
        norm = [[v.decode() if isinstance(v, bytes) else v for v in r]
                for r in norm]
        assert norm == [
            [1, -5, 1.5, "x", "2024-01-02", "10:20:30", "1.250"],
            [2, None, None, None, None, None, None]], norm
