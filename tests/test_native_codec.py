"""Native (C) codec vs pure-Python codec: byte-identical output on
randomized datums, plus fallback behavior for unsupported kinds."""

import random

import pytest

from tidb_tpu import native
from tidb_tpu.codec import codec
from tidb_tpu.types import Datum
from tidb_tpu.types.datum import Kind, NULL
from tidb_tpu.types.time_types import Duration, Time, parse_time


pytestmark = pytest.mark.skipif(native.codecx is None,
                                reason="native codec not built")


def _py_encode(datums, comparable):
    buf = bytearray()
    for d in datums:
        codec.encode_datum(buf, d, comparable)
    return bytes(buf)


def _random_datum(rng):
    roll = rng.random()
    if roll < 0.1:
        return NULL
    if roll < 0.3:
        return Datum.i64(rng.randint(-(1 << 63), (1 << 63) - 1))
    if roll < 0.4:
        return Datum.u64(rng.randint(0, (1 << 64) - 1))
    if roll < 0.55:
        return Datum.f64(rng.uniform(-1e12, 1e12))
    if roll < 0.7:
        n = rng.randint(0, 40)
        return Datum.string("".join(chr(rng.randint(32, 0x24F))
                                    for _ in range(n)))
    if roll < 0.8:
        n = rng.randint(0, 40)
        return Datum.bytes_(bytes(rng.randint(0, 255) for _ in range(n)))
    if roll < 0.9:
        return Datum(Kind.DURATION,
                     Duration(rng.randint(-(10 ** 15), 10 ** 15)))
    import datetime as dt
    t = parse_time("2000-01-01")
    return Datum(Kind.TIME, Time(
        t.dt + dt.timedelta(days=rng.randint(0, 10000),
                            seconds=rng.randint(0, 86399),
                            microseconds=rng.randint(0, 999999)), t.tp))


@pytest.mark.parametrize("comparable", [True, False])
def test_differential_random(comparable):
    rng = random.Random(99)
    for _ in range(300):
        datums = [_random_datum(rng) for _ in range(rng.randint(1, 6))]
        expect = _py_encode(datums, comparable)
        got = native.codecx.encode_datums(datums, comparable)
        assert got == expect, datums


def test_encode_row_matches():
    rng = random.Random(7)
    from tidb_tpu import tablecodec as tc
    for _ in range(100):
        n = rng.randint(0, 5)
        cids = [rng.randint(1, 200) for _ in range(n)]
        datums = [_random_datum(rng) for _ in range(n)]
        got = tc.encode_row(cids, datums)
        buf = bytearray()
        if not cids:
            expect = bytes([codec.NIL_FLAG])
        else:
            for cid, d in zip(cids, datums):
                codec.encode_datum(buf, Datum.i64(cid), comparable=False)
                codec.encode_datum(buf, d, comparable=False)
            expect = bytes(buf)
        assert got == expect


def test_decodes_back():
    rng = random.Random(5)
    from tidb_tpu import tablecodec as tc
    for _ in range(50):
        n = rng.randint(1, 6)
        cids = list(range(1, n + 1))
        datums = [_random_datum(rng) for _ in range(n)]
        row = tc.decode_row(tc.encode_row(cids, datums))
        for cid, d in zip(cids, datums):
            if d.is_null():
                assert cid not in row or row[cid].is_null()
            else:
                assert cid in row


def test_unsupported_falls_back():
    from decimal import Decimal
    # DECIMAL is not natively encodable; encode_value must fall back to
    # the Python path and still succeed
    d = Datum.dec(Decimal("123.456"))
    out = codec.encode_value([d, Datum.i64(5)])
    buf = bytearray()
    codec.encode_datum(buf, d, False)
    codec.encode_datum(buf, Datum.i64(5), False)
    assert out == bytes(buf)
    with pytest.raises(native.codecx.Unsupported):
        native.codecx.encode_datums([d], False)


def test_iterator_argument_survives_fallback():
    """encode_key/encode_value must not consume a generator argument in
    the native attempt and then fall back over an exhausted iterator."""
    from decimal import Decimal
    datums = [Datum.dec(Decimal("1.5")), Datum.i64(1)]
    expect = _py_encode(datums, True)
    got = codec.encode_key(d for d in datums)
    assert got == expect and len(got) > 0
