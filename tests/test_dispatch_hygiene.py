"""Static check: every jitted launch+readback site in tidb_tpu/ops/
must serialize on `kernels.dispatch_serial`.

PR 9 fixed a flaky runtime deadlock (concurrent statement threads racing
a jitted program's launch/first-compile + readback wedged the process)
by serializing every executable launch+readback on one metered lock.
That contract was prose until now — a new dispatch site (the
partitioned-pass joins, the key-partitioned mesh probe, any future
spill-capable operator) could silently reintroduce the deadlock class.
This AST walk makes it unrepresentable. Two rules over `tidb_tpu/ops/`:

  (a) every CALL to a jitted executable — a name bound from a
      `jax.jit(...)` result in the same scope (function or module), or
      the conventional cache-entry name `jitted` — must sit lexically
      inside a `with ... dispatch_serial` block, and
  (b) every `np.asarray(<call>)` readback (the certified completion
      point on tunneled deployments) must too — excluding host-side
      helpers (`np.asarray` of another np call, `unpack_outputs`).

Compute-only dispatches whose outputs stay device-resident (the join
build, plane pads/gathers/stacks, the dictionary remap) need no lock —
one physical device runs one program at a time and nothing reads back —
but must SAY so with an explicit `# dispatch-ok: <reason>` pragma on
the call line, so review sees every exemption.

Tier-1 fails on any new violation, with file:line and the rule.
"""

from __future__ import annotations

import ast
from pathlib import Path

_PKG = Path(__file__).resolve().parent.parent / "tidb_tpu"
ROOT = _PKG / "ops"
# the near-data states channel (PR 16) moved a launch+readback site into
# tidb_tpu/parallel (CoprMesh._run_shardmajor) — the walk covers it too
EXTRA_ROOTS = (_PKG / "parallel",)

PRAGMA = "# dispatch-ok:"

# cache-entry convention: jitted callables unpacked from kernel caches
# are always bound (or passed) under this name
SEED_JITTED_NAMES = {"jitted"}

# host-side helpers whose np.asarray(...) argument is NOT a readback
HOST_CALL_NAMES = {"asarray", "unpack_outputs", "atleast_1d", "zeros",
                   "ones", "arange", "concatenate", "where", "full"}


def _terminal_name(func) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _contains_jit_call(node) -> bool:
    return any(isinstance(n, ast.Call) and _terminal_name(n.func) == "jit"
               for n in ast.walk(node))


def _scope_nodes(scope):
    """All nodes of one scope, NOT descending into nested function /
    lambda bodies (those are their own scopes and walk separately)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _jitted_names(scope) -> set[str]:
    """Name targets assigned IN THIS SCOPE from an expression containing
    a jax.jit call."""
    names: set[str] = set()
    for node in _scope_nodes(scope):
        if isinstance(node, ast.Assign) and _contains_jit_call(node.value):
            for tgt in node.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
    return names


def _serial_ranges(tree) -> list[tuple[int, int]]:
    """(lineno, end_lineno) spans of every `with ... dispatch_serial`
    body."""
    spans = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            if _terminal_name(item.context_expr) == "dispatch_serial" or (
                    isinstance(item.context_expr, ast.Call)
                    and _terminal_name(item.context_expr.func)
                    == "dispatch_serial"):
                spans.append((node.lineno, node.end_lineno))
    return spans


def _inside(spans, lineno: int) -> bool:
    return any(a <= lineno <= b for a, b in spans)


def _is_np_asarray(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "asarray"
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "np")


def _violations(path: Path) -> list[str]:
    src = path.read_text()
    lines = src.splitlines()
    tree = ast.parse(src, filename=str(path))
    spans = _serial_ranges(tree)
    module_jitted = _jitted_names(tree)
    bad: list[str] = []

    def check_scope(scope, jitted: set[str]):
        for node in _scope_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            pragma = PRAGMA in lines[node.lineno - 1]
            fname = _terminal_name(node.func)
            # rule (a): launching a jitted executable
            if isinstance(node.func, ast.Name) and fname in jitted:
                if not _inside(spans, node.lineno) and not pragma:
                    bad.append(
                        f"{path.name}:{node.lineno}: jitted executable "
                        f"`{fname}(...)` launched outside `with "
                        f"dispatch_serial` — serialize it, or justify a "
                        f"no-readback dispatch with `{PRAGMA} <reason>`")
            # rule (b): np.asarray readback of a call result
            if _is_np_asarray(node) and node.args:
                inner = [n for n in ast.walk(node.args[0])
                         if isinstance(n, ast.Call)
                         and _terminal_name(n.func) not in HOST_CALL_NAMES]
                if inner and not _inside(spans, node.lineno) and not pragma:
                    bad.append(
                        f"{path.name}:{node.lineno}: np.asarray readback "
                        f"of a call result outside `with dispatch_serial` "
                        f"— the launch+readback race (PR 9 deadlock "
                        f"class); serialize it or justify with "
                        f"`{PRAGMA} <reason>`")

    check_scope(tree, module_jitted)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            check_scope(node, module_jitted | SEED_JITTED_NAMES
                        | _jitted_names(node))
    return bad


def test_every_jitted_launch_readback_serializes():
    files = sorted(ROOT.glob("*.py"))
    assert files, "tidb_tpu/ops/ not found — layout changed?"
    for extra in EXTRA_ROOTS:
        extra_files = sorted(extra.glob("*.py"))
        assert extra_files, f"{extra} not found — layout changed?"
        files.extend(extra_files)
    problems: list[str] = []
    for f in files:
        problems.extend(_violations(f))
    assert not problems, "\n".join(problems)


def _serial_span_of(path: Path, func_name: str) -> bool:
    """True iff `func_name` in `path` contains at least one
    `with ... dispatch_serial` block (the launch+readback home)."""
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == func_name):
            return bool(_serial_ranges(node))
    return False


def test_segmented_states_dispatch_sites_serialize():
    """The PR 16 near-data sites, pinned by name: the batched segmented
    states kernel and the mesh shard-major runner both own a
    launch+readback and must keep their dispatch_serial blocks — a
    refactor that renames or moves them out fails here, not at the next
    concurrency deadlock."""
    assert _serial_span_of(ROOT / "kernels.py",
                           "region_agg_states_batched"), \
        "kernels.region_agg_states_batched lost its dispatch_serial block"
    assert _serial_span_of(_PKG / "parallel" / "__init__.py",
                           "_run_shardmajor"), \
        "CoprMesh._run_shardmajor lost its dispatch_serial block"


def test_batched_filter_dispatch_site_serializes():
    """The PR 17 filter tier, pinned by name: the batched ragged filter
    kernel owns a launch+readback (bit-packed masks) and must keep its
    dispatch_serial block."""
    assert _serial_span_of(ROOT / "kernels.py", "region_filter_batched"), \
        "kernels.region_filter_batched lost its dispatch_serial block"


def test_serial_states_dispatch_site_serializes():
    """The PR 18 arg-plane work rides BOTH states kernels: the serial
    per-region variant (the below-floor / degraded rung) owns a
    launch+readback too and must keep its dispatch_serial block."""
    assert _serial_span_of(ROOT / "kernels.py", "region_agg_states"), \
        "kernels.region_agg_states lost its dispatch_serial block"


def test_spill_dispatch_sites_serialize():
    """The PR 20 out-of-core sites, pinned by name: the device sort
    permutation kernel (external sort passes) and the window segment
    scan both own a launch+readback and must keep their dispatch_serial
    blocks — partitioned passes multiply the dispatch count, so an
    unserialized spill site is the fastest route back to the PR 9
    deadlock class."""
    assert _serial_span_of(ROOT / "kernels.py", "sort_perm"), \
        "kernels.sort_perm lost its dispatch_serial block"
    assert _serial_span_of(ROOT / "kernels.py", "window_scan"), \
        "kernels.window_scan lost its dispatch_serial block"


def test_checker_detects_unserialized_launch(tmp_path):
    """Meta-test: the walker must flag both rule shapes end-to-end (a
    refactor cannot silently neuter it)."""
    import textwrap
    bad = tmp_path / "badmod.py"
    bad.write_text(textwrap.dedent("""
        import jax
        import numpy as np

        module_kernel = jax.jit(lambda x: x)

        def f(planes):
            fn = jax.jit(lambda x: x)
            packed = fn(planes)
            host = np.asarray(run_thing(planes))
            return np.asarray(module_kernel(packed)), host
    """))
    problems = _violations(bad)
    # fn launch, run_thing readback, module_kernel launch + readback
    assert len(problems) == 4, problems
    assert any("`fn(...)`" in p for p in problems)
    assert any("np.asarray readback" in p for p in problems)
    # pragma and serialization both clear the same shapes
    ok = tmp_path / "okmod.py"
    ok.write_text(textwrap.dedent("""
        import jax
        import numpy as np

        def f(planes):
            fn = jax.jit(lambda x: x)
            out = fn(planes)  # dispatch-ok: device-resident output
            with dispatch_serial:
                host = np.asarray(fn(planes))
            return out, host
    """))
    assert not _violations(ok)


ANNOTATE_PRAGMA = "# profile-ok:"


def _serial_with_nodes(tree):
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            if _terminal_name(item.context_expr) == "dispatch_serial" or (
                    isinstance(item.context_expr, ast.Call)
                    and _terminal_name(item.context_expr.func)
                    == "dispatch_serial"):
                yield node
                break


def _unannotated_serial_blocks(path: Path) -> list[str]:
    """Profiler-coverage rule (PR 19): every metered `with
    dispatch_serial` block must call `dispatch_serial.annotate(...)`
    inside its body, so the launch it serializes publishes into the
    per-(kind, signature) profile registry — an unannotated block's
    device time would land in the `other|~unannotated` bucket and the
    per-statement profile clause would under-attribute. A block whose
    dispatch genuinely has nothing to annotate says so with
    `# profile-ok: <reason>` on the `with` line."""
    src = path.read_text()
    lines = src.splitlines()
    tree = ast.parse(src, filename=str(path))
    bad: list[str] = []
    for node in _serial_with_nodes(tree):
        if ANNOTATE_PRAGMA in lines[node.lineno - 1]:
            continue
        has_annotate = any(
            isinstance(n, ast.Call)
            and _terminal_name(n.func) == "annotate"
            for b in node.body for n in ast.walk(b))
        if not has_annotate:
            bad.append(
                f"{path.name}:{node.lineno}: metered `with "
                f"dispatch_serial` block without an `annotate(...)` "
                f"call — the kernel profiler cannot attribute this "
                f"dispatch; annotate it or justify with "
                f"`{ANNOTATE_PRAGMA} <reason>`")
    return bad


def test_every_metered_dispatch_publishes_profile():
    """PR 19 coverage contract: a new launch+readback site that
    serializes correctly but forgets to annotate still fails tier-1 —
    unattributed device time is the profiler's silent-data-loss mode."""
    files = sorted(ROOT.glob("*.py"))
    for extra in EXTRA_ROOTS:
        files.extend(sorted(extra.glob("*.py")))
    problems: list[str] = []
    for f in files:
        problems.extend(_unannotated_serial_blocks(f))
    assert not problems, "\n".join(problems)


def test_jit_sites_confined_to_metered_roots():
    """Package-wide sweep: `jax.jit` may appear ONLY under the roots the
    launch+readback walk covers (tidb_tpu/ops/, tidb_tpu/parallel/) — a
    jit site anywhere else would dispatch outside the metered lock
    discipline and the rules above would never see it."""
    allowed = {ROOT.resolve()} | {e.resolve() for e in EXTRA_ROOTS}
    problems: list[str] = []
    for f in sorted(_PKG.rglob("*.py")):
        if f.parent.resolve() in allowed:
            continue
        tree = ast.parse(f.read_text(), filename=str(f))
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and _terminal_name(node.func) == "jit":
                problems.append(
                    f"{f.relative_to(_PKG)}:{node.lineno}: jax.jit "
                    f"outside tidb_tpu/ops//tidb_tpu/parallel — the "
                    f"dispatch-hygiene walk cannot see this site; move "
                    f"it under a covered root")
    assert not problems, "\n".join(problems)


def test_annotate_checker_detects_unannotated_block(tmp_path):
    """Meta-test for the coverage rule: an unannotated metered block is
    flagged; the pragma and a real annotate call both clear it."""
    import textwrap
    bad = tmp_path / "badmod.py"
    bad.write_text(textwrap.dedent("""
        import numpy as np

        def f(planes, jitted):
            with dispatch_serial:
                host = np.asarray(jitted(planes))
            return host
    """))
    problems = _unannotated_serial_blocks(bad)
    assert len(problems) == 1 and "annotate" in problems[0], problems
    ok = tmp_path / "okmod.py"
    ok.write_text(textwrap.dedent("""
        import numpy as np

        def f(planes, jitted):
            with dispatch_serial:
                host = np.asarray(jitted(planes))
                dispatch_serial.annotate("k", "s",
                                         readback_bytes=host.nbytes)
            with dispatch_serial:  # profile-ok: compile-only warmup
                jitted(planes)
            return host
    """))
    assert not _unannotated_serial_blocks(ok)


def test_checker_accepts_serialized_launch():
    import textwrap
    snippet = textwrap.dedent("""
        import jax
        import numpy as np

        def f(planes):
            fn = jax.jit(lambda x: x)
            with dispatch_serial:
                host = np.asarray(fn(planes))
            return host
    """)
    tree = ast.parse(snippet)
    spans = _serial_ranges(tree)
    assert spans and _inside(spans, 8)
