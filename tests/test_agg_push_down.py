"""Aggregation pushdown across joins: differential correctness.

Mirrors plan/aggregation_push_down.go. The strongest check for a rewrite
rule is the rewrite-free oracle: every query runs twice — once with the
rule, once with it disabled — over randomized NULL-dense data, and the
results must be identical.
"""

import random

import pytest

from tidb_tpu.plan import optimizer as opt_mod
from tidb_tpu.plan.plans import PhysicalHashJoin
from tests.testkit import TestKit


@pytest.fixture
def tk():
    t = TestKit()
    t.exec("create database d; use d")
    t.exec("create table a (id int primary key, k int, v int, u int)")
    t.exec("create table b (id int primary key, k int, w int)")
    rng = random.Random(7)
    arows = []
    for i in range(120):
        k = rng.randint(0, 8)
        v = "null" if rng.random() < 0.15 else rng.randint(-50, 50)
        u = rng.randint(0, 3)
        arows.append(f"({i}, {k}, {v}, {u})")
    brows = []
    for i in range(80):
        k = rng.randint(0, 10)
        w = "null" if rng.random() < 0.15 else rng.randint(0, 1000)
        brows.append(f"({i}, {k}, {w})")
    t.exec(f"insert into a values {', '.join(arows)}")
    t.exec(f"insert into b values {', '.join(brows)}")
    return t


QUERIES = [
    "select sum(a.v) from a, b where a.k = b.k",
    "select count(a.v), min(a.v), max(a.v) from a, b where a.k = b.k",
    "select a.k, sum(a.v) from a, b where a.k = b.k group by a.k "
    "order by a.k",
    "select a.k, a.u, sum(a.v), min(b.w) from a, b where a.k = b.k "
    "group by a.k, a.u order by a.k, a.u",
    "select b.k, count(a.id) from a, b where a.k = b.k group by b.k "
    "order by b.k",
    "select a.u, sum(b.w) from a, b where a.k = b.k group by a.u "
    "order by a.u",
    "select sum(a.v) from a join b on a.k = b.k where b.w > 300",
    "select a.k, sum(a.v), max(b.w) from a join b on a.k = b.k "
    "and a.u = 1 group by a.k order by a.k",
    # shapes the rule must refuse but still answer correctly
    "select sum(a.v), count(b.w) from a, b where a.k = b.k",
    "select a.k, avg(a.v) from a, b where a.k = b.k group by a.k "
    "order by a.k",
    "select sum(b.k) from a, b where a.k = b.k",
    "select sum(a.v + 1) from a, b where a.k = b.k",
    "select a.k, sum(a.v) from a left join b on a.k = b.k group by a.k "
    "order by a.k",
]


def _norm(rows):
    out = []
    for row in rows:
        nr = []
        for v in row:
            try:
                nr.append(float(v))
            except (TypeError, ValueError):
                nr.append(v.decode() if isinstance(v, bytes) else v)
        out.append(nr)
    return out


@pytest.mark.parametrize("sql", QUERIES)
def test_rule_matches_rewrite_free_oracle(tk, sql, monkeypatch):
    with_rule = _norm(tk.exec(sql).rows)
    monkeypatch.setattr(opt_mod, "aggregation_push_down", lambda p: None)
    without_rule = _norm(tk.exec(sql).rows)
    assert with_rule == without_rule, sql


def test_rule_actually_fires(tk):
    from tidb_tpu.plan import optimize_plan
    from tidb_tpu.plan.builder import PlanBuilder
    from tidb_tpu.plan.plans import PhysicalHashAgg
    s = tk.session
    stmt = s.parser.parse_one(
        "select a.k, sum(a.v) from a, b where a.k = b.k group by a.k")
    p = optimize_plan(PlanBuilder(s).build(stmt), s, s.client, set())

    def find(n, tp):
        found = []
        if isinstance(n, tp):
            found.append(n)
        for c in n.children:
            found.extend(find(c, tp))
        return found

    join = find(p, PhysicalHashJoin)[0]
    # the pushed partial aggregation sits BELOW the join on the a side
    assert find(join, PhysicalHashAgg), \
        "no partial aggregation below the join"
