"""metrics/push.py: push-gateway round-trips carrying the
workload-observability metric families (perfschema digest summary +
copr.region_heat), and exposition conformance of those families in
render_text — name charset, TYPE declarations, registry agreement.
"""

from __future__ import annotations

import http.server
import itertools
import re
import threading
import time

from tidb_tpu import metrics, tablecodec as tc
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)


def _workload_store():
    """A 2-region store that has run enough workload that every digest
    and heat family exists in the process registry, with the lazy
    gauges refreshed (reading the SQL surfaces is what refreshes them,
    same contract as the plane-cache gauges)."""
    store = new_store(f"cluster://3/mpush{next(_id)}")
    s = Session(store)
    s.execute("create database m")
    s.execute("use m")
    s.execute("create table t (id bigint primary key, v bigint)")
    s.execute("insert into t values " +
              ", ".join(f"({i}, {i})" for i in range(1, 41)))
    tid = s.info_schema().table_by_name("m", "t").info.id
    store.cluster.split_keys([tc.encode_row_key(tid, 21)])
    for i in (1, 5, 25, 30):
        s.execute(f"select v from t where id = {i}")
    s.execute("select * from information_schema.TIDB_TPU_HOT_REGIONS")
    s.execute("select * from performance_schema."
              "events_statements_summary_by_digest")
    return store, s


# the new families, by their exposition (dot→underscore) names
DIGEST_HEAT_FAMILIES = {
    "perfschema_digest_statements": "counter",
    "perfschema_digest_entries": "gauge",
    "copr_region_heat_read_rows": "counter",
    "copr_region_heat_read_bytes": "counter",
    "copr_region_heat_write_rows": "counter",
    "copr_region_heat_write_bytes": "counter",
    "copr_region_heat_regions": "gauge",
    "copr_region_heat_top_region": "gauge",
    "copr_region_heat_top_score": "gauge",
}


class TestPushRoundTrip:
    def test_push_once_carries_digest_and_heat_families(self):
        """One real HTTP PUT against an in-process Pushgateway-shaped
        server: the body must be the registry's exposition including
        every digest/heat family the workload populated."""
        _workload_store()
        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append((self.path,
                                 self.headers.get("Content-Type", ""),
                                 self.rfile.read(n)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            from tidb_tpu.metrics import push as mpush
            ok = mpush.push_once(f"127.0.0.1:{srv.server_port}",
                                 job="wk", instance="i1")
            assert ok
            assert received, "no push arrived"
            path, ctype, body = received[0]
            assert path == "/metrics/job/wk/instance/i1"
            assert ctype.startswith("text/plain")
            text = body.decode()
            for fam in DIGEST_HEAT_FAMILIES:
                assert f"\n{fam} " in "\n" + text, \
                    f"family {fam} missing from the pushed exposition"
        finally:
            srv.shutdown()

    def test_push_loop_keeps_families_fresh(self):
        """The interval loop re-renders at each push: a counter bumped
        between pushes shows its new value in a later body."""
        _store, s = _workload_store()
        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append(self.rfile.read(n))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            from tidb_tpu.metrics import push as mpush
            t = mpush.start_push_client(
                f"127.0.0.1:{srv.server_port}", 0.05, job="wk2")
            assert t is not None
            deadline = time.time() + 5
            while not received and time.time() < deadline:
                time.sleep(0.02)
            n_before = len(received)
            before = metrics.counter("perfschema.digest_statements").value
            s.execute("select v from t where id = 2")
            deadline = time.time() + 5
            while len(received) <= n_before and time.time() < deadline:
                time.sleep(0.02)
            t.stop_event.set()
            t.join(timeout=2)
            assert len(received) > n_before, "push loop stopped pushing"
            line = next(ln for ln in received[-1].decode().splitlines()
                        if ln.startswith("perfschema_digest_statements "))
            assert int(float(line.split()[-1])) >= before + 1
        finally:
            srv.shutdown()


_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$")


class TestExpositionConformance:
    def test_new_families_are_exposition_conformant(self):
        """Parse render_text back: every line is a comment or a valid
        sample, the digest/heat families carry correct TYPE
        declarations, and their values agree with the live registry."""
        store, _s = _workload_store()
        body = metrics.render_text()
        types: dict[str, str] = {}
        samples: dict[str, float] = {}
        for line in body.splitlines():
            if line.startswith("# TYPE "):
                _h, _t, name, kind = line.split(" ")
                assert _NAME_RE.fullmatch(name), name
                assert kind in ("counter", "gauge", "histogram"), line
                types[name] = kind
                continue
            if not line or line.startswith("#"):
                continue
            assert _SAMPLE_RE.match(line), f"malformed sample: {line!r}"
            name_part, value = line.rsplit(" ", 1)
            if "{" not in name_part:
                samples[name_part] = float(value)
        for fam, kind in DIGEST_HEAT_FAMILIES.items():
            assert types.get(fam) == kind, \
                f"{fam}: TYPE {types.get(fam)} != {kind}"
            assert fam in samples, f"{fam}: no sample line"
        # registry agreement for the flat (exact) counters
        assert samples["copr_region_heat_read_rows"] == \
            metrics.counter("copr.region_heat.read_rows").value > 0
        assert samples["perfschema_digest_statements"] == \
            metrics.counter("perfschema.digest_statements").value > 0
        # the decayed-window gauges refresh on snapshot: a fresh read
        # must agree with what the store's heat reports now
        snap = store.rpc.region_heat.snapshot()
        assert metrics.gauge("copr.region_heat.regions").value == len(snap)
        assert metrics.gauge("copr.region_heat.top_region").value == \
            snap[0]["region_id"]


class TestScrapeVsRotationRace:
    def test_concurrent_scrape_vs_digest_rotation_and_flush_failpoint(self):
        """Diagnostics-tier coverage: concurrent /metrics scrapes racing
        digest-window rotations under the summary/flush failpoint —
        every scrape parses as well-formed exposition (never torn
        mid-write) and the counters it reports stay MONOTONIC scrape
        over scrape, even while injected flush faults defer rotations."""
        from tidb_tpu import failpoint, perfschema

        store, _s = _workload_store()
        ds = perfschema.perf_for(store).digest_summary
        with ds.lock:
            saved_interval = ds.refresh_interval_s
            # sub-second so the writer forces MANY rotations (the public
            # setter clamps to >= 1 s; the race wants rotation pressure)
            ds.refresh_interval_s = 0.005
        failpoint.enable("summary/flush", when=("prob", 0.5), seed=7)
        stop = threading.Event()
        errs: list = []
        scrapes = {"n": 0}
        watch = ("perfschema_digest_statements",
                 "perfschema_digest_windows_flushed",
                 "copr_region_heat_read_rows")

        def writer():
            try:
                ss = Session(store)
                ss.execute("use m")
                i = 0
                while not stop.is_set():
                    ss.execute(f"select v from t where id = {1 + i % 40}")
                    i += 1
            except Exception as e:   # surfaced by the join assert
                errs.append(("writer", e))

        def scraper():
            last = {name: -1.0 for name in watch}
            try:
                while not stop.is_set():
                    samples = {}
                    for line in metrics.render_text().splitlines():
                        if line.startswith("#"):
                            assert line.startswith("# TYPE "), line
                            continue
                        assert _SAMPLE_RE.match(line), \
                            f"torn sample: {line!r}"
                        name_part, value = line.rsplit(" ", 1)
                        if "{" not in name_part:
                            samples[name_part] = float(value)
                    for name in watch:
                        v = samples.get(name, 0.0)
                        assert v >= last[name], \
                            f"{name} went backwards: {last[name]} -> {v}"
                        last[name] = v
                    scrapes["n"] += 1
            except Exception as e:
                errs.append(("scraper", e))

        flushed0 = metrics.counter(
            "perfschema.digest_windows_flushed").value
        threads = [threading.Thread(target=writer) for _ in range(2)] + \
                  [threading.Thread(target=scraper) for _ in range(2)]
        try:
            for t in threads:
                t.start()
            time.sleep(1.0)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
            failpoint.disable("summary/flush")
            ds.set_refresh_interval(saved_interval)
        assert not errs, errs[:3]
        assert scrapes["n"] >= 5, "scrapers starved"
        # the race was real: rotations happened AND injected flush
        # faults deferred some (deferral never drops a count — the
        # monotonic watch above proves it)
        assert metrics.counter(
            "perfschema.digest_windows_flushed").value > flushed0
        assert metrics.counter(
            "perfschema.digest_flush_errors").value >= 0
