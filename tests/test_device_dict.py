"""Differential suite for the device dictionary execution tier
(copr.dictionary): cluster-wide versioned string dictionaries + composite
key-tuple codes for string / multi-key equi-joins.

Every regime is judged against the kill-switch oracle (SET GLOBAL
tidb_tpu_device_dict = 0 pins the row-at-a-time dict path) row-for-row,
including emission order. Covered edges: the collation matrix (binary
rides, *_ci bails counted), NULL keys on both sides under INNER and LEFT
OUTER, the high-NDV ratio bail (tidb_tpu_dict_max_ndv), dictionary
version churn mid-workload (commits extending the append-only global
dictionaries between scans), the device/dict_remap failpoint degrading
to the dict path with unchanged answers under a seeded chaos schedule,
join→TopN by dictionary rank, DISTINCT over code planes, the micro-batch
scalar-aggregate slot kind (PR 9 residual a), and the pre-decoded delta
plane cache (PR 13 residual b).
"""

from __future__ import annotations

import itertools
import threading

import pytest

from tidb_tpu import failpoint, metrics, tablecodec as tc
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 240

JOIN_QUERIES = [
    # composite (varchar, varchar) key, inner + outer
    "select count(*), sum(v), min(dv), max(dv) from t "
    "join dim on f = df and g = dg",
    "select count(*), sum(v), sum(dv) from t "
    "left join dim on f = df and g = dg",
    # single string key
    "select count(*), sum(v) from t join dim on f = df",
    # mixed string + int composite key
    "select count(*), max(dv) from t join dim on f = df and v = dv",
    # string group-by over the join (codes through fused_agg)
    "select f, count(*), sum(v) from t join dim on f = df and g = dg "
    "group by f",
    # join→TopN ordered by dictionary rank (string primary key, desc
    # numeric tiebreak) — no row materialization on the device path
    "select f, g, v from t join dim on f = df and g = dg "
    "order by f, v desc limit 9",
    "select f, v from t join dim on f = df and g = dg "
    "order by f desc, v limit 7",
    # DISTINCT over the join's code planes
    "select distinct f, g from t join dim on f = df and g = dg",
]


def _c(name: str) -> int:
    return metrics.counter(name).value


def _build(n_regions: int = 4, ci: bool = False):
    store = new_store(f"cluster://3/devdict{next(_id)}")
    s = Session(store)
    s.execute("create database dd")
    s.execute("use dd")
    coll = " collate utf8_general_ci" if ci else ""
    s.execute(f"create table t (id bigint primary key, "
              f"f varchar(8){coll}, g varchar(8){coll}, v bigint)")
    s.execute(f"create table dim (k bigint primary key, "
              f"df varchar(8){coll}, dg varchar(8){coll}, dv bigint)")
    flags = ("AA", "NN", "RR", "QQ")
    stats = ("F", "O")
    rows = ", ".join(
        f"({i}, '{flags[i % 4]}', '{stats[i % 2]}', {i * 3})"
        if i % 9 else f"({i}, null, '{stats[i % 2]}', {i * 3})"
        for i in range(1, N_ROWS + 1))
    s.execute(f"insert into t values {rows}")
    drows = ", ".join(
        f"({i}, '{f}', '{st}', {i * 7})"
        for i, (f, st) in enumerate(
            (f, st) for f in flags + ("ZZ",) for st in stats))
    s.execute(f"insert into dim values {drows}")
    if n_regions > 1:
        tid = s.info_schema().table_by_name("dd", "t").info.id
        step = N_ROWS // n_regions
        store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _all(s) -> list:
    return [s.execute(q)[0].values() for q in JOIN_QUERIES]


def _oracle(s) -> list:
    s.execute("set global tidb_tpu_device_dict = 0")
    try:
        return _all(s)
    finally:
        s.execute("set global tidb_tpu_device_dict = 1")


def test_dict_join_parity_all_shapes():
    """Every covered join shape — composite/single/mixed keys, outer
    pads, NULL keys, group-by, TopN-by-rank, DISTINCT — must match the
    dict path row-for-row INCLUDING order, and must actually ride the
    tier (join-keys counter moves, zero degraded_dict)."""
    s = _build()
    jk0, dd0 = _c("copr.dict.join_keys"), _c("copr.degraded_dict")
    got = _all(s)
    assert _c("copr.dict.join_keys") - jk0 >= len(JOIN_QUERIES), \
        "joins did not route through composite key-tuple codes"
    assert _c("copr.degraded_dict") == dd0
    want = _oracle(s)
    for q, a, b in zip(JOIN_QUERIES, got, want):
        assert a == b, f"parity vs dict path: {q}"


def test_device_route_builds_keys_on_device():
    """At floor 0 the composite codes build through the device remap
    kernel (one dispatch per side, no readback) and the probe runs the
    device build/probe kernels — answers unchanged."""
    s = _build()
    s.execute("set global tidb_tpu_dispatch_floor = 0")
    dr0 = _c("copr.dict.device_remaps")
    got = _all(s)
    assert _c("copr.dict.device_remaps") - dr0 >= 2, \
        "device remap kernel never dispatched at floor 0"
    assert got == _oracle(s)


def test_ci_collation_bails_counted():
    """The collation matrix: *_ci keys bail to the dict path (its codec
    keys carry the casefold), counted on copr.degraded_dict — answers
    are the dict path's by construction."""
    s = _build(ci=True)
    dd0 = _c("copr.degraded_dict")
    jk0 = _c("copr.dict.join_keys")
    got = s.execute(JOIN_QUERIES[0])[0].values()
    assert _c("copr.degraded_dict") > dd0
    assert _c("copr.dict.join_keys") == jk0
    assert got == _oracle_one(s, JOIN_QUERIES[0])
    # and ci values actually merge case-insensitively (the semantics the
    # tier must NOT break by taking these joins)
    s.execute("insert into t values (9001, 'aa', 'f', 1)")
    a = s.execute("select count(*) from t join dim on f = df")[0].values()
    assert a == _oracle_one(s, "select count(*) from t join dim "
                               "on f = df")


def _oracle_one(s, q):
    s.execute("set global tidb_tpu_device_dict = 0")
    try:
        return s.execute(q)[0].values()
    finally:
        s.execute("set global tidb_tpu_device_dict = 1")


def test_high_ndv_bails_counted():
    """A string key whose distinct/rows ratio exceeds
    tidb_tpu_dict_max_ndv bails to the dict path, counted — and the
    registry refuses the column (rejected_ndv)."""
    s = _build()
    # every row a distinct key value, far above any sane ratio
    s.execute("create table hn (id bigint primary key, u varchar(16))")
    s.execute("create table hd (id bigint primary key, du varchar(16))")
    rows = ", ".join(f"({i}, 'u{i:05d}')" for i in range(1, 201))
    s.execute(f"insert into hn values {rows}")
    s.execute(f"insert into hd values {rows.replace('u', 'x')}")
    s.execute("set global tidb_tpu_dict_max_ndv = 0.01")
    try:
        dd0 = _c("copr.degraded_dict")
        q = "select count(*) from hn join hd on u = du"
        got = s.execute(q)[0].values()
        assert _c("copr.degraded_dict") > dd0, "high NDV not counted"
        assert got == _oracle_one(s, q)
    finally:
        s.execute("set global tidb_tpu_dict_max_ndv = 0.5")


def test_dictionary_version_churn_extends_append_only():
    """Commits that add new strings EXTEND the global dictionaries
    (append-only codes — delta entries counted) instead of invalidating;
    repeat joins stay exact across the churn."""
    from tidb_tpu.copr.dictionary import registry_for
    s = _build()
    got = _all(s)
    assert got == _oracle(s)
    reg = registry_for(s.store)
    assert reg is not None and len(reg) > 0, "nothing registered"
    tid = s.info_schema().table_by_name("dd", "t").info.id
    fcol = next(c for c in s.info_schema()
                .table_by_name("dd", "t").info.columns if c.name == "f")
    gd = reg.get(tid, fcol.id)
    assert gd is not None
    base_len = len(gd)
    de0 = _c("copr.dict.delta_entries")
    for i in range(3):
        s.execute(f"insert into t values ({9100 + i}, 'WW{i}', 'F', 1)")
        got = _all(s)
        assert got == _oracle(s), f"churn round {i} diverged"
    gd2 = reg.get(tid, fcol.id)
    assert gd2 is gd, "churn rebuilt the dictionary instead of extending"
    assert len(gd2) >= base_len + 3
    assert gd2.entries[:base_len] == gd.entries[:base_len]
    assert _c("copr.dict.delta_entries") - de0 >= 3


def test_dict_remap_failpoint_degrades_with_chaos():
    """device/dict_remap prob-failpoint under concurrent fan-out readers
    at floor 0: every fault degrades to the dict path with unchanged
    answers, counted on copr.degraded_dict."""
    s = _build()
    s.execute("set global tidb_tpu_dispatch_floor = 0")
    want = _oracle(s)
    dd0 = _c("copr.degraded_dict")
    failpoint.enable("device/dict_remap", when=("prob", 0.5), seed=7)
    try:
        errs: list = []

        def reader(seed: int):
            try:
                sess = Session(s.store)
                sess.execute("use dd")
                for q, w in zip(JOIN_QUERIES, want):
                    got = sess.execute(q)[0].values()
                    if got != w:
                        errs.append((q, got, w))
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)

        ts = [threading.Thread(target=reader, args=(i,)) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs[:2]
    finally:
        failpoint.disable("device/dict_remap")
    assert _c("copr.degraded_dict") > dd0, \
        "chaos schedule never fired the remap failpoint"
    # clean after disable
    assert _all(s) == want


def test_kill_switch_is_global_only_and_persisted():
    s = _build(n_regions=1)
    with pytest.raises(Exception):
        s.execute("set tidb_tpu_device_dict = 0")
    s.execute("set global tidb_tpu_device_dict = 0")
    assert s.execute("select @@tidb_tpu_device_dict")[0].values() \
        in ([["0"]], [[b"0"]], [[0]])
    jk0 = _c("copr.dict.join_keys")
    s.execute(JOIN_QUERIES[0])
    assert _c("copr.dict.join_keys") == jk0, "kill switch ignored"
    s.execute("set global tidb_tpu_device_dict = 1")
    with pytest.raises(Exception):
        s.execute("set global tidb_tpu_dict_max_ndv = 7")


def test_topn_and_distinct_plane_counters_and_null_order():
    """The plane TopN keeps MySQL NULL ordering (asc → first, desc →
    last) and the stable scan-position tiebreak; DISTINCT treats NULL as
    one value. Both counted."""
    s = _build()
    tp0, dp0 = _c("copr.dict.topn_plane"), _c("copr.dict.distinct_plane")
    qs = [
        "select f, v from t join dim on g = dg order by f limit 12",
        "select f, v from t join dim on g = dg order by f desc limit 12",
        "select distinct f from t join dim on g = dg",
    ]
    got = [s.execute(q)[0].values() for q in qs]
    assert _c("copr.dict.topn_plane") - tp0 >= 2
    assert _c("copr.dict.distinct_plane") - dp0 >= 1
    s.execute("set global tidb_tpu_device_dict = 0")
    try:
        want = [s.execute(q)[0].values() for q in qs]
    finally:
        s.execute("set global tidb_tpu_device_dict = 1")
    for q, a, b in zip(qs, got, want):
        assert a == b, f"plane TopN/DISTINCT parity: {q}"


def test_micro_batch_agg_slot_kind_parity():
    """PR 9 residual a: concurrent below-floor SCALAR aggregates batch
    as per-slot masked reductions — answers identical to the solo (kill
    switch) route, counted on sched.batched_agg_statements."""
    from tidb_tpu.ops.client import TpuClient
    store = new_store(f"memory://devdictagg{next(_id)}")
    s = Session(store)
    s.execute("create database ba")
    s.execute("use ba")
    s.execute("create table t (id bigint primary key, k bigint, "
              "v bigint, f varchar(4), d decimal(10,2), x double)")
    rows = ", ".join(
        f"({i}, {i % 7}, {i * 3}, '{'ANRQ'[i % 4]}', {i % 50}.25, "
        f"{i % 11}.5)" for i in range(1, 1201))
    s.execute(f"insert into t values {rows}")
    store.set_client(TpuClient(store))
    s.execute("set global tidb_tpu_batch_window_ms = 30")
    sqls = [
        "select count(*), sum(v), min(v), max(v) from t where k < 5",
        "select count(*), sum(d), min(d), max(d) from t where k < 5",
        "select min(f), max(f), count(f) from t where k < 5",
        "select avg(v), min(x), max(x) from t where k < 5",
        "select count(*) from t where k > 99",    # empty result set
    ]
    for q in sqls:
        s.execute(q)        # warm: pack + cache the batches

    def run_all():
        out = {}

        def w(i, sql):
            sess = Session(store)
            sess.execute("use ba")
            out[i] = tuple(map(tuple, sess.execute(sql)[0].values()))

        ts = [threading.Thread(target=w, args=(i, sql))
              for i, sql in enumerate(sqls * 2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return out

    b0 = _c("sched.batched_agg_statements")
    got = run_all()
    assert _c("sched.batched_agg_statements") - b0 > 0, \
        "scalar aggregates never rode the batched slot kind"
    s.execute("set global tidb_tpu_micro_batch = 0")
    try:
        want = run_all()
    finally:
        s.execute("set global tidb_tpu_micro_batch = 1")
    assert got == want


def test_delta_decode_reuse_counter():
    """PR 13 residual b: repeat merges over an unchanged delta pack
    generation reuse the pre-decoded appended-row planes instead of
    re-decoding per scan. The cache/no_admit failpoint keeps the merged
    batch out of the plane cache, so every scan at the current version
    re-merges the same generation — the second one must reuse."""
    s = _build(n_regions=2)
    q = "select count(*), sum(v) from t where v >= 0"
    s.execute(q)                                 # cache base planes
    s.execute("insert into t values (9500, 'AA', 'F', 42)")  # delta
    failpoint.enable("cache/no_admit", action="return", value=True)
    try:
        m0 = _c("copr.delta.merges")
        first = s.execute(q)[0].values()         # merge #1: decodes
        assert _c("copr.delta.merges") > m0
        r0 = _c("copr.delta.decode_reuse")
        again = s.execute(q)[0].values()         # merge #2: reuses
        assert again == first
        assert _c("copr.delta.decode_reuse") > r0, \
            "repeat merge re-decoded an unchanged pack generation"
    finally:
        failpoint.disable("cache/no_admit")


def test_device_remap_route_skips_host_key_planes():
    """PR 14 residual b: when the device remap route takes the join, the
    host composite key planes are never built — copr.dictionary
    .host_keys runs only for the below-floor route (or a device bail /
    out-of-core rung that actually partitions on host planes)."""
    from tidb_tpu.copr import dictionary as dict_mod
    s = _build()
    calls = []
    orig = dict_mod.host_keys

    def spy(specs, n):
        calls.append(n)
        return orig(specs, n)

    dict_mod.host_keys = spy
    try:
        s.execute("set global tidb_tpu_dispatch_floor = 0")
        got = s.execute(JOIN_QUERIES[0])[0].values()
        assert not calls, \
            f"device remap route still built host key planes ({calls})"
        # the below-floor route must still build them (the numpy
        # sort-merge joins on the host planes)
        s.execute("set global tidb_tpu_dispatch_floor = 1000000")
        below = s.execute(JOIN_QUERIES[0])[0].values()
        assert calls, "below-floor route never built host key planes"
        assert got == below
    finally:
        dict_mod.host_keys = orig
        s.execute("set global tidb_tpu_dispatch_floor = 16384")


def test_batched_gather_emit_matches_per_cell():
    """PR 14 residual c: the batched plane-gather emit (gather_datums /
    _gather_rows) must produce datums IDENTICAL to the per-cell
    datum_at protocol on every side shape — join output over row sides
    with LEFT OUTER pads, a real packed ColumnarScanResult (string
    dictionary, floats, NULLs), and the projected view."""
    import numpy as np

    from tidb_tpu.executor.executors import _ProjectedView, _gather_rows
    from tidb_tpu.ops import columnar as col_mod
    from tidb_tpu.types import Datum

    lrows = [[Datum.i64(i), Datum.bytes_(b"x%d" % (i % 3)),
              Datum.f64(i + 0.5)] for i in range(6)]
    lrows[3][1] = Datum.null() if hasattr(Datum, "null") else lrows[3][1]
    rrows = [[Datum.i64(10 + i), Datum.bytes_(b"y%d" % i)]
             for i in range(4)]
    l_idx = np.arange(6, dtype=np.int64)
    r_idx = np.array([0, -1, 2, 3, -1, 1], dtype=np.int64)
    res = col_mod.DeviceJoinResult(
        col_mod.RowsSide(lrows), col_mod.RowsSide(rrows),
        l_idx, r_idx, 3, 2)
    idx = [4, 0, 2, 5, 1]
    for j in range(5):
        got = res.gather_datums(j, idx)
        want = [res.datum_at(j, i) for i in idx]
        assert got == want, f"join gather_datums diverged on column {j}"
    rows = _gather_rows(res, np.asarray(idx), 5)
    assert rows == [[res.datum_at(j, i) for j in range(5)] for i in idx]
    # a real packed batch behind a ColumnarScanResult: drive one scan
    # through the device engine and rebuild the scan payload
    s = _build(n_regions=1)
    from tidb_tpu.ops import TpuClient
    store = s.store
    old = store.get_client()
    client = TpuClient(store, dispatch_floor_rows=0)
    store.set_client(client)
    try:
        s.execute("select count(*) from t where v >= 0")
        batch, cols = client._cur_batch, list(client._cur_cols)
        scan = col_mod.ColumnarScanResult(
            batch, np.arange(batch.n_rows, dtype=np.int64), cols)
        pick = [5, 0, 8, 3, 8]
        for j in range(len(cols)):
            got = scan.gather_datums(j, pick)
            want = [scan.datum_at(j, i) for i in pick]
            assert got == want, f"scan gather_datums diverged on col {j}"
        view = _ProjectedView(scan, [len(cols) - 1, 0])
        for j in range(2):
            assert view.gather_datums(j, pick) == \
                [view.datum_at(j, i) for i in pick]
    finally:
        store.set_client(old)
