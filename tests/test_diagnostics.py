"""Cluster diagnostics tier: queryable metrics time series, the
slow-statement flight recorder, the device-utilization profiler, and the
automatic inspection rules.

Four surfaces, each driven through real workload (and, for the rules,
the failpoint chaos schedule that produces its pathology):

  1. information_schema.TIDB_TPU_METRICS / TIDB_TPU_METRICS_HISTORY —
     `SELECT` over current values and time-bucketed samples with
     delta/rate, covering the copr/sched/pool/cache/mesh families.
  2. TIDB_TPU_SLOW_TRACES — a statement slowed by an injected failpoint
     lands its FULL span tree despite tidb_trace_enabled = 0; healthy
     statements retain nothing (the extended PR 4 guard lives in
     test_tracing).
  3. the profiler: device.busy_fraction from the metered dispatch lock,
     batch slot occupancy/padding waste, drain-pool queue wait and
     worker utilization, mesh shard balance, HBM pinned attribution —
     plus the quiesced-gauge fix (sched/pool queue depths report 0).
  4. each inspection rule fires under its driving chaos schedule and
     CLEARS after recovery (the window slides past the burst).
"""

from __future__ import annotations

import itertools
import json
import threading
import time

import pytest

from tidb_tpu import errors, failpoint, flight, inspection, metrics
from tidb_tpu import tablecodec as tc
from tidb_tpu.metrics import timeseries
from tidb_tpu.ops import TpuClient
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 1200
JOIN_AGG_Q = ("select count(*), sum(t.v), min(t.v), max(d.d_f) "
              "from t join d on t.k = d.d_k")


def _build(n_regions: int = 4):
    """4-region cluster store with a join-able workload (the
    test_tracing shape): fused aggregates ride the device combine, the
    fan-out rides the shared drain pool, packs ride the plane cache."""
    store = new_store(f"cluster://3/diag{next(_id)}")
    s = Session(store)
    s.execute("create database dg")
    s.execute("use dg")
    s.execute("create table t (id bigint primary key, k bigint, "
              "v bigint, f double)")
    rows = ", ".join(f"({i}, {i % 7}, {i * 10}, {i}.25)"
                     for i in range(1, N_ROWS + 1))
    s.execute(f"insert into t values {rows}")
    s.execute("create table d (d_k bigint primary key, d_f double)")
    s.execute("insert into d values " +
              ", ".join(f"({i}, {i}.5)" for i in range(7)))
    if n_regions > 1:
        tid = s.info_schema().table_by_name("dg", "t").info.id
        step = N_ROWS // n_regions
        s.store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _mk_batch_store(n_rows: int = 2500, window_ms: int = 40):
    """Local store + TpuClient with the floor raised so every statement
    is below-floor (test_concurrency_tier's micro-batch regime)."""
    store = new_store(f"memory://diagb{next(_id)}")
    s = Session(store)
    s.execute("set global tidb_slow_log_threshold = 0")
    s.execute("create database d")
    s.execute("use d")
    s.execute("create table t (id bigint primary key, v bigint)")
    s.execute("insert into t values " +
              ", ".join(f"({i}, {i % 97})" for i in range(1, n_rows + 1)))
    store.set_client(TpuClient(store, dispatch_floor_rows=1 << 20))
    client = store.get_client()
    client.batch_window_ms = window_ms
    s.execute("select id from t where v = 0")   # warm the packed batch
    return store, s, client


def _concurrent(store, sqls, setup=(), catch=()):
    """Execute sqls concurrently (one session each, barrier start);
    returns (results, caught_errors). Exceptions of types in `catch`
    are collected, anything else fails the test."""
    sessions = []
    for _q in sqls:
        ss = Session(store)
        ss.execute("use d")
        for stmt in setup:
            ss.execute(stmt)
        sessions.append(ss)
    out, caught, errs = {}, [], []
    lock = threading.Lock()
    barrier = threading.Barrier(len(sqls))

    def run(ss, q):
        try:
            barrier.wait()
            r = ss.execute(q)[0].values()
            with lock:
                out[q] = r
        except catch as e:
            with lock:
                caught.append(e)
        except Exception as e:   # surfaced by the caller's assert
            with lock:
                errs.append((q, e))
    ts = [threading.Thread(target=run, args=(ss, q))
          for ss, q in zip(sessions, sqls)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs, errs[:3]
    return out, caught


def _flush_window(n: int | None = None) -> None:
    """Push the inspection window past whatever the previous test (or
    burst) left in it: force `n` fresh samples (the recorder coalesces
    sub-ms forced samples, so space them)."""
    n = (int(inspection.threshold("window_samples")) + 2) if n is None else n
    for _ in range(n):
        timeseries.recorder.sample()
        time.sleep(0.002)


def _rows(s, sql):
    return s.execute(sql)[0].values()


def _sv(v):
    return v.decode() if isinstance(v, bytes) else v


# ---------------------------------------------------------------------------
# 1. metrics tables
# ---------------------------------------------------------------------------

class TestMetricsTables:
    def test_current_metrics_typed_and_documented(self):
        s = _build()
        s.execute(JOIN_AGG_Q)
        rows = _rows(s, "select NAME, TYPE, LABELS, METRIC_VALUE, HELP "
                        "from information_schema.TIDB_TPU_METRICS")
        by_name: dict = {}
        for name, tp, labels, val, help_ in rows:
            by_name.setdefault(_sv(name), []).append(
                (_sv(tp), _sv(labels), val, _sv(help_)))
        # counters/gauges: one row, typed, helped (catalog-documented)
        for want, wtp in (("ops.kernel_dispatches", "counter"),
                          ("copr.plane_cache.bytes", "gauge"),
                          ("copr.drain_pool.tasks", "counter")):
            assert want in by_name, f"{want} missing from TIDB_TPU_METRICS"
            tp, labels, val, help_ = by_name[want][0]
            assert tp == wtp and labels == "" and help_, (want, tp, help_)
            assert val >= 0
        # histograms expand to stat-labeled count/sum/avg rows
        hist = by_name.get("session.parse_seconds")
        assert hist is not None and len(hist) == 3
        stats = {lb for (_t, lb, _v, _h) in hist}
        assert stats == {'stat="count"', 'stat="sum"', 'stat="avg"'}
        assert all(t == "histogram" for (t, _l, _v, _h) in hist)

    def test_history_buckets_cover_all_families(self):
        """The acceptance criterion: SELECT over TIDB_TPU_METRICS_HISTORY
        returns time-bucketed samples for the copr / sched / pool /
        cache / mesh families, with sane delta/rate."""
        s = _build()
        # sched family needs the micro-batch tier engaged (process-wide
        # registry, so any store's traffic lands in the same history)
        bstore, _bs, _bc = _mk_batch_store()
        sqls = [f"select id from t where v = {k}" for k in (3, 11, 42, 7)]
        _concurrent(bstore, sqls)
        base = metrics.counter("ops.kernel_dispatches").value
        timeseries.recorder.sample()
        time.sleep(0.002)
        for _ in range(3):
            s.execute(JOIN_AGG_Q)           # copr/pool/cache/mesh traffic
            timeseries.recorder.sample()
            time.sleep(0.002)
        rows = _rows(s, "select TS, NAME, TYPE, METRIC_VALUE, DELTA, "
                        "RATE_PER_SEC from "
                        "information_schema.TIDB_TPU_METRICS_HISTORY")
        by_family: dict = {}
        ts_per_name: dict = {}
        for ts_, name, tp, val, delta, rate in rows:
            name = _sv(name)
            by_family.setdefault(name.split(".")[0], set()).add(name)
            ts_per_name.setdefault(name, []).append((ts_, val, delta, rate))
        names = set(ts_per_name)
        for fam_name in ("copr.plane_cache.hits",
                         "copr.plane_cache.misses",
                         "copr.drain_pool.tasks",
                         "copr.drain_pool.queue_wait_seconds_count",
                         "copr.mesh.shard_skew",
                         "sched.batched_dispatches",
                         "sched.slot_occupancy_count",
                         "ops.kernel_dispatches",
                         "device.busy_us"):
            assert fam_name in names, \
                f"{fam_name} missing from METRICS_HISTORY ({sorted(by_family)})"
        # time-bucketed: multiple distinct TS per series
        kd = ts_per_name["ops.kernel_dispatches"]
        assert len({t for (t, _v, _d, _r) in kd}) >= 3
        # deltas reconcile with the counter's true growth across the
        # window, and rates are non-negative for monotonic series
        total_delta = sum(d for (_t, _v, d, _r) in kd if d is not None)
        assert total_delta == kd[-1][1] - kd[0][1]
        assert kd[-1][1] >= base
        assert all(r >= 0 for (_t, _v, _d, r) in kd if r is not None)

    def test_history_ring_bounded_by_cap(self):
        s = _build(1)
        s.execute("set global tidb_tpu_metrics_history_cap = 5")
        try:
            for _ in range(12):
                timeseries.recorder.sample()
                time.sleep(0.002)
            assert timeseries.recorder.cap == 5
            rows = _rows(s, "select TS from "
                            "information_schema.TIDB_TPU_METRICS_HISTORY")
            assert 2 <= len({r[0] for r in rows}) <= 5
        finally:
            s.execute("set global tidb_tpu_metrics_history_cap = 240")

    def test_interval_sysvar_validated(self):
        s = _build(1)
        with pytest.raises(errors.ExecError):
            s.execute("set global tidb_tpu_metrics_interval_ms = 'x'")
        with pytest.raises(errors.ExecError):
            s.execute("set tidb_tpu_metrics_interval_ms = 50")  # GLOBAL-only
        s.execute("set global tidb_tpu_metrics_interval_ms = 50")
        try:
            assert timeseries.recorder.interval_s == 0.05
        finally:
            s.execute("set global tidb_tpu_metrics_interval_ms = 1000")


# ---------------------------------------------------------------------------
# 2. flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_slow_statement_retained_despite_tracing_off(self):
        """THE acceptance case: a statement slowed by an injected
        failpoint appears in TIDB_TPU_SLOW_TRACES with its full span
        tree, even though tidb_trace_enabled = 0 the whole time."""
        s = _build()
        assert not s._tracing_enabled()
        flight.recorder_for(s.store).clear()
        s.execute("set tidb_slow_log_threshold = 30")
        failpoint.enable("copr/region_scan", action="sleep", seconds=0.02)
        try:
            want = _rows(s, JOIN_AGG_Q)
        finally:
            failpoint.disable("copr/region_scan")
        rows = _rows(s, "select REASON, DURATION_MS, SPAN_COUNT, CONN_ID,"
                        " DIGEST, SQL_TEXT, TRACE_JSON from "
                        "information_schema.TIDB_TPU_SLOW_TRACES")
        assert rows, "slowed statement was not retained"
        reason, dur, spans, conn, dig, sql, tj = rows[-1]
        assert _sv(reason) == "slow"
        assert dur >= 30
        assert conn == s.vars.connection_id
        assert _sv(dig)                     # joins to the digest summary
        assert "from t join d" in _sv(sql)
        doc = json.loads(_sv(tj))
        assert doc["name"] == "statement"
        names = [sp["name"] for sp in _walk(doc)]
        # the FULL hierarchy: per-region copr tasks under the statement
        assert names.count("region_task") >= 4, names
        assert "copr" in names
        assert spans == len(names) >= 6
        # answers unchanged by the recording
        assert want == _rows(s, JOIN_AGG_Q)

    def test_deadline_death_retained_with_error(self):
        s = _build()
        flight.recorder_for(s.store).clear()
        s.execute("set tidb_tpu_max_execution_time = 150")
        failpoint.enable("copr/region_scan", action="hang")
        try:
            with pytest.raises(errors.DeadlineExceededError):
                s.execute(JOIN_AGG_Q)
        finally:
            failpoint.disable("copr/region_scan")
            s.execute("set tidb_tpu_max_execution_time = 0")
        rows = _rows(s, "select REASON, ERROR from "
                        "information_schema.TIDB_TPU_SLOW_TRACES")
        assert rows
        reason, err = rows[-1]
        assert _sv(reason) == "deadline"
        assert "deadline" in _sv(err).lower() or _sv(err)

    def test_degraded_statement_retained(self):
        """A statement that fell through a tier is diagnostics-worthy
        even when it stayed fast: the mesh-collective fault degrades the
        combine and the trace is kept under its degraded_* reason."""
        s = _build()
        s.execute(JOIN_AGG_Q)                    # warm (jit compile)
        flight.recorder_for(s.store).clear()
        s.execute("set tidb_slow_log_threshold = 0")   # isolate the reason
        failpoint.enable("device/mesh_collective")
        try:
            got = _rows(s, JOIN_AGG_Q)
        finally:
            failpoint.disable("device/mesh_collective")
        assert got == _rows(s, JOIN_AGG_Q)       # answers unchanged
        rows = _rows(s, "select REASON, KERNEL_DISPATCHES from "
                        "information_schema.TIDB_TPU_SLOW_TRACES")
        assert rows, "degraded statement was not retained"
        assert _sv(rows[-1][0]).startswith("degraded_")

    def test_ring_bounded_and_kill_switch_clears(self):
        s = _build(1)
        fr = flight.recorder_for(s.store)
        fr.clear()
        s.execute("set global tidb_tpu_slow_trace_cap = 3")
        s.execute("set tidb_slow_log_threshold = 1")
        try:
            for i in range(5):
                s.execute(f"select count(*) from t where v > {i}")
            entries = fr.entries()
            assert len(entries) == 3, "ring not bounded at the cap"
            # oldest dropped, newest kept
            assert "v > 4" in entries[-1]["sql"]
            s.execute("set global tidb_tpu_flight_recorder = 0")
            assert len(fr) == 0, "kill switch must clear the ring"
            s.execute("select count(*) from t where v > 99")
            assert len(fr) == 0, "disabled recorder retained a trace"
        finally:
            s.execute("set global tidb_tpu_flight_recorder = 1")
            s.execute("set global tidb_tpu_slow_trace_cap = 64")
        # re-enabled: retention works again
        s.execute("select count(*) from t where v > 5")
        assert len(fr) >= 1

    def test_global_only_sysvars(self):
        s = _build(1)
        for name in ("tidb_tpu_flight_recorder", "tidb_tpu_slow_trace_cap"):
            with pytest.raises(errors.ExecError):
                s.execute(f"set {name} = 1")


def _walk(doc):
    yield doc
    for c in doc.get("children", ()):
        yield from _walk(c)


# ---------------------------------------------------------------------------
# 3. device-utilization profiler
# ---------------------------------------------------------------------------

class TestProfiler:
    def test_device_busy_fraction_meters_dispatches(self):
        s = _build()
        s.execute(JOIN_AGG_Q)     # warm: compile outside the window
        busy0 = metrics.counter("device.busy_us").value
        timeseries.recorder.sample()
        time.sleep(0.002)
        for _ in range(3):
            s.execute(JOIN_AGG_Q)
        timeseries.recorder.sample()
        assert metrics.counter("device.busy_us").value > busy0, \
            "device dispatches did not meter busy time"
        frac = metrics.gauge("device.busy_fraction").value
        assert 0 < frac <= 1.0, frac

    def test_drain_pool_wait_and_utilization(self):
        s = _build()
        h = metrics.histogram("copr.drain_pool.queue_wait_seconds")
        c0 = h.count
        timeseries.recorder.sample()
        time.sleep(0.002)
        for _ in range(2):
            s.execute(JOIN_AGG_Q)     # 4-region fan-out rides the pool
        timeseries.recorder.sample()
        assert h.count > c0, "fan-out drains did not observe queue wait"
        assert metrics.histogram("copr.drain_pool.task_seconds").count > 0
        util = metrics.gauge("copr.drain_pool.worker_utilization").value
        assert 0 <= util <= 1.0
        assert metrics.gauge("copr.drain_pool.size").value >= 1

    def test_batch_slot_occupancy_and_quiesced_gauges(self):
        """Occupancy/padding histograms from the shared dispatch, and
        the satellite fix: after the burst drains, sched.queue_depth
        AND copr.drain_pool.queue_depth report 0 (quiesced server),
        including after follower-stall removals."""
        store, s, client = _mk_batch_store()
        occ = metrics.histogram("sched.slot_occupancy")
        pad = metrics.histogram("sched.padding_waste")
        o0, p0 = occ.count, pad.count
        sqls = [f"select id from t where v = {k}"
                for k in (3, 11, 42, 77, 90, 96)]
        _concurrent(store, sqls)
        assert occ.count > o0 and pad.count > p0
        # occupancy of a 6-statement burst in an 8-slot bucket
        _b, _c, osum, ocnt = occ.snapshot_buckets()
        assert 0 < osum / ocnt <= 1.0
        q50 = metrics.quantile(occ, 0.5)
        assert 0 < q50 <= 1.0
        assert metrics.gauge("sched.queue_depth").value == 0, \
            "quiesced micro-batcher reports a stale queue depth"
        # follower-stall path: a stalled window self-removes entries —
        # the gauge must still come back to 0
        failpoint.enable("sched/batch_window", action="sleep",
                         seconds=0.6)
        try:
            d0 = metrics.counter("copr.degraded_batch").value
            _concurrent(store, sqls[:3])
            assert metrics.counter("copr.degraded_batch").value > d0
        finally:
            failpoint.disable("sched/batch_window")
        assert metrics.gauge("sched.queue_depth").value == 0, \
            "stall-path removals left a stale sched.queue_depth"
        assert metrics.gauge("copr.drain_pool.queue_depth").value == 0, \
            "quiesced drain pool reports a stale queue depth"

    def test_mesh_shard_balance_gauges(self):
        s = _build()
        d0 = metrics.counter("copr.mesh.dispatches").value
        s.execute(JOIN_AGG_Q)     # mesh combine (8 forced host shards
        #                           under tier-1's XLA_FLAGS)
        assert metrics.counter("copr.mesh.dispatches").value > d0
        mx = metrics.gauge("copr.mesh.shard_rows_max").value
        mean = metrics.gauge("copr.mesh.shard_rows_mean").value
        skew = metrics.gauge("copr.mesh.shard_skew").value
        assert mx > 0 and mean > 0 and mx >= mean
        assert skew >= 1.0 and skew == pytest.approx(mx / mean, rel=1e-3)
        # the publisher computes skew correctly for imbalanced layouts
        from tidb_tpu.ops import mesh as mesh_mod
        mesh_mod.publish_shard_balance([4000, 500, 500, 1000])
        assert metrics.gauge("copr.mesh.shard_skew").value == \
            pytest.approx(4000 / 1500, rel=1e-3)
        mesh_mod.publish_shard_balance([mx])   # restore sane state

    def test_plane_cache_hbm_attribution(self):
        s = _build()
        s.execute(JOIN_AGG_Q)     # cold: packs + pins
        s.execute(JOIN_AGG_Q)     # warm: hits
        assert metrics.counter("copr.plane_cache.hits").value > 0
        pinned = metrics.gauge("copr.plane_cache.bytes_pinned").value
        top_b = metrics.gauge("copr.plane_cache.top_pinned_bytes").value
        top_t = metrics.gauge("copr.plane_cache.top_pinned_table").value
        assert pinned > 0 and top_b > 0
        assert top_b <= pinned
        tid = s.info_schema().table_by_name("dg", "t").info.id
        pc = s.store.rpc.plane_cache
        by_table = pc.pinned_by_table()
        assert by_table.get(tid, 0) > 0
        assert top_t in by_table


# ---------------------------------------------------------------------------
# 4. inspection rules — fire under chaos, clear after recovery
# ---------------------------------------------------------------------------

def _findings(s) -> list[tuple]:
    return [(_sv(r[0]), _sv(r[1]), _sv(r[2]))
            for r in _rows(s, "select RULE, ITEM, SEVERITY from "
                              "information_schema."
                              "TIDB_TPU_INSPECTION_RESULT")]


def _fired(s, rule: str, item: str | None = None) -> list[tuple]:
    return [f for f in _findings(s)
            if f[0] == rule and (item is None or f[1] == item)]


class TestInspectionRules:
    def test_degradation_burst_fires_and_clears(self):
        s = _build()
        s.execute(JOIN_AGG_Q)                 # warm
        _flush_window()
        assert not _fired(s, "degradation-burst")
        failpoint.enable("device/mesh_collective")
        try:
            for _ in range(int(inspection.threshold("degraded_burst")) + 1):
                s.execute(JOIN_AGG_Q)         # each degrades mesh→single
        finally:
            failpoint.disable("device/mesh_collective")
        hits = _fired(s, "degradation-burst")
        assert hits, "mesh degradation burst did not fire"
        assert any(item == "mesh" for (_r, item, _sev) in hits)
        # recovery: the window slides past the burst and the rule clears
        _flush_window()
        assert not _fired(s, "degradation-burst"), \
            "rule did not clear after recovery"

    def test_plane_cache_collapse_fires_and_clears(self):
        s = _build()
        s.execute(JOIN_AGG_Q)                 # warm + seed the cache
        _flush_window()
        failpoint.enable("cache/no_admit", action="return", value=True)
        # the HTAP delta tier would RESCUE this scenario (the commit's
        # delta merges/rekeys keep serving warm planes) — the rule's
        # pathology needs it off, like a deployment that disabled it
        s.execute("set global tidb_tpu_delta_pack = 0")
        try:
            # a commit bumps the table's data version (orphaning the
            # warm entries), and no_admit keeps every re-pack OUT of the
            # cache: 5 regions x 5 runs of pure misses, ratio 0
            s.execute("insert into t values (99991, 1, 1, 1.0)")
            for _ in range(5):
                s.execute(JOIN_AGG_Q)
            hits = _fired(s, "plane-cache-collapse", "hit-ratio")
            assert hits, "all-miss window did not fire the cache rule"
        finally:
            failpoint.disable("cache/no_admit")
            s.execute("set global tidb_tpu_delta_pack = 1")
        _flush_window()
        for _ in range(5):
            s.execute(JOIN_AGG_Q)             # warm hits dominate again
        assert not _fired(s, "plane-cache-collapse"), \
            "rule did not clear after the cache recovered"

    def test_drain_pool_saturation_fires_and_clears(self):
        from tidb_tpu.cluster.pool import get_pool, set_pool_size
        s = _build(1)
        _flush_window()
        set_pool_size(2)
        release = threading.Event()
        try:
            pool = get_pool()
            for _ in range(8):
                pool.submit(lambda: release.wait(5))
            # workers (2) busy, ≥ 2 queued → depth ≥ size
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and metrics.gauge(
                    "copr.drain_pool.queue_depth").value < 2:
                time.sleep(0.01)
            hits = _fired(s, "admission-saturation", "drain-pool")
            assert hits, "saturated drain pool did not fire"
        finally:
            release.set()
            set_pool_size(16)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and metrics.gauge(
                "copr.drain_pool.queue_depth").value > 0:
            time.sleep(0.01)
        assert not _fired(s, "admission-saturation", "drain-pool"), \
            "rule did not clear after the pool drained"

    def test_conn_queue_saturation_fires_and_clears(self):
        """The conn-queue item rides the queue-deadline counter: a
        timed-out queued connection (satellite a) is exactly the
        evidence the rule wants."""
        from tidb_tpu.server import MySQLError, Server
        from tests.test_server import connect
        s = _build(1)
        _flush_window()
        store = s.store
        s.execute("set global max_connections = 1")
        s.execute("set global tidb_tpu_conn_queue_depth = 4")
        s.execute("set global tidb_tpu_conn_queue_timeout_ms = 150")
        server = Server(store)
        server.start()
        try:
            c1 = connect(server)
            with pytest.raises(MySQLError):
                connect(server, timeout=10)   # queue-deadline death
            c1.close()
        finally:
            server.close()
        hits = _fired(s, "admission-saturation", "conn-queue")
        assert hits, "queue-deadline rejection did not fire the rule"
        _flush_window()
        assert not _fired(s, "admission-saturation", "conn-queue")

    def test_batch_expiry_spike_fires_and_clears(self):
        store, s, client = _mk_batch_store(window_ms=30)
        _flush_window()
        sqls = [f"select id from t where v = {k}"
                for k in (3, 11, 42, 77, 90)]
        failpoint.enable("sched/batch_window", action="sleep",
                         seconds=0.5)
        try:
            _ok, caught = _concurrent(
                store, sqls,
                setup=("set tidb_tpu_max_execution_time = 120",),
                catch=(errors.DeadlineExceededError,))
            assert len(caught) >= int(inspection.threshold("batch_expiries")), \
                f"only {len(caught)} deadlines expired in the window"
        finally:
            failpoint.disable("sched/batch_window")
        hits = _fired(s, "batch-expiry-spike", "gather-window")
        assert hits, "gather-window expiries did not fire the rule"
        _flush_window()
        assert not _fired(s, "batch-expiry-spike")

    def test_mesh_skew_fires_and_clears(self):
        from tidb_tpu.ops import mesh as mesh_mod
        s = _build(1)
        _flush_window()
        assert not _fired(s, "mesh-shard-skew")
        # a hot region dragging its home shard: max 8x the mean at a
        # non-trivial row count (the gauge seam the real combine feeds)
        mesh_mod.publish_shard_balance([8000, 500, 500, 1000])
        hits = _fired(s, "mesh-shard-skew", "placement")
        assert hits, "skewed shard layout did not fire"
        mesh_mod.publish_shard_balance([2000, 2000, 2000, 2000])
        assert not _fired(s, "mesh-shard-skew"), \
            "balanced layout did not clear the rule"

    def test_findings_carry_window_and_evidence(self):
        s = _build()
        s.execute(JOIN_AGG_Q)
        _flush_window()
        failpoint.enable("device/mesh_collective")
        try:
            for _ in range(int(inspection.threshold("degraded_burst")) + 1):
                s.execute(JOIN_AGG_Q)
        finally:
            failpoint.disable("device/mesh_collective")
        rows = _rows(s, "select RULE, ITEM, SEVERITY, ITEM_VALUE, "
                        "REFERENCE, DETAILS, WINDOW_BEGIN, WINDOW_END "
                        "from information_schema."
                        "TIDB_TPU_INSPECTION_RESULT")
        burst = [r for r in rows if _sv(r[0]) == "degradation-burst"
                 and _sv(r[1]) == "mesh"]
        assert burst
        _rule, _item, sev, val, ref, details, begin, end = burst[0]
        assert _sv(sev) in ("warning", "critical")
        assert int(val) >= int(inspection.threshold("degraded_burst"))
        assert "fallbacks/window" in _sv(ref)
        assert "copr.degraded_mesh" in _sv(details)
        assert 0 < begin <= end
        _flush_window()


class TestDaemonTicker:
    """Daemon-mode metrics ticker: a SERVING process accrues history
    buckets while fully idle (the PR 10 lazy-sampling residual); library
    embeds stay thread-free, and the sampler exits when the last server
    detaches."""

    def test_quiesced_server_accrues_history(self):
        from tidb_tpu.metrics.timeseries import recorder
        from tidb_tpu.server.server import Server
        store = new_store(f"memory://tick{next(_id)}")
        old_interval = recorder.interval_s
        recorder.set_interval(0.02)
        srv = Server(store, port=0)
        srv.start()
        try:
            assert timeseries.ticker_active()
            samples = recorder.samples()
            t0 = samples[-1].mono if samples else 0.0
            time.sleep(0.4)          # NO statements run anywhere
            fresh = sum(1 for smp in recorder.samples() if smp.mono > t0)
            assert fresh >= 3, \
                f"idle server accrued only {fresh} history buckets"
        finally:
            srv.close()
            recorder.set_interval(old_interval)
        # the sampler thread exits once no server remains
        deadline = time.monotonic() + 2.0
        while timeseries.ticker_active() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not timeseries.ticker_active(), \
            "ticker thread survived the last server close"

    def test_library_process_stays_lazy(self):
        """Without a wire server, no ticker is live — the zero-thread
        library contract holds."""
        assert not timeseries.ticker_active()


class TestInspectionThresholds:
    """tidb_tpu_inspection_* sysvars replace the static rule constants:
    GLOBAL-only, applied live, persisted + hydrated on bootstrap."""

    def test_set_global_applies_live_and_rule_uses_it(self):
        s = _build()
        s.execute(JOIN_AGG_Q)
        _flush_window()
        assert not _fired(s, "degradation-burst")
        try:
            # lower the burst threshold to 2: a 2-fault burst (below the
            # default 5) must now fire the rule
            s.execute("set global tidb_tpu_inspection_degraded_burst = 2")
            assert inspection.threshold("degraded_burst") == 2.0
            failpoint.enable("device/mesh_collective")
            try:
                for _ in range(2):
                    s.execute(JOIN_AGG_Q)
            finally:
                failpoint.disable("device/mesh_collective")
            assert _fired(s, "degradation-burst", "mesh"), \
                "tuned-down threshold did not fire on a 2-fault burst"
            _flush_window()
            assert not _fired(s, "degradation-burst")
        finally:
            inspection.reset_thresholds()

    def test_global_only_and_validation(self):
        s = _build(1)
        with pytest.raises(errors.TiDBError):
            s.execute("set tidb_tpu_inspection_mesh_skew = 3")
        with pytest.raises(errors.TiDBError):
            s.execute("set global tidb_tpu_inspection_mesh_skew = 'x'")
        with pytest.raises(errors.TiDBError):
            s.execute("set global tidb_tpu_inspection_mesh_skew = -1")
        assert inspection.threshold("mesh_skew") == \
            inspection.DEFAULTS["mesh_skew"]

    def test_persisted_and_hydrated_on_bootstrap(self):
        """A persisted threshold survives the in-memory cache being
        wiped: re-hydration (the restart path) reapplies it."""
        import tidb_tpu.session as sess_mod
        store = new_store(f"memory://insph{next(_id)}")
        s = Session(store)
        try:
            s.execute(
                "set global tidb_tpu_inspection_batch_expiries = 9")
            assert inspection.threshold("batch_expiries") == 9.0
            inspection.reset_thresholds()
            assert inspection.threshold("batch_expiries") == \
                inspection.DEFAULTS["batch_expiries"]
            # simulate a process restart: forget the bootstrap mark and
            # let a fresh session hydrate from mysql.global_variables
            sess_mod._BOOTSTRAPPED_STORES.discard(store.uuid())
            Session(store).execute("select 1")
            assert inspection.threshold("batch_expiries") == 9.0, \
                "persisted inspection threshold did not hydrate"
        finally:
            inspection.reset_thresholds()


# ---------------------------------------------------------------------------
# PR 13 satellites: per-entry trace truncation + the metrics label model
# ---------------------------------------------------------------------------

class TestFlightTruncation:
    def test_oversized_trace_keeps_root_and_slowest_subtrees(self):
        """tidb_tpu_slow_trace_max_spans bounds each RETAINED entry: a
        pathological fan-out keeps the root + the slowest subtrees,
        stamps truncated=true + dropped_spans in TRACE_JSON, and the
        slowest copr subtree survives the cut."""
        s = _build()
        fr = flight.recorder_for(s.store)
        fr.clear()
        s.execute("set global tidb_tpu_slow_trace_max_spans = 6")
        s.execute("set tidb_slow_log_threshold = 10")
        failpoint.enable("copr/region_scan", action="sleep", seconds=0.01)
        try:
            s.execute(JOIN_AGG_Q)
        finally:
            failpoint.disable("copr/region_scan")
            s.execute("set global tidb_tpu_slow_trace_max_spans = 512")
        rows = _rows(s, "select SPAN_COUNT, TRACE_JSON from "
                        "information_schema.TIDB_TPU_SLOW_TRACES")
        assert rows, "slowed statement was not retained"
        spans, tj = rows[-1]
        doc = json.loads(_sv(tj))
        assert doc.get("truncated") is True, \
            "oversized trace not stamped truncated"
        assert doc.get("dropped_spans", 0) > 0
        names = [sp["name"] for sp in _walk(doc)]
        assert len(names) <= 6, f"budget exceeded: {names}"
        assert spans == len(names)
        assert doc["name"] == "statement"
        # the slowest subtree (the copr fan-out) survives the cut
        assert "copr" in names, names

    def test_small_trace_untouched_and_zero_unbounded(self):
        s = _build(1)
        fr = flight.recorder_for(s.store)
        fr.clear()
        s.execute("set tidb_slow_log_threshold = 1")
        s.execute("select count(*) from t where v > 3")
        entries = fr.entries()
        assert entries
        assert "truncated" not in entries[-1]["trace"]
        # 0 = unbounded: a big tree stays whole
        s.execute("set global tidb_tpu_slow_trace_max_spans = 0")
        try:
            fr.clear()
            s.execute(JOIN_AGG_Q)
            entries = fr.entries()
            assert entries and "truncated" not in entries[-1]["trace"]
        finally:
            s.execute("set global tidb_tpu_slow_trace_max_spans = 512")

    def test_max_spans_sysvar_global_only_and_persisted(self):
        s = _build(1)
        with pytest.raises(errors.ExecError):
            s.execute("set tidb_tpu_slow_trace_max_spans = 5")
        s.execute("set global tidb_tpu_slow_trace_max_spans = 7")
        try:
            assert flight.recorder_for(s.store).max_spans == 7
            row = _rows(s, "select variable_value from "
                           "mysql.global_variables where variable_name ="
                           " 'tidb_tpu_slow_trace_max_spans'")
            assert row == [["7"]]
        finally:
            s.execute("set global tidb_tpu_slow_trace_max_spans = 512")


class TestMetricsLabels:
    def test_dynamic_families_split_into_name_and_labels(self):
        """Dynamic dotted families render as family NAME + kind LABEL in
        TIDB_TPU_METRICS, so HISTORY can aggregate across kinds."""
        s = _build()
        # produce a degraded_* family member
        failpoint.enable("device/mesh_collective")
        try:
            s.execute(JOIN_AGG_Q)
        finally:
            failpoint.disable("device/mesh_collective")
        rows = _rows(s, "select NAME, TYPE, LABELS from "
                        "information_schema.TIDB_TPU_METRICS")
        by_name: dict = {}
        for name, tp, labels in rows:
            by_name.setdefault(_sv(name), []).append((_sv(tp),
                                                      _sv(labels)))
        assert "copr.degraded" in by_name, sorted(by_name)[:40]
        kinds = {lb for (_t, lb) in by_name["copr.degraded"]}
        assert all(lb.startswith('kind="') for lb in kinds), kinds
        # exact catalog names keep full name + empty labels
        assert ("counter", "") in by_name["ops.kernel_dispatches"]
        # no raw dynamic member leaks through un-split
        assert not any(n.startswith("copr.degraded_") for n in by_name)

    def test_history_aggregates_across_kinds(self):
        """GROUP BY NAME over the labeled history sums a family's kinds
        (the satellite's acceptance shape)."""
        s = _build()
        s.execute(JOIN_AGG_Q)
        timeseries.recorder.sample()
        time.sleep(0.002)
        s.execute(JOIN_AGG_Q)
        timeseries.recorder.sample()
        rows = _rows(s, "select NAME, LABELS, METRIC_VALUE from "
                        "information_schema.TIDB_TPU_METRICS_HISTORY "
                        "where NAME = 'distsql.queries'")
        assert rows, "labeled family missing from HISTORY"
        assert all(_sv(lb).startswith('kind="') for _n, lb, _v in rows)
        agg = _rows(s, "select NAME, sum(METRIC_VALUE) from "
                       "information_schema.TIDB_TPU_METRICS_HISTORY "
                       "where NAME = 'distsql.queries' group by NAME")
        assert len(agg) == 1 and agg[0][1] > 0
