"""bench.py --smoke in tier-1: the headline benchmark's code paths (store
build, CPU baselines, device configs, mesh, join phases, join→agg fusion,
JSON emission) run at tiny CPU-safe sizes so a bench-path regression
fails here instead of surfacing at the next full BENCH round."""

from __future__ import annotations

import json
import os
import subprocess
import sys


def test_bench_smoke_emits_valid_json():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # even tinier than the --smoke defaults: this runs in tier-1
    env["BENCH_ROWS"] = "18000"
    env["BENCH_RUNS"] = "1"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, \
        f"bench --smoke failed:\n{proc.stderr[-4000:]}"
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line on stdout:\n{proc.stdout[-2000:]}"
    out = json.loads(lines[-1])
    assert out["smoke"] is True
    assert out["metric"] == "tpch_geomean_rows_per_sec_tpu"
    assert out["value"] > 0
    # the join figures the verdict parses must be present and sane
    assert out["join_rows_per_sec"] > 0
    assert out["join_speedup_vs_dict"] > 0
    assert out["join_numpy_rows_per_sec"] > 0
    assert out["join_build_ms"] >= 0
    assert out["join_probe_ms"] > 0
    assert out["join_agg_fused"] is True, \
        "join→agg e2e did not take the fused (no-materialization) path"
    assert out["join_agg_s"] > 0
    # the scan→join→agg pipeline must stay columnar end to end: planes
    # served for every scan, zero row-protocol fallbacks on the timed run
    assert out["scan_columnar"] is True, \
        "scan→join→agg e2e decoded rows (columnar_fallbacks > 0 or no hits)"
    assert out["join_e2e_rows_per_sec"] > 0
    assert out["columnar_fallbacks"] == 0
    # the per-region fan-out e2e: every region answered the columnar
    # channel and per-region partial aggregates merged device-side
    assert out["region_fanout_rows_per_sec"] > 0
    assert out["region_fanout_regions"] == 4
    assert out["columnar_partials"] >= 4
    assert out["region_fanout_fallbacks"] == 0
    assert out["region_partial_combines"] > 0
    # the repeat fan-out (plane cache) case: every region answered the
    # warm runs from its cached planes, parity-checked against the cold
    # re-pack regime and the row protocol inside the bench itself
    assert out["region_fanout_repeat_rows_per_sec"] > 0
    assert out["plane_cache_hits"] >= 4
    assert out["region_fanout_repeat_speedup_vs_cold"] > 0
    # the aggregate-pushdown regime: TPC-H-q1-shaped grouped aggregate
    # over the 4-region cluster store with partial STATES (not group
    # rows) crossing the wire, zero fallbacks, and the FINAL aggregate
    # fusing the states through the combine chain (parity vs the row
    # protocol asserted inside the bench itself)
    assert out["q1_pushdown_rows_per_sec"] > 0
    assert out["q1_pushdown_regions"] == 4
    assert out["q1_pushdown_fallbacks"] == 0
    assert out["q1_pushdown_states_partials"] >= 4
    assert out["q1_pushdown_state_fusions"] >= 1
    assert out["q1_states_bytes_vs_rows_bytes"] is not None \
        and out["q1_states_bytes_vs_rows_bytes"] > 0
    # near-data execution (PR 16): ALL regions' grouped partial states
    # compute in ONE batched segmented dispatch per statement — a
    # regression to one-dispatch-per-region fails here (the counter
    # delta is asserted inside measure_q1_pushdown too)
    assert out["q1_states_dispatches_per_stmt"] == 1, \
        (f"q1 ran {out['q1_states_dispatches_per_stmt']} states "
         "dispatches per statement — near-data batching regressed")
    # the TPC-H sweep regime (PR 18): every parser-accepted aggregate
    # shape — the REAL q1 with expression arguments, q6, min/max over
    # arithmetic, float expression args, decimal/datetime group keys —
    # stays columnar with ZERO fallbacks, expression arguments ride the
    # fused arg-plane states path, and the real-shape q1 keeps the ≤ 2
    # device-dispatches-per-statement budget (row-protocol parity for
    # every query asserted inside the bench itself)
    assert out["tpch_sweep_queries"] >= 6
    assert out["tpch_sweep_regions"] == 4
    assert out["tpch_sweep_rows_per_sec"] > 0
    assert out["tpch_sweep_fallbacks"] == 0, \
        "the TPC-H sweep fell off the columnar tier"
    assert out["tpch_sweep_arg_plane_partials"] >= 4, \
        "no expression aggregate argument rode the arg-plane path"
    assert out["q1full_fallbacks"] == 0, \
        "real-shape q1 (expression aggregate args) counted fallbacks"
    assert out["q1full_dispatches_per_stmt"] <= 2, \
        (f"real-shape q1 cost {out['q1full_dispatches_per_stmt']} device "
         "dispatches per statement — the ≤ 2 budget regressed")
    # the multi-key string-join regime: q3/q5-shaped joins on composite
    # (varchar, varchar) keys ride the dictionary tier fully columnar —
    # zero fallbacks, the device remap kernel built the key-tuple codes,
    # and join→TopN ordered by dictionary rank (parity vs the
    # kill-switch dict path and the numpy oracle asserted inside the
    # bench itself)
    assert out["multiq_rows_per_sec"] > 0
    assert out["multiq_regions"] == 4
    assert out["multiq_fallbacks"] == 0
    assert out["multiq_dict_joins"] >= 2
    assert out["multiq_device_remaps"] >= 2
    assert out["multiq_topn_plane"] >= 1
    assert out["multiq_vs_numpy_oracle"] > 0
    # the out-of-core join regime (HBM governance tier): a build side
    # ~4x the configured budget splits into radix-partitioned passes
    # through the existing kernels, bit-identical to the unpartitioned
    # budget-0 oracle (parity asserted inside the bench itself)
    assert out["oversized_join_rows_per_sec"] > 0
    assert out["oversized_join_passes"] >= 2, \
        "the oversized build side never split into partitioned passes"
    assert out["oversized_join_fallbacks"] == 0
    assert out["oversized_join_budget_bytes"] > 0
    # the out-of-core everything regime (PR 20): ORDER BY through the
    # range-partitioned external sort, the high-NDV group-by through
    # radix-partitioned states passes, and a window function over the
    # same ledger — zero fallbacks, bit parity vs the budget-0
    # kill-switch oracle asserted inside the bench itself
    assert out["spill_rows_per_sec"] > 0
    assert out["spill_passes"] >= 2, \
        "no out-of-core operator split into partitioned passes"
    assert out["spill_sort_passes"] >= 2, \
        "the external sort never took a partitioned device pass"
    assert out["spill_groupby_passes"] >= 2, \
        "the high-NDV states table never partitioned"
    assert out["spill_window_passes"] >= 1, \
        "no window function rode the device segment-scan kernel"
    assert out["spill_fallbacks"] == 0
    assert out["spill_budget_bytes"] > 0
    # the HTAP freshness regime: commits interleaved with repeat fan-out
    # scans keep the plane cache hot through region delta packs + device
    # base+delta merges (parity vs the row protocol and the commit-to-
    # table-B invariance are asserted inside the bench itself)
    assert out["htap_scan_rows_per_sec"] > 0
    assert out["htap_regions"] == 4
    assert out["htap_plane_cache_hit_ratio"] >= 0.8, \
        ("mixed commit/scan traffic re-colded the plane cache "
         f"(hit ratio {out['htap_plane_cache_hit_ratio']})")
    assert out["htap_plane_cache_hit_ratio_off"] < 0.3
    assert out["delta_merges"] >= 1, \
        "no scan answered through a base+delta merge"
    assert out["delta_repacks"] >= 1, \
        "the delta budget never folded a pack into a fresh base"
    # the mesh execution regime: q1 over the mesh client, and the
    # 4-region fan-out whose partial-aggregate combine rides the mesh
    # (1-shard on this rig — same code path, no collectives) with zero
    # columnar fallbacks
    assert out["q1_mesh_rows_per_sec"] > 0
    assert out["mesh_devices"] >= 1
    assert out["mesh_fanout_rows_per_sec"] > 0
    assert out["mesh_shards"] >= 1
    assert out["mesh_combines"] >= 1, \
        "the fan-out partial combine never rode the mesh tier"
    assert out["mesh_collective_ms"] >= 0
    assert out["mesh_transfer_bytes"] > 0
    assert out["mesh_fanout_fallbacks"] == 0
    assert out["trace_mesh_combines"] >= 0
    assert out["trace_mesh_ms_total"] >= 0
    # trace-derived kernel/copr instrumentation summary: present and
    # non-negative, so tier-1 guards the tracing layer itself
    assert out["trace_copr_tasks"] >= 4
    assert out["trace_copr_task_ms_max"] >= 0
    assert out["trace_copr_queue_ms_max"] >= 0
    assert out["trace_copr_retries"] >= 0
    assert out["trace_kernel_dispatches"] >= 1, \
        "traced fan-out run recorded no device kernel spans"
    assert out["trace_kernel_ms_total"] >= 0
    assert out["trace_readbacks"] >= 1
    assert out["trace_readback_bytes"] > 0
    # the sustained-QPS concurrency regime: concurrent below-floor
    # statements shared device dispatches (micro-batch tier), batched
    # answers matched the solo route exactly (asserted inside the bench,
    # surfaced as qps_parity), and p99 at 32 simulated connections held
    # within 2x the 1-connection p99 — the tier's exit criterion
    assert out["qps_connections"] == 32
    assert out["qps_sustained"] > 0
    assert out["qps_batched_dispatches"] > 0, \
        "no concurrent below-floor statements shared a dispatch"
    assert out["qps_batched_statements"] >= out["qps_batched_dispatches"]
    assert out["qps_parity"] is True
    assert out["qps_p99_ms"] > 0 and out["qps_p99_ms_1conn"] > 0
    assert out["qps_p99_ratio_vs_1conn"] <= 2.0, \
        (f"p99 at 32 connections is "
         f"{out['qps_p99_ratio_vs_1conn']:.2f}x the 1-connection p99 "
         "(concurrency tier failed to keep latency flat)")
    # workload-observability figures: the digest summary saw the fan-out
    # workload (plan digest asserted inside the bench), region heat
    # covers every region, and the digest pipeline stays under the same
    # 2ms/statement bound the tier-1 overhead guard enforces
    # diagnostics-tier figures: the metered dispatch lock saw device
    # time in the bracketed regime, the micro-batch profiler histograms
    # carry the qps regime's slot economics, the drain-pool wait
    # histogram saw the fan-out, and the flight recorder's fast path
    # stays under the same 2ms/statement contract as the digest pipeline
    assert 0 < out["device_busy_fraction"] <= 1.0
    assert out["device_busy_us"] > 0
    assert 0 < out["batch_slot_occupancy_p50"] <= 1.0, \
        "qps regime left no slot-occupancy observations"
    assert out["pool_queue_wait_p99_ms"] >= 0
    assert out["flight_recorder_overhead_us_per_stmt"] < 2000
    assert out["digest_entries"] >= 1
    assert out["digest_fanout_exec_count"] >= 2
    assert out["digest_fanout_device_ms"] >= 0
    assert out["digest_fanout_p95_ms"] > 0
    assert out["digest_overhead_us_per_stmt"] < 2000
    assert out["hot_region_count"] >= 4
    assert out["hot_region_top_read_rows"] > 0
    assert out["hot_region_top_score"] > 0
    # kernel-profiler figures (PR 19): the continuous profiler watched
    # every metered dispatch the regimes above ran — a top signature
    # exists, owns a real share of device time, and the retrace counter
    # reconciles with the jit-cache phase counters
    assert out["kernel_profile_signatures"] >= 1, \
        "the profiler registry saw no dispatches across the whole bench"
    top = out["kernel_profile_top_signature"]
    assert top and "|" in top, top
    assert out["kernel_profile_top_device_us"] > 0
    assert 0 < out["kernel_profile_top_device_us_share"] <= 1.0
    assert out["kernel_profile_retraces"] >= 1, \
        "cold jit compiles never published as retraces"
