"""Static check: retryable errors must never be swallowed silently.

The seed shipped a bug class this repo keeps re-finding: a broad
`except` (bare, Exception, TiDBError, KVError) in the coprocessor /
cluster / distsql path that catches a RETRYABLE error — a pending
Percolator lock, a region epoch move — and converts it into a string,
a None, or nothing, stranding the statement instead of driving the
client's resolve-and-retry ladder (PR 5 fixed exactly this in
copr/region_handler). This AST walk makes that class unrepresentable:
every broad handler in the guarded packages must either

  (a) contain a `raise` in its body (re-raise / wrap-and-raise), or
  (b) be preceded, in the same `try`, by a handler naming a retryable
      type (RetryableError / RegionError / KeyIsLockedError / ...)
      whose body re-raises — the broad catch then provably cannot see
      a live retryable, or
  (c) carry an explicit `# retryable-ok: <reason>` pragma on the
      `except` line, for the rare best-effort sites (2PC cleanup,
      straggler commits) where swallowing everything IS the contract.

Tier-1 fails on any new violation, with file:line and the rule.
"""

from __future__ import annotations

import ast
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent / "tidb_tpu"

GUARDED_DIRS = ("cluster", "copr", "distsql")

# names whose catch can swallow a retryable error (superclasses of
# RetryableError, or catch-everything forms)
BROAD_NAMES = {"Exception", "BaseException", "TiDBError", "KVError"}

# retryable family: a preceding re-raising handler for any of these
# clears the broad handler below it
RETRYABLE_NAMES = {
    "RetryableError", "RegionError", "KeyIsLockedError", "StaleEpochError",
    "NotLeaderError", "ServerIsBusyError", "RegionMissError",
    "RpcTimeoutError",
}

PRAGMA = "# retryable-ok:"


def _type_names(node) -> list[str]:
    """Terminal names of an except clause's type expression."""
    if node is None:
        return ["<bare>"]
    if isinstance(node, ast.Tuple):
        out: list[str] = []
        for elt in node.elts:
            out.extend(_type_names(elt))
        return out
    if isinstance(node, ast.Attribute):
        return [node.attr]
    if isinstance(node, ast.Name):
        return [node.id]
    return ["<dynamic>"]


def _contains_raise(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _violations(path: Path) -> list[str]:
    src = path.read_text()
    lines = src.splitlines()
    tree = ast.parse(src, filename=str(path))
    bad: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        retryable_cleared = False
        for handler in node.handlers:
            names = _type_names(handler.type)
            if any(n in RETRYABLE_NAMES for n in names) \
                    and _contains_raise(handler):
                retryable_cleared = True
            is_broad = handler.type is None \
                or any(n in BROAD_NAMES for n in names)
            if not is_broad:
                continue
            if _contains_raise(handler):
                continue
            if retryable_cleared:
                continue
            if PRAGMA in lines[handler.lineno - 1]:
                continue
            rel = path.relative_to(ROOT.parent)
            bad.append(
                f"{rel}:{handler.lineno}: broad `except "
                f"{'/'.join(names)}` can swallow a RetryableError — "
                f"re-raise, add a preceding `except RetryableError: "
                f"raise`, or justify with `{PRAGMA} <reason>`")
    return bad


def test_no_swallowed_retryables_in_guarded_packages():
    files = []
    for d in GUARDED_DIRS:
        files.extend(sorted((ROOT / d).rglob("*.py")))
    assert files, "guarded packages not found — layout changed?"
    problems: list[str] = []
    for f in files:
        problems.extend(_violations(f))
    assert not problems, "\n".join(problems)


def test_checker_detects_a_violation():
    """The checker itself must flag the seed's bug shape (meta-test so a
    refactor can't silently neuter the walk)."""
    import textwrap
    snippet = textwrap.dedent("""
        def f():
            try:
                g()
            except Exception as e:
                return str(e)
    """)
    tmp = ROOT / "cluster"
    tree = ast.parse(snippet)
    found = False
    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            h = node.handlers[0]
            found = _type_names(h.type) == ["Exception"] \
                and not _contains_raise(h)
    assert found and tmp.exists()


def test_filter_tier_degradation_seams_present():
    """PR 17 filter-tier seams, pinned by name: the batched filter
    kernel's device fault seam and the statement finisher's host-rung
    seam must stay wired to the degradation ladder (typed DeviceError
    handlers, counted on copr.degraded_filter_batch) — removing either
    silently un-certifies the ladder the differential suite exercises."""
    kernels = (ROOT / "ops" / "kernels.py").read_text()
    region = (ROOT / "copr" / "columnar_region.py").read_text()
    assert '"device/filter_batched"' in kernels, \
        "kernels.region_filter_batched lost its device/filter_batched seam"
    assert '"copr/filter_batched"' in region, \
        "_finish_filter_batch lost its copr/filter_batched seam"
    assert 'record_degraded("filter_batch")' in region, \
        "filter-tier fallbacks no longer counted on copr.degraded_filter_batch"


def test_arg_plane_degradation_seams_present():
    """PR 18 arg-plane seams, pinned by name: the statement finisher's
    host-exprc-rung failpoint and the degradation counter must stay
    wired — every arg-plane program that falls off the fused states
    kernel is counted on copr.degraded_arg_plane, never silent."""
    region = (ROOT / "copr" / "columnar_region.py").read_text()
    assert '"copr/arg_plane"' in region, \
        "finish_states_batch lost its copr/arg_plane seam"
    assert 'record_degraded("arg_plane")' in region, \
        "arg-plane fallbacks no longer counted on copr.degraded_arg_plane"
