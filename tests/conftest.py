"""Test configuration.

Tests run JAX on CPU with 8 virtual devices so multi-chip sharding paths
(tidb_tpu.parallel) are exercised without TPU hardware, per the driver's
dryrun contract. Must be set before jax is imported anywhere.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# the axon environment force-registers the TPU platform via jax.config
# (overriding JAX_PLATFORMS), so pin the config directly too
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
