"""Statement validation pass (round-3 coverage row #3: preprocess/
validate was inline in the plan builder; now a separate pass).

Reference: plan/preprocess.go:24, plan/validator.go:28-220.
"""

import pytest

from tidb_tpu import errors
from tidb_tpu.session import Session, new_store
from tests.testkit import _store_id


@pytest.fixture
def s():
    s = Session(new_store(f"memory://prep{next(_store_id)}"))
    s.execute("create database d; use d")
    s.execute("create table t (a bigint primary key, b int)")
    s.execute("insert into t values (1, 2), (2, 3)")
    return s


def _code(ei):
    return getattr(ei.value, "code", None)


def test_nested_aggregate_rejected(s):
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("select sum(count(b)) from t")
    assert _code(ei) == 1111
    with pytest.raises(errors.TiDBError):
        s.execute("select max(1 + min(b)) from t group by a")


def test_multiple_primary_keys_rejected(s):
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("create table bad (a int primary key, b int primary key)")
    assert _code(ei) == 1068
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("create table bad (a int primary key, b int, "
                  "primary key (b))")
    assert _code(ei) == 1068


def test_auto_increment_rules(s):
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("create table bad (a int auto_increment, "
                  "b int auto_increment, primary key (a))")
    assert _code(ei) == 1075
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("create table bad (a int auto_increment, b int)")
    assert _code(ei) == 1075   # auto column must be a key
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("create table bad (a varchar(5) auto_increment "
                  "primary key)")
    assert _code(ei) == 1063   # non-integer auto column
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("create table bad (a int auto_increment default 5 "
                  "primary key)")
    assert _code(ei) == 1067
    # the valid shapes still work
    s.execute("create table ok1 (a int auto_increment primary key)")
    s.execute("create table ok2 (a bigint auto_increment, unique key (a))")


def test_char_length_cap(s):
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("create table bad (a char(300))")
    assert _code(ei) == 1074
    s.execute("create table ok (a varchar(300))")   # varchar is fine


def test_duplicate_index_columns(s):
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("create index ix on t (b, b)")
    assert _code(ei) == 1060
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("create table bad (a int, b int, key kk (a, a))")
    assert _code(ei) == 1060


def test_stray_param_marker_rejected(s):
    with pytest.raises(errors.TiDBError):
        s.execute("select * from t where a = ?")
    # but PREPARE accepts markers, and EXECUTE binds them
    s.execute("prepare p1 from 'select b from t where a = ?'")
    s.execute("set @x = 1")
    assert s.execute("execute p1 using @x")[0].values() == [[2]]


def test_straight_join(s):
    """STRAIGHT_JOIN both as operator and SELECT option (parser.y
    StraightJoin productions): inner-join semantics, written order kept."""
    s.execute("create table u (a bigint primary key, c int)")
    s.execute("insert into u values (1, 10), (3, 30)")
    got = s.execute("select t.a, u.c from t straight_join u on t.a = u.a")[0] \
        .values()
    assert got == [[1, 10]]
    got = s.execute("select straight_join t.a, u.c from t, u "
                    "where t.a = u.a")[0].values()
    assert got == [[1, 10]]
    # plan keeps the written order: t's scan precedes u's scan
    txt = "\n".join(str(r[0]) for r in s.execute(
        "explain select t.a from t straight_join u on t.a = u.a")[0].rows)
    assert txt.index("table:t") < txt.index("table:u")
    # DISTINCT before STRAIGHT_JOIN parses (MySQL select-option order)
    s.execute("select distinct straight_join t.a from t, u "
              "where t.a = u.a")
    # aggregate inside a scalar subquery under an outer aggregate is a
    # FRESH aggregate scope — the validator must not flag it as nested
    # (the plan builder's subquery-in-agg-arg support is separate)
    from tidb_tpu.parser.parser import Parser
    from tidb_tpu.plan.preprocess import validate
    validate(Parser().parse_one(
        "select sum((select count(c) from u)) from t"))
    # while a genuinely nested aggregate inside the subquery still trips
    with pytest.raises(errors.TiDBError):
        validate(Parser().parse_one(
            "select (select max(count(c)) from u) from t"))


def test_row_expressions(s):
    """Row comparisons decompose to scalar 3VL expressions
    (evaluator_binop.go row compare; MySQL lexicographic ordering)."""
    s.execute("create table r (a bigint primary key, b int)")
    s.execute("insert into r values (1,3), (2,3), (3,1), (4,null)")
    q = lambda sql: s.execute(sql)[0].values()
    assert q("select a from r where (a, b) in ((1,3), (2,3)) "
             "order by a") == [[1], [2]]
    assert q("select a from r where (a, b) = (3, 1)") == [[3]]
    assert q("select a from r where (a, b) != (1, 3) order by a") == \
        [[2], [3], [4]]
    # lexicographic: (1,3) < (2,99); (2,3) < (2,99)
    assert q("select a from r where (a, b) < (2, 99) order by a") == \
        [[1], [2]]
    assert q("select a from r where (a, b) >= (2, 3) order by a") == \
        [[2], [3], [4]]
    # NULL propagates through the row compare
    assert q("select 1 where (1, null) = (1, 2)") == []
    assert q("select a from r where (a, b) not in ((1,3)) order by a") == \
        [[2], [3], [4]]   # (4,NULL): NOT(4=1 AND ...) = NOT(FALSE) = TRUE
    with pytest.raises(errors.TiDBError):
        s.execute("select 1 where (1, 2) = (1, 2, 3)")   # arity mismatch
    # ORM-scale IN lists must not blow the rewriter's recursion
    big = ", ".join(f"({i}, {i})" for i in range(2000))
    assert q(f"select a from r where (a, b) in ({big})") == []


def test_do_and_convert_using(s):
    """DO evaluates-and-discards (ast/misc.go DoStmt); CONVERT(expr USING
    charset) validates the charset and yields the string (parser.y:2446)."""
    assert s.execute("do 1 + 1, sleep(0)") == []
    s.execute("set @side = 41")
    assert s.execute("do @side + 1") == []   # evaluates, returns nothing
    assert s.execute("do (select count(*) from t)") == []   # subquery form
    assert s.execute("select convert('abc' using utf8)")[0].values() == \
        [["abc"]]
    assert s.execute("select convert(97 using latin1)")[0].values() == \
        [["97"]]
    with pytest.raises(errors.TiDBError) as ei:
        s.execute("select convert('x' using klingon)")
    assert _code(ei) == 1115
