"""Distributed-without-a-cluster tests (SURVEY §4 tier 4).

Mirrors store/tikv's mock-cluster suites: split_test.go,
region_cache_test.go, 2pc_test.go, isolation_test.go — topology is
manipulated mid-test to force NotLeader/StaleEpoch/region-miss retries,
and the Percolator invariants are checked directly.
"""

import threading

import pytest

from tidb_tpu import errors
from tidb_tpu.cluster import Cluster, DistStore, KeyIsLockedError
from tidb_tpu.cluster.mvcc import MvccStore
from tidb_tpu.cluster.twopc import TwoPhaseCommitter


@pytest.fixture
def store():
    return DistStore(n_stores=3)


class TestMvcc:
    def test_prewrite_commit_get(self):
        m = MvccStore()
        m.prewrite([("put", b"a", b"1"), ("put", b"b", b"2")], b"a", 10)
        # locked for readers at ts >= 10
        with pytest.raises(KeyIsLockedError):
            m.get(b"a", 15)
        assert m.get(b"a", 5) is None  # older snapshot unaffected
        m.commit([b"a", b"b"], 10, 20)
        assert m.get(b"a", 25) == b"1"
        assert m.get(b"a", 15) is None  # committed after that snapshot

    def test_write_conflict(self):
        m = MvccStore()
        m.prewrite([("put", b"k", b"1")], b"k", 10)
        m.commit([b"k"], 10, 20)
        from tidb_tpu.cluster.mvcc import WriteConflict
        with pytest.raises(WriteConflict):
            m.prewrite([("put", b"k", b"2")], b"k", 15)  # started before 20

    def test_rollback_then_commit_fails(self):
        m = MvccStore()
        m.prewrite([("put", b"k", b"1")], b"k", 10)
        m.rollback([b"k"], 10)
        from tidb_tpu.cluster.mvcc import TxnAborted
        with pytest.raises(TxnAborted):
            m.commit([b"k"], 10, 20)

    def test_gc(self):
        m = MvccStore()
        for i, ts in enumerate([(10, 20), (30, 40), (50, 60)]):
            m.prewrite([("put", b"k", b"v%d" % i)], b"k", ts[0])
            m.commit([b"k"], ts[0], ts[1])
        assert m.gc(45) == 1  # version @20 shadowed by @40
        assert m.get(b"k", 45) == b"v1"
        assert m.get(b"k", 65) == b"v2"


class TestTxn:
    def test_txn_across_regions_atomic(self, store):
        store.cluster.split_keys([b"m"])
        txn = store.begin()
        txn.set(b"a", b"1")
        txn.set(b"z", b"2")
        txn.commit()
        snap = store.get_snapshot()
        assert snap.get(b"a") == b"1"
        assert snap.get(b"z") == b"2"

    def test_snapshot_isolation(self, store):
        t1 = store.begin()
        t1.set(b"k", b"v1")
        t1.commit()
        t2 = store.begin()      # snapshot before v2
        t3 = store.begin()
        t3.set(b"k", b"v2")
        t3.commit()
        assert t2.get(b"k") == b"v1"
        assert store.get_snapshot().get(b"k") == b"v2"

    def test_conflict_detection(self, store):
        t0 = store.begin()
        t0.set(b"k", b"base")
        t0.commit()
        t1 = store.begin()
        t2 = store.begin()
        t1.set(b"k", b"t1")
        t2.set(b"k", b"t2")
        t1.commit()
        with pytest.raises(errors.RetryableError):
            t2.commit()

    def test_crashed_writer_lock_resolution(self, store):
        """Abandoned prewrite (expired TTL) gets rolled back by readers."""
        import tidb_tpu.cluster.twopc as twopc
        store.mvcc.prewrite([("put", b"k", b"ghost")], b"k",
                            store.oracle.current_version(), ttl_ms=0)
        snap = store.get_snapshot()
        assert snap.get_or_none(b"k") is None  # resolves the lock, reads on
        assert not store.mvcc.scan_locks(1 << 62)

    def test_committed_but_unresolved_secondary(self, store):
        """Primary committed, secondary lock left: readers roll it FORWARD."""
        store.cluster.split_keys([b"m"])
        start = store.oracle.current_version()
        store.mvcc.prewrite([("put", b"a", b"1")], b"a", start, ttl_ms=0)
        store.mvcc.prewrite([("put", b"z", b"2")], b"a", start, ttl_ms=0)
        commit_ts = store.oracle.current_version()
        store.mvcc.commit([b"a"], start, commit_ts)  # primary only
        snap = store.get_snapshot()
        assert snap.get(b"z") == b"2"  # secondary committed on resolve

    def test_gc_worker(self, store):
        for v in (b"1", b"2", b"3"):
            t = store.begin()
            t.set(b"k", v)
            t.commit()
        sp = store.oracle.current_version()
        removed = store.run_gc(sp)
        assert removed >= 2
        assert store.get_snapshot().get(b"k") == b"3"


class TestTopologyRetries:
    def test_read_after_leader_change(self, store):
        t = store.begin()
        t.set(b"k", b"v")
        t.commit()
        region = store.cluster.region_by_key(b"k")
        other = next(s for s in store.cluster.stores
                     if s != region.leader_store_id)
        store.cluster.change_leader(region.region_id, other)
        # stale cache → NotLeader → retry with new leader
        assert store.get_snapshot().get(b"k") == b"v"

    def test_read_after_split(self, store):
        t = store.begin()
        for k in (b"a", b"m", b"z"):
            t.set(k, b"v-" + k)
        t.commit()
        store.get_snapshot().get(b"a")  # populate cache
        store.cluster.split_keys([b"g", b"t"])
        # stale epoch → cache refresh → reads succeed
        snap = store.get_snapshot()
        for k in (b"a", b"m", b"z"):
            assert snap.get(k) == b"v-" + k

    def test_scan_across_split(self, store):
        t = store.begin()
        for i in range(20):
            t.set(b"k%02d" % i, b"%d" % i)
        t.commit()
        store.cluster.split_keys([b"k05", b"k10", b"k15"])
        snap = store.get_snapshot()
        keys = [k for k, _ in snap.iterate(b"k00", b"k99")]
        assert keys == [b"k%02d" % i for i in range(20)]

    def test_write_during_leader_flap(self, store):
        region = store.cluster.region_by_key(b"k")
        stores = list(store.cluster.stores)

        stop = threading.Event()

        def flap():
            i = 0
            while not stop.is_set():
                store.cluster.change_leader(region.region_id,
                                            stores[i % len(stores)])
                i += 1

        th = threading.Thread(target=flap)
        th.start()
        try:
            for i in range(10):
                t = store.begin()
                t.set(b"k", b"%d" % i)
                t.commit()
        finally:
            stop.set()
            th.join()
        assert store.get_snapshot().get(b"k") == b"9"


class TestSqlOverCluster:
    """The full engine stack over the distributed store (ticlient tier)."""

    def test_end_to_end_sql(self):
        from tidb_tpu.session import Session, new_store
        store = new_store("cluster://3")
        s = Session(store)
        s.execute("create database d")
        s.execute("use d")
        s.execute("create table t (id bigint primary key, v varchar(16), "
                  "n int, key idx_v (v))")
        s.execute("insert into t values (1,'a',10),(2,'b',20),(3,'a',30)")
        rs = s.execute("select v, sum(n) from t group by v order by v")[0]
        assert rs.values() == [["a", 40], ["b", 20]]
        rs = s.execute("select id from t where v = 'a' order by id")[0]
        assert rs.values() == [[1], [3]]
        # split the table region mid-session; queries keep working
        from tidb_tpu import tablecodec as tc
        tbl = s.info_schema().table_by_name("d", "t")
        store.cluster.split_keys([tc.encode_row_key(tbl.info.id, 2)])
        rs = s.execute("select count(*) from t")[0]
        assert rs.values() == [[3]]
        s.execute("update t set n = n + 1 where id = 2")
        rs = s.execute("select n from t where id = 2")[0]
        assert rs.values() == [[21]]

    def test_pipelined_fan_out_preserves_order(self):
        """Many regions + worker concurrency: results stream back in task
        order (copIterator ordered mode, coprocessor.go:348), so sorted
        scans stay sorted and desc still works."""
        from tidb_tpu import tablecodec as tc
        from tidb_tpu.session import Session, new_store
        store = new_store("cluster://3/pipeline")
        s = Session(store)
        s.execute("create database d; use d")
        s.execute("create table t (a int primary key, b int)")
        rows = ", ".join(f"({i}, {i * 2})" for i in range(300))
        s.execute(f"insert into t values {rows}")
        tid = s.info_schema().table_by_name("d", "t").id
        store.cluster.split_keys([tc.encode_row_key(tid, k)
                                  for k in range(30, 300, 30)])
        assert len(store.cluster.regions) >= 10
        got = s.execute("select a from t order by a")[0].values()
        assert got == [[i] for i in range(300)]
        assert s.execute("select a from t order by a desc limit 3"
                         )[0].values() == [[299], [298], [297]]
        assert s.execute("select sum(b), count(*) from t")[0].values() \
            == [[89700, 300]]

    def test_pipelined_fan_out_propagates_worker_errors(self):
        """An exception inside a worker must surface on the consumer, not
        hang the stream."""
        import pytest
        from tidb_tpu.cluster.store import _PipelinedResponse

        def run(rg):
            if rg == 2:
                raise RuntimeError("boom")
            return [rg]

        resp = _PipelinedResponse([1, 2, 3, 4], run, concurrency=2)
        with pytest.raises(RuntimeError):
            while resp.next() is not None:
                pass


class TestPipelinedBackpressure:
    def test_window_bounds_completed_results(self):
        from tidb_tpu.cluster.store import _PipelinedResponse
        import threading
        import time as _t
        ran = []
        def run(task):
            ran.append(task)
            return [task]
        resp = _PipelinedResponse(list(range(64)), run, concurrency=2)
        assert resp.next() == 0
        _t.sleep(0.2)
        # workers must stay within the sliding window of the consumer,
        # not race through all 64 tasks
        assert len(ran) <= 2 * 2 + 2 + 1
        while resp.next() is not None:
            pass
        assert sorted(ran) == list(range(64))

    def test_close_releases_parked_workers(self):
        from tidb_tpu.cluster.store import _PipelinedResponse
        import time as _t
        ran = []
        def run(task):
            ran.append(task)
            return [task]
        resp = _PipelinedResponse(list(range(64)), run, concurrency=2)
        assert resp.next() == 0          # consume one, then abandon (LIMIT)
        resp.close()
        _t.sleep(0.3)
        n_after_close = len(ran)
        _t.sleep(0.3)
        # workers exited: no further tasks execute after close settles
        assert len(ran) == n_after_close
        assert len(ran) < 64
