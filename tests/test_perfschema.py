"""performance_schema tests: statement instrumentation + virtual tables
queryable through the normal SQL path.

Mirrors perfschema/perfschema_test.go (statement events recorded around
Execute) with the virtual-table read checked via real SQL.
"""

from tidb_tpu import perfschema
from tests.testkit import TestKit


def hist(tk, cols="SQL_TEXT"):
    return tk.exec(f"select {cols} from "
                   "performance_schema.events_statements_history").rows


class TestPerfSchema:
    def test_statements_recorded_with_rows(self):
        tk = TestKit()
        tk.exec("create database d; use d; create table t (a int)")
        tk.exec("insert into t values (1), (2), (3)")
        tk.exec("select * from t where a > 1")
        rows = tk.exec(
            "select SQL_TEXT, ROWS_SENT, ROWS_AFFECTED from "
            "performance_schema.events_statements_history").rows
        texts = {(r[0].decode() if isinstance(r[0], bytes) else r[0]):
                 (r[1], r[2]) for r in rows}
        assert texts["insert into t values (1), (2), (3)"] == (0, 3)
        assert texts["select * from t where a > 1"] == (2, 0)

    def test_errors_recorded(self):
        tk = TestKit()
        try:
            tk.exec("select * from missing.t")
        except Exception:
            pass
        rows = tk.exec(
            "select ERRORS, MESSAGE_TEXT from "
            "performance_schema.events_statements_history "
            "where ERRORS = 1").rows
        assert rows and all(r[0] == 1 for r in rows)

    def test_timer_wait_positive_and_filterable(self):
        tk = TestKit()
        tk.exec("create database d; use d; create table t (a int)")
        n = tk.exec("select count(*) from "
                    "performance_schema.events_statements_history "
                    "where TIMER_WAIT > 0").rows[0][0]
        assert n > 0

    def test_history_bounded(self):
        tk = TestKit()
        ps = perfschema.perf_for(tk.store)
        for i in range(perfschema.HISTORY_CAP + 50):
            ev = ps.start_statement(1, f"stmt {i}")
            ps.end_statement(ev)
        assert len(ps.rows(perfschema.T_STMT_HISTORY)) == \
            perfschema.HISTORY_CAP

    def test_setup_instruments_and_show_tables(self):
        tk = TestKit()
        tk.exec("show tables from performance_schema").check(
            [["events_statements_current"], ["events_statements_history"],
             ["events_statements_summary_by_digest"],
             ["events_statements_summary_by_digest_history"],
             ["events_statements_summary_evicted"],
             ["setup_instruments"]])
        tk.exec("select ENABLED from performance_schema.setup_instruments"
                ).check([["YES"]])

    def test_aggregates_over_virtual_tables(self):
        """count/group-by must NOT push into the (nonexistent) coprocessor
        behind a virtual scan (regression: FINAL agg decoded raw rows)."""
        tk = TestKit()
        tk.exec("create database d; use d; create table t (a int)")
        tk.exec("insert into t values (1)")
        n = tk.exec("select count(*) from "
                    "performance_schema.events_statements_history"
                    ).rows[0][0]
        assert n > 0
        rows = tk.exec(
            "select THREAD_ID, count(*) from "
            "performance_schema.events_statements_history "
            "group by THREAD_ID").rows
        # the first count query itself lands in history before the second
        assert rows and rows[0][1] >= n

    def test_virtual_tables_read_only(self):
        from tidb_tpu import errors
        tk = TestKit()
        for sql in ("insert into performance_schema.setup_instruments "
                    "values ('x', 'YES', 'YES')",
                    "delete from performance_schema.setup_instruments",
                    "drop database performance_schema",
                    "create table performance_schema.hack (a int)",
                    "truncate table performance_schema.setup_instruments"):
            try:
                tk.exec(sql)
                raise AssertionError(f"{sql!r} should have failed")
            except errors.TiDBError:
                pass
        # still present and readable
        assert tk.exec("select count(*) from "
                       "performance_schema.setup_instruments").rows == [[1]]

    def test_current_keeps_latest_per_thread_bounded(self):
        tk = TestKit()
        ps = perfschema.perf_for(tk.store)
        for tid in range(perfschema.CURRENT_CAP + 20):
            ev = ps.start_statement(tid, "x")
            ps.end_statement(ev)
        assert len(ps.rows(perfschema.T_STMT_CURRENT)) == \
            perfschema.CURRENT_CAP

    def test_show_processlist_and_kill(self):
        from tidb_tpu import errors
        from tidb_tpu.session import Session
        tk = TestKit()
        other = Session(tk.store)
        rows = tk.exec("show processlist").rows
        ids = {int(r[0]) for r in rows}
        assert tk.session.vars.connection_id in ids
        assert other.vars.connection_id in ids
        tk.exec(f"kill {other.vars.connection_id}")
        import pytest as _pytest
        with _pytest.raises(errors.TiDBError):
            other.execute("select 1")
        other.execute("select 1")  # one interruption, then normal service

    def test_kill_connection_closes_wire_socket(self):
        from tidb_tpu.server import Client, Server
        from tidb_tpu.session import new_store
        from tests.testkit import _store_id
        store = new_store(f"memory://killconn{next(_store_id)}")
        srv = Server(store)
        srv.start()
        try:
            victim = Client("127.0.0.1", srv.port)
            victim.query("select 1")
            admin = Client("127.0.0.1", srv.port)
            vid = next(int(r[0]) for r in admin.query(
                "show processlist")[0].rows
                if (r[7] or "") == "select 1")
            admin.query(f"kill connection {vid}")
            import pytest as _pytest
            with _pytest.raises(Exception):
                victim.query("select 1")  # socket closed
            admin.query("select 1")       # admin unaffected
            admin.close()
        finally:
            srv.close()

    def test_kill_connection_tears_down_idle_victim(self):
        """KILL CONNECTION must wake a peer blocked in recv (shutdown
        before close) and free its session promptly (no conn↔session
        reference cycle pinning the processlist row)."""
        import time
        from tidb_tpu.server import Client, Server
        from tidb_tpu.session import new_store
        from tests.testkit import _store_id
        store = new_store(f"memory://killidle{next(_store_id)}")
        srv = Server(store)
        srv.start()
        try:
            victim = Client("127.0.0.1", srv.port)
            victim.query("select 1")

            def info(r):
                v = r[7]
                return v.decode() if isinstance(v, bytes) else (v or "")
            admin = Client("127.0.0.1", srv.port)
            vid = next(int(r[0]) for r in
                       admin.query("show processlist")[0].rows
                       if info(r) == "select 1")
            admin.query(f"kill connection {vid}")
            deadline = time.time() + 3.0
            while time.time() < deadline:
                rows = admin.query("show processlist")[0].rows
                if all(int(r[0]) != vid for r in rows):
                    break
                time.sleep(0.05)
            assert all(int(r[0]) != vid for r in rows)
            admin.close()
        finally:
            srv.close()

    def test_internal_sessions_hidden_and_unkillable(self):
        """The server's auth session must not appear in PROCESSLIST (and
        so can't be killed to break logins)."""
        from tidb_tpu.server import Client, Server
        from tidb_tpu.session import new_store, sessions_for
        from tests.testkit import _store_id
        store = new_store(f"memory://killauth{next(_store_id)}")
        srv = Server(store)
        srv.start()
        try:
            c = Client("127.0.0.1", srv.port)
            ids = {s.vars.connection_id for s in sessions_for(store)}
            assert srv._auth_session.vars.connection_id not in ids
            c.close()
            c2 = Client("127.0.0.1", srv.port)  # auth still works
            c2.query("select 1")
            c2.close()
        finally:
            srv.close()

    def test_processlist_hides_other_users_without_grant(self):
        from tidb_tpu.session import Session
        tk = TestKit()
        tk.exec("create user 'pl1'")
        restricted = Session(tk.store)
        restricted.vars.user = "pl1"
        rows = restricted.execute("show processlist")[0].values()
        users = {(r[1].decode() if isinstance(r[1], bytes) else r[1])
                 for r in rows}
        assert users <= {"pl1"}

    def test_kill_other_user_needs_grant(self):
        from tidb_tpu.privilege import AccessDenied
        from tidb_tpu.session import Session
        import pytest as _pytest
        tk = TestKit()
        tk.exec("create user 'k1'")
        victim = Session(tk.store)
        attacker = Session(tk.store)
        attacker.vars.user = "k1"
        with _pytest.raises(AccessDenied):
            attacker.execute(f"kill {victim.vars.connection_id}")

    def test_join_virtual_with_real_table(self):
        """Virtual tables flow through the regular planner: joins work."""
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table conns (tid int, who varchar(16))")
        tid = tk.session.vars.connection_id
        tk.exec(f"insert into conns values ({tid}, 'lib')")
        rows = tk.exec(
            "select distinct c.who from conns c, "
            "performance_schema.events_statements_history h "
            "where c.tid = h.THREAD_ID").rows
        assert rows == [["lib"]]
