"""Subqueries, derived tables, UNION — end-to-end SQL tests.

Reference behaviors: parser/parser.y (SubSelect/UnionStmt productions),
executor/executor.go (Apply/Exists/MaxOneRow/HashSemiJoin/Union),
plan/expression_rewriter.go (scalar / EXISTS / IN subquery lowering).
"""

import pytest

from testkit import TestKit


@pytest.fixture()
def tk():
    tk = TestKit()
    tk.exec("create database test")
    tk.exec("use test")
    tk.exec("create table t (id int primary key, a int, b varchar(32))")
    tk.exec("insert into t values (1, 10, 'x'), (2, 20, 'y'), (3, 30, 'z'), "
            "(4, 40, 'x'), (5, null, 'w')")
    tk.exec("create table s (id int primary key, ta int, c int)")
    tk.exec("insert into s values (1, 10, 100), (2, 20, 200), (3, 20, 300), "
            "(4, 99, 400)")
    return tk


# ---------------------------------------------------------------------------
# UNION
# ---------------------------------------------------------------------------

class TestUnion:
    def test_union_all(self, tk):
        tk.exec("select 1 union all select 2 union all select 1") \
            .check([[1], [2], [1]])

    def test_union_distinct(self, tk):
        tk.exec("select 1 union select 2 union select 1").sort() \
            .check([[1], [2]])

    def test_union_tables_order_limit(self, tk):
        tk.exec("select a from t where a <= 20 union all select c from s "
                "order by 1 limit 3").check([[10], [20], [100]])

    def test_union_parenthesized(self, tk):
        tk.exec("(select 1) union all (select 2)").check([[1], [2]])

    def test_union_column_count_mismatch(self, tk):
        with pytest.raises(Exception):
            tk.exec("select 1, 2 union select 3")

    def test_union_mixed_all_distinct(self, tk):
        # DISTINCT dedups operands to its left only (MySQL semantics)
        tk.exec("select 1 union select 2 union all select 2") \
            .check([[1], [2], [2]])
        tk.exec("select 1 union all select 1 union select 2").sort() \
            .check([[1], [2]])

    def test_parenthesized_select_trailing_limit(self, tk):
        tk.exec("(select a from t where a is not null) order by 1 limit 2") \
            .check([[10], [20]])

    def test_union_in_derived_table(self, tk):
        tk.exec("select count(*) from (select a from t union all "
                "select c from s) u").check([[9]])


# ---------------------------------------------------------------------------
# derived tables
# ---------------------------------------------------------------------------

class TestDerivedTable:
    def test_basic(self, tk):
        tk.exec("select d.a from (select a from t where a > 20) d "
                "order by d.a").check([[30], [40]])

    def test_aggregate_inside(self, tk):
        tk.exec("select cnt from (select b, count(*) cnt from t group by b) "
                "g where g.cnt > 1").check([[2]])

    def test_aggregate_over_derived(self, tk):
        tk.exec("select sum(x) from (select a + 1 x from t where a is not "
                "null) d").check([[104]])

    def test_join_derived(self, tk):
        tk.exec("select t.id, d.mx from t, (select max(c) mx from s) d "
                "where t.id = 1").check([[1, 400]])

    def test_requires_alias(self, tk):
        with pytest.raises(Exception):
            tk.exec("select * from (select a from t)")


# ---------------------------------------------------------------------------
# scalar subqueries
# ---------------------------------------------------------------------------

class TestScalarSubquery:
    def test_uncorrelated_where(self, tk):
        tk.exec("select id from t where a = (select max(c) / 10 from s)") \
            .check([[4]])

    def test_uncorrelated_select_list(self, tk):
        tk.exec("select id, (select min(ta) from s) from t where id = 2") \
            .check([[2, 10]])

    def test_empty_yields_null(self, tk):
        tk.exec("select (select c from s where ta = -1) from t "
                "where id = 1").check([[None]])

    def test_more_than_one_row_errors(self, tk):
        with pytest.raises(Exception):
            tk.exec("select id from t where a = (select ta from s)")

    def test_correlated(self, tk):
        # per-row max over matching s rows (TPC-H Q17 shape)
        tk.exec("select id from t where a < (select max(c) from s "
                "where s.ta = t.a) order by id").check([[1], [2]])

    def test_correlated_select_list(self, tk):
        tk.exec("select id, (select count(*) from s where s.ta = t.a) "
                "from t order by id").check(
            [[1, 1], [2, 2], [3, 0], [4, 0], [5, 0]])


# ---------------------------------------------------------------------------
# EXISTS
# ---------------------------------------------------------------------------

class TestExists:
    def test_uncorrelated_true(self, tk):
        tk.exec("select count(*) from t where exists (select 1 from s)") \
            .check([[5]])

    def test_uncorrelated_false(self, tk):
        tk.exec("select count(*) from t where exists (select 1 from s "
                "where ta < 0)").check([[0]])

    def test_correlated(self, tk):
        tk.exec("select id from t where exists (select 1 from s "
                "where s.ta = t.a) order by id").check([[1], [2]])

    def test_not_exists(self, tk):
        tk.exec("select id from t where not exists (select 1 from s "
                "where s.ta = t.a) order by id").check([[3], [4], [5]])


# ---------------------------------------------------------------------------
# IN subqueries
# ---------------------------------------------------------------------------

class TestInSubquery:
    def test_uncorrelated(self, tk):
        tk.exec("select id from t where a in (select ta from s) "
                "order by id").check([[1], [2]])

    def test_uncorrelated_not_in(self, tk):
        tk.exec("select id from t where a not in (select ta from s) "
                "order by id").check([[3], [4]])

    def test_not_in_with_inner_null(self, tk):
        tk.exec("insert into s values (5, null, 500)")
        # inner set contains NULL → NOT IN is never TRUE
        tk.exec("select count(*) from t where a not in (select ta from s)") \
            .check([[0]])

    def test_in_select_list_3vl(self, tk):
        tk.exec("select id, a in (select ta from s) from t order by id") \
            .check([[1, 1], [2, 1], [3, 0], [4, 0], [5, None]])

    def test_correlated_in(self, tk):
        tk.exec("select id from t where a in (select ta from s "
                "where s.c <= 200) order by id").check([[1], [2]])
        tk.exec("select id from t where id in (select id from s "
                "where s.ta = t.a) order by id").check([[1], [2]])

    def test_in_string_number_coercion(self, tk):
        # string probe vs int inner set goes through full MySQL coercion
        tk.exec("select '10' in (select a from t)").check([[1]])
        tk.exec("select '11' in (select id from t)").check([[0]])
        # no match + NULL present in the inner set → NULL, not FALSE
        tk.exec("select '11' in (select a from t)").check([[None]])

    def test_in_cross_type_numeric(self, tk):
        # int probe vs decimal/float inner set must match numerically
        tk.exec("select id from t where 1 in (select 1.0) order by id") \
            .check([[1], [2], [3], [4], [5]])
        tk.exec("select count(*) from t where a in (select ta + 0.0 from s)") \
            .check([[2]])

    def test_in_grouped_subquery(self, tk):
        # TPC-H Q18 shape: IN over GROUP BY ... HAVING
        tk.exec("select id from t where a in (select ta from s group by ta "
                "having count(*) > 1) order by id").check([[2]])


# ---------------------------------------------------------------------------
# regression: mixed shapes
# ---------------------------------------------------------------------------

class TestMixedSubqueries:
    def test_subquery_plus_filter_pushdown(self, tk):
        tk.exec("select id from t where a > 10 and exists (select 1 from s "
                "where s.ta = t.a) order by id").check([[2]])

    def test_nested_subquery(self, tk):
        tk.exec("select id from t where a in (select ta from s where c in "
                "(select c from s where c >= 300)) order by id").check([[2]])

    def test_union_of_subquery_filters(self, tk):
        tk.exec("select id from t where a in (select ta from s) union all "
                "select id from t where a = 30 order by 1") \
            .check([[1], [2], [3]])

    def test_update_with_subquery_where(self, tk):
        tk.exec("update t set a = 99 where id in (select id from s "
                "where c = 400)")
        tk.exec("select a from t where id = 4").check([[99]])

    def test_delete_with_subquery_where(self, tk):
        tk.exec("delete from t where a in (select ta from s where c = 100)")
        tk.exec("select count(*) from t").check([[4]])
