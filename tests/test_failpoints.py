"""Failpoint fault injection + the unified Backoffer: differential chaos.

The contract under test is the robustness tentpole's acceptance bar:
under a seeded failpoint schedule (region timeout, NotLeader, StaleEpoch,
ServerIsBusy, device join/combine/OOM/readback faults, region pack
faults, cache-admission drops) a 4-region scan→join→agg returns
row-for-row parity with the fault-free run; every tier fallback is
accounted on the copr.degraded_* counters; and a statement that hangs
under tidb_tpu_max_execution_time fails with a typed
DeadlineExceededError (ladder history attached) within budget instead of
wedging. Backoff schedules are asserted EXACTLY via the injectable
RNG/sleeper hooks — no wall-clock sleeping in this file.
"""

from __future__ import annotations

import itertools
import random
import socket
import time

import pytest

from tidb_tpu import errors, failpoint, metrics, tablecodec as tc, tracing
from tidb_tpu.kv import backoff as kvbackoff
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 240

QUERIES = [
    "select count(*), sum(t.v), min(t.v), max(d.d_f), avg(t.v) "
    "from t join d on t.k = d.d_k",
    "select t.k, count(*), sum(t.v), max(t.v) from t "
    "join d on t.k = d.d_k group by t.k order by t.k",
    "select id, v from t where v > 500 order by v desc limit 7",
    "select k, count(*), min(v) from t group by k order by k",
]

DEGRADED_KINDS = ("device_to_cpu", "join_to_numpy", "combine_to_host",
                  "region_to_rows", "mesh")


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()
    kvbackoff.reset_test_hooks()


def _build(n_regions: int = 4, floor0: bool = False) -> Session:
    store = new_store(f"cluster://3/fp{next(_id)}")
    s = Session(store)
    s.execute("create database fp")
    s.execute("use fp")
    s.execute("create table t (id bigint primary key, k bigint, "
              "v bigint, f double)")
    rows = ", ".join(f"({i}, {i % 7}, {i * 10}, {i}.25)"
                     for i in range(1, N_ROWS + 1))
    s.execute(f"insert into t values {rows}")
    s.execute("create table d (d_k bigint primary key, d_f double)")
    s.execute("insert into d values "
              + ", ".join(f"({i}, {i}.5)" for i in range(7)))
    if n_regions > 1:
        tid = s.info_schema().table_by_name("fp", "t").info.id
        step = N_ROWS // n_regions
        s.store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    if floor0:
        s.execute("set global tidb_tpu_dispatch_floor = 0")
    return s


def _degraded():
    return {k: metrics.counter(f"copr.degraded_{k}").value
            for k in DEGRADED_KINDS}


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_policies(self):
        failpoint.enable("x/always")
        assert [bool(_fires("x/always")) for _ in range(3)] == [True] * 3

        failpoint.enable("x/every", when=("every", 3))
        fired = [bool(_fires("x/every")) for _ in range(6)]
        assert fired == [False, False, True, False, False, True]

        failpoint.enable("x/first", when=("first", 2))
        fired = [bool(_fires("x/first")) for _ in range(4)]
        assert fired == [True, True, False, False]

        # probability replays EXACTLY for a given seed
        failpoint.enable("x/prob", when=("prob", 0.5), seed=42)
        a = [bool(_fires("x/prob")) for _ in range(20)]
        failpoint.enable("x/prob", when=("prob", 0.5), seed=42)
        b = [bool(_fires("x/prob")) for _ in range(20)]
        assert a == b and True in a and False in a
        assert failpoint.counters("x/prob")["evals"] == 20

    def test_actions_and_lifecycle(self):
        # error action with the call site's typed default
        failpoint.enable("x/err")
        with pytest.raises(errors.KVError):
            failpoint.eval("x/err", lambda: errors.KVError("typed"))
        # explicit exception class wins over the default
        failpoint.enable("x/err", exc=errors.DeviceError)
        with pytest.raises(errors.DeviceError):
            failpoint.eval("x/err", lambda: errors.KVError("typed"))
        # return action carries a value; sleep returns None and continues
        failpoint.enable("x/ret", action="return", value={"drop": 1})
        assert failpoint.eval("x/ret") == {"drop": 1}
        failpoint.enable("x/sleep", action="sleep", seconds=0.0)
        assert failpoint.eval("x/sleep") is None
        # disabled name is a no-op; counters read zeros
        failpoint.disable("x/ret")
        assert failpoint.eval("x/ret") is None
        assert failpoint.counters("x/ret") == {"evals": 0, "triggers": 0}
        # context manager cleans up even on error
        with pytest.raises(RuntimeError):
            with failpoint.failpoints({"x/cm": {"action": "return",
                                                "value": 1}}):
                assert failpoint.enabled("x/cm")
                raise RuntimeError
        assert not failpoint.enabled("x/cm")
        # invalid specs are rejected loudly
        with pytest.raises(ValueError):
            failpoint.enable("x/bad", action="explode")
        with pytest.raises(ValueError):
            failpoint.enable("x/bad", when=("never",))

    def test_trigger_metric(self):
        c0 = metrics.counter("failpoint.triggers.x.m").value
        failpoint.enable("x/m")
        with pytest.raises(failpoint.FailpointError):
            failpoint.eval("x/m")
        assert metrics.counter("failpoint.triggers.x.m").value == c0 + 1

    def test_disabled_path_is_inert(self):
        failpoint.disable_all()
        assert not failpoint._active
        for _ in range(1000):
            assert failpoint.eval("no/such/site") is None


def _fires(name: str) -> bool:
    t0 = failpoint.counters(name)["triggers"]
    try:
        failpoint.eval(name)
    except failpoint.FailpointError:
        pass
    return failpoint.counters(name)["triggers"] == t0 + 1


# ---------------------------------------------------------------------------
# Backoffer: exact schedules, shared budget, deadline
# ---------------------------------------------------------------------------

class TestBackoffer:
    def test_exact_schedule_via_hooks(self):
        slept: list[float] = []
        kvbackoff.set_test_hooks(rng=random.Random(7),
                                 sleeper=slept.append)
        bo = kvbackoff.Backoffer(budget_ms=100_000)
        err = errors.KVError("x")
        got = [bo.backoff("server_busy", err) for _ in range(4)]
        # recompute the same schedule with an identical RNG clone
        rng = random.Random(7)
        want = [min(20 * (2 ** n), 200) * (0.5 + rng.random() / 2)
                for n in range(4)]
        assert got == pytest.approx(want)
        assert slept == pytest.approx([ms / 1000.0 for ms in want])
        assert bo.attempts["server_busy"] == 4
        assert [h[0] for h in bo.history] == ["server_busy"] * 4

    def test_budget_exhaustion_typed_with_history(self):
        kvbackoff.set_test_hooks(rng=random.Random(1),
                                 sleeper=lambda s: None)
        bo = kvbackoff.Backoffer(budget_ms=50)
        err = errors.KVError("busy")
        with pytest.raises(errors.DeadlineExceededError) as ei:
            for _ in range(100):
                bo.backoff("server_busy", err)
        assert ei.value.history, "ladder history missing"
        assert ei.value.history[0][0] == "server_busy"
        assert "server_busy" in str(ei.value)
        # typed, NON-retryable: the session must not replay it
        assert not errors.is_retryable(ei.value)
        assert ei.value.code == 3024

    def test_deadline_bounds_sleep_and_raises(self):
        slept: list[float] = []

        def sleeper(sec: float) -> None:
            slept.append(sec)
            time.sleep(0.002)   # advance real time toward the deadline

        kvbackoff.set_test_hooks(rng=random.Random(3), sleeper=sleeper)
        bo = kvbackoff.Backoffer(budget_ms=None,
                                 deadline=time.monotonic() + 0.010)
        err = errors.KVError("x")
        with pytest.raises(errors.DeadlineExceededError):
            for _ in range(1000):
                bo.backoff("txn_lock", err)
        # every sleep was clamped to the remaining deadline
        assert slept and all(s <= 0.011 for s in slept)

    def test_txn_util_routes_through_hooks(self):
        from tidb_tpu.kv import txn_util
        slept: list[float] = []
        kvbackoff.set_test_hooks(rng=random.Random(5),
                                 sleeper=slept.append)
        got = [txn_util.backoff(n) for n in range(3)]
        rng = random.Random(5)
        want = [rng.uniform(0, min(100, 1 << n)) / 1000.0
                for n in range(3)]
        assert got == pytest.approx(want)
        assert slept == pytest.approx(want)

    def test_run_in_new_txn_exhaustion_counter(self):
        from tidb_tpu.kv import txn_util
        kvbackoff.set_test_hooks(sleeper=lambda s: None)
        store = new_store(f"memory://fpbo{next(_id)}")

        def always_conflict(txn):
            raise errors.RetryableError("injected conflict")

        e0 = metrics.counter("kv.txn_retry_exhausted").value
        r0 = metrics.counter("kv.txn_retries").value
        with pytest.raises(errors.RetryableError):
            txn_util.run_in_new_txn(store, True, always_conflict,
                                    max_retries=3)
        assert metrics.counter("kv.txn_retry_exhausted").value == e0 + 1
        assert metrics.counter("kv.txn_retries").value == r0 + 3

    def test_session_retry_metrics_and_span(self):
        s = _build(1)
        kvbackoff.set_test_hooks(sleeper=lambda sec: None)
        s.history = ["update t set v = v where id = 1"]
        s.vars.retry_limit = 3
        calls = {"n": 0}

        def conflict(*a, **k):
            calls["n"] += 1
            raise errors.RetryableError("injected write conflict")

        r0 = metrics.counter("session.retries").value
        e0 = metrics.counter("session.retry_exhausted").value
        root = tracing.Span("statement")
        tok = tracing.attach(root)
        orig = s._execute_one
        s._execute_one = conflict
        try:
            with pytest.raises(errors.RetryableError):
                s._retry()
        finally:
            s._execute_one = orig
            tracing.detach(tok)
        assert calls["n"] == 3
        assert metrics.counter("session.retries").value == r0 + 3
        assert metrics.counter("session.retry_exhausted").value == e0 + 1
        spans = root.find("session_retry")
        assert [sp.attrs["attempt"] for sp in spans] == [0, 1, 2]
        assert all("conflict" in sp.attrs for sp in spans)


# ---------------------------------------------------------------------------
# the differential chaos schedule (acceptance criterion)
# ---------------------------------------------------------------------------

def test_chaos_schedule_parity_4_region():
    """Every fault class injected at least once; the 4-region
    scan→join→agg answers row-for-row like the fault-free run; every
    tier fallback is accounted on copr.degraded_*; and after
    disable_all() the store behaves as if nothing happened."""
    s = _build(4, floor0=True)
    want = [s.execute(q)[0].values() for q in QUERIES]
    kvbackoff.set_test_hooks(sleeper=lambda sec: None)  # no wall-clock
    d0 = _degraded()
    schedule = {
        "rpc/timeout": {"when": ("first", 2)},
        "rpc/not_leader": {"when": ("first", 2)},
        "rpc/stale_epoch": {"when": ("first", 2)},
        "rpc/server_busy": {"when": ("first", 3)},
        "copr/region_timeout": {"when": ("first", 1)},
        "copr/pack": {"when": ("first", 1)},
        "copr/drop_columnar": {"action": "return", "value": True,
                               "when": ("first", 1)},
        "cache/no_admit": {"action": "return", "value": True,
                           "when": ("first", 2)},
        "device/join": {"when": ("first", 1)},
        # the ICI collective fault drives the mesh → single-device rung,
        # which is ALSO what lets device/combine (the next rung down) be
        # reached now that the mesh tier answers multi-region combines
        "device/mesh_collective": {"when": ("first", 3)},
        "device/combine": {"when": ("first", 1)},
    }
    # drop the warmed plane cache so the faulted runs exercise the pack
    # and admission seams (a cache hit would skip both)
    from tidb_tpu.copr.plane_cache import cache_for
    cache_for(s.store).clear()
    with failpoint.failpoints(schedule):
        got = [s.execute(q)[0].values() for q in QUERIES]
        got2 = [s.execute(q)[0].values() for q in QUERIES]
        for name in schedule:
            assert failpoint.counters(name)["triggers"] >= 1, \
                f"failpoint {name} never fired"
    for q, g, w in zip(QUERIES, want, got):
        assert g == w, f"parity broke under faults on {q!r}"
    for q, g, w in zip(QUERIES, want, got2):
        assert g == w, f"parity broke on the second faulted run {q!r}"
    d1 = _degraded()
    assert d1["join_to_numpy"] > d0["join_to_numpy"], \
        "device join fault did not account a join_to_numpy fallback"
    assert d1["combine_to_host"] > d0["combine_to_host"], \
        "combine fault did not account a combine_to_host fallback"
    assert d1["mesh"] > d0["mesh"], \
        "mesh collective fault did not account a copr.degraded_mesh"
    assert d1["region_to_rows"] > d0["region_to_rows"], \
        "region pack/drop faults did not account region_to_rows fallbacks"
    # clean after disable: parity again, no further degradation
    kvbackoff.reset_test_hooks()
    d2 = _degraded()
    clean = [s.execute(q)[0].values() for q in QUERIES]
    assert clean == want
    assert _degraded() == d2, "fallbacks counted with zero failpoints on"


def test_device_tier_faults_degrade_to_cpu():
    """TpuClient rung of the chain: injected compile / OOM / readback
    faults reroute the request to the CPU engine with identical answers,
    each accounted on copr.degraded_device_to_cpu — never a statement
    error while the lower tier exists."""
    s = _build(1)
    s.execute("set global tidb_tpu_dispatch_floor = 0")
    s.execute("set global tidb_copr_backend = 'tpu'")
    client = s.store.get_client()
    q = "select count(*), sum(v), min(v), max(f) from t where v > 100"
    want = s.execute(q)[0].values()
    d0 = _degraded()["device_to_cpu"]
    fb0 = client.stats["cpu_fallbacks"]
    for fp in ("device/oom", "device/readback"):
        with failpoint.failpoints({fp: {"when": ("first", 1)}}):
            assert s.execute(q)[0].values() == want, f"{fp} broke parity"
            assert failpoint.counters(fp)["triggers"] == 1
    # compile fires only on a jit-cache MISS: use a fresh request shape
    with failpoint.failpoints({"device/compile": {"when": ("first", 1)}}):
        q2 = "select count(*), sum(v) from t where v > 101"
        row_want = s.execute("select count(*) from t where v > 101")
        assert failpoint.counters("device/compile")["triggers"] >= 1
        del row_want
        assert s.execute(q2)[0].values() is not None
    assert _degraded()["device_to_cpu"] >= d0 + 3
    assert client.stats["cpu_fallbacks"] >= fb0 + 3
    # parity one more time with everything off
    assert s.execute(q)[0].values() == want


# ---------------------------------------------------------------------------
# statement deadline under an injected hang (acceptance criterion)
# ---------------------------------------------------------------------------

def test_hang_fails_typed_within_deadline():
    s = _build(4)
    s.execute("set tidb_tpu_max_execution_time = 400")
    failpoint.enable("copr/region_scan", action="hang")
    t0 = time.monotonic()
    with pytest.raises(errors.DeadlineExceededError) as ei:
        s.execute("select count(*) from t")
    elapsed = time.monotonic() - t0
    assert elapsed < 5.0, f"deadline not enforced within budget: {elapsed}"
    assert isinstance(ei.value.history, list)  # ladder history attached
    assert not errors.is_retryable(ei.value)
    failpoint.disable_all()
    s.execute("set tidb_tpu_max_execution_time = 0")
    # the session (and the store) remain fully usable afterwards
    got = s.execute("select count(*), sum(v) from t")[0].values()
    assert int(got[0][0]) == N_ROWS


def test_ladder_storm_exhausts_one_shared_budget():
    """With ServerIsBusy injected ALWAYS, the statement's retry ladders
    spin against ONE shared budget and surface DeadlineExceededError
    carrying the server_busy ladder history — instead of N independent
    per-call budgets retrying forever."""
    s = _build(2)
    kvbackoff.set_test_hooks(sleeper=lambda sec: None)
    e0 = metrics.counter("kv.backoff_exhausted").value
    with failpoint.failpoints({"rpc/server_busy": {}}):
        with pytest.raises(errors.DeadlineExceededError) as ei:
            s.execute("select count(*) from t")
    assert any(h[0] == "server_busy" for h in ei.value.history)
    assert metrics.counter("kv.backoff_exhausted").value > e0
    # recovery: ladder clean, answers intact
    kvbackoff.reset_test_hooks()
    assert int(s.execute("select count(*) from t")[0]
               .values()[0][0]) == N_ROWS


# ---------------------------------------------------------------------------
# pending-lock regression: RETRYABLE error still drives resolve-and-retry
# under an injected StaleEpoch on the same range
# ---------------------------------------------------------------------------

def test_pending_lock_resolves_under_injected_stale_epoch():
    s = _build(2)
    kvbackoff.set_test_hooks(sleeper=lambda sec: None)
    tid = s.info_schema().table_by_name("fp", "t").info.id
    q = "select count(*), sum(v) from t"
    want = s.execute(q)[0].values()
    key = tc.encode_row_key(tid, 10)
    # crashed-writer lock (expires immediately → TTL rollback path)
    s.store.mvcc.prewrite([("put", key, b"xx")], primary=key,
                          start_ts=s.store.oracle.current_version(),
                          ttl_ms=1)
    with failpoint.failpoints({"rpc/stale_epoch": {"when": ("first", 1)}}):
        got = s.execute(q)[0].values()
        assert failpoint.counters("rpc/stale_epoch")["triggers"] == 1
    assert got == want, \
        "pending lock + injected StaleEpoch broke resolve-and-retry"
    assert key not in s.store.mvcc._locks, \
        "the RETRYABLE lock error did not drive the resolver ladder"


# ---------------------------------------------------------------------------
# server/client.py typed timeouts (satellite)
# ---------------------------------------------------------------------------

class TestClientTimeout:
    def test_handshake_read_timeout_is_typed(self):
        from tidb_tpu.server.client import Client, ClientTimeout, MySQLError
        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)  # accepts connects, never sends a greeting
            port = srv.getsockname()[1]
            t0 = time.monotonic()
            with pytest.raises(ClientTimeout) as ei:
                Client("127.0.0.1", port, timeout=0.3)
            assert time.monotonic() - t0 < 3.0
            assert isinstance(ei.value, MySQLError)
            assert ei.value.code == 2013
            assert ei.value.op == "handshake"
        finally:
            srv.close()

    def test_read_timeout_plumbed_separately(self):
        from tidb_tpu.server.client import Client, ClientTimeout
        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            port = srv.getsockname()[1]
            t0 = time.monotonic()
            with pytest.raises(ClientTimeout) as ei:
                Client("127.0.0.1", port, timeout=10.0, read_timeout=0.2)
            # the short READ timeout governed the silent handshake, not
            # the long connect timeout
            assert time.monotonic() - t0 < 5.0
            assert ei.value.seconds == 0.2
        finally:
            srv.close()
