"""Concurrency chaos: writers, readers, online DDL, and GC running
simultaneously against one store, then full consistency checks.

The reference's equivalents are the race-enabled suites (Makefile `race`
target) and the DDL-with-concurrent-writes tests (ddl/*_test.go with
Callback hooks); here threads provide the interleavings and ADMIN CHECK
TABLE + invariant queries provide the oracle.

Invariants verified at the end:
  - ADMIN CHECK TABLE passes (row/index consistency both directions)
  - the running balance total is exactly preserved across random
    transfer transactions (optimistic retry must lose no updates)
  - every row inserted by the writer threads is present exactly once
  - reads during the run never see a torn transfer (sum invariant)
"""

import random
import threading
import time

import pytest

from tidb_tpu import errors, failpoint
from tidb_tpu.session import Session, new_store
from tests.testkit import _store_id

N_ACCOUNTS = 40
START_BALANCE = 1000


@pytest.fixture
def store():
    return new_store(f"memory://chaos{next(_store_id)}")


def _session(store, db=True):
    s = Session(store)
    if db:
        s.execute("use d")
    return s


def test_concurrent_transfers_ddl_and_reads(store):
    root = Session(store)
    root.execute("create database d")
    root.execute("use d")
    root.execute("create table acct (id bigint primary key, bal bigint, "
                 "note varchar(32))")
    rows = ", ".join(f"({i}, {START_BALANCE}, 'init')"
                     for i in range(N_ACCOUNTS))
    root.execute(f"insert into acct values {rows}")
    root.execute("create table audit_log (id bigint primary key "
                 "auto_increment, who int)")

    stop = threading.Event()
    failures: list = []
    torn: list = []
    retries = {"n": 0}

    def transfer_worker(seed):
        s = _session(store)
        rng = random.Random(seed)
        for _ in range(60):
            if stop.is_set():
                return
            a, b = rng.sample(range(N_ACCOUNTS), 2)
            amt = rng.randint(1, 50)
            try:
                # one txn: debit a, credit b (retry loop inside session)
                s.execute("begin")
                s.execute(f"update acct set bal = bal - {amt} "
                          f"where id = {a}")
                s.execute(f"update acct set bal = bal + {amt} "
                          f"where id = {b}")
                s.execute("commit")
            except errors.TiDBError:
                retries["n"] += 1
                try:
                    s.execute("rollback")
                except errors.TiDBError:
                    pass

    def insert_worker(tid):
        s = _session(store)
        for i in range(50):
            if stop.is_set():
                return
            try:
                s.execute(f"insert into audit_log (id, who) values "
                          f"({tid * 1000 + i}, {tid})")
            except errors.TiDBError as e:
                failures.append(("insert", tid, i, str(e)))

    def reader_worker():
        s = _session(store)
        for _ in range(40):
            if stop.is_set():
                return
            # one retry: a read can legitimately race a schema change
            # (the reference retries those); a SECOND failure is real
            for attempt in (0, 1):
                try:
                    got = s.execute("select sum(bal) from acct")[0]                         .values()
                    total = int(got[0][0])
                    if total != N_ACCOUNTS * START_BALANCE:
                        torn.append(total)
                    break
                except errors.TiDBError as e:
                    if attempt:
                        failures.append(("read", str(e)))

    def ddl_worker():
        s = _session(store)
        ops = ["create index ib on acct (bal)",
               "alter table acct add column tag int default 7",
               "drop index ib on acct",
               "alter table acct drop column tag",
               "create index inote on acct (note)"]
        for op in ops:
            if stop.is_set():
                return
            # retryable races with in-flight txns (write conflict on a
            # reorg batch, stale schema) get 3 attempts like the
            # reference's job-queue retry; persistent failure is real
            last = None
            for _ in range(3):
                try:
                    s.execute(op)
                    last = None
                    break
                except errors.TiDBError as e:
                    last = e
            if last is not None:
                failures.append(("ddl", op, str(last)))

    threads = ([threading.Thread(target=transfer_worker, args=(i,))
                for i in range(3)]
               + [threading.Thread(target=insert_worker, args=(i,))
                  for i in range(2)]
               + [threading.Thread(target=reader_worker)]
               + [threading.Thread(target=ddl_worker)])
    for t in threads:
        t.start()
    try:
        wedged = []
        for t in threads:
            t.join(timeout=120)
            if t.is_alive():
                wedged.append(t.name)
    finally:
        stop.set()   # before any assert: a wedged worker must not keep
        #              the other (non-daemon) threads spinning forever
    assert not wedged, f"workers wedged: {wedged}"

    assert not failures, failures[:5]
    assert not torn, f"readers saw torn transfers: {torn[:5]}"

    # final invariants
    total = int(root.execute("select sum(bal) from acct")[0].values()[0][0])
    assert total == N_ACCOUNTS * START_BALANCE, \
        f"money {'appeared' if total > N_ACCOUNTS * START_BALANCE else 'vanished'}: {total}"
    n = int(root.execute("select count(*) from audit_log")[0]
            .values()[0][0])
    assert n == 100, n
    dup = root.execute("select id from audit_log group by id "
                       "having count(*) > 1")[0].values()
    assert dup == []
    root.execute("admin check table acct")
    root.execute("admin check table audit_log")
    # informational: how often the optimistic-conflict path fired (the
    # money invariant above is the correctness proof either way)
    print(f"optimistic txn conflicts retried: {retries['n']}")


def test_tpu_batch_cache_under_concurrent_writes(store):
    """Concurrent writers vs TPU-tier readers: the device batch cache is
    keyed by (ranges, data version) — a stale batch serving a newer
    snapshot (or vice versa) would break the money invariant that every
    snapshot read must see. Readers run through the pushed-aggregate TPU
    path while transfers commit; any torn sum is a cache-coherence bug
    (ops/client.py _get_batch version gating)."""
    from tidb_tpu.ops import TpuClient

    store.set_client(TpuClient(store, dispatch_floor_rows=0))
    root = Session(store)
    root.execute("create database d")
    root.execute("use d")
    root.execute("create table acct (id bigint primary key, bal bigint)")
    rows = ", ".join(f"({i}, {START_BALANCE})" for i in range(N_ACCOUNTS))
    root.execute(f"insert into acct values {rows}")

    stop = threading.Event()
    torn: list = []
    failures: list = []

    def transfer_worker(seed):
        s = _session(store)
        rng = random.Random(seed)
        for _ in range(40):
            if stop.is_set():
                return
            a, b = rng.sample(range(N_ACCOUNTS), 2)
            amt = rng.randint(1, 50)
            try:
                s.execute("begin")
                s.execute(f"update acct set bal = bal - {amt} "
                          f"where id = {a}")
                s.execute(f"update acct set bal = bal + {amt} "
                          f"where id = {b}")
                s.execute("commit")
            except errors.TiDBError:
                try:
                    s.execute("rollback")
                except errors.TiDBError:
                    pass

    def tpu_reader():
        s = _session(store)
        for _ in range(30):
            if stop.is_set():
                return
            try:
                got = s.execute("select sum(bal), count(*) from acct")[0] \
                    .values()
                total, n = int(got[0][0]), int(got[0][1])
                if total != N_ACCOUNTS * START_BALANCE or n != N_ACCOUNTS:
                    torn.append((total, n))
            except errors.TiDBError as e:
                failures.append(str(e))

    threads = ([threading.Thread(target=transfer_worker, args=(i,))
                for i in range(2)]
               + [threading.Thread(target=tpu_reader) for _ in range(2)])
    for t in threads:
        t.start()
    try:
        wedged = [t.name for t in threads if (t.join(timeout=180),
                                              t.is_alive())[1]]
    finally:
        stop.set()
    assert not wedged, wedged
    assert not failures, failures[:3]
    assert not torn, f"TPU reads saw torn snapshots: {torn[:5]}"
    client = store.get_client()
    assert client.stats["tpu_requests"] > 0, "readers never hit the TPU tier"
    total = int(root.execute("select sum(bal) from acct")[0].values()[0][0])
    assert total == N_ACCOUNTS * START_BALANCE


def test_chaos_with_failpoints_active():
    """The original chaos shape run WITH a seeded failpoint schedule live
    mid-run — region timeouts, ServerIsBusy storms, and device-dispatch
    failures injected probabilistically under concurrent transfers,
    inserts, and TPU-tier readers — and the same four end-state
    invariants: money conserved, every insert present exactly once, no
    torn reads, ADMIN CHECK TABLE clean. Injected faults are RECOVERED
    faults: the retry ladder absorbs the region errors and the
    degradation chain absorbs the device errors, so the workload's
    observable behavior is unchanged."""
    from tidb_tpu.kv import backoff as kvbackoff
    from tidb_tpu.ops import TpuClient

    store = new_store(f"cluster://3/chaosfp{next(_store_id)}")
    store.set_client(TpuClient(store, dispatch_floor_rows=0))
    root = Session(store)
    root.execute("create database d")
    root.execute("use d")
    root.execute("create table acct (id bigint primary key, bal bigint)")
    rows = ", ".join(f"({i}, {START_BALANCE})" for i in range(N_ACCOUNTS))
    root.execute(f"insert into acct values {rows}")
    root.execute("create table audit_log (id bigint primary key, who int)")

    # mesh rung under chaos: a second cluster store (fan-out client — no
    # TpuClient in front, so regions answer per-region columnar partials)
    # runs the 4-region scan→join→agg shape whose partial-aggregate
    # combine rides the device mesh; the seeded device/mesh_collective
    # fault drives the mesh → single-device degradation mid-run with
    # unchanged answers
    from tidb_tpu import tablecodec as tc
    fan_store = new_store(f"cluster://3/chaosmesh{next(_store_id)}")
    fs = Session(fan_store)
    fs.execute("create database m")
    fs.execute("use m")
    fs.execute("create table t (id bigint primary key, k bigint, "
               "v bigint)")
    fs.execute("insert into t values " + ", ".join(
        f"({i}, {i % 5}, {i * 3})" for i in range(1, 161)))
    fs.execute("create table fd (d_k bigint primary key)")
    fs.execute("insert into fd values (0), (1), (2), (3), (4)")
    fan_tid = fs.info_schema().table_by_name("m", "t").info.id
    fan_store.cluster.split_keys(
        [tc.encode_row_key(fan_tid, 40 * i + 1) for i in range(1, 4)])
    FAN_Q = ("select count(*), sum(t.v), min(t.v), max(t.k) "
             "from t join fd on t.k = fd.d_k")
    fan_want = fs.execute(FAN_Q)[0].values()
    fan_diverged: list = []

    stop = threading.Event()
    torn: list = []
    failures: list = []

    def mesh_reader():
        s = _session(fan_store, db=False)
        s.execute("use m")
        for _ in range(12):
            if stop.is_set():
                return
            try:
                got = s.execute(FAN_Q)[0].values()
                if got != fan_want:
                    fan_diverged.append(got)
            except errors.TiDBError as e:
                failures.append(("mesh_read", str(e)))

    def transfer_worker(seed):
        s = _session(store)
        rng = random.Random(seed)
        for _ in range(25):
            if stop.is_set():
                return
            a, b = rng.sample(range(N_ACCOUNTS), 2)
            amt = rng.randint(1, 50)
            try:
                s.execute("begin")
                s.execute(f"update acct set bal = bal - {amt} "
                          f"where id = {a}")
                s.execute(f"update acct set bal = bal + {amt} "
                          f"where id = {b}")
                s.execute("commit")
            except errors.TiDBError:
                # injected fault storms may exhaust a statement budget —
                # a rolled-back transfer preserves the money invariant
                try:
                    s.execute("rollback")
                except errors.TiDBError:
                    pass

    def insert_worker(tid):
        s = _session(store)
        for i in range(30):
            if stop.is_set():
                return
            # inserts must land EXACTLY once despite injected faults:
            # retry until success; a duplicate-key error proves the
            # earlier attempt already applied
            for _attempt in range(50):
                try:
                    s.execute(f"insert into audit_log values "
                              f"({tid * 1000 + i}, {tid})")
                    break
                except errors.DupEntryError:
                    break
                except errors.TiDBError:
                    continue
            else:
                failures.append(("insert", tid, i))

    def tpu_reader():
        s = _session(store)
        for _ in range(15):
            if stop.is_set():
                return
            for attempt in (0, 1, 2):
                try:
                    got = s.execute(
                        "select sum(bal), count(*) from acct")[0].values()
                    total, n = int(got[0][0]), int(got[0][1])
                    if total != N_ACCOUNTS * START_BALANCE \
                            or n != N_ACCOUNTS:
                        torn.append((total, n))
                    break
                except errors.TiDBError as e:
                    if attempt == 2:
                        failures.append(("read", str(e)))

    # scale backoff sleeps down so injected storms retry fast, and seed
    # every probability so the schedule replays
    kvbackoff.set_test_hooks(sleeper=lambda s: time.sleep(min(s, 0.002)))
    failpoint.enable("rpc/server_busy", when=("prob", 0.03), seed=11)
    failpoint.enable("rpc/timeout", when=("prob", 0.01), seed=12)
    failpoint.enable("copr/region_timeout", when=("prob", 0.05), seed=13)
    failpoint.enable("device/oom", when=("prob", 0.10), seed=14)
    failpoint.enable("device/mesh_collective", when=("prob", 0.30),
                     seed=15)
    threads = ([threading.Thread(target=transfer_worker, args=(i,))
                for i in range(2)]
               + [threading.Thread(target=insert_worker, args=(1,))]
               + [threading.Thread(target=tpu_reader)]
               + [threading.Thread(target=mesh_reader)])
    evals = {}
    try:
        for t in threads:
            t.start()
        wedged = []
        for t in threads:
            t.join(timeout=120)
            if t.is_alive():
                wedged.append(t.name)
    finally:
        stop.set()
        # snapshot BEFORE disable_all: counters read zeros once disabled
        evals = {name: failpoint.counters(name)["evals"]
                 for name in ("rpc/server_busy", "copr/region_timeout",
                              "device/oom", "device/mesh_collective")}
        failpoint.disable_all()
        kvbackoff.reset_test_hooks()
    assert not wedged, f"workers wedged under failpoints: {wedged}"
    assert not failures, failures[:5]
    assert not torn, f"readers saw torn transfers: {torn[:5]}"
    assert not fan_diverged, \
        f"mesh reader diverged under mesh-collective faults: " \
        f"{fan_diverged[:3]}"
    # the schedule really ran: each fault class was evaluated at its seam
    # (probabilistic firing may legitimately be 0 for a short run, but a
    # never-EVALUATED site means the injection seam regressed)
    for name, n in evals.items():
        assert n > 0, f"failpoint seam {name} was never reached"
    # end-state invariants, fault-free verification pass
    total = int(root.execute("select sum(bal) from acct")[0]
                .values()[0][0])
    assert total == N_ACCOUNTS * START_BALANCE, \
        f"money {'appeared' if total > N_ACCOUNTS * START_BALANCE else 'vanished'}: {total}"
    n = int(root.execute("select count(*) from audit_log")[0]
            .values()[0][0])
    assert n == 30, n
    dup = root.execute("select id from audit_log group by id "
                       "having count(*) > 1")[0].values()
    assert dup == []
    root.execute("admin check table acct")
    root.execute("admin check table audit_log")


def test_digest_summary_reconciles_under_flush_chaos():
    """The workload-aggregation layer under concurrency + injected flush
    faults: three sessions run a known per-thread statement schedule on
    a 4-region store while a chaos thread ages the summary window to
    force rotations and a `summary/flush` failpoint probabilistically
    fails them. Contract: an injected flush fault DEFERS the rotation
    (the window extends) and never fails a statement or drops a count —
    per-digest exec counts summed across ALL windows (history + current)
    must equal the deterministic schedule exactly AND reconcile with the
    flat perfschema.digest_statements process counter, with no
    cross-session bleed."""
    from tidb_tpu import digest, metrics, perfschema, tablecodec as tc

    store = new_store(f"cluster://3/chaosdg{next(_store_id)}")
    root = Session(store)
    root.execute("create database d")
    root.execute("use d")
    root.execute("create table t (id bigint primary key, k bigint, "
                 "v bigint)")
    root.execute("insert into t values " +
                 ", ".join(f"({i}, {i % 7}, {i * 10})"
                           for i in range(1, 121)))
    tid = root.info_schema().table_by_name("d", "t").info.id
    store.cluster.split_keys([tc.encode_row_key(tid, 30 * i + 1)
                              for i in range(1, 4)])
    sessions = [_session(store) for _ in range(3)]
    ds = perfschema.perf_for(store).digest_summary
    # fresh window, nothing recorded for the reset itself
    ds.set_enabled(False)
    ds.set_enabled(True)
    c0 = metrics.counter("perfschema.digest_statements").value
    flush0 = metrics.counter("perfschema.digest_windows_flushed").value
    defer0 = metrics.counter("perfschema.digest_flush_errors").value

    # per-thread schedule: a SHARED shape (point read, literal variants)
    # plus one thread-UNIQUE shape — bleed in either direction breaks an
    # exact count below
    shared_counts = (11, 7, 5)
    unique_shapes = ("select v from t where k = %d",
                     "select k, v from t where id = %d",
                     "select sum(v) from t where id > %d")
    unique_counts = (4, 6, 8)
    stop = threading.Event()
    errs: list = []
    barrier = threading.Barrier(4)

    def worker(i):
        s = sessions[i]
        try:
            barrier.wait(timeout=30)
            for n in range(shared_counts[i]):
                s.execute(f"select v from t where id = {i * 40 + n + 1}")
            for n in range(unique_counts[i]):
                s.execute(unique_shapes[i] % n)
        except Exception as e:
            errs.append(e)

    def rotator():
        # age the current window past the refresh interval repeatedly so
        # rotations happen DURING the workload, racing the failpoint
        barrier.wait(timeout=30)
        for _ in range(12):
            if stop.is_set():
                return
            with ds.lock:
                ds.window_begin -= ds.refresh_interval_s + 1
            time.sleep(0.01)

    failpoint.enable("summary/flush", when=("prob", 0.5), seed=42)
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(3)] + [threading.Thread(target=rotator)]
    try:
        for t in threads:
            t.start()
        wedged = []
        for t in threads:
            t.join(timeout=120)
            if t.is_alive():
                wedged.append(t.name)
    finally:
        stop.set()
        fp_evals = failpoint.counters("summary/flush")
        failpoint.disable("summary/flush")
    assert not wedged, wedged
    assert not errs, errs[:3]
    assert fp_evals["evals"] > 0, "summary/flush seam never reached"

    # reconcile across EVERY window: nothing lost to rotation or to an
    # injected flush failure, nothing double-counted
    per_digest: dict = {}
    for _b, _e, entries, ed, ee in ds.windows():
        assert ed == 0 and ee == 0   # nothing evicted in this schedule
        for dig, e in entries.items():
            per_digest[dig] = per_digest.get(dig, 0) + e.exec_count
    shared_dig = digest.sql_digest("select v from t where id = 1")[0]
    assert per_digest.get(shared_dig) == sum(shared_counts)
    for i, shape in enumerate(unique_shapes):
        dig = digest.sql_digest(shape % 0)[0]
        assert per_digest.get(dig) == unique_counts[i], \
            f"thread-{i} unique shape bled: {per_digest.get(dig)}"
    recorded = metrics.counter("perfschema.digest_statements").value - c0
    assert sum(per_digest.values()) == recorded == \
        sum(shared_counts) + sum(unique_counts)
    # the chaos actually exercised both sides of the flush seam:
    # rotations happened AND at least one injected fault deferred one
    flushed = metrics.counter(
        "perfschema.digest_windows_flushed").value - flush0
    deferred = metrics.counter(
        "perfschema.digest_flush_errors").value - defer0
    assert flushed > 0, "no window ever rotated under the chaos schedule"
    assert deferred > 0, "the summary/flush failpoint never deferred"


def test_micro_batch_window_chaos_degrades_to_solo():
    """The micro-batch gather window under chaos: sched/batch_window
    fires probabilistically (sleep — a stalled leader) while concurrent
    sessions hammer below-floor statements. Followers that outwait a
    stalled leader reclaim their entries and answer through the SOLO
    route — answers never change, and every degradation is counted on
    copr.degraded_batch."""
    from tidb_tpu import metrics
    from tidb_tpu.ops import TpuClient

    store = new_store(f"memory://chaosmb{next(_store_id)}")
    root = Session(store)
    root.execute("set global tidb_slow_log_threshold = 0")
    root.execute("create database d")
    root.execute("use d")
    root.execute("create table bt (id bigint primary key, v bigint)")
    root.execute("insert into bt values " + ", ".join(
        f"({i}, {i % 40})" for i in range(1, 1501)))
    store.set_client(TpuClient(store, dispatch_floor_rows=1 << 20))
    client = store.get_client()
    client.batch_window_ms = 15
    root.execute("select id from bt where v = 0")   # pack warm

    # oracle answers via the solo route (kill switch)
    client.micro_batch = False
    queries = [f"select id, v from bt where v = {k}" for k in range(12)]
    want = {q: root.execute(q)[0].values() for q in queries}
    client.micro_batch = True

    diverged, failures = [], []
    lock = threading.Lock()

    def reader(i):
        s = _session(store)
        rng = random.Random(500 + i)
        for _ in range(10):
            q = queries[rng.randrange(len(queries))]
            try:
                got = s.execute(q)[0].values()
                if got != want[q]:
                    with lock:
                        diverged.append(q)
            except errors.TiDBError as e:
                with lock:
                    failures.append(str(e))

    d0 = metrics.counter("copr.degraded_batch").value
    failpoint.enable("sched/batch_window", action="sleep", seconds=0.3,
                     when=("prob", 0.5), seed=23)
    try:
        threads = [threading.Thread(target=reader, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        evals = failpoint.counters("sched/batch_window")["evals"]
    finally:
        failpoint.disable_all()
    degraded = metrics.counter("copr.degraded_batch").value - d0
    assert evals > 0, "the gather-window fault seam was never reached"
    assert not failures, failures[:3]
    assert not diverged, \
        f"stalled-window degradation changed answers: {diverged[:3]}"
    assert degraded > 0, \
        "stalled windows never counted on copr.degraded_batch"
    # chaos off: batching itself still works (a fresh concurrent burst
    # shares a dispatch again)
    b0 = metrics.counter("sched.batched_dispatches").value
    barrier = threading.Barrier(4)
    sess = [_session(store) for _ in range(4)]

    def burst(i):
        barrier.wait()
        sess[i].execute(queries[i])
    threads = [threading.Thread(target=burst, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert metrics.counter("sched.batched_dispatches").value > b0
