"""ENUM / SET / BIT / HEX through the full SQL surface (round-3 verdict
missing #6): DDL with elems, insert by name / index / number, predicate
semantics (names vs strings, indices vs numbers), sorting by index,
aggregates, indexes over enum columns, and wire output.

Reference: util/types/{enum,set,bit,hex}.go; parser/parser.y enum/set
column productions; tablecodec flatten/unflatten contract.
"""

import pytest

from tidb_tpu import errors
from tidb_tpu.session import Session, new_store


@pytest.fixture
def s():
    from tests.testkit import _store_id
    s = Session(new_store(f"memory://enumsql{next(_store_id)}"))
    s.execute("create database d; use d")
    s.execute("create table t (id bigint primary key, "
              "c enum('red','green','blue'), s set('a','b','c'), "
              "b bit(8))")
    s.execute("insert into t values "
              "(1, 'green', 'a,c', b'1010'), "
              "(2, 2, 5, 10), "
              "(3, 'BLUE', '', 0), "
              "(4, null, null, null)")
    return s


def test_storage_and_display(s):
    rows = s.execute("select id, c, s, b from t order by id")[0].rows
    shown = [[None if d.is_null() else str(d.val) for d in r] for r in rows]
    assert shown == [
        ["1", "green", "a,c", "0b00001010"],
        ["2", "green", "a,c", "0b00001010"],   # by index/number
        ["3", "blue", "", "0b00000000"],       # case-insensitive item match
        ["4", None, None, None]]


def test_predicates(s):
    q = lambda sql: s.execute(sql)[0].values()
    assert q("select id from t where c = 'green' order by id") == [[1], [2]]
    assert q("select id from t where c != 'green' order by id") == [[3]]
    assert q("select id from t where c > 1 order by id") == [[1], [2], [3]]
    assert q("select id from t where s = 'a,c' order by id") == [[1], [2]]
    assert q("select id from t where b = 10 order by id") == [[1], [2]]
    assert q("select id from t where c is null") == [[4]]


def test_enum_sorts_by_index_not_name(s):
    # green(2) < blue(3) although 'blue' < 'green' lexicographically
    assert s.execute("select id from t order by c, id")[0].values() == \
        [[4], [1], [2], [3]]


def test_aggregates(s):
    assert s.execute("select count(distinct c) from t")[0].values() == [[2]]
    mx = s.execute("select max(c), min(c) from t")[0].rows[0]
    assert str(mx[0].val) == "blue" and str(mx[1].val) == "green"
    g = s.execute("select c, count(*) from t group by c order by c")[0].rows
    assert [None if r[0].is_null() else str(r[0].val) for r in g] == \
        [None, "green", "blue"]


def test_invalid_values_rejected(s):
    with pytest.raises(errors.TiDBError):
        s.execute("insert into t values (9, 'yellow', null, null)")
    with pytest.raises(errors.TiDBError):
        s.execute("insert into t values (9, 9, null, null)")   # > 3 items
    with pytest.raises(errors.TiDBError):
        s.execute("insert into t values (9, null, 'z', null)")
    with pytest.raises(errors.TiDBError):
        s.execute("insert into t values (9, null, null, 256)")  # > BIT(8)
    # negatives must overflow like the reference's uint64 parse — never
    # wrap through Python's negative indexing into a live element
    with pytest.raises(errors.TiDBError):
        s.execute("insert into t values (9, -1, null, null)")
    with pytest.raises(errors.TiDBError):
        s.execute("insert into t values (9, null, -1, null)")
    with pytest.raises(errors.TiDBError):
        s.execute("insert into t values (9, null, null, -1)")


def test_index_on_enum_column(s):
    s.execute("create index ic on t (c)")
    s.execute("admin check table t")
    assert s.execute("select id from t use index (ic) where c = 'green' "
                     "order by id")[0].values() == [[1], [2]]


def test_update_and_cast(s):
    s.execute("update t set c = 'red' where id = 2")
    assert s.execute("select id from t where c = 'red'")[0].values() == [[2]]
    # enum → int cast context: numeric value is the index
    assert s.execute("select id + 0 from t where c = 'red'")[0] \
        .values() == [[2]]


def test_hex_bit_literals():
    s = Session(new_store("memory://hexlit"))
    s.execute("create database d; use d")
    r = s.execute("select 0x41 + 1, x'4142', b'01000001'")[0].rows[0]
    assert r[0].as_number() == 66            # numeric context
    assert r[1].get_string() == "AB"         # string context
    assert r[2].as_number() == 65
    # string functions see the bytes; comparisons see the dual nature
    assert s.execute("select length(x'4142')")[0].values() == [[2]]
    assert s.execute("select 1 where 0x41 = 'A'")[0].values() == [[1]]
    assert s.execute("select 1 where 0x41 = 65")[0].values() == [[1]]


def test_show_create_table_renders_elems(s):
    out = s.execute("show create table t")[0].values()[0][1]
    assert "enum('red','green','blue')" in out
    assert "set('a','b','c')" in out
    assert "bit(8)" in out


def test_wire_text_output():
    """Over the real socket: enum/set as names, bit as binary string."""
    from tests.testkit import _store_id
    from tidb_tpu.server import Client, Server

    store = new_store(f"memory://enumwire{next(_store_id)}")
    server = Server(store)
    server.start()
    try:
        c = Client("127.0.0.1", server.port)
        c.query("create database d")
        c.query("use d")
        c.query("create table t "
                "(id bigint primary key, c enum('x','y'), b bit(8))")
        c.query("insert into t values (1, 'y', 65)")
        rows = c.query("select c, b from t")[0].rows
        assert rows == [["y", "A"]]
    finally:
        server.close()
