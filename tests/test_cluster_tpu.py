"""TPU coprocessor over the DISTRIBUTED cluster store: the round-1 gap
where the TPU engine and the cluster tier "were two silos that had never
met". The TPU tier packs columnar batches through the cluster SNAPSHOT —
region routing, leader failover and lock resolution live below it — and
the CPU fallback is the region fan-out DistCoprClient.

Covers: full differential parity on cluster+TPU vs cluster+CPU, batch
cache versioning across writes, and splits / leader changes mid-query.
"""

import pytest

from tidb_tpu.ops import TpuClient
from tidb_tpu.session import Session, new_store


ROWS = ("(1, 10, 'x', 1.5, '2024-01-15'), "
        "(2, 20, 'y', 2.5, '2024-02-10'), "
        "(3, 30, 'x', 3.5, '2024-03-01'), "
        "(4, 40, 'z', null, '2024-04-20'), "
        "(5, 50, 'y', 4.5, null), "
        "(6, 30, null, 0.5, '2024-01-01'), "
        "(7, -5, 'xx', -1.5, '2023-12-31')")


def _setup(store):
    s = Session(store)
    s.execute("create database test")
    s.execute("use test")
    s.execute("create table t (id bigint primary key, a int, "
              "b varchar(32), c double, d date)")
    s.execute(f"insert into t values {ROWS}")
    return s


@pytest.fixture(scope="module")
def sessions():
    cpu_store = new_store("cluster://3/ctpu_cpu")
    tpu_store = new_store("cluster://3/ctpu_tpu")
    tpu_store.set_client(TpuClient(tpu_store, dispatch_floor_rows=0))
    return _setup(cpu_store), _setup(tpu_store)


QUERIES = [
    "select id from t where a > 25 order by id",
    "select id from t where b in ('x', 'z') order by id",
    "select count(*), sum(a), min(a), max(a) from t",
    "select sum(c), avg(c) from t",
    "select count(distinct a) from t",
    "select b, count(*), sum(a), min(c), max(c) from t group by b order by b",
    "select a, count(*) from t group by a order by a",
    "select b, a from t group by b order by b",
    "select id from t order by a desc limit 3",
]


def _norm(rows):
    from decimal import Decimal
    out = []
    for row in rows:
        nr = []
        for v in row:
            if isinstance(v, Decimal):
                nr.append(float(v))
            elif isinstance(v, bytes):
                nr.append(v.decode())
            elif isinstance(v, float):
                nr.append(round(v, 9))
            else:
                nr.append(v)
        out.append(nr)
    return out


@pytest.mark.parametrize("sql", QUERIES)
def test_cluster_parity(sessions, sql):
    cpu, tpu = sessions
    assert _norm(cpu.execute(sql)[0].values()) == \
        _norm(tpu.execute(sql)[0].values()), sql


def test_tpu_engine_used_on_cluster(sessions):
    _, tpu = sessions
    client = tpu.store.get_client()
    assert isinstance(client, TpuClient)
    assert client.stats["tpu_requests"] > 0


def test_split_and_leader_change_mid_session(sessions):
    """Topology changes move no data: the columnar cache stays valid and
    queries keep answering through the new region shape."""
    from tidb_tpu import tablecodec as tc
    _, tpu = sessions
    store = tpu.store
    client = store.get_client()
    before = client.stats["tpu_requests"]

    total0 = tpu.execute("select count(*), sum(a) from t")[0].values()

    tbl = tpu.info_schema().table_by_name("test", "t")
    store.cluster.split_keys([tc.encode_row_key(tbl.info.id, 3),
                              tc.encode_row_key(tbl.info.id, 6)])
    assert tpu.execute("select count(*), sum(a) from t")[0].values() == total0

    for region in list(store.cluster.regions):
        peers = [p.store_id for p in region.peers]
        if len(peers) > 1:
            other = next(p for p in peers
                         if p != region.leader_store_id)
            store.cluster.change_leader(region.region_id, other)
    assert tpu.execute("select count(*), sum(a) from t")[0].values() == total0
    assert client.stats["tpu_requests"] > before


def test_write_invalidates_columnar_cache(sessions):
    """data_version_at must bump on commit so the TPU batch cache never
    serves stale rows."""
    _, tpu = sessions
    n0 = tpu.execute("select count(*) from t")[0].values()[0][0]
    tpu.execute("insert into t values (100, 999, 'new', 9.9, '2025-01-01')")
    assert tpu.execute("select count(*) from t")[0].values() == [[n0 + 1]]
    assert tpu.execute("select a from t where id = 100")[0].values() == \
        [[999]]
    tpu.execute("delete from t where id = 100")
    assert tpu.execute("select count(*) from t")[0].values() == [[n0]]


def test_mesh_on_cluster(sessions):
    """Flat-batch mesh sharding over cluster data: partial aggregates
    combine across the 8 virtual devices, results match the CPU engine."""
    from tidb_tpu.parallel import CoprMesh
    cpu, _ = sessions
    store = new_store("cluster://3/ctpu_mesh")
    store.set_client(TpuClient(store, mesh=CoprMesh(), dispatch_floor_rows=0))
    s = _setup(store)
    for sql in ["select count(*), sum(a), min(a), max(a) from t",
                "select b, count(*), sum(a) from t group by b order by b"]:
        assert _norm(cpu.execute(sql)[0].values()) == \
            _norm(s.execute(sql)[0].values()), sql
    assert store.get_client().stats["tpu_requests"] > 0
