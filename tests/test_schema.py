"""Schema layer tests: meta/structure roundtrips, DDL state machine,
table read/write paths. Mirrors meta/, ddl/ suites in the reference."""

import pytest

from tidb_tpu import errors, mysqldef as my
from tidb_tpu.ddl import ColumnSpec, IndexSpec
from tidb_tpu.domain import Domain
from tidb_tpu.localstore import LocalStore
from tidb_tpu.meta import Meta
from tidb_tpu.model import DBInfo, SchemaState
from tidb_tpu.structure import TxStructure
from tidb_tpu.types import Datum, datum_from_py
from tidb_tpu.types.datum import NULL
from tidb_tpu.types.field_type import new_field_type


def _ft(tp, flag=0, flen=-1, dec=-1):
    ft = new_field_type(tp)
    ft.flag |= flag
    if flen >= 0:
        ft.flen = flen
    if dec >= 0:
        ft.decimal = dec
    return ft


@pytest.fixture
def store():
    return LocalStore()


@pytest.fixture
def domain(store):
    return Domain(store)


def test_structure_string_hash_list(store):
    txn = store.begin()
    t = TxStructure(txn, txn)
    t.set(b"s", b"v")
    assert t.get(b"s") == b"v"
    assert t.inc(b"ctr", 5) == 5
    assert t.inc(b"ctr") == 6

    t.hset(b"h", b"f1", b"a")
    t.hset(b"h", b"f2", b"b")
    assert t.hget(b"h", b"f1") == b"a"
    assert dict(t.hgetall(b"h")) == {b"f1": b"a", b"f2": b"b"}
    t.hdel(b"h", b"f1")
    assert t.hget(b"h", b"f1") is None

    t.rpush(b"l", b"x")
    t.rpush(b"l", b"y")
    assert t.llen(b"l") == 2
    assert t.lindex(b"l", 0) == b"x"
    t.lset(b"l", 0, b"x2")
    assert t.lpop(b"l") == b"x2"
    assert t.lpop(b"l") == b"y"
    assert t.lpop(b"l") is None
    txn.commit()


def test_meta_ids_and_dbs(store):
    txn = store.begin()
    m = Meta(txn)
    assert m.gen_global_id() == 1
    assert m.gen_global_ids(3) == [2, 3, 4]
    m.create_database(DBInfo(id=10, name="test"))
    assert m.get_database(10).name == "test"
    with pytest.raises(errors.DBExistsError):
        m.create_database(DBInfo(id=10, name="test"))
    assert [d.name for d in m.list_databases()] == ["test"]
    txn.commit()


def _create_test_table(domain, name="t", with_index=False):
    domain.ddl.create_schema("test")
    cols = [
        ColumnSpec("id", _ft(my.TypeLonglong)),
        ColumnSpec("v", _ft(my.TypeVarchar, flen=64)),
        ColumnSpec("n", _ft(my.TypeLong), default_value=7, has_default=True),
    ]
    idxs = [IndexSpec("primary", ["id"], primary=True)]
    if with_index:
        idxs.append(IndexSpec("idx_v", ["v"]))
    domain.ddl.create_table("test", name, cols, idxs)
    return domain.info_schema().table_by_name("test", name)


def test_ddl_create_schema_table(domain):
    tbl = _create_test_table(domain)
    assert tbl.info.pk_is_handle
    assert [c.name for c in tbl.info.columns] == ["id", "v", "n"]
    assert domain.info_schema().version >= 2
    with pytest.raises(errors.TableExistsError):
        domain.ddl.create_table("test", "t", [ColumnSpec("x", _ft(my.TypeLong))], [])
    with pytest.raises(errors.DBExistsError):
        domain.ddl.create_schema("test")


def test_table_crud(domain, store):
    tbl = _create_test_table(domain, with_index=True)
    txn = store.begin()
    row = [Datum.i64(1), datum_from_py("hello"), Datum.i64(42)]
    h = tbl.add_record(txn, row)
    assert h == 1  # pk-is-handle
    txn.commit()

    snap = store.get_snapshot()
    got = tbl.row_with_cols(snap, 1)
    assert got[0].get_int() == 1
    assert got[1].get_string() == "hello"
    assert got[2].get_int() == 42

    # duplicate pk
    txn = store.begin()
    with pytest.raises(errors.KeyExistsError):
        tbl.add_record(txn, row)
        txn.commit()
    txn.rollback()

    # update moves index entry
    txn = store.begin()
    new_row = [Datum.i64(1), datum_from_py("world"), Datum.i64(43)]
    tbl.update_record(txn, 1, got, new_row)
    txn.commit()
    snap = store.get_snapshot()
    idx = tbl.indices[0]
    entries = list(idx.iterate(snap))
    assert entries[0][0][0].get_bytes() == b"world"
    assert entries[0][1] == 1

    # delete
    txn = store.begin()
    tbl.remove_record(txn, 1, new_row)
    txn.commit()
    snap = store.get_snapshot()
    assert list(tbl.iter_records(snap)) == []
    assert list(idx.iterate(snap)) == []


def test_auto_increment_handles(domain, store):
    domain.ddl.create_schema("test")
    domain.ddl.create_table("test", "t", [ColumnSpec("v", _ft(my.TypeLong))], [])
    tbl = domain.info_schema().table_by_name("test", "t")
    txn = store.begin()
    h1 = tbl.add_record(txn, [Datum.i64(10)])
    h2 = tbl.add_record(txn, [Datum.i64(20)])
    txn.commit()
    assert h2 == h1 + 1
    rows = list(tbl.iter_records(store.get_snapshot()))
    assert [r[0] for r in rows] == [h1, h2]


def test_add_index_with_backfill(domain, store):
    tbl = _create_test_table(domain)
    txn = store.begin()
    for i in range(700):  # multiple reorg batches (REORG_BATCH_SIZE=256)
        tbl.add_record(txn, [Datum.i64(i), datum_from_py(f"v{i % 10}"), Datum.i64(i)])
    txn.commit()

    domain.ddl.create_index("test", "t", "idx_v", ["v"])
    tbl2 = domain.info_schema().table_by_name("test", "t")
    idx = next(i for i in tbl2.indices if i.info.name == "idx_v")
    assert idx.info.state == SchemaState.PUBLIC
    entries = list(idx.iterate(store.get_snapshot()))
    assert len(entries) == 700
    # index order: v0, v0, ..., v1 ...
    vals = [e[0][0].get_bytes() for e in entries]
    assert vals == sorted(vals)

    # unique index over duplicate data must fail and cancel the job
    with pytest.raises(errors.TiDBError):
        domain.ddl.create_index("test", "t", "uniq_v", ["v"], unique=True)


def test_drop_index(domain, store):
    tbl = _create_test_table(domain, with_index=True)
    txn = store.begin()
    tbl.add_record(txn, [Datum.i64(1), datum_from_py("a"), Datum.i64(0)])
    txn.commit()
    domain.ddl.drop_index("test", "t", "idx_v")
    tbl2 = domain.info_schema().table_by_name("test", "t")
    assert tbl2.info.find_index("idx_v") is None
    # index data gone
    from tidb_tpu import tablecodec as tc
    prefix = tc.table_index_prefix(tbl.id)
    assert list(store.get_snapshot().iterate(prefix, prefix + b"\xff" * 12)) == []


def test_add_drop_column(domain, store):
    tbl = _create_test_table(domain)
    txn = store.begin()
    tbl.add_record(txn, [Datum.i64(1), datum_from_py("a"), Datum.i64(5)])
    txn.commit()

    domain.ddl.add_column("test", "t", ColumnSpec(
        "extra", _ft(my.TypeLong), default_value=99, has_default=True))
    tbl2 = domain.info_schema().table_by_name("test", "t")
    assert [c.name for c in tbl2.info.columns] == ["id", "v", "n", "extra"]
    # old row: extra reads as original default 99
    row = tbl2.row_with_cols(store.get_snapshot(), 1)
    assert row[3].get_int() == 99
    # new row stores the column
    txn = store.begin()
    tbl2.add_record(txn, [Datum.i64(2), datum_from_py("b"), Datum.i64(6), Datum.i64(100)])
    txn.commit()
    row2 = tbl2.row_with_cols(store.get_snapshot(), 2)
    assert row2[3].get_int() == 100

    domain.ddl.drop_column("test", "t", "extra")
    tbl3 = domain.info_schema().table_by_name("test", "t")
    assert [c.name for c in tbl3.info.columns] == ["id", "v", "n"]
    assert len(tbl3.row_with_cols(store.get_snapshot(), 2)) == 3


def test_drop_table_and_truncate(domain, store):
    tbl = _create_test_table(domain)
    txn = store.begin()
    tbl.add_record(txn, [Datum.i64(1), datum_from_py("a"), Datum.i64(0)])
    txn.commit()

    old_id = tbl.id
    domain.ddl.truncate_table("test", "t")
    tbl2 = domain.info_schema().table_by_name("test", "t")
    assert tbl2.id != old_id
    assert list(tbl2.iter_records(store.get_snapshot())) == []

    domain.ddl.drop_table("test", "t")
    assert not domain.info_schema().table_exists("test", "t")
    with pytest.raises(errors.NoSuchTableError):
        domain.info_schema().table_by_name("test", "t")


def test_drop_schema(domain, store):
    _create_test_table(domain)
    domain.ddl.drop_schema("test")
    assert not domain.info_schema().schema_exists("test")
    with pytest.raises(errors.BadDBError):
        domain.ddl.drop_schema("test")


def test_unsigned_bigint_pk_not_handle(domain, store):
    domain.ddl.create_schema("test")
    domain.ddl.create_table("test", "u", [
        ColumnSpec("id", _ft(my.TypeLonglong, flag=my.UnsignedFlag)),
        ColumnSpec("v", _ft(my.TypeLong)),
    ], [IndexSpec("primary", ["id"], primary=True)])
    tbl = domain.info_schema().table_by_name("test", "u")
    # unsigned pk must NOT become the row handle (would wrap at 2^63)
    assert not tbl.info.pk_is_handle
    txn = store.begin()
    big = (1 << 63) + 5
    tbl.add_record(txn, [Datum.u64(big), Datum.i64(1)])
    txn.commit()
    rows = list(tbl.iter_records(store.get_snapshot()))
    assert len(rows) == 1
    assert rows[0][1][0].get_int() == big


def test_allocator_rebase_respects_meta_cursor():
    """A second allocator rebasing below an already-advanced meta cursor
    must not re-dispense ids from the first allocator's cached range."""
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.table.autoid import Allocator

    store = new_store("memory://autoid_rebase")
    s = Session(store)
    s.execute("create database autoid_t")
    s.execute("use autoid_t")
    s.execute("create table t (x int)")
    info = s.info_schema()
    tbl = info.table_by_name("autoid_t", "t")
    db_id = info.schema_by_name("autoid_t").id

    a1 = Allocator(store, db_id, tbl.id)
    assert a1.alloc() == 1          # meta cursor -> 1000; a1 holds 1..1000
    a2 = Allocator(store, db_id, tbl.id)
    a2.rebase(5)                    # explicit INSERT id below the cursor
    assert a2.alloc() > 1000        # must not collide with a1's range


def test_allocator_sequential_rebase_batches_meta_txns():
    """Ascending explicit PKs (bulk load) hit meta once per step, not per
    row (meta/autoid/autoid.go Rebase headroom)."""
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.table.autoid import Allocator

    store = new_store("memory://autoid_seq")
    s = Session(store)
    s.execute("create database autoid_s")
    s.execute("use autoid_s")
    s.execute("create table t (x int)")
    info = s.info_schema()
    tbl = info.table_by_name("autoid_s", "t")
    db_id = info.schema_by_name("autoid_s").id

    a = Allocator(store, db_id, tbl.id)
    calls = 0
    orig = a._refill

    import tidb_tpu.table.autoid as autoid_mod
    real_run = autoid_mod.run_in_new_txn

    def counting_run(store_, retryable, fn):
        nonlocal calls
        calls += 1
        return real_run(store_, retryable, fn)

    autoid_mod.run_in_new_txn = counting_run
    try:
        for v in range(1, 2001):
            a.rebase(v)
    finally:
        autoid_mod.run_in_new_txn = real_run
    assert calls <= 4, f"{calls} meta txns for 2000 sequential rebases"


class TestModifyColumn:
    """ALTER TABLE MODIFY COLUMN: metadata-only widening
    (ddl/ddl.go:1070 modifiable, ddl/column.go:421 onModifyColumn)."""

    def _mk(self):
        from tests.testkit import _store_id
        from tidb_tpu.session import Session, new_store
        s = Session(new_store(f"memory://modcol{next(_store_id)}"))
        s.execute("create database d; use d")
        s.execute("create table t (a bigint primary key, b int, "
                  "c varchar(10))")
        s.execute("insert into t values (1, 5, 'hello')")
        return s

    def test_widen_int_and_varchar(self):
        s = self._mk()
        s.execute("alter table t modify column b bigint")
        s.execute("alter table t modify c varchar(100)")
        info = s.info_schema().table_by_name("d", "t").info
        import tidb_tpu.mysqldef as my
        assert info.find_column("b").field_type.tp == my.TypeLonglong
        assert info.find_column("c").field_type.flen == 100
        # existing rows still read correctly after the metadata change
        assert s.execute("select b, c from t")[0].values() == [[5, "hello"]]
        s.execute("insert into t values (2, 9999999999, 'x' )")
        assert s.execute("select b from t where a = 2")[0].values() == \
            [[9999999999]]

    def test_narrowing_and_class_changes_rejected(self):
        import pytest
        from tidb_tpu import errors
        s = self._mk()
        for bad in ["alter table t modify c varchar(5)",      # shrink
                    "alter table t modify b varchar(20)",     # int → string
                    "alter table t modify c int",             # string → int
                    "alter table t modify b int unsigned"]:   # signedness
            with pytest.raises(errors.TiDBError):
                s.execute(bad)
        with pytest.raises(errors.TiDBError):
            s.execute("alter table t modify zz bigint")       # no such col

    def test_review_repros(self):
        """Round-4 review: flags survive MODIFY; storage width governs
        int changes; decimal scale cannot shrink to 0; ALL+DISTINCT."""
        import pytest
        from tidb_tpu import errors
        s = self._mk()
        # no-op retype of the pk keeps pk-handle detection working
        s.execute("alter table t modify a bigint")
        assert s.execute("select b from t where a = 1")[0].values() == [[5]]
        info = s.info_schema().table_by_name("d", "t").info
        assert info.pk_handle_column() is not None
        # tinyint(30) is NOT wider than bigint, whatever its display width
        with pytest.raises(errors.TiDBError):
            s.execute("alter table t modify b tinyint(30)")
        # decimal scale cannot shrink to 0
        s.execute("create table td (x decimal(10,2) primary key)")
        with pytest.raises(errors.TiDBError):
            s.execute("alter table td modify x decimal(10)")
        s.execute("alter table td modify x decimal(12,2)")   # widen ok
        with pytest.raises(errors.TiDBError) as ei:
            s.execute("select all distinct a from t")
        assert getattr(ei.value, "code", None) == 1221


def test_index_ids_never_reused_after_drop():
    """CREATE INDEX after DROP INDEX must allocate a fresh index id: a
    transaction planned against the pre-drop schema can commit AFTER the
    drop's delete pass, orphaning entries under the dead id — an index
    reusing that id would adopt them as corrupt rows (the test_chaos
    ADMIN CHECK mismatch: a bal-typed entry inside an index on note)."""
    from tests.testkit import _store_id
    from tidb_tpu.session import Session, new_store
    s = Session(new_store(f"memory://idxid{next(_store_id)}"))
    s.execute("create database d; use d")
    s.execute("create table t (id bigint primary key, bal bigint, "
              "note varchar(32))")
    s.execute("insert into t values (1, 992, 'init')")
    s.execute("create index ib on t (bal)")
    info = s.info_schema().table_by_name("d", "t").info
    ib_id = info.find_index("ib").id
    s.execute("drop index ib on t")
    s.execute("create index inote on t (note)")
    info = s.info_schema().table_by_name("d", "t").info
    inote_id = info.find_index("inote").id
    assert inote_id != ib_id, \
        "dropped index id reused — stale-schema writers would corrupt it"
    assert info.max_index_id >= inote_id
    # the high-water mark survives serialization (meta round trip)
    from tidb_tpu.model import TableInfo
    assert TableInfo.deserialize(info.serialize()).max_index_id == \
        info.max_index_id
    s.execute("admin check table t")


def test_index_ids_not_reused_for_create_table_inline_indexes():
    """The reuse guard must also cover indexes declared inline in CREATE
    TABLE: that path allocates ids outside alloc_index_id, so the
    builder must record the high-water mark (review finding)."""
    from tests.testkit import _store_id
    from tidb_tpu.session import Session, new_store
    s = Session(new_store(f"memory://idxid{next(_store_id)}"))
    s.execute("create database d; use d")
    s.execute("create table t (id bigint primary key, a bigint, "
              "b varchar(10), key ka (a))")
    info = s.info_schema().table_by_name("d", "t").info
    ka_id = info.find_index("ka").id
    assert info.max_index_id >= ka_id
    s.execute("drop index ka on t")
    s.execute("create index kb on t (b)")
    info = s.info_schema().table_by_name("d", "t").info
    assert info.find_index("kb").id != ka_id, \
        "CREATE TABLE-inline index id reused after drop"
