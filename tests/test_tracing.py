"""EXPLAIN ANALYZE + hierarchical query tracing: per-operator runtime
stats, per-region coprocessor task attribution (including mid-scan
split/merge retries), device-kernel attribution (readbacks, jit cache),
and the consistency contract — everything the trace reports must agree
row-for-row with the flat distsql.columnar_* counters that
tests/test_region_fanout_columnar.py already asserts.

Also: the tracing-disabled overhead guard (no Span is ever allocated for
an untraced statement; the per-statement hook cost stays under a fixed
bound vs a hooks-stubbed baseline) and the thread-local tally
cross-attribution test for concurrent sessions.
"""

from __future__ import annotations

import itertools
import json
import threading
import time

import pytest

from tidb_tpu import metrics, tablecodec as tc, tracing
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 240

JOIN_AGG_Q = ("select count(*), sum(t.v), min(t.v), max(d.d_f) "
              "from t join d on t.k = d.d_k")


def _build(n_regions: int):
    store = new_store(f"cluster://3/trace{next(_id)}")
    s = Session(store)
    s.execute("create database tr")
    s.execute("use tr")
    s.execute("create table t (id bigint primary key, k bigint, "
              "v bigint, f double)")
    rows = ", ".join(f"({i}, {i % 7}, {i * 10}, {i}.25)"
                     for i in range(1, N_ROWS + 1))
    s.execute(f"insert into t values {rows}")
    s.execute("create table d (d_k bigint primary key, d_f double)")
    s.execute("insert into d values " +
              ", ".join(f"({i}, {i}.5)" for i in range(7)))
    if n_regions > 1:
        tid = s.info_schema().table_by_name("tr", "t").info.id
        step = N_ROWS // n_regions
        s.store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _counter(name: str) -> int:
    return metrics.counter(f"distsql.columnar_{name}").value


def _spans(doc: dict, name: str, out=None) -> list[dict]:
    if out is None:
        out = []
    if doc.get("name") == name:
        out.append(doc)
    for c in doc.get("children", ()):
        _spans(c, name, out)
    return out


class TestExplainAnalyze:
    def test_four_region_scan_join_agg(self):
        """Acceptance: per-operator actual rows + wall time, per-region
        copr task timings, all consistent with the flat counters."""
        s = _build(4)
        h0, f0, p0 = _counter("hits"), _counter("fallbacks"), \
            _counter("partials")
        rs = s.execute("explain analyze " + JOIN_AGG_Q)[0]
        dh, df, dp = _counter("hits") - h0, _counter("fallbacks") - f0, \
            _counter("partials") - p0
        assert rs.field_names() == ["id", "actRows", "loops", "time_ms",
                                    "execution info"]
        rows = rs.values()
        by_id = {str(r[0]).strip(): r for r in rows}
        labels = list(by_id)
        assert any(l.startswith("HashAgg") for l in labels), labels
        assert any(l.startswith("HashJoin") for l in labels), labels
        scans = [r for r in rows if "TableScan" in str(r[0])]
        assert len(scans) == 2
        t_scan = next(r for r in scans if "table:t" in str(r[0]))
        d_scan = next(r for r in scans if "table:d" in str(r[0]))
        # actual rows: the t scan delivered all 240 rows (as planes), d 7
        assert int(t_scan[1]) == N_ROWS
        assert int(d_scan[1]) == 7
        # wall time present on every instrumented operator
        for r in rows:
            assert float(r[3]) >= 0.0
        # per-region copr tasks on the t scan, one per region
        info = str(t_scan[4])
        assert "partials:4" in info, info
        assert info.count("region#") == 4, info
        assert "queue:" in info and "run:" in info and "segments:" in info
        assert "drain_seq:" in info
        # row-for-row consistency with the flat counters this statement
        # actually incremented
        ea_partials = sum(
            int(str(r[4]).split("partials:")[1].split(" ")[0])
            for r in scans)
        ea_hits = sum(
            int(str(r[4]).split("columnar_hits:")[1].split(" ")[0])
            for r in scans)
        ea_fbs = sum(
            int(str(r[4]).split("columnar_fallbacks:")[1].split(" ")[0])
            for r in scans)
        assert ea_partials == dp == 5   # 4 t-regions + 1 d-region
        assert ea_hits == dh == 5
        assert ea_fbs == df == 0
        # device-kernel attribution: the fused aggregate merged the
        # per-region partial states over the MESH (per-shard partial agg
        # + ICI collectives) in one combine with one packed readback
        agg = next(r for r in rows if "HashAgg" in str(r[0]))
        agg_info = str(agg[4])
        assert "fused:true" in agg_info
        assert "combine_regions:4" in agg_info
        assert "mesh_shards:" in agg_info, agg_info
        assert "mesh_combines:1" in agg_info, agg_info
        assert "mesh_transfer_bytes:" in agg_info
        rb = int(agg_info.split("mesh_readback_bytes:")[1].split(" ")[0])
        assert rb > 0
        assert "psum" in agg_info.split("mesh_collectives:[")[1]

    def test_split_mid_scan_shows_retries(self):
        """A region split injected mid-scan surfaces as stale-epoch
        retries (and extra segments) on the region task attribution."""
        s = _build(4)
        store = s.store
        orig = store.rpc.cop_request
        state = {"n": 0, "done": False}

        def hook(ctx, sel, ranges, read_ts):
            state["n"] += 1
            if state["n"] == 2 and not state["done"]:
                state["done"] = True
                tid = s.info_schema().table_by_name("tr", "t").info.id
                store.cluster.split_keys([tc.encode_row_key(tid, 31),
                                          tc.encode_row_key(tid, 171)])
            return orig(ctx, sel, ranges, read_ts)

        store.rpc.cop_request = hook
        try:
            rs = s.execute("explain analyze " + JOIN_AGG_Q)[0]
        finally:
            store.rpc.cop_request = orig
        assert state["done"]
        t_scan = next(r for r in rs.values()
                      if "TableScan" in str(r[0]) and "table:t" in str(r[0]))
        info = str(t_scan[4])
        assert "retries:" in info, info
        assert "stale_epoch" in info, info
        # the split region re-emitted one partial per new segment
        segs = [int(p.split(" ")[0].split("]")[0].split(";")[0])
                for p in info.split("segments:")[1:]]
        assert sum(segs) > 4, info

    def test_plain_explain_unchanged(self):
        s = _build(1)
        rs = s.execute("explain " + JOIN_AGG_Q)[0]
        assert rs.field_names() == ["Plan"]

    def test_explain_analyze_write_executes(self):
        s = _build(1)
        s.execute("explain analyze insert into d values (100, 1.5)")
        got = s.execute("select d_f from d where d_k = 100")[0].values()
        assert got == [[1.5]]


class TestTraceJson:
    def test_span_tree_matches_counters(self):
        s = _build(4)
        h0, p0 = _counter("hits"), _counter("partials")
        rs = s.execute(f"trace format='json' {JOIN_AGG_Q}")[0]
        dh, dp = _counter("hits") - h0, _counter("partials") - p0
        assert rs.field_names() == ["trace"]
        doc = json.loads(rs.values()[0][0])
        assert doc["name"] == "statement"
        assert doc["duration_us"] > 0
        assert doc["rows_returned"] == 1
        # copr spans carry the same per-partial attribution the flat
        # counters tallied for this statement
        coprs = _spans(doc, "copr")
        assert sum(c.get("attrs", {}).get("columnar_hits", 0)
                   for c in coprs) == dh == 5
        assert sum(c.get("attrs", {}).get("columnar_partials", 0)
                   for c in coprs) == dp == 5
        # one region_task per region, each with pack/filter children
        tasks = _spans(doc, "region_task")
        assert len(tasks) == 5
        t_rows = 0
        for t in tasks:
            packs = _spans(t, "pack")
            assert len(packs) == 1
            t_rows += packs[0]["attrs"]["rows"]
            a = t["attrs"]
            assert a["queue_us"] >= 0 and a["run_us"] >= 0
            assert a["segments"] >= 1
            assert "complete_seq" in a
        assert t_rows == N_ROWS + 7
        # the mesh combine ran with one packed readback: per-shard
        # partial agg over the placed regions + collectives over ICI
        combines = _spans(doc, "mesh_combine")
        assert len(combines) == 1
        ca = combines[0]["attrs"]
        assert ca["regions"] == 4
        assert ca["shards"] >= 1
        assert ca["readbacks"] == 1 and ca["readback_bytes"] > 0
        assert ca["transfer_bytes"] > 0
        assert "psum" in ca["collectives"]
        shards = _spans(combines[0], "mesh_shard")
        assert len(shards) == ca["shards"]
        placed = [rid for sh in shards for rid in sh["attrs"]["regions"]]
        assert len(placed) == 4   # every region placed on exactly one shard
        assert sum(sh["attrs"]["rows"] for sh in shards) > 0
        # operators subtree mirrors the executor tree
        ops = doc["operators"]
        assert ops["operator"] == "Projection"
        agg = ops["children"][0]
        assert agg["operator"] == "HashAgg"
        assert agg["act_rows"] == 1
        assert agg["fused_agg"]["combine_regions"] == 4

    def test_trace_row_format(self):
        s = _build(2)
        rs = s.execute(f"trace format='row' {JOIN_AGG_Q}")[0]
        assert rs.field_names() == ["operation", "duration_us"]
        names = [str(r[0]).strip() for r in rs.values()]
        assert names[0] == "statement"
        assert any(n == "copr" for n in names)
        assert any(n == "region_task" for n in names)

    def test_trace_requires_statement(self):
        from tidb_tpu import errors
        s = _build(1)
        with pytest.raises(errors.ParseError):
            s.execute("trace format='json' set @x = 1")
        with pytest.raises(errors.ParseError):
            s.execute("trace format='xml' select 1")


class TestSessionTracing:
    def test_sysvar_traces_every_statement(self):
        s = _build(2)
        s.execute("set tidb_trace_enabled = 1")
        try:
            s.execute(JOIN_AGG_Q)
            root = s.last_trace
            assert root is not None and root.name == "statement"
            assert root.end_ns > 0
            assert len(root.find("region_task")) == 3  # 2 t + 1 d
        finally:
            s.execute("set tidb_trace_enabled = 0")
        # with the flight recorder ALSO off, the statement path is back
        # to PR 4's zero-allocation contract (recorder on, spans build
        # scratch trees but retain nothing — covered by the extended
        # guard in TestDisabledOverhead)
        s.execute("set global tidb_tpu_flight_recorder = 0")
        alloc = tracing.span_allocations
        s.execute(JOIN_AGG_Q)
        assert tracing.span_allocations == alloc, \
            "untraced statement allocated spans"

    def test_perfschema_execution_detail(self):
        s = _build(4)
        s.execute(JOIN_AGG_Q)
        rows = s.execute(
            "select SQL_TEXT, EXECUTION_DETAIL from "
            "performance_schema.events_statements_history")[0].values()

        def _s(v):
            return v.decode() if isinstance(v, bytes) else str(v)
        match = [r for r in rows
                 if "from t join d" in _s(r[0]) and r[1] is not None]
        assert match, "statement missing from events_statements_history"
        detail = _s(match[-1][1])
        assert "columnar_partials:5" in detail, detail
        assert "columnar_hits:5" in detail, detail
        assert "columnar_fallbacks:0" in detail, detail
        assert "kernel_dispatches:" in detail, detail
        assert "readback_bytes:" in detail, detail


class TestKernelAttribution:
    def test_tpu_client_kernel_spans_and_jit_cache(self):
        from tidb_tpu.ops import TpuClient
        store = new_store(f"memory://tracetpu{next(_id)}")
        s = Session(store)
        s.execute("create database k")
        s.execute("use k")
        s.execute("create table t (id bigint primary key, v bigint)")
        s.execute("insert into t values " +
                  ", ".join(f"({i}, {i * 2})" for i in range(1, 101)))
        store.set_client(TpuClient(store, dispatch_floor_rows=0))
        sess = Session(store)
        sess.execute("use k")
        doc = json.loads(sess.execute(
            "trace format='json' select sum(v), count(*) from t"
        )[0].values()[0][0])
        kernels = _spans(doc, "kernel")
        assert kernels, "device-routed aggregate recorded no kernel span"
        ka = kernels[0]["attrs"]
        assert ka["kind"] == "scalar"
        assert ka["phase"] == "trace+execute"   # first run pays compile
        assert ka["readbacks"] == 1
        assert ka["readback_bytes"] > 0
        coprs = _spans(doc, "copr")
        assert any(c.get("attrs", {}).get("route") == "tpu"
                   for c in coprs)
        # repeat: the jitted kernel is cached — phase drops to execute
        doc2 = json.loads(sess.execute(
            "trace format='json' select sum(v), count(*) from t"
        )[0].values()[0][0])
        ka2 = _spans(doc2, "kernel")[0]["attrs"]
        assert ka2["phase"] == "execute"
        hits = metrics.counter("ops.jit_cache_hits").value
        assert hits >= 1


class TestDisabledOverhead:
    def test_no_span_allocations_when_off(self):
        """With BOTH tracing and the flight recorder off, the statement
        path is PR 4's original zero-allocation contract: no Span is
        ever constructed."""
        s = _build(1)
        s.execute("set global tidb_tpu_flight_recorder = 0")
        s.execute(JOIN_AGG_Q)   # warm every lazy path
        alloc0 = tracing.span_allocations
        for _ in range(20):
            s.execute(JOIN_AGG_Q)
        assert tracing.span_allocations == alloc0, \
            "tracing-off statements allocated real spans (always-on " \
            "span leak)"

    def test_flight_recorder_fast_path_retains_nothing(self):
        """The EXTENDED PR 4 guard: with the flight recorder ON
        (default), statements build scratch span trees — but a healthy
        (fast, undegraded) statement RETAINS none of it: after a burst,
        no live Span objects exist and the slow-trace ring is empty."""
        import gc

        from tidb_tpu import flight
        s = _build(1)
        # threshold 0 disables the slow leg (this burst measures the
        # HEALTHY fast path; a first run pays jit compile > 300 ms)
        s.execute("set tidb_slow_log_threshold = 0")
        fr = flight.recorder_for(s.store)
        assert fr.enabled
        fr.clear()
        s.execute(JOIN_AGG_Q)   # warm every lazy path
        gc.collect()
        base = sum(1 for o in gc.get_objects()
                   if isinstance(o, tracing.Span))
        for _ in range(10):
            s.execute(JOIN_AGG_Q)
        assert len(fr) == 0, "healthy statements were retained"
        gc.collect()
        live = sum(1 for o in gc.get_objects()
                   if isinstance(o, tracing.Span))
        assert live <= base, \
            f"fast path retained {live - base} live spans"

    def test_per_statement_overhead_bounded(self):
        """Repeated-statement micro-benchmark, the EXTENDED PR 4 guard:
        statements with the tracing hooks live — including the flight
        recorder's always-on scratch span trees (its default) — vs the
        same statements with every hook stubbed out AND the recorder
        off. The per-statement delta must stay under the 2 ms bound, so
        the flight recorder's fast path is covered by the same contract
        the digest pipeline honors."""
        s = _build(1)
        sql = "select count(*) from t"
        n = 60

        def timed() -> float:
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(n):
                    s.execute(sql)
                best = min(best, time.perf_counter() - t0)
            return best

        from tidb_tpu import flight
        assert flight.recorder_for(s.store).enabled
        s.execute(sql)   # warm
        with_hooks = timed()

        saved = (tracing.counters_snapshot, tracing.counters_delta,
                 tracing.current, Session._tracing_enabled)
        tracing.counters_snapshot = lambda: {}
        tracing.counters_delta = lambda before: {}
        tracing.current = lambda: tracing.NOOP
        Session._tracing_enabled = lambda self: False
        s.execute("set global tidb_tpu_flight_recorder = 0")
        try:
            baseline = timed()
        finally:
            (tracing.counters_snapshot, tracing.counters_delta,
             tracing.current, Session._tracing_enabled) = saved
            s.execute("set global tidb_tpu_flight_recorder = 1")

        per_stmt_overhead = (with_hooks - baseline) / n
        assert per_stmt_overhead < 0.002, \
            f"tracing+flight-recorder overhead " \
            f"{per_stmt_overhead * 1e6:.0f}us per statement exceeds " \
            f"the 2ms bound"


class TestConcurrentAttribution:
    def test_thread_local_tallies_do_not_cross_attribute(self):
        """Two sessions executing concurrently on different stores (2 vs
        4 regions) must each see exactly their own per-statement columnar
        tallies, while the process-wide registry counters account for the
        sum — SHOW STATUS / /metrics agree with the slow-log numbers."""
        from tidb_tpu.distsql import thread_columnar_counts
        s2, s4 = _build(2), _build(4)
        for s in (s2, s4):
            s.execute(JOIN_AGG_Q)   # warm outside the measured window
        rounds = 5
        barrier = threading.Barrier(2)
        results: dict[str, list] = {"s2": [], "s4": []}
        errors: list = []

        def run(name, sess):
            try:
                barrier.wait(timeout=30)
                for _ in range(rounds):
                    h0, f0, p0 = thread_columnar_counts()
                    sess.execute(JOIN_AGG_Q)
                    h1, f1, p1 = thread_columnar_counts()
                    results[name].append((h1 - h0, f1 - f0, p1 - p0))
            except Exception as e:   # surfaced after join
                errors.append(e)

        g_hits0 = _counter("hits")
        g_parts0 = _counter("partials")
        threads = [threading.Thread(target=run, args=("s2", s2)),
                   threading.Thread(target=run, args=("s4", s4))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        # per-statement attribution: 2-region store = 2 t-partials + 1 d,
        # 4-region store = 4 + 1 — every round, no bleed-through
        assert results["s2"] == [(3, 0, 3)] * rounds, results["s2"]
        assert results["s4"] == [(5, 0, 5)] * rounds, results["s4"]
        # the process-wide counters saw the sum of both sessions
        assert _counter("hits") - g_hits0 == rounds * 8
        assert _counter("partials") - g_parts0 == rounds * 8


def test_trace_is_not_a_reserved_word():
    """TRACE dispatches as a bare identifier: columns and tables named
    `trace` must keep working in every expression position (review
    finding: making it a lexer keyword broke `select trace from t`)."""
    s = _build(1)
    s.execute("create table trace (id bigint primary key, trace bigint)")
    s.execute("insert into trace values (1, 42)")
    assert s.execute("select trace from trace where trace = 42"
                     )[0].values() == [[42]]
    assert s.execute("select t.trace from trace t order by trace"
                     )[0].values() == [[42]]
    # and the statement form still parses from the same spelling
    doc = json.loads(s.execute(
        "trace format='json' select trace from trace")[0].values()[0][0])
    assert doc["name"] == "statement"
