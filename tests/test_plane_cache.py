"""Differential suite for the per-region columnar plane cache
(copr.plane_cache): cache-on vs cache-off vs the row protocol must be
row-for-row identical across every invalidation edge — a committed write
between two runs (data-version bump → miss), a region split/merge
mid-scan (epoch bump → miss, worklist retry re-packs), two concurrent
sessions at different start_ts (snapshot isolation: the older snapshot
must never see the newer version's planes), and LRU eviction under a
tiny byte budget. Plus the observability contract: Prometheus
counters/gauges on /metrics, per-statement thread tallies in the
slow-query log, cache_hit/cache_miss on region_task spans, and the
device-resident reuse path (pinned planes consumed by the device join).
"""

from __future__ import annotations

import itertools
import logging

import pytest

from tidb_tpu import metrics, tablecodec as tc
from tidb_tpu.copr.plane_cache import cache_for
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 200

JOIN_AGG_Q = ("select count(*), sum(t.v), min(t.v), max(d.d_f), avg(t.f) "
              "from t join d on t.k = d.d_k")
QUERIES = [
    JOIN_AGG_Q,
    "select t.k, count(*), sum(t.v), min(t.f) from t join d "
    "on t.k = d.d_k group by t.k order by t.k",
    "select t.id, t.v, d.d_f from t join d on t.k = d.d_k order by t.id",
    "select id, v from t order by v desc limit 7",
    "select count(*), sum(v) from t where v > 500",
]


def _counter(name: str) -> int:
    return metrics.counter(f"copr.plane_cache.{name}").value


def _build(n_regions: int = 4):
    store = new_store(f"cluster://3/planecache{next(_id)}")
    s = Session(store)
    s.execute("create database pc")
    s.execute("use pc")
    s.execute("create table t (id bigint primary key, k bigint, "
              "v bigint, f double)")
    rows = ", ".join(
        f"({i}, {i % 7}, {i * 10}, {i}.25)" if i % 11 else
        f"({i}, null, {i * 10}, null)"
        for i in range(1, N_ROWS + 1))
    s.execute(f"insert into t values {rows}")
    s.execute("create table d (d_k bigint primary key, d_f double)")
    s.execute("insert into d values " +
              ", ".join(f"({i}, {i}.5)" for i in range(7)))
    if n_regions > 1:
        tid = s.info_schema().table_by_name("pc", "t").info.id
        step = N_ROWS // n_regions
        s.store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _all(s) -> list:
    return [s.execute(q)[0].values() for q in QUERIES]


def _parity_against_oracles(s, got: list) -> None:
    """got must equal the cache-off regime AND the row protocol."""
    s.execute("set global tidb_tpu_plane_cache = 0")
    try:
        off = _all(s)
    finally:
        s.execute("set global tidb_tpu_plane_cache = 1")
    for q, g, o in zip(QUERIES, got, off):
        assert g == o, f"cache-on diverged from cache-off on {q!r}"
    s.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        rows = _all(s)
    finally:
        s.execute("set global tidb_tpu_columnar_scan = 1")
    for q, g, r in zip(QUERIES, got, rows):
        assert g == r, f"cache-on diverged from the row protocol on {q!r}"


def test_repeat_query_hits_cache():
    """The repeat fan-out shape: the second run of the same query answers
    every region from cached planes — and matches the first run, the
    cache-off regime, and the row protocol."""
    s = _build(4)
    first = _all(s)
    h0 = _counter("hits")
    second = _all(s)
    assert _counter("hits") - h0 >= 4, \
        "repeat fan-out did not hit the plane cache per region"
    for q, a, b in zip(QUERIES, first, second):
        assert a == b, f"cached run diverged from the packing run on {q!r}"
    _parity_against_oracles(s, second)


def test_committed_write_invalidates_version():
    """A commit between two runs bumps the table's
    data_version_at(start_ts): the next run MISSES (never serves the
    stale planes) and sees the write. With the HTAP delta tier OFF this
    is PR 5's sweep (invalidations_version); with it on (the default)
    the old generation instead survives as a delta-merge base — covered
    by test_delta_pack.py."""
    s = _build(4)
    s.execute("set global tidb_tpu_delta_pack = 0")
    try:
        before = _all(s)
        s.execute(JOIN_AGG_Q)   # ensure cached planes exist for the join
        m0, iv0 = _counter("misses"), _counter("invalidations_version")
        s.execute("insert into t values (501, 1, 99999, 1.5)")
        after = s.execute(JOIN_AGG_Q)[0].values()
        assert after != before[0], "committed write invisible after caching"
        assert _counter("misses") > m0
        assert _counter("invalidations_version") > iv0, \
            "stale-version entries were not swept"
        got = _all(s)
    finally:
        s.execute("set global tidb_tpu_delta_pack = 1")
    _parity_against_oracles(s, got)


def test_update_and_delete_invalidate():
    """Non-append writes (UPDATE/DELETE) also bump the version — the
    cache must never serve planes that hide them."""
    s = _build(4)
    s.execute(JOIN_AGG_Q)
    s.execute("update t set v = v + 1 where id = 50")
    got = _all(s)
    _parity_against_oracles(s, got)
    s.execute("delete from t where id = 51")
    got = _all(s)
    _parity_against_oracles(s, got)


class TestEpochInvalidation:
    def test_split_between_runs(self):
        """A region split bumps the epoch: entries packed under the old
        shape are swept (invalidations_epoch) and never served."""
        s = _build(4)
        before = _all(s)
        ie0 = _counter("invalidations_epoch")
        tid = s.info_schema().table_by_name("pc", "t").info.id
        s.store.cluster.split_keys([tc.encode_row_key(tid, 26)])
        got = _all(s)
        for q, g, w in zip(QUERIES, got, before):
            assert g == w, f"post-split run diverged on {q!r}"
        assert _counter("invalidations_epoch") > ie0, \
            "old-epoch entries were not swept after the split"
        _parity_against_oracles(s, got)

    def test_merge_between_runs(self):
        s = _build(4)
        before = _all(s)
        regions = s.store.cluster.regions
        for i in range(len(regions) - 1):
            if regions[i].start:
                s.store.cluster.merge(regions[i].region_id,
                                      regions[i + 1].region_id)
                break
        got = _all(s)
        for q, g, w in zip(QUERIES, got, before):
            assert g == w, f"post-merge run diverged on {q!r}"
        _parity_against_oracles(s, got)

    def test_split_mid_scan(self):
        """Split injected DURING the fan-out (after the 2nd region
        request): the stale-epoch retry re-packs under the new shape;
        results match the pre-split runs and the steady state."""
        s = _build(4)
        store = s.store
        want = _all(s)           # also populates the cache
        orig = store.rpc.cop_request
        state = {"n": 0, "done": False}

        def hook(ctx, sel, ranges, read_ts):
            state["n"] += 1
            if state["n"] == 2 and not state["done"]:
                state["done"] = True
                tid = s.info_schema().table_by_name("pc", "t").info.id
                store.cluster.split_keys([tc.encode_row_key(tid, 31),
                                          tc.encode_row_key(tid, 171)])
            return orig(ctx, sel, ranges, read_ts)

        store.rpc.cop_request = hook
        try:
            got = _all(s)
        finally:
            store.rpc.cop_request = orig
        assert state["done"], "mid-scan split never fired"
        for q, g, w in zip(QUERIES, got, want):
            assert g == w, f"mid-scan split diverged on {q!r}"
        after = _all(s)
        for q, a, w in zip(QUERIES, after, want):
            assert a == w, f"post-split steady state diverged on {q!r}"
        _parity_against_oracles(s, after)


def test_snapshot_isolation_across_sessions():
    """Two sessions at different start_ts: the older snapshot (open
    transaction) must keep seeing ITS version's planes after a newer
    commit — and the newer session must see the write — with both
    served through the cache."""
    s1 = _build(4)
    s2 = Session(s1.store)
    s2.execute("use pc")
    q = "select count(*), sum(v) from t"
    s1.execute("begin")
    old = s1.execute(q)[0].values()
    # populate the cache at the OLD version through the open snapshot
    old2 = s1.execute(q)[0].values()
    assert old2 == old
    s2.execute("insert into t values (900, 2, 777, 9.5)")
    new = s2.execute(q)[0].values()
    assert new != old, "newer session missed the committed write"
    new2 = s2.execute(q)[0].values()       # cached at the new version
    assert new2 == new
    # the open older snapshot must NOT see the newer version's planes
    still_old = s1.execute(q)[0].values()
    assert still_old == old, \
        "older snapshot served planes from a newer data version"
    s1.execute("commit")
    assert s1.execute(q)[0].values() == new


def test_pending_lock_blocks_cache_hit():
    """Percolator lock gate: a pending prewrite lock with start_ts <=
    read_ts may resolve to a commit the reader must see (its commit_ts
    can predate read_ts) — the scan path blocks on it; a cached hit
    must NOT skip that check. With a blocking lock in range the cache
    refuses to serve; once the lock resolves (TTL rollback here) the
    result matches the pre-lock runs and hits resume."""
    s = _build(2)
    tid = s.info_schema().table_by_name("pc", "t").info.id
    q = "select id, v from t order by v desc limit 5"
    want = s.execute(q)[0].values()
    s.execute(q)                       # populate the cache
    key = tc.encode_row_key(tid, 10)
    s.store.mvcc.prewrite([("put", key, b"xx")], primary=key,
                          start_ts=s.store.oracle.current_version(),
                          ttl_ms=1)    # expires immediately → rollback
    got = s.execute(q)[0].values()
    assert got == want
    # the observable contract: the statement RESOLVED the lock (gate →
    # pack path → KeyIsLockedError → resolver ladder → TTL rollback)
    # instead of serving cached planes past it and leaving it pending.
    # (Serving a hit on the post-resolution retry is fine — a rollback
    # commits nothing, so the cached planes are still the snapshot.)
    # Pre-gate this bypassed the scan and left the lock in place;
    # pre-seed-fix the statement died with "coprocessor error: key
    # locked" because the row handler stringified the retryable error.
    assert key not in s.store.mvcc._locks, \
        "cached planes served past a pending blocking lock"
    # a non-blocking 'lock' kind (SELECT FOR UPDATE) must NOT gate hits
    key2 = tc.encode_row_key(tid, 11)
    s.store.mvcc.prewrite([("lock", key2, None)], primary=key2,
                          start_ts=s.store.oracle.current_version(),
                          ttl_ms=60000)
    try:
        s.execute(q)                   # repopulate post-rollback version
        h1 = _counter("hits")
        assert s.execute(q)[0].values() == want
        assert _counter("hits") > h1, \
            "a SELECT FOR UPDATE lock wrongly gated the cache"
    finally:
        s.store.mvcc.rollback([key2], s.store.mvcc._locks[key2].start_ts
                              if key2 in s.store.mvcc._locks else 0)


def test_tpu_client_batch_cache_lock_gate():
    """TpuClient on a cluster store (SET tidb_copr_backend='tpu'): its
    in-proc batch cache obeys the same Percolator lock gate — a pending
    blocking lock in the scanned ranges bypasses the hit so the
    snapshot scan resolves the lock, exactly like the region cache."""
    from tidb_tpu.ops import TpuClient
    s = _build(1)
    store = s.store
    store.set_client(TpuClient(store, dispatch_floor_rows=0))
    s2 = Session(store)
    s2.execute("use pc")
    q = "select count(*), sum(v) from t"
    want = s2.execute(q)[0].values()
    s2.execute(q)                     # populate the client batch cache
    client = store.get_client()
    tid = s2.info_schema().table_by_name("pc", "t").info.id
    key = tc.encode_row_key(tid, 10)
    store.mvcc.prewrite([("put", key, b"xx")], primary=key,
                        start_ts=store.oracle.current_version(),
                        ttl_ms=1)
    h0 = client.stats["batch_hits"]
    assert s2.execute(q)[0].values() == want
    assert key not in store.mvcc._locks, \
        "TpuClient batch-cache hit served past a pending blocking lock"
    assert client.stats["batch_hits"] == h0, \
        "batch cache hit under a pending blocking lock"


def test_bootstrap_hydration_reaches_region_cache_on_tpu_backend():
    """Persisted tidb_tpu_plane_cache=0 / _bytes must hydrate the region
    cache on restart EVEN when tidb_copr_backend='tpu' is persisted too
    (the backend branch used to skip the cache hydration block)."""
    from tidb_tpu import session as sess_mod
    s = _build(1)
    store = s.store
    s.execute("set global tidb_copr_backend = 'tpu'")
    s.execute("set global tidb_tpu_plane_cache = 0")
    s.execute("set global tidb_tpu_plane_cache_bytes = 12345")
    pc = cache_for(store)
    pc.enabled = True                 # simulate a fresh process's default
    pc.budget_bytes = 999
    try:
        # simulate restart: drop the bootstrapped mark and re-bind
        sess_mod._BOOTSTRAPPED_STORES.discard(store.uuid())
        sess_mod._global_vars_by_store.pop(store.uuid(), None)
        s2 = Session(store)
        assert pc.enabled is False, \
            "persisted plane-cache kill switch reverted on tpu backend"
        assert pc.budget_bytes == 12345
        s2.execute("set global tidb_tpu_plane_cache = 1")
    finally:
        s.execute("set global tidb_tpu_plane_cache_bytes = 268435456")
        s.execute("set global tidb_copr_backend = 'cpu'")


def test_lru_eviction_under_tiny_budget():
    """A byte budget smaller than the working set forces LRU evictions;
    results stay exact and the eviction counter moves."""
    s = _build(4)
    s.execute("set global tidb_tpu_plane_cache_bytes = 40000")
    try:
        ev0 = _counter("evictions")
        first = _all(s)
        second = _all(s)
        assert _counter("evictions") > ev0, \
            "tiny budget never evicted an entry"
        for q, a, b in zip(QUERIES, first, second):
            assert a == b, f"evicting cache diverged on {q!r}"
        _parity_against_oracles(s, second)
        pc = cache_for(s.store)
        assert pc.bytes_cached <= 40000
    finally:
        s.execute("set global tidb_tpu_plane_cache_bytes = 268435456")


def test_budget_zero_caches_nothing():
    s = _build(2)
    s.execute("set global tidb_tpu_plane_cache_bytes = 0")
    try:
        h0 = _counter("hits")
        got = [s.execute(JOIN_AGG_Q)[0].values() for _ in range(2)]
        assert got[0] == got[1]
        assert _counter("hits") == h0
        assert len(cache_for(s.store)) == 0
    finally:
        s.execute("set global tidb_tpu_plane_cache_bytes = 268435456")


def test_sysvars_global_only():
    s = _build(1)
    from tidb_tpu import errors
    with pytest.raises(errors.ExecError):
        s.execute("set tidb_tpu_plane_cache = 0")
    with pytest.raises(errors.ExecError):
        s.execute("set tidb_tpu_plane_cache_bytes = 1024")
    assert s.execute("select @@tidb_tpu_plane_cache")[0].values() \
        == [["1"]]


def test_kill_switch_disables_and_clears():
    s = _build(4)
    before = _all(s)
    pc = cache_for(s.store)
    assert len(pc) > 0
    s.execute("set global tidb_tpu_plane_cache = 0")
    try:
        assert len(pc) == 0, "kill switch left entries resident"
        h0 = _counter("hits")
        got = _all(s)
        assert _counter("hits") == h0, "disabled cache served a hit"
        for q, g, w in zip(QUERIES, got, before):
            assert g == w, f"cache-off diverged on {q!r}"
    finally:
        s.execute("set global tidb_tpu_plane_cache = 1")


def test_kill_switch_clears_tpu_client_batch_cache():
    """The same switch governs the in-proc TpuClient batch cache: off
    stops serving AND drops the held batches (with their device pins)."""
    from tidb_tpu.ops import TpuClient
    store = new_store(f"memory://planecache{next(_id)}")
    store.set_client(TpuClient(store, dispatch_floor_rows=0))
    s = Session(store)
    s.execute("create database pc; use pc")
    s.execute("create table t (id bigint primary key, v bigint)")
    s.execute("insert into t values " +
              ", ".join(f"({i}, {i * 3})" for i in range(1, 60)))
    q = "select count(*), sum(v) from t"
    want = s.execute(q)[0].values()
    client = store.get_client()
    assert s.execute(q)[0].values() == want
    assert client._batch_cache, "warm query never cached a batch"
    h0 = client.stats["batch_hits"]
    s.execute("set global tidb_tpu_plane_cache = 0")
    try:
        assert not client._batch_cache, \
            "kill switch left TpuClient batches resident"
        assert s.execute(q)[0].values() == want
        assert client.stats["batch_hits"] == h0, \
            "disabled batch cache served a hit"
    finally:
        s.execute("set global tidb_tpu_plane_cache = 1")
    assert s.execute(q)[0].values() == want


class TestObservability:
    def test_metrics_exposition(self):
        s = _build(4)
        _all(s)
        _all(s)
        text = metrics.render_text()
        assert "# TYPE copr_plane_cache_hits counter" in text
        assert "# TYPE copr_plane_cache_bytes_pinned gauge" in text
        assert "# TYPE copr_plane_cache_entries gauge" in text
        pc = cache_for(s.store)
        assert pc.bytes_cached > 0
        ent = metrics.gauge("copr.plane_cache.entries").value
        assert ent >= len(pc)   # other stores in-process may add more

    def test_slow_log_thread_tallies(self, caplog):
        """Per-statement plane-cache tallies ride the slow-query log with
        the same monotonic-diff contract as columnar_hits — and two
        runs attribute hit vs miss to the right statement."""
        s = _build(4)
        s.execute("set tidb_slow_log_threshold = 0.001")
        with caplog.at_level(logging.WARNING, logger="tidb_tpu.slowlog"):
            s.execute(JOIN_AGG_Q)
            s.execute(JOIN_AGG_Q)
        msgs = [r.getMessage() for r in caplog.records
                if "SLOW_QUERY" in r.getMessage()
                and "from t join d" in r.getMessage()]
        assert len(msgs) >= 2
        assert "plane_cache_misses:" in msgs[0], msgs[0]
        assert "plane_cache_hits:" in msgs[-1], msgs[-1]
        assert "plane_cache_misses:" not in msgs[-1], msgs[-1]

    def test_region_task_span_cache_attrs(self):
        """cache_hit / cache_miss land on the region_task spans of a
        traced statement."""
        s = _build(4)
        s.execute(JOIN_AGG_Q)                      # populate
        s.execute("set tidb_trace_enabled = 1")
        try:
            s.execute(JOIN_AGG_Q)
            root = s.last_trace
        finally:
            s.execute("set tidb_trace_enabled = 0")
        tasks = root.find("region_task")
        assert tasks, "traced fan-out produced no region_task spans"
        hits = sum(t.attrs.get("cache_hit", 0) for t in tasks)
        assert hits >= 4, [t.attrs for t in tasks]
        copr = root.find("copr")
        assert any(sp.attrs.get("plane_cache_hits", 0) >= 4
                   for sp in copr), [sp.attrs for sp in copr]


class TestDeviceResidentReuse:
    def test_pinned_planes_and_device_plane_parity(self):
        """Cached batches are pinned device-resident (jax is live in the
        test process); the columnar payload's device planes must equal
        its host planes value-for-value."""
        import numpy as np
        s = _build(2)
        s.execute(JOIN_AGG_Q)
        pc = cache_for(s.store)
        assert pc.bytes_pinned > 0, "admitted batches were not pinned"
        from tidb_tpu.ops import columnar as col
        info = s.info_schema().table_by_name("pc", "t").info
        parts = _cached_scan_results(s, pc, info)
        assert parts, "no cached batch for table t"
        res = parts[0]
        assert getattr(res.batch, "_device_planes", None) is not None
        checked = 0
        for j in range(len(res.pb_cols)):
            kind, vals, valid = res.column_plane(j)
            dev = res.device_plane(j)
            if kind in ("i64", "f64") and dev is not None:
                dv, dva = np.asarray(dev[0]), np.asarray(dev[1])
                assert dva.tolist() == valid.tolist()
                assert dv[valid].tolist() == vals[valid].tolist()
                checked += 1
        assert checked >= 2, "no numeric device planes to check"

    def test_device_join_over_cached_fanout(self):
        """With the dispatch floor at 0, a cluster-store join routes to
        the device kernels and consumes the cached partials' DEVICE
        planes (no host→device key transfer); results match the numpy
        route exactly."""
        from tidb_tpu.ops import kernels
        s = _build(4)
        base = _all(s)
        kd = metrics.counter("ops.kernel_dispatches")
        s.execute("set global tidb_tpu_dispatch_floor = 0")
        seen = {"device_keys": False}
        orig = kernels.join_match_pairs

        def spy(lkey, lvalid, rkey, rvalid, stats=None, device_keys=None,
                **kw):
            if device_keys is not None:
                seen["device_keys"] = True
            return orig(lkey, lvalid, rkey, rvalid, stats=stats,
                        device_keys=device_keys, **kw)

        kernels.join_match_pairs = spy
        try:
            s.execute(JOIN_AGG_Q)        # populate under the new version
            k0 = kd.value
            got = _all(s)
            assert kd.value > k0, "floor 0 never dispatched a device join"
        finally:
            kernels.join_match_pairs = orig
            s.execute("set global tidb_tpu_dispatch_floor = 16384")
        assert seen["device_keys"], \
            "device join never consumed the cached DEVICE key planes"
        for q, g, w in zip(QUERIES, got, base):
            assert g == w, f"device route diverged from numpy on {q!r}"

    def test_partial_set_device_stacking(self):
        """ColumnarPartialSet.device_plane stacks per-region pinned
        planes with the jitted device concat and equals the host
        np.concatenate stacking exactly."""
        import numpy as np
        from tidb_tpu.ops import columnar as col
        s = _build(4)
        s.execute(JOIN_AGG_Q)                  # populate all regions
        pc = cache_for(s.store)
        info = s.info_schema().table_by_name("pc", "t").info
        parts = _cached_scan_results(s, pc, info)
        assert len(parts) >= 2, "expected multiple cached region batches"
        ps = col.ColumnarPartialSet(parts)
        checked = 0
        for j in range(len(ps.pb_cols)):
            kind, vals, valid = ps.column_plane(j)
            dev = ps.device_plane(j)
            if kind in ("i64", "f64") and dev is not None:
                dv, dva = np.asarray(dev[0]), np.asarray(dev[1])
                assert dva.tolist() == valid.tolist()
                assert dv[valid].tolist() == vals[valid].tolist()
                checked += 1
        assert checked >= 2


def _cached_scan_results(s, pc, info):
    """One ColumnarScanResult (all live rows selected) per cached batch
    of `info`'s table, in region-start order — the cache key records the
    scanned column ids, so each wrapper carries exactly the columns its
    batch packed."""
    import numpy as np
    from tidb_tpu.ops import columnar as col
    by_id = {c.id: c for c in info.columns}
    pb_all = {c.column_id: c for c in _pb_columns(info)}
    out = []
    for fk, ent in sorted(pc._entries.items(),
                          key=lambda kv: kv[0][3]):   # by range bounds
        region_id, table_id = fk[0], fk[1]
        # the key's column part is the full schema SIGNATURE since the
        # per-table-version change (PR 13) — the column id leads each
        # per-column tuple
        cids = [c[0] if isinstance(c, tuple) else c for c in fk[2]]
        if table_id != info.id or not all(c in by_id for c in cids):
            continue
        out.append(col.ColumnarScanResult(
            ent.batch, np.arange(ent.batch.n_rows, dtype=np.int64),
            [pb_all[c] for c in cids]))
    return out


def _pb_columns(info):
    """PBColumnInfo list for a table the way the executor builds scan
    requests (executor.distsql_exec._pb_col contract)."""
    from tidb_tpu.copr.proto import PBColumnInfo
    pk = info.pk_handle_column()
    return [PBColumnInfo(column_id=c.id, tp=c.field_type.tp,
                         flag=c.field_type.flag, flen=c.field_type.flen,
                         decimal=c.field_type.decimal,
                         pk_handle=pk is not None and c.id == pk.id,
                         elems=list(c.field_type.elems))
            for c in info.columns]
