"""Statistics + cost-based access-path tests.

Mirrors plan/statistics/statistics_test.go (histogram estimation) and the
physical-planner cost checks: the access path must flip between index and
table scan as the data distribution (via ANALYZE) changes.
"""

from tidb_tpu import statistics
from tidb_tpu.plan.plans import PhysicalIndexScan, PhysicalTableScan
from tidb_tpu.types import Datum

from tests.testkit import TestKit


def _i(v):
    return Datum.i64(v)


class TestHistogram:
    def test_build_and_estimate(self):
        # 1000 rows: value i//10 → 100 distinct values, 10 repeats each
        vals = [_i(i // 10) for i in range(1000)]
        st = statistics.build_column_stats(1, vals, bucket_count=16)
        assert st.ndv == 100
        assert st.total == 1000
        eq = st.equal_row_count(_i(42))
        assert 5 <= eq <= 20  # true answer 10
        less = st.less_row_count(_i(50))
        assert 400 <= less <= 600  # true answer 500
        bt = st.between_row_count(_i(20), _i(30))
        assert 50 <= bt <= 200  # true answer 100

    def test_nulls_and_empty(self):
        from tidb_tpu.types.datum import NULL
        st = statistics.build_column_stats(1, [NULL, NULL, _i(1)])
        assert st.null_count == 2
        assert st.total == 1
        empty = statistics.build_column_stats(2, [])
        assert empty.total == 0
        assert empty.equal_row_count(_i(1)) == 0.0

    def test_serialize_round_trip(self):
        vals = [_i(i % 7) for i in range(100)]
        tbl = statistics.TableStats(
            5, 100, {1: statistics.build_column_stats(1, vals)})
        back = statistics.TableStats.deserialize(tbl.serialize())
        assert back.table_id == 5 and back.count == 100
        assert back.col(1).ndv == 7
        assert back.equal_row_count(1, _i(3)) == tbl.equal_row_count(1, _i(3))

    def test_pseudo_rates(self):
        st = statistics.pseudo_table(1)
        assert st.count == statistics.PSEUDO_ROW_COUNT
        assert st.equal_row_count(1, _i(5)) == \
            st.count / statistics.PSEUDO_EQUAL_RATE


class TestAnalyze:
    def test_analyze_persists_and_estimates(self):
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int primary key, b int, key idx_b (b))")
        for i in range(50):
            tk.exec(f"insert into t values ({i}, {i % 5})")
        tk.exec("analyze table t")
        info = tk.session.info_schema().table_by_name("d", "t")
        st = tk.session.stats_for(info.id)
        assert not st.pseudo
        assert st.count == 50
        b_id = info.info.find_column("b").id
        assert 8 <= st.equal_row_count(b_id, _i(2)) <= 12  # true 10

    def test_analyze_invalidates_prepared_plan_cache(self):
        """A plan cached from pseudo stats must be re-planned after ANALYZE
        (the cost-based access path may change)."""
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int primary key, b int, c int, "
                "key idx_b (b))")
        rows = ", ".join(
            f"({i}, {7 if i < 195 else 1000 + i}, {i})" for i in range(200))
        tk.exec(f"insert into t values {rows}")
        tk.exec("prepare p from 'select count(1) from t where b = 7'")
        tk.exec("execute p").check([[195]])
        tk.exec("execute p").check([[195]])
        assert tk.session.vars.last_plan_from_cache
        tk.exec("analyze table t")
        tk.exec("execute p").check([[195]])
        assert not tk.session.vars.last_plan_from_cache

    def test_drop_and_truncate_clear_stats(self):
        from tidb_tpu.meta import Meta
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int primary key, b int)")
        tk.exec("insert into t values (1, 1), (2, 2)")
        tk.exec("analyze table t")
        info = tk.session.info_schema().table_by_name("d", "t")
        old_id = info.id
        tk.exec("truncate table t")
        tk.exec("drop table t")
        txn = tk.store.begin()
        try:
            assert Meta(txn).get_table_stats(old_id) is None
        finally:
            txn.rollback()

    def test_analyze_empty_table_keeps_pseudo_paths(self):
        """Zero-count stats must not cost every path at 0 and pin table
        scans after the table grows."""
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int primary key, b int, c int, "
                "key idx_b (b))")
        tk.exec("analyze table t")  # analyzed while empty
        rows = ", ".join(f"({i}, {i}, {i})" for i in range(100))
        tk.exec(f"insert into t values {rows}")
        assert _scan_type(tk, "select c from t where b = 5") == "index"

    def test_analyze_sees_own_txn_writes(self):
        """ANALYZE implicitly commits (DDL rule) so the scan includes the
        session's pending rows."""
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int primary key, b int)")
        tk.exec("begin")
        tk.exec("insert into t values (1, 1), (2, 2), (3, 3)")
        tk.exec("analyze table t")
        info = tk.session.info_schema().table_by_name("d", "t")
        assert tk.session.stats_for(info.id).count == 3

    def test_analyze_missing_table_errors(self):
        tk = TestKit()
        tk.exec("create database d; use d")
        try:
            tk.exec("analyze table nope")
            assert False, "expected error"
        except Exception:
            pass


def _scan_type(tk, sql):
    from tidb_tpu.plan import optimize_plan
    from tidb_tpu.plan.builder import PlanBuilder
    s = tk.session
    stmt = s.parser.parse_one(sql)
    p = optimize_plan(PlanBuilder(s).build(stmt), s, s.client, set())

    def find(n, tp):
        if isinstance(n, tp):
            return n
        for c in n.children:
            r = find(c, tp)
            if r is not None:
                return r
        return None

    if find(p, PhysicalIndexScan) is not None:
        return "index"
    assert find(p, PhysicalTableScan) is not None
    return "table"


class TestCostBasedAccessPath:
    def test_path_flips_on_distribution(self):
        """where b = <common value> should table-scan once stats reveal the
        value matches most rows (double-read index would be slower); a rare
        value keeps the index path."""
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int primary key, b int, c int, "
                "key idx_b (b))")
        # 200 rows: b=7 on 195 of them, b unique elsewhere
        rows = ", ".join(
            f"({i}, {7 if i < 195 else 1000 + i}, {i})" for i in range(200))
        tk.exec(f"insert into t values {rows}")

        # pseudo stats: eq on an index is assumed selective → index path
        assert _scan_type(tk, "select c from t where b = 7") == "index"

        tk.exec("analyze table t")
        # common value: ~97% of the table → table scan wins
        assert _scan_type(tk, "select c from t where b = 7") == "table"
        # rare value: still the index
        assert _scan_type(tk, "select c from t where b = 1199") == "index"
        # results stay correct either way
        tk.exec("select count(1) from t where b = 7").check([[195]])
        tk.exec("select c from t where b = 1199").check([[199]])

    def test_join_reorder_by_table_size(self):
        """Inner-join chains order largest-first so every hash build side
        (right child) is as small as stats allow (join_reorder.go)."""
        from tidb_tpu.plan.plans import PhysicalHashJoin
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table big (id int primary key, k int)")
        tk.exec("create table small (id int primary key, k int)")
        rows = ", ".join(f"({i}, {i % 10})" for i in range(300))
        tk.exec(f"insert into big values {rows}")
        tk.exec("insert into small values (1, 1), (2, 2)")
        tk.exec("analyze table big, small")

        from tidb_tpu.plan import optimize_plan
        from tidb_tpu.plan.builder import PlanBuilder
        s = tk.session

        def top_join(sql):
            stmt = s.parser.parse_one(sql)
            p = optimize_plan(PlanBuilder(s).build(stmt), s, s.client, set())
            n = p
            while n is not None and not isinstance(n, PhysicalHashJoin):
                n = n.children[0] if n.children else None
            return n

        # syntax order small-first: reorder must put big on the LEFT
        # (probe) and small on the RIGHT (build)
        j = top_join("select * from small, big where small.k = big.k")
        names = [c.tbl_name for c in j.children[1].schema[:1]]
        assert names == ["small"], names
        # results stay correct (column order = declaration order)
        got = tk.exec("select small.id, big.id from small, big "
                      "where small.k = big.k and big.id < 15 "
                      "order by small.id, big.id").rows
        assert got == [[1, 1], [1, 11], [2, 2], [2, 12]]
        # three-way chain reorders and still answers correctly
        tk.exec("create table mid (id int primary key, k int)")
        tk.exec("insert into mid values " +
                ", ".join(f"({i}, {i % 10})" for i in range(30)))
        tk.exec("analyze table mid")
        got = tk.exec(
            "select small.id, mid.id, big.id from small, mid, big "
            "where small.k = mid.k and mid.k = big.k and big.id < 12 "
            "and mid.id < 12 order by small.id, mid.id, big.id").rows
        assert got == [[1, 1, 1], [1, 1, 11], [1, 11, 1], [1, 11, 11],
                       [2, 2, 2]]

    def test_on_condition_scope_not_widened_by_flatten(self):
        """An unqualified ON column that is unique at its own join level
        must not become ambiguous against factors joined later
        (regression: all ONs were resolved against the full chain)."""
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t1 (x int primary key, a int)")
        tk.exec("create table t2 (y int primary key, b int)")
        tk.exec("create table t3 (y int primary key, c int)")
        tk.exec("insert into t1 values (1, 1), (2, 2)")
        tk.exec("insert into t2 values (1, 10), (3, 30)")
        tk.exec("insert into t3 values (1, 100), (2, 200)")
        got = tk.exec("select t1.x, t2.b, t3.c from t1 join t2 on x = y "
                      "join t3 on t1.x = t3.y order by t1.x").rows
        assert got == [[1, 10, 100]]

    def test_range_estimation_flip(self):
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int primary key, b int, c int, "
                "key idx_b (b))")
        rows = ", ".join(f"({i}, {i}, {i})" for i in range(200))
        tk.exec(f"insert into t values {rows}")
        tk.exec("analyze table t")
        # narrow range → index; huge range needing a double read → table
        # scan; covering (index-only) stays index even for wide ranges
        assert _scan_type(tk, "select c from t where b < 5") == "index"
        assert _scan_type(tk, "select c from t where b < 190") == "table"
        assert _scan_type(tk, "select b from t where b < 190") == "index"
