"""Metrics registry + scheduled GC tests.

Mirrors: metrics.go phase histograms / distsql/metrics.go (via SHOW
STATUS), store/localstore/compactor.go (scheduled compaction), and
store/tikv/gc_worker.go leader election (lease-guarded cluster GC).
"""

import pytest

from tidb_tpu import metrics
from tidb_tpu.gcworker import Compactor, GCWorker
from tidb_tpu.session import Session, new_store
from tests.testkit import TestKit, _store_id


class TestMetrics:
    def test_counter_histogram(self):
        r = metrics.Registry()
        r.counter("x").inc()
        r.counter("x").inc(2)
        h = r.histogram("lat")
        h.observe(0.002)
        h.observe(0.2)
        snap = dict(r.snapshot())
        assert snap["x"] == "3"
        assert snap["lat_count"] == "2"
        assert abs(float(snap["lat_sum"]) - 0.202) < 1e-9

    def test_show_status_exposes_phases(self):
        tk = TestKit()
        tk.exec("create database d; use d; create table t (a int)")
        tk.exec("insert into t values (1)")
        tk.exec("select * from t")
        snap = {r[0]: r[1] for r in tk.exec("show status").rows}
        assert int(snap[b"session.compile_seconds_count".decode()]) > 0
        assert int(snap["session.run_seconds_count"]) > 0
        assert "session.statements.SelectStmt" in snap
        like = tk.exec("show status like 'distsql%'").rows
        assert all(r[0].startswith("distsql") for r in like)

    def test_tpu_fallback_counters(self):
        from tidb_tpu.ops import TpuClient
        store = new_store(f"memory://mgc{next(_store_id)}")
        store.set_client(TpuClient(store, dispatch_floor_rows=0))
        s = Session(store)
        before = metrics.counter("copr.tpu.requests").value
        s.execute("create database d; use d; create table t "
                  "(a int primary key)")
        s.execute("insert into t values (1), (2)")
        s.execute("select sum(a) from t")
        assert metrics.counter("copr.tpu.requests").value > before


class TestScheduledGC:
    def test_compactor_reclaims_old_versions(self):
        store = new_store(f"memory://mgc{next(_store_id)}")
        s = Session(store)
        s.execute("create database d; use d; create table t "
                  "(a int primary key, b int)")
        s.execute("insert into t values (1, 0)")
        for i in range(5):
            s.execute(f"update t set b = {i + 1}")
        c = Compactor(store, safe_age_ms=0)  # safepoint = now
        removed = c.tick()
        assert removed > 0
        # data still correct at the current snapshot
        assert s.execute("select b from t")[0].values() == [[5]]
        # idle tick (no new writes) is a no-op
        assert c.tick() == 0

    def test_domain_starts_a_worker(self):
        tk = TestKit()
        dom = tk.session.domain
        assert dom.gc_worker is not None
        assert dom.gc_worker._thread.is_alive()

    def test_cluster_gc_lease_single_leader(self):
        store = new_store(f"cluster://3/mgc{next(_store_id)}")
        s = Session(store)
        s.execute("create database d; use d; create table t "
                  "(a int primary key, b int)")
        s.execute("insert into t values (1, 0)")
        for i in range(4):
            s.execute(f"update t set b = {i + 1}")
        w1 = GCWorker(store, safe_age_ms=0)
        w2 = GCWorker(store, safe_age_ms=0)
        assert w1.tick() > 0          # takes the lease, collects
        assert w2.tick() == 0         # lease held by w1 → skipped
        assert w1.tick() >= 0         # holder renews fine
        assert s.execute("select b from t")[0].values() == [[4]]

    def test_lease_expiry_allows_takeover(self):
        store = new_store(f"cluster://3/mgc{next(_store_id)}")
        s = Session(store)
        s.execute("create database d; use d; create table t (a int)")
        s.execute("insert into t values (1)")
        w1 = GCWorker(store, safe_age_ms=0, lease_ms=0)  # expires instantly
        w2 = GCWorker(store, safe_age_ms=0)
        w1.tick()
        assert w2._try_lease()  # expired lease is free to take


class TestGCSafepointClamp:
    def test_active_snapshot_pins_versions(self):
        store = new_store(f"memory://mgc{next(_store_id)}")
        s = Session(store)
        s.execute("create database d; use d; create table t "
                  "(a int primary key, b int)")
        s.execute("insert into t values (1, 0)")
        snap_ts = store.current_version()
        snap = store.get_snapshot(snap_ts)     # long-running reader
        for i in range(5):
            s.execute(f"update t set b = {i + 1}")
        c = Compactor(store, safe_age_ms=0)
        c.tick()
        # the reader's version must have survived compaction
        from tidb_tpu import tablecodec as tc
        tbl = s.info_schema().table_by_name("d", "t")
        start_k, end_k = tc.encode_record_range(tbl.id)
        rows = list(snap.iterate(start_k, end_k))
        assert len(rows) == 1
        del snap, rows
        # with the reader gone, the same tick reclaims them
        s.execute("update t set b = 99")
        assert c.tick() > 0

    def test_cluster_gc_clamps_to_active_txn(self):
        store = new_store(f"cluster://3/mgc{next(_store_id)}")
        s = Session(store)
        s.execute("create database d; use d; create table t "
                  "(a int primary key, b int)")
        s.execute("insert into t values (1, 0)")
        reader = store.begin()                  # pins its start_ts
        for i in range(3):
            s.execute(f"update t set b = {i + 1}")
        w = GCWorker(store, safe_age_ms=0)
        w.tick()
        assert store.oldest_active_ts() is not None
        assert store.oldest_active_ts() <= reader.start_ts()
        reader.rollback()
