"""TPU coprocessor differential conformance: every query runs on BOTH
engines over the same store and must return identical results — the
"result parity vs CPU xeval" north-star gate (SURVEY §6).

Runs on CPU via the conftest JAX_PLATFORMS=cpu + 8 virtual devices env.
"""

import pytest

from tidb_tpu.session import Session, new_store
from tidb_tpu.ops import TpuClient


QUERIES = [
    # scans + filters
    "select id from t where a > 25 order by id",
    "select id from t where a > 10 and c < 4.0 order by id",
    "select id from t where b = 'x' order by id",
    "select id from t where b != 'x' order by id",
    "select id from t where b < 'y' order by id",
    "select id from t where b in ('x', 'z') order by id",
    "select id from t where b like 'x%' order by id",
    "select id from t where c is null order by id",
    "select id from t where c is not null order by id",
    "select id from t where a in (10, 30, 50) order by id",
    "select id from t where not (a > 25) order by id",
    "select id from t where a > 20 or b = 'x' order by id",
    "select id from t where d <= '2024-03-01' order by id",
    "select id from t where d > '2024-02-10' order by id",
    # projections over filtered scans
    "select id, a * 2 + 1 from t where a >= 20 order by id",
    # aggregates, no group
    "select count(*) from t",
    "select count(c) from t",
    "select sum(a), min(a), max(a) from t",
    "select sum(c), min(c), max(c) from t",
    "select avg(a), avg(c) from t",
    "select count(*), sum(a) from t where b = 'x'",
    "select min(b), max(b) from t",
    "select min(d), max(d) from t",
    "select count(distinct b) from t",
    "select count(distinct a) from t",
    # group by
    "select b, count(*) from t group by b order by b",
    "select b, count(*), sum(a), min(c), max(c) from t group by b order by b",
    "select b, avg(a) from t group by b order by b",
    "select b, count(*) from t where a > 15 group by b order by b",
    # group by non-dict columns (ranked kernel)
    "select a, count(*) from t group by a order by a",
    "select a, sum(c), min(c), max(b) from t group by a order by a",
    "select c, count(*) from t group by c order by c",
    "select d, count(*), sum(a) from t group by d order by d",
    "select a, b, count(*) from t group by a, b order by a, b",
    "select id, count(*) from t group by id order by id",
    "select a, count(*) from t where id > 100 group by a",
    # first_row on a non-group column (exact first-in-scan-order)
    "select b, a from t group by b order by b",
    "select a, c from t group by a order by a",
    "select b, d from t group by b order by b",
    # topn / limit
    "select id from t order by a desc limit 3",
    "select id from t order by c limit 2",
    "select id from t limit 3",
    # null-heavy
    "select sum(c) from t where id > 100",       # empty result set
    "select b, sum(c) from t group by b order by b",
]


@pytest.fixture(scope="module")
def stores():
    cpu_store = new_store("memory://parity_cpu")
    tpu_store = new_store("memory://parity_tpu")
    tpu_store.set_client(TpuClient(tpu_store, dispatch_floor_rows=0))
    sessions = []
    for st in (cpu_store, tpu_store):
        s = Session(st)
        s.execute("create database test")
        s.execute("use test")
        s.execute("create table t (id bigint primary key, a int, "
                  "b varchar(32), c double, d date)")
        s.execute(
            "insert into t values "
            "(1, 10, 'x', 1.5, '2024-01-15'), "
            "(2, 20, 'y', 2.5, '2024-02-10'), "
            "(3, 30, 'x', 3.5, '2024-03-01'), "
            "(4, 40, 'z', null, '2024-04-20'), "
            "(5, 50, 'y', 4.5, null), "
            "(6, 30, null, 0.5, '2024-01-01'), "
            "(7, -5, 'xx', -1.5, '2023-12-31')")
        sessions.append(s)
    return sessions


@pytest.mark.parametrize("sql", QUERIES)
def test_parity(stores, sql):
    cpu, tpu = stores
    cpu_rows = cpu.execute(sql)[0].values()
    tpu_rows = tpu.execute(sql)[0].values()
    assert _norm(cpu_rows) == _norm(tpu_rows), sql


def _norm(rows):
    from decimal import Decimal
    out = []
    for row in rows:
        nr = []
        for v in row:
            if isinstance(v, Decimal):
                nr.append(float(v))
            elif isinstance(v, bytes):
                nr.append(v.decode())
            elif isinstance(v, float):
                nr.append(round(v, 9))
            else:
                nr.append(v)
        out.append(nr)
    return out


def test_tpu_engine_actually_used(stores):
    _, tpu = stores
    client = tpu.store.get_client()
    assert isinstance(client, TpuClient)
    assert client.stats["tpu_requests"] > 0
    # warm cache: same-shape re-query hits the columnar cache
    before = client.stats["batch_hits"]
    tpu.execute("select sum(a), min(a), max(a) from t")
    assert client.stats["batch_hits"] > before


RANKED_QUERIES = [
    "select a, count(*) from t group by a order by a",
    "select a, b, count(*) from t group by a, b order by a, b",
    "select a, b from t group by a order by a",
    "select d, count(*), sum(a) from t group by d order by d",
]


@pytest.mark.parametrize("sql", RANKED_QUERIES)
def test_ranked_group_by_stays_on_tpu(stores, sql):
    """Int/float/time/mixed group-bys must run the ranked TPU kernel, not
    silently fall back to the CPU engine (round-1 weak #6)."""
    _, tpu = stores
    client = tpu.store.get_client()
    before = (client.stats["tpu_requests"], client.stats["cpu_fallbacks"])
    tpu.execute(sql)
    assert client.stats["tpu_requests"] > before[0], sql
    assert client.stats["cpu_fallbacks"] == before[1], sql


def test_fallback_on_unsupported(stores):
    _, tpu = stores
    client = tpu.store.get_client()
    before = client.stats["cpu_fallbacks"]
    # index request → CPU engine handles it
    tpu.execute("create index idx_b on t (b)")
    tpu.execute("select id from t where b = 'x' order by id")
    assert client.stats["cpu_fallbacks"] >= before


MESH_QUERIES = [
    "select count(*), sum(a), min(a), max(a) from t",
    "select sum(c), min(c), max(c) from t",
    "select count(*), sum(a) from t where b = 'x'",
    "select b, count(*), sum(a), min(c), max(c) from t group by b order by b",
    "select b, avg(a) from t group by b order by b",
    "select b, count(*) from t where a > 15 group by b order by b",
]


@pytest.fixture(scope="module")
def mesh_store(stores):
    """Same data, TPU client sharded over the 8 virtual devices."""
    from tidb_tpu.parallel import CoprMesh
    store = new_store("memory://parity_mesh")
    store.set_client(TpuClient(store, mesh=CoprMesh(), dispatch_floor_rows=0))
    s = Session(store)
    s.execute("create database test")
    s.execute("use test")
    s.execute("create table t (id bigint primary key, a int, "
              "b varchar(32), c double, d date)")
    s.execute(
        "insert into t values "
        "(1, 10, 'x', 1.5, '2024-01-15'), (2, 20, 'y', 2.5, '2024-02-10'), "
        "(3, 30, 'x', 3.5, '2024-03-01'), (4, 40, 'z', null, '2024-04-20'), "
        "(5, 50, 'y', 4.5, null), (6, 30, null, 0.5, '2024-01-01'), "
        "(7, -5, 'xx', -1.5, '2023-12-31')")
    return s


@pytest.mark.parametrize("sql", MESH_QUERIES)
def test_mesh_parity(stores, mesh_store, sql):
    """8-way sharded execution with psum/pmin/pmax combine must match the
    single-engine CPU results exactly."""
    import jax
    assert len(jax.devices()) == 8  # conftest virtual devices
    cpu, _ = stores
    cpu_rows = cpu.execute(sql)[0].values()
    mesh_rows = mesh_store.execute(sql)[0].values()
    assert _norm(cpu_rows) == _norm(mesh_rows), sql
    client = mesh_store.store.get_client()
    assert client.stats["tpu_requests"] > 0


MESH_NUMERIC_GROUP_QUERIES = [
    "select a, count(*), sum(c) from t group by a order by a",
    "select d, count(*), sum(a) from t group by d order by d",
    "select c, count(*) from t group by c order by c",
    "select a, b, count(*), min(c) from t group by a, b order by a, b",
]


@pytest.mark.parametrize("sql", MESH_NUMERIC_GROUP_QUERIES)
def test_mesh_numeric_group_keys(stores, mesh_store, sql):
    """int/float/date group keys must be mesh-combinable (host-built global
    dictionary codes → radix group ids → psum over ICI), NOT silent CPU
    fallbacks (round-2 weak #1)."""
    cpu, _ = stores
    client = mesh_store.store.get_client()
    before = client.stats["cpu_fallbacks"]
    cpu_rows = cpu.execute(sql)[0].values()
    mesh_rows = mesh_store.execute(sql)[0].values()
    assert _norm(cpu_rows) == _norm(mesh_rows), sql
    assert client.stats["cpu_fallbacks"] == before, sql


def test_set_copr_backend_sysvar():
    """SET tidb_copr_backend='tpu' must install/route to the TPU engine;
    'cpu' restores the default engine (round-1 weak #3: the var was dead)."""
    s = Session(new_store("memory://sysvar_route"))
    s.execute("create database sv")
    s.execute("use sv")
    s.execute("create table t (id bigint primary key, a int)")
    s.execute("insert into t values (1, 10), (2, 20)")
    assert not isinstance(s.store.get_client(), TpuClient)

    s.execute("set tidb_copr_backend = 'tpu'")
    client = s.store.get_client()
    assert isinstance(client, TpuClient)
    # default dispatch floor: a 2-row scan cannot amortize the device
    # round trip — the CPU engine answers, and no device dispatch happens
    assert client.dispatch_floor_rows > 0
    assert s.execute("select sum(a) from t")[0].values() == [[30]]
    assert client.stats["small_to_cpu"] > 0
    assert client.stats["tpu_requests"] == 0
    # dropping the floor routes the same query to the device
    s.execute("set global tidb_tpu_dispatch_floor = 0")
    assert client.dispatch_floor_rows == 0
    assert s.execute("select sum(a) from t")[0].values() == [[30]]
    assert client.stats["tpu_requests"] > 0

    s.execute("set tidb_copr_backend = 'cpu'")
    assert not isinstance(s.store.get_client(), TpuClient)
    assert s.execute("select sum(a) from t")[0].values() == [[30]]

    with pytest.raises(Exception):
        s.execute("set tidb_copr_backend = 'gpu'")


class TestMeshHighNdvMinMax:
    """Regression: with num_segments > ONEHOT_SEGMENTS_MAX the sorted
    min/max route gathers at segment boundaries; a chip whose shard holds
    NO rows of a group must contribute the sentinel there, not a
    neighboring segment's value, or pmin/pmax combines go wrong."""

    def test_grouped_minmax_across_shards(self):
        from tidb_tpu.parallel import CoprMesh
        cpu_store = new_store("memory://ndvmm_cpu")
        mesh_store_ = new_store("memory://ndvmm_mesh")
        mesh_store_.set_client(TpuClient(mesh_store_, mesh=CoprMesh(), dispatch_floor_rows=0))
        for st in (cpu_store, mesh_store_):
            s = Session(st)
            s.execute("create database d")
            s.execute("use d")
            s.execute("create table t (id bigint primary key, g int, "
                      "v int)")
            # 100 groups (> ONEHOT_SEGMENTS_MAX), CONTIGUOUS by handle so
            # row-sharding leaves most groups absent from most shards
            vals = ", ".join(
                f"({i}, {i // 8}, {(i * 37) % 1000})" for i in range(800))
            s.execute(f"insert into t values {vals}")
            if st is cpu_store:
                cpu_s = s
            else:
                mesh_s = s
        sql = ("select g, min(v), max(v), count(*) from t "
               "group by g order by g")
        cpu_rows = cpu_s.execute(sql)[0].values()
        mesh_rows = mesh_s.execute(sql)[0].values()
        assert _norm(cpu_rows) == _norm(mesh_rows)
        assert mesh_store_.get_client().stats["tpu_requests"] > 0


class TestRankLadderOverflowCompactsToTuple:
    """Single chip keeps the device-side sort-rank path, but when a group
    cardinality exceeds even the top rank bucket the client must compact
    to host-built composite tuple codes instead of raising Unsupported
    (round-3 verdict item 5: cardinality-agnostic group keys, matching
    store/localstore/local_aggregate.go:28)."""

    def test_overflow_falls_through_to_tuple_codes(self, monkeypatch):
        store = new_store("memory://rankovf")
        store.set_client(TpuClient(store, dispatch_floor_rows=0))
        s = Session(store)
        s.execute("create database d; use d")
        s.execute("create table t (id bigint primary key, g bigint, "
                  "h bigint, v int)")
        # 300 distinct (g, h) pairs; cross product 301*301 >> 64 so a
        # shrunken RADIX_MAX_SEGMENTS lowers to rank, and shrunken rank
        # caps force ladder overflow -> tuple compaction
        vals = ", ".join(
            f"({i}, {i % 300}, {(i * 7) % 300}, {i % 13})"
            for i in range(900))
        s.execute(f"insert into t values {vals}")

        from tidb_tpu.ops import client as cl, kernels
        monkeypatch.setattr(kernels, "RADIX_MAX_SEGMENTS", 1 << 10)
        monkeypatch.setattr(cl.TpuClient, "_RANK_CAPS", (17, 65))
        client = store.get_client()
        before = (client.stats["tpu_requests"], client.stats["cpu_fallbacks"])
        rows = s.execute("select g, h, count(*), sum(v) from t "
                         "group by g, h order by g, h")[0].values()
        assert client.stats["tpu_requests"] > before[0]
        assert client.stats["cpu_fallbacks"] == before[1]
        assert len(rows) == 300
        # oracle: python-side recompute
        import collections
        agg = collections.defaultdict(lambda: [0, 0])
        for i in range(900):
            k = (i % 300, (i * 7) % 300)
            agg[k][0] += 1
            agg[k][1] += i % 13
        expect = [[g, h, c, v] for (g, h), (c, v) in sorted(agg.items())]
        assert [[int(x) for x in r] for r in rows] == expect


def test_topn_limit_one():
    """LIMIT 1 through the TPU top-k path (regression: unpack_outputs
    scalarizes length-1 outputs; the index slice must restore the axis)."""
    store = new_store("memory://topn1")
    store.set_client(TpuClient(store, dispatch_floor_rows=0))
    s = Session(store)
    s.execute("create database d; use d")
    s.execute("create table t (a bigint primary key, b int)")
    s.execute("insert into t values (1, 30), (2, 10), (3, 20)")
    client = store.get_client()
    before = client.stats["tpu_requests"]
    assert s.execute("select a from t order by b limit 1")[0].values() == \
        [[2]]
    assert s.execute("select a from t order by b desc limit 1")[0] \
        .values() == [[1]]
    assert client.stats["tpu_requests"] > before
