"""Observability floor: HTTP status endpoint, slow-query log,
schema-validity kill-switch.

Reference: server/server.go:213 (status HTTP), executor_distsql.go:849
([TIME_TABLE_SCAN] slow logs), domain/domain.go:45,474 (schema validity).
"""

from __future__ import annotations

import json
import logging
import time
import urllib.request

import pytest

from tidb_tpu import errors
from tidb_tpu.server import Client, Server
from tidb_tpu.session import Session, new_store
from tests.testkit import TestKit, _store_id


class TestStatusHTTP:
    def test_status_and_metrics_endpoints(self):
        srv = Server(new_store(f"memory://obs{next(_store_id)}"),
                     status_port=0)
        srv.start()
        try:
            c = Client("127.0.0.1", srv.port)
            c.query("create database d; use d; "
                    "create table t (a int primary key); "
                    "insert into t values (1)")
            st = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.status_port}/status", timeout=5))
            assert st["connections"] == 1
            assert "TiDB" in st["version"]
            assert "tpu_requests" in st["copr"]
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.status_port}/metrics",
                timeout=5).read().decode()
            assert "session_run_seconds_count" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.status_port}/nope", timeout=5)
            c.close()
        finally:
            srv.close()

    def test_status_disabled_by_default(self):
        srv = Server(new_store(f"memory://obs{next(_store_id)}"))
        srv.start()
        try:
            assert srv._status_httpd is None
        finally:
            srv.close()


def _parse_prometheus(body: str) -> dict:
    """Tiny Prometheus text-format parser: name{labels} value lines →
    {name: value} for plain samples, {name: {le: cum}} for buckets."""
    out: dict = {}
    for line in body.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, value = line.rsplit(" ", 1)
        if "{" in name_part:
            name, labels = name_part.split("{", 1)
            labels = labels.rstrip("}")
            kv = dict(p.split("=", 1) for p in labels.split(","))
            le = kv.get('le', '').strip('"')
            out.setdefault(name, {})[le] = float(value)
        else:
            out[name_part] = float(value)
    return out


class TestMetricsExposition:
    def test_histogram_bucket_round_trip(self):
        """Histograms on /metrics emit conformant cumulative
        _bucket{le=...} series: parse the endpoint's text back and check
        monotonicity, the mandatory +Inf bucket == _count, and counter
        agreement with the live registry."""
        from tidb_tpu import metrics
        srv = Server(new_store(f"memory://obs{next(_store_id)}"),
                     status_port=0)
        srv.start()
        try:
            c = Client("127.0.0.1", srv.port)
            c.query("create database mh; use mh; "
                    "create table t (a int primary key)")
            for i in range(5):
                c.query(f"insert into t values ({i})")
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.status_port}/metrics",
                timeout=5).read().decode()
            parsed = _parse_prometheus(body)
            buckets = parsed["session_run_seconds_bucket"]
            assert "+Inf" in buckets, "mandatory +Inf bucket missing"
            # cumulative and monotone over ascending bounds
            finite = sorted((float(le), v) for le, v in buckets.items()
                            if le != "+Inf")
            cum = [v for _le, v in finite]
            assert cum == sorted(cum), "bucket counts not cumulative"
            assert all(v <= buckets["+Inf"] for v in cum)
            # +Inf == _count, and _sum present
            assert buckets["+Inf"] == parsed["session_run_seconds_count"]
            assert parsed["session_run_seconds_sum"] >= 0
            # registry agreement (>=: the registry is process-global and
            # background loops may observe after the HTTP fetch)
            hist = metrics.histogram("session.run_seconds")
            assert hist.count >= parsed["session_run_seconds_count"] > 0
            assert metrics.counter("server.connections_total").value >= \
                parsed["server_connections_total"] >= 1
            # SHOW STATUS (registry snapshot) exposes the same series
            snap = dict(metrics.registry.snapshot())
            assert float(snap["session.run_seconds_count"]) >= \
                parsed["session_run_seconds_count"]
            c.close()
        finally:
            srv.close()


class TestSlowQueryLog:
    def test_threshold_triggers_log(self):
        records = []

        class H(logging.Handler):
            def emit(self, r):
                records.append(r.getMessage())

        h = H()
        logging.getLogger("tidb_tpu.slowlog").addHandler(h)
        try:
            tk = TestKit()
            tk.exec("create database test")
            tk.exec("use test")
            tk.exec("create table t (a int primary key)")
            # below-threshold statements don't log (threshold set high so
            # a loaded machine can't push a fast insert over it; bootstrap
            # DDL may legitimately cross the 300ms default under load)
            tk.exec("set tidb_slow_log_threshold = 60000")
            tk.exec("insert into t values (0)")
            assert not any("insert into t values (0)" in m
                           for m in records)
            tk.exec("set tidb_slow_log_threshold = 0.0001")
            tk.exec("insert into t values (1)")
            assert any("[SLOW_QUERY]" in m and "insert into t" in m
                       for m in records)
            records.clear()
            tk.exec("set tidb_slow_log_threshold = 0")   # 0 disables
            tk.exec("insert into t values (2)")
            assert not any("insert into t values (2)" in m
                           for m in records)
        finally:
            logging.getLogger("tidb_tpu.slowlog").removeHandler(h)


class TestSchemaValidityKillSwitch:
    def test_stale_schema_fails_statements(self):
        tk = TestKit()
        tk.exec("create database test")
        tk.exec("use test")
        tk.exec("create table t (a int primary key)")
        dom = tk.session.domain
        dom.start_reload_loop(interval_s=3600)   # effectively stalled
        try:
            dom.schema_validity_lease_s = 0.05
            dom._last_reload_ok = time.monotonic() - 1.0  # stale
            with pytest.raises(errors.TiDBError) as ei:
                tk.exec("select * from t")
            assert getattr(ei.value, "code", None) == 8027
            # recovery: a successful reload clears the condition
            dom.mark_reload_ok()
            tk.exec("select * from t")
        finally:
            dom.schema_validity_lease_s = 0.0
            dom.close()

    def test_disabled_without_reload_loop(self):
        tk = TestKit()
        tk.exec("create database test")
        tk.exec("use test")
        dom = tk.session.domain
        dom.schema_validity_lease_s = 0.001
        try:
            time.sleep(0.01)
            # no reload loop running → embedding is synchronously current
            tk.exec("create table t2 (a int primary key)")
        finally:
            dom.schema_validity_lease_s = 0.0
