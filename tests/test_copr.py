"""Coprocessor conformance tests — SelectRequests against the CPU engine
through the LocalClient fan-out.

Mirrors store/localstore/xapi_test.go (275 LoC: Select/Index requests
against the local coprocessor directly). These fixtures define the contract
the TPU engine must match; test_tpu_copr reuses them differentially.
"""

from decimal import Decimal

import pytest

from tidb_tpu import mysqldef as my, tablecodec as tc
from tidb_tpu.copr import (
    ByItem, SelectRequest, columns_to_proto, expr_agg, expr_column, expr_op,
    expr_value, index_to_proto,
)
from tidb_tpu.copr.proto import iter_response_rows
from tidb_tpu.ddl.ddl import ColumnSpec, IndexSpec
from tidb_tpu.domain import Domain, clear_domains
from tidb_tpu.kv import kv
from tidb_tpu.localstore import LocalStore
from tidb_tpu.sqlast.opcode import Op
from tidb_tpu.types import Datum, datum_from_py
from tidb_tpu.types.field_type import FieldType


def _ft(tp, flag=0, flen=-1, dec=-1):
    return FieldType(tp, flag, flen, dec)


@pytest.fixture
def env():
    clear_domains()
    store = LocalStore()
    dom = Domain(store)
    dom.ddl.create_schema("test")
    dom.ddl.create_table("test", "t", [
        ColumnSpec("id", _ft(my.TypeLonglong)),
        ColumnSpec("name", _ft(my.TypeVarchar, flen=64)),
        ColumnSpec("score", _ft(my.TypeDouble)),
    ], [IndexSpec("primary", ["id"], primary=True),
        IndexSpec("idx_name", ["name"])])
    tbl = dom.info_schema().table_by_name("test", "t")
    rows = [
        (1, "alice", 90.0),
        (2, "bob", 75.5),
        (3, "carol", 90.0),
        (4, "dave", None),
        (5, "bob", 60.0),
    ]
    txn = store.begin()
    for rid, name, score in rows:
        tbl.add_record(txn, [datum_from_py(rid), datum_from_py(name),
                             datum_from_py(score)])
    txn.commit()
    return store, tbl


def _table_req(store, tbl, **kwargs):
    info = tbl.info
    pb_cols = columns_to_proto(info.columns, info.pk_is_handle)
    from tidb_tpu.copr.proto import PBTableInfo
    return SelectRequest(
        start_ts=store.current_version(),
        table_info=PBTableInfo(info.id, pb_cols), **kwargs)


def _send(store, req, tp=kv.REQ_TYPE_SELECT, ranges=None, table_id=None,
          concurrency=4, keep_order=False):
    if ranges is None:
        start, end = tc.encode_record_range(table_id)
        ranges = [kv.KeyRange(start, end)]
    client = store.get_client()
    resp = client.send(kv.Request(tp=tp, data=req, key_ranges=ranges,
                                  concurrency=concurrency,
                                  keep_order=keep_order))
    rows = []
    while True:
        part = resp.next()
        if part is None:
            break
        assert part.error is None, part.error
        rows.extend(iter_response_rows(part))
    return rows


def col_id(tbl, name):
    return tbl.info.find_column(name).id


class TestTableScan:
    def test_full_scan(self, env):
        store, tbl = env
        req = _table_req(store, tbl)
        rows = _send(store, req, table_id=tbl.info.id)
        assert len(rows) == 5
        handles = [h for h, _ in rows]
        assert handles == [1, 2, 3, 4, 5]
        # row layout follows table_info.columns order
        first = rows[0][1]
        assert first[0].val == 1
        assert first[1].get_string() == "alice"
        assert first[2].val == 90.0

    def test_filter(self, env):
        store, tbl = env
        where = expr_op(Op.GE, expr_column(col_id(tbl, "score")),
                        expr_value(Datum.f64(80)))
        req = _table_req(store, tbl, where=where)
        rows = _send(store, req, table_id=tbl.info.id)
        assert [h for h, _ in rows] == [1, 3]

    def test_filter_null_semantics(self, env):
        store, tbl = env
        # score < 100 excludes the NULL row (dave)
        where = expr_op(Op.LT, expr_column(col_id(tbl, "score")),
                        expr_value(Datum.f64(100)))
        rows = _send(store, _table_req(store, tbl, where=where),
                     table_id=tbl.info.id)
        assert [h for h, _ in rows] == [1, 2, 3, 5]

    def test_limit_and_desc(self, env):
        store, tbl = env
        rows = _send(store, _table_req(store, tbl, limit=2),
                     table_id=tbl.info.id)
        assert len(rows) == 2
        rows = _send(store, _table_req(store, tbl, limit=2, desc=True),
                     table_id=tbl.info.id)
        assert [h for h, _ in rows] == [5, 4]

    def test_point_range(self, env):
        store, tbl = env
        k = tc.encode_row_key(tbl.info.id, 3)
        rows = _send(store, _table_req(store, tbl),
                     ranges=[kv.KeyRange(k, k + b"\x00")])
        assert [h for h, _ in rows] == [3]

    def test_multi_region(self, env):
        store, tbl = env
        # split the table across 3 regions mid-keyspace
        store.regions.split_keys([tc.encode_row_key(tbl.info.id, 2),
                                  tc.encode_row_key(tbl.info.id, 4)])
        rows = _send(store, _table_req(store, tbl), table_id=tbl.info.id,
                     keep_order=True)
        assert [h for h, _ in rows] == [1, 2, 3, 4, 5]


class TestTopN:
    def test_topn_asc_desc(self, env):
        store, tbl = env
        order = [ByItem(expr_column(col_id(tbl, "score")), desc=True),
                 ByItem(expr_column(col_id(tbl, "id")))]
        req = _table_req(store, tbl, order_by=order, limit=3)
        rows = _send(store, req, table_id=tbl.info.id)
        # NULL sorts first ascending, last descending... desc=True on score:
        # 90(id1), 90(id3), 75.5(id2)
        assert [h for h, _ in rows] == [1, 3, 2]

    def test_topn_nulls(self, env):
        store, tbl = env
        order = [ByItem(expr_column(col_id(tbl, "score")))]
        req = _table_req(store, tbl, order_by=order, limit=2)
        rows = _send(store, req, table_id=tbl.info.id)
        # ascending: NULL first, then 60
        assert [h for h, _ in rows] == [4, 5]


class TestAggregate:
    def test_singleton_aggs(self, env):
        store, tbl = env
        sc = col_id(tbl, "score")
        req = _table_req(store, tbl, aggregates=[
            expr_agg("count", [expr_column(col_id(tbl, "id"))]),
            expr_agg("sum", [expr_column(sc)]),
            expr_agg("min", [expr_column(sc)]),
            expr_agg("max", [expr_column(sc)]),
        ])
        rows = _send(store, req, table_id=tbl.info.id)
        assert len(rows) == 1
        _, vals = rows[0]
        # layout: [group_key, count, sum_val, min_val, max_val]
        assert vals[0].val == b""
        assert vals[1].val == 5
        assert float(vals[2].val) == pytest.approx(315.5)
        assert vals[3].val == 60.0
        assert vals[4].val == 90.0

    def test_group_by(self, env):
        store, tbl = env
        name_c = expr_column(col_id(tbl, "name"))
        req = _table_req(
            store, tbl,
            group_by=[ByItem(name_c)],
            aggregates=[expr_agg("count", [expr_column(col_id(tbl, "id"))])])
        rows = _send(store, req, table_id=tbl.info.id)
        counts = {}
        from tidb_tpu.codec import codec
        for _, vals in rows:
            gk = codec.decode_all(vals[0].val)
            counts[gk[0].get_string()] = vals[1].val
        assert counts == {"alice": 1, "bob": 2, "carol": 1, "dave": 1}

    def test_partial_agg_across_regions(self, env):
        """Multi-region agg emits per-region partials; counts per group sum
        to the true totals — the partial/final split the TPU psum relies on."""
        store, tbl = env
        store.regions.split(tc.encode_row_key(tbl.info.id, 3))
        req = _table_req(
            store, tbl,
            group_by=[ByItem(expr_column(col_id(tbl, "name")))],
            aggregates=[expr_agg("count", [expr_column(col_id(tbl, "id"))])])
        rows = _send(store, req, table_id=tbl.info.id)
        from tidb_tpu.codec import codec
        merged = {}
        for _, vals in rows:
            g = codec.decode_all(vals[0].val)[0].get_string()
            merged[g] = merged.get(g, 0) + vals[1].val
        assert merged == {"alice": 1, "bob": 2, "carol": 1, "dave": 1}
        # bob spans regions → appears as two partial rows
        assert len(rows) == 5


class TestIndexScan:
    def test_index_scan_ordered(self, env):
        store, tbl = env
        idx = tbl.info.find_index("idx_name")
        pb = index_to_proto(tbl.info, idx)
        req = SelectRequest(start_ts=store.current_version(), index_info=pb)
        start = tc.encode_index_seek_key(tbl.info.id, idx.id)
        end = start + b"\xff" * 9
        rows = _send(store, req, tp=kv.REQ_TYPE_INDEX,
                     ranges=[kv.KeyRange(start, end)])
        names = [vals[0].get_string() for _, vals in rows]
        assert names == ["alice", "bob", "bob", "carol", "dave"]
        handles = [h for h, _ in rows]
        assert handles == [1, 2, 5, 3, 4]


class TestReviewRegressions:
    """Regressions from code review: serial fan-out deadlock, desc ordering
    across regions, distinct-agg pushdown rejection."""

    def test_many_regions_serial_no_deadlock(self, env):
        store, tbl = env
        store.regions.split_keys([tc.encode_row_key(tbl.info.id, h)
                                  for h in range(-20, 20, 3)])
        rows = _send(store, _table_req(store, tbl), table_id=tbl.info.id,
                     concurrency=1)
        assert [h for h, _ in rows] == [1, 2, 3, 4, 5]

    def test_desc_across_regions_with_limit(self, env):
        store, tbl = env
        store.regions.split_keys([tc.encode_row_key(tbl.info.id, 2),
                                  tc.encode_row_key(tbl.info.id, 4)])
        rows = _send(store, _table_req(store, tbl, desc=True, limit=3),
                     table_id=tbl.info.id)
        assert [h for h, _ in rows][:3] == [5, 4, 3]

    def test_distinct_agg_not_supported(self, env):
        from tidb_tpu.copr.xeval import supported_expr
        e = expr_agg("count", [expr_column(1)], distinct=True)
        assert not supported_expr(e)
        assert supported_expr(expr_agg("count", [expr_column(1)]))
