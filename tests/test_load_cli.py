"""LOAD DATA, CLI daemon, and workload harness tests.

Mirrors: executor/executor_write.go LoadData + server/conn.go:507
(LOCAL streaming), tidb-server/main.go flags, cmd/benchdb / cmd/benchkv.
"""

import os
import tempfile

import pytest

from tidb_tpu import errors
from tidb_tpu.server import Client, Server
from tidb_tpu.session import Session, new_store
from tests.testkit import TestKit, _store_id


def _write(content: str) -> str:
    fd, path = tempfile.mkstemp()
    with os.fdopen(fd, "w") as f:
        f.write(content)
    return path


class TestLoadData:
    def test_tab_separated_with_nulls(self):
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int, b varchar(20), c double)")
        path = _write("1\thello\t1.5\n2\t\\N\t2.5\n")
        try:
            tk.exec(f"load data infile '{path}' into table t")
            assert tk.session.vars.affected_rows == 2
            tk.exec("select * from t order by a").check(
                [[1, "hello", 1.5], [2, None, 2.5]])
        finally:
            os.unlink(path)

    def test_csv_options_ignore_and_columns(self):
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int, b varchar(8), c int)")
        path = _write('skip me\n"1","x"\n"2","y"\n')
        try:
            tk.exec(f"load data infile '{path}' into table t "
                    "fields terminated by ',' enclosed by '\"' "
                    "lines terminated by '\\n' ignore 1 lines (a, b)")
            tk.exec("select * from t order by a").check(
                [[1, "x", None], [2, "y", None]])
        finally:
            os.unlink(path)

    def test_missing_file_errors(self):
        tk = TestKit()
        tk.exec("create database d; use d; create table t (a int)")
        with pytest.raises(errors.TiDBError):
            tk.exec("load data infile '/no/such/file' into table t")

    def test_local_infile_over_the_wire(self):
        store = new_store(f"memory://ldw{next(_store_id)}")
        srv = Server(store)
        srv.start()
        path = _write("5\tfive\n6\tsix\n")
        try:
            c = Client("127.0.0.1", srv.port, local_infile=True)
            c.query("create database d; use d; "
                    "create table t (a int, b varchar(8))")
            r = c.query(f"load data local infile '{path}' into table t")
            assert r[0].affected == 2
            assert c.query("select * from t order by a")[0].rows == \
                [["5", "five"], ["6", "six"]]
            c.close()
        finally:
            os.unlink(path)
            srv.close()

    def test_local_infile_requires_capability(self):
        """A client that didn't negotiate CLIENT_LOCAL_FILES gets
        ER_NOT_ALLOWED_COMMAND, not a hanging 0xFB exchange."""
        from tidb_tpu.server import MySQLError
        store = new_store(f"memory://ldw{next(_store_id)}")
        srv = Server(store)
        srv.start()
        try:
            c = Client("127.0.0.1", srv.port)  # no local_infile opt-in
            c.query("create database d; use d; create table t (a int)")
            with pytest.raises(MySQLError) as ei:
                c.query("load data local infile '/tmp/x' into table t")
            assert ei.value.code == 1148
            assert c.query("select 1")[0].rows == [["1"]]  # still in sync
            c.close()
        finally:
            srv.close()

    def test_local_infile_missing_file_raises_client_side(self):
        from tidb_tpu.server import MySQLError
        store = new_store(f"memory://ldw{next(_store_id)}")
        srv = Server(store)
        srv.start()
        try:
            c = Client("127.0.0.1", srv.port, local_infile=True)
            c.query("create database d; use d; create table t (a int)")
            with pytest.raises(MySQLError):
                c.query("load data local infile '/no/such/f' into table t")
            c.close()
        finally:
            srv.close()

    def test_non_local_denied_for_authenticated_users(self):
        from tidb_tpu.server import MySQLError
        store = new_store(f"memory://ldw{next(_store_id)}")
        srv = Server(store)
        srv.start()
        path = _write("1\n")
        try:
            c = Client("127.0.0.1", srv.port)
            c.query("create database d; use d; create table t (a int)")
            with pytest.raises(MySQLError):  # server file read blocked
                c.query(f"load data infile '{path}' into table t")
            c.close()
        finally:
            os.unlink(path)
            srv.close()

    def test_enclosed_field_with_embedded_terminator(self):
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a varchar(16), b int)")
        path = _write('"a,b",2\n"x",3\n')
        try:
            tk.exec(f"load data infile '{path}' into table t "
                    "fields terminated by ',' enclosed by '\"'")
            tk.exec("select * from t order by b").check(
                [["a,b", 2], ["x", 3]])
        finally:
            os.unlink(path)

    def test_escaped_backslash_then_n_stays_literal(self):
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a varchar(16))")
        path = _write("a\\\\nb\n")  # file holds: a \ \ n b
        try:
            tk.exec(f"load data infile '{path}' into table t")
            got = tk.exec("select a from t").rows[0][0]
            got = got if isinstance(got, str) else got.decode()
            assert got == "a\\nb"  # literal backslash + n, NOT newline
        finally:
            os.unlink(path)

    def test_load_error_rolls_back_partial_rows(self):
        tk = TestKit()
        tk.exec("create database d; use d")
        tk.exec("create table t (a int not null)")
        path = _write("1\n2\n\\N\n")  # third row violates NOT NULL
        try:
            with pytest.raises(errors.TiDBError):
                tk.exec(f"load data infile '{path}' into table t")
            tk.exec("insert into t values (9)")  # next autocommit stmt
            tk.exec("select * from t").check([[9]])  # no partial rows
        finally:
            os.unlink(path)

    def test_load_requires_insert_priv(self):
        tk = TestKit()
        tk.exec("create database d; use d; create table t (a int)")
        tk.exec("create user 'ld1'")
        tk.exec("grant select on d.* to 'ld1'")
        path = _write("1\n")
        try:
            s = Session(tk.store)
            s.vars.user = "ld1"
            s.vars.current_db = "d"
            from tidb_tpu.privilege import AccessDenied
            with pytest.raises(AccessDenied):
                s.execute(f"load data infile '{path}' into table t")
        finally:
            os.unlink(path)


class TestCLI:
    def test_daemon_serves_wire_protocol(self):
        from tidb_tpu.cli import build_arg_parser, open_store
        args = build_arg_parser().parse_args(
            ["--store", "memory", "--path", f"cli{next(_store_id)}",
             "--port", "0"])
        store = open_store(args)
        srv = Server(store, host=args.host, port=args.port,
                     token_limit=args.token_limit)
        srv.start()
        try:
            c = Client("127.0.0.1", srv.port)
            c.query("select 1")
            c.close()
        finally:
            srv.close()

    def test_tpu_copr_flag_installs_engine(self):
        from tidb_tpu.cli import build_arg_parser, open_store
        from tidb_tpu.ops import TpuClient
        args = build_arg_parser().parse_args(
            ["--store", "memory", "--path", f"cli{next(_store_id)}",
             "--copr", "tpu"])
        store = open_store(args)
        assert isinstance(store.get_client(), TpuClient)


class TestHarnesses:
    def test_benchdb_jobs(self, capsys):
        from tidb_tpu.cmd.benchdb import main
        assert main(["--store", f"memory://bd{next(_store_id)}",
                     "--run", "create,insert:0_200,select:0_200:2,"
                     "update-range:10_20:2,truncate,gc"]) == 0
        out = capsys.readouterr().out
        assert "insert:0_200" in out and "gc" in out

    def test_benchkv_commits_all_keys(self, capsys):
        from tidb_tpu.cmd.benchkv import main
        assert main(["--store", f"memory://bk{next(_store_id)}",
                     "-N", "2000", "-C", "4"]) == 0
        assert "2000 keys committed, 0 failed" in capsys.readouterr().out
