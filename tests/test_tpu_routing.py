"""Cost-based TPU routing: requests below the device dispatch floor
answer on the CPU engine, above it on the device — with result parity
across the boundary.

The floor prices the flat device dispatch+readback round trip against the
CPU engine's per-row cost, the same tradeoff the reference encodes per
access path via netWorkFactor/cpuFactor (plan/physical_plans.go:70-84).
Two mechanisms, both covered here:
  * pre-pack: planner histograms (ANALYZE) put est_rows on the request —
    small scans route to CPU without packing a batch at all
  * post-pack backstop: pseudo-stats scans pack once, and the exact batch
    size routes every (cached) repeat below the floor to CPU
"""

import pytest

from tidb_tpu.ops import TpuClient
from tidb_tpu.ops import client as tpu_client_mod
from tidb_tpu.session import Session, new_store


def _tpu_session(name: str, floor: int):
    store = new_store(f"memory://{name}")
    client = TpuClient(store, dispatch_floor_rows=floor)
    store.set_client(client)
    s = Session(store)
    s.execute("create database r")
    s.execute("use r")
    return s, client


def test_default_floor_matches_sysvar_default():
    from tidb_tpu.sessionctx import SYSVAR_DEFAULTS
    assert SYSVAR_DEFAULTS["tidb_tpu_dispatch_floor"] == \
        str(tpu_client_mod.DISPATCH_FLOOR_ROWS)
    assert TpuClient(new_store("memory://floor_default")) \
        .dispatch_floor_rows == tpu_client_mod.DISPATCH_FLOOR_ROWS


def test_small_scan_routes_cpu_without_pack_when_analyzed():
    s, client = _tpu_session("route_pre", floor=8)
    s.execute("create table t (id bigint primary key, a int)")
    s.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    s.execute("analyze table t")
    assert s.execute("select sum(a) from t")[0].values() == [[60]]
    # histogram estimate (3 rows) < floor: no device dispatch AND no pack
    assert client.stats["small_to_cpu"] > 0
    assert client.stats["tpu_requests"] == 0
    assert client.stats["batch_packs"] == 0


def test_small_scan_routes_cpu_via_exact_backstop_without_stats():
    s, client = _tpu_session("route_post", floor=8)
    s.execute("create table t (id bigint primary key, a int)")
    s.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    # no ANALYZE: pseudo stats leave est_rows unset, so the engine packs
    # once and the exact (3-row) batch falls below the floor
    assert s.execute("select sum(a) from t")[0].values() == [[60]]
    assert client.stats["small_to_cpu"] == 1
    assert client.stats["tpu_requests"] == 0
    assert client.stats["batch_packs"] == 1
    # repeat: the cached batch answers the floor check — no repack
    assert s.execute("select sum(a) from t")[0].values() == [[60]]
    assert client.stats["small_to_cpu"] == 2
    assert client.stats["batch_packs"] == 1
    assert client.stats["batch_hits"] >= 1


def test_large_scan_routes_tpu_above_floor():
    s, client = _tpu_session("route_big", floor=8)
    s.execute("create table t (id bigint primary key, a int)")
    rows = ", ".join(f"({i}, {i * 3})" for i in range(1, 21))
    s.execute(f"insert into t values {rows}")
    want = sum(i * 3 for i in range(1, 21))
    assert s.execute("select sum(a) from t")[0].values() == [[want]]
    assert client.stats["tpu_requests"] > 0
    assert client.stats["small_to_cpu"] == 0
    # with ANALYZE the pre-pack estimate agrees: still the device
    s.execute("analyze table t")
    assert s.execute("select sum(a) from t")[0].values() == [[want]]
    assert client.stats["small_to_cpu"] == 0


def test_parity_across_the_routing_boundary():
    """The same query set must answer identically on either side of the
    floor — routing is a performance decision, never a semantic one."""
    queries = [
        "select sum(a), min(a), max(a), count(*) from t",
        "select b, count(*), avg(a) from t group by b order by b",
        "select count(distinct b) from t",
        "select id from t where a > 9 order by a desc limit 3",
    ]
    results = {}
    for floor in (0, 1_000_000):
        s, client = _tpu_session(f"route_parity_{floor}", floor=floor)
        s.execute("create table t (id bigint primary key, a int, "
                  "b varchar(10))")
        rows = ", ".join(f"({i}, {i % 7}, 'g{i % 3}')" for i in range(1, 31))
        s.execute(f"insert into t values {rows}")
        results[floor] = [s.execute(q)[0].values() for q in queries]
        if floor == 0:
            assert client.stats["tpu_requests"] > 0
        else:
            assert client.stats["tpu_requests"] == 0
    assert results[0] == results[1_000_000]


def test_distinct_below_floor_stays_request_global():
    """Distinct aggregates were admitted on the promise of request-global
    execution — the small-route must preserve that on a cluster store,
    where the plain CPU path would under-merge per-region partials."""
    store = new_store("cluster://4/route_distinct")
    client = TpuClient(store, dispatch_floor_rows=1_000_000)
    store.set_client(client)
    s = Session(store)
    s.execute("create database r")
    s.execute("use r")
    s.execute("create table t (id bigint primary key, a int)")
    rows = ", ".join(f"({i}, {i % 5})" for i in range(1, 41))
    s.execute(f"insert into t values {rows}")
    assert s.execute("select count(distinct a) from t")[0].values() == [[5]]
    assert client.stats["small_to_cpu"] > 0
    assert client.stats["tpu_requests"] == 0


def test_index_scan_carries_estimate():
    s, client = _tpu_session("route_idx", floor=50)
    s.execute("create table t (id bigint primary key, a int, key ia (a))")
    rows = ", ".join(f"({i}, {i % 4})" for i in range(1, 101))
    s.execute(f"insert into t values {rows}")
    s.execute("analyze table t")
    # an equality on the indexed column estimates ~25 rows < floor 50:
    # the index request routes to CPU pre-pack
    r = s.execute("select id from t where a = 1 order by id")[0].values()
    assert r == [[i] for i in range(1, 101) if i % 4 == 1]
    assert client.stats["small_to_cpu"] > 0
    assert client.stats["batch_packs"] == 0


def test_sysvar_validation():
    s, client = _tpu_session("route_sysvar", floor=8)
    with pytest.raises(Exception):
        s.execute("set global tidb_tpu_dispatch_floor = -1")
    with pytest.raises(Exception):
        s.execute("set global tidb_tpu_dispatch_floor = 'lots'")
    # GLOBAL-only: a session-scoped write would re-route every session
    # through the shared store client while only this session's var
    # recorded it (review finding)
    with pytest.raises(Exception, match="GLOBAL"):
        s.execute("set tidb_tpu_dispatch_floor = 1000")
    assert client.dispatch_floor_rows == 8   # nothing mutated


def test_floor_set_before_engine_swap_is_honored():
    """A floor set while the CPU engine is active must carry into the
    TpuClient that the backend swap creates (review finding: the sysvar
    and the live floor diverged)."""
    store = new_store("memory://route_swap")
    s = Session(store)
    s.execute("create database r")
    s.execute("use r")
    s.execute("set global tidb_tpu_dispatch_floor = 17")
    s.execute("set tidb_copr_backend = 'tpu'")
    assert store.get_client().dispatch_floor_rows == 17
    s.execute("set tidb_copr_backend = 'cpu'")


def test_floor_global_survives_restart(tmp_path):
    """SET GLOBAL tidb_tpu_dispatch_floor persists to
    mysql.global_variables and must hydrate back into both the global-var
    cache and the TpuClient after a process restart (review finding: the
    CLI path reverted to the default on restart)."""
    from tidb_tpu.domain import clear_domains
    from tidb_tpu.kv.kv import close_store
    from tidb_tpu.session import _BOOTSTRAPPED_STORES, _global_vars_by_store
    url = f"local://{tmp_path}/floor_db"
    s = Session(new_store(url))
    s.execute("set global tidb_tpu_dispatch_floor = 33")
    uuid = s.store.uuid()
    # simulate process death: evict every in-memory cache for the store
    close_store(url)
    clear_domains()
    _BOOTSTRAPPED_STORES.discard(uuid)
    _global_vars_by_store.pop(uuid, None)
    s2 = Session(new_store(url))
    assert s2.global_vars.get("tidb_tpu_dispatch_floor") == "33"
    s2.execute("set tidb_copr_backend = 'tpu'")
    assert s2.store.get_client().dispatch_floor_rows == 33
    s2.execute("set tidb_copr_backend = 'cpu'")


def test_range_scan_estimates_route_pre_pack():
    """Handle-range scans carry a row estimate even without ANALYZE (the
    span of finite PK ranges bounds the rows), so selective queries on
    huge tables route to CPU before packing (review finding: est_rows
    was the whole-table count and the fast path never fired)."""
    s, client = _tpu_session("route_range", floor=50)
    s.execute("create table t (id bigint primary key, a int)")
    rows = ", ".join(f"({i}, {i})" for i in range(1, 201))
    s.execute(f"insert into t values {rows}")
    # pseudo stats: the BETWEEN span (10) bounds the rows — no pack
    r = s.execute("select sum(a) from t where id between 1 and 10")
    assert r[0].values() == [[55]]
    assert client.stats["small_to_cpu"] == 1
    assert client.stats["batch_packs"] == 0
    # analyzed: the handle histogram estimates open-ended ranges too
    s.execute("analyze table t")
    r = s.execute("select sum(a) from t where id <= 10")
    assert r[0].values() == [[55]]
    assert client.stats["small_to_cpu"] == 2
    assert client.stats["batch_packs"] == 0


def test_backend_global_survives_restart(tmp_path):
    """SET GLOBAL tidb_copr_backend='tpu' must restore the ENGINE on
    restart, not just the variable's value (review finding: hydration
    reported 'tpu' while the CPU client served)."""
    from tidb_tpu.domain import clear_domains
    from tidb_tpu.kv.kv import close_store
    from tidb_tpu.session import _BOOTSTRAPPED_STORES, _global_vars_by_store
    url = f"local://{tmp_path}/backend_db"
    s = Session(new_store(url))
    s.execute("set global tidb_tpu_dispatch_floor = 44")
    s.execute("set global tidb_copr_backend = 'tpu'")
    uuid = s.store.uuid()
    close_store(url)
    clear_domains()
    _BOOTSTRAPPED_STORES.discard(uuid)
    _global_vars_by_store.pop(uuid, None)
    s2 = Session(new_store(url))
    client = s2.store.get_client()
    assert isinstance(client, TpuClient)
    assert client.dispatch_floor_rows == 44
    s2.execute("set tidb_copr_backend = 'cpu'")
