"""Charset / collation machinery (round-3 verdict missing #10).

Reference: util/charset/charset.go (registry, ValidCharsetAndCollation,
GetDefaultCollation), parser charset/collate options, executor/show.go
charset surfaces, and *_ci collation semantics in compare / order by /
group by — the part the reference leaves binary-only but MySQL requires.
"""

import pytest

from tidb_tpu import charset as cset, errors
from tidb_tpu.session import Session, new_store
from tests.testkit import _store_id


def _session():
    return Session(new_store(f"memory://cs{next(_store_id)}"))


class TestRegistry:
    def test_defaults_and_validation(self):
        assert cset.get_default_collation("utf8") == "utf8_bin"
        assert cset.get_default_collation("UTF8MB4") == "utf8mb4_bin"
        assert cset.valid_charset_and_collation("utf8", "utf8_general_ci")
        assert not cset.valid_charset_and_collation("utf8", "latin1_bin")
        assert not cset.valid_charset_and_collation("klingon", None)
        with pytest.raises(errors.TiDBError) as ei:
            cset.get_default_collation("klingon")
        assert ei.value.code == 1115

    def test_pair_resolution(self):
        assert cset.validate_column_charset("latin1", None) == \
            ("latin1", "latin1_bin")
        assert cset.validate_column_charset(None, "utf8_general_ci") == \
            ("utf8", "utf8_general_ci")
        with pytest.raises(errors.TiDBError) as ei:
            cset.validate_column_charset("ascii", "utf8_bin")
        assert ei.value.code == 1253


class TestDDLAndShow:
    def test_ddl_errors(self):
        s = _session()
        s.execute("create database d; use d")
        for sql, code in [
                ("create table b1 (x varchar(3) character set klingon)", 1115),
                ("create table b2 (x varchar(3) collate utf8_nope)", 1273),
                ("create table b3 (x varchar(3) character set ascii "
                 "collate utf8_bin)", 1253),
                ("create database b4 charset klingon", 1115),
                ("set names klingon", 1115)]:
            with pytest.raises(errors.TiDBError) as ei:
                s.execute(sql)
            assert ei.value.code == code, sql

    def test_table_default_inheritance(self):
        s = _session()
        s.execute("create database d; use d")
        s.execute("create table t (a varchar(5), b varchar(5) collate "
                  "utf8_bin, c int) default charset=utf8 "
                  "collate=utf8_general_ci")
        info = s.info_schema().table_by_name("d", "t").info
        assert info.collate == "utf8_general_ci"
        cols = {c.name: c.field_type for c in info.columns}
        assert cols["a"].collate == "utf8_general_ci"   # inherited
        assert cols["b"].collate == "utf8_bin"          # explicit wins
        assert cols["c"].collate != "" or True          # non-string: n/a
        out = s.execute("show create table t")[0].values()[0][1]
        assert "DEFAULT CHARSET=utf8 COLLATE=utf8_general_ci" in out
        assert "`b` varchar(5) CHARACTER SET utf8 COLLATE utf8_bin" in out

    def test_show_and_information_schema(self):
        s = _session()
        charsets = s.execute("show character set")[0].values()
        assert ["utf8", "UTF-8 Unicode", "utf8_bin", "3"] in charsets
        colls = s.execute("show collation like 'utf8%'")[0].values()
        assert any(r[0] == "utf8_general_ci" and r[1] == "utf8" and
                   r[2] == "33" for r in colls)
        rows = s.execute(
            "select collation_name, id, is_default from "
            "information_schema.collations where character_set_name = "
            "'utf8mb4' order by id")[0].values()
        assert [b"utf8mb4_general_ci", 45, b""] in rows
        assert [b"utf8mb4_bin", 46, b"Yes"] in rows
        db = s.execute("create database mb4 charset utf8mb4")
        got = s.execute("select default_character_set_name from "
                        "information_schema.schemata where schema_name = "
                        "'mb4'")[0].values()
        assert got == [[b"utf8mb4"]]


class TestCiSemantics:
    @pytest.fixture
    def s(self):
        s = _session()
        s.execute("create database d; use d")
        s.execute("create table t (id bigint primary key, "
                  "a varchar(20) collate utf8_general_ci, "
                  "b varchar(20))")
        s.execute("insert into t values (1,'Alpha','X'), (2,'ALPHA','x'), "
                  "(3,'beta','y')")
        return s

    def test_ci_compare(self, s):
        assert s.execute("select id from t where a = 'alpha' order by id")[0] \
            .values() == [[1], [2]]
        assert s.execute("select id from t where a != 'ALPHA' order by "
                         "id")[0].values() == [[3]]
        # bin column stays case-sensitive
        assert s.execute("select id from t where b = 'X'")[0].values() == [[1]]

    def test_ci_group_by(self, s):
        got = s.execute("select count(*) from t group by a order by 1")[0] \
            .values()
        assert got == [[1], [2]]   # alpha-group of 2, beta-group of 1

    def test_ci_order_by(self, s):
        # casefolded order: alpha-rows (ids 1,2) before 'beta' regardless
        # of 'ALPHA' vs 'Alpha' binary order
        got = [r[0] for r in
               s.execute("select id from t order by a, id")[0].values()]
        assert got == [1, 2, 3]

    def test_ci_predicates_stay_sql_side(self, s):
        """A ci-collated column comparison must not be pushed to the
        coprocessor (which compares binary)."""
        from tidb_tpu.plan.plans import PhysicalTableScan
        from tidb_tpu.plan import optimize_plan
        from tidb_tpu.plan.builder import PlanBuilder
        stmt = s.parser.parse_one("select id from t where a = 'alpha'")
        plan = optimize_plan(PlanBuilder(s).build(stmt), s,
                             s.store.get_client(), set())
        node = plan
        while node is not None and not isinstance(node, PhysicalTableScan):
            node = node.children[0] if node.children else None
        assert node is not None and node.pushed_where is None


class TestCiReviewRepros:
    """Round-4 review findings: ci semantics must hold on EVERY path —
    index ranges, stream agg over index order, DISTINCT, IN/LIKE,
    count(distinct), and database-default inheritance."""

    @pytest.fixture
    def s(self):
        s = _session()
        s.execute("create database d; use d")
        s.execute("create table t (id bigint primary key, "
                  "a varchar(20) collate utf8_general_ci)")
        s.execute("insert into t values (1,'ALPHA'), (2,'Apple'), "
                  "(3,'alpha')")
        s.execute("create index ka on t (a)")
        return s

    def test_indexed_ci_equality(self, s):
        assert s.execute("select id from t where a = 'alpha' order by id")[0] \
            .values() == [[1], [3]]
        assert s.execute("select id from t use index (ka) where a = 'alpha' "
                         "order by id")[0].values() == [[1], [3]]

    def test_group_by_over_index_not_split(self, s):
        got = s.execute("select count(*) from t use index (ka) group by a "
                        "order by 1")[0].values()
        assert got == [[1], [2]]

    def test_distinct_and_count_distinct(self, s):
        assert len(s.execute("select distinct a from t")[0].values()) == 2
        assert s.execute("select count(distinct a) from t")[0].values() == \
            [[2]]

    def test_in_and_like_agree_with_eq(self, s):
        assert s.execute("select id from t where a in ('alpha') "
                         "order by id")[0].values() == [[1], [3]]
        assert s.execute("select id from t where a like 'alp%' "
                         "order by id")[0].values() == [[1], [3]]
        assert s.execute("select id from t where a not in ('alpha', 'apple')")[0] \
            .values() == []

    def test_database_default_inheritance(self):
        s = _session()
        s.execute("create database m4 charset utf8mb4 collate "
                  "utf8mb4_general_ci")
        s.execute("use m4")
        s.execute("create table u (id bigint primary key, x varchar(5))")
        info = s.info_schema().table_by_name("m4", "u").info
        assert (info.charset, info.collate) == ("utf8mb4",
                                                "utf8mb4_general_ci")
        xft = info.find_column("x").field_type
        assert xft.collate == "utf8mb4_general_ci"
        # and the inherited ci semantics actually apply
        s.execute("insert into u values (1, 'Hi'), (2, 'HI')")
        assert s.execute("select count(*) from u where x = 'hi'")[0] \
            .values() == [[2]]
