"""Differential parity for the columnar AGGREGATE-PUSHDOWN channel and
the columnar INDEX channel: a pushed-down (partial-row) aggregate over
the cluster store's fan-out must answer with grouped partial STATES
(ColumnarAggStates — states, not rows, crossing the wire), merge through
the device/mesh combine chain, and stay row-for-row identical to the row
protocol AND a host oracle across 1/2/4/8 regions — including mid-scan
split/merge, u64 edge values, NULL group keys, float-sum sequential
rounding, and the tidb_tpu_columnar_scan kill switch. Index scans
(single read and double-read) must answer columnar with zero fallbacks,
survive a stale plane cache (version invalidation), and every new seam
must degrade device→host→row under its failpoint with unchanged
answers.
"""

from __future__ import annotations

import itertools
from decimal import Decimal

import numpy as np
import pytest

from tidb_tpu import failpoint, metrics, tablecodec as tc
from tidb_tpu.copr import columnar_region
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 260

Q1 = ("select l_flag, l_status, sum(l_qty), sum(l_price), avg(l_qty), "
      "avg(l_price), avg(l_disc), count(*) from lineitem "
      "where l_ship <= '1998-09-02' "
      "group by l_flag, l_status order by l_flag, l_status")

QUERIES = [
    Q1,
    # scalar aggregates (no group by): the PR 8 residual shape
    "select count(*), sum(l_qty), min(l_price), max(l_price), "
    "avg(l_disc), sum(l_disc) from lineitem",
    # NULL group keys form one group; float sums keep sequential rounding
    "select l_k, count(*), sum(l_disc), min(l_disc), max(l_qty) "
    "from lineitem group by l_k order by l_k",
    # string min/max + first_row-carried group columns
    "select l_flag, min(l_status), max(l_status), count(l_k) "
    "from lineitem group by l_flag order by l_flag",
    # filtered grouped aggregate
    "select l_status, count(*), sum(l_price) from lineitem "
    "where l_qty > 10 group by l_status order by l_status",
]


def _row_spec(i: int):
    flag = ("A", "N", "R")[i % 3]
    status = ("F", "O")[i % 2]
    qty = Decimal(i % 50) + Decimal(i % 4) / 4          # .00/.25/.50/.75
    price = Decimal(900 + i * 7) + Decimal(i % 10) / 10
    disc = (i % 11) * 0.01
    k = None if i % 11 == 0 else i % 7
    ship = f"1998-{(i % 12) + 1:02d}-{(i % 27) + 1:02d}"
    return flag, status, qty, price, disc, k, ship


def _build(n_regions: int) -> Session:
    store = new_store(f"cluster://3/aggpush{next(_id)}")
    s = Session(store)
    s.execute("create database ap")
    s.execute("use ap")
    s.execute(
        "create table lineitem (l_id bigint primary key, "
        "l_flag varchar(4), l_status varchar(4), l_qty decimal(12,2), "
        "l_price decimal(12,2), l_disc double, l_k bigint, l_ship date)")
    vals = []
    for i in range(1, N_ROWS + 1):
        flag, status, qty, price, disc, k, ship = _row_spec(i)
        vals.append(f"({i}, '{flag}', '{status}', {qty}, {price}, "
                    f"{disc!r}, {'null' if k is None else k}, '{ship}')")
    s.execute(f"insert into lineitem values {', '.join(vals)}")
    if n_regions > 1:
        tid = s.info_schema().table_by_name("ap", "lineitem").info.id
        step = N_ROWS // n_regions
        store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _counter(name: str) -> int:
    return metrics.counter(f"distsql.columnar_{name}").value


def _norm(rows):
    out = []
    for row in rows:
        nr = []
        for v in row:
            if v is None:
                nr.append(None)
            else:
                try:
                    nr.append(round(float(v), 9))
                except (TypeError, ValueError):
                    nr.append(v.decode() if isinstance(v, bytes) else v)
        out.append(nr)
    return out


def _row_protocol(s: Session, queries):
    s.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        return [s.execute(q)[0].values() for q in queries]
    finally:
        s.execute("set global tidb_tpu_columnar_scan = 1")


def _q1_oracle():
    """Host-computed TPC-H-q1-shaped expectation from the generator."""
    groups: dict = {}
    for i in range(1, N_ROWS + 1):
        flag, status, qty, price, disc, _k, ship = _row_spec(i)
        if ship > "1998-09-02":
            continue
        g = groups.setdefault((flag, status),
                              [Decimal(0), Decimal(0), 0.0, 0])
        g[0] += qty
        g[1] += price
        g[2] += disc
        g[3] += 1
    out = []
    for (flag, status) in sorted(groups):
        sq, sp, sd, n = groups[(flag, status)]
        out.append([flag, status, float(sq), float(sp),
                    float(sq) / n, float(sp) / n, sd / n, n])
    return out


@pytest.mark.parametrize("n_regions", [1, 2, 4, 8])
def test_states_parity_vs_row_protocol_and_oracle(n_regions):
    s = _build(n_regions)
    f0 = _counter("fallbacks")
    st0 = _counter("states")
    sp0 = metrics.counter("copr.agg_states.partials").value
    got = [s.execute(q)[0].values() for q in QUERIES]
    assert _counter("fallbacks") == f0, \
        "a hinted aggregate partial fell back to rows"
    d_states = _counter("states") - st0
    assert d_states >= n_regions * len(QUERIES), \
        f"only {d_states} STATES partials crossed the wire"
    assert metrics.counter("copr.agg_states.partials").value - sp0 \
        >= n_regions * len(QUERIES)
    want = _row_protocol(s, QUERIES)
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"states channel diverged from row protocol on {q!r}"
    # the host oracle pins both engines to the generator's ground truth
    q1 = got[0]
    oracle = _q1_oracle()
    assert len(q1) == len(oracle)
    for g, w in zip(q1, oracle):
        keys = [v.decode() if isinstance(v, bytes) else v for v in g[:2]]
        assert keys == w[:2]
        for a, b in zip(g[2:], w[2:]):
            assert float(a) == pytest.approx(b, rel=1e-9), (g, w)


def test_float_sum_keeps_sequential_rounding_exact():
    """Float SUM/AVG parity must be EXACT (==), not approximate: the
    per-region partials accumulate in row order and merge in task
    order, reproducing the row protocol's rounding sequence bit for
    bit."""
    s = _build(4)
    q = ("select l_k, sum(l_disc), avg(l_disc) from lineitem "
         "group by l_k order by l_k")
    got = s.execute(q)[0].values()
    want = _row_protocol(s, [q])[0]
    assert got == want     # bitwise-identical floats


def test_kill_switch_pins_row_protocol():
    s = _build(4)
    s.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        st0 = _counter("states")
        h0 = _counter("hits")
        s.execute(Q1)
        assert _counter("states") == st0
        assert _counter("hits") == h0
    finally:
        s.execute("set global tidb_tpu_columnar_scan = 1")


def test_u64_edge_value_degrades_to_rows_exactly():
    """An unsigned bigint above the int64 plane cannot pack: the region
    degrades to the row protocol (counted per partial) and answers are
    unchanged."""
    store = new_store(f"cluster://3/aggpushu{next(_id)}")
    s = Session(store)
    s.execute("create database u")
    s.execute("use u")
    s.execute("create table t (id bigint primary key, "
              "v bigint unsigned, k bigint)")
    big = (1 << 63) + 5
    vals = ", ".join(f"({i}, {big if i == 7 else i}, {i % 3})"
                     for i in range(1, 41))
    s.execute(f"insert into t values {vals}")
    tid = s.info_schema().table_by_name("u", "t").info.id
    store.cluster.split_keys([tc.encode_row_key(tid, 21)])
    f0 = _counter("fallbacks")
    q = "select k, count(*), max(v) from t group by k order by k"
    got = s.execute(q)[0].values()
    assert _counter("fallbacks") > f0, \
        "u64-over-i64 region should have fallen back to rows"
    want = _row_protocol(s, [q])[0]
    assert got == want


def test_mid_scan_split_and_merge_keep_parity():
    s = _build(4)
    store = s.store
    want = [s.execute(q)[0].values() for q in QUERIES]
    tid = s.info_schema().table_by_name("ap", "lineitem").info.id

    def mutate_split(st):
        st.cluster.split_keys([tc.encode_row_key(tid, 33),
                               tc.encode_row_key(tid, 177)])

    def mutate_merge(st):
        regions = st.cluster.regions
        for i in range(len(regions) - 1):
            if regions[i].start:
                st.cluster.merge(regions[i].region_id,
                                 regions[i + 1].region_id)
                return

    for mutate in (mutate_split, mutate_merge):
        orig = store.rpc.cop_request
        state = {"n": 0, "done": False}

        def hook(ctx, sel, ranges, read_ts, orig=orig, state=state,
                 mutate=mutate):
            state["n"] += 1
            if state["n"] == 2 and not state["done"]:
                state["done"] = True
                mutate(store)
            return orig(ctx, sel, ranges, read_ts)

        store.rpc.cop_request = hook
        try:
            got = [s.execute(q)[0].values() for q in QUERIES]
        finally:
            store.rpc.cop_request = orig
        assert state["done"]
        for q, g, w in zip(QUERIES, got, want):
            assert _norm(g) == _norm(w), \
                f"mid-scan topology change diverged on {q!r}"


def test_device_states_failpoint_degrades_to_host(monkeypatch):
    """device/agg_states inside the states kernel → the region computes
    the SAME monoid states host-side (copr.degraded_states_to_host),
    still shipping a STATES payload — answers unchanged."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _row_protocol(s, QUERIES)
    deg = metrics.counter("copr.degraded_states_to_host")
    st0 = _counter("states")
    d0 = deg.value
    failpoint.enable("device/agg_states")
    try:
        got = [s.execute(q)[0].values() for q in QUERIES]
    finally:
        failpoint.disable("device/agg_states")
    assert deg.value > d0, "device states fault never degraded to host"
    assert _counter("states") - st0 >= 4 * len(QUERIES), \
        "host-degraded regions stopped shipping states payloads"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), f"host-path states diverged on {q!r}"
    # and the DEVICE path itself (floor 0, no fault) matches too
    got2 = [s.execute(q)[0].values() for q in QUERIES]
    for q, g, w in zip(QUERIES, got2, want):
        assert _norm(g) == _norm(w), f"device-path states diverged on {q!r}"


def test_agg_states_failpoint_degrades_to_row_protocol():
    """copr/agg_states → the region drops to partial ROWS (counted as a
    per-partial fallback) — the bottom rung, answers unchanged."""
    s = _build(4)
    want = _row_protocol(s, QUERIES)
    f0 = _counter("fallbacks")
    failpoint.enable("copr/agg_states")
    try:
        got = [s.execute(q)[0].values() for q in QUERIES]
    finally:
        failpoint.disable("copr/agg_states")
    assert _counter("fallbacks") > f0
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), f"row-degraded agg diverged on {q!r}"


def test_combine_failpoint_degrades_to_host_combine():
    """device/combine under a 4-region states merge → the host monoid
    combine answers (copr.degraded_combine_to_host), same results."""
    s = _build(4)
    want = _row_protocol(s, QUERIES)
    deg = metrics.counter("copr.degraded_combine_to_host")
    d0 = deg.value
    failpoint.enable("device/combine")
    failpoint.enable("device/mesh_collective")
    try:
        got = [s.execute(q)[0].values() for q in QUERIES]
    finally:
        failpoint.disable("device/combine")
        failpoint.disable("device/mesh_collective")
    assert deg.value > d0, "combine fault never reached the host rung"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), f"host combine diverged on {q!r}"


# ---------------------------------------------------------------------------
# columnar index channel
# ---------------------------------------------------------------------------

IDX_QUERIES = [
    # covering single-read (index columns only)
    "select l_k from lineitem use index (ik) where l_k >= 3 order by l_k",
    # double-read: handles resolve through the columnar table lookup
    "select l_id, l_k, l_flag, l_price from lineitem use index (ik) "
    "where l_k = 2 order by l_id",
    "select l_id, l_disc from lineitem use index (ik) "
    "where l_k between 1 and 4 order by l_id",
]


def _build_indexed(n_regions: int) -> Session:
    s = _build(n_regions)
    s.execute("create index ik on lineitem (l_k)")
    return s


@pytest.mark.parametrize("n_regions", [1, 4])
def test_index_scans_answer_columnar_with_zero_fallbacks(n_regions):
    s = _build_indexed(n_regions)
    f0, h0 = _counter("fallbacks"), _counter("hits")
    got = [s.execute(q)[0].values() for q in IDX_QUERIES]
    assert _counter("fallbacks") == f0, \
        "a hinted index partial fell back to rows"
    assert _counter("hits") > h0
    want = _row_protocol(s, IDX_QUERIES)
    for q, g, w in zip(IDX_QUERIES, got, want):
        assert g == w, f"columnar index scan diverged on {q!r}"


def test_index_double_read_stale_cache_invalidation():
    """A committed UPDATE bumps the data version: cached index AND base
    planes must invalidate, so the re-run sees fresh values (parity with
    the row protocol after the write)."""
    s = _build_indexed(4)
    q = IDX_QUERIES[1]
    before = s.execute(q)[0].values()
    assert before, "fixture query returned no rows"
    s.execute("update lineitem set l_price = l_price + 1000 where l_k = 2")
    f0 = _counter("fallbacks")
    after = s.execute(q)[0].values()
    assert _counter("fallbacks") == f0
    assert after != before, "stale cached planes served after a commit"
    want = _row_protocol(s, [q])[0]
    assert after == want


def test_index_pack_failpoint_degrades_to_rows():
    s = _build_indexed(4)
    want = _row_protocol(s, IDX_QUERIES)
    f0 = _counter("fallbacks")
    failpoint.enable("copr/pack")
    try:
        got = [s.execute(q)[0].values() for q in IDX_QUERIES]
    finally:
        failpoint.disable("copr/pack")
    assert _counter("fallbacks") > f0
    for q, g, w in zip(IDX_QUERIES, got, want):
        assert g == w, f"row-degraded index scan diverged on {q!r}"


# ---------------------------------------------------------------------------
# index-carried aggregates ride STATES (PR 11 residual b)
# ---------------------------------------------------------------------------

IDX_AGG_QUERIES = [
    # grouped over the index column, args on index column + pk handle
    "select l_k, count(*), min(l_id), max(l_id) from lineitem "
    "use index (ik) where l_k >= 0 group by l_k order by l_k",
    # scalar aggregates over the covering index
    "select count(*), min(l_k), max(l_k), sum(l_k) from lineitem "
    "use index (ik) where l_k >= 0",
    "select l_k, sum(l_id) from lineitem use index (ik) "
    "where l_k between 1 and 5 group by l_k order by l_k",
]


@pytest.mark.parametrize("n_regions", [1, 4])
def test_index_aggregates_answer_with_states(n_regions):
    """A covering index request carrying pushed-down aggregates answers
    with grouped partial STATES (ColumnarAggStates) like base-table
    requests — counted on distsql.columnar_states, fused by the FINAL
    aggregate, row-for-row vs the row protocol AND the table-scan
    plan."""
    from tidb_tpu.codec import codec
    from tidb_tpu.executor import fused_agg
    from tidb_tpu.types import Datum
    s = _build_indexed(n_regions)
    if n_regions > 1:
        # row-key splits leave the whole INDEX keyspace in one region —
        # split it too so the states really fan out per region
        info = s.info_schema().table_by_name("ap", "lineitem").info
        ik = next(ix for ix in info.indices if ix.name.lower() == "ik")
        seek = tc.encode_index_seek_key(info.id, ik.id)
        s.store.cluster.split_keys(
            [seek + codec.encode_key([Datum.i64(k)]) for k in (2, 4)])
    st0, f0 = _counter("states"), _counter("fallbacks")
    fu0 = fused_agg.stats["final_states"]
    got = [s.execute(q)[0].values() for q in IDX_AGG_QUERIES]
    per_q = 3 if n_regions > 1 else 1   # index segments serving a query
    assert _counter("states") - st0 >= per_q * len(IDX_AGG_QUERIES), \
        "index aggregates did not ship partial STATES"
    assert _counter("fallbacks") == f0
    assert fused_agg.stats["final_states"] > fu0, \
        "the FINAL aggregate never fused the index states"
    want = _row_protocol(s, IDX_AGG_QUERIES)
    for q, g, w in zip(IDX_AGG_QUERIES, got, want):
        assert g == w, f"index states diverged from the row protocol {q!r}"
    # and vs the table-scan plan of the same aggregates (no hint)
    plain = [s.execute(q.replace("use index (ik) ", ""))[0].values()
             for q in IDX_AGG_QUERIES]
    for q, g, p in zip(IDX_AGG_QUERIES, got, plain):
        assert g == p, f"index states diverged from the table plan {q!r}"


def test_index_decimal_aggregate_keeps_row_protocol_exact():
    """DECIMAL-valued aggregates over an index stay on the row handler
    (comparable-key scale canonicalization) — per-partial fallback, same
    answers."""
    s = _build_indexed(4)
    s.execute("create index ipr on lineitem (l_price)")
    q = ("select count(*), sum(l_price), min(l_price) from lineitem "
         "use index (ipr) where l_price >= 0")
    got = s.execute(q)[0].values()
    want = _row_protocol(s, [q])[0]
    assert got == want


def test_index_agg_states_failpoint_degrades_to_rows():
    """copr/agg_states over the index request degrades that region to
    partial ROWS with unchanged answers (the bottom rung)."""
    s = _build_indexed(4)
    want = _row_protocol(s, IDX_AGG_QUERIES)
    failpoint.enable("copr/agg_states")
    try:
        got = [s.execute(q)[0].values() for q in IDX_AGG_QUERIES]
    finally:
        failpoint.disable("copr/agg_states")
    for q, g, w in zip(IDX_AGG_QUERIES, got, want):
        assert g == w, f"row-degraded index aggregate diverged on {q!r}"


# ---------------------------------------------------------------------------
# micro-batch mask readback bit-packing (PR 9 residual satellite)
# ---------------------------------------------------------------------------

def test_bitpacked_mask_words_roundtrip():
    """_unpack_mask_words inverts the kernel's 64-rows-per-int64 pack
    for every bit position, including bit 63 (the int64 sign bit)."""
    from tidb_tpu.ops.sched import _unpack_mask_words
    rng = np.random.default_rng(7)
    for kb, capacity in ((1, 1024), (8, 1024), (3, 2048)):
        masks = rng.random((kb, capacity)) < 0.3
        masks[:, 63] = True          # exercise the sign bit
        masks[:, capacity - 1] = True
        bits = masks.reshape(kb, -1, 64).astype(np.uint64)
        weights = (np.uint64(1) << np.arange(64, dtype=np.uint64))
        words = (bits * weights).sum(axis=-1, dtype=np.uint64) \
            .astype(np.int64)        # two's complement reinterpretation
        out = _unpack_mask_words(words.reshape(-1), kb, capacity)
        assert np.array_equal(out, masks)


def test_batched_mask_readback_parity_vs_solo():
    """The bit-packed batched dispatch answers exactly what the solo
    route answers — concurrent below-floor statements over a TpuClient
    store, same shape, batched vs kill switch."""
    import threading

    from tidb_tpu.ops import TpuClient

    store = new_store(f"memory://bitpack{next(_id)}")
    s = Session(store)
    s.execute("create database b")
    s.execute("use b")
    s.execute("create table t (id bigint primary key, v bigint)")
    s.execute("insert into t values " +
              ", ".join(f"({i}, {i % 97})" for i in range(1, 301)))
    store.set_client(TpuClient(store, dispatch_floor_rows=10**9))
    s.execute("set global tidb_tpu_batch_window_ms = 30")

    def run_all(label):
        out = {}

        def worker(j):
            sess = Session(store)
            sess.execute("use b")
            out[j] = sess.execute(
                f"select id, v from t where v > {40 + j} order by id"
            )[0].values()

        threads = [threading.Thread(target=worker, args=(j,))
                   for j in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    batched = metrics.counter("sched.batched_statements")
    b0 = batched.value
    got = run_all("batched")
    assert batched.value > b0, "no statement rode the batched dispatch"
    s.execute("set global tidb_tpu_micro_batch = 0")
    try:
        want = run_all("solo")
    finally:
        s.execute("set global tidb_tpu_micro_batch = 1")
    assert got == want
