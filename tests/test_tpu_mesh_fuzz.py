"""Mesh-parity fuzz across the group-cardinality ladder (round-3 verdict
item 5): the SAME grouped queries run on the CPU engine and on a TpuClient
sharded over the 8-virtual-device mesh, crossing

  - high-NDV single-key radix group-by (NDV ~5200 int, ~12k int64 — global
    host-built dictionary codes, psum-combined),
  - composite TUPLE codes: group-bys whose mixed-radix cross product
    overflows RADIX_MAX_SEGMENTS (a×f ≈ 72M, a×b×e ≈ 3.5M) and so used to
    be single-chip only — now compacted host-side to dense global ids
    (ColumnBatch.tuple_codes) and psum-combined like any radix request,
  - NULL groups, decimal group keys, first_row, and per-group distinct
    inside tuple-coded segments.

Reference: store/localstore/local_aggregate.go:28 getGroupKey is kind- and
cardinality-agnostic; this suite proves the mesh path now is too.
"""

import random

import pytest

from tidb_tpu.ops import TpuClient
from tidb_tpu.session import Session, new_store

N_ROWS = 12_000


def _build(store):
    from decimal import Decimal as _D

    from tidb_tpu.types import Datum
    from tidb_tpu.types.datum import NULL

    s = Session(store)
    s.execute("create database mz")
    s.execute("use mz")
    s.execute(
        "create table t (id bigint primary key, a int, b varchar(32), "
        "c double, e int, f bigint, m decimal(12,2))")
    tbl = s.info_schema().table_by_name("mz", "t")

    rng = random.Random(97)
    words = [f"w{i:03d}" for i in range(64)]
    txn = store.begin()
    for i in range(1, N_ROWS + 1):
        a = Datum.i64(rng.randint(0, 5999)) if rng.random() > 0.05 else NULL
        b = Datum.string(rng.choice(words)) if rng.random() > 0.15 else NULL
        c = Datum.f64(round(rng.uniform(-1e6, 1e6), 4)) \
            if rng.random() > 0.30 else NULL
        e = Datum.i64(rng.randint(0, 8))
        f = Datum.i64(rng.randint(-10**12, 10**12))
        m = Datum.dec(_D(rng.randint(-10**7, 10**7)) / 100) \
            if rng.random() > 0.20 else NULL
        tbl.add_record(txn, [Datum.i64(i), a, b, c, e, f, m],
                       skip_unique_check=True)
        if i % 3000 == 0:
            txn.commit()
            txn = store.begin()
    txn.commit()
    return s


@pytest.fixture(scope="module")
def sessions():
    from tidb_tpu.parallel import CoprMesh

    cpu_store = new_store("memory://meshfz_cpu")
    mesh_store = new_store("memory://meshfz_mesh")
    mesh_store.set_client(TpuClient(mesh_store, mesh=CoprMesh(), dispatch_floor_rows=0))
    return _build(cpu_store), _build(mesh_store)


QUERIES = [
    # scalar sanity over the mesh combine
    "select count(*), sum(c), min(a), max(f) from t",
    # radix ladder: low NDV → ~5200 → ~12k, all psum-combined
    "select e, count(*), sum(a), min(c), max(c), avg(c) from t "
    "group by e order by e",
    "select a, count(*), sum(c) from t group by a order by a",
    "select f, count(*) from t group by f order by f",
    # composite tuple codes: cross product 6001×~12k ≈ 72M >> ceiling,
    # actual distinct tuples ~12k — dense global ids, mesh-combined
    "select a, f, count(*), sum(c), min(c) from t group by a, f "
    "order by a, f",
    # tuple codes with NULL groups on two of three key columns
    "select a, b, e, count(*), sum(c) from t group by a, b, e "
    "order by a, b, e",
    # decimal group key inside a tuple (fixed-point plane as code source)
    "select a, m, count(*) from t group by a, m order by a, m",
    # first_row (non-group select column) through the tuple path
    "select a, f, b from t group by a, f order by a, f",
    # per-group distinct inside tuple-coded segments
    "select a, f, count(distinct e) from t group by a, f order by a, f",
    # round-5: filter requests are row-sharded over the mesh (the mask
    # comes back shard-major in global row order)
    "select id from t where c > 0.5 order by id",
    "select id, a from t where a > 3000 and f < 100 order by id",
    "select id from t where b is null order by id",
    # round-5: per-shard fixed-k top-k + host merge
    "select id from t order by c desc limit 7",
    "select id from t order by a limit 5",
    "select id from t where c > 0.2 order by f desc, a limit 9",
    "select id from t order by b limit 6",           # NULLs first asc
    "select id from t order by b desc limit 6",      # NULLs last desc
]


def _norm(rows):
    from decimal import Decimal
    out = []
    for row in rows:
        nr = []
        for v in row:
            if isinstance(v, Decimal):
                v = float(v)
            if isinstance(v, bytes):
                nr.append(v.decode())
            elif isinstance(v, float):
                nr.append(("f", v))
            else:
                nr.append(v)
        out.append(nr)
    return out


def _close(a, b):
    if isinstance(a, tuple) and a[0] == "f":
        return isinstance(b, tuple) and \
            abs(a[1] - b[1]) <= 1e-9 * max(abs(a[1]), abs(b[1]), 1.0)
    return a == b


@pytest.mark.parametrize("sql", QUERIES)
def test_mesh_fuzz_parity(sessions, sql):
    cpu, mesh = sessions
    client = mesh.store.get_client()
    before = (client.stats["tpu_requests"], client.stats["cpu_fallbacks"])
    cpu_rows = _norm(cpu.execute(sql)[0].values())
    mesh_rows = _norm(mesh.execute(sql)[0].values())
    assert client.stats["tpu_requests"] > before[0], sql
    assert client.stats["cpu_fallbacks"] == before[1], sql
    assert len(cpu_rows) == len(mesh_rows), sql
    for cr, tr in zip(cpu_rows, mesh_rows):
        assert len(cr) == len(tr), sql
        for a, b in zip(cr, tr):
            assert _close(a, b), (sql, cr, tr)


def test_high_ndv_queries_cross_the_ladder(sessions):
    """The suite only proves what the verdict asked if the cardinalities
    really cross the rank-bucket ladder: assert the group counts."""
    cpu, _ = sessions
    n_a = len(cpu.execute("select a, count(*) from t group by a")[0].values())
    n_af = len(cpu.execute(
        "select a, f, count(*) from t group by a, f")[0].values())
    assert n_a >= 3000, n_a          # > first rank bucket (1025)
    assert n_af >= 10_000, n_af      # > second bucket territory


def test_tuple_lowering_used_on_mesh(sessions):
    """group by a, f must actually take the composite-tuple route (not
    radix, not CPU fallback): its cross product overflows the ceiling."""
    from tidb_tpu.copr.proto import ByItem, SelectRequest, expr_column
    from tidb_tpu.ops import kernels

    _, mesh = sessions
    client = mesh.store.get_client()
    mesh.execute("select a, f, count(*) from t group by a, f")
    batch = client._cur_batch
    assert batch is not None
    info = mesh.info_schema().table_by_name("mz", "t").info
    cid = {c.name: c.id for c in info.columns}
    req = SelectRequest(start_ts=0, group_by=[
        ByItem(expr_column(cid["a"])), ByItem(expr_column(cid["f"]))])
    gspec = kernels.lower_group_by(req, batch)
    assert gspec.kind == "rank"
    tspec = kernels.lower_tuple_group(gspec, batch)
    assert tspec is not None and tspec.kind == "tuple"
    assert tspec.n_groups >= 10_000
    assert tspec.percol.shape == (tspec.n_groups, 2)
