"""HBM governance tier (ops.membudget): the budget ledger's accounting,
the radix-partitioned out-of-core join — single-device passes AND the
key-partitioned mesh probe — with its escalation/degradation chain, the
sysvar plumbing, the plane cache's pin skip under pressure, and the
hbm-pressure inspection rule.

The parity oracle throughout is the UNPARTITIONED route under budget 0
(the kill switch): every partitioned answer must be bit-identical —
exact pair equality at the kernel level, row-for-row at the SQL level.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from tidb_tpu import errors, failpoint, inspection, metrics
from tidb_tpu.ops import TpuClient, kernels, membudget
from tests.testkit import TestKit


@pytest.fixture(autouse=True)
def _reset_budget():
    yield
    membudget.set_budget(membudget.DEFAULT_BUDGET_SPEC)
    failpoint.disable_all()


def _cnt(name: str) -> int:
    return metrics.counter(name).value


def _mk_keys(seed=7, n_l=30_000, n_r=12_000, ndv=5000):
    rng = np.random.default_rng(seed)
    lkey = rng.integers(0, ndv, n_l).astype(np.int64)
    rkey = rng.integers(0, ndv, n_r).astype(np.int64)
    lvalid = rng.random(n_l) > 0.05
    rvalid = rng.random(n_r) > 0.05
    return lkey, lvalid, rkey, rvalid


class TestLedger:
    def test_budget_spec_validation(self):
        membudget.set_budget("auto")
        assert membudget.budget_bytes() >= 0   # CPU rig: resolves 0
        membudget.set_budget(12345)
        assert membudget.budget_bytes() == 12345
        membudget.set_budget("0")
        assert membudget.budget_bytes() == 0
        with pytest.raises(ValueError):
            membudget.set_budget("-1")
        with pytest.raises(ValueError):
            membudget.set_budget("lots")

    def test_reserve_pin_accounting_and_gauges(self):
        membudget.set_budget(10_000)
        r0, p0 = membudget.usage()
        membudget.pin(4_000)
        try:
            assert membudget.usage()[1] == p0 + 4_000
            assert membudget.headroom() <= 6_000 - r0
            with membudget.reserve(1_000, "test"):
                res, _ = membudget.usage()
                assert res == r0 + 1_000
                assert metrics.gauge("device.hbm.reserved").value == res
            assert membudget.usage()[0] == r0
            assert metrics.gauge("device.hbm.budget").value == 10_000
        finally:
            membudget.unpin(4_000)

    def test_over_budget_reservation_counts(self):
        membudget.set_budget(1_000)
        before = _cnt("device.hbm.over_budget")
        with membudget.reserve(100_000, "test"):
            pass
        assert _cnt("device.hbm.over_budget") == before + 1
        # unlimited budget never counts
        membudget.set_budget(0)
        with membudget.reserve(100_000, "test"):
            pass
        assert _cnt("device.hbm.over_budget") == before + 1

    def test_partition_codes_discipline(self):
        # equal keys share a partition; -0.0 joins +0.0; NULLs home at 0
        vals = np.array([3.5, 0.0, -0.0, 3.5, 9.25])
        valid = np.array([True, True, True, True, False])
        part = membudget.partition_codes(vals, valid, 8)
        assert part[0] == part[3]
        assert part[1] == part[2]
        assert part[4] == 0
        assert ((part >= 0) & (part < 8)).all()
        ints = np.array([5, -5, 5, (1 << 62)], dtype=np.int64)
        pi = membudget.partition_codes(ints, np.ones(4, bool), 16)
        assert pi[0] == pi[2]
        assert ((pi >= 0) & (pi < 16)).all()


class TestPartitionedPasses:
    def test_int_key_parity_and_counters(self):
        lkey, lvalid, rkey, rvalid = _mk_keys()
        membudget.set_budget(0)
        li0, ri0 = membudget.join_match_pairs(lkey, lvalid, rkey, rvalid)
        membudget.set_budget(64 * 1024)
        j0, p0 = _cnt("copr.partitioned_joins"), \
            _cnt("copr.partitioned_passes")
        st: dict = {}
        li1, ri1 = membudget.join_match_pairs(lkey, lvalid, rkey, rvalid,
                                              stats=st)
        assert st["partitioned"] and st["passes"] >= 2
        assert _cnt("copr.partitioned_joins") == j0 + 1
        assert _cnt("copr.partitioned_passes") - p0 == st["passes"]
        assert np.array_equal(li0, li1) and np.array_equal(ri0, ri1)

    def test_float_key_parity_signed_zero(self):
        rng = np.random.default_rng(11)
        base = np.concatenate([rng.random(2000) * 50,
                               np.array([0.0, -0.0])])
        lk = rng.choice(base, 20_000)
        rk = rng.choice(base, 9_000)
        lv = rng.random(20_000) > 0.1
        rv = rng.random(9_000) > 0.1
        membudget.set_budget(0)
        li0, ri0 = membudget.join_match_pairs(lk, lv, rk, rv)
        membudget.set_budget(48 * 1024)
        st: dict = {}
        li1, ri1 = membudget.join_match_pairs(lk, lv, rk, rv, stats=st)
        assert st["partitioned"] and st["passes"] >= 2
        assert np.array_equal(li0, li1) and np.array_equal(ri0, ri1)

    def test_budget_zero_pins_unpartitioned(self):
        lkey, lvalid, rkey, rvalid = _mk_keys(seed=3)
        membudget.set_budget(0)
        j0 = _cnt("copr.partitioned_joins")
        st: dict = {}
        membudget.join_match_pairs(lkey, lvalid, rkey, rvalid, stats=st)
        assert "partitioned" not in st
        assert _cnt("copr.partitioned_joins") == j0

    def test_oom_escalates_partitions_not_host(self):
        lkey, lvalid, rkey, rvalid = _mk_keys(seed=5)
        membudget.set_budget(0)
        li0, ri0 = membudget.join_match_pairs(lkey, lvalid, rkey, rvalid)
        membudget.set_budget(128 * 1024)
        d0 = _cnt("copr.degraded_partition")
        failpoint.enable("device/oom", when=("first", 1))
        try:
            st: dict = {}
            li1, ri1 = membudget.join_match_pairs(
                lkey, lvalid, rkey, rvalid, stats=st)
        finally:
            failpoint.disable("device/oom")
        assert st["partition_escalations"] == 1
        assert st["partitions"] >= 4       # doubled at least once
        assert _cnt("copr.degraded_partition") == d0 + 1
        assert np.array_equal(li0, li1) and np.array_equal(ri0, ri1)

    def test_oom_escalation_is_bounded(self):
        lkey, lvalid, rkey, rvalid = _mk_keys(seed=6, n_l=8_000,
                                              n_r=4_000)
        membudget.set_budget(32 * 1024)
        failpoint.enable("device/oom")       # every pass OOMs forever
        try:
            with pytest.raises(errors.DeviceError):
                membudget.join_match_pairs(lkey, lvalid, rkey, rvalid)
        finally:
            failpoint.disable("device/oom")


class TestMeshPartitionedProbe:
    def _mesh(self):
        from tidb_tpu.parallel import CoprMesh
        mesh = CoprMesh()
        assert mesh.n == 8, "test env must expose 8 virtual devices"
        return mesh

    def test_key_partitioned_probe_parity(self):
        mesh = self._mesh()
        lkey, lvalid, rkey, rvalid = _mk_keys(seed=9)
        membudget.set_budget(0)
        li0, ri0 = membudget.join_match_pairs(lkey, lvalid, rkey, rvalid)
        membudget.set_budget(64 * 1024)
        p0 = _cnt("copr.partitioned_passes")
        st: dict = {}
        li1, ri1 = membudget.join_match_pairs(lkey, lvalid, rkey, rvalid,
                                              stats=st, mesh=mesh)
        assert st["mesh_partitioned"] and st["mesh_shards"] == 8
        assert _cnt("copr.partitioned_passes") == p0 + 8
        assert np.array_equal(li0, li1) and np.array_equal(ri0, ri1)

    def test_collective_fault_degrades_to_replicated(self):
        """partitioned-mesh → replicated-mesh rung: the collective
        failpoint kills the key-partitioned probe, the replicated probe
        answers (the failpoint seam lives only in the partitioned
        kernel), counted on copr.degraded_mesh — answers unchanged."""
        mesh = self._mesh()
        lkey, lvalid, rkey, rvalid = _mk_keys(seed=13)
        membudget.set_budget(0)
        li0, ri0 = membudget.join_match_pairs(lkey, lvalid, rkey, rvalid)
        membudget.set_budget(64 * 1024)
        d0 = _cnt("copr.degraded_mesh")
        failpoint.enable("device/mesh_collective")
        try:
            st: dict = {}
            li1, ri1 = membudget.join_match_pairs(
                lkey, lvalid, rkey, rvalid, stats=st, mesh=mesh)
        finally:
            failpoint.disable("device/mesh_collective")
        assert "mesh_partitioned" not in st
        assert _cnt("copr.degraded_mesh") >= d0 + 1
        assert np.array_equal(li0, li1) and np.array_equal(ri0, ri1)


N_PROBE = 3000
N_BUILD = 2000
JOIN_Q = "select l.id, r.w from l join r on l.k = r.k order by l.id, r.w"
OUTER_Q = ("select l.id, r.w from l left join r on l.k = r.k "
           "order by l.id, r.w")
AGG_Q = "select count(*), sum(r.w), min(l.id) from l join r on l.k = r.k"


def _join_store() -> TestKit:
    tk = TestKit()
    tk.exec("create database mb; use mb")
    tk.exec("create table l (id bigint primary key, k bigint)")
    tk.exec("create table r (id bigint primary key, k bigint, w bigint)")
    lrows = ", ".join(f"({i}, {i % (N_BUILD + 40)})"
                      for i in range(1, N_PROBE + 1))
    tk.exec(f"insert into l values {lrows}")
    rrows = ", ".join(f"({i}, {i % N_BUILD}, {i * 7})"
                      for i in range(1, N_BUILD + 1))
    tk.exec(f"insert into r values {rrows}")
    tk.store.set_client(TpuClient(tk.store, dispatch_floor_rows=0))
    return tk


class TestExecutorRoute:
    def test_sql_parity_partitioned_vs_kill_switch(self):
        tk = _join_store()
        membudget.set_budget(0)
        oracle = [tk.query(q).rows for q in (JOIN_Q, OUTER_Q, AGG_Q)]
        tk.exec("set global tidb_tpu_hbm_budget_bytes = 12288")
        assert membudget.budget_bytes() == 12288
        j0 = _cnt("copr.partitioned_joins")
        p0 = _cnt("copr.partitioned_passes")
        got = [tk.query(q).rows for q in (JOIN_Q, OUTER_Q, AGG_Q)]
        assert _cnt("copr.partitioned_joins") >= j0 + 3
        assert _cnt("copr.partitioned_passes") >= p0 + 6  # >=2 per join
        assert got == oracle
        # kill switch pins the unpartitioned route
        tk.exec("set global tidb_tpu_hbm_budget_bytes = 0")
        j1 = _cnt("copr.partitioned_joins")
        assert [tk.query(q).rows
                for q in (JOIN_Q, OUTER_Q, AGG_Q)] == oracle
        assert _cnt("copr.partitioned_joins") == j1

    def test_chaos_oom_mid_pass_answers_unchanged(self):
        """The satellite chaos schedule: a prob-seeded device/oom fires
        mid-pass across repeated partitioned joins — P escalates
        (copr.degraded_partition) and every answer stays equal to the
        kill-switch oracle; even a join that exhausts its escalation
        budget lands on the executor's numpy rung, never an error."""
        tk = _join_store()
        membudget.set_budget(0)
        oracle = tk.query(JOIN_Q).rows
        tk.exec("set global tidb_tpu_hbm_budget_bytes = 12288")
        d0 = _cnt("copr.degraded_partition")
        failpoint.enable("device/oom", when=("prob", 0.25), seed=42)
        try:
            for _ in range(6):
                assert tk.query(JOIN_Q).rows == oracle
        finally:
            failpoint.disable("device/oom")
        assert _cnt("copr.degraded_partition") > d0, \
            "no pass ever escalated under the prob schedule"
        tk.exec("set global tidb_tpu_hbm_budget_bytes = 'auto'")

    def test_sysvar_is_global_only_and_validated(self):
        tk = _join_store()
        with pytest.raises(errors.TiDBError):
            tk.exec("set tidb_tpu_hbm_budget_bytes = 4096")   # no GLOBAL
        with pytest.raises(errors.TiDBError):
            tk.exec("set global tidb_tpu_hbm_budget_bytes = 'sometimes'")
        with pytest.raises(errors.TiDBError):
            tk.exec("set global tidb_tpu_hbm_budget_bytes = -3")
        tk.exec("set global tidb_tpu_hbm_budget_bytes = 'auto'")
        r = tk.query("select @@tidb_tpu_hbm_budget_bytes").rows
        assert r[0][0] in (b"auto", "auto")

    def test_dict_join_partitions_through_host_keys_fn(self):
        """String-key joins reach the partitioned route through the
        LAZY host-key planes: the device remap path skips them, the
        out-of-core rungs resolve them on demand — answers equal the
        kill-switch oracle."""
        tk = TestKit()
        tk.exec("create database mbs; use mbs")
        tk.exec("create table sl (id bigint primary key, s varchar(16))")
        tk.exec("create table sr (id bigint primary key, s varchar(16), "
                "w bigint)")
        lrows = ", ".join(f"({i}, 'k{i % 600}')" for i in range(1, 2501))
        tk.exec(f"insert into sl values {lrows}")
        rrows = ", ".join(f"({i}, 'k{i % 500}', {i})"
                          for i in range(1, 2001))
        tk.exec(f"insert into sr values {rrows}")
        tk.store.set_client(TpuClient(tk.store, dispatch_floor_rows=0))
        q = ("select sl.id, sr.w from sl join sr on sl.s = sr.s "
             "order by sl.id, sr.w")
        membudget.set_budget(0)
        oracle = tk.query(q).rows
        membudget.set_budget(12 * 1024)
        j0 = _cnt("copr.partitioned_joins")
        assert tk.query(q).rows == oracle
        assert _cnt("copr.partitioned_joins") == j0 + 1


class TestPlaneCachePinSkip:
    def test_pin_skipped_under_pressure_cache_still_serves(self):
        from tidb_tpu import tablecodec as tc
        from tidb_tpu.session import Session, new_store
        store = new_store("cluster://3/mbpin1")
        s = Session(store)
        s.execute("create database pc")
        s.execute("use pc")
        s.execute("create table t (id bigint primary key, v bigint)")
        s.execute("insert into t values " +
                  ", ".join(f"({i}, {i * 3})" for i in range(1, 1201)))
        tid = s.info_schema().table_by_name("pc", "t").info.id
        store.cluster.split_keys([tc.encode_row_key(tid, 601)])
        q = "select count(*), sum(v) from t"
        membudget.set_budget(0)
        oracle = s.execute(q)[0].values()
        # a 1-byte budget: every admission must skip the device pin but
        # still cache host-side (repeat scans hit)
        membudget.set_budget(1)
        sk0 = _cnt("copr.plane_cache.pin_skipped")
        h0 = _cnt("copr.plane_cache.hits")
        s.execute("insert into pc.t values (9999, 1)")  # orphan entries
        s.execute(q)
        assert _cnt("copr.plane_cache.pin_skipped") > sk0
        got = s.execute(q)[0].values()
        assert _cnt("copr.plane_cache.hits") > h0
        assert [int(v) for v in got[0][:1]] == [int(oracle[0][0]) + 1]


class TestInspectionRule:
    def test_hbm_pressure_fires_and_clears(self):
        from tidb_tpu.metrics import timeseries
        membudget.set_budget(10_000)
        membudget.pin(9_500)
        try:
            timeseries.recorder.sample()
            findings = [f for f in inspection.inspect()
                        if f["rule"] == "hbm-pressure"]
            assert findings, "pressured ledger did not fire hbm-pressure"
            assert findings[0]["item"] == "ledger"
            assert findings[0]["value"] >= \
                inspection.threshold("hbm_pressure_ratio")
        finally:
            membudget.unpin(9_500)
        # pressure drained (the budget outgrows the live pinned set —
        # earlier tests' batches may still pin real planes): the rule
        # clears once the over-budget burst ages out of the window
        membudget.set_budget(membudget.usage()[1] * 4 + (1 << 20))
        for _ in range(int(inspection.threshold("window_samples")) + 2):
            timeseries.recorder.sample()
            time.sleep(0.002)   # forced sub-ms samples coalesce
        assert not [f for f in inspection.inspect()
                    if f["rule"] == "hbm-pressure"], \
            "rule did not clear after the ledger drained"

    def test_unlimited_budget_never_fires(self):
        from tidb_tpu.metrics import timeseries
        membudget.set_budget(0)
        membudget.pin(1 << 30)
        try:
            timeseries.recorder.sample()
            assert not [f for f in inspection.inspect()
                        if f["rule"] == "hbm-pressure"]
        finally:
            membudget.unpin(1 << 30)
