"""Multi-server DDL: owner lease, cross-server convergence, mid-DDL
writes, background drop queue.

Mirrors ddl/ddl_worker.go:97 (checkOwner lease + takeover),
ddl/column_change_test.go (writes interleaved with schema states from a
second server), and ddl/bg_worker.go (deferred drop-data deletion). Two
Domain instances over ONE store stand in for two tidb-server processes —
exactly the reference's multi-server test construction.
"""

import json
import time

import pytest

from tidb_tpu import tablecodec as tc
from tidb_tpu.ddl import ddl as ddl_mod
from tidb_tpu.ddl.callback import Callback
from tidb_tpu.domain import Domain, clear_domains
from tidb_tpu.meta import Meta
from tidb_tpu.session import Session, new_store
from tests.testkit import _store_id


@pytest.fixture
def store():
    clear_domains()
    return new_store(f"memory://msddl{next(_store_id)}")


def two_domains(store):
    d1, d2 = Domain(store), Domain(store)
    return d1, d2


class TestOwnerLease:
    def test_enqueuer_waits_for_live_owner(self, store):
        """When another server holds a live lease, the enqueuing server
        must NOT process; it waits for the owner's worker."""
        d1, d2 = two_domains(store)
        d1.ddl.create_schema("d")

        # d1 grabs the owner lease explicitly
        def grab(txn):
            m = Meta(txn)
            assert d1.ddl._take_owner(m)
        from tidb_tpu.kv import run_in_new_txn
        run_in_new_txn(store, True, grab)

        d1.ddl.start_worker(interval_s=0.02)
        d2.reload()  # see the schema d1 created
        try:
            t0 = time.time()
            d2.ddl.create_table("d", "t", [ddl_mod.ColumnSpec(
                "a", _ft())], [])
            assert time.time() - t0 < ddl_mod.OWNER_TIMEOUT_MS / 1000.0, \
                "job should be processed by d1's worker, not by takeover"
        finally:
            d1.ddl.stop_worker()
        d2.reload()
        assert d2.info_schema().table_exists("d", "t")

    def test_dead_owner_taken_over(self, store):
        """An expired lease must not block DDL forever."""
        d1, d2 = two_domains(store)
        d1.ddl.create_schema("d")
        # forge a dead owner: someone else's id, stale timestamp
        from tidb_tpu.kv import run_in_new_txn

        def forge(txn):
            stale = {"id": "deadbeef", "ts": int(time.time() * 1000)
                     - ddl_mod.OWNER_TIMEOUT_MS - 1}
            Meta(txn).set_owner(json.dumps(stale).encode())
        run_in_new_txn(store, True, forge)
        d2.reload()
        d2.ddl.create_table("d", "t", [ddl_mod.ColumnSpec("a", _ft())], [])
        assert d2.info_schema().table_exists("d", "t")


def _ft():
    from tidb_tpu import mysqldef as my
    from tidb_tpu.types.field_type import FieldType
    return FieldType(my.TypeLong)


class TestConvergence:
    def test_second_domain_sees_ddl_via_reload(self, store):
        d1, d2 = two_domains(store)
        s1 = Session(store)          # uses the registered get_domain(...)
        s1.execute("create database d")
        s1.execute("use d")
        s1.execute("create table t (a int primary key)")
        assert d2.maybe_reload()
        assert d2.info_schema().table_exists("d", "t")
        # no further changes: reload is a no-op
        assert not d2.maybe_reload()

    def test_reload_loop_converges(self, store):
        d1, d2 = two_domains(store)
        d2.start_reload_loop(interval_s=0.02)
        try:
            d1.ddl.create_schema("d")
            d1.ddl.create_table("d", "t", [ddl_mod.ColumnSpec("a", _ft())],
                                [])
            deadline = time.time() + 2.0
            while time.time() < deadline:
                if d2.info_schema().table_exists("d", "t"):
                    break
                time.sleep(0.01)
            assert d2.info_schema().table_exists("d", "t")
        finally:
            d2.close()


class TestMidDDLWrites:
    def test_writes_from_second_server_during_add_index(self, store):
        """column_change_test.go shape: while the owner steps an ADD INDEX
        through delete-only/write-only/reorg, a session on ANOTHER domain
        keeps inserting; the final index must cover every row."""
        d1 = Domain(store)
        s = Session(store)
        s.execute("create database d; use d")
        s.execute("create table t (a int primary key, b int)")
        for i in range(20):
            s.execute(f"insert into t values ({i}, {i})")

        inserted = []

        class Interleave(Callback):
            def __init__(self, store):
                self.n = 100
                self.store = store
                self.session = None

            def on_changed(self, err):
                # runs between schema states, AFTER the version bump — a
                # fresh session writes under the new schema state
                if self.session is None:
                    self.session = Session(self.store)
                    self.session.execute("use d")
                self.n += 1
                try:
                    self.session.execute(
                        f"insert into t values ({self.n}, {self.n})")
                    inserted.append(self.n)
                except Exception:
                    pass

        d2 = Domain(store, ddl_callback=Interleave(store))
        d2.ddl.create_index("d", "t", "idx_b", ["b"])
        assert inserted, "callback never interleaved writes"

        # index must be complete and consistent (ADMIN CHECK TABLE)
        s2 = Session(store)
        s2.execute("use d")
        s2.execute("admin check table t")
        n = s2.execute("select count(*) from t")[0].values()[0][0]
        # every interleaved row is found VIA THE INDEX
        hits = s2.execute(
            "select count(*) from t where b > 20")[0].values()[0][0]
        assert hits == len([i for i in inserted if i > 20])
        assert n == 20 + len(inserted)


class TestBackgroundDrop:
    def test_drop_table_data_drains_via_bg_queue(self, store):
        d1 = Domain(store)
        s = Session(store)
        s.execute("create database d; use d")
        s.execute("create table t (a int primary key)")
        s.execute("insert into t values (1), (2), (3)")
        info = s.info_schema().table_by_name("d", "t")
        tid = info.id
        s.execute("drop table t")
        # the drop itself already drained the bg queue opportunistically
        snap = store.get_snapshot()
        start, end = tc.encode_record_range(tid)
        assert list(snap.iterate(start, end)) == []

    def test_bg_queue_processed_by_other_server(self, store):
        """A queued drop left by a dead server is drained by any worker."""
        d1, d2 = two_domains(store)
        s = Session(store)
        s.execute("create database d; use d")
        s.execute("create table t (a int primary key)")
        s.execute("insert into t values (1)")
        info = s.info_schema().table_by_name("d", "t")
        from tidb_tpu.kv import run_in_new_txn

        def enqueue_only(txn):
            m = Meta(txn)
            d1.ddl._enqueue_bg_drop(m, info.db_id, info.id)
            # the "dead server": its bg lease has expired
            stale = {"id": "deadbeef", "ts": int(time.time() * 1000)
                     - ddl_mod.OWNER_TIMEOUT_MS - 1}
            m.set_owner(json.dumps(stale).encode(), bg=True)
        run_in_new_txn(store, True, enqueue_only)
        d2.ddl._handle_bg_queue()
        snap = store.get_snapshot()
        start, end = tc.encode_record_range(info.id)
        assert list(snap.iterate(start, end)) == []


class TestWritesDuringColumnStates:
    """UPDATE/DELETE while an ADD/DROP COLUMN job is mid-state (round-4
    chaos finding): executor rows carry the PUBLIC schema, so the write
    paths must map positions, not assume model offsets — a half-added
    column used to raise IndexError and a half-dropped one could miswrite
    neighboring columns. Reference: F1 write states, ddl/column.go."""

    def _hooked(self, op_sql):
        """Run `op_sql` (DML) from the DDL callback after EVERY state
        transition of a concurrent column job."""
        from tidb_tpu.ddl.callback import Callback

        store = new_store(f"memory://midcol{next(_store_id)}")
        s = Session(store)
        s.execute("create database d; use d")
        s.execute("create table t (a bigint primary key, b bigint, "
                  "c varchar(8))")
        s.execute("insert into t values (1, 10, 'x'), (2, 20, 'y')")
        dml = Session(store)
        dml.execute("use d")
        ran = []

        class Hook(Callback):
            def on_changed(self, err):
                if err is None:
                    try:
                        dml.execute(op_sql)
                        ran.append(True)
                    except errors.TiDBError as e:
                        ran.append(str(e))

        s.domain.ddl.callback = Hook()
        return store, s, dml, ran

    def test_update_during_add_column(self):
        store, s, dml, ran = self._hooked(
            "update t set b = b + 1 where a = 1")
        s.execute("alter table t add column tag int default 7")
        s.domain.ddl.callback = type(s.domain.ddl.callback).__bases__[0]()
        assert ran and all(r is True for r in ran), ran
        # b incremented once per state transition; tag default intact
        rows = s.execute("select a, b, c, tag from t order by a")[0].values()
        assert rows[0][1] == 10 + len(ran), rows   # every UPDATE landed
        assert rows[0][2] == "x" and rows[0][3] == 7
        assert rows[1] == [2, 20, "y", 7]
        s.execute("admin check table t")

    def test_delete_during_add_column(self):
        store, s, dml, ran = self._hooked("delete from t where a = 2")
        s.execute("alter table t add column tag int default 5")
        s.domain.ddl.callback = type(s.domain.ddl.callback).__bases__[0]()
        assert ran and all(r is True for r in ran), ran
        assert s.execute("select a from t")[0].values() == [[1]]
        s.execute("admin check table t")

    def test_update_during_drop_column(self):
        """Mid-DROP the hidden column leaves an offset GAP: updates to the
        columns AROUND it must hit the right columns."""
        store, s, dml, ran = self._hooked(
            "update t set c = 'upd', a = a where a = 1")
        s.execute("alter table t drop column b")
        s.domain.ddl.callback = type(s.domain.ddl.callback).__bases__[0]()
        assert ran and all(r is True for r in ran), ran
        rows = s.execute("select a, c from t order by a")[0].values()
        assert rows == [[1, "upd"], [2, "y"]]
        s.execute("admin check table t")

    def test_on_duplicate_during_drop_column(self):
        """ON DUPLICATE KEY UPDATE mid-DROP: the eval schema must match
        the public-order row (round-4 review repro: IndexError / silent
        cross-column corruption)."""
        store, s, dml, ran = self._hooked(
            "insert into t (a, c) values (1, 'z') "
            "on duplicate key update c = 'dup'")
        s.execute("alter table t drop column b")
        s.domain.ddl.callback = type(s.domain.ddl.callback).__bases__[0]()
        assert ran and all(r is True for r in ran), ran
        rows = s.execute("select a, c from t order by a")[0].values()
        assert rows == [[1, "dup"], [2, "y"]]
        s.execute("admin check table t")


class TestSchemaBarrierAutoArm:
    """Round-4 weak #6: the 2xlease waitSchemaChanged barrier must arm
    itself when live PEER servers share the store, even in embedded mode
    where no explicit --lease was configured."""

    def test_single_server_stays_unarmed(self, store):
        d = Domain(store)
        assert d.ddl._effective_lease() == 0.0

    def test_two_servers_arm_the_barrier(self, store):
        d1, d2 = two_domains(store)
        assert d1.ddl._effective_lease() == d1.ddl.EMBEDDED_PEER_LEASE_S
        assert d2.ddl._effective_lease() > 0
        # explicit lease wins over the embedded floor
        d1.ddl.schema_lease_s = 1.5
        assert d1.ddl._effective_lease() == 1.5

    def test_close_unregisters(self, store):
        d1, d2 = two_domains(store)
        d2.close()
        assert d1.ddl._effective_lease() == 0.0

    def test_barrier_applies_during_ddl(self, store):
        import time as _t
        d1, d2 = two_domains(store)
        from tidb_tpu.session import Session
        s = Session(store)
        s.domain = d1
        s.execute("create database bar")
        s.execute("use bar")
        s.execute("create table t (a int)")
        t0 = _t.time()
        s.execute("alter table t add index ia (a)")   # multi-state job
        elapsed = _t.time() - t0
        # add-index walks >=3 schema states; each pauses 2x the embedded
        # peer lease → the DDL visibly waits for peers
        assert elapsed >= 3 * 2 * d1.ddl.EMBEDDED_PEER_LEASE_S * 0.8
