"""Differential suite for the DEVICE FILTER tier (PR 17): a pushed-down
aggregate statement with a WHERE ships every region's payload with the
filter AND the states deferred, and the statement finisher runs the
whole thing in ≤ 2 device dispatches: ONE batched ragged filter
(kernels.region_filter_batched — bit-packed survivor masks, rows never
transit the host) feeding ONE batched segmented states dispatch. The
contract across 1/2/4/8 regions: answers identical to the host exprc
rung (BATCH_FILTER_ENABLED=False) AND the row protocol — including NULL
planes in the predicate, dictionary-code predicates (prefix LIKE as a
code-range compare, IN as a sorted-membership probe), every failpoint
rung of the filter degradation ladder, mid-scan split/merge
re-batching, shape-bucketed jit (bounded retraces under skewed splits),
and the cross-STATEMENT gather window that batches concurrent
below-floor statements into one shared states dispatch."""

from __future__ import annotations

import itertools
import threading
import time

import numpy as np
import pytest

from tidb_tpu import failpoint, metrics, tablecodec as tc
from tidb_tpu.copr import columnar_region
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 260

QUERIES = [
    # q1 shape: numeric compare WHERE, decimal sums, string group keys
    "select l_flag, l_status, sum(l_qty), sum(l_price), avg(l_qty), "
    "avg(l_price), avg(l_disc), count(*) from lineitem "
    "where l_ship <= 180 group by l_flag, l_status "
    "order by l_flag, l_status",
    # scalar aggregates under an AND of compares
    "select count(*), sum(l_qty), min(l_price), max(l_price), "
    "avg(l_disc), sum(l_disc) from lineitem "
    "where l_qty > 10 and l_price < 2400",
    # NULL plane in the predicate AND the group key: l_k is NULL every
    # 11th row (NULL < 5 is UNKNOWN → filtered out, MySQL semantics)
    "select l_k, count(*), sum(l_disc), min(l_disc), max(l_qty) "
    "from lineitem where l_k < 5 group by l_k order by l_k",
    # prefix LIKE over the sorted global dictionary: a caseless-ASCII
    # prefix lowers to an integer code-RANGE compare (PR 14 residual d)
    "select l_flag, count(*), sum(l_price) from lineitem "
    "where l_ref like '2-%' group by l_flag order by l_flag",
    # IN over dict codes (one absent item exercises the dropped -1
    # code) + a general non-prefix LIKE (the dictionary LUT path)
    "select l_status, count(*), sum(l_qty) from lineitem "
    "where l_flag in ('A', 'Z') and l_ref like '%-y' "
    "group by l_status order by l_status",
]


def _build(n_regions: int) -> Session:
    store = new_store(f"cluster://3/filterbatch{next(_id)}")
    s = Session(store)
    s.execute("create database fb")
    s.execute("use fb")
    s.execute(
        "create table lineitem (l_id bigint primary key, "
        "l_flag varchar(4), l_status varchar(4), l_qty decimal(12,2), "
        "l_price decimal(12,2), l_disc double, l_k bigint, "
        "l_ship bigint, l_ref varchar(8))")
    from decimal import Decimal
    vals = []
    for i in range(1, N_ROWS + 1):
        flag = ("A", "N", "R")[i % 3]
        status = ("F", "O")[i % 2]
        qty = Decimal(i % 50) + Decimal(i % 4) / 4
        price = Decimal(900 + i * 7) + Decimal(i % 10) / 10
        disc = (i % 11) * 0.01
        k = "null" if i % 11 == 0 else str(i % 7)
        ref = f"{i % 4}-{'xyz'[i % 3]}"
        vals.append(f"({i}, '{flag}', '{status}', {qty}, {price}, "
                    f"{disc!r}, {k}, {i % 365}, '{ref}')")
    s.execute(f"insert into lineitem values {', '.join(vals)}")
    if n_regions > 1:
        tid = s.info_schema().table_by_name("fb", "lineitem").info.id
        step = N_ROWS // n_regions
        store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _c(name: str) -> int:
    return metrics.counter(name).value


def _fdisp() -> int:
    return _c("copr.filter.batched_dispatches")


def _sdisp() -> int:
    """States dispatches, whichever device route answered."""
    return (_c("copr.states_batch.dispatches")
            + _c("copr.mesh.near_data_dispatches"))


def _all(s: Session, queries=QUERIES) -> list:
    return [s.execute(q)[0].values() for q in queries]


def _host_rung(s: Session, monkeypatch, queries=QUERIES) -> list:
    """Oracle 1: the per-region HOST exprc filter (the pre-PR-17 eager
    path — same compiled predicate algebra, evaluated region-side)."""
    monkeypatch.setattr(columnar_region, "BATCH_FILTER_ENABLED", False)
    try:
        return [s.execute(q)[0].values() for q in queries]
    finally:
        monkeypatch.setattr(columnar_region, "BATCH_FILTER_ENABLED", True)


def _row_protocol(s: Session, queries=QUERIES) -> list:
    """Oracle 2: the row protocol (kill switch)."""
    s.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        return [s.execute(q)[0].values() for q in queries]
    finally:
        s.execute("set global tidb_tpu_columnar_scan = 1")


def _norm(rows):
    out = []
    for row in rows:
        nr = []
        for v in row:
            if v is None:
                nr.append(None)
            else:
                try:
                    nr.append(round(float(v), 9))
                except (TypeError, ValueError):
                    nr.append(v.decode() if isinstance(v, bytes) else v)
        out.append(nr)
    return out


@pytest.mark.parametrize("n_regions", [1, 2, 4, 8])
def test_filter_plus_states_in_two_dispatches(n_regions, monkeypatch):
    """The headline invariant: a pushed-down aggregate with a WHERE
    costs ONE batched filter dispatch + at most one states dispatch per
    statement — never one per region — with answers identical to the
    host exprc rung and the row protocol."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(n_regions)
    f0, s0 = _fdisp(), _sdisp()
    fr0 = _c("copr.filter.batched_regions")
    def0 = _c("distsql.columnar_filter_deferred")
    fb0 = _c("distsql.columnar_fallbacks")
    got = _all(s)
    assert _fdisp() - f0 == len(QUERIES), \
        (f"{_fdisp() - f0} batched filter dispatches for {len(QUERIES)} "
         f"statements over {n_regions} regions — not one per statement")
    assert (_fdisp() - f0) + (_sdisp() - s0) <= 2 * len(QUERIES), \
        "a statement cost more than 2 device dispatches (filter+states)"
    assert _c("copr.filter.batched_regions") - fr0 == \
        n_regions * len(QUERIES), \
        "not every region's WHERE rode the batched filter dispatches"
    assert _c("distsql.columnar_filter_deferred") - def0 == \
        n_regions * len(QUERIES), \
        "not every region deferred its filter to the statement finisher"
    assert _c("distsql.columnar_fallbacks") == fb0, \
        "the filter tier pushed a region off the columnar channel"

    host = _host_rung(s, monkeypatch)
    for q, g, w in zip(QUERIES, got, host):
        assert _norm(g) == _norm(w), \
            f"device filter diverged from the host exprc rung on {q!r}"
    rows = _row_protocol(s)
    for q, g, w in zip(QUERIES, got, rows):
        assert _norm(g) == _norm(w), \
            f"device filter diverged from the row protocol on {q!r}"


def test_float_sums_after_device_filter_bitexact(monkeypatch):
    """Float SUM/AVG over device-filtered survivors stay EXACT (==, not
    approximate) vs the row protocol: the mask is bit-identical to the
    host filter's, and the surviving floats accumulate in row order."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    q = ("select l_k, sum(l_disc), avg(l_disc) from lineitem "
         "where l_ship <= 180 group by l_k order by l_k")
    f0 = _fdisp()
    got = s.execute(q)[0].values()
    assert _fdisp() > f0, "filtered float query missed the filter batch"
    want = _row_protocol(s, [q])[0]
    assert got == want     # bitwise-identical floats


def test_jit_churn_bounded_under_skewed_splits(monkeypatch):
    """Residual-b churn guard: plane capacities, filter caps and
    segment spans are power-of-two BUCKETED, so repeated scans retrace
    NOTHING and a skewed mid-table split retraces at most the handful
    of shapes its new region count introduces — the re-scan after each
    split compiles nothing new."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    store = s.store
    tid = s.info_schema().table_by_name("fb", "lineitem").info.id
    q = QUERIES[0]
    s.execute(q)                       # warm: traces the 4-region shapes
    m0 = _c("ops.jit_cache_misses")
    for _ in range(5):
        s.execute(q)
    assert _c("ops.jit_cache_misses") == m0, \
        "steady-state repeat scans paid trace+compile (jit churn)"
    # skewed splits: 4 → 5 → 6 regions at lopsided keys. Each new
    # region COUNT may trace its own batched shapes once; the repeat
    # scan after each split must hit every cache (shape bucketing eats
    # the row-count/group-count skew).
    for split_at in (7, 251):
        store.cluster.split_keys([tc.encode_row_key(tid, split_at)])
        s.execute(q)
        m_after = _c("ops.jit_cache_misses")
        s.execute(q)
        assert _c("ops.jit_cache_misses") == m_after, \
            f"re-scan after split@{split_at} still paid trace+compile"
    total = _c("ops.jit_cache_misses") - m0
    # budget: per new region count ≤ (filter trace + states trace +
    # per-region predicate compiles + final-combine shapes) — bounded
    # by the topology changes, NOT by the scan count
    assert total <= 20, \
        f"{total} jit misses across 2 splits — shape bucketing regressed"


def test_copr_filter_batched_fault_takes_host_rung(monkeypatch):
    """copr/filter_batched (finisher seam) → the statement's masks come
    from the per-region host exprc rung (copr.degraded_filter_batch),
    no filter kernel dispatch, answers unchanged."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _all(s)
    deg = metrics.counter("copr.degraded_filter_batch")
    d0, f0 = deg.value, _fdisp()
    failpoint.enable("copr/filter_batched", "return", value=True)
    try:
        got = _all(s)
    finally:
        failpoint.disable("copr/filter_batched")
    assert deg.value - d0 == len(QUERIES), \
        "the finisher seam never degraded the filter batch"
    assert _fdisp() == f0, \
        "degraded statements still dispatched the filter kernel"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"host-rung degraded filter diverged on {q!r}"


def test_device_filter_batched_fault_takes_host_rung(monkeypatch):
    """device/filter_batched (kernel seam) → typed DeviceError → the
    host exprc rung answers (copr.degraded_filter_batch), the states
    batch still runs, answers unchanged."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _all(s)
    deg = metrics.counter("copr.degraded_filter_batch")
    d0, s0 = deg.value, _sdisp()
    failpoint.enable("device/filter_batched")
    try:
        got = _all(s)
    finally:
        failpoint.disable("device/filter_batched")
    assert deg.value - d0 == len(QUERIES), \
        "the kernel fault never degraded the filter batch"
    assert _sdisp() > s0, \
        "host-rung masks no longer feed the batched states dispatch"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"kernel-fault degraded filter diverged on {q!r}"


def test_device_fault_ladder_bottoms_out_at_host(monkeypatch):
    """Every device rung out at once (filter kernel + states kernel +
    mesh collective): masks from host exprc, states from host numpy —
    answers still identical to the row protocol."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _row_protocol(s)
    deg_f = metrics.counter("copr.degraded_filter_batch")
    f0 = deg_f.value
    failpoint.enable("device/filter_batched")
    failpoint.enable("device/agg_states")
    failpoint.enable("device/mesh_collective")
    try:
        got = _all(s)
    finally:
        failpoint.disable("device/mesh_collective")
        failpoint.disable("device/agg_states")
        failpoint.disable("device/filter_batched")
    assert deg_f.value > f0, \
        "the filter kernel fault never hit the degradation counter"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"all-host degraded pipeline diverged on {q!r}"


def test_copr_filter_fault_degrades_to_rows(monkeypatch):
    """copr/filter (region seam, below the deferral) → the region drops
    to the row protocol entirely: nothing defers, fallbacks are counted
    per partial, answers unchanged."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _row_protocol(s)
    f0, fb0 = _fdisp(), _c("distsql.columnar_fallbacks")
    failpoint.enable("copr/filter")
    try:
        got = _all(s)
    finally:
        failpoint.disable("copr/filter")
    assert _c("distsql.columnar_fallbacks") > fb0
    assert _fdisp() == f0, \
        "row-degraded regions still rode the batched filter"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"row-degraded filter diverged on {q!r}"


def test_copr_agg_states_fault_degrades_to_rows(monkeypatch):
    """copr/agg_states fires at REGION time in deferred mode too (the
    seam is hoisted above the deferral decision): a typed fault drops
    the region to partial rows exactly as the eager path does."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    want = _row_protocol(s)
    f0, fb0 = _fdisp(), _c("distsql.columnar_fallbacks")
    failpoint.enable("copr/agg_states")
    try:
        got = _all(s)
    finally:
        failpoint.disable("copr/agg_states")
    assert _c("distsql.columnar_fallbacks") > fb0
    assert _fdisp() == f0, \
        "agg-states-degraded regions still deferred their filter"
    for q, g, w in zip(QUERIES, got, want):
        assert _norm(g) == _norm(w), \
            f"row-degraded aggregate diverged on {q!r}"


def test_mid_scan_split_and_merge_rebatch(monkeypatch):
    """A split/merge injected DURING the fan-out: the stale-epoch retry
    re-collects deferred payloads and the finisher still filters the
    statement in ONE batched dispatch over the NEW region set."""
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 0)
    s = _build(4)
    store = s.store
    want = _all(s)
    tid = s.info_schema().table_by_name("fb", "lineitem").info.id

    def mutate_split(st):
        st.cluster.split_keys([tc.encode_row_key(tid, 33),
                               tc.encode_row_key(tid, 177)])

    def mutate_merge(st):
        regions = st.cluster.regions
        for i in range(len(regions) - 1):
            if regions[i].start:
                st.cluster.merge(regions[i].region_id,
                                 regions[i + 1].region_id)
                return

    for mutate in (mutate_split, mutate_merge):
        orig = store.rpc.cop_request
        state = {"n": 0, "done": False}

        def hook(ctx, sel, ranges, read_ts, orig=orig, state=state,
                 mutate=mutate):
            state["n"] += 1
            if state["n"] == 2 and not state["done"]:
                state["done"] = True
                mutate(store)
            return orig(ctx, sel, ranges, read_ts)

        store.rpc.cop_request = hook
        f0 = _fdisp()
        try:
            got = _all(s)
        finally:
            store.rpc.cop_request = orig
        assert state["done"]
        assert _fdisp() - f0 == len(QUERIES), \
            "mid-scan topology change broke one-filter-dispatch-per-stmt"
        for q, g, w in zip(QUERIES, got, want):
            assert _norm(g) == _norm(w), \
                f"mid-scan topology change diverged on {q!r}"


def test_states_gather_combines_concurrent_submissions():
    """The cross-statement gather, driven directly: two below-floor
    submissions inside one window combine past the floor into ONE
    batched dispatch (sched.cross_stmt_states_batches), each getting
    exactly its own segment's slice — identical to a solo dispatch."""
    from tidb_tpu.ops import kernels, sched
    g = sched.StatesGather(window_s=0.25)
    g._last_multi = time.monotonic()   # hot signature: leader waits
    n = 64
    gid = (np.arange(n, dtype=np.int64) % 4)
    vals = np.arange(n, dtype=np.int64)
    ok = np.ones(n, dtype=bool)
    seg = (gid, [("sum", vals, ok)], 4)
    want = kernels.region_agg_states_batched([seg])[0]   # solo oracle
    c0 = _c("sched.cross_stmt_states_batches")
    outs = [None, None]
    barrier = threading.Barrier(2)

    def run(i):
        barrier.wait()
        outs[i] = g.submit(("sum",), [seg], n, 100)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # 64 < 100 each, 128 ≥ 100 combined: both fulfilled by one dispatch
    assert outs[0] is not None and outs[1] is not None, \
        "combined-past-the-floor submissions stayed serial"
    assert _c("sched.cross_stmt_states_batches") == c0 + 1, \
        "two concurrent statements did not share one states dispatch"
    for o in outs:
        np.testing.assert_array_equal(np.asarray(o[0][0]),
                                      np.asarray(want[0]))


def test_cross_statement_batching_parity_vs_solo(monkeypatch):
    """E2E: two below-floor statements running CONCURRENTLY drain into
    the gather window and answer from one shared states dispatch — with
    answers identical to each statement running solo."""
    from tidb_tpu.ops import sched
    monkeypatch.setattr(columnar_region, "STATES_DEVICE_FLOOR", 300)
    g = sched.StatesGather(window_s=0.25)
    g._last_multi = time.monotonic()
    monkeypatch.setattr(sched, "states_gather", g)
    s1 = _build(4)
    s2 = Session(s1.store)
    s2.execute("use fb")
    q = QUERIES[0]
    solo = s1.execute(q)[0].values()     # warm + solo oracle
    g._last_multi = time.monotonic()     # keep the hot-sig gate open
    c0 = _c("sched.cross_stmt_states_batches")
    results = [None, None]
    errs = []
    barrier = threading.Barrier(2)

    def run(i, sess):
        try:
            barrier.wait()
            results[i] = sess.execute(q)[0].values()
        except Exception as e:          # noqa: BLE001 - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=run, args=(0, s1)),
          threading.Thread(target=run, args=(1, s2))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    assert _c("sched.cross_stmt_states_batches") > c0, \
        "concurrent below-floor statements never shared a dispatch"
    for got in results:
        assert _norm(got) == _norm(solo), \
            "cross-statement batched answer diverged from solo"
