"""Native batched row→plane decode: differential parity vs the Python
scan, plus the incremental (append-only) columnar cache.

Mirrors tests/test_native_codec.py's approach: the Python implementation
is the semantic definition; the C path must produce identical planes.
"""

import numpy as np
import pytest

from tidb_tpu import tablecodec as tc
from tidb_tpu.copr.proto import PBColumnInfo
from tidb_tpu.kv.kv import KeyRange
from tidb_tpu.ops import columnar as col
from tidb_tpu.ops import nativepack
from tidb_tpu.session import Session, new_store
from tidb_tpu.types import Datum
from tests.testkit import TestKit, _store_id


def _pb_cols(tbl):
    info = tbl.info
    pk = info.pk_handle_column()
    return [PBColumnInfo(column_id=c.id, tp=c.field_type.tp,
                         flag=c.field_type.flag,
                         pk_handle=(pk is not None and c.id == pk.id))
            for c in info.public_columns()]


@pytest.fixture
def table():
    tk = TestKit()
    tk.exec("create database d; use d")
    tk.exec("create table t (id bigint primary key, a int, b varchar(16), "
            "c double, d date, e bigint)")
    rows = []
    for i in range(1, 301):
        b = "null" if i % 7 == 0 else f"'s{i % 11}'"
        c = "null" if i % 5 == 0 else str(i * 0.25)
        d = "null" if i % 13 == 0 else f"'2024-{(i % 12) + 1:02d}-15'"
        rows.append(f"({i}, {i % 9}, {b}, {c}, {d}, {i * 10})")
    tk.exec(f"insert into t values {', '.join(rows)}")
    tbl = tk.session.info_schema().table_by_name("d", "t")
    return tk, tbl


def _full_ranges(tbl):
    s, e = tc.encode_record_range(tbl.id)
    return [KeyRange(s, e)]


class TestNativePackParity:
    def test_planes_identical_to_python_scan(self, table):
        tk, tbl = table
        if nativepack._cx is None or not hasattr(nativepack._cx,
                                                 "pack_rows"):
            pytest.skip("native codec unavailable")
        snap = tk.store.get_snapshot()
        cols = _pb_cols(tbl)
        ranges = _full_ranges(tbl)
        native = nativepack.scan_rows(snap, tbl.id, cols, ranges, {})
        assert native is not None
        nh, nraw, nvalid = native

        # force the Python path for the oracle
        saved = nativepack._cx
        nativepack._cx = None
        try:
            ph, praw, pvalid = col._scan_rows(snap, tbl.id, cols, ranges, {})
        finally:
            nativepack._cx = saved

        assert list(nh) == list(ph)
        for c in cols:
            cid = c.column_id
            assert list(np.asarray(nvalid[cid])) == list(pvalid[cid]), cid
            nv, pv = nraw[cid], praw[cid]
            for a, b, ok in zip(nv, pv, pvalid[cid]):
                if not ok:
                    continue
                assert a == b, (cid, a, b)

    def test_full_batch_identical(self, table):
        tk, tbl = table
        snap = tk.store.get_snapshot()
        cols = _pb_cols(tbl)
        ranges = _full_ranges(tbl)
        b1 = col.pack_ranges(snap, tbl.id, cols, ranges)
        saved = nativepack._cx
        nativepack._cx = None
        try:
            b2 = col.pack_ranges(snap, tbl.id, cols, ranges)
        finally:
            nativepack._cx = saved
        assert np.array_equal(b1.handles, b2.handles)
        for cid in b1.columns:
            c1, c2 = b1.columns[cid], b2.columns[cid]
            assert np.array_equal(c1.valid, c2.valid), cid
            assert np.array_equal(c1.values, c2.values), cid
            assert c1.dictionary == c2.dictionary, cid


class TestIncrementalCache:
    def _tpu_session(self):
        from tidb_tpu.ops import TpuClient
        store = new_store(f"memory://inc{next(_store_id)}")
        store.set_client(TpuClient(store, dispatch_floor_rows=0))
        s = Session(store)
        s.execute("create database d; use d")
        s.execute("create table t (id bigint primary key, a int, "
                  "b varchar(8))")
        rows = ", ".join(f"({i}, {i % 7}, '{chr(97 + i % 5)}')"
                         for i in range(1, 201))
        s.execute(f"insert into t values {rows}")
        return store, s, store.get_client()

    def test_insert_takes_append_path(self):
        store, s, cl = self._tpu_session()
        q = "select count(*), sum(a), min(b), max(b) from t"

        def norm(rows):
            return [[int(r[0]), int(r[1]),
                     r[2] if isinstance(r[2], str) else r[2].decode(),
                     r[3] if isinstance(r[3], str) else r[3].decode()]
                    for r in rows]

        assert norm(s.execute(q)[0].values()) == [[200, 598, "a", "e"]]
        s.execute("insert into t values (300, 5, 'zz')")
        assert norm(s.execute(q)[0].values()) == [[201, 603, "a", "zz"]]
        assert cl.stats["batch_appends"] == 1
        assert cl.stats["batch_packs"] == 1  # only the initial pack

    def test_update_and_delete_force_full_repack(self):
        store, s, cl = self._tpu_session()
        q = "select count(*), sum(a) from t"
        s.execute(q)
        s.execute("update t set a = 100 where id = 1")
        assert s.execute(q)[0].values() == [[200, 697]]
        assert cl.stats["batch_appends"] == 0
        s.execute("delete from t where id = 1")
        assert s.execute(q)[0].values() == [[199, 597]]
        assert cl.stats["batch_appends"] == 0
        assert cl.stats["batch_packs"] >= 3

    def test_other_table_write_keeps_batch(self):
        store, s, cl = self._tpu_session()
        s.execute("create table u (x int primary key)")
        q = "select count(*) from t"
        s.execute(q)
        packs = cl.stats["batch_packs"]
        hits = cl.stats["batch_hits"]
        s.execute("insert into u values (1)")
        assert s.execute(q)[0].values() == [[200]]
        # per-table commit filtering (PR 13): a commit to table u does
        # not move t's version at all — the cached batch EXACT-hits
        # (pre-PR-13 this cost a zero-delta append pass)
        assert cl.stats["batch_packs"] == packs
        assert cl.stats["batch_appends"] == 0
        assert cl.stats["batch_hits"] == hits + 1

    def test_older_snapshot_never_sees_newer_batch(self):
        """Snapshot isolation: a txn whose start_ts predates an insert
        must not be served the newer cached batch (regression: the append
        check treated cached-newer as cached-older)."""
        store, s, cl = self._tpu_session()
        q = "select count(*) from t"
        old = Session(store)
        old.execute("use d")
        old.execute("begin")
        assert old.execute(q)[0].values() == [[200]]  # pins start_ts
        s.execute("insert into t values (900, 1, 'q')")
        assert s.execute(q)[0].values() == [[201]]    # newer batch cached
        assert old.execute(q)[0].values() == [[200]]  # still its snapshot
        old.execute("commit")
        assert old.execute(q)[0].values() == [[201]]

    def test_bounds_window_expiry_forces_full_pack(self):
        store, s, cl = self._tpu_session()
        # the appends-only proof rides the PER-TABLE bounds window now
        # (table_commits_below); shrinking it past the cached version
        # makes the proof unknowable → full repack
        store._table_log_cap = 2
        q = "select count(*) from t"
        s.execute(q)
        for i in range(400, 405):  # push the window past the cached version
            s.execute(f"insert into t values ({i}, 1, 'w')")
        assert s.execute(q)[0].values() == [[205]]
        assert cl.stats["batch_appends"] == 0  # window gone → full repack

    def test_append_with_new_dictionary_words_grouped_correctly(self):
        store, s, cl = self._tpu_session()
        q = "select b, count(*) from t group by b order by b"

        def norm(rows):
            return [[r[0] if isinstance(r[0], str) else r[0].decode(),
                     int(r[1])] for r in rows]

        base = norm(s.execute(q)[0].values())
        s.execute("insert into t values (301, 1, 'aa'), (302, 1, 'aa')")
        got = norm(s.execute(q)[0].values())
        assert got == sorted(base + [["aa", 2]])
        assert cl.stats["batch_appends"] >= 1


class TestPackRowsValidation:
    def test_bad_pk_idx_rejected(self):
        if nativepack._cx is None or not hasattr(nativepack._cx,
                                                 "pack_rows"):
            pytest.skip("native codec unavailable")
        cx = nativepack._cx
        with pytest.raises(ValueError):
            cx.pack_rows([], [], [1], b"i", 5)      # pk_idx >= m
        with pytest.raises(ValueError):
            cx.pack_rows([], [], [1], b"s", 0)      # pk into string col
        n, *_ = cx.pack_rows([], [], [1], b"i", 0)  # valid call still fine
        assert n == 0
