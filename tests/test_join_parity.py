"""Differential join parity: the device build/probe path and the numpy
sort-merge path must be INVISIBLE next to the dict build/probe oracle —
row-for-row identical output, values AND order, on every covered shape
(LEFT_OUTER + other_conditions, NULL keys, mixed-kind bail-out,
ci-collation bail-out, wide match sets), plus join→agg fusion parity
and the dispatch-floor routing contract.
"""

from __future__ import annotations

import numpy as np
import pytest

from tidb_tpu.executor import executors
from tests.testkit import TestKit


QUERIES = [
    # inner / outer, NULL keys on both sides (seeded below)
    "select l.id, r.id from l join r on l.k = r.k",
    "select l.id, r.id from l left join r on l.k = r.k",
    # LEFT_OUTER + other_conditions (non-equi residual on both sides)
    "select l.id, r.id from l left join r on l.k = r.k and l.v > 2 "
    "and r.w < 22",
    "select l.id, r.w from l join r on l.k = r.k and l.v > 2",
    # wide match sets (k=2 fans out) + filter above the join
    "select l.id, r.id from l left join r on l.k = r.k where l.id > 1",
    # float keys
    "select l.id, r.id from l join r on l.v = r.f",
    "select l.id, r.id from l left join r on l.v = r.f",
]


def _seed(tk: TestKit):
    tk.exec("create table l (id bigint primary key, k int, v double)")
    tk.exec("create table r (id bigint primary key, k int, w int, "
            "f double)")
    tk.exec("insert into l values (1, 1, 1.5), (2, 2, null), "
            "(3, null, 3.5), (4, 2, 4.5), (5, 9, 5.5), (6, 2, 2.5)")
    tk.exec("insert into r values (10, 2, 20, 4.5), (11, 2, 21, 1.5), "
            "(12, 1, 22, null), (13, null, 23, 2.5), (14, 2, 24, 4.5)")


class _ForceDevice:
    """Route every HashJoinExec through the device kernels (floor 0)."""

    def __enter__(self):
        self._orig = executors.HashJoinExec._device_join_floor
        executors.HashJoinExec._device_join_floor = lambda self: 0
        return self

    def __exit__(self, *exc):
        executors.HashJoinExec._device_join_floor = self._orig


class _ForceDict:
    """Pin every HashJoinExec to the dict build/probe oracle."""

    def __enter__(self):
        self._orig = executors.HashJoinExec._try_vector_join
        executors.HashJoinExec._try_vector_join = lambda self: False
        return self

    def __exit__(self, *exc):
        executors.HashJoinExec._try_vector_join = self._orig


def _run_all(tk, queries):
    return [tk.query(q).rows for q in queries]


class TestJoinPathParity:
    @pytest.fixture()
    def tk(self):
        tk = TestKit()
        tk.exec("create database jp; use jp")
        _seed(tk)
        return tk

    def test_three_paths_row_for_row(self, tk):
        """device == numpy == dict, values and order, on every shape."""
        with _ForceDict():
            oracle = _run_all(tk, QUERIES)
        numpy_rows = _run_all(tk, QUERIES)   # default: numpy path
        with _ForceDevice():
            device_rows = _run_all(tk, QUERIES)
        for q, d, n, o in zip(QUERIES, device_rows, numpy_rows, oracle):
            assert n == o, f"numpy vs dict diverged on {q!r}"
            assert d == o, f"device vs dict diverged on {q!r}"
        # sanity: the inner joins actually matched rows
        assert len(oracle[0]) > 0 and len(oracle[3]) > 0

    def test_mixed_kind_key_bails_to_dict(self, tk):
        """A derived side mixing int and float key kinds must bail (after
        both drains) and still produce the dict path's rows."""
        q = ("select x.k, r.id from (select 1 as k union all "
             "select 4.5e0 as k) x join r on x.k = r.f")
        with _ForceDict():
            oracle = tk.query(q).rows
        assert sorted(map(tuple, oracle)) == [(4.5, 10), (4.5, 14)]
        with _ForceDevice():
            assert tk.query(q).rows == oracle

    def test_ci_collation_key_bails_to_dict(self, tk):
        """*_ci string keys must stay on the dict path (its codec keys
        carry the collation), on every forced route."""
        tk.exec("create table cl (id bigint primary key, "
                "s varchar(8) collate utf8_general_ci)")
        tk.exec("create table cr (id bigint primary key, "
                "s varchar(8) collate utf8_general_ci)")
        tk.exec("insert into cl values (1, 'Ant'), (2, 'bee'), (3, null)")
        tk.exec("insert into cr values (10, 'Ant'), (11, 'BEE'), "
                "(12, 'cat')")
        q = "select cl.id, cr.id from cl join cr on cl.s = cr.s"
        with _ForceDict():
            oracle = tk.query(q).rows
        assert len(oracle) > 0   # the exact-case pair matched
        with _ForceDevice():
            assert tk.query(q).rows == oracle

    def test_wide_match_set_left_outer(self, tk):
        """One probe row fanning out to many matches (the old
        _pending.pop(0) O(n²) shape) — parity and completeness."""
        tk.exec("create table wl (id bigint primary key, k int)")
        tk.exec("create table wr (id bigint primary key, k int)")
        tk.exec("insert into wl values (1, 7), (2, 7), (3, 8)")
        rows = ", ".join(f"({i}, 7)" for i in range(10, 400))
        tk.exec(f"insert into wr values {rows}")
        q = "select wl.id, wr.id from wl left join wr on wl.k = wr.k"
        with _ForceDict():
            oracle = tk.query(q).rows
        assert len(oracle) == 2 * 390 + 1
        numpy_rows = tk.query(q).rows
        with _ForceDevice():
            device_rows = tk.query(q).rows
        assert numpy_rows == oracle
        assert device_rows == oracle


class TestDeviceJoinKernels:
    """Unit coverage of the kernel driver's edge shapes."""

    def _pairs(self, lk, lv, rk, rv):
        from tidb_tpu.ops import kernels
        li, ri = kernels.join_match_pairs(
            np.asarray(lk), np.asarray(lv, bool),
            np.asarray(rk), np.asarray(rv, bool))
        return list(zip(li.tolist(), ri.tolist()))

    def test_sentinel_valued_keys_match(self):
        """A genuine I64_MAX key must match — the NULL/padding sentinel
        clamp may not eat it."""
        big = (1 << 63) - 1
        got = self._pairs([big, 0], [True, True],
                          [big, big, 5], [True, False, True])
        assert got == [(0, 0)]   # the valid big key only, not the NULL

    def test_probe_capacity_escalation(self):
        """total > initial out_cap (left bucket) forces the retry with a
        larger bucket — pairs must be complete and ordered."""
        n_l, n_r = 8, 3000    # 8 * 3000 = 24000 pairs >> bucket(8)=1024
        got = self._pairs([7] * n_l, [True] * n_l,
                          [7] * n_r, [True] * n_r)
        assert len(got) == n_l * n_r
        assert got[:3] == [(0, 0), (0, 1), (0, 2)]
        assert got[-1] == (n_l - 1, n_r - 1)

    def test_empty_and_all_null_sides(self):
        assert self._pairs([1, 2], [True, True], [], []) == []
        assert self._pairs([1, 2], [False, False],
                           [1, 2], [True, True]) == []
        assert self._pairs([], [], [1], [True]) == []

    def test_float_keys_with_inf(self):
        inf = float("inf")
        got = self._pairs([inf, 1.0], [True, True],
                          [inf, 1.0, 2.0], [True, True, False])
        assert got == [(0, 0), (1, 1)]


class TestJoinAggFusion:
    """join→agg fusion must be invisible: identical rows, identical
    order, vs the row-loop aggregate over the dict-path join."""

    @pytest.fixture()
    def tk(self):
        tk = TestKit()
        tk.exec("create database jf; use jf")
        _seed(tk)
        return tk

    AGG_QUERIES = [
        "select count(*), sum(r.w), avg(l.v), min(r.w), max(l.v) "
        "from l join r on l.k = r.k",
        "select l.k, count(*), sum(r.w), min(l.v) from l join r "
        "on l.k = r.k group by l.k",
        "select l.k, count(r.w), sum(l.v) from l left join r "
        "on l.k = r.k group by l.k",
        # empty join input: scalar aggs still emit one row
        "select count(*), sum(r.w), max(l.v) from l join r "
        "on l.k = r.k and l.v > 1e9",
        # group-by over an empty join: no rows
        "select l.k, count(*) from l join r on l.k = r.k "
        "and l.v > 1e9 group by l.k",
    ]

    def test_fused_matches_row_loop(self, tk):
        from tidb_tpu.executor import fused_agg
        with _ForceDict():
            oracle = _run_all(tk, self.AGG_QUERIES)
        before = fused_agg.stats["fused"]
        with _ForceDevice():
            fused = _run_all(tk, self.AGG_QUERIES)
        assert fused_agg.stats["fused"] > before, \
            "device join+agg never took the fused path"
        for q, f, o in zip(self.AGG_QUERIES, fused, oracle):
            assert f == o, f"fused agg diverged on {q!r}"

    def test_first_row_and_strings(self, tk):
        """first_row gathers exact source datums (any kind); string
        min/max falls back to the row loop — both must match."""
        tk.exec("create table sl (id bigint primary key, k int, "
                "s varchar(8))")
        tk.exec("insert into sl values (1, 2, 'x'), (2, 2, 'y'), "
                "(3, 1, null)")
        q = ("select sl.k, min(sl.s), max(r.w) from sl join r "
             "on sl.k = r.k group by sl.k")
        with _ForceDict():
            oracle = tk.query(q).rows
        with _ForceDevice():
            assert tk.query(q).rows == oracle


class TestJoinRouting:
    """The dispatch floor gates the device path; the sysvar kill switch
    pins joins to the host."""

    def test_floor_routes_numpy_below_device_above(self):
        from tidb_tpu.ops import TpuClient
        from tidb_tpu.session import new_store
        store = new_store("memory://joinroute1")
        store.set_client(TpuClient(store, dispatch_floor_rows=4))
        tk = TestKit(store)
        tk.exec("create database jr; use jr")
        tk.exec("create table a (id bigint primary key, k int)")
        tk.exec("create table b (id bigint primary key, k int)")
        tk.exec("insert into a values (1, 1), (2, 2), (3, 3), (4, 4), "
                "(5, 5)")
        tk.exec("insert into b values (1, 1), (2, 2), (3, 9)")
        seen = []
        orig = executors.HashJoinExec._try_vector_join

        def spy(self):
            out = orig(self)
            seen.append(self.join_stats.get("path"))
            return out
        executors.HashJoinExec._try_vector_join = spy
        try:
            q = "select a.id, b.id from a join b on a.k = b.k"
            rows = tk.query(q).rows
            assert sorted(map(tuple, rows)) == [(1, 1), (2, 2)]
            assert seen[-1] == "device"   # 5 rows >= floor 4
            tk.exec("set global tidb_tpu_dispatch_floor = 1000")
            assert tk.query(q).rows == rows
            assert seen[-1] == "numpy"    # below the floor
            tk.exec("set global tidb_tpu_dispatch_floor = 4")
            tk.exec("set global tidb_tpu_device_join = 0")
            assert tk.query(q).rows == rows
            assert seen[-1] == "numpy"    # kill switch
            tk.exec("set global tidb_tpu_device_join = 1")
            assert tk.query(q).rows == rows
            assert seen[-1] == "device"
        finally:
            executors.HashJoinExec._try_vector_join = orig

    def test_device_join_kill_switch_survives_new_client(self):
        """A freshly constructed TpuClient must resolve the persisted
        tidb_tpu_device_join global, not revert to the default."""
        from tidb_tpu.ops import TpuClient
        from tidb_tpu.session import new_store
        store = new_store("memory://joinroute_dj")
        store.set_client(TpuClient(store, dispatch_floor_rows=0))
        tk = TestKit(store)
        tk.exec("set global tidb_tpu_device_join = 0")
        assert store.get_client().device_join is False
        assert TpuClient(store).device_join is False
        tk.exec("set global tidb_tpu_device_join = 1")
        assert TpuClient(store).device_join is True

    def test_no_tpu_client_stays_on_host(self):
        """Without a TpuClient on the store, joins must not touch the
        device path regardless of size."""
        tk = TestKit()
        tk.exec("create database jr2; use jr2")
        tk.exec("create table a (id bigint primary key, k int)")
        tk.exec("create table b (id bigint primary key, k int)")
        tk.exec("insert into a values (1, 1), (2, 2)")
        tk.exec("insert into b values (1, 1), (2, 9)")
        seen = []
        orig = executors.HashJoinExec._try_vector_join

        def spy(self):
            out = orig(self)
            seen.append(self.join_stats.get("path"))
            return out
        executors.HashJoinExec._try_vector_join = spy
        try:
            rows = tk.query(
                "select a.id, b.id from a join b on a.k = b.k").rows
            assert sorted(map(tuple, rows)) == [(1, 1)]
            assert seen[-1] == "numpy"
        finally:
            executors.HashJoinExec._try_vector_join = orig
