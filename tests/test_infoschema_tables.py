"""INFORMATION_SCHEMA virtual table tests (infoschema/tables.go)."""

import pytest

from tidb_tpu import errors
from tests.testkit import TestKit


@pytest.fixture
def tk():
    t = TestKit()
    t.exec("create database d; use d")
    t.exec("create table t (id int primary key, name varchar(32) not null, "
           "v double, key idx_n (name))")
    return t


class TestInformationSchema:
    def test_schemata(self, tk):
        rows = tk.exec("select SCHEMA_NAME from "
                       "information_schema.SCHEMATA order by "
                       "SCHEMA_NAME").rows
        names = [r[0] if isinstance(r[0], str) else r[0].decode()
                 for r in rows]
        assert "d" in names and "mysql" in names
        assert "performance_schema" not in names  # virtual dbs excluded

    def test_tables_and_filtering(self, tk):
        tk.exec("select TABLE_NAME, TABLE_TYPE from "
                "information_schema.TABLES "
                "where TABLE_SCHEMA = 'd'").check([["t", "BASE TABLE"]])

    def test_columns(self, tk):
        rows = tk.exec(
            "select COLUMN_NAME, ORDINAL_POSITION, IS_NULLABLE, DATA_TYPE,"
            " COLUMN_KEY from information_schema.COLUMNS "
            "where TABLE_NAME = 't' order by ORDINAL_POSITION").rows

        def s(v):
            return v if isinstance(v, str) else v.decode()
        assert [[s(r[0]), r[1], s(r[2]), s(r[3]), s(r[4])] for r in rows] \
            == [["id", 1, "NO", "int", "PRI"],
                ["name", 2, "NO", "varchar", "MUL"],
                ["v", 3, "YES", "double", ""]]

    def test_statistics(self, tk):
        tk.exec("select INDEX_NAME, SEQ_IN_INDEX, COLUMN_NAME from "
                "information_schema.STATISTICS where TABLE_NAME = 't'"
                ).check([["idx_n", 1, "name"]])

    def test_snapshot_consistency_after_ddl(self, tk):
        tk.exec("create table u (x int)")
        n = tk.exec("select count(*) from information_schema.TABLES "
                    "where TABLE_SCHEMA = 'd'").rows[0][0]
        assert n == 2
        tk.exec("drop table u")
        n = tk.exec("select count(*) from information_schema.TABLES "
                    "where TABLE_SCHEMA = 'd'").rows[0][0]
        assert n == 1

    def test_read_only_and_case_insensitive_db(self, tk):
        with pytest.raises(errors.TiDBError):
            tk.exec("insert into INFORMATION_SCHEMA.TABLES values ()")
        assert tk.exec("select count(*) from "
                       "INFORMATION_SCHEMA.SCHEMATA").rows[0][0] >= 2

    def test_join_with_group_by(self, tk):
        rows = tk.exec(
            "select TABLE_NAME, count(*) from information_schema.COLUMNS "
            "where TABLE_SCHEMA = 'd' group by TABLE_NAME").rows
        assert [[r[0] if isinstance(r[0], str) else r[0].decode(), r[1]]
                for r in rows] == [["t", 3]]
