"""Privilege system tests: GRANT/REVOKE at all three scopes, CREATE/DROP
USER, and enforcement at execute time.

Mirrors the reference's privileges/privileges_test.go (cache over grant
tables) and executor grant tests; enforcement is exercised both at the
session layer (vars.user set, like a bound Checker) and over the wire.
"""

import pytest

from tidb_tpu import errors
from tidb_tpu.privilege import AccessDenied
from tidb_tpu.server import Client, MySQLError, Server
from tidb_tpu.session import Session, new_store
from tests.testkit import TestKit, _store_id


@pytest.fixture
def env():
    tk = TestKit()
    tk.exec("create database app; use app")
    tk.exec("create table t (a int primary key, b int)")
    tk.exec("insert into t values (1, 10), (2, 20)")
    tk.exec("create database other")
    tk.exec("create table other.s (x int)")
    return tk


def as_user(tk, name):
    s = Session(tk.store)
    s.vars.user = name
    s.vars.current_db = "app"
    return s


class TestGrantLevels:
    def test_global_grant(self, env):
        env.exec("create user 'g1'")
        env.exec("grant select on *.* to 'g1'")
        s = as_user(env, "g1")
        assert s.execute("select b from t where a = 1")[0].values() == [[10]]
        assert s.execute("select x from other.s")[0].values() == []
        with pytest.raises(AccessDenied):
            s.execute("insert into t values (3, 30)")

    def test_db_grant(self, env):
        env.exec("create user 'd1'")
        env.exec("grant select, insert on app.* to 'd1'")
        s = as_user(env, "d1")
        s.execute("insert into t values (3, 30)")
        assert len(s.execute("select * from t")[0].values()) == 3
        with pytest.raises(AccessDenied):
            s.execute("select * from other.s")
        with pytest.raises(AccessDenied):
            s.execute("delete from t")

    def test_table_grant(self, env):
        env.exec("create user 'Tt1'")
        env.exec("grant select on app.t to 'Tt1'")
        s = as_user(env, "Tt1")
        assert len(s.execute("select * from t")[0].values()) == 2
        env.exec("create table u (z int)")
        with pytest.raises(AccessDenied):
            s.execute("select * from u")

    def test_ddl_denied_without_privs(self, env):
        env.exec("create user 'd2'")
        env.exec("grant select on app.* to 'd2'")
        s = as_user(env, "d2")
        for sql in ("create table v (a int)", "drop table t",
                    "create index ix on t (b)", "alter table t add column c int",
                    "truncate table t", "create database newdb",
                    "grant select on app.* to 'd2'"):
            with pytest.raises(AccessDenied):
                s.execute(sql)

    def test_revoke(self, env):
        env.exec("create user 'r1'")
        env.exec("grant all on app.* to 'r1'")
        s = as_user(env, "r1")
        s.execute("delete from t where a = 1")
        env.exec("revoke delete on app.* from 'r1'")
        with pytest.raises(AccessDenied):
            s.execute("delete from t")
        s.execute("select * from t")  # select survives the delete revoke

    def test_insert_select_needs_both(self, env):
        env.exec("create user 'is1'")
        env.exec("grant insert on app.t to 'is1'")
        s = as_user(env, "is1")
        with pytest.raises(AccessDenied):
            s.execute("insert into t select x, x from other.s")
        env.exec("grant select on other.s to 'is1'")
        s.execute("insert into t select x, x from other.s")

    def test_subquery_tables_checked(self, env):
        env.exec("create user 'sq1'")
        env.exec("grant select on app.t to 'sq1'")
        s = as_user(env, "sq1")
        with pytest.raises(AccessDenied):
            s.execute("select * from t where a in (select x from other.s)")

    def test_prepare_execute_checked(self, env):
        """EXECUTE must check the PREPAREd statement's privileges — the
        ExecuteStmt shell itself requires nothing (regression: privilege
        hole via the plan cache path)."""
        env.exec("create user 'pe1'")
        env.exec("grant select on app.t to 'pe1'")
        s = as_user(env, "pe1")
        s.execute("prepare p1 from 'select * from t'")
        s.execute("execute p1")  # allowed: select granted
        s.execute("prepare p2 from 'drop table t'")
        with pytest.raises(AccessDenied):
            s.execute("execute p2")
        env.exec("select count(1) from t").check([[2]])  # still there

    def test_bare_table_grant_without_db_errors(self, env):
        env.exec("create user 'bt1'")
        s = Session(env.store)
        s.vars.user = ""  # root-equivalent internal session, no db
        with pytest.raises(errors.TiDBError):
            s.execute("grant select on t to 'bt1'")
        # and the user must NOT have silently received a global grant
        u = as_user(env, "bt1")
        with pytest.raises(AccessDenied):
            u.execute("select * from t")

    def test_copr_backend_needs_global_grant(self, env):
        env.exec("create user 'cb1'")
        env.exec("grant select on app.* to 'cb1'")
        s = as_user(env, "cb1")
        with pytest.raises(AccessDenied):
            s.execute("set tidb_copr_backend = 'cpu'")

    def test_dispatch_floor_needs_global_grant(self, env):
        """The floor re-routes every session's queries (store-level client
        state) — same Grant gate as the backend switch."""
        env.exec("create user 'df1'")
        env.exec("grant select on app.* to 'df1'")
        s = as_user(env, "df1")
        with pytest.raises(AccessDenied):
            s.execute("set global tidb_tpu_dispatch_floor = 0")

    def test_bare_star_grant_is_current_db_not_global(self, env):
        """GRANT ... ON * = current database (MySQL), NOT *.*."""
        env.exec("create user 'bs1'")
        env.exec("use app")
        env.exec("grant select on * to 'bs1'")
        s = as_user(env, "bs1")
        s.execute("select * from t")  # app.* granted
        with pytest.raises(AccessDenied):
            s.execute("select * from other.s")  # NOT global
        with pytest.raises(AccessDenied):
            s.execute("select User from mysql.user")

    def test_show_grants_for_other_user_needs_mysql_select(self, env):
        env.exec("create user 'sg1'")
        env.exec("grant select on app.* to 'sg1'")
        s = as_user(env, "sg1")
        s.execute("show grants")  # own grants: fine
        with pytest.raises(AccessDenied):
            s.execute("show grants for 'root'")
        env.exec("grant select on mysql.* to 'sg1'")
        assert s.execute("show grants for 'root'")[0].values()

    def test_illegal_table_scope_priv_rejected(self, env):
        env.exec("create user 'il1'")
        with pytest.raises(errors.TiDBError):
            env.exec("grant execute on app.t to 'il1'")
        env.exec("grant all on app.t to 'il1'")  # ALL expands per scope

    def test_unknown_user_denied(self, env):
        s = as_user(env, "ghost")
        with pytest.raises(AccessDenied):
            s.execute("select * from t")


class TestUserManagement:
    def test_create_drop_user(self, env):
        env.exec("create user 'u1' identified by 'secret'")
        with pytest.raises(errors.TiDBError):
            env.exec("create user 'u1'")
        env.exec("create user if not exists 'u1'")
        env.exec("drop user 'u1'")
        with pytest.raises(errors.TiDBError):
            env.exec("drop user 'u1'")
        env.exec("drop user if exists 'u1'")

    def test_drop_user_removes_grants(self, env):
        env.exec("create user 'u2'")
        env.exec("grant select on app.* to 'u2'")
        env.exec("drop user 'u2'")
        env.exec("create user 'u2'")  # fresh user, old grants gone
        s = as_user(env, "u2")
        with pytest.raises(AccessDenied):
            s.execute("select * from t")

    def test_grant_creates_user_and_sets_password(self, env):
        env.exec("grant select on app.* to 'auto1' identified by 'pw1'")
        rows = env.exec("select count(1) from mysql.user "
                        "where User = 'auto1'").rows
        assert rows == [[1]]


class TestWireAuth:
    def test_created_user_authenticates_and_is_enforced(self):
        store = new_store(f"memory://privwire{next(_store_id)}")
        srv = Server(store)
        srv.start()
        try:
            root = Client("127.0.0.1", srv.port)
            root.query("create database app; use app; "
                       "create table t (a int); insert into t values (1)")
            root.query("create user 'w1' identified by 'pw'")
            root.query("grant select on app.t to 'w1'")
            c = Client("127.0.0.1", srv.port, user="w1", password="pw",
                       db="app")
            assert c.query("select a from t")[0].rows == [["1"]]
            with pytest.raises(MySQLError) as ei:
                c.query("drop table t")
            assert ei.value.code == 1045
            c.close()
            root.close()
        finally:
            srv.close()


class TestRevokeNoGrant:
    def test_revoke_db_level_without_grant_errors(self, env):
        env.exec("create user 'rng1'")
        with pytest.raises(Exception) as ei:
            env.exec("revoke select on app.* from 'rng1'")
        assert "no such grant" in str(ei.value)

    def test_revoke_table_level_without_grant_errors(self, env):
        env.exec("create user 'rng2'")
        with pytest.raises(Exception) as ei:
            env.exec("revoke select on app.t from 'rng2'")
        assert "no such grant" in str(ei.value)

    def test_revoke_after_grant_still_works(self, env):
        env.exec("create user 'rng3'")
        env.exec("grant select on app.* to 'rng3'")
        env.exec("revoke select on app.* from 'rng3'")  # no raise
        s = as_user(env, "rng3")
        with pytest.raises(AccessDenied):
            s.execute("select * from t")


class TestSchemaInspectionGate:
    def test_show_create_table_denied_without_any_priv(self, env):
        env.exec("create user 'si1'")
        s = as_user(env, "si1")
        with pytest.raises(AccessDenied):
            s.execute("show create table app.t")

    def test_show_columns_allowed_with_table_priv(self, env):
        env.exec("create user 'si2'")
        env.exec("grant select on app.t to 'si2'")
        s = as_user(env, "si2")
        s.execute("use app")
        assert s.execute("show columns from t")[0].values()


class TestHostMatching:
    """Host-scoped identities (round-3 weak #6): grant rows carry host
    patterns matched against the client address — 'u'@'a' and 'u'@'b'
    are now DIFFERENT identities. Reference row filter:
    privilege/privileges/privileges.go:253 (Host = h OR Host = '%'),
    generalized to MySQL %/_ patterns."""

    def test_pattern_matching(self):
        from tidb_tpu.privilege import host_match, host_specificity
        assert host_match("%", "anything.example.com")
        assert host_match("", "h")
        assert host_match("localhost", "LOCALHOST")
        assert not host_match("localhost", "remote")
        assert host_match("10.0.0.%", "10.0.0.7")
        assert not host_match("10.0.0.%", "10.0.1.7")
        assert host_match("app_.corp", "app1.corp")
        # specificity: exact < wildcarded; fewer wildcards first
        order = sorted(["%", "10.0.0.%", "localhost"],
                       key=host_specificity)
        assert order == ["localhost", "10.0.0.%", "%"]

    def test_host_scoped_privileges(self):
        from tidb_tpu import privilege as pv
        from tests.testkit import _store_id
        from tidb_tpu.session import Session, new_store
        store = new_store(f"memory://privhost{next(_store_id)}")
        root = Session(store)
        root.execute("create database app; use app")
        root.execute("create table t (a int primary key)")
        root.execute("insert into t values (1)")
        root.execute("create user 'u'@'localhost' identified by 'pw'")
        root.execute("create user 'u'@'10.0.0.%' identified by 'pw2'")
        root.execute("grant select on app.* to 'u'@'localhost'")
        root.execute("grant insert on app.* to 'u'@'10.0.0.%'")
        c = pv.checker_for(store)
        assert c.check("u", "app", "t", "Select", host="localhost")
        assert not c.check("u", "app", "t", "Insert", host="localhost")
        assert c.check("u", "app", "t", "Insert", host="10.0.0.9")
        assert not c.check("u", "app", "t", "Select", host="10.0.0.9")
        # a host matching NO row holds nothing
        assert not c.check("u", "app", "t", "Select", host="evil.example")

    def test_auth_picks_most_specific_row(self):
        """'u'@'localhost' and 'u'@'%' with different passwords: a local
        client must authenticate against the localhost row."""
        from tests.testkit import _store_id
        from tidb_tpu.server import Client, MySQLError, Server
        from tidb_tpu.session import Session, new_store
        store = new_store(f"memory://privauth{next(_store_id)}")
        root = Session(store)
        root.execute("create user 'u'@'localhost' identified by 'local_pw'")
        root.execute("create user 'u'@'%' identified by 'any_pw'")
        server = Server(store)
        server.start()
        try:
            c = Client("127.0.0.1", server.port, user="u",
                       password="local_pw")
            c.close()
            with pytest.raises(MySQLError):
                Client("127.0.0.1", server.port, user="u",
                       password="any_pw")
        finally:
            server.close()

    def test_check_stmt_uses_client_host(self):
        from tests.testkit import _store_id
        from tidb_tpu import privilege as pv
        from tidb_tpu.session import Session, new_store
        store = new_store(f"memory://privstmt{next(_store_id)}")
        root = Session(store)
        root.execute("create database app; use app")
        root.execute("create table t (a int primary key)")
        root.execute("create user 'ro'@'localhost'")
        root.execute("grant select on app.t to 'ro'@'localhost'")
        s = Session(store)
        s.vars.user = "ro"
        s.vars.client_host = "localhost"
        s.execute("use app")
        assert s.execute("select * from t")[0].values() == []
        s.vars.client_host = "elsewhere.net"
        with pytest.raises(pv.AccessDenied):
            s.execute("select * from t")


class TestHostReviewFixes:
    """Round-4 review: bare GRANT must not mint passwordless identities;
    SHOW GRANTS is identity-scoped."""

    def test_bare_grant_to_unknown_identity_errors_1133(self):
        from tests.testkit import _store_id
        from tidb_tpu.session import Session, new_store
        store = new_store(f"memory://privnac{next(_store_id)}")
        root = Session(store)
        root.execute("create database app; use app")
        root.execute("create table t (a int primary key)")
        root.execute("create user 'u'@'%' identified by 'pw'")
        with pytest.raises(errors.TiDBError) as ei:
            root.execute("grant select on app.* to 'u'@'localhost'")
        assert getattr(ei.value, "code", None) == 1133
        # with a password the account IS created (MySQL GRANT..IDENTIFIED)
        root.execute("grant select on app.* to 'v'@'localhost' "
                     "identified by 'vpw'")
        n = root.execute("select count(1) from mysql.user where User = 'v' "
                         "and Host = 'localhost'")[0].values()
        assert n == [[1]]

    def test_show_grants_scoped_to_identity(self):
        from tests.testkit import _store_id
        from tidb_tpu.session import Session, new_store
        store = new_store(f"memory://privsg{next(_store_id)}")
        root = Session(store)
        root.execute("create database app; use app")
        root.execute("create table t (a int primary key)")
        root.execute("create user 'u'@'localhost' identified by 'p1'")
        root.execute("create user 'u'@'%' identified by 'p2'")
        root.execute("grant select on app.* to 'u'@'localhost'")
        # FOR 'u'@'%' must NOT list the localhost identity's SELECT
        rows = [r[0] for r in
                root.execute("show grants for 'u'@'%'")[0].values()]
        assert not any("SELECT" in g for g in rows), rows
        rows = [r[0] for r in
                root.execute("show grants for 'u'@'localhost'")[0].values()]
        assert any("SELECT" in g and "@'localhost'" in g for g in rows)
        # a session authenticated via the % row from a remote host sees
        # only what it actually holds
        s = Session(store)
        s.vars.user = "u"
        s.vars.client_host = "10.1.2.3"
        rows = [r[0] for r in s.execute("show grants")[0].values()]
        assert not any("SELECT" in g for g in rows), rows
