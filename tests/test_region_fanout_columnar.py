"""Differential parity for the columnar channel ACROSS the cluster
store's per-region fan-out: a scan answered as per-region
ColumnarScanResult partials (stacked into a ColumnarPartialSet, fused
aggregates merging per-region partial states device-side) must be
row-for-row identical to the single-region columnar path AND to the row
protocol — including a region split and a region merge injected MID-SCAN
via cluster.topology, the tidb_tpu_columnar_scan kill switch, per-PARTIAL
hit/fallback attribution for mixed responses, and the unsigned-bigint
pack overflow regression on both pack paths.
"""

from __future__ import annotations

import itertools

import pytest

from tidb_tpu import metrics, tablecodec as tc
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 240

JOIN_AGG_Q = ("select count(*), sum(t.v), min(t.v), max(d.d_f), avg(t.v), "
              "sum(t.f) from t join d on t.k = d.d_k")
GROUPED_Q = ("select t.k, count(*), sum(t.v), min(t.f), max(t.v) "
             "from t join d on t.k = d.d_k group by t.k order by t.k")
QUERIES = [
    JOIN_AGG_Q,
    GROUPED_Q,
    "select t.id, t.v, d.d_f from t join d on t.k = d.d_k order by t.id",
    "select t.id, d.d_k from t left join d on t.k = d.d_k "
    "and d.d_f > 2.0 order by t.id",
    "select count(*), sum(v) from t join d on t.k = d.d_k "
    "where t.v > 500",
    "select id, v from t order by v desc limit 7",
    "select id, f from t where k < 5 order by f limit 9",
    "select k, count(*), min(v) from t group by k order by k",
]


def _build(n_regions: int):
    store = new_store(f"cluster://3/fanout{next(_id)}")
    s = Session(store)
    s.execute("create database fo")
    s.execute("use fo")
    s.execute("create table t (id bigint primary key, k bigint, "
              "v bigint, f double)")
    rows = ", ".join(
        f"({i}, {i % 7}, {i * 10}, {i}.25)" if i % 11 else
        f"({i}, null, {i * 10}, null)"
        for i in range(1, N_ROWS + 1))
    s.execute(f"insert into t values {rows}")
    s.execute("create table d (d_k bigint primary key, d_f double)")
    s.execute("insert into d values " +
              ", ".join(f"({i}, {i}.5)" for i in range(7)))
    if n_regions > 1:
        tid = s.info_schema().table_by_name("fo", "t").info.id
        step = N_ROWS // n_regions
        s.store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _counter(name):
    return metrics.counter(f"distsql.columnar_{name}").value


@pytest.fixture(scope="module")
def single():
    return _build(1)


@pytest.mark.parametrize("n_regions", [2, 4, 8])
def test_multi_region_parity(single, n_regions):
    """Stacked per-region partials vs the single-region columnar path vs
    the row protocol: row-for-row identical on every query shape."""
    multi = _build(n_regions)
    h0, p0, f0 = _counter("hits"), _counter("partials"), _counter(
        "fallbacks")
    got = [multi.execute(q)[0].values() for q in QUERIES]
    assert _counter("hits") - h0 >= n_regions, \
        "fan-out scans did not answer per-region columnar partials"
    assert _counter("partials") - p0 >= n_regions
    assert _counter("fallbacks") == f0, \
        "a hinted region partial fell back to rows"
    want = [single.execute(q)[0].values() for q in QUERIES]
    for q, g, w in zip(QUERIES, got, want):
        assert g == w, f"multi-region diverged from single-region on {q!r}"
    multi.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        rows = [multi.execute(q)[0].values() for q in QUERIES]
    finally:
        multi.execute("set global tidb_tpu_columnar_scan = 1")
    for q, g, r in zip(QUERIES, got, rows):
        assert g == r, f"columnar fan-out diverged from row protocol {q!r}"


def test_partial_combine_runs_device_side(single):
    """The fused aggregate over a 4-region join merges per-region partial
    states through the device combine (one combine per fusion)."""
    from tidb_tpu.executor import fused_agg
    multi = _build(4)
    before = fused_agg.stats["partial_combines"]
    got = multi.execute(JOIN_AGG_Q)[0].values()
    assert fused_agg.stats["partial_combines"] > before, \
        "multi-region fusion did not take the partial-combine path"
    assert fused_agg.stats["last_combine_regions"] >= 4
    assert got == single.execute(JOIN_AGG_Q)[0].values()
    # grouped fusion combines too
    before = fused_agg.stats["partial_combines"]
    got = multi.execute(GROUPED_Q)[0].values()
    assert fused_agg.stats["partial_combines"] > before
    assert got == single.execute(GROUPED_Q)[0].values()


class TestTopologyChangesMidScan:
    """Region split / merge DURING the fan-out: the per-task worklist
    retries on StaleEpoch and re-emits partials for the new region shape
    without breaking plane alignment (each partial is self-contained)."""

    def _with_mid_scan(self, mutate_after: int, mutate):
        s = _build(4)
        store = s.store
        want = [s.execute(q)[0].values() for q in QUERIES]
        orig = store.rpc.cop_request
        state = {"n": 0, "done": False}

        def hook(ctx, sel, ranges, read_ts):
            state["n"] += 1
            if state["n"] == mutate_after and not state["done"]:
                state["done"] = True
                mutate(store)
            return orig(ctx, sel, ranges, read_ts)

        store.rpc.cop_request = hook
        try:
            got = [s.execute(q)[0].values() for q in QUERIES]
        finally:
            store.rpc.cop_request = orig
        assert state["done"], "topology mutation never fired"
        for q, g, w in zip(QUERIES, got, want):
            assert g == w, f"mid-scan topology change diverged on {q!r}"
        # and the post-mutation steady state still matches
        after = [s.execute(q)[0].values() for q in QUERIES]
        for q, a, w in zip(QUERIES, after, want):
            assert a == w, f"post-mutation steady state diverged on {q!r}"

    def test_split_mid_scan(self):
        def split(store):
            # split INSIDE the table's key space, between existing splits
            from tidb_tpu.session import Session
            s = Session(store)
            tid = s.info_schema().table_by_name("fo", "t").info.id
            store.cluster.split_keys([tc.encode_row_key(tid, 31),
                                      tc.encode_row_key(tid, 171)])

        self._with_mid_scan(2, split)

    def test_merge_mid_scan(self):
        def merge(store):
            regions = store.cluster.regions
            # merge the two middle regions (adjacent by construction)
            for i in range(len(regions) - 1):
                if regions[i].start:   # skip the leading region
                    store.cluster.merge(regions[i].region_id,
                                        regions[i + 1].region_id)
                    return

        self._with_mid_scan(2, merge)


def test_mixed_response_counts_per_partial():
    """A response where ONE region falls back to rows (u64 values above
    the int64 plane live only in that region) counts hits for the
    columnar partials AND fallbacks for the row partial on the SAME
    request, and every result still matches the row protocol."""
    store = new_store(f"cluster://3/fanmix{next(_id)}")
    s = Session(store)
    s.execute("create database fm")
    s.execute("use fm")
    s.execute("create table t (id bigint primary key, u bigint unsigned, "
              "k bigint)")
    rows = ", ".join(f"({i}, {i}, {i % 3})" for i in range(1, 101))
    s.execute(f"insert into t values {rows}")
    # the poison value lives in the LAST region only
    s.execute("insert into t values (200, 9223372036854775813, 1)")
    s.execute("create table d (d_k bigint primary key)")
    s.execute("insert into d values (0), (1), (2)")
    tid = s.info_schema().table_by_name("fm", "t").info.id
    store.cluster.split_keys([tc.encode_row_key(tid, 40),
                              tc.encode_row_key(tid, 80),
                              tc.encode_row_key(tid, 120)])
    q = "select t.id, t.u from t join d on t.k = d.d_k order by t.id"
    h0, f0 = _counter("hits"), _counter("fallbacks")
    got = s.execute(q)[0].values()
    assert _counter("hits") - h0 >= 3, \
        "clean regions did not answer columnar partials"
    assert _counter("fallbacks") - f0 >= 1, \
        "the u64-poisoned region did not count a row fallback"
    s.execute("set global tidb_tpu_columnar_scan = 0")
    assert s.execute(q)[0].values() == got
    assert len(got) == 101


class TestU64PackRegression:
    """Seed bug: unsigned bigint above int64 range broke the columnar
    pack (Python path OverflowError, native path silent wrap). Both
    paths must raise TypeError_ → CPU fallback, like out-of-scale
    decimals."""

    BIG = 9223372036854775813          # i64max + 6
    ROWS = ("(1, 5), (2, 9223372036854775813), "
            "(3, 18446744073709551615), (4, null)")

    def _tpu_session(self):
        from tidb_tpu.ops import TpuClient
        store = new_store(f"memory://u64pack{next(_id)}")
        store.set_client(TpuClient(store, dispatch_floor_rows=0))
        s = Session(store)
        s.execute("create database u; use u")
        s.execute("create table t (id bigint primary key, "
                  "u bigint unsigned)")
        s.execute(f"insert into t values {self.ROWS}")
        return s

    WANT_MAX = [[4, 18446744073709551615]]

    def test_native_pack_path_falls_back(self):
        s = self._tpu_session()
        client = s.store.get_client()
        f0 = client.stats["cpu_fallbacks"]
        assert s.execute("select count(*), max(u) from t")[0].values() \
            == self.WANT_MAX
        assert client.stats["cpu_fallbacks"] > f0, \
            "u64 overflow did not take the CPU fallback (native pack)"
        assert s.execute("select u from t where u > 10 order by id")[0] \
            .values() == [[self.BIG], [18446744073709551615]]

    def test_python_pack_path_falls_back(self):
        import tidb_tpu.ops.nativepack as npk
        s = self._tpu_session()
        client = s.store.get_client()
        orig = npk.scan_rows
        npk.scan_rows = lambda *a, **k: None   # force the Python pack
        try:
            f0 = client.stats["cpu_fallbacks"]
            assert s.execute("select count(*), max(u) from t")[0] \
                .values() == self.WANT_MAX
            assert client.stats["cpu_fallbacks"] > f0, \
                "u64 overflow did not take the CPU fallback (python pack)"
        finally:
            npk.scan_rows = orig

    def test_region_pack_falls_back_to_rows(self):
        """The per-region columnar engine takes the same TypeError_ →
        row-handler fallback (counted as a per-partial fallback)."""
        store = new_store(f"cluster://3/u64r{next(_id)}")
        s = Session(store)
        s.execute("create database u; use u")
        s.execute("create table t (id bigint primary key, "
                  "u bigint unsigned, k bigint)")
        s.execute("insert into t values (1, 9223372036854775813, 1), "
                  "(2, 7, 1)")
        s.execute("create table d (d_k bigint primary key)")
        s.execute("insert into d values (1)")
        f0 = _counter("fallbacks")
        got = s.execute("select t.id, t.u from t join d on t.k = d.d_k "
                        "order by t.id")[0].values()
        assert got == [[1, self.BIG], [2, 7]]
        assert _counter("fallbacks") > f0


def test_in_proc_single_partial_unchanged():
    """The localstore TpuClient response stays a single partial: one hit,
    one partial per hinted scan (back-compat for the PR-2 contract)."""
    from tidb_tpu.ops import TpuClient
    store = new_store(f"memory://fanone{next(_id)}")
    store.set_client(TpuClient(store, dispatch_floor_rows=0))
    s = Session(store)
    s.execute("create database o; use o")
    s.execute("create table t (id bigint primary key, k bigint)")
    s.execute("insert into t values " +
              ", ".join(f"({i}, {i % 3})" for i in range(1, 40)))
    s.execute("create table d (d_k bigint primary key)")
    s.execute("insert into d values (0), (1), (2)")
    h0, p0 = _counter("hits"), _counter("partials")
    s.execute("select count(*) from t join d on t.k = d.d_k")
    assert _counter("hits") - h0 == 2        # one per scan side
    assert _counter("partials") - p0 == 2
