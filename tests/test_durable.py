"""Durable localstore engine: WAL + snapshot recovery.

Reference: store/localstore/engine/engine.go:22-60 (Driver/DB/Batch
boundary), goleveldb.go / boltdb.go (disk engines selected by
--store/--path, tidb-server/main.go:66). Here the engine is the
durability boundary: commits are WAL-appended before the in-memory apply,
snapshots checkpoint the MVCC state, recovery = snapshot + WAL replay
with torn-tail truncation.
"""

from __future__ import annotations

import itertools
import os
import struct

import pytest

from tidb_tpu.domain import clear_domains
from tidb_tpu.kv.kv import close_store
from tidb_tpu.localstore.engine import WalEngine
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)


def _open(url):
    return Session(new_store(url))


def _restart(url):
    """Simulate a process restart: close + evict the store, drop domains
    (schema caches die with the process)."""
    close_store(url)
    clear_domains()


@pytest.fixture
def url(tmp_path):
    return f"local://{tmp_path}/db{next(_id)}"


class TestDurability:
    def test_schema_rows_meta_survive_restart(self, url):
        s = _open(url)
        s.execute("create database app; use app")
        s.execute("create table t (a int primary key auto_increment, "
                  "b varchar(20), key ib (b))")
        s.execute("insert into t (b) values ('x'), ('y'), ('z')")
        s.execute("update t set b = 'yy' where a = 2")
        s.execute("delete from t where a = 3")
        _restart(url)

        s2 = _open(url)
        s2.execute("use app")
        assert s2.execute("select a, b from t order by a")[0].values() == \
            [[1, "x"], [2, "yy"]]
        # index scan works → index KV survived
        rows = s2.execute("select a from t where b = 'yy'")[0].values()
        assert rows == [[2]]
        # auto-id allocator resumes ABOVE old handles (meta survived)
        s2.execute("insert into t (b) values ('w')")
        new_id = s2.execute("select max(a) from t")[0].values()[0][0]
        assert new_id > 2

    def test_stats_survive_restart(self, url):
        s = _open(url)
        s.execute("create database app; use app")
        s.execute("create table t (a int primary key)")
        s.execute("insert into t values " +
                  ", ".join(f"({i})" for i in range(1, 101)))
        s.execute("analyze table t")
        _restart(url)
        s2 = _open(url)
        s2.execute("use app")
        st = s2.domain.stats_for(
            s2.info_schema().table_by_name("app", "t").info.id)
        assert st is not None and st.count == 100

    def test_oracle_monotonic_after_restart(self, url):
        s = _open(url)
        s.execute("create database app")
        before = s.store.current_version()
        _restart(url)
        s2 = _open(url)
        assert s2.store.current_version() > before

    def test_crash_mid_commit_truncates_torn_tail(self, url):
        s = _open(url)
        s.execute("create database app; use app; "
                  "create table t (a int primary key)")
        s.execute("insert into t values (1), (2)")
        store = s.store
        wal = store.engine.wal_path
        close_store(url)
        clear_domains()
        # simulate a crash mid-append: a half-written record at the tail
        good = os.path.getsize(wal)
        with open(wal, "ab") as f:
            f.write(struct.pack("<II", 1 << 20, 0xDEAD) + b"partial")
        s2 = _open(url)
        s2.execute("use app")
        assert s2.execute("select count(1) from t")[0].values() == [[2]]
        # the torn tail was truncated; new commits append cleanly
        s2.execute("insert into t values (3)")
        _restart(url)
        s3 = _open(url)
        s3.execute("use app")
        assert s3.execute("select count(1) from t")[0].values() == [[3]]
        assert os.path.getsize(s3.store.engine.wal_path) >= good

    def test_snapshot_checkpoint_and_recovery(self, url, tmp_path):
        s = _open(url)
        store = s.store
        # force frequent snapshots
        store.engine.snapshot_wal_bytes = 1
        s.execute("create database app; use app; "
                  "create table t (a int primary key, b int)")
        for i in range(5):
            s.execute(f"insert into t values ({i}, {i * 10})")
        assert os.path.exists(store.engine.snap_path)
        # WAL restarted after the checkpoint → small
        assert store.engine.wal_size() < 4096
        _restart(url)
        s2 = _open(url)
        s2.execute("use app")
        assert s2.execute("select count(1), sum(b) from t")[0].values() == \
            [[5, 100]]

    def test_torn_snapshot_is_ignored(self, url):
        s = _open(url)
        s.execute("create database app; use app; "
                  "create table t (a int primary key)")
        s.execute("insert into t values (1)")
        snap = s.store.engine.snap_path
        close_store(url)
        clear_domains()
        with open(snap, "wb") as f:
            f.write(b"TPUSNAP1garbage")   # corrupt: fails CRC
        s2 = _open(url)
        s2.execute("use app")
        # WAL alone still reconstructs everything
        assert s2.execute("select count(1) from t")[0].values() == [[1]]


class TestWalEngineUnit:
    def test_roundtrip_tombstones_and_values(self, tmp_path):
        e = WalEngine(str(tmp_path / "e1"))
        cells, commits = e.recover()
        assert cells is None and commits == []
        e.append_commit(7, [(b"k1", b"v1"), (b"k2", None)])
        e.append_commit(9, [(b"k1", None)])
        e.close()
        e2 = WalEngine(str(tmp_path / "e1"))
        cells, commits = e2.recover()
        assert cells is None
        assert commits == [(7, [(b"k1", b"v1"), (b"k2", None)]),
                           (9, [(b"k1", None)])]
        e2.close()

    def test_snapshot_roundtrip(self, tmp_path):
        e = WalEngine(str(tmp_path / "e2"))
        e.recover()
        e.append_commit(5, [(b"a", b"1")])
        e.snapshot({b"a": [(5, b"1"), (3, None)]})
        e.append_commit(8, [(b"b", b"2")])
        e.close()
        e2 = WalEngine(str(tmp_path / "e2"))
        cells, commits = e2.recover()
        assert cells == {b"a": [(5, b"1"), (3, None)]}
        assert commits == [(8, [(b"b", b"2")])]
        e2.close()
