"""Builtin-function parity gate vs the reference evaluator registry.

REF_FUNCS below is the complete key set of the reference's Funcs map
(/root/reference/evaluator/builtin.go:43, ast constants resolved through
ast/functions.go), transcribed so the gate holds without the reference
checked out. Every name must either be a registered callable builtin
(expression/builtin.py FUNCS) or execute through its SQL special form
(operators, CONVERT, ROW, user variables — which the reference routes
through the same Funcs map but this engine implements in the
expression-ops layer). Nothing may be silently absent.
"""

import pytest

from tidb_tpu.expression import builtin
from tidb_tpu.session import Session, new_store

REF_FUNCS = """
abs and ascii bitand bitneg bitor bitxor case ceil ceiling coalesce concat
concat_ws connection_id convert curdate current_date current_time
current_timestamp current_user curtime database date date_arith date_format
day dayname dayofmonth dayofweek dayofyear div eq extract found_rows
from_unixtime ge get_lock getvar greatest gt hex hour if ifnull in intdiv
isfalse isnull istrue last_insert_id lcase le left leftshift length like
locate lower lt ltrim microsecond minus minute mod month monthname mul ne
not now nulleq nullif or plus pow power rand regexp release_lock repeat
replace reverse rightshift round row rtrim second setvar sleep space strcmp
substring substring_index sysdate time trim ucase unaryminus unaryplus
unhex upper user utc_date version week weekday weekofyear xor year yearweek
""".split()

# reference Funcs entries that are SQL special forms here, with a probe
# statement exercising each through the full parse→plan→execute path
SPECIAL_FORMS = {
    "and": "select 1 and 0",
    "or": "select 1 or 0",
    "not": "select not 1",
    "xor": "select 1 xor 0",
    "bitand": "select 6 & 3",
    "bitor": "select 6 | 3",
    "bitxor": "select 6 ^ 3",
    "bitneg": "select ~1",
    "leftshift": "select 1 << 2",
    "rightshift": "select 8 >> 2",
    "plus": "select 1 + 2",
    "minus": "select 3 - 1",
    "mul": "select 2 * 3",
    "div": "select 7 / 2",
    "intdiv": "select 7 div 2",
    "mod": "select 7 % 3",
    "unaryminus": "select -(1)",
    "unaryplus": "select +(1)",
    "eq": "select 1 = 1",
    "ne": "select 1 != 2",
    "lt": "select 1 < 2",
    "le": "select 1 <= 2",
    "gt": "select 2 > 1",
    "ge": "select 2 >= 1",
    "nulleq": "select null <=> null",
    "istrue": "select 1 is true",
    "isfalse": "select 0 is false",
    "convert": "select convert('12', signed)",
    "date_arith": "select date_add('2024-01-01', interval 1 day)",
    "row": "select (1, 2) = (1, 2)",
    "getvar": "select @parity_var",
    "setvar": "set @parity_var = 5",
    "case": "select case when 1 then 'a' else 'b' end",
    "in": "select 1 in (1, 2)",
    "like": "select 'ab' like 'a%'",
    "if": "select if(1, 'a', 'b')",          # also a callable builtin
}


def test_reference_funcs_count_is_stable():
    assert len(REF_FUNCS) == 110
    assert len(set(REF_FUNCS)) == 110


def test_every_reference_func_has_a_counterpart():
    missing = [n for n in REF_FUNCS
               if n not in builtin.FUNCS and n not in SPECIAL_FORMS]
    assert not missing, f"reference Funcs with no counterpart: {missing}"


@pytest.fixture(scope="module")
def s():
    sess = Session(new_store("memory://funcs_parity"))
    sess.execute("create database fp")
    sess.execute("use fp")
    return sess


def test_special_forms_execute(s):
    for name, sql in SPECIAL_FORMS.items():
        s.execute(sql)   # must not raise


def test_registered_builtins_are_callable(s):
    """Smoke-call each reference Funcs entry that maps to a callable
    builtin with representative arguments (NULL propagation makes a
    single NULL argument a safe universal probe for most)."""
    argful = {
        "get_lock": "select get_lock('fp_l', 0)",
        "release_lock": "select release_lock('fp_l')",
        "sleep": "select sleep(0)",
        "strcmp": "select strcmp('a', 'b')",
        "locate": "select locate('b', 'abc')",
        "concat_ws": "select concat_ws(',', 'a', 'b')",
        "nullif": "select nullif(1, 2)",
        "ifnull": "select ifnull(null, 2)",
        "if": "select if(1, 2, 3)",
        "greatest": "select greatest(1, 2)",
        "coalesce": "select coalesce(null, 1)",
        "pow": "select pow(2, 3)",
        "power": "select power(2, 3)",
        "round": "select round(1.5)",
        "left": "select left('abc', 2)",
        "repeat": "select repeat('a', 2)",
        "substring": "select substring('abc', 2)",
        "substring_index": "select substring_index('a.b', '.', 1)",
        "regexp": "select 'a' regexp 'a'",
        "date_format": "select date_format('2024-01-02', '%Y')",
        "from_unixtime": "select from_unixtime(0)",
        "week": "select week('2024-01-02')",
        "yearweek": "select yearweek('2024-01-02')",
        "extract": "select extract(year from '2024-01-02')",
        "replace": "select replace('aa', 'a', 'b')",
    }
    zero_arg = {"connection_id", "current_user", "database", "found_rows",
                "last_insert_id", "user", "version", "rand", "now",
                "curdate", "current_date", "curtime", "current_time",
                "current_timestamp", "sysdate", "utc_date"}
    for name in REF_FUNCS:
        if name not in builtin.FUNCS:
            continue
        if name in SPECIAL_FORMS and name not in argful:
            continue   # keyword syntax; already probed above
        if name in argful:
            s.execute(argful[name])
        elif name in zero_arg:
            s.execute(f"select {name}()")
        else:
            s.execute(f"select {name}(null)")   # NULL-propagating probe


# every top-level statement production of the reference grammar
# (parser.y:4246 Statement:), with a probe that must parse here
REF_STATEMENTS = {
    "AdminStmt": "admin check table t",
    "AlterTableStmt": "alter table t add column c int",
    "AnalyzeTableStmt": "analyze table t",
    "BeginTransactionStmt": "begin",
    "BinlogStmt": "binlog 'YmFzZTY0'",
    "CommitStmt": "commit",
    "CreateDatabaseStmt": "create database d",
    "CreateIndexStmt": "create index i on t (a)",
    "CreateTableStmt": "create table t (a int)",
    "CreateUserStmt": "create user 'u'",
    "DeallocateStmt": "deallocate prepare p",
    "DeleteFromStmt": "delete from t where a = 1",
    "DoStmt": "do 1",
    "DropDatabaseStmt": "drop database d",
    "DropIndexStmt": "drop index i on t",
    "DropTableStmt": "drop table t",
    "DropUserStmt": "drop user 'u'",
    "DropViewStmt": "drop view if exists v",
    "EmptyStmt": ";",
    "ExecuteStmt": "execute p",
    "ExplainStmt": "explain select 1",
    "FlushStmt": "flush privileges",
    "GrantStmt": "grant select on d.* to 'u'",
    "InsertIntoStmt": "insert into t values (1)",
    "LoadDataStmt": "load data local infile 'f' into table t",
    "LockTablesStmt": "lock tables t read, u write",
    "PreparedStmt": "prepare p from 'select 1'",
    "ReplaceIntoStmt": "replace into t values (1)",
    "RollbackStmt": "rollback",
    "SelectStmt": "select 1",
    "SetStmt": "set @x = 1",
    "ShowStmt": "show tables",
    "TruncateTableStmt": "truncate table t",
    "UnionStmt": "select 1 union select 2",
    "UnlockTablesStmt": "unlock tables",
    "UpdateStmt": "update t set a = 1",
    "UseStmt": "use d",
}


def test_every_reference_statement_parses():
    from tidb_tpu.parser.parser import Parser
    p = Parser()
    failed = []
    for name, sql in REF_STATEMENTS.items():
        try:
            p.parse(sql)
        except Exception as e:
            failed.append((name, str(e)[:60]))
    assert not failed, failed
    assert len(REF_STATEMENTS) == 37   # transcription guard
