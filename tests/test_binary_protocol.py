"""Binary prepared-statement protocol (COM_STMT_PREPARE/EXECUTE/CLOSE).

Two tiers, per the round-3 verdict's conformance ask:

1. Round-trip tests through the in-repo client's binary half
   (server/client.py prepare/execute) — breadth over types and flows.
2. GOLDEN-PACKET tests: raw command payloads hand-assembled from the
   MySQL 4.1 protocol specification (byte layouts transcribed from the
   protocol docs, matching what mysql-connector/pymysql emit), sent over
   the socket without using the server's own protocol helpers, and the
   responses asserted byte-for-byte. The server is graded against the
   spec, not against its twin.

Reference: server/conn_stmt.go:47 (handleStmtPrepare), :104
(handleStmtExecute), binary resultset encoding therein.
"""

from __future__ import annotations

import datetime as dt
import struct
from decimal import Decimal

import pytest

from tidb_tpu.server import Client, MySQLError, Server
from tidb_tpu.server import protocol as p
from tests.testkit import _store_id
from tidb_tpu.session import new_store


@pytest.fixture
def srv():
    store = new_store(f"memory://binproto{next(_store_id)}")
    server = Server(store)
    server.start()
    yield server
    server.close()


def connect(server, **kw) -> Client:
    return Client("127.0.0.1", server.port, **kw)


@pytest.fixture
def seeded(srv):
    c = connect(srv)
    c.query("create database app; use app; "
            "create table t (a bigint primary key, b varchar(20), "
            "c double, d date)")
    c.query("insert into t values (1, 'x', 1.5, '2024-01-15'), "
            "(2, 'y', 2.5, '2024-02-10'), (3, null, null, null)")
    return srv, c


class TestBinaryRoundTrip:
    def test_select_with_params(self, seeded):
        srv_, c = seeded
        sid, n = c.prepare("select a, b, c, d from t where a > ? order by a")
        assert n == 1
        r = c.execute(sid, (1,))
        assert r.columns == ["a", "b", "c", "d"]
        assert r.rows[0][:3] == [2, "y", 2.5]
        assert r.rows[0][3] == dt.datetime(2024, 2, 10)
        assert r.rows[1] == [3, None, None, None]
        c.close_stmt(sid)

    def test_param_types(self, seeded):
        srv_, c = seeded
        sid, n = c.prepare("select ?, ?, ?, ?")
        assert n == 4
        r = c.execute(sid, (42, 2.5, "hi", None))
        assert r.rows == [[42, 2.5, "hi", None]]

    def test_insert_update_affected_rows(self, seeded):
        srv_, c = seeded
        sid, _ = c.prepare("insert into t values (?, ?, ?, ?)")
        r = c.execute(sid, (10, "z", 9.5, "2024-03-03"))
        assert r.affected == 1 and r.rows is None
        sid2, _ = c.prepare("update t set b = ? where a >= ?")
        r = c.execute(sid2, ("w", 2))
        assert r.affected >= 2
        check = c.query("select b from t where a = 10")[0]
        assert check.rows == [["w"]]

    def test_decimal_and_null_params(self, seeded):
        srv_, c = seeded
        c.query("create table app.dec1 (a decimal(10,2))")
        sid, _ = c.prepare("insert into app.dec1 values (?)")
        c.execute(sid, (Decimal("12.34"),))
        c.execute(sid, (None,))
        r = c.query("select a from app.dec1 order by a")[0]
        assert r.rows == [[None], ["12.34"]]

    def test_repeat_execute_uses_plan_cache(self, seeded):
        srv_, c = seeded
        sid, _ = c.prepare("select count(1) from t where a >= ?")
        assert c.execute(sid, (1,)).rows == [[3]]
        assert c.execute(sid, (3,)).rows == [[1]]
        assert c.execute(sid, (99,)).rows == [[0]]

    def test_unknown_stmt_id_errors(self, seeded):
        srv_, c = seeded
        with pytest.raises(MySQLError) as ei:
            c.execute(9999, ())
        assert ei.value.code == 1243

    def test_close_then_execute_errors(self, seeded):
        srv_, c = seeded
        sid, _ = c.prepare("select 1")
        c.close_stmt(sid)
        with pytest.raises(MySQLError):
            c.execute(sid, ())

    def test_prepared_privileges_enforced(self, seeded):
        srv_, c = seeded
        c.query("create user 'bp1' identified by 'pw'")
        c.query("grant select on app.t to 'bp1'")
        u = connect(srv_, user="bp1", password="pw", db="app")
        sid, _ = u.prepare("select a from t where a = ?")
        assert u.execute(sid, (1,)).rows == [[1]]
        sid2, _ = u.prepare("delete from t where a = ?")
        with pytest.raises(MySQLError) as ei:
            u.execute(sid2, (1,))
        assert ei.value.code == 1045
        u.close()


# ---------------------------------------------------------------------------
# golden packets: spec-transcribed bytes, responses asserted byte-for-byte
# ---------------------------------------------------------------------------


def _raw_conn(server):
    """Authenticated raw packet channel (auth itself is covered by the
    round-trip tier; these tests focus on COM_STMT_* framing)."""
    c = connect(server)
    return c, c.pkt


class TestGoldenPackets:
    def test_prepare_response_framing(self, seeded):
        srv_, _ = seeded
        c, pkt = _raw_conn(srv_)
        c.query("use app")
        # COM_STMT_PREPARE "select b from t where a = ?"
        # spec: 1 byte command 0x16 + query text
        pkt.reset_sequence()
        pkt.write_packet(b"\x16select b from t where a = ?")
        head = pkt.read_packet()
        # spec: [00][stmt_id u32][num_columns u16][num_params u16]
        #       [filler 00][warning_count u16]
        assert head[0] == 0x00
        assert len(head) == 12
        stmt_id = struct.unpack_from("<I", head, 1)[0]
        n_cols, n_params = struct.unpack_from("<HH", head, 5)
        assert n_params == 1
        assert head[9] == 0x00
        # one param definition packet + EOF follows (n_cols==0 → no
        # column block)
        pdef = pkt.read_packet()
        assert pdef[:4] == b"\x03def"
        eof = pkt.read_packet()
        assert eof[0] == 0xFE and len(eof) == 5
        if n_cols:
            for _ in range(n_cols):
                pkt.read_packet()
            pkt.read_packet()

        # COM_STMT_EXECUTE, spec layout:
        # [17][stmt_id u32][flags=00][iteration=1 u32]
        # [null bitmap 1 byte][new_params_bound=01]
        # [param type: 08 00 (LONGLONG)][value: 8 bytes LE]
        body = (b"\x17" + struct.pack("<I", stmt_id) + b"\x00"
                + struct.pack("<I", 1) + b"\x00" + b"\x01"
                + b"\x08\x00" + struct.pack("<q", 2))
        pkt.reset_sequence()
        pkt.write_packet(body)
        # response: column count 1
        assert pkt.read_packet() == b"\x01"
        cdef = pkt.read_packet()
        assert cdef[:4] == b"\x03def"
        assert pkt.read_packet()[0] == 0xFE       # EOF after columns
        row = pkt.read_packet()
        # spec binary row: [00 header][null bitmap (1+7+2)//8 = 1 byte]
        # [lenenc 'y'] — column b of row a=2 is 'y'
        assert row == b"\x00\x00\x01y"
        assert pkt.read_packet()[0] == 0xFE       # trailing EOF
        c.close()

    def test_execute_null_param_golden(self, seeded):
        srv_, _ = seeded
        c, pkt = _raw_conn(srv_)
        c.query("use app")
        pkt.reset_sequence()
        pkt.write_packet(b"\x16select ?")
        head = pkt.read_packet()
        stmt_id = struct.unpack_from("<I", head, 1)[0]
        pkt.read_packet()    # param def
        pkt.read_packet()    # EOF
        # NULL param: bitmap bit 0 set, type NULL (06 00), no value bytes
        body = (b"\x17" + struct.pack("<I", stmt_id) + b"\x00"
                + struct.pack("<I", 1) + b"\x01" + b"\x01" + b"\x06\x00")
        pkt.reset_sequence()
        pkt.write_packet(body)
        assert pkt.read_packet() == b"\x01"
        pkt.read_packet()
        assert pkt.read_packet()[0] == 0xFE
        row = pkt.read_packet()
        # NULL result: header 00, bitmap bit (0+2) set → 0x04, no value
        assert row == b"\x00\x04"
        c.close()

    def test_stmt_close_sends_no_response_and_ping_works(self, seeded):
        srv_, _ = seeded
        c, pkt = _raw_conn(srv_)
        pkt.reset_sequence()
        pkt.write_packet(b"\x16select 1")
        head = pkt.read_packet()
        stmt_id = struct.unpack_from("<I", head, 1)[0]
        # COM_STMT_CLOSE: [19][stmt_id u32]; spec: NO response packet
        pkt.reset_sequence()
        pkt.write_packet(b"\x19" + struct.pack("<I", stmt_id))
        # the very next command must be answered immediately — if the
        # server wrongly responded to CLOSE, this read would see that
        # stray packet instead of the PING OK
        pkt.reset_sequence()
        pkt.write_packet(b"\x0e")          # COM_PING
        ok = pkt.read_packet()
        assert ok[0] == 0x00
        c.close()

    def test_stmt_reset_returns_ok(self, seeded):
        srv_, _ = seeded
        c, pkt = _raw_conn(srv_)
        pkt.reset_sequence()
        pkt.write_packet(b"\x16select ?")
        head = pkt.read_packet()
        stmt_id = struct.unpack_from("<I", head, 1)[0]
        pkt.read_packet()
        pkt.read_packet()
        pkt.reset_sequence()
        pkt.write_packet(b"\x1a" + struct.pack("<I", stmt_id))
        assert pkt.read_packet()[0] == 0x00
        c.close()

    def test_binary_longlong_and_double_row_golden(self, seeded):
        srv_, _ = seeded
        c, pkt = _raw_conn(srv_)
        c.query("use app")
        pkt.reset_sequence()
        pkt.write_packet(b"\x16select a, c from t where a = 1")
        head = pkt.read_packet()
        stmt_id = struct.unpack_from("<I", head, 1)[0]
        body = (b"\x17" + struct.pack("<I", stmt_id) + b"\x00"
                + struct.pack("<I", 1))
        pkt.reset_sequence()
        pkt.write_packet(body)
        assert pkt.read_packet() == b"\x02"
        pkt.read_packet()
        pkt.read_packet()
        assert pkt.read_packet()[0] == 0xFE
        row = pkt.read_packet()
        # [00][bitmap 1B=00][a: i64 1 LE][c: f64 1.5 LE]
        assert row == (b"\x00\x00" + struct.pack("<q", 1)
                       + struct.pack("<d", 1.5))
        c.close()
