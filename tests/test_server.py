"""MySQL wire protocol server tests.

Conformance is checked with the in-repo client (tidb_tpu/server/client.py)
— the analogue of the reference's go-sql-driver-based server tests
(server/server_test.go).
"""

import socket
import threading

import pytest

from tidb_tpu.server import Client, MySQLError, Server
from tidb_tpu.server import protocol as p
from tidb_tpu.server.packetio import PacketIO
from tidb_tpu.session import Session, new_store
from tests.testkit import _store_id  # reuse unique store naming


@pytest.fixture
def srv():
    store = new_store(f"memory://srv{next(_store_id)}")
    server = Server(store)
    server.start()
    yield server
    server.close()


def connect(server, **kw) -> Client:
    return Client("127.0.0.1", server.port, **kw)


class TestHandshake:
    def test_root_empty_password(self, srv):
        c = connect(srv)
        assert c.server_version.startswith("5.7")
        c.ping()
        c.close()

    def test_unknown_user_rejected(self, srv):
        with pytest.raises(MySQLError) as ei:
            connect(srv, user="nobody")
        assert ei.value.code == 1045

    def test_password_auth_round_trip(self, srv):
        h = p.password_hash("s3cret")
        s = Session(srv.store)
        s.execute("insert into mysql.user (Host, User, Password) "
                  f"values ('%', 'alice', '{h}')")
        c = connect(srv, user="alice", password="s3cret")
        c.ping()
        c.close()
        with pytest.raises(MySQLError):
            connect(srv, user="alice", password="wrong")
        with pytest.raises(MySQLError):
            connect(srv, user="alice", password="")

    def test_connect_with_db(self, srv):
        c = connect(srv)
        c.query("create database hsdb")
        c.close()
        c2 = connect(srv, db="hsdb")
        c2.query("create table t (a int)")
        c2.query("insert into t values (1)")
        assert c2.query("select * from t")[0].rows == [["1"]]
        c2.close()

    def test_connect_with_bad_db(self, srv):
        with pytest.raises(MySQLError):
            connect(srv, db="no_such_db")


class TestQuery:
    def test_resultset_types_and_null(self, srv):
        c = connect(srv)
        c.query("create database d; use d")
        c.query("create table t (a int primary key, b varchar(20), "
                "c double, d decimal(10,2))")
        r = c.query("insert into t values (1,'x',1.5,'3.75'), "
                    "(2,null,null,null)")[0]
        assert r.affected == 2
        r = c.query("select * from t order by a")[0]
        assert r.columns == ["a", "b", "c", "d"]
        assert r.rows[0] == ["1", "x", "1.5", "3.75"]
        assert r.rows[1] == ["2", None, None, None]
        c.close()

    def test_multi_statement_multi_resultset(self, srv):
        c = connect(srv)
        rs = c.query("select 1; select 'two'; select 3")
        assert [x.rows for x in rs] == [[["1"]], [["two"]], [["3"]]]
        c.close()

    def test_multi_statement_per_statement_framing(self, srv):
        """Effect statements get their own OK (with affected rows) even
        mid-sequence — drivers attribute results positionally."""
        c = connect(srv)
        c.query("create database dm; use dm; create table t (a int)")
        rs = c.query("insert into t values (1), (2); select 99; "
                     "insert into t values (3)")
        assert len(rs) == 3
        assert rs[0].rows is None and rs[0].affected == 2
        assert rs[1].rows == [["99"]]
        assert rs[2].rows is None and rs[2].affected == 1
        c.close()

    def test_empty_query_gets_err_packet(self, srv):
        c = connect(srv)
        for q in ("", ";", "-- just a comment"):
            with pytest.raises(MySQLError) as ei:
                c.query(q)
            assert ei.value.code == 1065
        assert c.query("select 1")[0].rows == [["1"]]
        c.close()

    def test_hostile_usernames_rejected_cleanly(self, srv):
        for user in ("evil\\", "ro'ot", "a' or '1'='1"):
            with pytest.raises(MySQLError) as ei:
                connect(srv, user=user)
            assert ei.value.code in (1045, 1105)

    def test_error_keeps_connection_alive(self, srv):
        c = connect(srv)
        with pytest.raises(MySQLError) as ei:
            c.query("select * from missing.t")
        assert ei.value.code != 0
        assert c.query("select 42")[0].rows == [["42"]]
        c.close()

    def test_init_db_command(self, srv):
        c = connect(srv)
        c.query("create database d2")
        c.pkt.reset_sequence()
        c.pkt.write_packet(bytes((p.COM_INIT_DB,)) + b"d2")
        assert c.pkt.read_packet()[0] == 0x00
        c.query("create table t (a int)")
        assert c.query("select count(1) from t")[0].rows == [["0"]]
        c.close()

    def test_txn_rolls_back_on_disconnect(self, srv):
        c = connect(srv)
        c.query("create database d3; use d3; create table t (a int)")
        c.query("begin")
        c.query("insert into t values (1)")
        c.close()
        c2 = connect(srv, db="d3")
        assert c2.query("select count(1) from t")[0].rows == [["0"]]
        c2.close()

    def test_driver_handshake_queries(self, srv):
        """The statements real MySQL drivers issue right after connecting
        must all succeed — and version()/@@version/handshake must agree."""
        from tidb_tpu import mysqldef as my
        c = connect(srv)
        assert c.query("select @@version_comment")[0].rows
        assert c.query("select @@version")[0].rows == \
            [[my.SERVER_VERSION]]
        assert c.query("select version()")[0].rows == \
            [[my.SERVER_VERSION]]
        assert c.server_version == my.SERVER_VERSION
        for q in ("set names utf8", "set names 'utf8mb4'",
                  "set character set utf8", "flush privileges",
                  "flush tables"):
            c.query(q)
        with pytest.raises(MySQLError):
            c.query("flush privleges")  # typo must not silently succeed
        c.close()

    def test_flush_privileges_reloads_grants(self, srv):
        """Only a FLUSH may surface a grant-table row edited BEHIND the
        executors (GRANT itself already invalidates)."""
        c = connect(srv)
        c.query("create database fp; use fp; create table t (a int)")
        c.query("create user 'fp1' identified by 'x'")
        u = Client("127.0.0.1", srv.port, user="fp1", password="x", db="fp")
        with pytest.raises(MySQLError):
            u.query("select count(*) from t")  # no grant yet
        # edit the grant table directly: checker cache must NOT see it
        c.query("insert into mysql.db (Host, DB, User, Select_priv) "
                "values ('%', 'fp', 'fp1', 'Y')")
        with pytest.raises(MySQLError):
            u.query("select count(*) from t")
        c.query("flush privileges")
        assert u.query("select count(*) from t")[0].rows == [["0"]]
        u.close()
        c.close()

    def test_prepared_statements_text_protocol(self, srv):
        c = connect(srv)
        c.query("create database d4; use d4; create table t (a int)")
        c.query("insert into t values (1), (2), (3)")
        c.query("prepare p from 'select a from t where a > ?'")
        c.query("set @x = 1")
        assert c.query("execute p using @x")[0].rows == [["2"], ["3"]]
        c.close()


class TestServerLimits:
    def test_token_limit(self):
        store = new_store(f"memory://srvlim{next(_store_id)}")
        server = Server(store, token_limit=1)
        server.start()
        try:
            c1 = connect(server)
            # second connection is closed before handshake
            with pytest.raises(Exception):
                connect(server, timeout=2.0)
            c1.close()
        finally:
            server.close()


class TestPacketIO:
    def test_large_packet_split_round_trip(self):
        a, b = socket.socketpair()
        pa, pb = PacketIO(a), PacketIO(b)
        payload = bytes(range(256)) * 70000  # ~17.9MB > 0xffffff
        got = {}
        t = threading.Thread(target=lambda: got.setdefault(
            "data", pb.read_packet()))
        t.start()
        pa.write_packet(payload)
        t.join(timeout=30)
        assert got["data"] == payload
        a.close()
        b.close()

    def test_exact_boundary_payload(self):
        a, b = socket.socketpair()
        pa, pb = PacketIO(a), PacketIO(b)
        payload = b"x" * 0xFFFFFF  # exact multiple → empty trailer packet
        got = {}
        t = threading.Thread(target=lambda: got.setdefault(
            "data", pb.read_packet()))
        t.start()
        pa.write_packet(payload)
        t.join(timeout=30)
        assert got["data"] == payload
        a.close()
        b.close()


class TestAuthPrimitives:
    def test_scramble_round_trip(self):
        salt = p.new_salt()
        token = p.scramble_password("hunter2", salt)
        assert p.check_auth(token, p.password_hash("hunter2"), salt)
        assert not p.check_auth(token, p.password_hash("other"), salt)
        assert not p.check_auth(b"", p.password_hash("hunter2"), salt)
        assert p.check_auth(b"", "", salt)

    def test_lenenc_int_round_trip(self):
        for n in (0, 250, 251, 65535, 65536, 1 << 23, 1 << 24, 1 << 60):
            enc = p.lenenc_int(n)
            dec, pos = p.read_lenenc_int(enc, 0)
            assert dec == n and pos == len(enc)


class TestAdmissionGate:
    """max_connections + bounded admission queue + typed ER 1040
    rejection (the heavy-traffic tier's front door)."""

    def test_too_many_connections_typed_1040(self):
        store = new_store(f"memory://srvadm{next(_store_id)}")
        root = Session(store)
        root.execute("set global max_connections = 2")
        root.execute("set global tidb_tpu_conn_queue_depth = 0")
        server = Server(store)
        server.start()
        try:
            c1 = connect(server)
            c2 = connect(server)
            with pytest.raises(MySQLError) as ei:
                connect(server)
            assert ei.value.code == 1040
            assert "Too many connections" in str(ei.value)
            # a freed slot admits the next connection (typed rejection is
            # overload shedding, not a ban). The worker releases its slot
            # asynchronously after the close, so poll.
            c1.close()
            c3 = None
            for _ in range(200):
                try:
                    c3 = connect(server)
                    break
                except MySQLError:
                    import time
                    time.sleep(0.02)
            assert c3 is not None, "freed slot never admitted a connection"
            c3.ping()
            c3.close()
            c2.close()
        finally:
            server.close()

    def test_admission_queue_serves_when_worker_frees(self):
        store = new_store(f"memory://srvadm{next(_store_id)}")
        root = Session(store)
        root.execute("set global max_connections = 1")
        root.execute("set global tidb_tpu_conn_queue_depth = 4")
        server = Server(store)
        server.start()
        try:
            c1 = connect(server)
            # second connection queues (no worker yet): handshake blocks
            # until c1 closes, so connect() must be concurrent
            got = {}

            def waiter():
                try:
                    c = connect(server, timeout=10)
                    c.ping()
                    got["ok"] = True
                    c.close()
                except Exception as e:   # surfaces via assert below
                    got["err"] = e

            t = threading.Thread(target=waiter)
            t.start()
            t.join(timeout=1)
            assert t.is_alive(), "queued connection was served early"
            c1.close()
            t.join(timeout=10)
            assert got.get("ok"), f"queued connection failed: {got.get('err')}"
        finally:
            server.close()

    def test_conn_queue_wait_deadline_typed_1040(self):
        """ROADMAP concurrency residual (f): a connection queued behind
        the admission gate dies TYPED (ER 1040) after
        tidb_tpu_conn_queue_timeout_ms instead of waiting forever on the
        client's own connect timeout, counted on
        server.conn_queue_timeouts — and the deadline sheds only the
        queued socket, never the served connection."""
        import time

        from tidb_tpu import metrics
        store = new_store(f"memory://srvadm{next(_store_id)}")
        root = Session(store)
        root.execute("set global max_connections = 1")
        root.execute("set global tidb_tpu_conn_queue_depth = 4")
        root.execute("set global tidb_tpu_conn_queue_timeout_ms = 200")
        server = Server(store)
        server.start()
        try:
            c1 = connect(server)        # occupies the only worker
            n0 = metrics.counter("server.conn_queue_timeouts").value
            t0 = time.time()
            with pytest.raises(MySQLError) as ei:
                # queues (depth 4 > 0), then the sweeper rejects typed —
                # WELL before the client's own 10 s timeout
                connect(server, timeout=10)
            elapsed = time.time() - t0
            assert ei.value.code == 1040
            assert "Too many connections" in str(ei.value)
            assert 0.15 <= elapsed < 5, \
                f"queue deadline fired at {elapsed:.2f}s, not ~0.2s"
            assert metrics.counter(
                "server.conn_queue_timeouts").value == n0 + 1
            # the served connection is untouched, and a freed worker
            # still admits fresh connections afterwards
            c1.ping()
            c1.close()
            c2 = None
            for _ in range(200):
                try:
                    c2 = connect(server)
                    break
                except MySQLError:
                    time.sleep(0.02)
            assert c2 is not None
            c2.ping()
            c2.close()
        finally:
            server.close()

    def test_conn_queue_timeout_applies_to_already_queued_sockets(self):
        """SET GLOBAL tidb_tpu_conn_queue_timeout_ms while sockets are
        ALREADY queued still sheds them: the sweeper runs whenever the
        queue is non-empty and reads the sysvar live — enabling the
        deadline mid-backlog must not strand the waiting sockets."""
        import time

        store = new_store(f"memory://srvadm{next(_store_id)}")
        root = Session(store)
        root.execute("set global max_connections = 1")
        root.execute("set global tidb_tpu_conn_queue_depth = 4")
        root.execute("set global tidb_tpu_conn_queue_timeout_ms = 0")
        server = Server(store)
        server.start()
        try:
            c1 = connect(server)        # occupies the only worker
            got = {}

            def waiter():
                try:
                    connect(server, timeout=10)
                    got["ok"] = True
                except Exception as e:
                    got["err"] = e

            t = threading.Thread(target=waiter)
            t.start()
            t.join(timeout=0.4)
            assert t.is_alive(), "socket was not queued"
            # enable the deadline AFTER the socket queued; it has
            # already waited > 100 ms, so the sweeper sheds it promptly
            root.execute(
                "set global tidb_tpu_conn_queue_timeout_ms = 100")
            t.join(timeout=5)
            assert not t.is_alive(), \
                "mid-backlog deadline never shed the queued socket"
            err = got.get("err")
            assert err is not None and getattr(err, "code", None) == 1040, \
                f"expected typed ER 1040, got {got}"
            c1.ping()
            c1.close()
        finally:
            server.close()

    def test_conn_queue_timeout_zero_waits(self):
        """tidb_tpu_conn_queue_timeout_ms = 0 restores wait-forever: the
        queued connection is served when the worker frees, never
        deadline-rejected."""
        store = new_store(f"memory://srvadm{next(_store_id)}")
        root = Session(store)
        root.execute("set global max_connections = 1")
        root.execute("set global tidb_tpu_conn_queue_depth = 4")
        root.execute("set global tidb_tpu_conn_queue_timeout_ms = 0")
        server = Server(store)
        server.start()
        try:
            c1 = connect(server)
            got = {}

            def waiter():
                try:
                    c = connect(server, timeout=10)
                    c.ping()
                    got["ok"] = True
                    c.close()
                except Exception as e:   # surfaces via assert below
                    got["err"] = e

            t = threading.Thread(target=waiter)
            t.start()
            t.join(timeout=0.6)   # > a would-be small deadline window
            assert t.is_alive(), "queued connection was served early"
            c1.close()
            t.join(timeout=10)
            assert got.get("ok"), \
                f"queued connection failed: {got.get('err')}"
        finally:
            server.close()

    def test_bounded_workers_reused_across_churn(self):
        store = new_store(f"memory://srvadm{next(_store_id)}")
        server = Server(store)
        server.start()
        try:
            before = threading.active_count()
            for _ in range(10):
                c = connect(server)
                c.ping()
                c.close()
            # worker threads are reused/retired, never one-per-connection
            # accumulation
            assert threading.active_count() <= before + 2
        finally:
            server.close()
