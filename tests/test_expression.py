"""Expression system tests.

Modeled on the reference's evaluator tests (evaluator/evaluator_test.go,
builtin_*_test.go) and expression/aggregation tests — table-driven over the
scalar compute core, builtins, and aggregate partial/final merging.
"""

from decimal import Decimal

import pytest

from tidb_tpu import errors
from tidb_tpu.expression import (
    AggFunctionMode, AggregationFunction, Column, Constant, ScalarFunction,
    new_op, ops as xops,
)
from tidb_tpu.sqlast.opcode import Op
from tidb_tpu.types import Datum, datum_from_py
from tidb_tpu.types.datum import NULL


def d(v):
    return datum_from_py(v)


def const(v):
    return Constant(d(v))


def fn(name, *args):
    return ScalarFunction(name, [const(a) if not hasattr(a, "eval") else a
                                 for a in args])


def ev(e):
    return e.eval([])


class TestScalarOps:
    @pytest.mark.parametrize("op,a,b,want", [
        (Op.Plus, 1, 2, 3),
        (Op.Plus, 1.5, 2, 3.5),
        (Op.Minus, 5, 7, -2),
        (Op.Mul, 3, 4, 12),
        (Op.Div, 3, 2, Decimal("1.5")),
        (Op.Div, 3.0, 2, 1.5),
        (Op.Div, 1, 0, None),
        (Op.IntDiv, 7, 2, 3),
        (Op.IntDiv, -7, 2, -3),     # truncation toward zero
        (Op.Mod, 7, 3, 1),
        (Op.Mod, -7, 3, -1),        # sign of dividend
        (Op.Mod, 7, 0, None),
    ])
    def test_arith(self, op, a, b, want):
        got = xops.compute_arith(op, d(a), d(b))
        if want is None:
            assert got.is_null()
        else:
            assert got.val == want

    @pytest.mark.parametrize("op,a,b,want", [
        (Op.EQ, 1, 1, 1), (Op.EQ, 1, 2, 0),
        (Op.EQ, "12", 12, 1),       # string-number coercion
        (Op.NE, 1, 2, 1),
        (Op.LT, 1, 2, 1), (Op.LE, 2, 2, 1),
        (Op.GT, 3, 2, 1), (Op.GE, 2, 3, 0),
        (Op.EQ, "abc", "ABC", 0),   # binary collation
    ])
    def test_compare(self, op, a, b, want):
        assert xops.compute_compare(op, d(a), d(b)).val == want

    def test_compare_null(self):
        assert xops.compute_compare(Op.EQ, NULL, d(1)).is_null()
        assert xops.compute_compare(Op.NullEQ, NULL, NULL).val == 1
        assert xops.compute_compare(Op.NullEQ, NULL, d(1)).val == 0

    def test_three_valued_logic(self):
        T, F, N = d(1), d(0), NULL
        assert xops.compute_logic(Op.AndAnd, F, N).val == 0
        assert xops.compute_logic(Op.AndAnd, T, N).is_null()
        assert xops.compute_logic(Op.OrOr, T, N).val == 1
        assert xops.compute_logic(Op.OrOr, F, N).is_null()
        assert xops.compute_logic(Op.Xor, T, N).is_null()

    def test_bit_ops(self):
        assert xops.compute_bit(Op.BitAnd, d(6), d(3)).val == 2
        assert xops.compute_bit(Op.BitOr, d(6), d(3)).val == 7
        assert xops.compute_bit(Op.BitXor, d(6), d(3)).val == 5
        assert xops.compute_bit(Op.LeftShift, d(1), d(3)).val == 8
        assert xops.compute_bit(Op.RightShift, d(8), d(3)).val == 1
        # MySQL bit ops are uint64: -1 & anything
        assert xops.compute_bit(Op.BitAnd, d(-1), d(7)).val == 7

    def test_unary(self):
        assert xops.compute_unary(Op.UnaryMinus, d(5)).val == -5
        assert xops.compute_unary(Op.UnaryNot, d(0)).val == 1
        assert xops.compute_unary(Op.UnaryNot, d(3)).val == 0
        assert xops.compute_unary(Op.UnaryNot, NULL).is_null()
        assert xops.compute_unary(Op.BitNeg, d(0)).val == (1 << 64) - 1

    def test_overflow(self):
        with pytest.raises(errors.OverflowError_):
            xops.compute_arith(Op.Plus, d((1 << 63) - 1), d(1))

    def test_like(self):
        assert xops.compute_like(d("abc"), d("a%")).val == 1
        assert xops.compute_like(d("abc"), d("_bc")).val == 1
        assert xops.compute_like(d("abc"), d("b%")).val == 0
        assert xops.compute_like(d("ABC"), d("abc")).val == 1  # ci
        assert xops.compute_like(d("a%c"), d(r"a\%c")).val == 1
        assert xops.compute_like(NULL, d("x")).is_null()
        assert xops.compute_like(d("abc"), d("b%"), negated=True).val == 1

    def test_in(self):
        assert xops.compute_in(d(2), [d(1), d(2)]).val == 1
        assert xops.compute_in(d(3), [d(1), d(2)]).val == 0
        assert xops.compute_in(d(3), [d(1), NULL]).is_null()
        assert xops.compute_in(d(1), [d(1), NULL]).val == 1
        assert xops.compute_in(NULL, [d(1)]).is_null()
        assert xops.compute_in(d(3), [d(1), d(2)], negated=True).val == 1


class TestScalarFunction:
    def test_op_expr_and_shortcircuit(self):
        e = new_op(Op.Plus, const(1), const(2))
        assert ev(e).val == 3
        # OR short-circuits: right side would raise (unknown column offset)
        bad = Column(col_name="x")
        e = new_op(Op.OrOr, const(1), bad)
        assert ev(e).val == 1

    def test_control_funcs(self):
        assert ev(fn("if", 1, "a", "b")).val == "a"
        assert ev(fn("if", 0, "a", "b")).val == "b"
        assert ev(fn("ifnull", NULL_D(), 5)).val == 5
        assert ev(fn("nullif", 1, 1)).is_null()
        assert ev(fn("coalesce", NULL_D(), NULL_D(), 7)).val == 7
        assert ev(fn("isnull", NULL_D())).val == 1

    def test_string_funcs(self):
        assert ev(fn("concat", "a", 1, "b")).val == "a1b"
        assert ev(fn("concat", "a", NULL_D())).is_null()
        assert ev(fn("lower", "AbC")).val == "abc"
        assert ev(fn("substring", "hello", 2)).val == "ello"
        assert ev(fn("substring", "hello", 2, 2)).val == "el"
        assert ev(fn("substring", "hello", -3, 2)).val == "ll"
        assert ev(fn("left", "hello", 2)).val == "he"
        assert ev(fn("replace", "aaa", "a", "b")).val == "bbb"
        assert ev(fn("locate", "ll", "hello")).val == 3
        assert ev(fn("length", "héllo")).val == 6   # bytes
        assert ev(fn("char_length", "héllo")).val == 5
        assert ev(fn("lpad", "5", 3, "0")).val == "005"

    def test_math_funcs(self):
        assert ev(fn("abs", -3)).val == 3
        assert ev(fn("floor", 1.7)).val == 1
        assert ev(fn("ceil", 1.2)).val == 2
        assert ev(fn("round", 2.5)).val == 3.0      # half away from zero
        assert ev(fn("round", 1.234, 2)).val == 1.23
        assert ev(fn("pow", 2, 10)).val == 1024.0
        assert ev(fn("sign", -9)).val == -1
        assert ev(fn("greatest", 1, 9, 3)).val == 9
        assert ev(fn("least", 4, 2, 8)).val == 2

    def test_case(self):
        # searched case: when,then,when,then,else
        e = fn("case", 0, "a", 1, "b", "c")
        assert ev(e).val == "b"
        e = fn("case", 0, "a", 0, "b", "c")
        assert ev(e).val == "c"

    def test_column_eval(self):
        c = Column(col_name="x", index=1)
        assert c.eval([d(10), d(20)]).val == 20


def NULL_D():
    return Constant(NULL)


class TestAggregation:
    def _run(self, agg, rows):
        ctx = agg.create_context()
        for r in rows:
            agg.update(ctx, r)
        return agg.get_result(ctx)

    def test_count_sum_avg(self):
        col = Column(index=0)
        rows = [[d(1)], [d(2)], [NULL], [d(3)]]
        assert self._run(AggregationFunction("count", [col]), rows).val == 3
        s = self._run(AggregationFunction("sum", [col]), rows)
        assert s.val == Decimal(6)  # int sum → decimal exactness
        a = self._run(AggregationFunction("avg", [col]), rows)
        assert a.val == Decimal(2)

    def test_min_max_first(self):
        col = Column(index=0)
        rows = [[d(5)], [d(1)], [NULL], [d(9)]]
        assert self._run(AggregationFunction("max", [col]), rows).val == 9
        assert self._run(AggregationFunction("min", [col]), rows).val == 1
        assert self._run(AggregationFunction("first_row", [col]), rows).val == 5

    def test_distinct(self):
        col = Column(index=0)
        rows = [[d(1)], [d(1)], [d(2)], [NULL]]
        assert self._run(AggregationFunction("count", [col], distinct=True),
                         rows).val == 2
        assert self._run(AggregationFunction("sum", [col], distinct=True),
                         rows).val == Decimal(3)

    def test_group_concat(self):
        col = Column(index=0)
        rows = [[d("a")], [d("b")], [NULL]]
        assert self._run(AggregationFunction("group_concat", [col]),
                         rows).val == "a,b"

    def test_empty_group_results(self):
        col = Column(index=0)
        assert self._run(AggregationFunction("count", [col]), []).val == 0
        assert self._run(AggregationFunction("sum", [col]), []).is_null()
        assert self._run(AggregationFunction("avg", [col]), []).is_null()
        assert self._run(AggregationFunction("max", [col]), []).is_null()

    def test_partial_final_roundtrip(self):
        """Partial rows from two 'regions' merge to the complete answer —
        the invariant the TPU psum combine relies on."""
        col = Column(index=0)
        region_rows = [[[d(1)], [d(2)]], [[d(3)], [NULL], [d(4)]]]
        for name, want in [("count", 4), ("sum", Decimal(10)),
                           ("avg", Decimal("2.5")), ("max", 4), ("min", 1)]:
            partial = AggregationFunction(name, [col])
            partial_rows = []
            for rows in region_rows:
                ctx = partial.create_context()
                for r in rows:
                    partial.update(ctx, r)
                partial_rows.append(partial.get_partial_result(ctx))
            width = len(partial_rows[0])
            final_args = [Column(index=i) for i in range(width)]
            final = AggregationFunction(name, final_args,
                                        mode=AggFunctionMode.FINAL)
            fctx = final.create_context()
            for pr in partial_rows:
                final.update(fctx, pr)
            got = final.get_result(fctx)
            assert got.val == want, name


class TestRound4Builtins:
    """Round-4 breadth: the remaining evaluator/builtin.go registry rows
    (time formatting, name lookups, regexp, utility no-ops)."""

    def g(self, name, *args):
        return ev(fn(name, *args)).val

    def gs(self, name, *args):
        v = ev(fn(name, *args))
        return v.get_string() if not v.is_null() else None

    def test_dayname_monthname(self):
        assert self.gs("dayname", "2026-07-30") == "Thursday"
        assert self.gs("monthname", "2026-07-30") == "July"
        assert ev(fn("dayname", "not-a-date")).is_null()

    def test_weekofyear_yearweek(self):
        assert self.g("weekofyear", "2026-01-01") == 1
        assert self.g("weekofyear", "2024-12-30") == 1   # ISO rollover
        assert self.g("yearweek", "2026-07-30") == 202630
        assert self.g("yearweek", "2026-07-30", 1) == 202631

    def test_date_format(self):
        assert self.gs("date_format", "2026-07-30 15:04:05",
                       "%Y-%m-%d %H:%i:%s") == "2026-07-30 15:04:05"
        assert self.gs("date_format", "2026-07-30", "%W %M %D") == \
            "Thursday July 30th"
        assert self.gs("date_format", "2026-07-30 15:04:05", "%r") == \
            "03:04:05 PM"
        assert self.gs("date_format", "2026-07-30", "%% %q") == "% q"

    def test_from_unixtime(self):
        import datetime as dt
        got = ev(fn("from_unixtime", 0))
        assert got.val.dt == dt.datetime.fromtimestamp(0)
        assert self.gs("from_unixtime", 86400 * 365, "%Y") == \
            dt.datetime.fromtimestamp(86400 * 365).strftime("%Y")
        assert ev(fn("from_unixtime", -5)).is_null()

    def test_substring_index(self):
        assert self.gs("substring_index", "www.mysql.com", ".", 2) == \
            "www.mysql"
        assert self.gs("substring_index", "www.mysql.com", ".", -2) == \
            "mysql.com"
        assert self.gs("substring_index", "www.mysql.com", ".", 0) == ""
        assert self.gs("substring_index", "a,b", ";", 5) == "a,b"

    def test_time_and_curtime(self):
        from tidb_tpu.types.time_types import Duration
        v = ev(fn("time", "2026-07-30 15:04:05"))
        assert isinstance(v.val, Duration) and str(v.val) == "15:04:05"
        v = ev(fn("time", "12:30:00"))
        assert str(v.val).startswith("12:30:00")
        assert isinstance(ev(fn("curtime")).val, Duration)
        assert ev(fn("utc_date")).val.tp is not None

    def test_now_and_curtime_fsp(self):
        """CURTIME(n)/NOW(n) honor the fractional precision argument
        (round-4 advice: the fsp arg was accepted but ignored)."""
        from tidb_tpu.types.time_types import Duration
        v = ev(fn("curtime", 3)).val
        assert isinstance(v, Duration) and v.fsp == 3
        assert v.nanos % 1_000_000 == 0          # truncated to millis
        t0 = ev(fn("curtime", 0)).val
        assert t0.fsp == 0 and t0.nanos % 1_000_000_000 == 0
        n6 = ev(fn("now", 6)).val
        assert n6.fsp == 6
        n0 = ev(fn("now")).val
        assert n0.fsp == 0 and n0.dt.microsecond == 0
        with pytest.raises(errors.TiDBError):
            ev(fn("curtime", 7))

    def test_regexp(self):
        assert self.g("regexp", "abcdef", "c.e") == 1
        assert self.g("regexp", "abcdef", "^c") == 0
        assert self.g("not_regexp", "abcdef", "^c") == 1
        assert ev(fn("regexp", "x", None)).is_null()
        with pytest.raises(errors.TiDBError):
            self.g("regexp", "x", "(")

    def test_utility_no_ops(self):
        assert self.g("get_lock", "name", 3) == 1
        assert self.g("release_lock", "name") == 1
        assert self.g("sleep", 0) == 0


def test_regexp_parses_end_to_end():
    from tidb_tpu.session import Session, new_store
    s = Session(new_store("memory://rx"))
    s.execute("create database d; use d")
    s.execute("create table t (a int primary key, b varchar(20))")
    s.execute("insert into t values (1, 'hello'), (2, 'world'), (3, null)")
    assert s.execute("select a from t where b regexp '^h' order by a")[0] \
        .values() == [[1]]
    assert s.execute("select a from t where b rlike 'o' order by a")[0] \
        .values() == [[1], [2]]
    assert s.execute("select a from t where b not regexp 'o' order by a")[0] \
        .values() == []   # NULL row filtered too
