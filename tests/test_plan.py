"""Planner tests: plan shapes, pushdown decisions, pruning, ranges.

Mirrors plan/logical_plan_test.go and plan/physical_plan_test.go (golden
plan-string checks) at smaller scale.
"""

import pytest

from tidb_tpu import mysqldef as my
from tidb_tpu.ddl.ddl import ColumnSpec, IndexSpec
from tidb_tpu.domain import Domain, clear_domains
from tidb_tpu.localstore import LocalStore
from tidb_tpu.parser.parser import Parser
from tidb_tpu.plan import optimize, tree_string
from tidb_tpu.plan.plans import (
    PhysicalHashAgg, PhysicalHashJoin, PhysicalIndexScan, PhysicalLimit,
    PhysicalProjection, PhysicalSelection, PhysicalTableScan, PhysicalTopN,
)
from tidb_tpu.plan.refiner import TableRange
from tidb_tpu.types.field_type import FieldType


def _ft(tp, flag=0, flen=-1, dec=-1):
    return FieldType(tp, flag, flen, dec)


class Ctx:
    def __init__(self, dom, db="test"):
        self.dom = dom
        self.current_db = db
        self.params = []

    def info_schema(self):
        return self.dom.info_schema()

    def get_sysvar(self, name, is_global):
        return None


@pytest.fixture
def env():
    clear_domains()
    store = LocalStore()
    dom = Domain(store)
    dom.ddl.create_schema("test")
    dom.ddl.create_table("test", "t", [
        ColumnSpec("id", _ft(my.TypeLonglong)),
        ColumnSpec("a", _ft(my.TypeLong)),
        ColumnSpec("b", _ft(my.TypeVarchar, flen=64)),
        ColumnSpec("c", _ft(my.TypeDouble)),
    ], [IndexSpec("primary", ["id"], primary=True),
        IndexSpec("idx_b", ["b"])])
    dom.ddl.create_table("test", "s", [
        ColumnSpec("id", _ft(my.TypeLonglong)),
        ColumnSpec("t_id", _ft(my.TypeLonglong)),
        ColumnSpec("v", _ft(my.TypeVarchar, flen=64)),
    ], [IndexSpec("primary", ["id"], primary=True)])
    ctx = Ctx(dom)
    client = store.get_client()
    return ctx, client


def plan_for(ctx, client, sql):
    stmt = Parser().parse_one(sql)
    return optimize(stmt, ctx, client)


def find_node(p, tp):
    if isinstance(p, tp):
        return p
    for c in p.children:
        r = find_node(c, tp)
        if r is not None:
            return r
    return None


class TestPushdown:
    def test_filter_fully_pushed(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select a from t where a > 5")
        scan = find_node(p, PhysicalTableScan)
        assert scan is not None
        assert scan.pushed_where is not None
        assert not scan.conditions
        # no SQL-side selection remains
        assert find_node(p, PhysicalSelection) is None

    def test_agg_pushdown_rewrites_final(self, env):
        ctx, client = env
        p = plan_for(ctx, client,
                     "select b, sum(c), count(*) from t group by b")
        scan = find_node(p, PhysicalTableScan)
        agg = find_node(p, PhysicalHashAgg)
        assert scan.aggregated_push_down
        assert len(scan.aggregates) >= 2
        assert scan.group_by_pb
        assert agg.has_pushed_child
        # final agg funcs run in FINAL mode over the partial layout
        from tidb_tpu.expression.aggregation import AggFunctionMode
        assert all(f.mode == AggFunctionMode.FINAL for f in agg.agg_funcs)

    def test_distinct_agg_not_pushed(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select count(distinct a) from t")
        scan = find_node(p, PhysicalTableScan)
        assert not scan.aggregated_push_down
        agg = find_node(p, PhysicalHashAgg)
        assert agg is not None and not agg.has_pushed_child

    def test_topn_pushdown(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select a from t order by a desc limit 10")
        scan = find_node(p, PhysicalTableScan)
        topn = find_node(p, PhysicalTopN)
        assert topn is not None
        assert scan.topn_pb
        assert scan.limit == 10

    def test_limit_pushdown(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select a from t limit 3,7")
        scan = find_node(p, PhysicalTableScan)
        lim = find_node(p, PhysicalLimit)
        assert lim is not None and lim.offset == 3 and lim.count == 7
        assert scan.limit == 10  # offset+count pushed

    def test_agg_blocked_by_residual_filter(self, env):
        ctx, client = env
        # CAST has no pushdown conversion → residual filter → agg stays up
        p = plan_for(ctx, client,
                     "select sum(a) from t where cast(a as char(10)) = '5'")
        scan = find_node(p, PhysicalTableScan)
        assert scan.conditions  # residual SQL-side filter
        assert not scan.aggregated_push_down


class TestAccessPaths:
    def test_pk_range(self, env):
        ctx, client = env
        p = plan_for(ctx, client,
                     "select a from t where id > 10 and id <= 20")
        scan = find_node(p, PhysicalTableScan)
        assert scan.ranges == [TableRange(11, 20)]
        assert scan.pushed_where is None  # fully consumed by the range

    def test_pk_point(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select a from t where id = 7")
        scan = find_node(p, PhysicalTableScan)
        assert scan.ranges == [TableRange(7, 7)]

    def test_pk_in_list(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select a from t where id in (3, 1, 5)")
        scan = find_node(p, PhysicalTableScan)
        assert scan.ranges == [TableRange(1, 1), TableRange(3, 3),
                               TableRange(5, 5)]

    def test_index_selected_for_eq(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select id from t where b = 'x'")
        iscan = find_node(p, PhysicalIndexScan)
        assert iscan is not None
        assert iscan.index.name == "idx_b"
        assert not iscan.double_read  # id (handle) + b covered by index
        assert len(iscan.ranges) == 1

    def test_index_double_read(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select c from t where b = 'x'")
        iscan = find_node(p, PhysicalIndexScan)
        assert iscan is not None and iscan.double_read


class TestPruning:
    def test_scan_columns_pruned(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select a from t where c > 1.5")
        scan = find_node(p, PhysicalTableScan)
        names = {c.col_name for c in scan.schema}
        assert names == {"a", "c"}  # b and id dropped

    def test_agg_prune_keeps_needed(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select sum(c) from t group by b")
        scan = find_node(p, PhysicalTableScan)
        names = {c.col_name for c in scan.schema}
        assert names == {"b", "c"}


class TestJoins:
    def test_inner_join_eq_extracted(self, env):
        ctx, client = env
        p = plan_for(ctx, client,
                     "select t.a, s.v from t join s on t.id = s.t_id "
                     "where s.v = 'x'")
        hj = find_node(p, PhysicalHashJoin)
        assert hj is not None
        assert len(hj.eq_conditions) == 1
        # s.v='x' pushed into the s-side scan
        scans = []

        def collect(n):
            if isinstance(n, PhysicalTableScan):
                scans.append(n)
            for c in n.children:
                collect(c)
        collect(p)
        assert len(scans) == 2
        assert any(s.pushed_where is not None for s in scans)

    def test_left_join_where_stays(self, env):
        ctx, client = env
        p = plan_for(ctx, client,
                     "select t.a from t left join s on t.id = s.t_id "
                     "where s.v = 'x'")
        # right-side WHERE filter must stay above the join
        sel = find_node(p, PhysicalSelection)
        hj = find_node(p, PhysicalHashJoin)
        assert hj is not None and sel is not None


class TestMisc:
    def test_select_no_from(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select 1 + 1")
        assert find_node(p, PhysicalProjection) is not None

    def test_tree_string_smoke(self, env):
        ctx, client = env
        p = plan_for(ctx, client, "select b, count(*) from t group by b")
        s = tree_string(p)
        assert "tscan" in s and "phashagg" in s


class TestStreamAgg:
    """PhysicalStreamAgg fires when the child index scan's leading
    columns cover the group keys (executor/executor.go:1085)."""

    from tests.testkit import TestKit as _TK

    @pytest.fixture
    def tk(self):
        t = self._TK()
        t.exec("create database test")
        t.exec("use test")
        t.exec("create table s (a int primary key, b int, c int, "
               "key ib (b, c))")
        t.exec("insert into s values " +
               ", ".join(f"({i}, {i % 4}, {i % 3})" for i in range(1, 60)))
        return t

    def _plan(self, t, sql):
        return "\n".join(str(r[0]) for r in t.query("explain " + sql).rows)

    def test_emitted_on_ordered_index_prefix(self, tk):
        # CAST keeps the filter SQL-side → aggregation can't push down;
        # the hinted index orders rows by (b, c) → stream aggregation
        p = self._plan(tk, "select b, count(1) from s use index (ib) "
                           "where cast(c as char) != '9' group by b")
        assert "pstreamagg" in p

    def test_results_match_hash_agg(self, tk):
        sql_stream = ("select b, count(1), sum(a) from s use index (ib) "
                      "where cast(c as char) != '9' group by b order by b")
        sql_hash = ("select b, count(1), sum(a) from s "
                    "where cast(c as char) != '9' group by b order by b")
        assert "pstreamagg" in self._plan(tk, sql_stream)
        assert tk.query(sql_stream).rows == tk.query(sql_hash).rows

    def test_two_column_group_prefix(self, tk):
        sql = ("select b, c, count(1) from s use index (ib) "
               "where cast(a as char) != 'x' group by b, c order by b, c")
        assert "pstreamagg" in self._plan(tk, sql)
        r = tk.query(sql).rows
        assert sum(row[2] for row in r) == 59

    def test_not_emitted_when_group_not_prefix(self, tk):
        # group by c alone is NOT a prefix of (b, c)
        p = self._plan(tk, "select c, count(1) from s use index (ib) "
                           "where cast(a as char) != '9' group by c")
        assert "pstreamagg" not in p
