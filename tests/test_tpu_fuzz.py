"""Randomized large-scale differential parity: a 10k-row table with
NULL-dense columns, many dictionary values, and group cardinalities that
cross the ranked-kernel bucket ladder, run on BOTH engines (reference
oracle: distsql/xeval/eval_test.go's table-driven style, scaled up).

Size-dependent failure modes this exercises that the 7-row fixture cannot:
dictionary packing with 64 distinct strings, pad-to-bucket capacity
boundaries (10000 → 16384 pad), rank-bucket overflow escalation
(NDV ≈ 3000 > 1024 first bucket), segment sinks with most rows dead,
and float accumulation order differences (relative-tolerance compare).
"""

import random

import pytest

from tidb_tpu.ops import TpuClient
from tidb_tpu.session import Session, new_store

N_ROWS = 10_000


def _build(store):
    from tidb_tpu.types import Datum, datum_from_py
    from tidb_tpu.types.datum import NULL
    from tidb_tpu.types.time_types import Time, parse_time

    s = Session(store)
    s.execute("create database fz")
    s.execute("use fz")
    s.execute(
        "create table t (id bigint primary key, a int, b varchar(32), "
        "c double, d date, e int, f int, m decimal(12,2))")
    tbl = s.info_schema().table_by_name("fz", "t")
    date_tp = tbl.info.columns[4].field_type.tp

    rng = random.Random(1234)
    words = [f"w{i:03d}" for i in range(64)]
    base = parse_time("2020-01-01")
    import datetime as dt
    txn = store.begin()
    for i in range(1, N_ROWS + 1):
        # a: high-ish NDV (~3000) to force the 1025→16385 bucket escalation
        a = Datum.i64(rng.randint(0, 2999)) if rng.random() > 0.05 else NULL
        b = Datum.string(rng.choice(words)) if rng.random() > 0.15 else NULL
        c = Datum.f64(round(rng.uniform(-1e6, 1e6), 4)) \
            if rng.random() > 0.30 else NULL
        d = datum_from_py(
            Time(base.dt + dt.timedelta(days=rng.randint(0, 365)), date_tp)) \
            if rng.random() > 0.10 else NULL
        e = Datum.i64(rng.randint(0, 7))
        f = Datum.i64(rng.randint(-10**12, 10**12))
        from decimal import Decimal as _D
        m = Datum.dec(_D(rng.randint(-10**7, 10**7)) / 100) \
            if rng.random() > 0.20 else NULL
        tbl.add_record(txn, [Datum.i64(i), a, b, c, d, e, f, m],
                       skip_unique_check=True)
        if i % 2000 == 0:
            txn.commit()
            txn = store.begin()
    txn.commit()
    return s


@pytest.fixture(scope="module")
def sessions():
    cpu_store = new_store("memory://fuzz_cpu")
    tpu_store = new_store("memory://fuzz_tpu")
    tpu_store.set_client(TpuClient(tpu_store, dispatch_floor_rows=0))
    return _build(cpu_store), _build(tpu_store)


QUERIES = [
    # scalar aggregates over NULL-dense data
    "select count(*), count(a), count(c), count(d) from t",
    "select sum(a), min(a), max(a), avg(a) from t",
    "select sum(c), min(c), max(c), avg(c) from t",
    "select min(b), max(b), min(d), max(d) from t",
    "select sum(f), min(f), max(f) from t",
    "select count(distinct a) from t",
    "select count(distinct b) from t",
    "select count(distinct e) from t",
    # filters at scale
    "select count(*), sum(c) from t where a > 1500",
    "select count(*) from t where b like 'w00%'",
    "select count(*) from t where c is null",
    "select count(*), sum(a) from t where d >= '2020-06-01' and e < 4",
    "select count(*) from t where a in (10, 20, 30) or b = 'w001'",
    # low-cardinality group-by (dict + int paths)
    "select e, count(*), sum(a), min(c), max(c), avg(c) from t "
    "group by e order by e",
    "select b, count(*), sum(c) from t group by b order by b",
    # NULL group + mixed columns
    "select b, e, count(*), sum(a) from t group by b, e order by b, e",
    # high-cardinality int group-by (rank bucket escalation 1025→16385)
    "select a, count(*), sum(c) from t group by a order by a",
    # date group-by
    "select d, count(*) from t group by d order by d",
    # first_row on non-group columns at scale
    "select e, a, b from t group by e order by e",
    # filter + group
    "select e, count(*), avg(c) from t where a between 500 and 2500 "
    "group by e order by e",
    # topn at scale
    "select id from t order by c desc limit 50",
    "select id from t order by a limit 25",
    # multi-key topn (lexicographic; NULL ordering differs per direction)
    "select id from t order by e desc, c limit 40",
    "select id from t order by b, a desc, id limit 30",
    # per-group distinct (sort-within-segment boundary counting)
    "select e, count(distinct a) from t group by e order by e",
    "select e, count(distinct b), sum(distinct a) from t "
    "group by e order by e",
    "select b, count(distinct e) from t group by b order by b",
    # distinct over the whole request
    "select sum(distinct e), avg(distinct e) from t",
    # fixed-point decimal plane: EXACT aggregates / filters / group keys
    "select sum(m), min(m), max(m), avg(m), count(m) from t",
    "select e, sum(m), min(m) from t group by e order by e",
    "select count(*) from t where m > 1234.56",
    "select count(*) from t where m between -50000 and 50000",
    "select count(distinct m) from t",
    "select sum(m + m), sum(m * 2) from t where m < 0",
]


INDEX_QUERIES = [
    # covering single-read, double-read, ranges, desc
    "select a from t where a = 1500",
    "select id, a from t where a > 2900 order by id",
    "select count(*) from t where a between 100 and 200",
    "select b from t where a = 777 order by id",
]


def test_index_with_pk_as_explicit_column():
    """An index whose columns include the integer pk: PBIndexInfo carries
    that column id twice (indexed datum + pk_handle) and the pack must not
    double-append its plane (regression: broadcast ValueError)."""
    store = new_store("memory://fuzz_pkidx")
    store.set_client(TpuClient(store, dispatch_floor_rows=0))
    s = Session(store)
    s.execute("create database d; use d")
    s.execute("create table t (id bigint primary key, a int)")
    rows = ", ".join(f"({i}, {i % 5})" for i in range(100))
    s.execute(f"insert into t values {rows}")
    s.execute("create index idx_ai on t (a, id)")
    client = store.get_client()
    before = client.stats["tpu_requests"]
    got = s.execute("select id, a from t where a = 3 order by id")[0].values()
    assert got == [[i, 3] for i in range(3, 100, 5)]
    assert client.stats["tpu_requests"] > before


@pytest.fixture(scope="module")
def indexed_sessions(sessions):
    cpu, tpu = sessions
    cpu.execute("create index idx_a on t (a)")
    tpu.execute("create index idx_a on t (a)")
    cpu.execute("create index idx_ai on t (a, id)")
    tpu.execute("create index idx_ai on t (a, id)")
    return cpu, tpu


@pytest.mark.parametrize("sql", INDEX_QUERIES)
def test_fuzz_index_parity(indexed_sessions, sql):
    """REQ_TYPE_INDEX lowered to index-plane batches (round-2 missing #8):
    same results as the CPU engine, served from the TPU tier."""
    cpu, tpu = indexed_sessions
    client = tpu.store.get_client()
    before = client.stats["tpu_requests"]
    cpu_rows = _norm(cpu.execute(sql)[0].values())
    tpu_rows = _norm(tpu.execute(sql)[0].values())
    assert cpu_rows == tpu_rows, sql
    assert client.stats["tpu_requests"] > before, sql


def _norm(rows):
    from decimal import Decimal
    out = []
    for row in rows:
        nr = []
        for v in row:
            if isinstance(v, Decimal):
                v = float(v)
            if isinstance(v, bytes):
                nr.append(v.decode())
            elif isinstance(v, float):
                nr.append(("f", v))
            else:
                nr.append(v)
        out.append(nr)
    return out


def _close(a, b):
    if isinstance(a, tuple) and a[0] == "f":
        return isinstance(b, tuple) and \
            abs(a[1] - b[1]) <= 1e-9 * max(abs(a[1]), abs(b[1]), 1.0)
    return a == b


@pytest.mark.parametrize("sql", QUERIES)
def test_fuzz_parity(sessions, sql):
    cpu, tpu = sessions
    cpu_rows = _norm(cpu.execute(sql)[0].values())
    tpu_rows = _norm(tpu.execute(sql)[0].values())
    assert len(cpu_rows) == len(tpu_rows), sql
    for cr, tr in zip(cpu_rows, tpu_rows):
        assert len(cr) == len(tr), sql
        for a, b in zip(cr, tr):
            assert _close(a, b), (sql, cr, tr)


def test_fuzz_tpu_used(sessions):
    _, tpu = sessions
    client = tpu.store.get_client()
    assert client.stats["tpu_requests"] >= 15


def test_decimal_stays_on_tpu(sessions):
    """Fixed-point decimal requests must run the TPU kernels, not fall
    back (round-2 weak #6: decimal semantics on TPU were float/absent)."""
    _, tpu = sessions
    client = tpu.store.get_client()
    before = (client.stats["tpu_requests"], client.stats["cpu_fallbacks"])
    tpu.execute("select e, sum(m), min(m), max(m) from t "
                "group by e order by e")
    tpu.execute("select count(*) from t where m > 0 and m < 90000")
    assert client.stats["tpu_requests"] == before[0] + 2
    assert client.stats["cpu_fallbacks"] == before[1]


def test_too_fine_decimal_falls_back_cleanly():
    """A decimal column beyond the fixed-point plane (scale > 6) must fall
    back to the CPU engine — NOT error (regression: TypeError_ escaped
    send())."""
    store = new_store("memory://fuzz_decfine")
    store.set_client(TpuClient(store, dispatch_floor_rows=0))
    s = Session(store)
    s.execute("create database d; use d")
    s.execute("create table t (a int primary key, p decimal(20,8))")
    s.execute("insert into t values (1, '1.00000001'), (2, '2.5')")
    client = store.get_client()
    before = client.stats["cpu_fallbacks"]
    got = s.execute("select sum(p) from t")[0].values()
    assert float(got[0][0]) == 3.50000001
    assert client.stats["cpu_fallbacks"] > before
