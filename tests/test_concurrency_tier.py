"""Differential suite for the heavy-traffic concurrency tier.

Three surfaces, each checked against its parity oracle:
  1. micro-batched device dispatch (ops.sched): concurrent below-floor
     statements sharing one padded dispatch must answer row-for-row what
     the solo route (SET GLOBAL tidb_tpu_micro_batch = 0) answers —
     mixed shapes, NULL planes, string/float/decimal literals, desc and
     limit, deadline exhaustion inside a shared batch.
  2. the shared drain pool (cluster.pool): pooled per-region fan-out
     drains must answer exactly what sequential (concurrency-1)
     execution and the row protocol answer, with NO per-statement thread
     spawns.
  3. admission-tier observability: batched statements tally `batched:`
     into perfschema EXECUTION_DETAIL and count sched.* metrics.
"""

from __future__ import annotations

import threading
import time

import pytest

from tidb_tpu import errors, failpoint, metrics
from tidb_tpu import tablecodec as tc
from tidb_tpu.ops import TpuClient
from tidb_tpu.session import Session, new_store
from tests.testkit import _store_id


def _mk_store(n_rows: int = 3000, window_ms: int = 40):
    """Local store + TpuClient with the floor raised so EVERY statement
    is below-floor (the micro-batch tier's regime)."""
    store = new_store(f"memory://conc{next(_store_id)}")
    s = Session(store)
    s.execute("set global tidb_slow_log_threshold = 0")
    s.execute("create database d")
    s.execute("use d")
    s.execute("create table t (id bigint primary key, v bigint, "
              "f double, sx varchar(16), dc decimal(8,2))")
    vals = []
    for i in range(1, n_rows + 1):
        # every 7th row: NULL v and f (NULL-plane coverage)
        if i % 7 == 0:
            vals.append(f"({i}, null, null, 's{i % 5}', {i % 50}.25)")
        else:
            vals.append(f"({i}, {i % 97}, {i}.5, 's{i % 5}', "
                        f"{i % 50}.25)")
    s.execute("insert into t values " + ", ".join(vals))
    store.set_client(TpuClient(store, dispatch_floor_rows=1 << 20))
    client = store.get_client()
    client.batch_window_ms = window_ms
    # warm the packed batch (solo route) so concurrent submitters all
    # hit the batch cache and land inside one gather window
    s.execute("select id from t where v = 0")
    return store, s, client


MIXED_SHAPES = [
    "select id, v from t where v = {k}",
    "select id from t where v between {k} and {k2}",
    "select id, sx from t where sx = 's{m}'",
    "select id from t where f > {k}.5",
    "select id, v from t where v is null",
    "select id from t where v is not null and v < {k}",
    "select id from t where dc = {m}.25",
    "select id, v from t where v = {k} or v = {k2}",
    "select id from t where not (v = {k})",
    "select id, v from t where v = {k} limit 3",
]


def _fill(tpl: str, seed: int) -> str:
    return tpl.format(k=seed % 90, k2=seed % 90 + 5, m=seed % 5)


def _concurrent(store, sqls):
    """Execute sqls concurrently (one session each, barrier start) and
    return {sql: rows}."""
    sessions = [Session(store) for _ in sqls]
    for ss in sessions:
        ss.execute("use d")
    out = {}
    lock = threading.Lock()
    barrier = threading.Barrier(len(sqls))
    errs = []

    def run(ss, q):
        try:
            barrier.wait()
            r = ss.execute(q)[0].values()
            with lock:
                out[q] = r
        except Exception as e:   # surfaced by the caller's assert
            with lock:
                errs.append((q, e))
    ts = [threading.Thread(target=run, args=(ss, q))
          for ss, q in zip(sessions, sqls)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs, errs[:3]
    return out


class TestBatchedVsSolo:
    def test_same_shape_batch_parity_and_counters(self):
        store, s, client = _mk_store()
        sqls = [f"select id, v from t where v = {k}"
                for k in (3, 11, 42, 77, 90, 96, 55, 7)]
        client.micro_batch = False
        oracle = {q: s.execute(q)[0].values() for q in sqls}
        client.micro_batch = True
        d0 = metrics.counter("sched.batched_dispatches").value
        s0 = metrics.counter("sched.batched_statements").value
        got = _concurrent(store, sqls)
        assert metrics.counter("sched.batched_dispatches").value > d0, \
            "concurrent same-shape statements never shared a dispatch"
        assert metrics.counter("sched.batched_statements").value >= s0 + 2
        for q in sqls:
            assert got[q] == oracle[q], q

    def test_mixed_shapes_null_planes_parity(self):
        store, s, client = _mk_store()
        sqls = [_fill(tpl, seed) for seed in (13, 31)
                for tpl in MIXED_SHAPES]
        client.micro_batch = False
        oracle = {q: s.execute(q)[0].values() for q in sqls}
        client.micro_batch = True
        got = _concurrent(store, sqls)
        for q in sqls:
            assert got[q] == oracle[q], q

    def test_desc_and_limit_demux_per_statement(self):
        store, s, client = _mk_store()
        sqls = ["select id from t where v = 5 order by id desc limit 4",
                "select id from t where v = 5",
                "select id from t where v = 12 limit 2"]
        client.micro_batch = False
        oracle = {q: s.execute(q)[0].values() for q in sqls}
        client.micro_batch = True
        got = _concurrent(store, sqls)
        for q in sqls:
            assert got[q] == oracle[q], q

    def test_topn_batches_and_matches_solo(self):
        """Below-floor ORDER BY ... LIMIT statements ride the top-n slot
        kind (sequential-rounding concerns pin float SUM/AVG solo, not
        top-n) and answer row-for-row what the solo route answers —
        multi-key, desc, NULL ordering, string/decimal/float keys."""
        store, s, client = _mk_store()
        shapes = [
            "select id, v from t where v > {k} order by v, id limit 5",
            "select id, v from t where v > {k} order by v desc, id limit 5",
            "select id, f from t where v > {k} order by f desc limit 7",
            "select id, sx from t where v > {k} order by sx desc, id limit 6",
            "select id, dc from t where v > {k} order by dc, id desc limit 4",
            "select id, f from t where v > {k} order by f limit 9",
        ]
        # two literals per shape: every signature gathers >= 2 entries,
        # so each rides a genuinely shared top-n dispatch
        sqls = [tpl.format(k=k) for tpl in shapes for k in (10, 60)]
        client.micro_batch = False
        oracle = {q: s.execute(q)[0].values() for q in sqls}
        client.micro_batch = True
        t0 = metrics.counter("sched.batched_topn_statements").value
        got = _concurrent(store, sqls)
        assert metrics.counter("sched.batched_topn_statements").value > t0, \
            "below-floor top-n statements never rode the batched dispatch"
        for q in sqls:
            assert got[q] == oracle[q], q

    def test_topn_batched_vs_row_protocol(self):
        """The batched top-n against the row protocol oracle (columnar
        scan off): the per-slot lexsort must reproduce the CPU heap's
        order, ties and NULLs included."""
        store, s, client = _mk_store()
        shapes = ["select id, v from t where v < {k} order by v, id limit 8",
                  "select id, sx from t where v < {k} order by sx, id limit 8",
                  "select id, f from t where v < {k} order by f desc, id "
                  "limit 8"]
        sqls = [tpl.format(k=k) for tpl in shapes for k in (50, 90)]
        s.execute("set global tidb_tpu_columnar_scan = 0")
        try:
            oracle = {q: s.execute(q)[0].values() for q in sqls}
        finally:
            s.execute("set global tidb_tpu_columnar_scan = 1")
        got = _concurrent(store, sqls)
        for q in sqls:
            assert got[q] == oracle[q], q

    def test_kill_switch_pins_solo_route(self):
        store, s, client = _mk_store()
        s2 = Session(store)
        s2.execute("set global tidb_tpu_micro_batch = 0")
        assert client.micro_batch is False
        sqls = [f"select id, v from t where v = {k}" for k in range(8)]
        d0 = metrics.counter("sched.batched_dispatches").value
        c0 = client.stats["small_to_cpu"]
        got = _concurrent(store, sqls)
        assert metrics.counter("sched.batched_dispatches").value == d0, \
            "kill switch off but statements still batched"
        assert client.stats["small_to_cpu"] - c0 >= len(sqls)
        s2.execute("set global tidb_tpu_micro_batch = 1")
        oracle = {q: s.execute(q)[0].values() for q in sqls}
        for q in sqls:
            assert got[q] == oracle[q], q

    def test_hot_signature_single_rides_device(self):
        """After a multi-statement batch, a lone statement of the same
        shape keeps riding the device (1-slot dispatch) while traffic is
        hot — and answers exactly the same."""
        store, s, client = _mk_store()
        sqls = [f"select id, v from t where v = {k}" for k in (1, 2, 3, 4)]
        _concurrent(store, sqls)    # heats the signature
        d0 = metrics.counter("sched.batched_dispatches").value
        got = s.execute("select id, v from t where v = 9")[0].values()
        assert metrics.counter("sched.batched_dispatches").value == d0 + 1, \
            "hot-signature single did not ride a 1-slot dispatch"
        client.micro_batch = False
        want = s.execute("select id, v from t where v = 9")[0].values()
        assert got == want

    def test_u64_literal_above_i64_degrades_to_solo(self):
        """A literal outside int64 must not crash the batch tier — the
        solo route answers (regression: np.int64 OverflowError)."""
        store, s, client = _mk_store()
        big = (1 << 63) + 7
        sqls = [f"select id from t where v = {big}",
                f"select id from t where v = {big}",
                "select id from t where v = 4"]
        client.micro_batch = False
        oracle = {q: s.execute(q)[0].values() for q in set(sqls)}
        client.micro_batch = True
        got = _concurrent(store, list(set(sqls)))
        for q in set(sqls):
            assert got[q] == oracle[q], q

    def test_batched_tally_in_execution_detail(self):
        """Satellite: `batched:` lands on perfschema EXECUTION_DETAIL
        (and therefore the slow-log key set) for batched statements."""
        store, s, client = _mk_store(window_ms=200)
        sqls = [f"select id, v from t where v = {k}"
                for k in (21, 22, 23, 24, 25, 26)]
        _concurrent(store, sqls)
        rows = s.execute(
            "select SQL_TEXT, EXECUTION_DETAIL from "
            "performance_schema.events_statements_history")[0].values()
        details = [str(r[1]) for r in rows
                   if "where v =" in str(r[0])]
        assert any("batched:1" in d for d in details), \
            f"no EXECUTION_DETAIL carried the batched: tally: {details[-4:]}"

    def test_deadline_in_shared_batch_fails_only_expired(self):
        """A statement whose deadline expires while parked in the gather
        window dies typed (3024) — its batch-mates answer normally."""
        store, s, client = _mk_store(window_ms=150)
        sqls = [f"select id, v from t where v = {k}" for k in (5, 6, 7, 8)]
        client.micro_batch = False
        oracle = {q: s.execute(q)[0].values() for q in sqls}
        client.micro_batch = True

        sessions = [Session(store) for _ in sqls]
        for ss in sessions:
            ss.execute("use d")
        # the LAST session gets a deadline far shorter than the window:
        # it will expire while waiting inside the shared batch
        sessions[-1].execute("set tidb_tpu_max_execution_time = 30")
        out, errs = {}, []
        lock = threading.Lock()
        barrier = threading.Barrier(len(sqls))

        def run(i):
            try:
                barrier.wait()
                if i == len(sqls) - 1:
                    time.sleep(0.01)   # arrive as a follower
                r = sessions[i].execute(sqls[i])[0].values()
                with lock:
                    out[sqls[i]] = r
            except errors.TiDBError as e:
                with lock:
                    errs.append((i, e))
        ts = [threading.Thread(target=run, args=(i,))
              for i in range(len(sqls))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert len(errs) == 1 and errs[0][0] == len(sqls) - 1, \
            f"expected exactly the short-deadline statement to fail: {errs}"
        assert isinstance(errs[0][1], errors.DeadlineExceededError), errs
        for q in sqls[:-1]:
            assert out[q] == oracle[q], q


class TestStalledWindowDegrades:
    def test_stalled_gather_window_degrades_to_solo(self):
        """sched/batch_window hang: followers reclaim their entries and
        answer through the solo route with unchanged answers, counted on
        copr.degraded_batch."""
        store, s, client = _mk_store(window_ms=20)
        sqls = [f"select id, v from t where v = {k}"
                for k in (31, 32, 33, 34, 35)]
        client.micro_batch = False
        oracle = {q: s.execute(q)[0].values() for q in sqls}
        client.micro_batch = True
        d0 = metrics.counter("copr.degraded_batch").value
        failpoint.enable("sched/batch_window", action="sleep",
                         seconds=0.6)
        try:
            got = _concurrent(store, sqls)
        finally:
            failpoint.disable_all()
        assert metrics.counter("copr.degraded_batch").value > d0, \
            "stalled window never counted a batch degradation"
        for q in sqls:
            assert got[q] == oracle[q], q


class TestPooledDrain:
    def _fan_store(self, n_regions: int = 4):
        store = new_store(f"cluster://3/concfan{next(_store_id)}")
        s = Session(store)
        s.execute("create database m")
        s.execute("use m")
        s.execute("create table ft (id bigint primary key, k bigint, "
                  "v bigint)")
        s.execute("insert into ft values " + ", ".join(
            f"({i}, {i % 5}, {i * 3})" for i in range(1, 241)))
        tid = s.info_schema().table_by_name("m", "ft").info.id
        step = 240 // n_regions
        store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
        return store, s

    def test_pooled_drain_parity_vs_sequential_and_rowpath(self):
        """Pooled fan-out (shared bounded pool) vs concurrency-1
        sequential execution vs the row protocol — row-for-row."""
        store, s = self._fan_store()
        q = ("select k, count(*), sum(v), min(v), max(v) from ft "
             "group by k order by k")
        scan = "select id, v from ft where v > 100 order by id"
        pooled = {x: s.execute(x)[0].values() for x in (q, scan)}
        # sequential oracle: distsql concurrency 1 routes through
        # _ListResponse (no pool involvement at all)
        s.execute("set tidb_distsql_scan_concurrency = 1")
        seq = {x: s.execute(x)[0].values() for x in (q, scan)}
        s.execute("set tidb_distsql_scan_concurrency = 10")
        # row-protocol oracle
        s.execute("set global tidb_tpu_columnar_scan = 0")
        try:
            rowp = {x: s.execute(x)[0].values() for x in (q, scan)}
        finally:
            s.execute("set global tidb_tpu_columnar_scan = 1")
        for x in (q, scan):
            assert pooled[x] == seq[x], f"pooled != sequential: {x}"
            assert pooled[x] == rowp[x], f"pooled != row protocol: {x}"

    def test_no_per_statement_thread_spawns(self):
        """The fan-out drain path spawns no per-statement threads: the
        shared pool's worker count is bounded across many statements."""
        from tidb_tpu.cluster.pool import get_pool
        store, s = self._fan_store()
        q = "select k, count(*), sum(v) from ft group by k order by k"
        s.execute(q)    # pool warm
        before = threading.active_count()
        for _ in range(12):
            s.execute(q)
        after = threading.active_count()
        pool = get_pool()
        assert after <= before + pool.size, \
            (f"thread count grew {before} -> {after} across statements "
             f"(pool size {pool.size}) — per-statement spawns remain")
        st = pool.stats()
        assert st["threads"] <= pool.size, st

    def test_pooled_drain_concurrent_statements_parity(self):
        """Many statements share the bounded pool concurrently; every
        answer matches the single-threaded oracle."""
        store, s = self._fan_store()
        q = "select k, count(*), sum(v) from ft group by k order by k"
        want = s.execute(q)[0].values()
        sessions = [Session(store) for _ in range(8)]
        for ss in sessions:
            ss.execute("use m")
        outs, errs = [], []
        lock = threading.Lock()
        barrier = threading.Barrier(len(sessions))

        def run(ss):
            try:
                barrier.wait()
                for _ in range(3):
                    r = ss.execute(q)[0].values()
                    with lock:
                        outs.append(r)
            except Exception as e:
                with lock:
                    errs.append(e)
        ts = [threading.Thread(target=run, args=(ss,)) for ss in sessions]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errs, errs[:3]
        assert len(outs) == 24
        for r in outs:
            assert r == want

    def test_pool_preserves_backoffer_deadline(self):
        """A statement deadline still bounds pooled fan-out workers: a
        hang inside a region task fails typed, within the deadline."""
        store, s = self._fan_store()
        s.execute("set tidb_tpu_max_execution_time = 400")
        failpoint.enable("copr/region_scan", action="hang")
        try:
            t0 = time.monotonic()
            with pytest.raises(errors.DeadlineExceededError):
                s.execute("select count(*), sum(v) from ft")
            assert time.monotonic() - t0 < 30
        finally:
            failpoint.disable_all()
            s.execute("set tidb_tpu_max_execution_time = 0")
        # pool workers recovered: the next statement answers normally
        r = s.execute("select count(*) from ft")[0].values()
        assert int(r[0][0]) == 240

    def test_deadline_enforced_while_tasks_queued_in_pool(self):
        """A statement whose fan-out tasks sit QUEUED behind another
        statement's slow tasks in the shared pool still fails its
        deadline typed — the consumer polls the Backoffer while
        waiting, instead of sleeping until a worker frees."""
        from tidb_tpu.cluster.pool import get_pool
        store, s = self._fan_store()
        pool = get_pool()
        old_size = pool.size
        pool.set_size(1)
        failpoint.enable("copr/region_scan", action="sleep", seconds=0.8)
        try:
            holder = Session(store)
            holder.execute("use m")
            t = threading.Thread(
                target=lambda: holder.execute("select count(*) from ft"))
            t.start()
            time.sleep(0.1)   # the single worker is now busy sleeping
            victim = Session(store)
            victim.execute("use m")
            victim.execute("set tidb_tpu_max_execution_time = 200")
            t0 = time.monotonic()
            with pytest.raises(errors.DeadlineExceededError):
                victim.execute("select count(*), sum(v) from ft")
            took = time.monotonic() - t0
            assert took < 2.0, \
                f"queued statement overshot its 200ms deadline by {took:.1f}s"
            t.join(timeout=30)
        finally:
            failpoint.disable_all()
            pool.set_size(old_size)
