"""Differential suite for the HTAP freshness tier (copr.delta): per-table
commit filtering + region-side delta packs over cached base planes with
device base+delta merge at scan time.

Every regime is judged against two oracles — the kill switch
(tidb_tpu_delta_pack = 0 restores invalidate-on-commit) and the row
protocol (tidb_tpu_columnar_scan = 0) — row-for-row, including emission
order. Snapshot isolation is exercised both ways (a newer reader merges
the delta; an older open snapshot keeps its pre-delta generation), the
budget fold (background re-pack) and both degradation rungs
(copr/delta_merge → re-pack, device/delta_merge → host merge plan) are
driven by failpoints, and a chaos schedule races a writer thread against
fan-out readers under prob-failpoints.
"""

from __future__ import annotations

import itertools
import threading

import pytest

from tidb_tpu import errors, failpoint, metrics, tablecodec as tc
from tidb_tpu.copr.delta import delta_for
from tidb_tpu.session import Session, new_store

_id = itertools.count(1)

N_ROWS = 240

SCALAR_Q = ("select count(*), sum(v), min(v), max(f), min(sv), sum(dc) "
            "from t where k < 9")
QUERIES = [
    SCALAR_Q,
    "select k, count(*), sum(v) from t group by k order by k",
    "select id, k, v, f, sv, dc from t order by id",
    "select id, v from t order by v desc limit 9",
]


def _c(name: str) -> int:
    return metrics.counter(name).value


def _build(n_regions: int = 4):
    store = new_store(f"cluster://3/deltapack{next(_id)}")
    s = Session(store)
    s.execute("create database dp")
    s.execute("use dp")
    s.execute("create table t (id bigint primary key, k bigint, "
              "v bigint, f double, sv varchar(16), dc decimal(10,2))")
    s.execute("create table other (id bigint primary key, x bigint)")
    rows = ", ".join(
        f"({i}, {i % 13}, {i * 10}, {i}.25, 's{i % 17:02d}', {i}.5)"
        if i % 11 else
        f"({i}, null, {i * 10}, null, null, null)"
        for i in range(1, N_ROWS + 1))
    s.execute(f"insert into t values {rows}")
    s.execute("insert into other values (0, 0)")
    if n_regions > 1:
        tid = s.info_schema().table_by_name("dp", "t").info.id
        step = N_ROWS // n_regions
        s.store.cluster.split_keys(
            [tc.encode_row_key(tid, step * i + 1)
             for i in range(1, n_regions)])
    return s


def _all(s) -> list:
    return [s.execute(q)[0].values() for q in QUERIES]


def _parity(s, got: list) -> None:
    """got must equal the delta-off regime AND the row protocol — at the
    CURRENT state (no commits in between)."""
    s.execute("set global tidb_tpu_delta_pack = 0")
    try:
        off = _all(s)
    finally:
        s.execute("set global tidb_tpu_delta_pack = 1")
    for q, g, o in zip(QUERIES, got, off):
        assert g == o, f"delta-on diverged from delta-off on {q!r}"
    s.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        rows = _all(s)
    finally:
        s.execute("set global tidb_tpu_columnar_scan = 1")
    for q, g, r in zip(QUERIES, got, rows):
        assert g == r, f"delta-on diverged from the row protocol on {q!r}"


def test_commit_to_other_table_never_touches_cached_planes():
    """The per-table commit filter: table B traffic leaves table A's
    entries untouched — exact hits, zero misses, zero version sweeps
    (the acceptance criterion's counter assert)."""
    s = _build(4)
    _all(s)                          # populate every region's planes
    _all(s)
    h0 = _c("copr.plane_cache.hits")
    m0 = _c("copr.plane_cache.misses")
    i0 = _c("copr.plane_cache.invalidations_version")
    g0 = _c("copr.delta.merges")
    for i in range(3):
        s.execute(f"insert into other values ({i + 1}, {i})")
        got = _all(s)
    assert _c("copr.plane_cache.misses") == m0, \
        "a commit to table B re-packed table A"
    assert _c("copr.plane_cache.invalidations_version") == i0, \
        "a commit to table B swept table A's entries"
    assert _c("copr.delta.merges") == g0, \
        "a commit to table B forced a delta merge on table A"
    assert _c("copr.plane_cache.hits") - h0 >= 3 * 4
    _parity(s, got)


def test_delta_merge_parity_insert_update_delete():
    """Mixed mutations (inserts between existing handles, updates,
    deletes, new dictionary strings) merge base+delta into exactly the
    batch a re-pack would build — row-for-row vs both oracles, in scan
    order, with the merges counted."""
    s = _build(4)
    _all(s)
    s.execute("insert into t values (1000, 3, 5, 0.5, 'zzz-new', 7.25), "
              "(1001, null, -4, null, null, null)")
    s.execute("update t set v = -77, sv = 'aa-upd' where id = 10")
    s.execute("delete from t where id in (11, 12)")
    # counters snapshot AFTER the DML: the DML statements' own scans use
    # fresh point-range base keys whose first lookups legitimately miss
    g0 = _c("copr.delta.merges")
    m0 = _c("copr.plane_cache.misses")
    got = _all(s)
    d_merges = _c("copr.delta.merges") - g0
    d_misses = _c("copr.plane_cache.misses") - m0
    assert d_merges > 0, "no scan took the merge path"
    # a merge-served lookup still counts a miss (no EXACT entry served);
    # the claim is that every such miss merged instead of re-packing
    assert d_merges == d_misses, \
        f"{d_misses - d_merges} lookups re-packed instead of merging"
    _parity(s, got)
    # the merged generation was admitted: repeat scans exact-hit
    h0 = _c("copr.plane_cache.hits")
    again = _all(s)
    assert again == got
    assert _c("copr.plane_cache.hits") - h0 >= 4


def test_old_snapshot_reader_keeps_pre_delta_generation():
    """Snapshot isolation both ways: after a delta lands, a still-open
    older snapshot keeps reading its pre-delta data while new readers
    see the merge. Regions the commit actually touched keep serving the
    old reader from its retained base entry; version-only regions were
    re-keyed forward (identical planes, exact byte accounting), so the
    old reader re-packs those ONCE and re-admits its own generation —
    repeat old reads then exact-hit again."""
    s1 = _build(4)
    s2 = Session(s1.store)
    s2.execute("use dp")
    q = "select count(*), sum(v) from t"
    s1.execute("begin")
    old = s1.execute(q)[0].values()
    s1.execute(q)                    # cache at the old generation
    s2.execute("insert into t values (2000, 1, 999999, null, null, null)")
    new = s2.execute(q)[0].values()
    assert new != old, "newer session missed the committed write"
    still_old = s1.execute(q)[0].values()
    assert still_old == old, \
        "older snapshot observed the delta (snapshot isolation broken)"
    h0, m0 = _c("copr.plane_cache.hits"), _c("copr.plane_cache.misses")
    again_old = s1.execute(q)[0].values()
    assert again_old == old
    assert _c("copr.plane_cache.hits") - h0 >= 4 and \
        _c("copr.plane_cache.misses") == m0, \
        "old snapshot did not re-establish its own cached generation"
    s1.execute("commit")
    assert s1.execute(q)[0].values() == new


def test_budget_fold_resets_pack():
    """A pack past tidb_tpu_delta_budget_rows folds into a fresh base on
    the next scan (background re-pack): counted, pack emptied, answers
    exact."""
    s = _build(2)
    s.execute("set global tidb_tpu_delta_budget_rows = 8")
    try:
        _all(s)
        r0 = _c("copr.delta.repacks")
        vals = ", ".join(f"({3000 + i}, 1, {i}, null, null, null)"
                         for i in range(24))
        s.execute(f"insert into t values {vals}")
        got = _all(s)
        assert _c("copr.delta.repacks") > r0, \
            "over-budget delta never folded into a fresh base"
        ds = delta_for(s.store)
        tid = s.info_schema().table_by_name("dp", "t").info.id
        assert all(ds.pack_rows(r.region_id, tid) == 0
                   for r in s.store.cluster.regions), \
            "fold did not reset the pack"
        _parity(s, got)
    finally:
        s.execute("set global tidb_tpu_delta_budget_rows = 4096")


def test_failpoint_degrades_to_repack():
    """copr/delta_merge drops the merge path: the scan re-packs (the
    plain PR-5 behavior) with unchanged answers, counted on
    copr.degraded_delta_to_repack."""
    s = _build(4)
    want_pre = _all(s)
    s.execute("insert into t values (4000, 2, 42, null, null, null)")
    d0 = _c("copr.degraded_delta_to_repack")
    failpoint.enable("copr/delta_merge", action="return", value=True)
    try:
        got = _all(s)
    finally:
        failpoint.disable("copr/delta_merge")
    assert got != want_pre           # the write is visible either way
    assert _c("copr.degraded_delta_to_repack") > d0
    _parity(s, got)
    # after the failpoint clears, the merge path resumes on fresh deltas
    g0 = _c("copr.delta.merges")
    s.execute("insert into t values (4001, 2, 43, null, null, null)")
    got2 = _all(s)
    assert _c("copr.delta.merges") > g0
    _parity(s, got2)


def test_device_fault_degrades_to_host_plan(monkeypatch):
    """device/delta_merge fails the kernel: the merge degrades to the
    host numpy plan (identical order), counted on
    copr.degraded_delta_to_host."""
    from tidb_tpu.copr import delta as delta_mod
    monkeypatch.setattr(delta_mod, "MERGE_DEVICE_FLOOR", 0)
    s = _build(4)
    _all(s)
    s.execute("update t set v = v + 5 where id < 20")
    d0 = _c("copr.degraded_delta_to_host")
    g0 = _c("copr.delta.merges")
    failpoint.enable("device/delta_merge")
    try:
        got = _all(s)
    finally:
        failpoint.disable("device/delta_merge")
    assert _c("copr.degraded_delta_to_host") > d0, \
        "device fault did not degrade to the host merge plan"
    assert _c("copr.delta.merges") > g0
    _parity(s, got)


def test_device_merge_plan_matches_host(monkeypatch):
    """The device kernel's order plan is bit-identical to the host
    numpy plan (floor forced to 0 so the kernel actually runs)."""
    from tidb_tpu.copr import delta as delta_mod
    monkeypatch.setattr(delta_mod, "MERGE_DEVICE_FLOOR", 0)
    s = _build(4)
    _all(s)
    s.execute("insert into t values (5000, 4, 1, 1.0, 'kx', 2.5)")
    s.execute("delete from t where id = 30")
    got = _all(s)
    _parity(s, got)


def test_kill_switch_and_sysvars():
    """GLOBAL-only validation, persistence, and the kill switch clearing
    live packs."""
    s = _build(2)
    with pytest.raises(errors.TiDBError):
        s.execute("set tidb_tpu_delta_pack = 0")          # GLOBAL-only
    with pytest.raises(errors.TiDBError):
        s.execute("set global tidb_tpu_delta_pack = 'x'")
    with pytest.raises(errors.TiDBError):
        s.execute("set global tidb_tpu_delta_budget_rows = 0")
    _all(s)
    s.execute("insert into t values (6000, 1, 1, null, null, null)")
    _all(s)                          # delta pack now live
    ds = delta_for(s.store)
    assert len(ds) > 0
    s.execute("set global tidb_tpu_delta_pack = 0")
    try:
        assert len(ds) == 0, "kill switch left packs behind"
        assert not ds.enabled
        got = _all(s)
        s.execute("set global tidb_tpu_columnar_scan = 0")
        try:
            rows = _all(s)
        finally:
            s.execute("set global tidb_tpu_columnar_scan = 1")
        assert got == rows
        row = s.execute(
            "select variable_value from mysql.global_variables where "
            "variable_name = 'tidb_tpu_delta_pack'")[0].values()
        assert row == [["0"]]
    finally:
        s.execute("set global tidb_tpu_delta_pack = 1")
    s.execute("set global tidb_tpu_delta_budget_rows = 512")
    try:
        assert ds.budget_rows == 512
        row = s.execute(
            "select variable_value from mysql.global_variables where "
            "variable_name = 'tidb_tpu_delta_budget_rows'")[0].values()
        assert row == [["512"]]
    finally:
        s.execute("set global tidb_tpu_delta_budget_rows = 4096")


def test_chaos_writer_races_fanout_readers():
    """Chaos schedule (satellite): one writer thread committing
    inserts/updates/deletes on t (plus unrelated-table traffic) races
    fan-out readers while copr/delta_merge and cache/no_admit fire
    probabilistically. Invariants: no reader ever errors or diverges
    from the row protocol at its own snapshot (checked differentially
    inside each reader turn), the old-snapshot session keeps its
    pre-delta read, and degraded accounting only grows."""
    s = _build(4)
    store = s.store
    q = "select count(*), sum(v), min(v) from t"
    # the pinned old snapshot (its generation must survive the chaos)
    s_old = Session(store)
    s_old.execute("use dp")
    s_old.execute("begin")
    old_want = s_old.execute(q)[0].values()
    _all(s)
    d0 = _c("copr.degraded_delta_to_repack")
    stop = threading.Event()
    errors_seen: list = []

    def writer():
        w = Session(store)
        w.execute("use dp")
        i = 0
        while not stop.is_set():
            i += 1
            try:
                w.execute(f"insert into t values ({7000 + i}, {i % 13}, "
                          f"{i}, null, null, null)")
                w.execute(f"update t set v = v + 1 where id = {i % 100 + 1}")
                if i % 3 == 0:
                    w.execute(f"insert into other values ({100 + i}, {i})")
                if i % 5 == 0:
                    w.execute(f"delete from t where id = {7000 + i}")
            except errors.TiDBError as e:   # retryable-ok: chaos noise
                errors_seen.append(("writer", e))

    def reader(seed: int):
        r = Session(store)
        r.execute("use dp")
        rq = QUERIES[seed % len(QUERIES)]
        while not stop.is_set():
            try:
                r.execute(rq)
            except errors.TiDBError as e:
                errors_seen.append(("reader", e))

    failpoint.enable("copr/delta_merge", action="return", value=True,
                     when=("prob", 0.3), seed=11)
    failpoint.enable("cache/no_admit", action="return", value=True,
                     when=("prob", 0.2), seed=13)
    threads = [threading.Thread(target=writer)] + \
        [threading.Thread(target=reader, args=(i,)) for i in range(3)]
    try:
        for t in threads:
            t.start()
        import time
        time.sleep(2.0)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        failpoint.disable("copr/delta_merge")
        failpoint.disable("cache/no_admit")
    assert not errors_seen, f"chaos surfaced errors: {errors_seen[:3]}"
    assert not any(t.is_alive() for t in threads), "chaos thread wedged"
    # the old snapshot read is unchanged through all of it
    assert s_old.execute(q)[0].values() == old_want, \
        "old-snapshot reader lost its pre-delta generation"
    s_old.execute("rollback")
    # steady state: full differential parity at the final state
    got = _all(s)
    _parity(s, got)
    assert _c("copr.degraded_delta_to_repack") >= d0


def test_modify_column_ddl_never_serves_stale_pack():
    """Per-table versions deliberately ignore meta-only DDL commits —
    the cache key's full column-schema SIGNATURE is what maps a MODIFY
    COLUMN onto fresh entries (a pre-DDL pack must never serve the
    post-DDL request shape)."""
    s = _build(4)
    s.execute("create table mt (id bigint primary key, a int)")
    s.execute("insert into mt values " +
              ", ".join(f"({i}, {i % 9})" for i in range(1, 121)))
    tid = s.info_schema().table_by_name("dp", "mt").info.id
    s.store.cluster.split_keys([tc.encode_row_key(tid, 61)])
    q = "select count(*), sum(a) from mt where a < 7"
    want = s.execute(q)[0].values()
    s.execute(q)                        # cache at the pre-DDL signature
    m0 = _c("copr.plane_cache.misses")
    s.execute("alter table mt modify column a bigint")   # int → bigint
    got = s.execute(q)[0].values()
    assert got == want
    assert _c("copr.plane_cache.misses") > m0, \
        "post-DDL request was served from the pre-DDL signature"
    # the same-type no-op form keeps hitting (signature unchanged)
    h0 = _c("copr.plane_cache.hits")
    s.execute(q)
    assert _c("copr.plane_cache.hits") > h0
    s.execute("set global tidb_tpu_columnar_scan = 0")
    try:
        rows = s.execute(q)[0].values()
    finally:
        s.execute("set global tidb_tpu_columnar_scan = 1")
    assert got == rows
