"""Headline benchmark: TPC-H Q6 rows/sec/chip, TPU coprocessor vs the CPU
xeval baseline (BASELINE.md configs 1-2).

Builds a lineitem-shaped table in the in-memory MVCC store, runs Q6 through
the FULL engine stack (SQL → plan → pushdown → coprocessor) on both
engines, and prints ONE JSON line:

    {"metric": "tpch_q6_rows_per_sec_tpu", "value": ..., "unit": "rows/s",
     "vs_baseline": <tpu_rows_per_sec / cpu_rows_per_sec>}

Environment:
    BENCH_ROWS   lineitem row count (default 300000)
    BENCH_RUNS   timed repetitions per engine (default 3)
"""

from __future__ import annotations

import json
import os
import random
import sys
import time


Q6 = ("select sum(l_extendedprice * l_discount) from lineitem "
      "where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01' "
      "and l_discount >= 0.05 and l_discount <= 0.07 "
      "and l_quantity < 24")


def build_store(n_rows: int):
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum, datum_from_py
    from tidb_tpu.types.time_types import parse_time

    store = new_store(f"memory://bench{n_rows}")
    s = Session(store)
    s.execute("create database tpch")
    s.execute("use tpch")
    s.execute(
        "create table lineitem ("
        " l_id bigint primary key,"
        " l_quantity double, l_extendedprice double, l_discount double,"
        " l_tax double, l_returnflag varchar(1), l_linestatus varchar(1),"
        " l_shipdate date)")
    tbl = s.info_schema().table_by_name("tpch", "lineitem")

    rng = random.Random(42)
    flags = ["A", "N", "R"]
    statuses = ["F", "O"]
    base = parse_time("1992-01-01")
    import datetime as dt
    t0 = time.time()
    batch = 20000
    i = 1
    while i <= n_rows:
        txn = store.begin()
        for _ in range(min(batch, n_rows - i + 1)):
            ship = base.dt + dt.timedelta(days=rng.randint(0, 2500))
            from tidb_tpu.types.time_types import Time
            row = [
                Datum.i64(i),
                Datum.f64(float(rng.randint(1, 50))),
                Datum.f64(round(rng.uniform(900.0, 105000.0), 2)),
                Datum.f64(round(rng.uniform(0.0, 0.1), 2)),
                Datum.f64(round(rng.uniform(0.0, 0.08), 2)),
                Datum.string(rng.choice(flags)),
                Datum.string(rng.choice(statuses)),
                datum_from_py(Time(ship, tbl.info.columns[7].field_type.tp)),
            ]
            tbl.add_record(txn, row, skip_unique_check=True)
            i += 1
        txn.commit()
    load_s = time.time() - t0
    return store, s, load_s


def timed_runs(session, sql: str, runs: int):
    session.execute(sql)  # warm (compile + cache)
    results = []
    t0 = time.time()
    for _ in range(runs):
        results.append(session.execute(sql)[0].values())
    return (time.time() - t0) / runs, results


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", "300000"))
    runs = int(os.environ.get("BENCH_RUNS", "3"))

    from tidb_tpu.ops import TpuClient
    from tidb_tpu.session import Session

    store, session, load_s = build_store(n_rows)
    print(f"# loaded {n_rows} rows in {load_s:.1f}s", file=sys.stderr)

    # CPU xeval baseline (store/localstore/local_region.go equivalent)
    cpu_s, cpu_results = timed_runs(session, Q6, runs)
    cpu_rps = n_rows / cpu_s

    # TPU coprocessor
    store.set_client(TpuClient(store))
    tpu_session = Session(store)
    tpu_session.execute("use tpch")
    tpu_s, tpu_results = timed_runs(tpu_session, Q6, runs)
    tpu_rps = n_rows / tpu_s

    client = store.get_client()
    assert client.stats["tpu_requests"] > 0, "TPU engine was never used"

    # result parity (float path: relative tolerance)
    cpu_v = float(cpu_results[0][0][0])
    tpu_v = float(tpu_results[0][0][0])
    assert abs(cpu_v - tpu_v) <= 1e-6 * max(abs(cpu_v), 1.0), \
        f"parity failure: cpu={cpu_v} tpu={tpu_v}"

    print(f"# cpu: {cpu_s:.3f}s/run ({cpu_rps:,.0f} rows/s)  "
          f"tpu: {tpu_s:.4f}s/run ({tpu_rps:,.0f} rows/s)  "
          f"speedup {tpu_rps / cpu_rps:.1f}x", file=sys.stderr)
    print(json.dumps({
        "metric": "tpch_q6_rows_per_sec_tpu",
        "value": round(tpu_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 2),
    }))


if __name__ == "__main__":
    main()
