"""Headline benchmark: TPC-H-shaped configs from BASELINE.md, TPU
coprocessor vs the CPU xeval baseline, through the FULL engine stack
(SQL → plan → pushdown → coprocessor).

Configs (BASELINE.md):
  2. Q6  — scan + 3-predicate filter + single sum, no group-by
  3. Q1  — scan + filter + 8 aggregates GROUP BY 2 cols
  4. count(distinct l_orderkey) — distinct kernel
  5. Q1 via the device mesh (region-sharded partial-agg combine)

Prints per-config lines to stderr and ONE JSON line to stdout:

    {"metric": "tpch_geomean_rows_per_sec_tpu", "value": ...,
     "unit": "rows/s", "vs_baseline": <geomean speedup over configs 2-4>}

Environment:
    BENCH_ROWS   lineitem row count (default 300000)
    BENCH_RUNS   timed repetitions per engine (default 3)
"""

from __future__ import annotations

import json
import math
import os
import random
import sys
import time


Q6 = ("select sum(l_extendedprice * l_discount) from lineitem "
      "where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01' "
      "and l_discount >= 0.05 and l_discount <= 0.07 "
      "and l_quantity < 24")

Q1 = ("select l_returnflag, l_linestatus, "
      "sum(l_quantity), sum(l_extendedprice), "
      "sum(l_extendedprice * (1 - l_discount)), "
      "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)), "
      "avg(l_quantity), avg(l_extendedprice), avg(l_discount), count(*) "
      "from lineitem where l_shipdate <= '1998-09-02' "
      "group by l_returnflag, l_linestatus "
      "order by l_returnflag, l_linestatus")

QDIST = "select count(distinct l_orderkey) from lineitem"


def build_store(n_rows: int):
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum, datum_from_py
    from tidb_tpu.types.time_types import parse_time

    store = new_store(f"memory://bench{n_rows}")
    s = Session(store)
    s.execute("create database tpch")
    s.execute("use tpch")
    s.execute(
        "create table lineitem ("
        " l_id bigint primary key, l_orderkey bigint,"
        " l_quantity double, l_extendedprice double, l_discount double,"
        " l_tax double, l_returnflag varchar(1), l_linestatus varchar(1),"
        " l_shipdate date)")
    tbl = s.info_schema().table_by_name("tpch", "lineitem")

    rng = random.Random(42)
    flags = ["A", "N", "R"]
    statuses = ["F", "O"]
    base = parse_time("1992-01-01")
    import datetime as dt
    from tidb_tpu.types.time_types import Time
    date_tp = tbl.info.columns[8].field_type.tp

    # generate rows first so the load metric measures the ENGINE write
    # path (add_record + membuffer + codec + commit), not random()
    rows = []
    for i in range(1, n_rows + 1):
        ship = base.dt + dt.timedelta(days=rng.randint(0, 2500))
        rows.append([
            Datum.i64(i),
            Datum.i64((i + 3) // 4),
            Datum.f64(float(rng.randint(1, 50))),
            Datum.f64(round(rng.uniform(900.0, 105000.0), 2)),
            Datum.f64(round(rng.uniform(0.0, 0.1), 2)),
            Datum.f64(round(rng.uniform(0.0, 0.08), 2)),
            Datum.string(rng.choice(flags)),
            Datum.string(rng.choice(statuses)),
            datum_from_py(Time(ship, date_tp)),
        ])

    t0 = time.time()
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        for row in rows[start:start + batch]:
            tbl.add_record(txn, row, skip_unique_check=True)
        txn.commit()
    load_s = time.time() - t0
    return store, s, load_s


def timed_runs(session, sql: str, runs: int):
    session.execute(sql)  # warm (compile + cache)
    results = []
    t0 = time.time()
    for _ in range(runs):
        results.append(session.execute(sql)[0].values())
    return (time.time() - t0) / runs, results


def check_parity(name: str, cpu_rows, tpu_rows):
    assert len(cpu_rows) == len(tpu_rows), \
        f"{name}: row count {len(cpu_rows)} vs {len(tpu_rows)}"
    for cr, tr in zip(cpu_rows, tpu_rows):
        assert len(cr) == len(tr), \
            f"{name}: column count {len(cr)} vs {len(tr)}"
        for cv, tv in zip(cr, tr):
            if isinstance(cv, (int,)) and isinstance(tv, (int,)):
                assert cv == tv, f"{name}: {cv} != {tv}"
            elif cv is None or tv is None:
                assert cv is None and tv is None, f"{name}: {cv} vs {tv}"
            elif isinstance(cv, (bytes, str)):
                assert cv == tv, f"{name}: {cv!r} != {tv!r}"
            else:
                a, b = float(cv), float(tv)
                assert abs(a - b) <= 1e-6 * max(abs(a), abs(b), 1.0), \
                    f"{name}: {a} != {b}"


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", "300000"))
    runs = int(os.environ.get("BENCH_RUNS", "3"))

    from tidb_tpu.ops import TpuClient
    from tidb_tpu.session import Session

    store, session, load_s = build_store(n_rows)
    print(f"# loaded {n_rows} rows in {load_s:.1f}s "
          f"({n_rows / load_s:,.0f} rows/s)", file=sys.stderr)

    configs = [("q6", Q6), ("q1", Q1), ("distinct", QDIST)]

    # CPU xeval baseline (store/localstore/local_region.go equivalent)
    cpu = {}
    for name, sql in configs:
        cpu_s, cpu_results = timed_runs(session, sql, runs)
        cpu[name] = (cpu_s, cpu_results)

    # TPU coprocessor
    store.set_client(TpuClient(store))
    tpu_session = Session(store)
    tpu_session.execute("use tpch")
    tpu_client = store.get_client()
    speedups = []
    tpu_rps_all = []
    for name, sql in configs:
        before = (tpu_client.stats["tpu_requests"],
                  tpu_client.stats["cpu_fallbacks"])
        tpu_s, tpu_results = timed_runs(tpu_session, sql, runs)
        assert tpu_client.stats["tpu_requests"] > before[0], \
            f"{name}: never reached the TPU engine"
        assert tpu_client.stats["cpu_fallbacks"] == before[1], \
            f"{name}: fell back to the CPU engine"
        cpu_s, cpu_results = cpu[name]
        check_parity(name, cpu_results[0], tpu_results[0])
        cpu_rps, tpu_rps = n_rows / cpu_s, n_rows / tpu_s
        speedups.append(tpu_rps / cpu_rps)
        tpu_rps_all.append(tpu_rps)
        print(f"# {name}: cpu {cpu_s:.3f}s/run ({cpu_rps:,.0f} rows/s)  "
              f"tpu {tpu_s:.4f}s/run ({tpu_rps:,.0f} rows/s)  "
              f"speedup {tpu_rps / cpu_rps:.1f}x", file=sys.stderr)

    client = store.get_client()
    assert client.stats["tpu_requests"] > 0, "TPU engine was never used"

    # config 5: Q1 with the mesh client — partial aggregates combined over
    # the device axis (psum/pmin/pmax); on single-chip hardware this runs
    # with axis size 1, under the test env with 8 virtual devices
    import jax
    from tidb_tpu.parallel import CoprMesh
    mesh_client = TpuClient(store, mesh=CoprMesh())
    store.set_client(mesh_client)
    mesh_session = Session(store)
    mesh_session.execute("use tpch")
    mesh_s, mesh_results = timed_runs(mesh_session, Q1, runs)
    check_parity("q1_mesh", cpu["q1"][1][0], mesh_results[0])
    assert mesh_client.stats["tpu_requests"] > 0, "mesh engine never used"
    print(f"# q1_mesh ({len(jax.devices())} devices): {mesh_s:.4f}s/run "
          f"({n_rows / mesh_s:,.0f} rows/s)", file=sys.stderr)

    geo_rps = math.exp(sum(math.log(x) for x in tpu_rps_all)
                       / len(tpu_rps_all))
    geo_speedup = math.exp(sum(math.log(x) for x in speedups)
                           / len(speedups))
    print(json.dumps({
        "metric": "tpch_geomean_rows_per_sec_tpu",
        "value": round(geo_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(geo_speedup, 2),
    }))


if __name__ == "__main__":
    main()
