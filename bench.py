"""Headline benchmark: TPC-H-shaped configs from BASELINE.md, TPU
coprocessor vs the CPU xeval baseline, through the FULL engine stack
(SQL → plan → pushdown → coprocessor).

Configs (BASELINE.md):
  2. Q6  — scan + 3-predicate filter + single sum, no group-by
  3. Q1  — scan + filter + 8 aggregates GROUP BY 2 cols
  4. count(distinct l_orderkey) — distinct kernel
  5. Q1 via the device mesh (region-sharded partial-agg combine)

Measurement honesty (round 4): on the axon-tunneled chip, timings taken
BEFORE the first device→host transfer are meaningless — block_until_ready
returns optimistically (experiments/exp_axon_prims.py). A database's
steady state is inherently post-D2H (every query reads its result), so
this bench deliberately performs one tiny D2H right after JAX init
("poisons" the tunnel into its synchronous mode) and then measures
EVERYTHING in that world:

  - device kernel   = dispatch + block_until_ready on resident planes
                      (real compute + one ~33 ms tunnel round trip)
  - e2e             = full SQL stack, result decode included
  - hbm_peak_gbps   = bandwidth of a pure jnp.sum sweep over a resident
                      f64 plane — the roofline the kernels are judged
                      against; per-config fraction is reported

Scale strategy (honest accounting at 10M+ rows): a BENCH_BASE_ROWS store
is generated through the real write path, then replicated at the KV level
(handle-shifted copies of the encoded rows) up to BENCH_ROWS. The CPU
xeval baseline is timed on the base store (its per-row cost is linear; 1M
base rows keep the extrapolation factor at 10×). Parity is checked EXACTLY
via the replication algebra: count/sum scale by the copy factor,
avg/min/max are invariant, and count(distinct l_orderkey) is invariant
(copies duplicate orderkeys).

Prints per-config lines to stderr and ONE JSON line to stdout:

    {"metric": "tpch_geomean_rows_per_sec_tpu", "value": ...,
     "unit": "rows/s", "vs_baseline": <geomean speedup>, ...extras}

Environment:
    BENCH_ROWS        total lineitem rows for the TPU engine (default 10.2M)
    BENCH_BASE_ROWS   generated base rows / CPU-baseline rows (default 1.02M)
    BENCH_RUNS        timed repetitions (default 3)

`--smoke` runs the same code paths at tiny, CPU-safe sizes (~25k rows,
1 run, no crossover sweep / 1 GB HBM sweep) — tests/test_bench_smoke.py
runs it in tier-1 so bench-path regressions fail fast.
"""

from __future__ import annotations

import json
import math
import os
import random
import sys
import time


Q6 = ("select sum(l_extendedprice * l_discount) from lineitem "
      "where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01' "
      "and l_discount >= 0.05 and l_discount <= 0.07 "
      "and l_quantity < 24")

Q1 = ("select l_returnflag, l_linestatus, "
      "sum(l_quantity), sum(l_extendedprice), "
      "sum(l_extendedprice * (1 - l_discount)), "
      "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)), "
      "avg(l_quantity), avg(l_extendedprice), avg(l_discount), count(*) "
      "from lineitem "
      "where l_shipdate <= date '1998-12-01' - interval 90 day "
      "group by l_returnflag, l_linestatus "
      "order by l_returnflag, l_linestatus")

QDIST = "select count(distinct l_orderkey) from lineitem"

# referenced lineitem columns per config (for the HBM-bytes figure):
# value plane 8B + validity 1B per column per row
REFERENCED_COLS = {"q6": 4, "q1": 7, "distinct": 1}


def build_store(n_rows: int):
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum, datum_from_py
    from tidb_tpu.types.time_types import parse_time

    store = new_store(f"memory://bench{n_rows}")
    s = Session(store)
    s.execute("create database tpch")
    s.execute("use tpch")
    s.execute(
        "create table lineitem ("
        " l_id bigint primary key, l_orderkey bigint,"
        " l_quantity double, l_extendedprice double, l_discount double,"
        " l_tax double, l_returnflag varchar(1), l_linestatus varchar(1),"
        " l_shipdate date)")
    tbl = s.info_schema().table_by_name("tpch", "lineitem")

    rng = random.Random(42)
    flags = ["A", "N", "R"]
    statuses = ["F", "O"]
    base = parse_time("1992-01-01")
    import datetime as dt
    from tidb_tpu.types.time_types import Time
    date_tp = tbl.info.columns[8].field_type.tp

    # generate rows first so the load metric measures the ENGINE write
    # path (add_record + membuffer + codec + commit), not random()
    rows = []
    for i in range(1, n_rows + 1):
        ship = base.dt + dt.timedelta(days=rng.randint(0, 2500))
        rows.append([
            Datum.i64(i),
            Datum.i64((i + 3) // 4),
            Datum.f64(float(rng.randint(1, 50))),
            Datum.f64(round(rng.uniform(900.0, 105000.0), 2)),
            Datum.f64(round(rng.uniform(0.0, 0.1), 2)),
            Datum.f64(round(rng.uniform(0.0, 0.08), 2)),
            Datum.string(rng.choice(flags)),
            Datum.string(rng.choice(statuses)),
            datum_from_py(Time(ship, date_tp)),
        ])

    t0 = time.time()
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + batch],
                        skip_unique_check=True)
        txn.commit()
    load_s = time.time() - t0
    return store, s, tbl, load_s


def replicate_store(base_store, base_session, tbl, n_base: int,
                    factor: int):
    """Clone the base store's lineitem rows (factor-1) more times with
    shifted handles, straight through commit_txn — scale data without
    paying per-datum encode again."""
    from tidb_tpu import tablecodec as tc
    from tidb_tpu.session import Session, new_store

    big = new_store(f"memory://bench_big{n_base * factor}")
    s = Session(big)
    s.execute("create database tpch")
    s.execute("use tpch")
    # same DDL → same column ids (fresh store, deterministic id alloc)
    s.execute(
        "create table lineitem ("
        " l_id bigint primary key, l_orderkey bigint,"
        " l_quantity double, l_extendedprice double, l_discount double,"
        " l_tax double, l_returnflag varchar(1), l_linestatus varchar(1),"
        " l_shipdate date)")
    big_tbl = s.info_schema().table_by_name("tpch", "lineitem")

    snap = base_store.get_snapshot()
    start_k, end_k = tc.encode_record_range(tbl.id)
    pairs = [(k, v) for k, v in snap.iterate(start_k, end_k)]
    t0 = time.time()
    chunk = 250_000
    for copy in range(factor):
        shift = copy * n_base
        muts = []
        for k, v in pairs:
            _, handle = tc.decode_row_key(k)
            muts.append((tc.encode_row_key(big_tbl.id, handle + shift), v))
            if len(muts) >= chunk:
                big.commit_txn(big.current_version(), muts)
                muts = []
        if muts:
            big.commit_txn(big.current_version(), muts)
    rep_s = time.time() - t0
    return big, s, rep_s


def poison_tunnel():
    """Force the axon tunnel into its post-D2H (synchronous) mode so every
    subsequent timing is the steady-state truth. A no-op elsewhere."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    np.asarray(jnp.zeros(8))
    jax.block_until_ready(jnp.zeros(8))


def measure_hbm_peak(runs: int = 3) -> float:
    """Achieved GB/s of the simplest possible HBM sweep (summing a
    resident 1 GB f64 plane) in the post-D2H world — the per-chip roofline
    the query kernels are judged against. The fixed sweep size amortizes
    the ~130 ms dispatch+readback overhead that masquerades as bandwidth
    on smaller working sets."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    elems = 128 << 20          # 1 GB — fixed ~130 ms dispatch overhead
    #                            amortizes; larger sweeps cost H2D setup
    plane = jnp.ones(elems, jnp.float64)
    f = jax.jit(lambda v: jnp.sum(v))
    jax.block_until_ready(f(plane))
    t0 = time.time()
    for _ in range(runs):
        np.asarray(f(plane))   # result readback = certified completion
    dt = (time.time() - t0) / runs
    return elems * 8 / dt / 1e9


def kernel_probe(client, runs: int):
    """Device-kernel timing: re-dispatch the EXACT jitted callable +
    device-resident planes the client's most recent e2e query ran
    (TpuClient._last_dispatch). No plan/request reconstruction — the probe
    cannot drift from the real execution path (round-4 weak #1: a
    duplicated harness emitted a 29.2 s "kernel" inside a 0.10 s query).
    Runs AFTER poison_tunnel(): the figure is dispatch + compute + the
    packed-output readback, i.e. the same device round trip every query
    pays. Returns None when the last query used no single-chip aggregate
    kernel (ranked path, mesh, filter)."""
    import numpy as np

    if client._last_dispatch is None:
        return None
    jitted, planes, live = client._last_dispatch
    np.asarray(jitted(planes, live))   # warm (already compiled by e2e)
    samples = []
    for _ in range(max(runs, 3)):
        # the result D2H is the only certified completion point on this
        # platform (block_until_ready can return early post-D2H)
        t0 = time.time()
        np.asarray(jitted(planes, live))
        samples.append(time.time() - t0)
    # min over samples: a fixed dispatch cost with one-sided noise (GC
    # pause, page fault under suite load) — a single spiked sample must
    # not fail the kernel<=e2e containment assert at runs=1
    return min(samples)


def bytes_matched_sweep(elems: int, runs: int) -> float:
    """Seconds for the simplest possible reduction over a plane of the
    SAME size a config references — the roofline for THAT working set.
    The 1 GB copy-sweep 'peak' is unreachable for small configs on this
    rig (the flat dispatch round trip dominates below ~1 GB: a 10.2M-row
    single-column sweep measures 0.7 GB/s where the 1 GB sweep measures
    9.7 — experiments/exp_distinct_r5.py), so fraction-of-peak understated
    small configs by up to 14x (round-4 weak #4: distinct's '7% of peak'
    kernel is in fact AT its bytes-matched roofline)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    plane = jnp.ones(elems, jnp.float64)
    f = jax.jit(lambda v: jnp.sum(v))
    np.asarray(f(plane))
    t0 = time.time()
    for _ in range(runs):
        np.asarray(f(plane))
    return (time.time() - t0) / runs


def measure_crossover(store, runs: int):
    """Empirical CPU/device crossover on a simple SUM over growing
    handle-range subsets — the measurement behind the dispatch-floor
    default (round-4 weak #2: every routed query paid the flat ~110 ms
    device round trip; the floor routes scans below the crossover to the
    CPU engine). Device side runs with the floor disabled so every size
    actually dispatches. Restores the store's client before returning."""
    from tidb_tpu.ops import TpuClient
    from tidb_tpu.session import Session

    old_client = store.get_client()
    sizes = [1_000, 4_000, 16_000, 64_000, 256_000]
    sweep = {}
    for engine in ("cpu", "tpu"):
        if engine == "cpu":
            factory = getattr(store, "copr_cpu_client", None)
            if factory is not None:
                store.set_client(factory())
        else:
            store.set_client(TpuClient(store, dispatch_floor_rows=0))
        sess = Session(store)
        sess.execute("use tpch")
        times = []
        for n in sizes:
            sql = f"select sum(l_quantity) from lineitem where l_id <= {n}"
            t, _ = timed_runs(sess, sql, max(1, runs - 1))
            times.append(t)
        sweep[engine] = times
    store.set_client(old_client)
    for n, c, t in zip(sizes, sweep["cpu"], sweep["tpu"]):
        print(f"# crossover sweep {n:>7} rows: cpu {c * 1000:8.2f} ms  "
              f"device {t * 1000:8.2f} ms", file=sys.stderr)
    # first sign change of (cpu - device), linearly interpolated between
    # the bracketing sizes (the sweep is geometric, so the first winning
    # size alone would overstate the crossover by up to 4x)
    for i, (c, t) in enumerate(zip(sweep["cpu"], sweep["tpu"])):
        if t < c:
            if i == 0:
                return sizes[0]
            c0, t0 = sweep["cpu"][i - 1], sweep["tpu"][i - 1]
            d0, d1 = t0 - c0, t - c     # positive → device slower
            frac = d0 / (d0 - d1) if d0 != d1 else 0.0
            return int(sizes[i - 1] + frac * (sizes[i] - sizes[i - 1]))
    return -1


def numpy_oracle_time(name: str, batch, col_id: dict, runs: int):
    """Vectorized host oracle over the SAME packed planes the device
    sees: filter masks + bincount aggregates in numpy. This is the
    honest CPU baseline for the speedup headline (round-4 weak #3: the
    per-row Python xeval understates any real CPU engine by ~2 orders,
    inflating vs_baseline ~100x). Returns seconds/run, None when the
    batch shape is unexpected."""
    import numpy as np
    from tidb_tpu.types.time_types import parse_time

    if batch is None:
        return None
    cols = batch.columns
    live = np.asarray(batch.row_mask()) if hasattr(batch, "row_mask") \
        else np.ones(batch.capacity, bool)

    def plane(cname):
        cd = cols[col_id[cname]]
        return np.asarray(cd.values), np.asarray(cd.valid) & live

    def packed(day: str) -> int:
        return parse_time(day).to_packed_int()

    if name == "q6":
        ship, ship_ok = plane("l_shipdate")
        disc, disc_ok = plane("l_discount")
        qty, qty_ok = plane("l_quantity")
        price, price_ok = plane("l_extendedprice")
        lo, hi = packed("1994-01-01"), packed("1995-01-01")

        def run():
            m = (ship_ok & disc_ok & qty_ok & price_ok
                 & (ship >= lo) & (ship < hi)
                 & (disc >= 0.05) & (disc <= 0.07) & (qty < 24))
            return float(np.sum(price[m] * disc[m]))
    elif name == "q1":
        ship, ship_ok = plane("l_shipdate")
        qty, _ = plane("l_quantity")
        price, _ = plane("l_extendedprice")
        disc, _ = plane("l_discount")
        tax, _ = plane("l_tax")
        rf, _ = plane("l_returnflag")
        ls, _ = plane("l_linestatus")
        cutoff = packed("1998-09-03")   # <= '1998-09-02'
        stride = int(ls.max()) + 1
        nseg = (int(rf.max()) + 1) * stride + 1

        def run():
            m = ship_ok & (ship < cutoff)
            g = (rf * stride + ls)[m]
            one_disc = 1.0 - disc[m]
            outs = [np.bincount(g, weights=w, minlength=nseg)
                    for w in (qty[m], price[m], price[m] * one_disc,
                              price[m] * one_disc * (1.0 + tax[m]),
                              disc[m])]
            outs.append(np.bincount(g, minlength=nseg))
            return outs
    elif name == "distinct":
        okey, okey_ok = plane("l_orderkey")

        def run():
            return int(np.unique(okey[okey_ok]).size)
    else:
        return None

    run()   # warm (allocator, caches)
    t0 = time.time()
    for _ in range(runs):
        run()
    return (time.time() - t0) / runs


def measure_join(n_left: int = 1_000_000, n_right: int = 100_000):
    """Join-operator throughput at the verdict shape (1M probe x 100k
    build) across all three HashJoinExec paths, on pre-materialized rows
    so the figure isolates the JOIN (the e2e query is scan-dominated and
    measures the row-decode path instead):

      device — build/probe kernels + columnar assembly (floor forced 0)
      numpy  — host sort-merge, the below-dispatch-floor route (forced
               by a floor ABOVE the row counts: proves the routing)
      dict   — per-row hash build/probe (the oracle)

    All three must emit identical row counts; device phase times (build /
    probe / emit) come from HashJoinExec.join_stats. Returns a dict of
    figures for the bench JSON."""
    from tidb_tpu import mysqldef as my
    from tidb_tpu.executor import executors
    from tidb_tpu.expression import Column
    from tidb_tpu.plan.plans import Join
    from tidb_tpu.types import Datum
    from tidb_tpu.types.field_type import new_field_type

    class _Rows:
        def __init__(self, rows, width):
            self.rows, self.schema = rows, [None] * width

        def drain(self):
            return self.rows

    ft = new_field_type(my.TypeLonglong)
    lrows = [[Datum.i64(i), Datum.i64(i % n_right)]
             for i in range(n_left)]
    rrows = [[Datum.i64(i), Datum.i64(i * 3)] for i in range(n_right)]

    class _Plan:
        pass

    plan = _Plan()
    plan.eq_conditions = [(Column(ret_type=ft, index=1),
                           Column(ret_type=ft, index=0))]
    plan.right_conditions = []
    plan.left_conditions = []
    plan.other_conditions = []
    plan.join_type = Join.INNER

    def make(label):
        j = executors.HashJoinExec(_Rows(lrows, 2), _Rows(rrows, 2),
                                   plan, None)
        if label == "device":
            j.device_floor = 0
        elif label == "numpy":
            # a floor above both row counts must route to the numpy path
            j.device_floor = max(n_left, n_right) + 1
        else:
            j._vector_tried = True
            rit = iter(rrows)
            j.children[1].next = lambda it=rit: next(it, None)
            lit = iter(lrows)
            j.children[0].next = lambda it=lit: next(it, None)
        return j

    # warm: a FULL drain, not one next() — the first drain pays jit
    # trace+compile for the build/probe buckets AND the native row-
    # assembly warm-up (codecx buffers, allocator growth), so the timed
    # runs below are steady state (BENCH_r05 recorded 333k rows/s with
    # speedup 0.94x vs dict because cold-path costs leaked into the
    # timed window; the sizes themselves already sit above the default
    # tidb_tpu_dispatch_floor so routing is not the variable)
    warm = make("device")
    while warm.next() is not None:
        pass
    times, stats = {}, {}
    for label in ("device", "numpy", "dict"):
        best = None
        for _ in range(2):      # best-of-2: drop scheduler-noise outliers
            j = make(label)
            t0 = time.time()
            n = 0
            while j.next() is not None:
                n += 1
            dt = time.time() - t0
            assert n == n_left, \
                f"{label} join produced {n} rows, expected {n_left}"
            if best is None or dt < best:
                best = dt
                stats[label] = j.join_stats
        times[label] = best
    assert stats["device"].get("path") == "device", stats["device"]
    assert stats["numpy"].get("path") == "numpy", \
        "below-floor join did not take the numpy path"
    dev = stats["device"]
    return {
        "join_rows_per_sec": round(n_left / times["device"], 1),
        "join_speedup_vs_dict": round(times["dict"] / times["device"], 2),
        "join_numpy_rows_per_sec": round(n_left / times["numpy"], 1),
        "join_build_ms": round(dev.get("build_s", 0.0) * 1000, 2),
        "join_probe_ms": round(dev.get("probe_s", 0.0) * 1000, 2),
        "join_emit_ms": round(dev.get("emit_s", 0.0) * 1000, 2),
    }


JOIN_AGG_SQL = ("select count(*), sum(l_extendedprice), avg(l_quantity), "
                "min(d_f), max(l_discount) from lineitem "
                "join dim on l_orderkey = d_k")


def measure_join_e2e(store, n_probe: int, n_dim: int, runs: int,
                     floor=None):
    """scan→join→agg e2e through the full SQL stack, three regimes of the
    same query:

      columnar — coprocessor answers scans with COLUMN PLANES
                 (SelectResponse.columnar), the device join builds and
                 probes straight off them, and the aggregate fuses over
                 the gathered planes: from KV decode to aggregate
                 emission no row is ever materialized. Asserts
                 distsql.columnar_fallbacks == 0 over the timed window.
      row path — tidb_tpu_columnar_scan off: the PR-1 regime (scan rows
                 chunk-encoded, decoded, key planes re-extracted per
                 row), the speedup denominator.
      oracle   — device join off too: numpy join + per-row aggregate
                 loop, the parity check.

    Returns the bench-JSON figure dict."""
    from tidb_tpu import metrics
    from tidb_tpu.executor import fused_agg
    from tidb_tpu.ops import TpuClient
    from tidb_tpu.session import Session

    s = Session(store)
    s.execute("use tpch")
    s.execute("create table if not exists dim ("
              "d_k bigint primary key, d_f double)")
    if not s.execute("select count(*) from dim")[0].values()[0][0]:
        batch = 20000
        for start in range(1, n_dim + 1, batch):
            vals = ", ".join(f"({k}, {k % 97}.5)"
                             for k in range(start, min(start + batch,
                                                       n_dim + 1)))
            s.execute(f"insert into dim values {vals}")

    old_client = store.get_client()
    client = TpuClient(store, dispatch_floor_rows=floor)
    store.set_client(client)
    hits = metrics.counter("distsql.columnar_hits")
    fbs = metrics.counter("distsql.columnar_fallbacks")
    try:
        sess = Session(store)
        sess.execute("use tpch")
        before = fused_agg.stats["fused"]
        sess.execute(JOIN_AGG_SQL)        # warm (pack + compile)
        h0, f0 = hits.value, fbs.value
        t0 = time.time()
        results = []
        for _ in range(runs):
            results.append(sess.execute(JOIN_AGG_SQL)[0].values())
        t_col = (time.time() - t0) / runs
        d_hits, d_fbs = hits.value - h0, fbs.value - f0
        fused = fused_agg.stats["fused"] > before
        scan_columnar = d_hits > 0 and d_fbs == 0

        # PR-1 row-materializing path: columnar channel off, device join on
        client.columnar_scan = False
        sess.execute(JOIN_AGG_SQL)        # warm the row regime
        t0 = time.time()
        for _ in range(runs):
            row_results = sess.execute(JOIN_AGG_SQL)[0].values()
        t_row = (time.time() - t0) / runs

        # oracle: device join off too (numpy join + row-loop aggregate)
        client.device_join = False
        oracle = sess.execute(JOIN_AGG_SQL)[0].values()
        for name, got_rows in (("columnar", results[0]),
                               ("rowpath", row_results)):
            assert len(got_rows) == len(oracle), \
                f"join_e2e {name} parity: {len(got_rows)} vs {len(oracle)}"
            for got, want in zip(got_rows, oracle):
                assert len(got) == len(want), \
                    f"join_e2e {name} parity: {len(got)} vs {len(want)} cols"
                for a, b in zip(got, want):
                    assert _close(float(a), float(b)), \
                        f"join_e2e {name} parity: {a} != {b}"
        return {
            "join_agg_s": round(t_col, 4),
            "join_agg_fused": fused,
            "join_e2e_rows_per_sec": round(n_probe / t_col, 1),
            "join_e2e_speedup_vs_rowpath": round(t_row / t_col, 2),
            "scan_columnar": scan_columnar,
            "columnar_hits": d_hits,
            "columnar_fallbacks": d_fbs,
        }
    finally:
        store.set_client(old_client)


REGION_FANOUT_SQL = ("select count(*), sum(f_v), min(f_v), max(d_f) "
                     "from fan join fdim on f_k = d_k")


def measure_region_fanout(n_rows: int, n_dim: int, n_regions: int,
                          runs: int):
    """scan→join→agg e2e ACROSS a real per-region fan-out: a cluster
    store split into n_regions, each region answering the hinted scan
    with a ColumnarScanResult PARTIAL (copr.columnar_region), the numpy
    join building off the stacked planes, and the fused aggregate
    merging per-region partial states device-side (one combine, one
    readback). The row-protocol regime (kill switch) is the speedup
    denominator. Asserts columnar_fallbacks == 0 and ≥ n_regions
    partials over the timed window."""
    from tidb_tpu import metrics, tablecodec as tc
    from tidb_tpu.executor import fused_agg
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum

    store = new_store(f"cluster://3/benchfan{n_rows}")
    s = Session(store)
    s.execute("create database fan")
    s.execute("use fan")
    s.execute("create table fan (f_id bigint primary key, f_k bigint, "
              "f_v bigint)")
    s.execute("create table fdim (d_k bigint primary key, d_f double)")
    tbl = s.info_schema().table_by_name("fan", "fan")
    rows = [[Datum.i64(i), Datum.i64(i % n_dim), Datum.i64(i * 3)]
            for i in range(1, n_rows + 1)]
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + batch],
                        skip_unique_check=True)
        txn.commit()
    for start in range(0, n_dim, batch):
        vals = ", ".join(f"({k}, {k % 97}.5)"
                         for k in range(start, min(start + batch, n_dim)))
        s.execute(f"insert into fdim values {vals}")
    step = max(n_rows // n_regions, 1)
    store.cluster.split_keys(
        [tc.encode_row_key(tbl.info.id, step * i + 1)
         for i in range(1, n_regions)])

    hits = metrics.counter("distsql.columnar_hits")
    fbs = metrics.counter("distsql.columnar_fallbacks")
    parts = metrics.counter("distsql.columnar_partials")
    # no pushed-down WHERE on this shape: the filter tier must stay out
    # of the way (0 batched filter dispatches across the timed window)
    fdisp = metrics.counter("copr.filter.batched_dispatches")
    sess = Session(store)
    sess.execute("use fan")
    # the fan-out figure measures the PACK PATH (comparable across bench
    # rounds): the plane cache is disabled for this phase so every timed
    # run re-packs every region; the repeat case below measures the
    # cache against exactly this regime
    sess.execute("set global tidb_tpu_plane_cache = 0")
    sess.execute(REGION_FANOUT_SQL)       # warm (jit)
    h0, f0, p0 = hits.value, fbs.value, parts.value
    c0 = fused_agg.stats["partial_combines"]
    fd0 = fdisp.value
    t0 = time.time()
    for _ in range(runs):
        col_results = sess.execute(REGION_FANOUT_SQL)[0].values()
    t_col = (time.time() - t0) / runs
    d_hits, d_fbs = hits.value - h0, fbs.value - f0
    d_parts = parts.value - p0
    combines = fused_agg.stats["partial_combines"] - c0
    assert d_fbs == 0, \
        f"region fan-out run counted {d_fbs} columnar fallbacks"
    assert d_parts >= n_regions * runs, \
        f"only {d_parts} columnar partials across {n_regions} regions"
    assert combines > 0, \
        "fused aggregate never merged per-region partials device-side"
    assert fdisp.value - fd0 == 0, \
        (f"WHERE-less fan-out ran {fdisp.value - fd0} batched filter "
         f"dispatches — the filter tier fired without a predicate")

    # row-protocol regime across the SAME fan-out (the kill switch path)
    client = store.get_client()
    client.columnar_scan = False
    try:
        sess.execute(REGION_FANOUT_SQL)   # warm the row regime
        t0 = time.time()
        for _ in range(runs):
            row_results = sess.execute(REGION_FANOUT_SQL)[0].values()
        t_row = (time.time() - t0) / runs
    finally:
        client.columnar_scan = True
    for got, want in zip(col_results[0], row_results[0]):
        assert _close(float(got), float(want)), \
            f"region fan-out parity: {got} != {want}"

    # REPEAT fan-out regime: the dashboard/serving shape the per-region
    # plane cache exists for. The cold denominator IS the main phase
    # above (cache disabled: every run re-packed every region); warm =
    # cache on (every region answers from its pinned planes; hits >=
    # regions per run). Both regimes and the row protocol must agree
    # exactly.
    pc_hits = metrics.counter("copr.plane_cache.hits")
    t_cold, cold_results = t_col, col_results
    sess.execute("set global tidb_tpu_plane_cache = 1")
    sess.execute(REGION_FANOUT_SQL)       # populate the cache
    h0, f0 = pc_hits.value, fbs.value
    t0 = time.time()
    for _ in range(runs):
        warm_results = sess.execute(REGION_FANOUT_SQL)[0].values()
    t_warm = (time.time() - t0) / runs
    d_pc_hits = pc_hits.value - h0
    assert fbs.value == f0, \
        "plane-cache repeat run counted columnar fallbacks"
    assert d_pc_hits >= n_regions * runs, \
        (f"repeat fan-out hit the plane cache only {d_pc_hits}x across "
         f"{n_regions} regions x {runs} runs")
    for got, want in zip(warm_results[0], cold_results[0]):
        assert _close(float(got), float(want)), \
            f"plane-cache parity (warm vs cold): {got} != {want}"
    for got, want in zip(warm_results[0], row_results[0]):
        assert _close(float(got), float(want)), \
            f"plane-cache parity (warm vs row protocol): {got} != {want}"
    return {
        "region_fanout_rows_per_sec": round(n_rows / t_col, 1),
        "region_fanout_speedup_vs_rowpath": round(t_row / t_col, 2),
        "region_fanout_regions": n_regions,
        "region_fanout_fallbacks": d_fbs,
        "columnar_partials": d_parts,
        "region_partial_combines": combines,
        "region_fanout_repeat_rows_per_sec": round(n_rows / t_warm, 1),
        "region_fanout_repeat_speedup_vs_cold": round(t_cold / t_warm, 2),
        "plane_cache_hits": d_pc_hits,
        **trace_summary(sess, REGION_FANOUT_SQL),
        **workload_summary(store, sess, n_regions),
    }


OVERSIZED_SQL = ("select count(*), sum(f_v), min(f_v), max(d_f) "
                 "from fan join odim on f_k = d_k")


def measure_join_oversized(n_rows: int, n_dim: int, n_regions: int,
                           runs: int):
    """Out-of-core join regime (HBM governance tier): the BUILD side
    (odim) is sized ~4x the configured `tidb_tpu_hbm_budget_bytes`, so
    every join over the 4-region cluster store takes the
    radix-partitioned grace-hash route — split by key radix, run in
    passes through the existing kernels, merged bit-identically to the
    single-pass order. Asserts >= 2 partitioned passes on the counters,
    zero columnar fallbacks, and row-for-row parity against the
    unpartitioned oracle (budget 0 — the kill switch) inside the bench
    itself."""
    from tidb_tpu import metrics, tablecodec as tc
    from tidb_tpu.ops import membudget
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum

    store = new_store(f"cluster://3/benchov{n_rows}")
    s = Session(store)
    s.execute("create database ov")
    s.execute("use ov")
    s.execute("create table fan (f_id bigint primary key, f_k bigint, "
              "f_v bigint)")
    s.execute("create table odim (d_k bigint primary key, d_f double)")
    tbl = s.info_schema().table_by_name("ov", "fan")
    rows = [[Datum.i64(i), Datum.i64(i % n_dim), Datum.i64(i * 3)]
            for i in range(1, n_rows + 1)]
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + batch],
                        skip_unique_check=True)
        txn.commit()
    dtbl = s.info_schema().table_by_name("ov", "odim")
    drows = [[Datum.i64(k), Datum.f64(k % 97 + 0.5)]
             for k in range(n_dim)]
    for start in range(0, n_dim, batch):
        txn = store.begin()
        dtbl.add_records(txn, drows[start:start + batch],
                         skip_unique_check=True)
        txn.commit()
    step = max(n_rows // n_regions, 1)
    store.cluster.split_keys(
        [tc.encode_row_key(tbl.info.id, step * i + 1)
         for i in range(1, n_regions)])

    sess = Session(store)
    sess.execute("use ov")
    sess.execute("set global tidb_tpu_dispatch_floor = 0")
    # build side ~4x the budget: the ledger must partition every run
    budget = max(membudget.build_bytes_estimate(n_dim) // 4, 4096)
    pj = metrics.counter("copr.partitioned_joins")
    pp = metrics.counter("copr.partitioned_passes")
    fbs = metrics.counter("distsql.columnar_fallbacks")
    try:
        sess.execute(f"set global tidb_tpu_hbm_budget_bytes = {budget}")
        sess.execute(OVERSIZED_SQL)       # warm (pack + compile)
        j0, p0, f0 = pj.value, pp.value, fbs.value
        t0 = time.time()
        for _ in range(runs):
            part_results = sess.execute(OVERSIZED_SQL)[0].values()
        t_part = (time.time() - t0) / runs
        d_joins, d_passes = pj.value - j0, pp.value - p0
        d_fbs = fbs.value - f0
        assert d_joins >= runs, \
            (f"oversized build side took the partitioned route only "
             f"{d_joins}x in {runs} runs")
        assert d_passes >= 2 * runs, \
            (f"only {d_passes} partitioned passes across {runs} runs — "
             "the out-of-core join did not split")
        assert d_fbs == 0, \
            f"oversized join run counted {d_fbs} columnar fallbacks"
        # parity oracle: budget 0 pins the unpartitioned single-pass
        # route — answers must match row for row
        sess.execute("set global tidb_tpu_hbm_budget_bytes = 0")
        j1 = pj.value
        oracle = sess.execute(OVERSIZED_SQL)[0].values()
        assert pj.value == j1, \
            "budget 0 (kill switch) still took the partitioned route"
        for got, want in zip(part_results[0], oracle[0]):
            assert _close(float(got), float(want)), \
                f"oversized join parity: {got} != {want}"
    finally:
        sess.execute("set global tidb_tpu_hbm_budget_bytes = 'auto'")
    return {
        "oversized_join_rows_per_sec": round(n_rows / t_part, 1),
        "oversized_join_passes": d_passes,
        "oversized_join_partitions": d_passes // max(d_joins, 1),
        "oversized_join_fallbacks": d_fbs,
        "oversized_join_budget_bytes": budget,
        "oversized_join_regions": n_regions,
    }


SPILL_SORT_SQL = ("select s_id, s_v from sp join spd on s_k = d_k "
                  "order by s_v desc, s_id")
SPILL_WINDOW_SQL = ("select s_id, rank() over "
                    "(partition by s_w order by s_v) from sp")
SPILL_GROUPBY_SQL = "select s_g, sum(s_v), count(*) from sp group by s_g"


def measure_spill(n_rows: int, n_dim: int, n_regions: int, runs: int):
    """Out-of-core everything regime (HBM governance tier): the HBM
    budget is set to a fraction of every operator's working set, so over
    the 4-region cluster store (a) the join→ORDER BY sorts its key
    planes through the range-partitioned external sort, (b) the window
    function rides the same external sort plus the segment-scan kernel,
    and (c) the high-NDV group-by runs its states table in key-radix-
    partitioned passes. Asserts the partitioned routes actually engaged
    (>= 2 passes on the counters), zero fallbacks of any kind, and
    row-for-row parity against the budget-0 kill-switch oracle inside
    the bench itself."""
    from tidb_tpu import metrics, tablecodec as tc
    from tidb_tpu.ops import extsort, membudget
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum

    store = new_store(f"cluster://3/benchsp{n_rows}")
    s = Session(store)
    s.execute("create database sp")
    s.execute("use sp")
    s.execute("create table sp (s_id bigint primary key, s_k bigint, "
              "s_g bigint, s_w bigint, s_v bigint)")
    s.execute("create table spd (d_k bigint primary key, d_f double)")
    tbl = s.info_schema().table_by_name("sp", "sp")
    # s_g: high-NDV group key (~n/2 distinct), s_w: 64 window
    # partitions, s_v: pseudo-shuffled sort/agg payload
    rows = [[Datum.i64(i), Datum.i64(i % n_dim),
             Datum.i64((i * 7919) % max(n_rows // 2, 1)),
             Datum.i64(i % 64),
             Datum.i64((i * 2654435761) % 1000003)]
            for i in range(1, n_rows + 1)]
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + batch],
                        skip_unique_check=True)
        txn.commit()
    dtbl = s.info_schema().table_by_name("sp", "spd")
    drows = [[Datum.i64(k), Datum.f64(k % 89 + 0.25)] for k in range(n_dim)]
    for start in range(0, n_dim, batch):
        txn = store.begin()
        dtbl.add_records(txn, drows[start:start + batch],
                         skip_unique_check=True)
        txn.commit()
    step = max(n_rows // n_regions, 1)
    store.cluster.split_keys(
        [tc.encode_row_key(tbl.info.id, step * i + 1)
         for i in range(1, n_regions)])

    sess = Session(store)
    sess.execute("use sp")
    sess.execute("set global tidb_tpu_dispatch_floor = 0")
    # budget a fraction of the sort working set (60 B/row: two
    # (i64 value, int8 null) key levels, x2 partition scratch, +24
    # order/perm) — sized so each range partition stays at or above
    # SORT_DEVICE_FLOOR rows and still takes a device pass. The cached
    # region planes PIN ledger bytes for the life of the store, so the
    # budget rides on top of the pinned residue (measured after warm,
    # when every plane this workload touches is packed).
    pieces = min(4, max(2, n_rows // extsort.SORT_DEVICE_FLOOR))
    sort_est = 60 * n_rows
    c_sorts = metrics.counter("copr.spill.sorts")
    c_spass = metrics.counter("copr.spill.sort_passes")
    c_plane = metrics.counter("copr.spill.plane_sorts")
    c_gbys = metrics.counter("copr.spill.groupbys")
    c_gpass = metrics.counter("copr.spill.groupby_passes")
    c_wpass = metrics.counter("copr.spill.window_passes")
    c_esc = metrics.counter("copr.spill.escalations")
    fbs = metrics.counter("distsql.columnar_fallbacks")
    degr = [metrics.counter(f"copr.degraded_spill_{k}")
            for k in ("sort", "groupby", "window")]
    legs = (SPILL_SORT_SQL, SPILL_WINDOW_SQL, SPILL_GROUPBY_SQL)
    try:
        warm_budget = 16 * sort_est
        sess.execute(f"set global tidb_tpu_hbm_budget_bytes = "
                     f"{warm_budget}")
        for sql in legs:                  # warm (pack + pin + compile)
            sess.execute(sql)
        pinned = warm_budget - membudget.headroom()
        budget = pinned + int(sort_est / pieces * 1.15)
        sess.execute(f"set global tidb_tpu_hbm_budget_bytes = {budget}")
        s0, sp0, pl0 = c_sorts.value, c_spass.value, c_plane.value
        g0, gp0, w0 = c_gbys.value, c_gpass.value, c_wpass.value
        e0, f0 = c_esc.value, fbs.value
        d0 = [c.value for c in degr]
        t0 = time.time()
        for _ in range(runs):
            sort_rows = sess.execute(SPILL_SORT_SQL)[0].values()
            win_rows = sess.execute(SPILL_WINDOW_SQL)[0].values()
            gby_rows = sess.execute(SPILL_GROUPBY_SQL)[0].values()
        t_spill = (time.time() - t0) / runs
        d_sorts, d_spass = c_sorts.value - s0, c_spass.value - sp0
        d_plane = c_plane.value - pl0
        d_gbys, d_gpass = c_gbys.value - g0, c_gpass.value - gp0
        d_wpass, d_esc = c_wpass.value - w0, c_esc.value - e0
        d_fbs = (fbs.value - f0) \
            + sum(c.value - v for c, v in zip(degr, d0))
        assert d_plane >= runs, \
            (f"only {d_plane} plane sorts in {runs} runs — ORDER BY "
             "never rode the columnar external sort")
        assert d_sorts >= 2 * runs, \
            (f"only {d_sorts} over-headroom sorts in {runs} runs — the "
             "external sort did not partition")
        assert d_spass >= 2 * runs, \
            f"only {d_spass} device sort passes across {runs} runs"
        assert d_gbys >= runs and d_gpass >= 2 * runs, \
            (f"high-NDV group-by spilled {d_gbys}x / {d_gpass} passes "
             f"in {runs} runs — the states table did not partition")
        assert d_wpass >= runs, \
            f"only {d_wpass} window scan passes across {runs} runs"
        assert d_fbs == 0, \
            f"spill regime counted {d_fbs} fallbacks/degraded rungs"
        # parity oracle: budget 0 pins the host rungs (np.lexsort, the
        # unpartitioned states dispatch, the numpy window scan) —
        # answers must match row for row
        sess.execute("set global tidb_tpu_hbm_budget_bytes = 0")
        s1 = c_sorts.value
        o_sort = sess.execute(SPILL_SORT_SQL)[0].values()
        o_win = sess.execute(SPILL_WINDOW_SQL)[0].values()
        o_gby = sess.execute(SPILL_GROUPBY_SQL)[0].values()
        assert c_sorts.value == s1, \
            "budget 0 (kill switch) still took the partitioned sort"
        assert list(sort_rows) == list(o_sort), \
            "external sort parity vs kill-switch oracle"
        assert list(win_rows) == list(o_win), \
            "window function parity vs kill-switch oracle"
        # spilled states passes may emit groups in partition order —
        # group-by output order is unspecified, compare as sets of rows
        assert sorted(map(tuple, gby_rows)) == sorted(map(tuple, o_gby)), \
            "spilling group-by parity vs kill-switch oracle"
    finally:
        sess.execute("set global tidb_tpu_hbm_budget_bytes = 'auto'")
    d_passes = d_spass + d_gpass + d_wpass
    assert d_passes >= 2, \
        f"only {d_passes} partitioned passes — nothing spilled"
    return {
        "spill_rows_per_sec": round(3 * n_rows / t_spill, 1),
        "spill_passes": d_passes,
        "spill_sort_passes": d_spass,
        "spill_groupby_passes": d_gpass,
        "spill_window_passes": d_wpass,
        "spill_escalations": d_esc,
        "spill_fallbacks": d_fbs,
        "spill_budget_bytes": budget,
        "spill_regions": n_regions,
    }


Q1_PUSHDOWN_SQL = (
    "select l_flag, l_status, sum(l_qty), sum(l_price), avg(l_qty), "
    "avg(l_price), avg(l_disc), count(*) from lineitem "
    "where l_ship <= 180 group by l_flag, l_status "
    "order by l_flag, l_status")


def measure_q1_pushdown(n_rows: int, n_regions: int, runs: int):
    """TPC-H-q1-shaped aggregate PUSHDOWN over the 4-region cluster
    store: the planner pushes the partial-row aggregate, every region
    answers with grouped partial STATES (ColumnarAggStates — states,
    not rows, cross the wire), and the FINAL aggregate merges them
    through the device/mesh combine chain (fused_agg.try_fused_final).
    Asserts zero columnar fallbacks, ≥ n_regions states partials per
    run, a states-channel fusion per run, and exact parity vs the row
    protocol (kill switch). Emits the states-vs-rows wire-bytes ratio
    from the copr.agg_{states,rows}.wire_bytes counters."""
    from tidb_tpu import metrics, tablecodec as tc
    from tidb_tpu.executor import fused_agg
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum

    store = new_store(f"cluster://3/benchq1p{n_rows}")
    s = Session(store)
    s.execute("create database q1p")
    s.execute("use q1p")
    s.execute("create table lineitem (l_id bigint primary key, "
              "l_flag varchar(4), l_status varchar(4), "
              "l_qty decimal(12,2), l_price decimal(12,2), "
              "l_disc double, l_ship bigint)")
    tbl = s.info_schema().table_by_name("q1p", "lineitem")
    from decimal import Decimal
    flags = ("A", "N", "R")
    stats = ("F", "O")
    rows = [[Datum.i64(i), Datum.string(flags[i % 3]),
             Datum.string(stats[i % 2]),
             Datum.dec(Decimal(i % 50) + Decimal(i % 4) / 4),
             Datum.dec(Decimal(900 + i * 7) + Decimal(i % 10) / 10),
             Datum.f64((i % 11) * 0.01), Datum.i64(i % 365)]
            for i in range(1, n_rows + 1)]
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + batch],
                        skip_unique_check=True)
        txn.commit()
    step = max(n_rows // n_regions, 1)
    store.cluster.split_keys(
        [tc.encode_row_key(tbl.info.id, step * i + 1)
         for i in range(1, n_regions)])

    fbs = metrics.counter("distsql.columnar_fallbacks")
    states = metrics.counter("distsql.columnar_states")
    st_bytes = metrics.counter("copr.agg_states.wire_bytes")
    row_bytes = metrics.counter("copr.agg_rows.wire_bytes")
    # the near-data headline: states DISPATCHES per statement — one
    # batched segmented dispatch (mesh or single-device) must cover ALL
    # regions; the serial per-region counter rides the sum so any
    # degradation to one-dispatch-per-region fails the == 1 assert
    disp = (metrics.counter("copr.states_batch.dispatches"),
            metrics.counter("copr.mesh.near_data_dispatches"),
            metrics.counter("copr.states_batch.serial_dispatches"))
    # the filter headline: the pushed-down WHERE (l_ship <= 180) must
    # ride ONE batched device filter dispatch per statement — filter +
    # states together cost ≤ 2 device dispatches for the whole fan-out
    fdisp = metrics.counter("copr.filter.batched_dispatches")
    s.execute(Q1_PUSHDOWN_SQL)            # warm (pack + jit)
    f0, p0, b0 = fbs.value, states.value, st_bytes.value
    d0 = sum(c.value for c in disp)
    fd0 = fdisp.value
    fs0 = fused_agg.stats["final_states"]
    t0 = time.time()
    for _ in range(runs):
        col_results = s.execute(Q1_PUSHDOWN_SQL)[0].values()
    t_col = (time.time() - t0) / runs
    d_fbs = fbs.value - f0
    d_states = states.value - p0
    d_st_bytes = st_bytes.value - b0
    d_disp = sum(c.value for c in disp) - d0
    d_fdisp = fdisp.value - fd0
    d_fusions = fused_agg.stats["final_states"] - fs0
    assert d_fbs == 0, \
        f"q1 pushdown counted {d_fbs} columnar fallbacks"
    assert d_states >= n_regions * runs, \
        (f"only {d_states} partial-STATES payloads crossed the wire "
         f"across {n_regions} regions x {runs} runs")
    assert d_fusions >= runs, \
        "the FINAL aggregate never fused the partial states"
    disp_per_stmt = d_disp / runs if runs else 0.0
    assert disp_per_stmt == 1, \
        (f"q1 ran {disp_per_stmt} states dispatches per statement "
         f"across {n_regions} regions — near-data batching regressed")
    fdisp_per_stmt = d_fdisp / runs if runs else 0.0
    assert fdisp_per_stmt == 1, \
        (f"q1 ran {fdisp_per_stmt} batched filter dispatches per "
         f"statement across {n_regions} regions — the pushed-down WHERE "
         f"fell off the device filter tier")
    assert fdisp_per_stmt + disp_per_stmt <= 2, \
        (f"q1 cost {fdisp_per_stmt + disp_per_stmt} device dispatches "
         f"per statement — the ≤ 2 filter+states budget regressed")

    # row-protocol regime (kill switch): the parity oracle AND the
    # wire-bytes denominator (partial chunk rows per region)
    client = store.get_client()
    client.columnar_scan = False
    try:
        s.execute(Q1_PUSHDOWN_SQL)        # warm the row regime
        rb0 = row_bytes.value
        t0 = time.time()
        for _ in range(runs):
            row_results = s.execute(Q1_PUSHDOWN_SQL)[0].values()
        t_row = (time.time() - t0) / runs
        d_row_bytes = row_bytes.value - rb0
    finally:
        client.columnar_scan = True
    assert len(col_results) == len(row_results)
    for got, want in zip(col_results, row_results):
        for a, b in zip(got, want):
            ga = a.decode() if isinstance(a, bytes) else a
            gb = b.decode() if isinstance(b, bytes) else b
            # EXACT parity: Decimal sums compare at full precision and
            # float SUM/AVG must be bit-identical (the states channel
            # preserves the row path's sequential rounding)
            assert ga == gb, f"q1 pushdown parity: {a} != {b}"
    return {
        "q1_pushdown_rows_per_sec": round(n_rows / t_col, 1),
        "q1_pushdown_speedup_vs_rowpath": round(t_row / t_col, 2),
        "q1_pushdown_regions": n_regions,
        "q1_pushdown_fallbacks": d_fbs,
        "q1_pushdown_states_partials": d_states,
        "q1_pushdown_state_fusions": d_fusions,
        "q1_states_dispatches_per_stmt": disp_per_stmt,
        "q1_filter_dispatches_per_stmt": fdisp_per_stmt,
        "q1_device_dispatches_per_stmt": fdisp_per_stmt + disp_per_stmt,
        "q1_states_bytes_vs_rows_bytes": round(
            d_st_bytes / d_row_bytes, 3) if d_row_bytes else None,
    }


# every TPC-H aggregate shape the parser accepts over one lineitem
# store: the REAL q1 (expression aggregate arguments, 10 aggregates),
# the q6 scalar reduction, min/max over arithmetic, float expression
# arguments (bit-parity rung), and decimal / datetime GROUP columns —
# the full expression-pushdown surface of PR 18. Every statement must
# stay columnar: the sweep asserts ZERO fallbacks across all of them.
TPCH_SWEEP_SQLS = (
    ("q1full",
     "select l_returnflag, l_linestatus, sum(l_quantity), "
     "sum(l_extendedprice), sum(l_extendedprice * (1 - l_discount)), "
     "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)), "
     "avg(l_quantity), avg(l_extendedprice), avg(l_discount), count(*) "
     "from lineitem where l_ship <= 180 "
     "group by l_returnflag, l_linestatus "
     "order by l_returnflag, l_linestatus"),
    ("q6",
     "select sum(l_extendedprice * l_discount) from lineitem "
     "where l_ship <= 120"),
    ("minmax_expr",
     "select l_returnflag, min(l_extendedprice - l_discount), "
     "max(l_extendedprice + l_tax) from lineitem "
     "group by l_returnflag order by l_returnflag"),
    ("float_expr",
     "select l_returnflag, sum(l_fdisc * 2), avg(l_fdisc + 0.5) "
     "from lineitem group by l_returnflag order by l_returnflag"),
    ("dec_group",
     "select l_quantity, count(*), sum(l_extendedprice) from lineitem "
     "group by l_quantity order by l_quantity"),
    ("date_group",
     "select l_shipdate, count(*), "
     "sum(l_extendedprice * (1 - l_discount)) from lineitem "
     "group by l_shipdate order by l_shipdate"),
)


def measure_tpch_sweep(n_rows: int, n_regions: int, runs: int):
    """TPC-H sweep over the 4-region cluster store: every aggregate
    shape the parser accepts (TPCH_SWEEP_SQLS — the real q1 with
    expression arguments, q6, min/max over arithmetic, float expression
    args, decimal and datetime group keys) runs columnar with ZERO
    fallbacks, and the real-shape q1 counter-asserts ≤ 2 device
    dispatches per statement. Exact parity vs the row protocol (kill
    switch) for every query — Decimal sums at full precision, float
    SUM/AVG bit-identical."""
    from decimal import Decimal

    from tidb_tpu import metrics, tablecodec as tc
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum, Kind
    from tidb_tpu.types.time_types import parse_time

    store = new_store(f"cluster://3/benchtpch{n_rows}")
    s = Session(store)
    s.execute("create database tpch")
    s.execute("use tpch")
    s.execute("create table lineitem (l_id bigint primary key, "
              "l_returnflag varchar(4), l_linestatus varchar(4), "
              "l_quantity decimal(12,2), l_extendedprice decimal(12,2), "
              "l_discount decimal(12,2), l_tax decimal(12,2), "
              "l_fdisc double, l_ship bigint, l_shipdate datetime)")
    tbl = s.info_schema().table_by_name("tpch", "lineitem")
    flags = ("A", "N", "R")
    stats = ("F", "O")
    rows = [[Datum.i64(i), Datum.string(flags[i % 3]),
             Datum.string(stats[i % 2]),
             Datum.dec(Decimal(i % 50) + Decimal(i % 4) / 4),
             Datum.dec(Decimal(900 + i * 7 % 1000) + Decimal(i % 10) / 10),
             Datum.dec(Decimal(i % 11) / 100),
             Datum.dec(Decimal(i % 9) / 100),
             Datum.f64((i % 7) * 0.01), Datum.i64(i % 365),
             Datum(Kind.TIME,
                   parse_time(f"2024-0{1 + i % 9}-1{i % 9} 00:00:00",
                              fsp=0))]
            for i in range(1, n_rows + 1)]
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + batch],
                        skip_unique_check=True)
        txn.commit()
    step = max(n_rows // n_regions, 1)
    store.cluster.split_keys(
        [tc.encode_row_key(tbl.info.id, step * i + 1)
         for i in range(1, n_regions)])

    fbs = metrics.counter("distsql.columnar_fallbacks")
    argp = metrics.counter("distsql.columnar_arg_planes")
    disp = (metrics.counter("copr.states_batch.dispatches"),
            metrics.counter("copr.mesh.near_data_dispatches"),
            metrics.counter("copr.states_batch.serial_dispatches"),
            metrics.counter("copr.filter.batched_dispatches"))
    for _, sql in TPCH_SWEEP_SQLS:
        s.execute(sql)                    # warm (pack + jit)

    col_results = {}
    q1_disp_per_stmt = 0.0
    f0, a0 = fbs.value, argp.value
    t0 = time.time()
    for name, sql in TPCH_SWEEP_SQLS:
        if name == "q1full":
            d0 = sum(c.value for c in disp)
            for _ in range(runs):
                col_results[name] = s.execute(sql)[0].values()
            q1_disp_per_stmt = (sum(c.value for c in disp) - d0) / runs
        else:
            for _ in range(runs):
                col_results[name] = s.execute(sql)[0].values()
    t_col = (time.time() - t0) / runs
    d_fbs = fbs.value - f0
    d_argp = argp.value - a0
    assert d_fbs == 0, \
        f"tpch sweep counted {d_fbs} columnar fallbacks"
    assert d_argp >= 4 * runs, \
        (f"only {d_argp} arg-plane states partials across the sweep — "
         f"expression arguments fell off the fused states path")
    assert q1_disp_per_stmt <= 2, \
        (f"real-shape q1 cost {q1_disp_per_stmt} device dispatches per "
         f"statement — the ≤ 2 filter+states budget regressed")

    # row-protocol regime (kill switch): the parity oracle for every
    # sweep shape — the same statements, rows crossing the wire
    client = store.get_client()
    client.columnar_scan = False
    try:
        t0 = time.time()
        row_results = {name: s.execute(sql)[0].values()
                       for name, sql in TPCH_SWEEP_SQLS}
        t_row = time.time() - t0
    finally:
        client.columnar_scan = True
    for name, _ in TPCH_SWEEP_SQLS:
        got_rows, want_rows = col_results[name], row_results[name]
        assert len(got_rows) == len(want_rows), name
        for got, want in zip(got_rows, want_rows):
            for a, b in zip(got, want):
                ga = a.decode() if isinstance(a, bytes) else a
                gb = b.decode() if isinstance(b, bytes) else b
                # EXACT parity: Decimal sums at full precision, float
                # SUM/AVG bit-identical (the arg-plane channel preserves
                # the row path's sequential rounding); str() pins the
                # display SCALE too — the states channel must render the
                # same codec-canonical decimals the row partials carry
                assert ga == gb and str(ga) == str(gb), \
                    f"tpch sweep parity [{name}]: {a!r} != {b!r}"
    return {
        "tpch_sweep_queries": len(TPCH_SWEEP_SQLS),
        "tpch_sweep_regions": n_regions,
        "tpch_sweep_rows_per_sec": round(
            n_rows * len(TPCH_SWEEP_SQLS) / t_col, 1),
        "tpch_sweep_speedup_vs_rowpath": round(t_row * runs / t_col, 2)
        if t_col else None,
        "tpch_sweep_fallbacks": d_fbs,
        "tpch_sweep_arg_plane_partials": d_argp,
        "q1full_fallbacks": d_fbs,
        "q1full_dispatches_per_stmt": q1_disp_per_stmt,
    }


MULTIQ_Q5_SQL = (
    "select l_nation, count(*), sum(l_qty), min(l_price), max(l_price) "
    "from lineitem join nation on l_flag = n_flag and l_status = n_status "
    "where l_ship < 300 group by l_nation")
MULTIQ_Q3_SQL = (
    "select l_nation, l_price, l_qty from lineitem "
    "join nation on l_flag = n_flag and l_status = n_status "
    "where l_ship < 300 order by l_nation, l_price desc limit 10")


def measure_multiq(n_rows: int, n_regions: int, runs: int,
                   floor: int | None = None):
    """TPC-H-q3/q5-shaped MULTI-KEY STRING joins over the 4-region
    cluster store — the device dictionary execution tier's headline
    regime (copr.dictionary): both queries join on a composite
    (varchar, varchar) key lowered to key-tuple codes over shared
    dictionary domains, the q5 shape groups by a string column riding
    the same codes, and the q3 shape orders by DICTIONARY RANK through
    the join→TopN plane path without materializing rows. Asserts the
    run is fully columnar (multiq_fallbacks == 0, zero degraded_dict,
    composite keys on the device join path via the remap kernel) with
    row-for-row parity against BOTH the kill-switch dict path
    (tidb_tpu_device_dict = 0) and a vectorized numpy oracle computing
    the same queries over pre-encoded planes per run."""
    import numpy as np

    from tidb_tpu import metrics, tablecodec as tc
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum

    store = new_store(f"cluster://3/benchmq{n_rows}")
    s = Session(store)
    s.execute("create database mq")
    s.execute("use mq")
    s.execute("create table lineitem (l_id bigint primary key, "
              "l_flag varchar(4), l_status varchar(4), "
              "l_nation varchar(16), l_qty bigint, l_price bigint, "
              "l_ship bigint)")
    s.execute("create table nation (n_id bigint primary key, "
              "n_flag varchar(4), n_status varchar(4), n_disc bigint)")
    flags = ("A", "N", "R")
    stats_ = ("F", "O")
    nations = ("ALGERIA", "BRAZIL", "CANADA", "EGYPT", "FRANCE",
               "GERMANY", "INDIA", "JAPAN")
    tbl = s.info_schema().table_by_name("mq", "lineitem")
    rows = [[Datum.i64(i), Datum.string(flags[i % 3]),
             Datum.string(stats_[i % 2]), Datum.string(nations[i % 8]),
             Datum.i64(i % 50), Datum.i64(900 + (i * 7) % 1000),
             Datum.i64(i % 365)]
            for i in range(1, n_rows + 1)]
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + batch],
                        skip_unique_check=True)
        txn.commit()
    # one nation row per (flag, status) combo: FK-shaped composite key
    drows = ", ".join(
        f"({i}, '{f}', '{st}', {i * 3})"
        for i, (f, st) in enumerate((f, st) for f in flags
                                    for st in stats_))
    s.execute(f"insert into nation values {drows}")
    step = max(n_rows // n_regions, 1)
    store.cluster.split_keys(
        [tc.encode_row_key(tbl.info.id, step * i + 1)
         for i in range(1, n_regions)])
    if floor is not None:
        s.execute(f"set global tidb_tpu_dispatch_floor = {floor}")

    fbs = metrics.counter("distsql.columnar_fallbacks")
    jk = metrics.counter("copr.dict.join_keys")
    dr = metrics.counter("copr.dict.device_remaps")
    tp = metrics.counter("copr.dict.topn_plane")
    dd = metrics.counter("copr.degraded_dict")
    s.execute(MULTIQ_Q5_SQL)              # warm (pack + dicts + jit)
    s.execute(MULTIQ_Q3_SQL)
    f0, j0, d0, t0c, g0 = (fbs.value, jk.value, dr.value, tp.value,
                           dd.value)
    t0 = time.time()
    for _ in range(runs):
        q5_col = s.execute(MULTIQ_Q5_SQL)[0].values()
        q3_col = s.execute(MULTIQ_Q3_SQL)[0].values()
    t_col = (time.time() - t0) / (2 * runs)
    d_fbs = fbs.value - f0
    d_jk = jk.value - j0
    d_dr = dr.value - d0
    d_tp = tp.value - t0c
    assert d_fbs == 0, f"multiq counted {d_fbs} columnar fallbacks"
    assert dd.value == g0, "multiq degraded to the dict path"
    assert d_jk >= 2 * runs, \
        f"only {d_jk} joins rode composite key-tuple codes"
    assert d_dr >= 2 * runs, \
        (f"only {d_dr} device remap dispatches — composite keys did not "
         f"ride the device join path")
    assert d_tp >= runs, \
        "join→TopN never took the dictionary-rank plane path"

    # kill-switch regime: the row-at-a-time dict path is the oracle
    s.execute("set global tidb_tpu_device_dict = 0")
    try:
        s.execute(MULTIQ_Q5_SQL)          # warm the dict regime
        s.execute(MULTIQ_Q3_SQL)
        t0 = time.time()
        for _ in range(runs):
            q5_dict = s.execute(MULTIQ_Q5_SQL)[0].values()
            q3_dict = s.execute(MULTIQ_Q3_SQL)[0].values()
        t_dict = (time.time() - t0) / (2 * runs)
    finally:
        s.execute("set global tidb_tpu_device_dict = 1")

    def norm(rows_):
        return [tuple(a.decode() if isinstance(a, bytes) else a
                      for a in r) for r in rows_]

    assert norm(q5_col) == norm(q5_dict), "multiq q5 parity vs dict path"
    assert norm(q3_col) == norm(q3_dict), "multiq q3 parity vs dict path"

    # vectorized numpy oracle over pre-encoded planes (the pack-time
    # analog): per run it evaluates the filter, builds the composite
    # keys, joins via sort+searchsorted, and computes the group-by /
    # top-n — the honest host baseline for the dictionary tier
    lf = np.array([flags[i % 3] for i in range(1, n_rows + 1)])
    ls = np.array([stats_[i % 2] for i in range(1, n_rows + 1)])
    ln = np.array([nations[i % 8] for i in range(1, n_rows + 1)])
    lq = np.arange(1, n_rows + 1, dtype=np.int64) % 50
    lp = 900 + (np.arange(1, n_rows + 1, dtype=np.int64) * 7) % 1000
    lsh = np.arange(1, n_rows + 1, dtype=np.int64) % 365
    combos = [(f, st) for f in flags for st in stats_]
    nf = np.array([f for f, _ in combos])
    ns = np.array([st for _, st in combos])
    # shared dictionary codes (what the registry provides the engine)
    fu = np.unique(np.concatenate([lf, nf]))
    su = np.unique(np.concatenate([ls, ns]))
    nu = np.unique(ln)
    lfc = np.searchsorted(fu, lf)
    lsc = np.searchsorted(su, ls)
    lnc = np.searchsorted(nu, ln)
    nfc = np.searchsorted(fu, nf)
    nsc = np.searchsorted(su, ns)

    def oracle_run():
        m = lsh < 300
        lkey = lfc * len(su) + lsc
        rkey = nfc * len(su) + nsc
        order = np.argsort(rkey, kind="stable")
        rs = rkey[order]
        pos = np.searchsorted(rs, lkey)
        posc = np.clip(pos, 0, len(rs) - 1)
        matched = m & (rs[posc] == lkey)
        # q5: group by nation over the matched rows
        g = lnc[matched]
        cnt = np.bincount(g, minlength=len(nu))
        qty = np.bincount(g, weights=lq[matched].astype(np.float64),
                          minlength=len(nu))
        price = lp[matched]
        mn = np.full(len(nu), np.iinfo(np.int64).max, np.int64)
        np.minimum.at(mn, g, price)
        mx = np.full(len(nu), np.iinfo(np.int64).min, np.int64)
        np.maximum.at(mx, g, price)
        q5 = [(nu[i], int(cnt[i]), int(qty[i]), int(mn[i]), int(mx[i]))
              for i in range(len(nu)) if cnt[i]]
        # q3: order by (nation asc, price desc, scan position) limit 10
        idx = np.flatnonzero(matched)
        top = idx[np.lexsort([idx, -lp[idx], lnc[idx]])][:10]
        q3 = [(ln[i], int(lp[i]), int(lq[i])) for i in top.tolist()]
        return q5, q3

    q5_o, q3_o = oracle_run()     # warm + parity sample
    t0 = time.time()
    for _ in range(runs):
        oracle_run()
    t_oracle = (time.time() - t0) / (2 * runs)
    got5 = sorted((r[0], r[1], int(r[2]), int(r[3]), int(r[4]))
                  for r in norm(q5_col))
    assert got5 == sorted(q5_o), "multiq q5 parity vs numpy oracle"
    got3 = [(r[0], int(r[1]), int(r[2])) for r in norm(q3_col)]
    assert got3 == q3_o, "multiq q3 parity vs numpy oracle"
    return {
        "multiq_rows_per_sec": round(n_rows / t_col, 1),
        # 4 decimals: the tiny smoke rig can put the numpy oracle under
        # 1/100th of the columnar run — 2 would round the figure to 0
        "multiq_vs_numpy_oracle": round(t_oracle / t_col, 4),
        "multiq_fallbacks": d_fbs,
        "multiq_regions": n_regions,
        "multiq_dict_joins": d_jk,
        "multiq_device_remaps": d_dr,
        "multiq_topn_plane": d_tp,
        "multiq_speedup_vs_dict_path": round(t_dict / t_col, 2),
    }


HTAP_SQL = "select count(*), sum(v), min(v), max(v) from ht where k < 6"


def measure_htap_mixed(n_rows: int, n_regions: int, runs: int):
    """The HTAP freshness regime (ROADMAP exit criterion): OLTP commits
    interleaved with repeat 4-region fan-out scans. With the delta tier
    on (tidb_tpu_delta_pack=1), every post-commit scan answers from
    cached base planes + a device base+delta merge — plane-cache hit
    ratio (exact hits + delta merges over lookups) stays high; with it
    off, every commit re-colds the cache and the ratio collapses. A
    commit to an unrelated table never touches the hot table's entries
    (counter-asserted: zero misses, zero version invalidations), and
    every iteration's answer is row-for-row identical to the row
    protocol at the same state. A small delta budget forces fold-and-
    reset cycles so the background re-pack path is exercised too."""
    from tidb_tpu import metrics, tablecodec as tc
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum

    store = new_store(f"cluster://3/benchhtap{n_rows}")
    s = Session(store)
    s.execute("create database htap")
    s.execute("use htap")
    s.execute("create table ht (id bigint primary key, k bigint, "
              "v bigint)")
    s.execute("create table other (id bigint primary key, x bigint)")
    tbl = s.info_schema().table_by_name("htap", "ht")
    rows = [[Datum.i64(i), Datum.i64(i % 11), Datum.i64(i * 3)]
            for i in range(1, n_rows + 1)]
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + batch],
                        skip_unique_check=True)
        txn.commit()
    s.execute("insert into other values (0, 0)")
    step = max(n_rows // n_regions, 1)
    store.cluster.split_keys(
        [tc.encode_row_key(tbl.info.id, step * i + 1)
         for i in range(1, n_regions)])
    # a small delta budget so the fold-and-reset (background re-pack)
    # path fires inside the timed regime
    s.execute("set global tidb_tpu_delta_budget_rows = 64")

    hits = metrics.counter("copr.plane_cache.hits")
    misses = metrics.counter("copr.plane_cache.misses")
    merges = metrics.counter("copr.delta.merges")
    repacks = metrics.counter("copr.delta.repacks")
    inv = metrics.counter("copr.plane_cache.invalidations_version")
    client = store.get_client()
    iters = max(6, runs * 6)
    next_id = n_rows + 1
    merges_at_entry, repacks_at_entry = merges.value, repacks.value

    def regime(label: str):
        """One interleaved commit/scan loop; returns (scan rows/s, hit
        ratio, per-iteration parity failures)."""
        nonlocal next_id
        s.execute(HTAP_SQL)         # warm / populate the cache
        h0, m0, g0 = hits.value, misses.value, merges.value
        t_scan = 0.0
        for i in range(iters):
            vals = ", ".join(f"({next_id + j}, {j % 11}, {j})"
                             for j in range(32))
            next_id += 32
            s.execute(f"insert into ht values {vals}")
            s.execute(f"update ht set v = v + 1 where id = {i % n_rows + 1}")
            # the deleted id is never re-inserted (next_id only grows),
            # so its tombstone must KEEP holding through every later
            # merge — the parity check below would catch a resurrection
            s.execute(f"delete from ht where id = {next_id - 1}")
            t0 = time.time()
            got = s.execute(HTAP_SQL)[0].values()
            t_scan += time.time() - t0
            # exact row-for-row parity vs the row protocol AT THE SAME
            # STATE (no commit between the two runs)
            client.columnar_scan = False
            try:
                want = s.execute(HTAP_SQL)[0].values()
            finally:
                client.columnar_scan = True
            assert got == want, \
                f"{label} iter {i}: columnar {got} != row protocol {want}"
        lookups = (hits.value - h0) + (misses.value - m0)
        served_warm = (hits.value - h0) + (merges.value - g0)
        ratio = served_warm / lookups if lookups else 0.0
        return n_rows * iters / t_scan, ratio

    rps_on, ratio_on = regime("delta_on")
    d_merges = merges.value - merges_at_entry
    d_repacks = repacks.value - repacks_at_entry
    assert d_merges > 0, "HTAP regime never took a base+delta merge"
    assert d_repacks > 0, \
        "delta budget never triggered a fold-and-reset re-pack"

    # unrelated-table commits: table B traffic must not move table A's
    # cached planes at all (per-table commit filtering)
    s.execute(HTAP_SQL)
    m0, i0, h0 = misses.value, inv.value, hits.value
    for i in range(4):
        s.execute(f"insert into other values ({i + 1}, {i})")
        s.execute(HTAP_SQL)
    assert misses.value == m0 and inv.value == i0, \
        "a commit to table B invalidated table A's cached planes"
    assert hits.value - h0 >= 4 * n_regions, \
        "post-B-commit scans did not exact-hit table A's planes"

    # kill-switch regime: every commit re-colds the cache (the PR-5
    # behavior) — the ratio must collapse while answers stay identical
    s.execute("set global tidb_tpu_delta_pack = 0")
    try:
        rps_off, ratio_off = regime("delta_off")
    finally:
        s.execute("set global tidb_tpu_delta_pack = 1")
        s.execute("set global tidb_tpu_delta_budget_rows = 4096")
    assert ratio_on >= 0.8, \
        f"HTAP hit ratio {ratio_on:.2f} < 0.8 with the delta tier on"
    assert ratio_off < 0.3, \
        f"delta-off hit ratio {ratio_off:.2f} not near zero (bad oracle)"
    return {
        "htap_scan_rows_per_sec": round(rps_on, 1),
        "htap_scan_rows_per_sec_off": round(rps_off, 1),
        "htap_plane_cache_hit_ratio": round(ratio_on, 3),
        "htap_plane_cache_hit_ratio_off": round(ratio_off, 3),
        "htap_regions": n_regions,
        "delta_merges": d_merges,
        "delta_repacks": d_repacks,
    }


MESH_FANOUT_SQL = ("select f_g, count(*), sum(f_v), min(f_v), max(d_f) "
                   "from mfan join mdim on f_k = d_k "
                   "group by f_g order by f_g")


def measure_mesh_fanout(n_rows: int, n_dim: int, n_regions: int,
                        runs: int):
    """The MESH execution regime over a real per-region fan-out: a
    4-region cluster store answers the columnar channel per region, each
    region's partials land on their home shard (region→shard placement
    over the device mesh), and the grouped partial-aggregate states
    combine via psum/pmin/pmax over ICI (ops.mesh.combine_rows_sharded)
    instead of the host-side [R, G] stack. On a 1-device rig this runs
    the same code path over a 1-shard mesh; on the 8-device dryrun the
    combine crosses real shard boundaries. Asserts zero columnar
    fallbacks and ≥1 mesh combine per timed run; parity is checked
    against the mesh-off (single-device combine) regime AND the row
    protocol."""
    from tidb_tpu import metrics, tablecodec as tc
    from tidb_tpu.executor import fused_agg
    from tidb_tpu.ops import mesh as mesh_mod
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum

    store = new_store(f"cluster://3/benchmesh{n_rows}")
    s = Session(store)
    s.execute("create database mesh")
    s.execute("use mesh")
    s.execute("create table mfan (f_id bigint primary key, f_g bigint, "
              "f_k bigint, f_v bigint)")
    s.execute("create table mdim (d_k bigint primary key, d_f double)")
    tbl = s.info_schema().table_by_name("mesh", "mfan")
    rows = [[Datum.i64(i), Datum.i64(i % 24), Datum.i64(i % n_dim),
             Datum.i64(i * 3)] for i in range(1, n_rows + 1)]
    batch = 20000
    for start in range(0, n_rows, batch):
        txn = store.begin()
        tbl.add_records(txn, rows[start:start + batch],
                        skip_unique_check=True)
        txn.commit()
    for start in range(0, n_dim, batch):
        vals = ", ".join(f"({k}, {k % 89}.25)"
                         for k in range(start, min(start + batch, n_dim)))
        s.execute(f"insert into mdim values {vals}")
    step = max(n_rows // n_regions, 1)
    store.cluster.split_keys(
        [tc.encode_row_key(tbl.info.id, step * i + 1)
         for i in range(1, n_regions)])

    fbs = metrics.counter("distsql.columnar_fallbacks")
    sess = Session(store)
    sess.execute("use mesh")
    sess.execute(MESH_FANOUT_SQL)          # warm (pack + jit)
    f0 = fbs.value
    mc0 = fused_agg.stats["mesh_combines"]
    t0 = time.time()
    for _ in range(runs):
        mesh_results = sess.execute(MESH_FANOUT_SQL)[0].values()
    t_mesh = (time.time() - t0) / runs
    d_fbs = fbs.value - f0
    combines = fused_agg.stats["mesh_combines"] - mc0
    assert d_fbs == 0, \
        f"mesh fan-out run counted {d_fbs} columnar fallbacks"
    assert combines >= runs, \
        (f"only {combines} mesh combines across {runs} runs — the "
         "partial combine did not ride the mesh")
    mesh = mesh_mod.get_mesh()
    shards = mesh.n if mesh is not None else 0

    # collective time: one traced run, summed over its mesh_combine spans
    doc = json.loads(sess.execute(
        f"trace format='json' {MESH_FANOUT_SQL}")[0].values()[0][0])

    def spans(d, name, out):
        if d.get("name") == name:
            out.append(d)
        for c in d.get("children", ()):
            spans(c, name, out)
        return out

    meshes = spans(doc, "mesh_combine", [])
    collective_ms = sum(m.get("duration_us", 0.0) for m in meshes) / 1e3
    transfer_bytes = sum(m.get("attrs", {}).get("transfer_bytes", 0)
                         for m in meshes)

    # parity regime 1: mesh off → the single-device combine answers
    sess.execute("set global tidb_tpu_mesh = 0")
    try:
        sess.execute(MESH_FANOUT_SQL)      # warm the single-device jit
        t0 = time.time()
        for _ in range(runs):
            single_results = sess.execute(MESH_FANOUT_SQL)[0].values()
        t_single = (time.time() - t0) / runs
    finally:
        sess.execute("set global tidb_tpu_mesh = 1")
    # parity regime 2: the row protocol
    client = store.get_client()
    client.columnar_scan = False
    try:
        row_results = sess.execute(MESH_FANOUT_SQL)[0].values()
    finally:
        client.columnar_scan = True
    assert mesh_results == single_results, \
        "mesh combine diverged from the single-device combine"
    assert mesh_results == row_results, \
        "mesh combine diverged from the row protocol"
    return {
        "mesh_fanout_rows_per_sec": round(n_rows / t_mesh, 1),
        "mesh_fanout_vs_single_device": round(t_single / t_mesh, 2),
        "mesh_shards": shards,
        "mesh_combines": combines,
        "mesh_collective_ms": round(collective_ms, 3),
        "mesh_transfer_bytes": transfer_bytes,
        "mesh_fanout_fallbacks": d_fbs,
    }


def measure_qps(n_conns: int, smoke: bool):
    """The heavy-traffic concurrency regime: N simulated connections run
    the SAME mixed point/range/join sequence (literals differ per
    connection) against one store whose table sits below the dispatch
    floor — the regime the micro-batch tier (ops.sched) exists for.
    Below-floor statements gather inside the batch window and ride
    shared padded device dispatches; the 1-connection control runs the
    identical workload with no peers to batch with (solo below-floor
    routing). Emits sustained QPS, p50/p99 per regime, the p99 ratio
    (the tier's exit criterion: p99 at N connections <= 2x p99 at 1),
    and batched-dispatch counts; parity of batched answers vs the solo
    route (kill switch) is asserted INSIDE the regime on a sampled
    statement set."""
    import threading

    import numpy as np

    from tidb_tpu import metrics
    from tidb_tpu.ops import TpuClient
    from tidb_tpu.session import Session, new_store
    from tidb_tpu.types import Datum

    n_rows, n_vals = 16384, 256
    # window sized for wave cohesion at n_conns on a GIL rig: the whole
    # wave's submits (~1-3 ms python each, serialized) must land inside
    # one gather window or waves fragment into sub-batches whose extra
    # window+dispatch rounds inflate p99. The 1-connection control pays
    # NO window (the traffic gate solo-routes lone statements), so a
    # wide window costs nothing in the denominator.
    window_ms = 100
    per_conn = 4 if smoke else 8
    per_conn_1 = 8 if smoke else 16

    store = new_store(f"memory://benchqps{n_conns}")
    s = Session(store)
    s.execute("set global tidb_slow_log_threshold = 0")
    s.execute(f"set global tidb_tpu_batch_window_ms = {window_ms}")
    s.execute("create database q")
    s.execute("use q")
    s.execute("create table qtab (q_id bigint primary key, q_v bigint, "
              "q_j bigint)")
    s.execute("create table qdim (d_v bigint primary key)")
    tbl = s.info_schema().table_by_name("q", "qtab")
    rows = [[Datum.i64(i), Datum.i64(i % n_vals), Datum.i64(i % 32)]
            for i in range(1, n_rows + 1)]
    txn = store.begin()
    tbl.add_records(txn, rows, skip_unique_check=True)
    txn.commit()
    s.execute("insert into qdim values "
              + ", ".join(f"({i})" for i in range(32)))
    # the whole table sits below the floor: every statement is the
    # small-statement shape that dominates the millions-of-users regime
    store.set_client(TpuClient(store, dispatch_floor_rows=1 << 20))
    client = store.get_client()

    def seq(conn_id: int, n: int):
        rng = random.Random(1000 + conn_id)
        shapes = ("point", "range", "point", "range", "join")
        out = []
        for i in range(n):
            sh = shapes[i % len(shapes)]
            if sh == "point":
                out.append(f"select q_id, q_j from qtab "
                           f"where q_v = {rng.randrange(n_vals)}")
            elif sh == "range":
                a = rng.randrange(n_vals - 4)
                out.append(f"select q_id from qtab "
                           f"where q_v between {a} and {a + 3}")
            else:
                a = rng.randrange(28)
                out.append(f"select q_id, d_v from qtab join qdim "
                           f"on q_j = d_v "
                           f"where q_v between {a} and {a + 2}")
        return out

    def run_regime(conns: int, per: int):
        sessions = [Session(store) for _ in range(conns)]
        for ss in sessions:
            ss.execute("use q")
        plans = [seq(i, per) for i in range(conns)]
        lat: list = []
        results: list = []
        lock = threading.Lock()
        barrier = threading.Barrier(conns)

        def worker(i):
            my_lat, my_res = [], []
            barrier.wait()
            for sql in plans[i]:
                t0 = time.perf_counter()
                rs = sessions[i].execute(sql)[0].values()
                my_lat.append((time.perf_counter() - t0) * 1000)
                my_res.append((sql, rs))
            with lock:
                lat.extend(my_lat)
                results.extend(my_res)
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(conns)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return np.array(lat), time.time() - t0, results

    # warm: pack the batch, compile the solo paths AND every batchable
    # signature at both slot buckets (concurrent bursts)
    warm_sessions = [Session(store) for _ in range(40)]
    for ss in warm_sessions:
        ss.execute("use q")

    def warm_burst(n: int, sql_for):
        b = threading.Barrier(n)

        def w(i):
            b.wait()
            warm_sessions[i].execute(sql_for(i))
        ts = [threading.Thread(target=w, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    for sql in seq(0, 5):
        s.execute(sql)
    for n in (4, min(40, max(n_conns, 8))):
        warm_burst(n, lambda i: f"select q_id, q_j from qtab "
                                f"where q_v = {i}")
        warm_burst(n, lambda i: f"select q_id from qtab "
                                f"where q_v between {i} and {i + 3}")
        warm_burst(n, lambda i: f"select q_id, d_v from qtab join qdim "
                                f"on q_j = d_v where q_v between "
                                f"{i % 28} and {i % 28 + 2}")

    # 1-connection control AFTER the hot signatures cool: the solo
    # regime must see the genuine below-floor solo routing
    time.sleep(2.2)
    lat1, wall1, _ = run_regime(1, per_conn_1)

    batched0 = metrics.counter("sched.batched_dispatches").value
    stmts0 = metrics.counter("sched.batched_statements").value
    degr0 = metrics.counter("copr.degraded_batch").value
    lat_n, wall_n, results = run_regime(n_conns, per_conn)
    batched = metrics.counter("sched.batched_dispatches").value - batched0
    batched_stmts = metrics.counter("sched.batched_statements").value \
        - stmts0
    degraded = metrics.counter("copr.degraded_batch").value - degr0
    assert batched > 0, \
        "concurrent below-floor statements never shared a dispatch"

    # parity: a deterministic sample of the concurrent run's statements,
    # re-answered by the SOLO route (micro-batch kill switch) — batched
    # answers must match exactly, row for row
    client.micro_batch = False
    try:
        sample = results[:: max(1, len(results) // 10)]
        for sql, got in sample:
            want = s.execute(sql)[0].values()
            assert got == want, \
                f"batched answer diverged from solo route: {sql}"
    finally:
        client.micro_batch = True

    p50_1 = float(np.percentile(lat1, 50))
    p99_1 = float(np.percentile(lat1, 99))
    p50_n = float(np.percentile(lat_n, 50))
    p99_n = float(np.percentile(lat_n, 99))
    return {
        "qps_connections": n_conns,
        "qps_sustained": round(len(lat_n) / wall_n, 1),
        "qps_1conn": round(len(lat1) / wall1, 1),
        "qps_p50_ms": round(p50_n, 2),
        "qps_p99_ms": round(p99_n, 2),
        "qps_p50_ms_1conn": round(p50_1, 2),
        "qps_p99_ms_1conn": round(p99_1, 2),
        "qps_p99_ratio_vs_1conn": round(p99_n / p99_1, 3),
        "qps_batched_dispatches": batched,
        "qps_batched_statements": batched_stmts,
        "qps_degraded_batch": degraded,
        "qps_batch_window_ms": window_ms,
        "qps_parity": True,
    }


def workload_summary(store, sess, n_regions: int) -> dict:
    """Workload-observability figures off the fan-out store: the digest
    summary's view of the run just measured (every timed statement above
    rolled into its digest's entry), the region heat the fan-out left
    behind, and the digest pipeline's per-statement cost.
    tests/test_bench_smoke.py asserts the digest_*/hot_region_* keys, so
    tier-1 guards the aggregation layer the same way it guards tracing."""
    from tidb_tpu import digest as _digest, perfschema
    dig, _norm = _digest.sql_digest(REGION_FANOUT_SQL)
    ds = perfschema.perf_for(store).digest_summary
    entries = ds.windows()[-1][2]
    e = entries.get(dig)
    assert e is not None, "fan-out query missing from the digest summary"
    assert e.plan_digest, "fan-out digest entry recorded no plan digest"
    heat = store.rpc.region_heat.snapshot()
    assert len(heat) >= n_regions, \
        f"only {len(heat)} regions carry heat across {n_regions}"

    # digest-pipeline overhead: trivial statements with the summary on
    # vs off — the same <2ms contract the tier-1 guard enforces
    n = 40
    sess.execute("select 1")   # warm
    t0 = time.time()
    for _ in range(n):
        sess.execute("select 1")
    t_on = time.time() - t0
    sess.execute("set global tidb_tpu_stmt_summary = 0")
    try:
        sess.execute("select 1")
        t0 = time.time()
        for _ in range(n):
            sess.execute("select 1")
        t_off = time.time() - t0
    finally:
        sess.execute("set global tidb_tpu_stmt_summary = 1")
    return {
        "digest_entries": len(entries),
        "digest_fanout_exec_count": e.exec_count,
        "digest_fanout_device_ms": round(e.device_time_us() / 1e3, 3),
        "digest_fanout_p95_ms": round(e.p95_latency_ms(), 3),
        "digest_overhead_us_per_stmt": round(
            max(0.0, (t_on - t_off) / n) * 1e6, 1),
        "hot_region_count": len(heat),
        "hot_region_top_read_rows": int(heat[0]["total_read_rows"]),
        "hot_region_top_score": round(heat[0]["heat"], 3),
    }


def diagnostics_summary() -> dict:
    """Diagnostics-tier figures for the bench JSON (tier-1-asserted like
    the digest/trace summaries): device busy fraction over a bracketed
    device regime (the metered dispatch_serial → metrics-recorder
    derivation), micro-batch slot-occupancy p50 and drain-pool
    queue-wait p99 from the profiler histograms the earlier regimes
    populated, and the flight recorder's per-statement cost under the
    same <2 ms contract as the digest pipeline."""
    from tidb_tpu import metrics
    from tidb_tpu.metrics import timeseries
    from tidb_tpu.ops import TpuClient
    from tidb_tpu.session import Session, new_store

    store = new_store("memory://bench_diag")
    sess = Session(store)
    sess.execute("create database bd")
    sess.execute("use bd")
    sess.execute("create table t (id bigint primary key, v bigint)")
    sess.execute("insert into t values " +
                 ", ".join(f"({i}, {i % 101})" for i in range(1, 4001)))
    store.set_client(TpuClient(store, dispatch_floor_rows=0))
    sess.execute("select sum(v), count(*) from t")   # warm: jit compile
    timeseries.recorder.sample()
    busy0 = metrics.counter("device.busy_us").value
    t0 = time.perf_counter()
    for _ in range(5):
        sess.execute("select sum(v), count(*) from t")
    wall_us = (time.perf_counter() - t0) * 1e6
    timeseries.recorder.sample()
    busy_us = metrics.counter("device.busy_us").value - busy0
    # the recorder-derived gauge covers the whole inter-sample window
    # (statement ends land extra samples); the bracketed ratio is the
    # regime-local figure — report the derivation, bound it to [0, 1]
    busy_fraction = min(1.0, busy_us / max(wall_us, 1.0))

    occ_p50 = metrics.quantile(
        metrics.histogram("sched.slot_occupancy"), 0.5)
    wait_p99_ms = metrics.quantile(
        metrics.histogram("copr.drain_pool.queue_wait_seconds"),
        0.99) * 1e3

    # flight-recorder overhead: trivial statements with the recorder on
    # (its default — scratch span trees built, nothing retained) vs off.
    # Best-of-3 perf_counter loops, the same noise discipline as the
    # tier-1 tracing guard — a single GC pause must not flake the
    # <2 ms/statement assert
    n = 40

    def timed_loop() -> float:
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                sess.execute("select 1")
            best = min(best, time.perf_counter() - t0)
        return best

    sess.execute("select 1")
    t_on = timed_loop()
    sess.execute("set global tidb_tpu_flight_recorder = 0")
    try:
        t_off = timed_loop()
    finally:
        sess.execute("set global tidb_tpu_flight_recorder = 1")
    return {
        "device_busy_fraction": round(busy_fraction, 4),
        "device_busy_us": int(busy_us),
        "batch_slot_occupancy_p50": round(occ_p50, 4),
        "pool_queue_wait_p99_ms": round(wait_p99_ms, 3),
        "flight_recorder_overhead_us_per_stmt": round(
            max(0.0, (t_on - t_off) / n) * 1e6, 1),
    }


def kernel_profile_summary() -> dict:
    """Continuous-profiler figures for the bench JSON: the process-wide
    per-(kind, signature) registry has watched EVERY metered dispatch the
    regimes above ran — report the top signature by device time, its
    share of total device time, and the retrace (jit-miss) count.
    tests/test_bench_smoke.py asserts these keys, so tier-1 guards the
    profiler's accounting path itself."""
    from tidb_tpu import profiler
    snap = profiler.registry_snapshot()
    total_us = sum(e["device_us"] for e in snap.values()) or 1
    top_label, top = max(snap.items(),
                         key=lambda kv: kv[1]["device_us"],
                         default=("", {"device_us": 0}))
    return {
        "kernel_profile_signatures": len(snap),
        "kernel_profile_top_signature": top_label,
        "kernel_profile_top_device_us": int(top["device_us"]),
        "kernel_profile_top_device_us_share": round(
            top["device_us"] / total_us, 4),
        "kernel_profile_retraces": int(
            sum(e["jit_misses"] for e in snap.values())),
    }


def trace_summary(sess, sql: str) -> dict:
    """Trace-derived kernel/copr timing figures for the bench JSON: run
    the query once under TRACE FORMAT='json' and summarize its span
    tree (per-region task timings, device-kernel dispatches/readbacks).
    tests/test_bench_smoke.py asserts these are present and
    non-negative, so tier-1 guards the instrumentation itself."""
    doc = json.loads(
        sess.execute(f"trace format='json' {sql}")[0].values()[0][0])

    def spans(d, name, out):
        if d.get("name") == name:
            out.append(d)
        for c in d.get("children", ()):
            spans(c, name, out)
        return out

    tasks = spans(doc, "region_task", [])
    kernels = spans(doc, "kernel", []) + \
        spans(doc, "combine_region_partials", []) + \
        spans(doc, "mesh_combine", [])
    meshes = spans(doc, "mesh_combine", [])
    attrs = [t.get("attrs", {}) for t in tasks]
    kattrs = [k.get("attrs", {}) for k in kernels]
    return {
        "trace_mesh_combines": len(meshes),
        "trace_mesh_ms_total": round(
            sum(m.get("duration_us", 0.0) for m in meshes) / 1e3, 3),
        "trace_copr_tasks": len(tasks),
        "trace_copr_task_ms_max": round(
            max((a.get("run_us", 0.0) for a in attrs), default=0.0) / 1e3,
            3),
        "trace_copr_queue_ms_max": round(
            max((a.get("queue_us", 0.0) for a in attrs), default=0.0)
            / 1e3, 3),
        "trace_copr_retries": sum(a.get("retries", 0) for a in attrs),
        "trace_kernel_dispatches": len(kernels),
        "trace_kernel_ms_total": round(
            sum(k.get("duration_us", 0.0) for k in kernels) / 1e3, 3),
        "trace_readbacks": sum(a.get("readbacks", 0) for a in kattrs),
        "trace_readback_bytes": sum(a.get("readback_bytes", 0)
                                    for a in kattrs),
    }


def timed_runs(session, sql: str, runs: int):
    session.execute(sql)  # warm (compile + cache + pack)
    results = []
    t0 = time.time()
    for _ in range(runs):
        results.append(session.execute(sql)[0].values())
    return (time.time() - t0) / runs, results


def _close(a: float, b: float, tol=1e-6) -> bool:
    return abs(a - b) <= tol * max(abs(a), abs(b), 1.0)


def check_scaled_parity(name: str, cpu_rows, tpu_rows, factor: int):
    """Exact parity under the replication algebra (see module docstring)."""
    assert len(cpu_rows) == len(tpu_rows), \
        f"{name}: row count {len(cpu_rows)} vs {len(tpu_rows)}"
    for cr, tr in zip(cpu_rows, tpu_rows):
        assert len(cr) == len(tr), f"{name}: column count"
        if name == "q6":
            assert _close(float(cr[0]) * factor, float(tr[0])), \
                f"{name}: {cr[0]}x{factor} != {tr[0]}"
        elif name == "distinct":
            assert int(cr[0]) == int(tr[0]), f"{name}: {cr[0]} != {tr[0]}"
        elif name.startswith("q1"):
            # [flag, status, 4×sum, 3×avg, count]
            for j in (0, 1):
                a = cr[j].decode() if isinstance(cr[j], bytes) else cr[j]
                b = tr[j].decode() if isinstance(tr[j], bytes) else tr[j]
                assert a == b, f"{name}: group {a} != {b}"
            for j in (2, 3, 4, 5):
                assert _close(float(cr[j]) * factor, float(tr[j])), \
                    f"{name}: sum col {j}"
            for j in (6, 7, 8):
                assert _close(float(cr[j]), float(tr[j])), \
                    f"{name}: avg col {j}"
            assert int(cr[9]) * factor == int(tr[9]), f"{name}: count"


def main(smoke: bool = False, full: bool = False):
    if smoke:
        # --smoke: tiny row counts, CPU-safe, same code paths — a tier-1
        # test runs this so bench-path regressions fail fast instead of
        # surfacing at the next full BENCH round
        n_rows = int(os.environ.get("BENCH_ROWS", "24576"))
        n_base = int(os.environ.get("BENCH_BASE_ROWS", str(n_rows)))
        runs = int(os.environ.get("BENCH_RUNS", "1"))
    elif full:
        # --full: every measure_* regime at its canonical full size in
        # ONE pass — env overrides are ignored so a BENCH_ROWS left
        # behind in the environment can never silently shrink a
        # published round
        n_rows, n_base, runs = 10_200_000, 1_020_000, 3
    else:
        n_rows = int(os.environ.get("BENCH_ROWS", "10200000"))
        n_base = int(os.environ.get("BENCH_BASE_ROWS", "1020000"))
        runs = int(os.environ.get("BENCH_RUNS", "3"))
    n_base = min(n_base, n_rows)
    factor = max(1, n_rows // n_base)
    n_rows = n_base * factor

    from tidb_tpu.ops import TpuClient
    from tidb_tpu.session import Session

    base_store, base_session, tbl, load_s = build_store(n_base)
    print(f"# loaded {n_base} rows in {load_s:.1f}s "
          f"({n_base / load_s:,.0f} rows/s write path)", file=sys.stderr)

    if factor > 1:
        big_store, big_session, rep_s = replicate_store(
            base_store, base_session, tbl, n_base, factor)
        print(f"# replicated to {n_rows} rows in {rep_s:.1f}s",
              file=sys.stderr)
    else:
        big_store, big_session = base_store, base_session

    configs = [("q6", Q6), ("q1", Q1), ("distinct", QDIST)]

    # CPU xeval baseline on the base store (local_region.go equivalent)
    cpu = {}
    for name, sql in configs:
        cpu_s, cpu_results = timed_runs(base_session, sql,
                                        max(1, runs if n_base <= 300_000
                                            else 1))
        cpu[name] = (cpu_s, cpu_results)
        print(f"# {name}: cpu xeval {cpu_s:.3f}s/run "
              f"({n_base / cpu_s:,.0f} rows/s at {n_base} rows)",
              file=sys.stderr)

    # TPU coprocessor on the full store
    big_store.set_client(TpuClient(big_store))
    tpu_session = Session(big_store)
    tpu_session.execute("use tpch")
    tpu_client = big_store.get_client()

    # phase 0 — put the tunnel into its post-D2H mode NOW: every number
    # from here on is measured in the same (real, synchronous) regime a
    # serving database lives in. Pre-D2H timings on this platform are
    # optimistic fiction (experiments/exp_axon_prims.py).
    poison_tunnel()
    hbm_peak = measure_hbm_peak() if not smoke else 1.0
    print(f"# hbm peak (post-D2H copy-sweep): {hbm_peak:.2f} GB/s",
          file=sys.stderr)
    # calibrate the kernel profiler's roofline against the measured
    # tunnel rate so its readback-bound verdicts use this rig's number
    from tidb_tpu import profiler
    profiler.set_tunnel_gbps(hbm_peak)

    # routing: measured CPU/device crossover (on the base store, where the
    # CPU side stays tractable) + the steady-state latency of a small query
    # under the default floor — must be CPU-fast, not device-fast
    # (smoke skips the sweep: 10 timed SQL runs for a figure the smoke
    # JSON does not assert on)
    crossover_rows = measure_crossover(base_store, runs) if not smoke \
        else -1
    small_sql = "select sum(l_quantity) from lineitem where l_id <= 1000"
    tpu_session.execute(small_sql)   # warm: pack the 1k-row range batch
    t0 = time.time()
    for _ in range(5):
        tpu_session.execute(small_sql)
    small_ms = (time.time() - t0) / 5 * 1000
    assert tpu_client.stats["small_to_cpu"] > 0, \
        "small query did not take the dispatch-floor CPU route"
    print(f"# routing: crossover ~{crossover_rows} rows, floor "
          f"{tpu_client.dispatch_floor_rows}, 1k-row SUM {small_ms:.2f} ms "
          "(CPU-routed)", file=sys.stderr)

    # phases 1+2 — end-to-end SQL (parse → plan → dispatch → decode), then
    # the kernel probe re-times the very dispatch that e2e just ran; by
    # construction kernel <= e2e, and the bench FAILS if measurement says
    # otherwise (a broken probe must never reach BENCH_r*.json again)
    kernel_s: dict[str, float] = {}
    speedups, tpu_rps_all, bw_figures, roofline = [], [], {}, {}
    oracle_rps, oracle_speedups = {}, []
    big_info = big_session.info_schema().table_by_name("tpch",
                                                       "lineitem").info
    col_id = {c.name: c.id for c in big_info.columns}
    for name, sql in configs:
        before = (tpu_client.stats["tpu_requests"],
                  tpu_client.stats["cpu_fallbacks"])
        t_pack0 = time.time()
        tpu_session.execute(sql)  # warm (pack batch + compile kernel)
        first_s = time.time() - t_pack0
        tpu_s, tpu_results = timed_runs(tpu_session, sql, runs)
        assert tpu_client.stats["tpu_requests"] > before[0], \
            f"{name}: never reached the TPU engine"
        assert tpu_client.stats["cpu_fallbacks"] == before[1], \
            f"{name}: fell back to the CPU engine"
        cpu_s, cpu_results = cpu[name]
        check_scaled_parity(name, cpu_results[0], tpu_results[0], factor)
        cpu_rps, tpu_rps = n_base / cpu_s, n_rows / tpu_s
        speedups.append(tpu_rps / cpu_rps)
        tpu_rps_all.append(tpu_rps)
        ks = kernel_probe(tpu_client, runs)
        if ks is not None:
            assert ks <= tpu_s * 1.10 + 0.01, \
                (f"{name}: kernel probe {ks:.4f}s exceeds the e2e "
                 f"{tpu_s:.4f}s that contains it — probe harness broken")
            kernel_s[name] = ks
            bw = n_rows * REFERENCED_COLS[name] * 9 / ks / 1e9
            bw_figures[name] = round(bw, 2)
            sweep_t = bytes_matched_sweep(n_rows * REFERENCED_COLS[name],
                                          runs)
            roofline[name] = round(sweep_t / ks, 3)
            print(f"# {name}: device kernel {ks * 1000:.1f} ms/run "
                  f"({n_rows / ks:,.0f} rows/s/chip, {bw:.1f} GB/s = "
                  f"{bw / hbm_peak * 100:.0f}% of 1GB-sweep peak, "
                  f"{roofline[name] * 100:.0f}% of its bytes-matched "
                  f"roofline [{sweep_t * 1000:.0f} ms sweep])",
                  file=sys.stderr)
        else:
            bw_figures[name] = 0.0
        batch = tpu_client._cur_batch   # set by every routed request; the
        assert batch is not None, name  # tpu_requests assert above proves
        #                                 this config went through one
        o_s = numpy_oracle_time(name, batch, col_id, runs)
        assert o_s is not None, f"{name}: numpy oracle did not run"
        extra = ""
        if o_s:
            oracle_rps[name] = round(n_rows / o_s, 1)
            oracle_speedups.append(tpu_rps / (n_rows / o_s))
            extra = (f"  vs numpy oracle {oracle_speedups[-1]:.1f}x "
                     f"({n_rows / o_s / 1e6:.1f}M rows/s host)")
        print(f"# {name}: tpu e2e {tpu_s:.4f}s/run ({tpu_rps:,.0f} rows/s"
              f"/chip, first-run {first_s:.1f}s)  "
              f"speedup {tpu_rps / cpu_rps:.1f}x{extra}", file=sys.stderr)

    # config 5: Q1 with the mesh client — partial aggregates combined over
    # the device axis (psum/pmin/pmax); on single-chip hardware this runs
    # with axis size 1, under the test env with 8 virtual devices
    import jax
    from tidb_tpu.parallel import CoprMesh
    mesh_client = TpuClient(big_store, mesh=CoprMesh())
    big_store.set_client(mesh_client)
    mesh_session = Session(big_store)
    mesh_session.execute("use tpch")
    mesh_s, mesh_results = timed_runs(mesh_session, Q1, runs)
    check_scaled_parity("q1_mesh", cpu["q1"][1][0], mesh_results[0], factor)
    assert mesh_client.stats["tpu_requests"] > 0, "mesh engine never used"
    print(f"# q1_mesh ({len(jax.devices())} devices): {mesh_s:.4f}s/run "
          f"({n_rows / mesh_s:,.0f} rows/s)", file=sys.stderr)
    q1_mesh_rps = round(n_rows / mesh_s, 1)

    jl, jr = (60_000, 10_000) if smoke else (1_000_000, 100_000)
    join_figs = measure_join(jl, jr)
    print(f"# join ({jl / 1000:.0f}k x {jr / 1000:.0f}k int key, "
          f"operator-level): {join_figs['join_rows_per_sec']:,.0f} probe "
          f"rows/s device ({join_figs['join_speedup_vs_dict']:.1f}x vs "
          f"dict; build {join_figs['join_build_ms']:.1f} ms, probe "
          f"{join_figs['join_probe_ms']:.1f} ms, emit "
          f"{join_figs['join_emit_ms']:.1f} ms), numpy below-floor "
          f"{join_figs['join_numpy_rows_per_sec']:,.0f} rows/s",
          file=sys.stderr)

    # scan→join→agg e2e: in smoke the dim side sits below the default
    # dispatch floor, so the floor is disabled there (same code paths,
    # tiny sizes — the point of smoke); the full run uses the default
    n_dim = 4_000 if smoke else 100_000
    e2e_figs = measure_join_e2e(base_store, n_base, n_dim, runs=1,
                                floor=0 if smoke else None)
    print(f"# join_e2e ({n_base / 1e6:.2f}M join {n_dim / 1000:.0f}k "
          f"scan→join→agg): "
          f"{e2e_figs['join_e2e_rows_per_sec']:,.0f} probe rows/s "
          f"columnar ({e2e_figs['join_e2e_speedup_vs_rowpath']:.2f}x the "
          f"row-materializing path), fused={e2e_figs['join_agg_fused']}, "
          f"scan_columnar={e2e_figs['scan_columnar']} "
          f"(hits {e2e_figs['columnar_hits']}, fallbacks "
          f"{e2e_figs['columnar_fallbacks']})", file=sys.stderr)

    # per-region fan-out e2e: every region answers the columnar channel,
    # per-region partial aggregates merge device-side (4-region cluster)
    fr, fd = (6_000, 500) if smoke else (120_000, 5_000)
    fan_figs = measure_region_fanout(fr, fd, n_regions=4, runs=runs)
    print(f"# region_fanout ({fr / 1000:.0f}k rows x "
          f"{fan_figs['region_fanout_regions']} regions scan→join→agg): "
          f"{fan_figs['region_fanout_rows_per_sec']:,.0f} rows/s columnar "
          f"({fan_figs['region_fanout_speedup_vs_rowpath']:.2f}x the row "
          f"protocol), {fan_figs['columnar_partials']} partials, "
          f"{fan_figs['region_fanout_fallbacks']} fallbacks, "
          f"{fan_figs['region_partial_combines']} device partial-combines",
          file=sys.stderr)
    print(f"# region_fanout_repeat (plane cache): "
          f"{fan_figs['region_fanout_repeat_rows_per_sec']:,.0f} rows/s "
          f"warm ({fan_figs['region_fanout_repeat_speedup_vs_cold']:.2f}x "
          f"the cold re-pack regime), {fan_figs['plane_cache_hits']} "
          f"plane-cache hits", file=sys.stderr)
    # aggregate-pushdown regime: TPC-H-q1-shaped grouped aggregate over
    # the 4-region cluster store, partial STATES (not group rows)
    # crossing the wire and merging through the device combine chain
    qr = 8_000 if smoke else 200_000
    q1p_figs = measure_q1_pushdown(qr, n_regions=4, runs=runs)
    print(f"# q1_pushdown ({qr / 1000:.0f}k rows x "
          f"{q1p_figs['q1_pushdown_regions']} regions grouped agg): "
          f"{q1p_figs['q1_pushdown_rows_per_sec']:,.0f} rows/s states "
          f"channel ({q1p_figs['q1_pushdown_speedup_vs_rowpath']:.2f}x "
          f"the row protocol), "
          f"{q1p_figs['q1_pushdown_states_partials']} states partials, "
          f"{q1p_figs['q1_pushdown_fallbacks']} fallbacks, states/rows "
          f"wire bytes {q1p_figs['q1_states_bytes_vs_rows_bytes']}",
          file=sys.stderr)
    # TPC-H sweep regime: every parser-accepted aggregate shape — the
    # REAL q1 (expression aggregate arguments), q6, min/max arithmetic,
    # float expression args, decimal/datetime group keys — all columnar,
    # zero fallbacks, exact row-protocol parity (PR 18)
    tsr = 8_000 if smoke else 150_000
    tpch_figs = measure_tpch_sweep(tsr, n_regions=4, runs=runs)
    print(f"# tpch_sweep ({tsr / 1000:.0f}k rows x "
          f"{tpch_figs['tpch_sweep_regions']} regions, "
          f"{tpch_figs['tpch_sweep_queries']} query shapes): "
          f"{tpch_figs['tpch_sweep_rows_per_sec']:,.0f} rows/s columnar "
          f"({tpch_figs['tpch_sweep_speedup_vs_rowpath']:.2f}x the row "
          f"protocol), {tpch_figs['tpch_sweep_fallbacks']} fallbacks, "
          f"{tpch_figs['tpch_sweep_arg_plane_partials']} arg-plane "
          f"partials, q1full "
          f"{tpch_figs['q1full_dispatches_per_stmt']} dispatches/stmt",
          file=sys.stderr)
    # multi-key string-join regime: TPC-H-q3/q5-shaped joins on
    # composite (varchar, varchar) keys riding the dictionary tier's
    # key-tuple codes (device remap kernel at floor 0 so the smoke rig
    # exercises the device join path too)
    mqr = 6_000 if smoke else 120_000
    mq_figs = measure_multiq(mqr, n_regions=4, runs=runs, floor=0)
    print(f"# multiq ({mqr / 1000:.0f}k rows x "
          f"{mq_figs['multiq_regions']} regions, composite string keys): "
          f"{mq_figs['multiq_rows_per_sec']:,.0f} rows/s columnar "
          f"({mq_figs['multiq_speedup_vs_dict_path']:.2f}x the dict "
          f"path, {mq_figs['multiq_vs_numpy_oracle']:.2f}x vs numpy "
          f"oracle), {mq_figs['multiq_dict_joins']} dict joins / "
          f"{mq_figs['multiq_device_remaps']} device remaps / "
          f"{mq_figs['multiq_topn_plane']} plane TopNs, "
          f"{mq_figs['multiq_fallbacks']} fallbacks", file=sys.stderr)
    # out-of-core join regime (HBM governance): build side ~4x the
    # configured HBM budget — the join splits into radix-partitioned
    # passes bit-identical to the single-pass oracle
    ovr, ovd = (6_000, 4_000) if smoke else (120_000, 60_000)
    ov_figs = measure_join_oversized(ovr, ovd, n_regions=4, runs=runs)
    print(f"# join_oversized ({ovr / 1000:.0f}k probe x {ovd / 1000:.0f}k "
          f"build, budget {ov_figs['oversized_join_budget_bytes']} B): "
          f"{ov_figs['oversized_join_rows_per_sec']:,.0f} rows/s across "
          f"{ov_figs['oversized_join_passes']} partitioned passes "
          f"({ov_figs['oversized_join_partitions']} partitions/join), "
          f"{ov_figs['oversized_join_fallbacks']} fallbacks",
          file=sys.stderr)
    # out-of-core everything regime: ORDER BY + window function +
    # high-NDV group-by at a budget a fraction of every working set —
    # partitioned external sort / spilling states / chunked window
    # scans, bit-identical to the kill-switch oracle
    spr, spd = (12_000, 3_000) if smoke else (40_000, 10_000)
    sp_figs = measure_spill(spr, spd, n_regions=4, runs=runs)
    print(f"# spill ({spr / 1000:.0f}k rows x {sp_figs['spill_regions']} "
          f"regions, budget {sp_figs['spill_budget_bytes']} B): "
          f"{sp_figs['spill_rows_per_sec']:,.0f} rows/s across "
          f"{sp_figs['spill_passes']} spill passes "
          f"({sp_figs['spill_sort_passes']} sort / "
          f"{sp_figs['spill_groupby_passes']} group-by / "
          f"{sp_figs['spill_window_passes']} window), "
          f"{sp_figs['spill_fallbacks']} fallbacks", file=sys.stderr)
    # HTAP freshness regime: OLTP commits interleaved with repeat fan-out
    # scans — cached planes stay warm through region delta packs + device
    # base+delta merges; the kill-switch regime is the collapse oracle
    hr = 4_000 if smoke else 100_000
    htap_figs = measure_htap_mixed(hr, n_regions=4, runs=runs)
    print(f"# htap_mixed ({hr / 1000:.0f}k rows x "
          f"{htap_figs['htap_regions']} regions, commits interleaved): "
          f"{htap_figs['htap_scan_rows_per_sec']:,.0f} rows/s scans "
          f"(hit ratio {htap_figs['htap_plane_cache_hit_ratio']:.2f} "
          f"delta-on vs {htap_figs['htap_plane_cache_hit_ratio_off']:.2f} "
          f"off), {htap_figs['delta_merges']} delta merges, "
          f"{htap_figs['delta_repacks']} re-packs", file=sys.stderr)
    # mesh fan-out regime: region partials land on their home shards and
    # the grouped partial-agg states combine over ICI (1-shard on a
    # single-device rig — same code path, no collectives)
    mr, md = (6_000, 500) if smoke else (120_000, 5_000)
    mesh_figs = measure_mesh_fanout(mr, md, n_regions=4, runs=runs)
    print(f"# mesh_fanout ({mr / 1000:.0f}k rows x 4 regions → "
          f"{mesh_figs['mesh_shards']} shards): "
          f"{mesh_figs['mesh_fanout_rows_per_sec']:,.0f} rows/s "
          f"({mesh_figs['mesh_fanout_vs_single_device']:.2f}x the "
          f"single-device combine), {mesh_figs['mesh_combines']} ICI "
          f"combines, collective {mesh_figs['mesh_collective_ms']:.1f} ms"
          f", {mesh_figs['mesh_transfer_bytes']} shard-fan-in bytes",
          file=sys.stderr)
    # sustained-QPS concurrency regime: N simulated connections x mixed
    # point/range/join below-floor workload — the micro-batch tier's
    # headline production metric (p99 must stay flat as connections grow)
    qps_figs = measure_qps(n_conns=32, smoke=smoke)
    print(f"# qps ({qps_figs['qps_connections']} conns mixed "
          f"point/range/join): {qps_figs['qps_sustained']:,.0f} stmt/s "
          f"sustained ({qps_figs['qps_1conn']:.1f} at 1 conn), p50 "
          f"{qps_figs['qps_p50_ms']:.0f} ms / p99 "
          f"{qps_figs['qps_p99_ms']:.0f} ms vs 1-conn p99 "
          f"{qps_figs['qps_p99_ms_1conn']:.0f} ms (ratio "
          f"{qps_figs['qps_p99_ratio_vs_1conn']:.2f}), "
          f"{qps_figs['qps_batched_dispatches']} batched dispatches / "
          f"{qps_figs['qps_batched_statements']} batched statements, "
          f"{qps_figs['qps_degraded_batch']} degraded", file=sys.stderr)
    diag_figs = diagnostics_summary()
    print(f"# diagnostics: device busy "
          f"{diag_figs['device_busy_fraction']:.2f} of the bracketed "
          f"regime ({diag_figs['device_busy_us']} us), batch slot "
          f"occupancy p50 {diag_figs['batch_slot_occupancy_p50']:.2f}, "
          f"pool queue wait p99 "
          f"{diag_figs['pool_queue_wait_p99_ms']:.2f} ms, flight "
          f"recorder "
          f"{diag_figs['flight_recorder_overhead_us_per_stmt']:.0f} "
          f"us/stmt", file=sys.stderr)
    print(f"# workload: {fan_figs['digest_entries']} digests "
          f"(fan-out query x{fan_figs['digest_fanout_exec_count']}, "
          f"{fan_figs['digest_fanout_device_ms']:.1f} ms device, "
          f"p95 {fan_figs['digest_fanout_p95_ms']:.1f} ms), digest "
          f"pipeline {fan_figs['digest_overhead_us_per_stmt']:.0f} us/stmt, "
          f"{fan_figs['hot_region_count']} hot regions (top "
          f"{fan_figs['hot_region_top_read_rows']} rows read, score "
          f"{fan_figs['hot_region_top_score']:.0f})", file=sys.stderr)

    kprof_figs = kernel_profile_summary()
    print(f"# kernel profile: {kprof_figs['kernel_profile_signatures']} "
          f"signatures, top {kprof_figs['kernel_profile_top_signature']} "
          f"({kprof_figs['kernel_profile_top_device_us']} us, "
          f"{kprof_figs['kernel_profile_top_device_us_share']:.2f} of "
          f"device time), {kprof_figs['kernel_profile_retraces']} "
          f"retraces", file=sys.stderr)

    geo_rps = math.exp(sum(math.log(x) for x in tpu_rps_all)
                       / len(tpu_rps_all))
    geo_speedup = math.exp(sum(math.log(x) for x in speedups)
                           / len(speedups))
    kernel_rps = {name: round(n_rows / s, 1)
                  for name, s in kernel_s.items()}
    print(json.dumps({
        "metric": "tpch_geomean_rows_per_sec_tpu",
        "value": round(geo_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(geo_speedup, 2),
        "rows": n_rows,
        "cpu_baseline_rows": n_base,
        "hbm_peak_gbps": round(hbm_peak, 2),
        "hbm_gbps": bw_figures,
        "hbm_fraction": {k: round(v / hbm_peak, 3)
                         for k, v in bw_figures.items()},
        "kernel_rows_per_sec": kernel_rps,
        "roofline_fraction": roofline,
        "dispatch_floor_rows": tpu_client.dispatch_floor_rows,
        "routing_crossover_rows": crossover_rows,
        "small_query_ms": round(small_ms, 2),
        **join_figs,
        **e2e_figs,
        **fan_figs,
        **q1p_figs,
        **tpch_figs,
        **mq_figs,
        **ov_figs,
        **sp_figs,
        **htap_figs,
        "q1_mesh_rows_per_sec": q1_mesh_rps,
        "mesh_devices": len(jax.devices()),
        **mesh_figs,
        **qps_figs,
        **diag_figs,
        **kprof_figs,
        "smoke": smoke,
        # the honest CPU comparison: a vectorized-numpy engine over the
        # same packed planes (the Python xeval baseline above understates
        # any real CPU engine; keep both so rounds stay comparable)
        "numpy_oracle_rows_per_sec": oracle_rps,
        "vs_numpy_oracle": round(
            math.exp(sum(math.log(x) for x in oracle_speedups)
                     / len(oracle_speedups)), 2) if oracle_speedups
        else None,
    }))


if __name__ == "__main__":
    _argv = sys.argv[1:]
    if "--smoke" in _argv and "--full" in _argv:
        sys.exit("bench.py: --smoke and --full are mutually exclusive")
    main(smoke="--smoke" in _argv, full="--full" in _argv)
