"""Find the buffer-size threshold where post-D2H dispatches start
re-staging arguments (BENCH_r03: q1 at 133MB/plane was byte-proportional;
exp_axon_staging at 32MB/plane showed only a flat ~33ms RTT)."""
import time

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

rng = np.random.default_rng(0)
SIZES = [4_000_000, 8_000_000, 12_000_000, 16_700_000]  # 32/64/96/134 MB
arrs = {n: jnp.asarray(rng.random(n)) for n in SIZES}
jax.block_until_ready(list(arrs.values()))

fns = {n: jax.jit(lambda v: jnp.sum(v)) for n in SIZES}


def t(fn, *a, n=3):
    r = fn(*a)
    jax.block_until_ready(r)
    t0 = time.time()
    for _ in range(n):
        r = fn(*a)
    jax.block_until_ready(r)
    return (time.time() - t0) / n


for n in SIZES:
    print(f"pre-D2H  sum({n*8/1e6:.0f} MB): {t(fns[n], arrs[n])*1e3:8.1f} ms")

_ = np.asarray(fns[SIZES[0]](arrs[SIZES[0]]))
print("--- first D2H done ---")

for n in SIZES:
    print(f"post-D2H sum({n*8/1e6:.0f} MB): {t(fns[n], arrs[n])*1e3:8.1f} ms")

# multi-plane at the big size: is cost per-buffer or total-bytes?
big = SIZES[-1]
p7 = {i: jnp.asarray(rng.random(big)) for i in range(7)}
jax.block_until_ready(list(p7.values()))
f7 = jax.jit(lambda pl: sum(jnp.sum(pl[i]) for i in range(7)))
print(f"post-D2H 7-plane sum (7x{big*8/1e6:.0f} MB): {t(f7, p7)*1e3:8.1f} ms")
