"""Where does Q1 e2e time go? Stand up the bench store at small scale,
run Q1 through the full SQL stack, and time the phases inside the TPU
client (dispatch vs D2H vs emit vs SQL-side)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench
from tidb_tpu.ops import TpuClient
from tidb_tpu.session import Session

N = int(os.environ.get("ROWS", "2000000"))
BASE = min(N, 250_000)
factor = max(1, N // BASE)

store, s, tbl, load_s = bench.build_store(BASE)
print(f"# loaded {BASE} in {load_s:.1f}s", file=sys.stderr)
if factor > 1:
    store, s, rep_s = bench.replicate_store(store, s, tbl, BASE, factor)
    print(f"# replicated to {BASE*factor} in {rep_s:.1f}s", file=sys.stderr)

store.set_client(TpuClient(store))
sess = Session(store)
sess.execute("use tpch")
client = store.get_client()

# instrument the client phases
import tidb_tpu.ops.client as cl

orig_run_agg = TpuClient._run_aggregate
phase = {}


def timed_run_agg(self, sel, batch, where):
    t0 = time.time()
    r = orig_run_agg(self, sel, batch, where)
    phase["run_aggregate"] = time.time() - t0
    return r


TpuClient._run_aggregate = timed_run_agg

orig_get_batch = TpuClient._get_batch


def timed_get_batch(self, sel, ranges):
    t0 = time.time()
    r = orig_get_batch(self, sel, ranges)
    phase["get_batch"] = time.time() - t0
    return r


TpuClient._get_batch = timed_get_batch


def run(sql, label, runs=3):
    sess.execute(sql)  # warm
    times = []
    for _ in range(runs):
        t0 = time.time()
        sess.execute(sql)
        times.append(time.time() - t0)
    print(f"{label}: {min(times)*1e3:.0f}..{max(times)*1e3:.0f} ms/run  "
          f"phases={ {k: round(v*1e3) for k, v in phase.items()} }",
          file=sys.stderr)


print("=== pre-D2H state is already gone (execute reads results) ===",
      file=sys.stderr)
run(bench.Q6, "q6 e2e")
run(bench.Q1, "q1 e2e")
run(bench.QDIST, "distinct e2e")
run(bench.Q1, "q1 e2e again")

# break down inside run_aggregate for q1: time dispatch vs asarray
import jax
from tidb_tpu.ops import kernels

sel_holder = {}
orig_send_tpu = TpuClient._send_tpu


def capture_send(self, req, sel):
    sel_holder["sel"] = sel
    sel_holder["ranges"] = req.key_ranges
    return orig_send_tpu(self, req, sel)


TpuClient._send_tpu = capture_send
sess.execute(bench.Q1)
sel = sel_holder["sel"]
batch = client._get_batch(sel, sel_holder["ranges"])
specs = kernels.lower_aggregates(sel, batch)
planes = kernels.batch_planes(
    batch, with_pos=any(sp.name == "first_row" for sp in specs))
live = np.zeros(batch.capacity, dtype=bool)
live[: batch.n_rows] = True
gspec = kernels.lower_group_by(sel, batch)
print(f"gspec kind={gspec.kind} plane_keys={gspec.plane_keys} "
      f"sizes={gspec.sizes}", file=sys.stderr)
planes = client._with_group_planes(batch, gspec, planes)
fn, wrapper, jitted = client._kernel(
    sel, batch, "grouped",
    lambda: kernels.build_grouped_agg_fn(
        kernels.compile_expr(sel.where, batch) if sel.where is not None
        else None, specs, gspec.plane_keys, gspec.sizes))
r = jitted(planes, live)
jax.block_until_ready(r)
for lbl, fn_call in [
    ("dispatch+block (host live)",
     lambda: jax.block_until_ready(jitted(planes, live))),
]:
    t0 = time.time()
    for _ in range(3):
        fn_call()
    print(f"{lbl}: {(time.time()-t0)/3*1e3:.0f} ms", file=sys.stderr)
live_dev = __import__("jax.numpy", fromlist=["asarray"]).asarray(live)
r = jitted(planes, live_dev)
jax.block_until_ready(r)
t0 = time.time()
for _ in range(3):
    jax.block_until_ready(jitted(planes, live_dev))
print(f"dispatch+block (dev live): {(time.time()-t0)/3*1e3:.0f} ms",
      file=sys.stderr)
t0 = time.time()
for _ in range(3):
    packed = jitted(planes, live_dev)
    np.asarray(packed)
print(f"dispatch+1xD2H: {(time.time()-t0)/3*1e3:.0f} ms", file=sys.stderr)
