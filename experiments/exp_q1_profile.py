"""Where does Q1 e2e time go? Superseded by the kernel-level continuous
profiler (tidb_tpu.profiler): the monkey-patched client-phase timers this
experiment used to carry are now first-class — every metered dispatch
publishes into the per-(kind, signature) registry, and the same figures
are queryable live via information_schema.TIDB_TPU_KERNEL_PROFILE.

This wrapper stands up the bench store, runs the three bench queries
through the full SQL stack, and prints the profiler's roofline table
plus the statement's Perfetto trace-event export path.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
from tidb_tpu.ops import TpuClient
from tidb_tpu.session import Session

N = int(os.environ.get("ROWS", "2000000"))
BASE = min(N, 250_000)
factor = max(1, N // BASE)

store, s, tbl, load_s = bench.build_store(BASE)
print(f"# loaded {BASE} in {load_s:.1f}s", file=sys.stderr)
if factor > 1:
    store, s, rep_s = bench.replicate_store(store, s, tbl, BASE, factor)
    print(f"# replicated to {BASE*factor} in {rep_s:.1f}s", file=sys.stderr)

store.set_client(TpuClient(store))
sess = Session(store)
sess.execute("use tpch")

for label, sql in (("q6", bench.Q6), ("q1", bench.Q1),
                   ("distinct", bench.QDIST)):
    sess.execute(sql)   # warm (trace)
    sess.execute(sql)   # steady state (execute)
    print(f"# {label}: ran", file=sys.stderr)

# the roofline table the old hand-timed phases approximated: device time,
# tunnel bytes, rows, and the readback-vs-compute-bound verdict per
# kernel signature
from tidb_tpu import profiler

for row in profiler.profile_rows():
    print(f"{row['kind']}|{row['signature']}: "
          f"{row['dispatches']} dispatches "
          f"({row['retraces']} retraces), "
          f"{row['device_us']} us device "
          f"({row['trace_us']} us tracing), "
          f"{row['readback_bytes']} B readback at "
          f"{row['bytes_per_device_sec']/1e9:.2f} GB/s, "
          f"{row['rows_per_sec']:,.0f} rows/s -> {row['bound']}",
          file=sys.stderr)

# cross-thread timeline of the most recent retained statement trace
# (SET GLOBAL tidb_slow_log_threshold low enough and re-run to retain):
# the same JSON ADMIN TPU PROFILE EXPORT returns — load it in Perfetto
sess.execute("set global tidb_slow_log_threshold = 1")
sess.execute(bench.Q1)
rs = sess.execute("admin tpu profile export")[0]
rows = rs.values()
if rows:
    doc = json.loads(rows[0][2])
    print(f"# trace-event export: {len(doc['traceEvents'])} events "
          f"(load in ui.perfetto.dev)", file=sys.stderr)
    print(json.dumps(doc))
