"""Which primitives stay fast post-D2H on the axon tunnel?

Known: scatter (segment_sum) degrades to O(rows) per op; fused
elementwise+reduce stays ~33ms + real compute. Test: sort, argsort,
lexsort, cumsum, top_k, gather, and a scatter-free grouped-agg prototype
(masked reductions over 13 segments, 20 outputs).
"""
import time

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

N = 500_000
S = 13
rng = np.random.default_rng(0)
v = jnp.asarray(rng.random(N))
iv = jnp.asarray(rng.integers(0, 1 << 40, N))
gid = jnp.asarray(rng.integers(0, S, N))
idx = jnp.asarray(rng.integers(0, N, N))
jax.block_until_ready([v, iv, gid, idx])

fns = {
    "sort f64": jax.jit(lambda: jnp.sort(v)[0]),
    "sort i64": jax.jit(lambda: jnp.sort(iv)[0]),
    "argsort i64": jax.jit(lambda: jnp.argsort(iv)[0]),
    "lexsort 3key": jax.jit(lambda: jnp.lexsort([iv, gid, gid])[0]),
    "cumsum": jax.jit(lambda: jnp.cumsum(v)[-1]),
    "top_k": jax.jit(lambda: jax.lax.top_k(v, 100)[0][0]),
    "gather": jax.jit(lambda: jnp.sum(v[idx])),
    "boundary-distinct": jax.jit(
        lambda: jnp.sum((lambda s: jnp.concatenate(
            [jnp.ones(1, bool), s[1:] != s[:-1]]))(jnp.sort(iv)))),
}


def grouped_masked(v, gid):
    """Scatter-free grouped agg: 20 outputs x 13 segments via one-hot
    masked reductions — [S, N] broadcast fused into reduces."""
    oh = gid[None, :] == jnp.arange(S)[:, None]          # [S, N] bool
    outs = []
    for i in range(10):
        vv = v + i
        outs.append(jnp.sum(jnp.where(oh, vv[None, :], 0.0), axis=1))
        outs.append(jnp.sum(oh & (vv[None, :] > 0.5), axis=1))
    return jnp.concatenate(outs)


def grouped_dot(v, gid):
    """One-hot contraction variant: [S,N] f64 matmul-like einsum."""
    oh = (gid[None, :] == jnp.arange(S)[:, None]).astype(jnp.float64)
    vals = jnp.stack([v + i for i in range(10)])          # [10, N]
    return jnp.einsum("sn,an->sa", oh, vals)

fns["grouped-masked 20x13"] = jax.jit(lambda: grouped_masked(v, gid)[0])
fns["grouped-dot 10x13"] = jax.jit(lambda: grouped_dot(v, gid)[0, 0])


def t(fn, n=3):
    r = fn()
    jax.block_until_ready(r)
    t0 = time.time()
    for _ in range(n):
        r = fn()
    jax.block_until_ready(r)
    return (time.time() - t0) / n


_ = np.asarray(jnp.sum(v))
print("--- D2H done; all timings post-D2H (the real steady-state world) ---")
for name, fn in fns.items():
    try:
        print(f"running {name}...", flush=True)
        print(f"{name:24s}: {t(fn)*1e3:8.1f} ms")
    except Exception as e:
        print(f"{name:24s}: FAIL {type(e).__name__} {str(e)[:80]}")
