"""Test: post-D2H, is per-dispatch cost ~ (number of XLA thunks) x RTT?

Build executables with controlled numbers of unfusable ops (segment_sum
scatters force separate thunks) and compare pre/post-D2H dispatch times.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

N = 2_000_000
rng = np.random.default_rng(0)
v = jnp.asarray(rng.random(N))
gid = jnp.asarray(rng.integers(0, 13, N))
jax.block_until_ready([v, gid])


def mk_seg(k):
    @jax.jit
    def f(v, gid):
        outs = []
        for i in range(k):
            outs.append(jax.ops.segment_sum(v + i, gid, num_segments=13))
        return jnp.concatenate(outs)
    return f


def mk_chain(k):
    @jax.jit
    def f(v, gid):
        x = v
        for i in range(k):
            x = x * 1.0000001 + 0.1   # fuses into one elementwise kernel
        return jnp.sum(x)
    return f


def t(fn, *a, n=3):
    r = fn(*a)
    jax.block_until_ready(r)
    t0 = time.time()
    for _ in range(n):
        r = fn(*a)
    jax.block_until_ready(r)
    return (time.time() - t0) / n


segs = {k: mk_seg(k) for k in (1, 4, 16)}
chain = mk_chain(64)

for k, f in segs.items():
    print(f"pre-D2H  seg x{k:2d}: {t(f, v, gid)*1e3:8.1f} ms")
print(f"pre-D2H  chain64: {t(chain, v, gid)*1e3:8.1f} ms")

_ = np.asarray(jnp.sum(v))
print("--- first D2H done ---")

for k, f in segs.items():
    print(f"post-D2H seg x{k:2d}: {t(f, v, gid)*1e3:8.1f} ms")
print(f"post-D2H chain64: {t(chain, v, gid)*1e3:8.1f} ms")

# fresh compiles post-D2H for the same shapes
segs2 = {k: mk_seg(k) for k in (1, 16)}
for k, f in segs2.items():
    print(f"post-D2H seg x{k:2d} (fresh): {t(f, v, gid)*1e3:8.1f} ms")

# does input size matter at fixed thunk count?
v4 = jnp.asarray(rng.random(4 * N))
gid4 = jnp.asarray(rng.integers(0, 13, 4 * N))
jax.block_until_ready([v4, gid4])
f16 = mk_seg(16)
print(f"post-D2H seg x16 at 4x rows: {t(f16, v4, gid4)*1e3:8.1f} ms")
