"""Round-5 distinct-kernel isolation (post-poison, real chip).

Question: at bench scale (10.2M rows), is the distinct sort kernel
bandwidth-bound, or is it inside the flat ~110-130 ms dispatch window that
every kernel on this rig pays? Compare:
  1. pure sum sweep over the same i64 plane (bytes-matched roofline)
  2. the actual _distinct_reduce kernel (sort + boundary count)
  3. the same at 4x rows (does sort scale worse than linear?)
"""

import time

import numpy as np

import jax
import jax.numpy as jnp

from tidb_tpu.ops import kernels


def bench(fn, *args, runs=5):
    np.asarray(fn(*args))  # compile + poison-certified completion
    t0 = time.time()
    for _ in range(runs):
        np.asarray(fn(*args))
    return (time.time() - t0) / runs


def main():
    np.asarray(jnp.zeros(8))  # poison the tunnel

    for n in (10_200_000, 40_800_000):
        rng = np.random.RandomState(7)
        v = jnp.asarray(rng.randint(1, n // 4, size=n).astype(np.int64))
        contrib = jnp.asarray(rng.rand(n) < 0.97)

        sweep = jax.jit(lambda x: jnp.sum(x))
        t_sweep = bench(sweep, v)

        dist = jax.jit(lambda x, c: kernels._distinct_reduce(x, c))
        t_dist = bench(dist, v, contrib)

        # what the bench's count(distinct) actually runs: XLA DCEs the
        # distinct-sum half when only the count output is consumed
        cnt_only = jax.jit(lambda x, c: kernels._distinct_reduce(x, c)[0])
        t_cnt = bench(cnt_only, v, contrib)

        sort_only = jax.jit(lambda x: jnp.sort(x)[-1])
        t_sort = bench(sort_only, v)

        gb = n * 8 / 1e9
        print(f"n={n:,}: sweep {t_sweep*1e3:8.1f} ms ({gb/t_sweep:5.2f} GB/s)"
              f"  sort {t_sort*1e3:8.1f} ms"
              f"  cnt-distinct {t_cnt*1e3:8.1f} ms"
              f"  distinct(cnt+sum) {t_dist*1e3:8.1f} ms "
              f"({gb/t_dist:5.2f} GB/s)")


if __name__ == "__main__":
    main()
