"""Empirical probe of the axon tunnel's post-D2H dispatch degradation.

Question: after the first device->host transfer, WHICH dispatches re-stage
their argument buffers — all of them, or only executables compiled after
the D2H?  (BENCH_r03 shows q6 e2e staying fast at 0.73s while q1/distinct,
whose jitted fns are first compiled after q6's result read, run at exactly
plane-bytes / tunnel-rate.)

Run on the real chip: python experiments/exp_axon_staging.py
"""
import time

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

N = 4_000_000
MB = N * 8 / 1e6
rng = np.random.default_rng(0)
planes = {i: jnp.asarray(rng.random(N)) for i in range(6)}
live_np = np.ones(N, dtype=bool)
live_dev = jnp.asarray(live_np)
jax.block_until_ready(list(planes.values()))


def mk(name, cols):
    def f(pl, live):
        s = jnp.float64(0)
        for c in cols:
            s = s + jnp.sum(jnp.where(live, pl[c], 0.0))
        return s
    f.__name__ = name
    return jax.jit(f)


def t(fn, *a, n=3):
    r = fn(*a)
    jax.block_until_ready(r)
    t0 = time.time()
    for _ in range(n):
        r = fn(*a)
    jax.block_until_ready(r)
    return (time.time() - t0) / n


f = mk("f", [0, 1, 2])
g = mk("g", [3, 4, 5])
h = mk("h", [0, 1, 2, 3, 4, 5])
h_exe = h.lower(planes, live_dev).compile()   # AOT pre-D2H, never dispatched

print(f"plane bytes per fn: 3 cols = {3*MB:.0f} MB, 6 cols = {6*MB:.0f} MB")
print(f"pre-D2H  f(3 cols, dev live): {t(f, planes, live_dev)*1e3:8.1f} ms")
print(f"pre-D2H  g(3 cols, dev live): {t(g, planes, live_dev)*1e3:8.1f} ms")
print(f"pre-D2H  f(3 cols, HOST live):{t(f, planes, live_np)*1e3:8.1f} ms")

x = np.asarray(f(planes, live_dev))           # FIRST D2H
print("--- first D2H done ---", float(x))

print(f"post-D2H f (compiled+dispatched pre): {t(f, planes, live_dev)*1e3:8.1f} ms")
print(f"post-D2H g (compiled+dispatched pre): {t(g, planes, live_dev)*1e3:8.1f} ms")
k = mk("k", [0, 1, 2])
print(f"post-D2H k (fresh jit, compiled post): {t(k, planes, live_dev)*1e3:8.1f} ms")
print(f"post-D2H h (AOT pre, 1st dispatch post): {t(h_exe, planes, live_dev)*1e3:8.1f} ms")

new0 = jnp.asarray(rng.random(N))
planes2 = dict(planes)
planes2[0] = new0
print(f"post-D2H f with NEW dev arg:           {t(f, planes2, live_dev)*1e3:8.1f} ms")

sm = jax.jit(lambda v: jnp.sum(v))
small = jnp.asarray(rng.random(1000))
print(f"post-D2H small fresh fn (8KB arg):     {t(sm, small)*1e3:8.1f} ms")

# does a SECOND D2H make things worse / does k stay degraded?
_ = np.asarray(g(planes, live_dev))
print(f"post-2xD2H f:                          {t(f, planes, live_dev)*1e3:8.1f} ms")
print(f"post-2xD2H k:                          {t(k, planes, live_dev)*1e3:8.1f} ms")
